"""Property + golden tests for the numpy reference core (SURVEY.md §4.1-4.2).

The single most important property (per the SHEEP paper): partial-tree
merge is associative and commutative — T(A ∪ B) == T(T(A) ∪ T(B)) — since
that is what makes the distributed algorithm correct.
"""

import numpy as np
import pytest

from sheep_tpu.core import pure
from sheep_tpu.io import generators


def _graph_cases():
    return {
        "karate": (generators.karate_club(), 34),
        "path": (generators.path_graph(50), 50),
        "star": (generators.star_graph(40), 40),
        "grid": (generators.grid_graph(8, 9), 72),
        "random": (generators.random_graph(200, 1500, seed=1), 200),
        "random_sparse": (generators.random_graph(300, 350, seed=2), 300),
        "rmat": (generators.rmat(8, 8, seed=4), 256),
    }


@pytest.fixture(params=list(_graph_cases()))
def graph(request):
    e, n = _graph_cases()[request.param]
    return e, n


def _tree(e, n):
    deg = pure.degrees(e, n)
    pos = pure.elimination_order(deg)
    return pure.build_elim_tree(e, pos), pos


# ---------------------------------------------------------------- trees ---

def test_tree_wellformed(graph):
    e, n = graph
    tree, _ = _tree(e, n)
    tree.validate()  # parents later in order => acyclic


def test_tree_components_match_graph(graph):
    """Forest connectivity == graph connectivity (same components)."""
    e, n = graph
    tree, pos = _tree(e, n)

    def comps(edge_arr):
        lbl = np.arange(n)

        def find(x):
            while lbl[x] != x:
                lbl[x] = lbl[lbl[x]]
                x = lbl[x]
            return x

        for u, v in edge_arr.reshape(-1, 2).tolist():
            ru, rv = find(u), find(v)
            if ru != rv:
                lbl[ru] = rv
        return np.array([find(x) for x in range(n)])

    def canon(labels):
        # relabel classes by first appearance so equal partitions compare equal
        seen = {}
        return np.array([seen.setdefault(int(l), len(seen)) for l in labels])

    np.testing.assert_array_equal(canon(comps(e)), canon(comps(tree.edges())))


def test_merge_equals_whole(graph):
    """T(G1 ∪ G2) == T(T(G1) ∪ T(G2)) for random edge splits."""
    e, n = graph
    deg = pure.degrees(e, n)
    pos = pure.elimination_order(deg)
    whole = pure.build_elim_tree(e, pos)
    rng = np.random.default_rng(0)
    for trial in range(3):
        mask = rng.random(len(e)) < 0.5
        t1 = pure.build_elim_tree(e[mask], pos)
        t2 = pure.build_elim_tree(e[~mask], pos)
        merged = pure.merge_trees(t1, t2)
        np.testing.assert_array_equal(merged.parent, whole.parent)


def test_merge_commutes(graph):
    e, n = graph
    deg = pure.degrees(e, n)
    pos = pure.elimination_order(deg)
    half = len(e) // 2
    t1 = pure.build_elim_tree(e[:half], pos)
    t2 = pure.build_elim_tree(e[half:], pos)
    ab = pure.merge_trees(t1, t2)
    ba = pure.merge_trees(t2, t1)
    np.testing.assert_array_equal(ab.parent, ba.parent)


def test_merge_associative():
    e = generators.random_graph(150, 900, seed=7)
    n = 150
    pos = pure.elimination_order(pure.degrees(e, n))
    a, b, c = e[:300], e[300:600], e[600:]
    ta, tb, tc = (pure.build_elim_tree(x, pos) for x in (a, b, c))
    left = pure.merge_trees(pure.merge_trees(ta, tb), tc)
    right = pure.merge_trees(ta, pure.merge_trees(tb, tc))
    np.testing.assert_array_equal(left.parent, right.parent)


def test_incremental_build_equals_batch(graph):
    """Streaming chunk-by-chunk with carried parent == one-shot build."""
    e, n = graph
    pos = pure.elimination_order(pure.degrees(e, n))
    whole = pure.build_elim_tree(e, pos)
    tree = None
    parent = None
    for off in range(0, len(e), 17):
        tree = pure.build_elim_tree(e[off : off + 17], pos, parent=parent)
        parent = tree.parent
    np.testing.assert_array_equal(tree.parent, whole.parent)


# ---------------------------------------------------------------- split ---

@pytest.mark.parametrize("k", [2, 3, 8])
def test_split_valid_and_balanced(graph, k):
    e, n = graph
    tree, _ = _tree(e, n)
    a = pure.tree_split(tree, k)
    assert a.min() >= 0 and a.max() < k
    loads = np.bincount(a, minlength=k)
    # every part nonempty unless graph is tiny; balance within 2x ideal
    assert loads.max() <= max(2.0 * n / k, loads.max() * (n < 3 * k))


# -------------------------------------------------------------- scoring ---

def test_score_basics():
    e = generators.path_graph(10)
    a = np.array([0] * 5 + [1] * 5, dtype=np.int32)
    cut, total, balance, cv = pure.edge_cut_score(e, a, 2)
    assert (cut, total) == (1, 9)
    assert balance == 1.0
    assert cv == 2  # vertex 4 <-> part 1, vertex 5 <-> part 0


def test_full_pipeline_karate():
    e = generators.karate_club()
    res = pure.partition_arrays(e, 2)
    res.validate(34)
    assert res.total_edges == 78
    # sanity: a sensible partitioner beats random (39 expected cut) easily
    assert res.edge_cut < 30
    assert res.balance <= 1.6
