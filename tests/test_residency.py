"""Out-of-core residency manager (ISSUE 20).

The pins, unit level first, then end-to-end:

- byte accounting: the budget caps the resident set through overflow,
  rotation, boundary eviction and pressure spill; the high-water /
  eviction / reload counters track every transition;
- eviction order: the sticky prefix anchors at the stream HEAD (the
  overflow carve drops the highest prefix index, never chunk 0), the
  tail window rotates FIFO, checkpoint boundaries evict only window
  entries behind the confirmed index;
- a leased chunk refuses eviction (LeasedChunkError) and is skipped by
  every spill scan — leased bytes are not modeled as reclaimable;
- spill-before-shrink: with spillable bytes the degrade ladder's first
  rung is ("spill", ...) with the dispatch knobs UNCHANGED; the retry
  wrapper performs the spill and halves the residency budget;
- a build under a deliberately tiny SHEEP_CACHE_BYTES budget is
  bit-identical to the unconstrained oracle on tpu / tpu-sharded /
  tpu-bigv, with the spill counters on the diagnostics record;
- the served scheduler ADMITS an over-budget job in spilled mode
  (knobs pinned to 1, no shared-cache lease) instead of rejecting it,
  bit-identically; only a job whose irreducible floor exceeds the
  budget is still rejected;
- a run killed mid-build under a spilling budget resumes bit-identical
  to the unconstrained oracle (the PR-8 contract holds through the
  eviction/reload plane).
"""

import numpy as np
import pytest

from sheep_tpu.backends.base import get_backend, list_backends
from sheep_tpu.io.edgestream import EdgeStream
from sheep_tpu.io import generators
from sheep_tpu.utils.checkpoint import Checkpointer
from sheep_tpu.utils.fault import ENV_VAR, InjectedFault
from sheep_tpu.utils import membudget
from sheep_tpu.utils import retry as retry_mod
from sheep_tpu.utils.residency import (LeasedChunkError, ResidencyManager,
                                       manager_from_env)

K = 4
CHUNK = 256  # 2048 B/chunk on the single-device backend


def graph():
    e = generators.rmat(10, 8, seed=3)
    return EdgeStream.from_array(e, n_vertices=1 << 10)


# ----------------------------------------------------------------- unit level

def test_prefix_admission_byte_accounting():
    stats: dict = {}
    rm = ResidencyManager(100, stats=stats)
    assert rm.admit(0, "a", 40) and rm.admit(1, "b", 40)
    assert rm.used == 80
    assert rm.get(0) == "a" and rm.get(1) == "b"
    assert stats["residency_hits"] == 2
    assert rm.spillable_bytes() == 80
    assert stats.get("spill_evictions", 0) == 0


def test_overflow_carve_keeps_stream_head():
    """First overflow carves the tail window out of the prefix TOP:
    chunk 0 (what every later pass re-reads first) stays resident."""
    rm = ResidencyManager(100, stats={})
    rm.admit(0, "a", 40)
    rm.admit(1, "b", 40)
    assert rm.admit(2, "c", 40)  # overflow -> carve -> window
    assert rm.get(0) == "a", "head anchor evicted by the carve"
    assert rm.get(1) is None, "carve must drop the highest prefix idx"
    assert rm.get(2) == "c"
    assert rm.used <= rm.budget


def test_window_rotates_fifo():
    rm = ResidencyManager(100, stats={})
    for i, ref in enumerate("abcd"):
        rm.admit(i, ref, 40)
    # window holds one 40 B chunk: 2 rotated out for 3, 3 for 4... the
    # newest window entry and the head-anchored prefix survive
    assert rm.get(0) == "a"
    assert rm.get(2) is None
    assert rm.get(3) == "d"
    assert rm.stats["spill_evictions"] >= 2


def test_budget_caps_resident_set_always():
    """A single chunk larger than the whole budget is refused — the
    byte cap holds unconditionally."""
    rm = ResidencyManager(100, stats={})
    assert not ResidencyManager(0).admit(0, "x", 1)
    rm.admit(0, "a", 90)
    assert not rm.admit(1, "big", 150)
    assert rm.used <= rm.budget


def test_checkpoint_boundary_evicts_confirmed_window_only():
    rm = ResidencyManager(100, stats={}, window_fraction=0.6)
    rm.admit(0, "a", 40)       # prefix
    rm.admit(1, "b", 40)
    rm.admit(2, "c", 30)       # overflow: window carved (60 B)
    rm.admit(3, "d", 30)       # both fit the window
    assert rm.get(2) == "c" and rm.get(3) == "d"
    freed = rm.boundary(3)     # chunks < 3 confirmed on disk
    assert freed == 30
    assert rm.get(2) is None, "confirmed window entry must be evicted"
    assert rm.get(3) == "d", "unconfirmed window entry must survive"
    assert rm.get(0) == "a", "boundary must never touch the prefix"
    assert rm.stats["residency_boundary_evictions"] == 1


def test_leased_chunk_refuses_eviction():
    rm = ResidencyManager(100, stats={})
    rm.admit(0, "a", 40)
    rm.lease(0)
    with pytest.raises(LeasedChunkError):
        rm.evict(0)
    assert rm.spillable_bytes() == 0, "leased bytes modeled reclaimable"
    assert rm.spill(None) == 0, "spill scan must skip leased entries"
    assert rm.get(0) == "a"
    rm.release(0)
    assert rm.evict(0) == 40
    assert rm.get(0) is None


def test_reload_accounting_on_reupload():
    stats: dict = {}
    rm = ResidencyManager(100, stats=stats)
    rm.admit(0, "a", 40)
    rm.evict(0)
    rm.admit(0, "a2", 40)  # the disk tier re-upload
    assert stats["spill_reload_bytes"] == 40
    assert stats["spill_reloads"] == 1
    assert stats["spill_resident_bytes"] == 40  # high water


def test_complete_fast_path_only_without_evictions():
    rm = ResidencyManager(1000, stats={})
    for i in range(4):
        rm.admit(i, i, 40)
    rm.note_stream_end(4)
    assert rm.complete
    over = ResidencyManager(100, stats={})
    for i in range(4):
        over.admit(i, i, 40)
    over.note_stream_end(4)
    assert not over.complete


def test_pressure_spill_drops_all_and_halves_budget():
    stats: dict = {}
    rm = ResidencyManager(100, stats=stats)
    rm.admit(0, "a", 40)
    rm.admit(1, "b", 40)
    freed = rm.pressure_spill()
    assert freed == 80 and rm.used == 0 and rm.budget == 50
    assert not rm.complete
    rm.pressure_spill()  # walks toward 0: 25 -> ... -> 0 eventually
    assert rm.budget == 25


def test_manager_from_env(monkeypatch):
    monkeypatch.delenv("SHEEP_CACHE_BYTES", raising=False)
    assert manager_from_env() is None
    monkeypatch.setenv("SHEEP_CACHE_BYTES", "0")
    assert manager_from_env() is None
    monkeypatch.setenv("SHEEP_CACHE_BYTES", "4096")
    rm = manager_from_env(stats={})
    assert rm is not None and rm.budget == 4096


# -------------------------------------------------- spill-before-shrink ladder

def test_degraded_dispatch_spills_before_shrinking():
    n, cs = 1 << 10, CHUNK
    step = membudget.degraded_dispatch(n, cs, 4, 2, spillable_bytes=1)
    assert step == ("spill", 4, 2), "knobs must come back unchanged"
    step = membudget.degraded_dispatch(n, cs, 4, 2, h2d_ring=2,
                                       spillable_bytes=1)
    assert step == ("spill", 4, 2, 2)
    # even at batch=1/inflight=1 a spill rung precedes the None fallback
    assert membudget.degraded_dispatch(n, cs, 1, 1,
                                       spillable_bytes=1) == ("spill", 1, 1)
    # with nothing spillable the ladder halves as before
    nxt = membudget.degraded_dispatch(n, cs, 4, 2, spillable_bytes=0)
    assert nxt is not None and nxt[0] * nxt[1] < 8


def test_retry_degrade_performs_the_spill():
    stats: dict = {}
    rm = ResidencyManager(1 << 20, stats=stats)
    rm.admit(0, "a", 4096)
    rm.admit(1, "b", 4096)
    nxt = retry_mod.degrade_dispatch(1 << 10, CHUNK, 4, 2, False,
                                     stats, 7, residency=rm)
    assert nxt == (4, 2), "spill rung must leave the knobs unchanged"
    assert rm.used == 0 and rm.budget == (1 << 20) // 2
    assert stats["spill_degrades"] == 1
    # drained manager: the next fault falls through to plain halving
    nxt = retry_mod.degrade_dispatch(1 << 10, CHUNK, 4, 2, False,
                                     stats, 7, residency=rm)
    assert nxt is not None and nxt != (4, 2)


def test_build_phase_bytes_resident_term():
    n, cs = 1 << 10, CHUNK
    base = membudget.build_phase_bytes(n, cs)
    held = membudget.build_phase_bytes(n, cs, resident_bytes=12345)
    assert held["resident_bytes"] == 12345
    assert held["total_bytes"] == base["total_bytes"] + 12345


# --------------------------------------------------- end-to-end bit-identity

OOCORE_BACKENDS = [
    pytest.param(b, marks=[pytest.mark.slow] if b == "tpu-bigv" else [])
    for b in ("tpu", "tpu-sharded", "tpu-bigv") if b in list_backends()
]
# one lockstep batch is 8 chunks x 2048 B on the 8-device mesh: budgets
# sized to hold ~2 admission units so every driver overflows mid-stream
TINY_BUDGET = {"tpu": "6000", "tpu-sharded": "40000", "tpu-bigv": "40000"}


@pytest.mark.parametrize("backend", OOCORE_BACKENDS)
def test_tiny_budget_build_bit_equals_oracle(backend, monkeypatch):
    es = graph()
    kw = {"chunk_edges": CHUNK}
    monkeypatch.delenv("SHEEP_CACHE_BYTES", raising=False)
    oracle = get_backend(backend, **kw).partition(es, K, comm_volume=True)
    monkeypatch.setenv("SHEEP_CACHE_BYTES", TINY_BUDGET[backend])
    tiny = get_backend(backend, **kw).partition(es, K, comm_volume=True)
    assert np.array_equal(tiny.assignment, oracle.assignment)
    assert tiny.edge_cut == oracle.edge_cut
    assert tiny.total_edges == oracle.total_edges
    assert tiny.comm_volume == oracle.comm_volume
    d = tiny.diagnostics or {}
    assert d.get("spill_evictions", 0) > 0, \
        "tiny budget never evicted: the out-of-core plane did not engage"
    assert d.get("spill_reload_bytes", 0) > 0
    assert d.get("spill_resident_bytes", 0) > 0
    assert d.get("spill_resident_bytes") <= int(TINY_BUDGET[backend])
    assert d.get("residency_hits", 0) > 0, \
        "the sticky prefix never served a later pass"


@pytest.mark.parametrize("backend", OOCORE_BACKENDS)
def test_kill_resume_through_half_spilled_build(backend, tmp_path,
                                               monkeypatch):
    """The PR-8 contract through the eviction/reload plane: kill the
    build mid-stream under a spilling budget, resume, bit-equal the
    UNCONSTRAINED oracle."""
    es = graph()
    kw = {"chunk_edges": CHUNK}
    monkeypatch.delenv("SHEEP_CACHE_BYTES", raising=False)
    oracle = get_backend(backend, **kw).partition(es, K, comm_volume=True)

    monkeypatch.setenv("SHEEP_CACHE_BYTES", TINY_BUDGET[backend])
    ck = Checkpointer(str(tmp_path), every=1)
    monkeypatch.setenv(ENV_VAR, "build:2")
    with pytest.raises(InjectedFault):
        get_backend(backend, **kw).partition(
            es, K, comm_volume=True, checkpointer=ck)
    monkeypatch.delenv(ENV_VAR)
    assert ck.load() is not None, "no checkpoint before the fault"

    res = get_backend(backend, **kw).partition(
        es, K, comm_volume=True, checkpointer=ck, resume=True)
    assert np.array_equal(res.assignment, oracle.assignment)
    assert res.edge_cut == oracle.edge_cut
    assert res.comm_volume == oracle.comm_volume


def test_oom_spills_before_shrinking_end_to_end(monkeypatch):
    """An injected RESOURCE fault on a build with resident chunks takes
    the spill rung: counters on record, dispatch knobs unchanged, and
    the result still bit-equals the oracle."""
    es = graph()
    kw = {"chunk_edges": CHUNK}
    monkeypatch.delenv("SHEEP_CACHE_BYTES", raising=False)
    oracle = get_backend("tpu", **kw).partition(es, K, comm_volume=True)
    monkeypatch.setenv("SHEEP_CACHE_BYTES", "6000")
    monkeypatch.setenv("SHEEP_RETRY_BASE_S", "0.01")
    monkeypatch.setenv(ENV_VAR, "oom@build:2:1")
    res = get_backend("tpu", **kw).partition(es, K, comm_volume=True)
    d = res.diagnostics or {}
    assert d.get("spill_degrades", 0) >= 1, \
        "RESOURCE fault with resident chunks must take the spill rung"
    assert d.get("degraded_dispatch_batch", 0) == 0, \
        "spill-before-shrink: the dispatch knobs must stay untouched"
    assert np.array_equal(res.assignment, oracle.assignment)


# ----------------------------------------------------- served spilled mode

def test_served_over_budget_job_admitted_spilled():
    """A job the halving ladder cannot fit is ADMITTED at the
    irreducible floor — knobs pinned to 1, spilled flag on the job, no
    shared-cache lease — and bit-equals the solo build."""
    import threading

    from sheep_tpu.server.protocol import JobSpec
    from sheep_tpu.server.scheduler import Scheduler

    es = graph()
    ref = get_backend("tpu", chunk_edges=1024).partition(es, K).assignment
    n, cs = 1 << 10, 1024
    floor = membudget.build_phase_bytes(
        n, cs, dispatch_batch=1, inflight=1, h2d_ring=1)["total_bytes"]
    sched = Scheduler(budget_bytes=int(floor * 1.2))
    t = threading.Thread(target=sched.run, daemon=True)
    t.start()
    try:
        spec = JobSpec.from_request(
            {"input": "rmat:10:8:3", "k": [K], "chunk_edges": cs,
             "dispatch_batch": 8, "inflight": 2}, tenant="t")
        job = sched.submit(spec)
        job = sched.wait(job.id, timeout_s=240)
        assert job.state == "done", job.error
        assert job.spilled
        assert job.stats.get("admission_spilled") == 1
        assert job.spec.dispatch_batch == 1 and job.spec.inflight == 1
        assert np.array_equal(job.results[0].assignment, ref)
    finally:
        sched.shutdown()
        t.join(timeout=30)
        assert not t.is_alive()


def test_served_floor_over_budget_still_rejected():
    """Rejection remains for jobs whose spilled-mode floor itself
    exceeds the budget — spilled admission is not unbounded."""
    import threading

    from sheep_tpu.server.protocol import JobSpec
    from sheep_tpu.server.scheduler import Scheduler

    sched = Scheduler(budget_bytes=10000)
    t = threading.Thread(target=sched.run, daemon=True)
    t.start()
    try:
        spec = JobSpec.from_request(
            {"input": "rmat:10:8:3", "k": [K], "chunk_edges": 1024},
            tenant="t")
        job = sched.submit(spec)
        job = sched.wait(job.id, timeout_s=60)
        assert job.state == "rejected"
        assert "even spilled" in (job.error or "")
    finally:
        sched.shutdown()
        t.join(timeout=30)
