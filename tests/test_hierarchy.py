"""Hierarchical partitioning tests (sheep_tpu/hierarchy.py).

The quality claim (k split into levels beats flat k above the LP signal
threshold) is measured at scale in BASELINE.md; these tests pin the
mechanics: valid labels, level composition, degenerate parts, and that
hierarchy does not LOSE to flat refine on a structured graph where flat
stalls.
"""

import numpy as np
import pytest

import sheep_tpu


SPEC = "sbm-hash:11:16:0.05:16:1"


def test_hier_valid_and_not_worse_than_flat():
    flat = sheep_tpu.partition(SPEC, 16, backend="cpu"
                               if "cpu" in sheep_tpu.list_backends()
                               else "pure", comm_volume=False, refine=4)
    hier = sheep_tpu.partition_hierarchical(
        SPEC, [4, 4], backend=flat.backend.split("+")[0],
        refine=4, comm_volume=False)
    assert hier.k == 16
    a = hier.assignment
    assert a.shape == (1 << 11,) and a.min() >= 0 and a.max() < 16
    # each level refines above the signal threshold; flat k=16 at this
    # density is at/below it — hierarchy must not lose
    assert hier.cut_ratio <= flat.cut_ratio + 0.02, \
        (hier.cut_ratio, flat.cut_ratio)
    # balance compounds per level (~1.1 per level at the default cap)
    assert hier.balance <= 1.35


def test_single_level_equals_partition():
    r1 = sheep_tpu.partition(SPEC, 4, backend="pure", comm_volume=False)
    rh = sheep_tpu.partition_hierarchical(SPEC, [4], backend="pure",
                                          refine=0, comm_volume=False)
    assert np.array_equal(r1.assignment, rh.assignment)
    assert r1.edge_cut == rh.edge_cut


def test_degenerate_tiny_parts():
    # path graph of 12 vertices into [4, 4] = 16 > V parts: every label
    # must stay in range even when parts hold fewer vertices than k_sub
    from sheep_tpu.io import formats, generators
    import tempfile

    with tempfile.TemporaryDirectory() as d:
        p = f"{d}/path.edges"
        formats.write_edges(p, generators.path_graph(12))
        res = sheep_tpu.partition_hierarchical(p, [4, 4], backend="pure",
                                               refine=0,
                                               comm_volume=False)
        a = res.assignment
        assert a.shape == (12,) and a.min() >= 0 and a.max() < 16


def test_validation():
    with pytest.raises(ValueError, match="positive"):
        sheep_tpu.partition_hierarchical(SPEC, [4, 0])
    with pytest.raises(ValueError, match="positive"):
        sheep_tpu.partition_hierarchical(SPEC, [])


def test_balance_budget_compounds_to_beta():
    # balance=BETA budgets BETA**(1/L) per level; delivered end-to-end
    # balance must respect the product bound (plus the +max_w slack of
    # each level's envelope — generous margin here)
    res = sheep_tpu.partition_hierarchical(
        SPEC, [4, 4], backend="pure", refine=2, balance=1.2,
        comm_volume=False)
    assert res.balance <= 1.2 + 0.05, res.balance
    with pytest.raises(ValueError, match="balance"):
        sheep_tpu.partition_hierarchical(SPEC, [4, 4], balance=0.9)
    with pytest.raises(ValueError, match="alpha"):
        sheep_tpu.partition_hierarchical(SPEC, [4, 4], balance=1.2,
                                         alpha=0.5)


def test_final_refine_never_worse():
    base = sheep_tpu.partition_hierarchical(
        SPEC, [4, 4], backend="pure", refine=2, comm_volume=False)
    rep = sheep_tpu.partition_hierarchical(
        SPEC, [4, 4], backend="pure", refine=2, final_refine=4,
        comm_volume=False)
    # warm-start LP at full k keeps the non-regression rollback
    assert rep.edge_cut <= base.edge_cut, (rep.edge_cut, base.edge_cut)
    a = rep.assignment
    assert a.min() >= 0 and a.max() < 16


def test_refine_budget_plumbing_output_invariant():
    """refine_budget_bytes threads partition_hierarchical ->
    refine_result -> refine_assignment without changing output (the
    --refine-budget-gb contract: the budget trades stream passes,
    never output). At this scale the min_block floor keeps even a
    starved budget in full-histogram mode, so mode-switch equality
    itself is pinned by test_refine_blocked_histogram_matches_full
    (min_block=64); this test pins the hierarchy-level kwarg path."""
    import numpy as np

    full = sheep_tpu.partition_hierarchical(
        SPEC, [4, 4], backend="pure", refine=2, final_refine=3,
        comm_volume=False)
    starved = sheep_tpu.partition_hierarchical(
        SPEC, [4, 4], backend="pure", refine=2, final_refine=3,
        comm_volume=False, refine_budget_bytes=1 << 16)
    np.testing.assert_array_equal(np.asarray(full.assignment),
                                  np.asarray(starved.assignment))
    assert starved.edge_cut == full.edge_cut


def test_spill_matches_scoring_and_bounds_disk(tmp_path):
    # the spilled file-backed recursion must produce a valid, internally
    # consistent result (scored cut == recount over the raw stream), and
    # the spill dir must be cleaned up afterwards
    spill = tmp_path / "spill"
    spill.mkdir()
    res = sheep_tpu.partition_hierarchical(
        SPEC, [4, 4], backend="pure", refine=0, comm_volume=False,
        spill_dir=str(spill))
    from sheep_tpu.io.edgestream import open_input

    a = res.assignment
    with open_input(SPEC) as es:
        cut = sum(int((a[np.asarray(c)[:, 0]] != a[np.asarray(c)[:, 1]])
                      .sum()) for c in es.chunks(1 << 20))
    assert cut == res.edge_cut
    assert list(spill.iterdir()) == []  # temp tree removed


def test_cli_k_levels(tmp_path, capsys):
    import json

    from sheep_tpu import cli
    from sheep_tpu.io import formats, generators

    p = str(tmp_path / "g.edges")
    formats.write_edges(p, generators.sbm_hash_range(10, 0, 4 << 10, 4,
                                                     0.05, seed=1))
    out = str(tmp_path / "g.parts")
    rc = cli.main(["--input", p, "--k-levels", "2,2", "--backend", "pure",
                   "--refine", "2", "--no-comm-volume", "--json",
                   "--output", out])
    assert rc == 0
    line = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert line["k"] == 4 and line["backend"].endswith("+hier[2, 2]")
    parts = formats.read_partition(out)
    assert parts.shape == (1 << 10,) and parts.max() < 4
    # exclusions are clean usage errors (--checkpoint-dir/--resume and
    # multi-host now COMPOSE with --k-levels — ISSUE 8; the kill+resume
    # drills live in tests/test_checkpoint.py)
    for argv in (["--input", p, "--k-levels", "2,2", "--k", "4"],
                 ["--input", p, "--k-levels", "2,x"],
                 ["--input", p, "--k-levels", "2,2", "--resume"],
                 # hierarchy-only flags are errors on the flat path
                 ["--input", p, "--k", "4", "--final-refine", "2"],
                 ["--input", p, "--k", "4", "--spill-dir", str(tmp_path)],
                 # --balance with an explicit --alpha stays an error
                 ["--input", p, "--k-levels", "2,2", "--balance", "1.2",
                  "--alpha", "0.5"]):
        with pytest.raises(SystemExit):
            cli.main(argv)
    # --balance and --final-refine now COMPOSE with --k-levels
    rc = cli.main(["--input", p, "--k-levels", "2,2", "--backend", "pure",
                   "--refine", "2", "--balance", "1.2",
                   "--final-refine", "2", "--no-comm-volume", "--json"])
    assert rc == 0
    line = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert line["k"] == 4 and line["balance"] <= 1.25
