"""Hierarchical partitioning tests (sheep_tpu/hierarchy.py).

The quality claim (k split into levels beats flat k above the LP signal
threshold) is measured at scale in BASELINE.md; these tests pin the
mechanics: valid labels, level composition, degenerate parts, and that
hierarchy does not LOSE to flat refine on a structured graph where flat
stalls.
"""

import numpy as np
import pytest

import sheep_tpu


SPEC = "sbm-hash:11:16:0.05:16:1"


def test_hier_valid_and_not_worse_than_flat():
    flat = sheep_tpu.partition(SPEC, 16, backend="cpu"
                               if "cpu" in sheep_tpu.list_backends()
                               else "pure", comm_volume=False, refine=4)
    hier = sheep_tpu.partition_hierarchical(
        SPEC, [4, 4], backend=flat.backend.split("+")[0],
        refine=4, comm_volume=False)
    assert hier.k == 16
    a = hier.assignment
    assert a.shape == (1 << 11,) and a.min() >= 0 and a.max() < 16
    # each level refines above the signal threshold; flat k=16 at this
    # density is at/below it — hierarchy must not lose
    assert hier.cut_ratio <= flat.cut_ratio + 0.02, \
        (hier.cut_ratio, flat.cut_ratio)
    # balance compounds per level (~1.1 per level at the default cap)
    assert hier.balance <= 1.35


def test_single_level_equals_partition():
    r1 = sheep_tpu.partition(SPEC, 4, backend="pure", comm_volume=False)
    rh = sheep_tpu.partition_hierarchical(SPEC, [4], backend="pure",
                                          refine=0, comm_volume=False)
    assert np.array_equal(r1.assignment, rh.assignment)
    assert r1.edge_cut == rh.edge_cut


def test_degenerate_tiny_parts():
    # path graph of 12 vertices into [4, 4] = 16 > V parts: every label
    # must stay in range even when parts hold fewer vertices than k_sub
    from sheep_tpu.io import formats, generators
    import tempfile

    with tempfile.TemporaryDirectory() as d:
        p = f"{d}/path.edges"
        formats.write_edges(p, generators.path_graph(12))
        res = sheep_tpu.partition_hierarchical(p, [4, 4], backend="pure",
                                               refine=0,
                                               comm_volume=False)
        a = res.assignment
        assert a.shape == (12,) and a.min() >= 0 and a.max() < 16


def test_validation():
    with pytest.raises(ValueError, match="positive"):
        sheep_tpu.partition_hierarchical(SPEC, [4, 0])
    with pytest.raises(ValueError, match="positive"):
        sheep_tpu.partition_hierarchical(SPEC, [])


def test_cli_k_levels(tmp_path, capsys):
    import json

    from sheep_tpu import cli
    from sheep_tpu.io import formats, generators

    p = str(tmp_path / "g.edges")
    formats.write_edges(p, generators.sbm_hash_range(10, 0, 4 << 10, 4,
                                                     0.05, seed=1))
    out = str(tmp_path / "g.parts")
    rc = cli.main(["--input", p, "--k-levels", "2,2", "--backend", "pure",
                   "--refine", "2", "--no-comm-volume", "--json",
                   "--output", out])
    assert rc == 0
    line = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert line["k"] == 4 and line["backend"].endswith("+hier[2, 2]")
    parts = formats.read_partition(out)
    assert parts.shape == (1 << 10,) and parts.max() < 4
    # exclusions are clean usage errors
    for argv in (["--input", p, "--k-levels", "2,2", "--k", "4"],
                 ["--input", p, "--k-levels", "2,x"],
                 ["--input", p, "--k-levels", "2,2",
                  "--checkpoint-dir", str(tmp_path)]):
        with pytest.raises(SystemExit):
            cli.main(argv)
