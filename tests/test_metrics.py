"""JSONL metrics sink (SURVEY.md §5 metrics/observability)."""

import io
import json

import numpy as np

from sheep_tpu import cli
from sheep_tpu.io import formats, generators
from sheep_tpu.utils.metrics import MetricsWriter, emit_run_metrics


def test_writer_appends_jsonl(tmp_path):
    path = str(tmp_path / "m.jsonl")
    with MetricsWriter(path) as mw:
        mw.emit("phase", phase="build", seconds=1.5)
    with MetricsWriter(path) as mw:
        mw.emit("scores", edge_cut=np.int64(7), loads=np.array([1, 2]))
    recs = [json.loads(l) for l in open(path)]
    assert [r["event"] for r in recs] == ["phase", "scores"]
    assert recs[0]["phase"] == "build" and "ts" in recs[0]
    assert recs[1]["edge_cut"] == 7 and recs[1]["loads"] == [1, 2]


def test_emit_run_metrics_record_set():
    from sheep_tpu.backends.base import get_backend
    from sheep_tpu.io.edgestream import EdgeStream

    es = EdgeStream.from_array(generators.karate_club(), n_vertices=34)
    res = get_backend("pure").partition(es, 2)
    buf = io.StringIO()
    emit_run_metrics(MetricsWriter(buf), res, 34, 0.5, graph="karate")
    recs = [json.loads(l) for l in buf.getvalue().splitlines()]
    events = [r["event"] for r in recs]
    assert events[:4] == ["run", "phase", "phase", "phase"] or "run" in events
    by = {}
    for r in recs:
        by.setdefault(r["event"], r)
    assert by["run"]["k"] == 2 and by["run"]["total_edges"] == 78
    assert by["scores"]["edge_cut"] == res.edge_cut
    assert sum(by["part_loads"]["loads"]) == 34


def test_cli_metrics_out(tmp_path):
    gpath = str(tmp_path / "g.edges")
    formats.write_edges(gpath, generators.karate_club())
    mpath = str(tmp_path / "m.jsonl")
    assert cli.main(["--input", gpath, "--k", "2", "--backend", "pure",
                     "--metrics-out", mpath, "--json"]) == 0
    recs = [json.loads(l) for l in open(mpath)]
    events = {r["event"] for r in recs}
    assert {"run", "phase", "scores", "part_loads"} <= events


def test_hier_quality_mixed_type_diagnostics_coercion():
    """The PR-1 defensive string-coercion path: a completed multi-hour
    quality run must write its artifact even when the diagnostics dict
    mixes floats with status strings (refine's 'refine_skipped'
    fallback) — float('refine_skipped') used to kill it at the very
    end. No regression test existed until ISSUE 13."""
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "hier_quality",
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "tools", "hier_quality.py"))
    hq = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(hq)
    mixed = {"refine_rounds_run": 4.0,
             "refine_skipped": "histogram over budget",
             "cut_level0": np.float64(0.27),
             "spill_bytes": np.int64(4096),
             "mode": "blocked"}
    out = {k: hq._num(v) for k, v in mixed.items()}
    assert out["refine_rounds_run"] == 4.0
    assert out["refine_skipped"] == "histogram over budget"
    assert out["cut_level0"] == 0.27 and out["spill_bytes"] == 4096.0
    assert out["mode"] == "blocked"
    # and the whole mixed dict survives the JSONL sink end-to-end
    buf = io.StringIO()
    with MetricsWriter(buf) as mw:
        mw.emit("diagnostics", **{k: v for k, v in mixed.items()})
    rec = json.loads(buf.getvalue())
    assert rec["refine_skipped"] == "histogram over budget"
    assert rec["cut_level0"] == 0.27


def test_accumulate_cv_keys_compacts_past_cap(monkeypatch):
    """cv-key host memory must stay bounded: past the cap the pending
    chunks are compacted (sort+unique) in place (VERDICT r1 weak #5)."""
    import numpy as np

    from sheep_tpu.ops import score as score_ops

    monkeypatch.setattr(score_ops, "CV_COMPACT_ENTRIES", 10)
    acc = []
    for i in range(8):
        score_ops.accumulate_cv_keys(
            acc, np.array([1, 2, 3, i], dtype=np.int64))
    assert sum(len(c) for c in acc) <= 10 + 4, \
        "accumulator grew past cap + one chunk"
    from sheep_tpu.utils.checkpoint import compact_cv_keys

    assert set(compact_cv_keys(acc)) == {1, 2, 3, 0, 4, 5, 6, 7}
