"""Checked-in golden fixtures (SURVEY.md §4.2).

Every other correctness test computes the numpy oracle *dynamically*, so a
silent semantic drift of the oracle itself — the root of the whole
equivalence-test DAG — would pass the suite. These fixtures pin the
oracle's exact output (elimination-forest parent array, partition map,
edge cut, balance, communication volume) on the karate club (driver eval
config 1) and an RMAT-8 graph, as files generated once and committed.

Any intentional algorithm change must regenerate them consciously:

    python - <<'EOF'
    ... see tests/golden/README.md
    EOF
"""

import json
import os

import numpy as np
import pytest

from sheep_tpu.core import pure
from sheep_tpu.io import generators
from sheep_tpu.io.edgestream import EdgeStream

GOLDEN_DIR = os.path.join(os.path.dirname(__file__), "golden")

_GRAPHS = {
    "karate_k2": lambda: (generators.karate_club(), 34, 2),
    "rmat8_k8": lambda: (generators.rmat(8, 8, seed=4), 256, 8),
}


def _load(name):
    with open(os.path.join(GOLDEN_DIR, f"{name}.json")) as f:
        return json.load(f)


@pytest.fixture(params=list(_GRAPHS))
def case(request):
    e, n, k = _GRAPHS[request.param]()
    return request.param, e, n, k, _load(request.param)


def test_oracle_matches_golden(case):
    """The numpy spec reproduces the committed fixture bit-for-bit."""
    name, e, n, k, gold = case
    deg = pure.degrees(e, n)
    pos = pure.elimination_order(deg)
    tree = pure.build_elim_tree(e, pos)
    a = pure.tree_split(tree, k)
    cut, total, balance, cv = pure.edge_cut_score(e, a, k)
    np.testing.assert_array_equal(tree.parent, np.asarray(gold["parent"]))
    np.testing.assert_array_equal(a, np.asarray(gold["assignment"]))
    assert (cut, total, cv) == (gold["edge_cut"], gold["total_edges"],
                                gold["comm_volume"])
    assert balance == pytest.approx(gold["balance"], abs=1e-12)


@pytest.mark.parametrize("backend", ["pure", "cpu", "tpu"])
def test_backends_match_golden(case, backend):
    """Every backend reproduces the committed partition and scores exactly
    (the suite's usual cross-backend equality, but anchored to a file)."""
    from sheep_tpu.backends.base import get_backend, list_backends

    if backend not in list_backends():
        pytest.skip(f"{backend} unavailable")
    name, e, n, k, gold = case
    res = get_backend(backend).partition(
        EdgeStream.from_array(e, n_vertices=n), k)
    np.testing.assert_array_equal(res.assignment,
                                  np.asarray(gold["assignment"], np.int32))
    assert res.edge_cut == gold["edge_cut"]
    assert res.total_edges == gold["total_edges"]
    assert res.comm_volume == gold["comm_volume"]
    assert res.balance == pytest.approx(gold["balance"], abs=1e-12)
