"""Live telemetry plane tests (ISSUE 11): histogram bucket math,
Prometheus rendering pinned by a golden file, the text parser, the
flight recorder's rings/routing/dump triggers, and the disabled-path
overhead smoke. Server-side wiring (metrics verb, HTTP scrape, fault
dumps through a real scheduler) lives in test_server.py; the full
daemon leg is tools/obs_smoke.sh leg 7."""

import io
import json
import math
import os
import time

import pytest

from sheep_tpu import obs
from sheep_tpu.obs import metrics as metrics_mod
from sheep_tpu.obs.flightrec import FlightRecorder
from sheep_tpu.obs.metrics import (MetricRegistry, histogram_series_quantile,
                                   parse_prometheus,
                                   quantile_from_cumulative)

GOLDEN = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "golden", "metrics_prom.txt")


# ---------------------------------------------------------------------------
# histogram bucket math
# ---------------------------------------------------------------------------

def test_histogram_boundary_values_use_le_semantics():
    """An observation EQUAL to a bucket's upper bound lands in that
    bucket (Prometheus `le`), one epsilon above lands in the next."""
    r = MetricRegistry()
    h = r.histogram("h_seconds", buckets=(0.1, 1.0, 10.0))
    h.observe(0.1)    # le="0.1"
    h.observe(0.1001)  # le="1"
    h.observe(10.0)   # le="10"
    h.observe(10.001)  # +Inf
    snap = h.snapshot()
    assert snap["cum"] == [1, 2, 3, 4]
    assert snap["count"] == 4
    assert snap["sum"] == pytest.approx(0.1 + 0.1001 + 10.0 + 10.001)


def test_histogram_inf_bucket_and_rendering_is_cumulative():
    r = MetricRegistry()
    h = r.histogram("h_seconds", buckets=(1.0,))
    for v in (0.5, 2.0, 3.0):
        h.observe(v)
    text = r.render()
    assert 'h_seconds_bucket{le="1"} 1' in text
    assert 'h_seconds_bucket{le="+Inf"} 3' in text
    assert "h_seconds_count 3" in text


def test_histogram_rejects_bad_buckets():
    r = MetricRegistry()
    with pytest.raises(ValueError):
        r.histogram("a", buckets=(1.0, 1.0))      # not ascending
    with pytest.raises(ValueError):
        r.histogram("b", buckets=(1.0, 0.5))
    with pytest.raises(ValueError):
        r.histogram("c", buckets=(1.0, math.inf))  # +Inf is implicit
    with pytest.raises(ValueError):
        r.histogram("d", buckets=())


def test_quantile_estimates_interpolate_within_bucket():
    # 10 observations uniform in (0, 1], bucket uppers 0.5/1.0: the
    # median rank sits at the upper edge of the first bucket
    r = MetricRegistry()
    h = r.histogram("q_seconds", buckets=(0.5, 1.0))
    for i in range(1, 11):
        h.observe(i / 10)
    assert h.quantile(0.5) == pytest.approx(0.5)
    assert h.quantile(0.25) == pytest.approx(0.25)
    assert h.quantile(1.0) == pytest.approx(1.0)
    # empty series: no estimate, not a crash
    assert h.quantile(0.5, **{}) is not None
    h2 = r.histogram("q2_seconds", buckets=(0.5,))
    assert h2.quantile(0.9) is None


def test_quantile_landing_in_inf_bucket_returns_last_finite_upper():
    assert quantile_from_cumulative((0.1, 1.0), [0, 0, 5], 0.5) == 1.0
    assert quantile_from_cumulative((1.0,), [0, 0], 0.5) is None


def test_counter_and_gauge_semantics():
    r = MetricRegistry()
    c = r.counter("jobs_total", labelnames=("tenant",))
    c.inc(tenant="a")
    c.inc(4, tenant="a")
    assert c.value(tenant="a") == 5
    with pytest.raises(ValueError):
        c.inc(-1, tenant="a")          # counters never decrease
    with pytest.raises(ValueError):
        c.inc(tenant="a", bogus="x")   # label mismatch
    g = r.gauge("depth")
    g.set(7)
    g.dec(2)
    assert g.value() == 5
    g.remove()
    assert "depth 5" not in r.render()


def test_registry_is_idempotent_but_type_strict():
    r = MetricRegistry()
    c1 = r.counter("x_total", labelnames=("tenant",))
    assert r.counter("x_total", labelnames=("tenant",)) is c1
    with pytest.raises(ValueError):
        r.gauge("x_total")                         # kind mismatch
    with pytest.raises(ValueError):
        r.counter("x_total", labelnames=("job",))  # label mismatch
    with pytest.raises(ValueError):
        r.counter("bad name")


# ---------------------------------------------------------------------------
# rendering, pinned by the golden file
# ---------------------------------------------------------------------------

def build_golden_registry() -> MetricRegistry:
    r = MetricRegistry()
    c = r.counter("sheepd_jobs_submitted_total",
                  "jobs accepted at the protocol boundary", ("tenant",))
    c.inc(tenant="alice")
    c.inc(2, tenant="bob")
    g = r.gauge("sheepd_queue_depth", "jobs waiting for headroom")
    g.set(3)
    h = r.histogram("sheepd_request_latency_seconds",
                    "queued->done request latency (the SLO series)",
                    ("tenant",), buckets=(0.1, 1.0, 10.0))
    h.observe(0.05, tenant="alice")
    h.observe(1.0, tenant="alice")   # boundary: the le="1" bucket
    h.observe(25.0, tenant="alice")  # +Inf
    r.add_collector(lambda: {"sheepd_uptime_seconds": 42})
    r.add_collector(lambda: [("sheepd_job_steps",
                              {"job": "j1", "tenant": 'a"b'}, 7)])
    return r


def test_render_matches_golden_file():
    """The exposition format is a WIRE contract (scrapers, the future
    replica router): any drift must be a deliberate golden update."""
    got = build_golden_registry().render()
    with open(GOLDEN) as f:
        want = f.read()
    assert got == want, (
        "Prometheus rendering drifted from tests/golden/"
        "metrics_prom.txt — if intentional, regenerate the golden "
        "file from build_golden_registry()")


def test_parse_prometheus_roundtrip_with_escaped_labels():
    parsed = parse_prometheus(build_golden_registry().render())
    assert parsed["sheepd_jobs_submitted_total"] == [
        ({"tenant": "alice"}, 1.0), ({"tenant": "bob"}, 2.0)]
    assert ({"le": "+Inf", "tenant": "alice"}, 3.0) in \
        parsed["sheepd_request_latency_seconds_bucket"]
    # escaped quote survives the round trip
    (labels, v), = parsed["sheepd_job_steps"]
    assert labels == {"job": "j1", "tenant": 'a"b'} and v == 7.0
    # quantile straight from parsed bucket samples (the sheeptop path)
    q = histogram_series_quantile(
        parsed["sheepd_request_latency_seconds_bucket"], 0.5,
        {"tenant": "alice"})
    assert 0.1 <= q <= 10.0


def test_parse_unescapes_backslash_before_n_correctly():
    """Regression: a label holding a literal backslash followed by
    'n' must survive the render->parse round trip (chained .replace
    unescaping ate half the escaped backslash and fabricated a
    newline)."""
    r = MetricRegistry()
    r.counter("c_total", labelnames=("tenant",)).inc(
        tenant="ops\\nightly")
    (labels, v), = parse_prometheus(r.render())["c_total"]
    assert labels == {"tenant": "ops\\nightly"} and v == 1.0
    r2 = MetricRegistry()
    r2.counter("d_total", labelnames=("t",)).inc(t="a\nb")
    (labels2, _), = parse_prometheus(r2.render())["d_total"]
    assert labels2 == {"t": "a\nb"}


def test_collector_failure_does_not_kill_the_scrape():
    r = MetricRegistry()
    r.gauge("ok").set(1)
    r.add_collector(lambda: 1 / 0)
    r.add_collector(lambda: {"fine": 2, "skipped": "not-a-number"})
    text = r.render()
    assert "ok 1" in text and "fine 2" in text
    assert "skipped" not in text


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------

def test_flight_recorder_rings_bounded_and_routed():
    fr = FlightRecorder(per_job=3, max_jobs=2, global_events=4)
    for i in range(5):
        fr.record("e", {"job": "j1", "i": i})
    evs = fr.events("j1")
    assert [e["i"] for e in evs] == [2, 3, 4]  # last 3 only
    fr.record("g", {})                          # global ring
    assert fr.events()[-1]["ev"] == "g"
    # a third job ring evicts the oldest wholesale
    fr.record("e", {"job": "j2"})
    fr.record("e", {"job": "j3"})
    assert fr.jobs() == ["j2", "j3"]
    fr.forget("j2")
    assert fr.jobs() == ["j3"]


def test_flight_recorder_thread_context_routes_unlabeled_events():
    fr = FlightRecorder()
    with fr.job_context("j9"):
        fr.record("engine_event", {"detail": 1})
    fr.record("after", {})
    assert [e["ev"] for e in fr.events("j9")] == ["engine_event"]
    assert [e["ev"] for e in fr.events()] == ["after"]


def test_fault_event_triggers_dump_into_trace():
    """Recording a fault_inject/chaos_inject event dumps the owning
    ring to the active tracer immediately — the ring's tail AT the
    moment of injection is preserved even if retries later succeed."""
    buf = io.StringIO()
    fr = FlightRecorder()
    obs.install_flight(fr)
    try:
        with obs.tracing(buf):
            obs.event("retry", job="j1", fault_class="resource")
            obs.event("fault_inject", job="j1", kind="oom",
                      phase="dispatch")
    finally:
        obs.uninstall_flight()
    dumps = [json.loads(line) for line in buf.getvalue().splitlines()
             if '"flight_dump"' in line]
    assert len(dumps) == 1 and dumps[0]["job"] == "j1"
    assert "fault_inject" in dumps[0]["reason"]
    kinds = [e["ev"] for e in dumps[0]["events"]]
    assert kinds == ["retry", "fault_inject"]
    assert fr.dumps == 1


def test_prefetch_worker_inherits_flight_job_context():
    """Regression: events emitted on a prefetch WORKER thread (read
    faults/retries while pre-reading a served job's chunks) must land
    in the ring of the job whose step created the prefetcher —
    thread-locals don't cross threads, so the worker re-enters the
    creating thread's context explicitly."""
    from sheep_tpu.utils.prefetch import prefetch

    fr = FlightRecorder()
    obs.install_flight(fr)
    try:
        def reader():
            obs.event("retry", fault_class="transient", kind="read")
            yield 1

        with fr.job_context("j7"):
            pf = prefetch(reader(), depth=1)
        assert next(pf) == 1
        pf.close()
        assert [e["ev"] for e in fr.events("j7")] == ["retry"]
        assert fr.events() == []
    finally:
        obs.uninstall_flight()


def test_dump_never_records_itself():
    fr = FlightRecorder()
    obs.install_flight(fr)
    try:
        fr.record("a", {"job": "j1"})
        fr.dump("j1", reason="manual")   # untraced: stderr fallback
        assert [e["ev"] for e in fr.events("j1")] == ["a"]
    finally:
        obs.uninstall_flight()


def test_dump_all_sweeps_global_and_job_rings():
    buf = io.StringIO()
    fr = FlightRecorder()
    fr.record("g", {})
    fr.record("x", {"job": "j1"})
    with obs.tracing(buf):
        assert fr.dump_all(reason="shutdown") == 2
    jobs = sorted(json.loads(line)["job"]
                  for line in buf.getvalue().splitlines()
                  if '"flight_dump"' in line)
    assert jobs == ["_daemon", "j1"]


# ---------------------------------------------------------------------------
# disabled-path overhead
# ---------------------------------------------------------------------------

def test_disabled_and_flight_only_paths_are_cheap():
    """obs.event with NOTHING installed is two global reads; with only
    the flight recorder it is one dict build + one deque append. The
    bounds are deliberately loose (shared CI boxes) — they catch a
    path that accidentally grew I/O or locks-per-call, not scheduler
    jitter."""
    n = 20000
    t0 = time.perf_counter()
    for i in range(n):
        obs.event("tick", i=i)
    disabled_s = time.perf_counter() - t0
    assert disabled_s < 0.5, f"disabled obs.event path: {disabled_s}s"

    obs.install_flight(FlightRecorder())
    try:
        t0 = time.perf_counter()
        for i in range(n):
            obs.event("tick", i=i)
        flight_s = time.perf_counter() - t0
    finally:
        obs.uninstall_flight()
    assert flight_s < 2.0, f"flight-recorder path: {flight_s}s"


# ---------------------------------------------------------------------------
# sheeptop rendering (pure string assembly — no daemon needed)
# ---------------------------------------------------------------------------

def test_sheeptop_render_lines_from_model():
    from sheep_tpu.server import sheeptop

    text = build_golden_registry().render() + (
        "sheepd_active_jobs 1\nsheepd_reserved_bytes 1048576\n"
        "sheepd_budget_bytes 4194304\nsheepd_flight_dumps 0\n"
        "sheepd_uptime_seconds 42\n")
    model = {"metrics": metrics_mod.parse_prometheus(text),
             "jobs": [{"job_id": "j1", "tenant": "alice",
                       "state": "running", "phase": "build",
                       "steps": 12, "start_t": 100.0},
                      {"job_id": "j2", "tenant": "bob",
                       "state": "done", "steps": 30, "start_t": 90.0,
                       "end_t": 104.0, "wall_s": 14.0,
                       "results": [{"k": 8, "cut_ratio": 0.1252,
                                    "balance": 1.049}]}],
             "t": 110.0}
    lines = sheeptop.render_lines(model)
    joined = "\n".join(lines)
    assert "queue=3" in joined and "active=1" in joined
    assert "1.0MiB/4.0MiB" in joined
    assert "alice" in joined and "p99" in joined
    assert "build" in joined and "10.0s" in joined
    # quality columns (ISSUE 13): done jobs show their final score,
    # running jobs show the placeholder
    assert "cut" in lines[-3] and "bal" in lines[-3]  # header row
    j1 = next(ln for ln in lines if ln.startswith("j1"))
    j2 = next(ln for ln in lines if ln.startswith("j2"))
    assert "12.52%" in j2 and "1.049" in j2
    assert j1.rstrip().endswith("-")
    rows = sheeptop.tenant_slo_rows(model["metrics"])
    assert rows and rows[0]["tenant"] == "alice" \
        and rows[0]["requests"] == 3
