"""Vertex-sharded big-V pipeline vs the sequential oracle (SURVEY.md §7
hard part #2; BASELINE.md eval config 5 class).

Tables are block-sharded (no replicated O(V) state) and the displacement
fixpoint runs as one distributed forest through routed collectives; the
elimination tree is order-determined, so results must match the oracle
EXACTLY on every shape — including the ones that stress routing (hubs
concentrating requests on one owner) and displacement chains.
"""

import numpy as np
import pytest

import jax

from sheep_tpu.core import pure
from sheep_tpu.io import generators
from sheep_tpu.io.edgestream import EdgeStream
from sheep_tpu.parallel.bigv import BigVPipeline
from sheep_tpu.parallel.mesh import shards_mesh

pytestmark = pytest.mark.skipif(jax.device_count() < 8,
                                reason="needs 8 virtual devices")


def _run(e, n, k=8, n_devices=8, chunk_edges=128, jumps=4):
    mesh = shards_mesh(n_devices)
    pipe = BigVPipeline(n, chunk_edges, mesh, jumps=jumps)
    return pipe.run(EdgeStream.from_array(e, n_vertices=n), k=k,
                    comm_volume=True)


def _oracle(e, n, k=8):
    ref = pure.partition_arrays(e, k, n=n)
    tree = pure.build_elim_tree(e, pure.elimination_order(pure.degrees(e, n)))
    return ref, tree.parent


CASES = {
    "karate": (generators.karate_club(), 34),
    "rmat9": (generators.rmat(9, 8, seed=21), 512),
    "grid": (generators.grid_graph(16, 16), 256),
    "path": (generators.path_graph(200), 200),
    "star_hub": (generators.star_graph(300), 300),  # all requests -> 1 owner
    "two_components": (
        np.concatenate([generators.path_graph(40),
                        40 + generators.star_graph(50)]), 90),
}


@pytest.fixture(params=list(CASES))
def graph(request):
    return CASES[request.param]


def test_bigv_matches_oracle_exactly(graph):
    e, n = graph
    out = _run(e, n)
    ref, expect_parent = _oracle(e, n)
    np.testing.assert_array_equal(out["parent"], expect_parent)
    assert out["total_edges"] == ref.total_edges
    assert out["edge_cut"] == ref.edge_cut
    assert out["comm_volume"] == ref.comm_volume
    np.testing.assert_array_equal(out["assignment"], ref.assignment)


@pytest.mark.parametrize("n_devices", [1, 2, 3, 5, 8])
def test_bigv_device_count_invariance(n_devices):
    e = generators.rmat(8, 8, seed=33)
    n = 256
    out = _run(e, n, n_devices=n_devices)
    _, expect_parent = _oracle(e, n)
    np.testing.assert_array_equal(out["parent"], expect_parent)


@pytest.mark.parametrize("jumps", [1, 2, 8])
def test_bigv_jumps_invariance(jumps):
    """The climb depth per round is a performance knob, never a
    correctness one."""
    e = generators.rmat(8, 8, seed=34)
    n = 256
    out = _run(e, n, jumps=jumps)
    _, expect_parent = _oracle(e, n)
    np.testing.assert_array_equal(out["parent"], expect_parent)


def test_bigv_worst_case_displacement_order():
    """Descending pos[hi] streaming maximizes displacement chains through
    the routed scatter replies."""
    e, n = generators.rmat(9, 4, seed=7), 512
    pos_np = pure.elimination_order(pure.degrees(e, n))
    key = np.maximum(pos_np[e[:, 0]], pos_np[e[:, 1]])
    out = _run(e[np.argsort(-key, kind="stable")], n, chunk_edges=64)
    _, expect_parent = _oracle(e, n)
    np.testing.assert_array_equal(out["parent"], expect_parent)


def test_bigv_duplicates_and_self_loops():
    base = generators.random_graph(60, 150, seed=17)
    loops = np.stack([np.arange(10), np.arange(10)], axis=1)
    e = np.concatenate([base, base, loops, base])
    rng = np.random.default_rng(5)
    e = e[rng.permutation(len(e))]
    out = _run(e, 60)
    ref, expect_parent = _oracle(e, 60)
    np.testing.assert_array_equal(out["parent"], expect_parent)
    assert out["edge_cut"] == ref.edge_cut


def test_bigv_backend_registration():
    from sheep_tpu.backends.base import get_backend, list_backends

    assert "tpu-bigv" in list_backends()
    e = generators.rmat(8, 8, seed=35)
    n = 256
    res = get_backend("tpu-bigv", chunk_edges=300).partition(
        EdgeStream.from_array(e, n_vertices=n), 8)
    ref = pure.partition_arrays(e, 8, n=n)
    assert res.edge_cut == ref.edge_cut
    assert res.comm_volume == ref.comm_volume
    np.testing.assert_array_equal(res.assignment, ref.assignment)


def test_bigv_per_device_tables_are_sharded():
    """The whole point: no device holds a full O(V) table. Check the
    placed shards' per-device byte footprint."""
    n = 1 << 12
    mesh = shards_mesh(8)
    pipe = BigVPipeline(n, 128, mesh)
    sharded = pipe._shard_table(np.full(n + 1, n, np.int32))
    shard_shapes = {s.data.shape for s in sharded.addressable_shards}
    assert shard_shapes == {(pipe.B,)}
    assert pipe.B < (n + 1) / 4  # 8 devices -> each holds ~1/8


def test_bigv_lift_bulk_and_compaction_paths():
    """Exercise the bulk-phase stream-descent LIFT kernel and the
    dedup'd compaction in the default suite: every other test here uses
    tiny chunks (Q <= TAIL_Q), which run jump rounds only. RMAT-13 ef16
    at D=8 gives a per-device Q of 16384 > TAIL_Q = 8192, so the first
    segments run the lifting climb, then the live-set collapse triggers
    compaction (with in-shard dedup) and the jump tail. The forest must
    still match the oracle exactly."""
    n = 1 << 13
    e = generators.rmat(13, 16, seed=41)
    out = _run(e, n, chunk_edges=len(e))
    _, expect_parent = _oracle(e, n)
    np.testing.assert_array_equal(out["parent"], expect_parent)
    st = out["build_stats"]
    assert st.get("compactions", 0) >= 1, st
    assert st.get("collective_bytes", 0) > 0


def test_bigv_hoisted_lifting_ab_identical():
    """The per-segment (stale) lifting stack must not change the forest:
    hoist_bytes=0 (per-round squaring, the round-2 behavior) vs the
    default hoisted stack, same graph, bulk-regime chunk width. Also
    pins the byte-cap arithmetic: the stack never exceeds the budget
    and never exceeds lift_levels - 1."""
    n = 1 << 13
    e = generators.rmat(13, 16, seed=5)
    mesh = shards_mesh(8)
    outs = {}
    for hb in (0, 1 << 30):
        pipe = BigVPipeline(n, len(e), mesh, hoist_bytes=hb)
        assert pipe.hoist_levels == (0 if hb == 0 else pipe.lift_levels - 1)
        assert pipe.hoist_levels * 4 * pipe.B <= max(hb, 0)
        outs[hb] = pipe.run(EdgeStream.from_array(e, n_vertices=n), k=8)
    np.testing.assert_array_equal(outs[0]["parent"], outs[1 << 30]["parent"])
    np.testing.assert_array_equal(outs[0]["assignment"],
                                  outs[1 << 30]["assignment"])
    # a byte budget smaller than one table block disables hoisting
    tiny = BigVPipeline(n, len(e), mesh, hoist_bytes=4 * 100)
    assert 4 * 100 < 4 * tiny.B  # premise: budget < one block
    assert tiny.hoist_levels == 0


def test_bigv_balance_budget_respected():
    """--balance BETA threads to tpu-bigv exactly like the flat backends
    (the CLI converts BETA to alpha = BETA - 1; the backend ctor
    forwards it to the host tree split): the delivered balance obeys
    max load <= BETA * total/k + max_w, while the alpha=1.0 default run
    exceeds that bound on the same graph — the default that shipped the
    committed k=1024 artifacts at balance ~1.97 (ROADMAP item 5)."""
    from sheep_tpu.backends.base import get_backend

    e = generators.rmat(10, 8, seed=7)
    n, k, beta = 1 << 10, 64, 1.1

    def run(alpha):
        return get_backend("tpu-bigv", chunk_edges=512, alpha=alpha,
                           n_devices=8).partition(
            EdgeStream.from_array(e, n_vertices=n), k, comm_volume=False)

    default, tight = run(1.0), run(beta - 1.0)
    bound = beta + k * 1.0 / n  # balance form of the +max_w slack (unit)
    assert tight.balance <= bound + 1e-9, tight.balance
    assert default.balance > bound, \
        "default-alpha run is inside the budget; the A/B no longer " \
        "demonstrates the --balance gap"
