"""Documented-envelope rejection + Python-API validation tests.

SURVEY.md §2 #1: trillion-edge capable means failing loudly at the
documented boundary — a graph beyond a backend's envelope (>= 2^31
vertex ids on int32-table TPU backends) must reject up front at the CLI,
not stack-trace from inside the degrees loop.
"""

import numpy as np
import pytest

from sheep_tpu.io import formats, generators
from sheep_tpu.io.edgestream import EdgeStream
from sheep_tpu.types import MAX_TPU_VERTICES, UnsupportedGraphError


@pytest.mark.parametrize("backend", ["tpu", "tpu-sharded", "tpu-bigv"])
def test_tpu_backends_reject_huge_v_up_front(backend):
    from sheep_tpu.backends.base import get_backend, list_backends

    if backend not in list_backends():
        pytest.skip(f"{backend} unavailable")
    es = EdgeStream.from_array(np.array([[0, 1]], dtype=np.int64),
                               n_vertices=MAX_TPU_VERTICES + 2)
    with pytest.raises(UnsupportedGraphError, match="int32"):
        get_backend(backend).partition(es, 2)


def test_cli_rejects_huge_v_cleanly(tmp_path, capsys):
    """CLI exit code 2 + a one-line error, no traceback."""
    from sheep_tpu import cli
    from sheep_tpu.backends.base import list_backends

    if "tpu" not in list_backends():
        pytest.skip("tpu backend unavailable")

    p = str(tmp_path / "tiny.edges")
    formats.write_edges(p, generators.karate_club())
    rc = cli.main(["--input", p, "--k", "2", "--backend", "tpu",
                   "--num-vertices", str(2**31 + 5)])
    assert rc == 2
    err = capsys.readouterr().err
    assert "int32" in err and "--backend cpu" in err


def test_warm_schedule_python_api_validation():
    """_resolve silently promotes levels <= 0 to full depth — the Python
    API must reject malformed warm entries instead (ADVICE r2)."""
    jnp = pytest.importorskip("jax.numpy")

    from sheep_tpu.ops import elim

    n = 8
    P = jnp.full(n + 1, n, dtype=jnp.int32)
    lo = jnp.full(4, n, dtype=jnp.int32)
    hi = jnp.full(4, n, dtype=jnp.int32)
    with pytest.raises(ValueError, match="warm_schedule"):
        elim.fold_edges_adaptive_pos(P, lo, hi, n, warm_schedule=((1, 0),))
    with pytest.raises(ValueError, match="warm_schedule"):
        elim.fold_edges_adaptive_pos(P, lo, hi, n, warm_schedule=((0, 8),))


def test_pure_backend_takes_alpha():
    """--alpha routes to every built-in backend uniformly (ADVICE r2: it
    was silently dropped for pure)."""
    from sheep_tpu.backends.base import get_backend

    e = generators.karate_club()
    es = EdgeStream.from_array(e, n_vertices=34)
    tight = get_backend("pure", alpha=1.0).partition(es, 4)
    loose = get_backend("pure", alpha=1.6).partition(es, 4)
    # alpha=1.6 provably changes the result on karate k=4 (cut 47 -> 39,
    # balance 1.059 -> 1.529); identical outputs mean alpha was dropped
    assert not np.array_equal(tight.assignment, loose.assignment)
    assert (tight.edge_cut, tight.balance) != (loose.edge_cut, loose.balance)
