"""Result-store durability contract (ISSUE 16 satellite).

The content-addressed result cache may only ever cost a rebuild —
never serve a torn, stale or wrong answer. These tests pin that down:
torn/partial entries are quarantined or refused (per SHEEP_IO_POLICY,
the journal's damage contract), eviction under a tiny byte cap drops
oldest-first, and a kill -9 landing between the journal terminal and
the store publish resolves to a bit-identical rebuild on the next
submit of the same digest.
"""

import os
import time

import numpy as np
import pytest

from sheep_tpu.server.resultstore import ResultStore, ResultStoreError


def dig(i: int) -> str:
    return f"{i:040x}"


def entry(i: int = 0, pad: int = 0) -> dict:
    return {"t": 1.0, "tenant": "t", "n_vertices": 8,
            "results": [{"k": 4, "edge_cut": i, "pad": "x" * pad}]}


def entry_path(rs: ResultStore, digest: str) -> str:
    return os.path.join(rs.root, digest + ".json")


def test_round_trip_and_miss(tmp_path):
    rs = ResultStore(str(tmp_path / "r"))
    assert rs.get(dig(1)) is None
    assert rs.put(dig(1), entry(1))
    doc = rs.get(dig(1))
    assert doc["digest"] == dig(1)
    assert doc["results"][0]["edge_cut"] == 1
    assert rs.bytes_used > 0


def test_bad_digest_refused(tmp_path):
    rs = ResultStore(str(tmp_path / "r"))
    for bad in ("", "../../etc/passwd", "ABC", "a/b"):
        with pytest.raises(ValueError):
            rs.get(bad)


def test_torn_entry_is_a_miss_under_quarantine(tmp_path, monkeypatch):
    """A partial write / torn tail NEVER serves: quarantine policy
    reports a miss and drops the carcass so the job rebuilds."""
    monkeypatch.setenv("SHEEP_IO_POLICY", "quarantine")
    rs = ResultStore(str(tmp_path / "r"))
    assert rs.put(dig(2), entry(2))
    path = entry_path(rs, dig(2))
    with open(path, "rb") as f:
        blob = f.read()
    with open(path, "wb") as f:
        f.write(blob[: len(blob) // 2])
    assert rs.get(dig(2)) is None
    assert not os.path.exists(path), "damaged entry must be dropped"


def test_torn_entry_raises_under_strict(tmp_path, monkeypatch):
    """Default (strict) policy refuses to silently rebuild: damage is
    an error the operator sees, exactly like journal replay."""
    monkeypatch.setenv("SHEEP_IO_POLICY", "strict")
    rs = ResultStore(str(tmp_path / "r"))
    assert rs.put(dig(3), entry(3))
    path = entry_path(rs, dig(3))
    with open(path, "a", encoding="utf-8") as f:
        f.write("garbage-tail")
    with pytest.raises(ResultStoreError):
        rs.get(dig(3))
    assert os.path.exists(path), "strict policy must not destroy evidence"


def test_bitrot_checksum_mismatch_is_damage(tmp_path, monkeypatch):
    """Valid JSON whose body no longer matches the embedded sha (bit
    rot, hand edits) is damage, not an answer."""
    monkeypatch.setenv("SHEEP_IO_POLICY", "quarantine")
    rs = ResultStore(str(tmp_path / "r"))
    assert rs.put(dig(4), entry(4))
    path = entry_path(rs, dig(4))
    with open(path, "r", encoding="utf-8") as f:
        text = f.read()
    assert '"tenant":"t"' in text
    with open(path, "w", encoding="utf-8") as f:
        f.write(text.replace('"tenant":"t"', '"tenant":"u"'))
    assert rs.get(dig(4)) is None
    assert not os.path.exists(path)


def test_entry_under_wrong_digest_is_damage(tmp_path, monkeypatch):
    """A (checksum-valid) entry filed under a different digest must
    not serve — content addressing is the whole correctness story."""
    monkeypatch.setenv("SHEEP_IO_POLICY", "quarantine")
    rs = ResultStore(str(tmp_path / "r"))
    assert rs.put(dig(5), entry(5))
    os.replace(entry_path(rs, dig(5)), entry_path(rs, dig(6)))
    assert rs.get(dig(6)) is None


def test_newer_version_entry_skipped_not_fatal(tmp_path):
    rs = ResultStore(str(tmp_path / "r"))
    assert rs.put(dig(7), entry(7))
    path = entry_path(rs, dig(7))
    with open(path, "r", encoding="utf-8") as f:
        text = f.read()
    # a future daemon's entry: version bumped, checksum recomputed
    import json as json_mod

    from sheep_tpu.server import resultstore as rs_mod

    doc = json_mod.loads(text)
    doc.pop("sha")
    doc["v"] = rs_mod.STORE_VERSION + 1
    doc["sha"] = rs_mod._body_sha(doc)
    with open(path, "w", encoding="utf-8") as f:
        json_mod.dump(doc, f)
    assert rs.get(dig(7)) is None  # skipped, no raise either policy


def test_tmp_orphans_swept_on_open(tmp_path):
    root = str(tmp_path / "r")
    os.makedirs(root)
    orphan = os.path.join(root, dig(8) + ".json.tmp")
    with open(orphan, "w", encoding="utf-8") as f:
        f.write('{"half-written":')
    ResultStore(root)
    assert not os.path.exists(orphan)


def test_eviction_oldest_first_under_tiny_cap(tmp_path):
    probe = ResultStore(str(tmp_path / "probe"))
    assert probe.put(dig(0), entry(0, pad=64))
    size = probe.bytes_used
    # room for two entries plus slack, never three
    rs = ResultStore(str(tmp_path / "r"), max_bytes=2 * size + size // 2)
    for i in (1, 2, 3):
        assert rs.put(dig(i), entry(i, pad=64))
        # publish order == mtime order even on coarse filesystem clocks
        os.utime(entry_path(rs, dig(i)), ns=(i * 10**9, i * 10**9))
    assert rs.get(dig(1)) is None, "oldest entry must be the evictee"
    assert rs.get(dig(2)) is not None
    assert rs.get(dig(3)) is not None
    assert rs.evictions == 1
    assert rs.bytes_used <= rs.max_bytes


def test_entry_larger_than_cap_refused(tmp_path):
    rs = ResultStore(str(tmp_path / "r"), max_bytes=128)
    assert rs.put(dig(9), entry(9, pad=4096)) is False
    assert rs.get(dig(9)) is None
    assert rs.bytes_used == 0


def test_disabled_store_is_inert(tmp_path):
    rs = ResultStore(str(tmp_path / "r"), max_bytes=0)
    assert rs.put(dig(1), entry(1)) is False
    assert rs.get(dig(1)) is None
    assert rs.bytes_used == 0


def test_crash_between_terminal_and_publish_rebuilds_identically(tmp_path):
    """kill -9 after the journal's fsync'd DONE terminal but before
    the store publish leaves NO entry (at worst a .tmp orphan, swept
    on open). The next identical submit must miss the store and
    rebuild — bit-identical to the original — never serve a torn or
    wrong answer."""
    import threading

    from sheep_tpu.server.protocol import JobSpec
    from sheep_tpu.server.scheduler import Scheduler

    store_root = str(tmp_path / "results")
    body = {"input": "rmat:8:4:3", "k": [4], "chunk_edges": 512}

    def run_one():
        sched = Scheduler(result_store=store_root)
        t = threading.Thread(target=sched.run, daemon=True)
        t.start()
        try:
            job = sched.submit(JobSpec.from_request(body, tenant="t"))
            job = sched.wait(job.id, timeout_s=240)
            assert job.state == "done", job.error
            deadline = time.time() + 30
            while not sched.lookup_digest(job.digest) \
                    and time.time() < deadline:
                time.sleep(0.01)
            assert sched.lookup_digest(job.digest)
            return (job.digest, job.results[0].assignment.copy(),
                    int(job.results[0].edge_cut),
                    int(job.stats.get("result_cache_hit", 0)))
        finally:
            sched.shutdown()
            t.join(timeout=30)

    digest, a0, cut0, hit0 = run_one()
    assert hit0 == 0
    # simulate the crash window: the publish never landed — drop the
    # entry and leave a torn publish orphan behind
    os.unlink(os.path.join(store_root, digest + ".json"))
    with open(os.path.join(store_root, digest + ".json.tmp"), "w",
              encoding="utf-8") as f:
        f.write('{"torn":')
    _, a1, cut1, hit1 = run_one()
    assert hit1 == 0, "a missing entry must rebuild, not hit"
    assert cut1 == cut0
    assert np.array_equal(a1, a0), "rebuild must be bit-identical"
