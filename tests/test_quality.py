"""Quality observability plane (ISSUE 13): the cut ledger, the recipe
advisor, the new scenario generators, and the quality CI gate."""

import importlib.util
import io
import json
import os
import sys
from contextlib import redirect_stdout

import numpy as np
import pytest

import sheep_tpu
from sheep_tpu import obs

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


quality_regress = _load_tool("quality_regress")
trace_report = _load_tool("trace_report")


# ---------------------------------------------------------------------------
# recipe advisor (ops/degrees.py)
# ---------------------------------------------------------------------------

def test_advise_recipe_signal_law():
    from sheep_tpu.ops.degrees import advise_recipe

    # the measured s22 shape: V=2^22, E=16*2^22 (avg degree 32), k=64:
    # signal 0.5 < 1 -> the winning [8, 8] split, final refine, balance
    a = advise_recipe(1 << 22, 16 << 22, 64)
    assert a["mode"] == "hier" and a["k_levels"] == [8, 8]
    assert a["final_refine"] > 0 and a["balance"] > 1.0
    assert a["signal"] == pytest.approx(0.5)
    # healthy signal: flat is the right call
    assert advise_recipe(1 << 22, 16 << 22, 8)["mode"] == "flat"
    # unknown edge count: no verdict, never a guess
    assert advise_recipe(1 << 22, None, 64)["mode"] == "unknown"
    # prime k past the per-level cap: no usable split, stay flat
    assert advise_recipe(1 << 10, 4 << 10, 13)["mode"] == "flat"


def test_factor_levels():
    from sheep_tpu.ops.degrees import factor_levels

    assert factor_levels(64, 32) == [8, 8]
    assert factor_levels(16, 8) == [4, 4]
    assert factor_levels(8, 32) == [8]          # fits one level
    assert factor_levels(60, 5) == [5, 4, 3]
    assert factor_levels(7, 4) is None          # prime past the cap


def test_cli_advisor_prints_and_auto_recipe_bit_identical(tmp_path,
                                                          capsys):
    """The acceptance contract: the naive flat invocation PRINTS the
    recipe, and --auto-recipe reproduces the manual-flags invocation
    bit for bit (same code path, same knobs)."""
    from sheep_tpu import cli
    from sheep_tpu.io.formats import read_partition

    spec = "sbm-hash:9:16:0.05:4:1"  # avg degree 8, k=16 -> signal 0.5
    naive_out = str(tmp_path / "naive.pbin")
    rc = cli.main(["--input", spec, "--k", "16", "--backend", "cpu",
                   "--refine", "1", "--no-comm-volume", "--json",
                   "--output", naive_out])
    assert rc == 0
    err = capsys.readouterr().err
    assert "quality advisor" in err
    assert "--k-levels 4,4" in err and "--auto-recipe" in err

    auto_out = str(tmp_path / "auto.pbin")
    # explicit --refine 0/--final-refine 2 keep the test out of the
    # compile-heavy per-level refine; the advisor recipe honors both
    rc = cli.main(["--input", spec, "--k", "16", "--backend", "cpu",
                   "--refine", "0", "--final-refine", "2",
                   "--no-comm-volume", "--auto-recipe",
                   "--json", "--output", auto_out])
    assert rc == 0
    cap = capsys.readouterr()
    auto_line = json.loads(cap.out.strip().splitlines()[-1])
    assert auto_line["k"] == 16 and "+hier" in auto_line["backend"]

    manual_out = str(tmp_path / "manual.pbin")
    rc = cli.main(["--input", spec, "--k-levels", "4,4", "--backend",
                   "cpu", "--refine", "0", "--final-refine", "2",
                   "--balance", "1.05", "--no-comm-volume", "--json",
                   "--output", manual_out])
    assert rc == 0
    manual_line = json.loads(
        capsys.readouterr().out.strip().splitlines()[-1])
    np.testing.assert_array_equal(read_partition(auto_out),
                                  read_partition(manual_out))
    assert auto_line["edge_cut"] == manual_line["edge_cut"]
    assert auto_line["balance"] == manual_line["balance"]


def test_cli_auto_recipe_healthy_signal_stays_flat(capsys):
    from sheep_tpu import cli

    rc = cli.main(["--input", "sbm-hash:9:4:0.05:16:1", "--k", "4",
                   "--backend", "pure", "--no-comm-volume",
                   "--auto-recipe", "--json"])
    assert rc == 0
    cap = capsys.readouterr()
    assert "flat path as asked" in cap.err
    line = json.loads(cap.out.strip().splitlines()[-1])
    assert "+hier" not in line["backend"]


def test_cli_auto_recipe_validation(tmp_path):
    from sheep_tpu import cli
    from sheep_tpu.io import formats, generators

    p = str(tmp_path / "g.edges")
    formats.write_edges(p, generators.karate_club())
    for argv in (["--k-levels", "2,2", "--auto-recipe"],
                 ["--k", "4,8", "--auto-recipe"],
                 ["--score-only", p, "--auto-recipe"],
                 # flags a --k-levels run cannot honor reject UP FRONT,
                 # not data-dependently on the input's degree signal
                 ["--k", "4", "--inflight", "2", "--auto-recipe"],
                 ["--k", "4", "--dispatch-batch", "2", "--auto-recipe"]):
        with pytest.raises(SystemExit):
            cli.main(["--input", p] + argv)


def test_cli_auto_recipe_explicit_final_refine_zero(capsys):
    """An EXPLICIT --final-refine 0 must survive into the applied
    recipe (review finding: the falsy-zero `or` silently substituted
    the advisor's default 10)."""
    from sheep_tpu import cli

    rc = cli.main(["--input", "sbm-hash:9:16:0.05:4:1", "--k", "16",
                   "--backend", "pure", "--refine", "0",
                   "--final-refine", "0", "--no-comm-volume",
                   "--auto-recipe", "--json"])
    assert rc == 0
    cap = capsys.readouterr()
    assert "--final-refine 0" in cap.err
    line = json.loads(cap.out.strip().splitlines()[-1])
    assert "+hier" in line["backend"]


# ---------------------------------------------------------------------------
# the cut ledger (hierarchy.py + ops/refine.py + ops/split.py)
# ---------------------------------------------------------------------------

SPEC = "sbm-hash:10:16:0.05:8:1"


def test_hierarchy_ledger_levels_sum_to_cut(tmp_path):
    trace = str(tmp_path / "t.jsonl")
    with obs.tracing(trace):
        res = sheep_tpu.partition_hierarchical(
            SPEC, [4, 4], backend="pure", refine=0, final_refine=2,
            comm_volume=False)
    d = res.diagnostics
    assert d["cut_level0"] + d["cut_level1"] == res.edge_cut
    assert d["cut_ratio_level0"] == pytest.approx(
        d["cut_level0"] / res.total_edges, abs=1e-5)
    assert "ledger_parts_at_capacity" in d
    assert "final_refine_repaired" in d
    evs = [json.loads(ln) for ln in open(trace)]
    ql = [e for e in evs if e["event"] == "quality_ledger"]
    assert len(ql) == 1
    q = ql[0]
    assert q["k_levels"] == [4, 4]
    assert sum(lv["cut"] for lv in q["levels"]) == q["edge_cut"]
    assert [lv["level"] for lv in q["levels"]] == [0, 1]
    # the ledger prices what SHIPPED: post-final-refine labels
    assert q["edge_cut"] == res.edge_cut
    # the per-level spans nested in the trace
    names = {e.get("span") for e in evs if e["event"] == "span_start"}
    assert {"hier_partition", "hier_spill", "refine"} <= names


def test_hierarchy_ledger_single_level():
    res = sheep_tpu.partition_hierarchical(SPEC, [4], backend="pure",
                                           refine=0, comm_volume=False)
    assert res.diagnostics["cut_level0"] == res.edge_cut


def test_level_ledger_function_three_levels():
    from sheep_tpu.hierarchy import level_ledger
    from sheep_tpu.io.edgestream import open_input

    with open_input(SPEC) as es:
        res = sheep_tpu.partition_hierarchical(
            SPEC, [2, 2, 2], backend="pure", refine=0,
            comm_volume=False)
        rows = level_ledger(es, res.assignment, [2, 2, 2],
                            res.edge_cut, res.total_edges)
    assert [r["k"] for r in rows] == [2, 4, 8]
    assert sum(r["cut"] for r in rows) == res.edge_cut
    assert all(r["cut"] >= 0 for r in rows)


def test_refine_move_accounting():
    from sheep_tpu.io.edgestream import open_input
    from sheep_tpu.ops.refine import refine_assignment

    with open_input(SPEC) as es:
        n = es.num_vertices
        rng = np.random.default_rng(0)
        bad = rng.integers(0, 16, n).astype(np.int32)
        # a tight cap forces capacity blocking: plenty of vertices want
        # to move toward the planted blocks, few fit
        _, stats = refine_assignment(bad, es, n, 16, rounds=2,
                                     alpha=1.01)
    assert stats["refine_moves_wanted"] >= stats["refine_moves_applied"]
    assert stats["refine_moves_capacity_blocked"] == \
        stats["refine_moves_wanted"] - stats["refine_moves_applied"]
    assert stats["refine_moves_wanted"] > 0
    assert stats["refine_moves_capacity_blocked"] > 0


def test_refine_round_events_and_counters(tmp_path):
    from sheep_tpu.io.edgestream import open_input
    from sheep_tpu.ops.refine import refine_assignment

    trace = str(tmp_path / "t.jsonl")
    with open_input(SPEC) as es:
        n = es.num_vertices
        rng = np.random.default_rng(0)
        bad = rng.integers(0, 16, n).astype(np.int32)
        with obs.tracing(trace) as tr:
            refine_assignment(bad, es, n, 16, rounds=2, alpha=1.10)
            counters = dict(tr.counters)
    evs = [json.loads(ln) for ln in open(trace)]
    rounds = [e for e in evs if e["event"] == "refine_round"]
    assert rounds, "per-round ledger events missing"
    for e in rounds:
        assert e["moves_applied"] <= e["moves_wanted"]
        assert "gain" in e and "accepted" in e
    assert counters.get("refine_moves_wanted", 0) > 0
    spans = [e for e in evs if e["event"] == "span_end"
             and e.get("span") == "refine"]
    assert spans and "cut_after" in spans[0]


def test_split_balance_event(tmp_path):
    trace = str(tmp_path / "t.jsonl")
    with obs.tracing(trace):
        sheep_tpu.partition(SPEC, 4, backend="pure", comm_volume=False)
    evs = [json.loads(ln) for ln in open(trace)]
    sb = [e for e in evs if e["event"] == "split_balance"]
    assert sb and sb[0]["k"] == 4
    assert {"balance", "cap", "parts_at_capacity",
            "frozen_load_fraction"} <= set(sb[0])


def test_part_loads_accounting():
    from sheep_tpu.ops.score import part_loads_accounting

    a = np.array([0, 0, 0, 1, 2, 2], np.int32)
    acct = part_loads_accounting(a, 4, cap=2.0)
    assert acct["max_load"] == 3 and acct["empty_parts"] == 1
    assert acct["parts_at_capacity"] == 2  # loads 3 and 2 are >= cap
    assert acct["frozen_load_fraction"] == pytest.approx(5 / 6)
    w = np.array([10.0, 1, 1, 1, 1, 1])
    acct = part_loads_accounting(a, 4, weights=w, cap=100.0)
    assert acct["max_load"] == 12 and acct["parts_at_capacity"] == 0


def test_residual_attribution():
    from sheep_tpu.utils.metrics import residual_attribution

    # 1000 edges; level0 cut 300 vs planted 40 cumulative, level1 cut
    # 100 on top of planted 50 cumulative -> level0 owns the residual
    r = residual_attribution([300, 100], [0.04, 0.05], 1000)
    assert r["dominant"] == "level0_fragmentation"
    assert r["levels"][0]["excess"] == pytest.approx(0.26)
    assert r["levels"][1]["excess"] == pytest.approx(0.09)
    assert r["dominant_share"] == pytest.approx(0.26 / 0.35, abs=1e-3)
    assert residual_attribution([], [], 10) is None
    assert residual_attribution([1], [0.1, 0.2], 10) is None


# ---------------------------------------------------------------------------
# scenario generators (io/generators.py + open_input)
# ---------------------------------------------------------------------------

def test_bipartite_stream():
    from sheep_tpu.io.edgestream import open_input

    with open_input("bipartite-hash:10:4:0.02:8:1") as es:
        n = es.num_vertices
        e = es.read_all()
        half = n // 2
        assert (e[:, 0] < half).all() and (e[:, 1] >= half).all(), \
            "every edge must cross the halves"
        # deterministic random access
        assert np.array_equal(es._range(100, 50),
                              es.read_all()[100:150])
        gt = es.ground_truth()
        measured = float((gt[e[:, 0]] != gt[e[:, 1]]).mean())
        assert measured == pytest.approx(es.planted_cut_ratio(),
                                         abs=0.01)
        # grouped planted optimum shrinks with k (cross edges can land
        # in the same group)
        assert es.planted_cut_ratio(2) < es.planted_cut_ratio()


def test_nearclique_stream_is_dense_planted():
    from sheep_tpu.io import generators
    from sheep_tpu.io.edgestream import open_input

    with open_input("nearclique-hash:10:4:0.01:8:1") as es:
        assert isinstance(es, generators.NearCliqueStream)
        assert es.n_blocks == 1 << (10 - 4)
        e = es.read_all()
        gt = es.ground_truth()
        measured = float((gt[e[:, 0]] != gt[e[:, 1]]).mean())
        assert measured == pytest.approx(0.01, abs=0.01)
        # near-clique density: intra edges per block ~ ef * 2^cb = 128
        # against 120 distinct pairs — every block is near clique-dense
        intra = e[gt[e[:, 0]] == gt[e[:, 1]]]
        per_block = np.bincount(gt[intra[:, 0]], minlength=es.n_blocks)
        assert per_block.min() > 60


def test_powerlaw_sbm_stream():
    from sheep_tpu.io.edgestream import open_input

    with open_input("plsbm-hash:12:4:0.0:16:1") as es:
        e = es.read_all()
        deg = np.bincount(e.ravel(), minlength=es.num_vertices)
        # power-law within blocks: hubs far above the mean (flat SBM
        # tops out near the Poisson tail, ~2x the mean)
        assert deg.max() > 10 * deg.mean()
        gt = es.ground_truth()
        assert (gt[e[:, 0]] == gt[e[:, 1]]).all(), \
            "p_out=0 must produce zero planted cut"
    with open_input("plsbm-hash:10:4:0.05:8:1") as es:
        e = es.read_all()
        gt = es.ground_truth()
        measured = float((gt[e[:, 0]] != gt[e[:, 1]]).mean())
        assert measured == pytest.approx(0.05, abs=0.012)


def test_new_spec_validation():
    from sheep_tpu.io.edgestream import open_input

    for bad in ("bipartite-hash:10", "bipartite-hash:10:3:0.02",
                "nearclique-hash:10:12:0.01", "plsbm-hash:10:x:0.05",
                "plsbm-hash:10:1024:0.05"):
        with pytest.raises(ValueError):
            open_input(bad)
    with pytest.raises(ValueError, match="contradicts"):
        open_input("bipartite-hash:10:4:0.02", n_vertices=999)


# ---------------------------------------------------------------------------
# the quality CI gate (tools/quality_regress.py)
# ---------------------------------------------------------------------------

def _artifact(tmp_path, name, scenarios, suite=quality_regress.SUITE):
    p = str(tmp_path / name)
    json.dump({"tool": "quality_regress", "suite": suite,
               "scenarios": scenarios}, open(p, "w"))
    return p


BASE_SC = {"a": {"cut_ratio": 0.10, "balance": 1.05},
           "b": {"cut_ratio": 0.70, "balance": 1.20}}


def test_quality_regress_pass_and_detect(tmp_path):
    old = _artifact(tmp_path, "old.json", BASE_SC)
    same = _artifact(tmp_path, "same.json", BASE_SC)
    assert quality_regress.main([same, old]) == 0
    worse = _artifact(tmp_path, "worse.json",
                      {"a": {"cut_ratio": 0.15, "balance": 1.05},
                       "b": BASE_SC["b"]})
    assert quality_regress.main([worse, old, "--threshold", "0.02"]) == 2
    # a balance blow-up gates too
    fat = _artifact(tmp_path, "fat.json",
                    {"a": {"cut_ratio": 0.10, "balance": 1.40},
                     "b": BASE_SC["b"]})
    assert quality_regress.main([fat, old]) == 2
    # improvement is a pass
    better = _artifact(tmp_path, "better.json",
                       {"a": {"cut_ratio": 0.05, "balance": 1.02},
                        "b": BASE_SC["b"]})
    assert quality_regress.main([better, old]) == 0


def test_quality_regress_skipped_incomparable(tmp_path):
    old = _artifact(tmp_path, "old.json", BASE_SC)
    new = _artifact(tmp_path, "new.json",
                    {"a": BASE_SC["a"],
                     "c": {"cut_ratio": 0.3, "balance": 1.1}})
    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = quality_regress.main([new, old])
    assert rc == 0
    out = buf.getvalue()
    assert "skipped-incomparable: b, c" in out
    # json shape carries them too
    buf = io.StringIO()
    with redirect_stdout(buf):
        quality_regress.main([new, old, "--json"])
    doc = json.loads(buf.getvalue())
    assert doc["skipped"] == ["b", "c"] and not doc["regressions"]


def test_quality_regress_suite_mismatch_vacuous(tmp_path):
    old = _artifact(tmp_path, "old.json", BASE_SC, suite=0)
    new = _artifact(tmp_path, "new.json", BASE_SC)
    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = quality_regress.main([new, old])
    assert rc == 0 and "not comparable" in buf.getvalue()


def test_quality_regress_sweep_against_committed_seed(tmp_path):
    """The tier-1 wiring: ONE fast scenario run fresh must agree with
    the committed QUALITY_r01.json seed (bit-deterministic sweep); the
    other scenarios report as skipped, not as failures. The FULL sweep
    runs in tools/obs_smoke.sh leg 9."""
    seed = os.path.join(REPO, "QUALITY_r01.json")
    assert os.path.exists(seed), "committed quality seed artifact"
    fresh = str(tmp_path / "QUALITY_fresh.json")
    assert quality_regress.main(
        ["--run", fresh, "--scenarios", "rmat_expander"]) == 0
    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = quality_regress.main([fresh, seed, "--threshold", "0.001"])
    out = buf.getvalue()
    assert rc == 0, out
    assert "rmat_expander" in out and "skipped-incomparable" in out
    doc = json.load(open(fresh))
    committed = json.load(open(seed))
    assert doc["scenarios"]["rmat_expander"] == \
        committed["scenarios"]["rmat_expander"], \
        "the sweep is deterministic: a fresh run bit-equals the seed"


def test_quality_seed_artifact_contract():
    """The committed sweep covers >= 5 scenarios including the new
    bipartite + near-clique classes, and the planted hierarchical
    scenario records the per-level ledger + residual attribution."""
    doc = json.load(open(os.path.join(REPO, "QUALITY_r01.json")))
    sc = doc["scenarios"]
    assert len(sc) >= 5
    assert {"sbm_planted", "sbm_powerlaw", "rmat_expander", "bipartite",
            "near_clique"} <= set(sc)
    planted = sc["sbm_planted"]
    assert "cut_level0" in planted["levels"]
    assert "cut_level1" in planted["levels"]
    assert planted["residual"]["dominant"] in (
        "level0_fragmentation", "level1_misassignment")


# ---------------------------------------------------------------------------
# trace_report renders the quality tree
# ---------------------------------------------------------------------------

def test_trace_report_quality_tree(tmp_path):
    trace = str(tmp_path / "t.jsonl")
    with obs.tracing(trace):
        sheep_tpu.partition_hierarchical(SPEC, [4, 4], backend="pure",
                                         refine=0, final_refine=2,
                                         comm_volume=False)
    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = trace_report.main([trace])
    out = buf.getvalue()
    assert rc == 0
    assert "quality ledger:" in out
    assert "level0 (fragmentation)" in out
    assert "level1 (misassignment)" in out
    assert "final refine repaired" in out
    assert "refine rounds:" in out and "capacity-blocked" in out
    buf = io.StringIO()
    with redirect_stdout(buf):
        trace_report.main([trace, "--json"])
    doc = json.loads(buf.getvalue())
    t = doc["traces"][0]
    assert t["quality_ledgers"] and t["refine_rounds"]
    assert sum(lv["cut"] for lv in t["quality_ledgers"][0]["levels"]) \
        == t["quality_ledgers"][0]["edge_cut"]


def test_quality_dynamic_scenario_artifact():
    """ISSUE 15 satellite: the committed QUALITY_r02.json carries the
    dynamic-graph scenario (half-stream + delta epochs through the
    REAL incremental path) inside its anchored-drift bound, and
    extends QUALITY_r01.json bit-identically on the shared rows."""
    doc = json.load(open(os.path.join(REPO, "QUALITY_r02.json")))
    sc = doc["scenarios"]["dynamic_sbm"]
    assert "oneshot_cut_ratio" in sc and "anchored_drift" in sc
    assert sc["epoch"] == sc["recipe"]["dynamic"]["epochs"]
    assert sc["anchored_drift"] <= sc["recipe"]["dynamic"]["bound"]
    assert "bound_exceeded" not in sc
    r01 = json.load(open(os.path.join(REPO, "QUALITY_r01.json")))
    for name, row in r01["scenarios"].items():
        assert doc["scenarios"][name] == row, name


def test_quality_sharded_dynamic_scenario_artifact():
    """ISSUE 19 satellite: QUALITY_r03.json adds the multi-device
    dynamic scenario — the same delta epochs through the tpu-sharded
    incremental path (distributed rescore + audit on) — inside the
    same drift bound, extending QUALITY_r02.json bit-identically on
    the shared rows. The sharded fold is bit-identical to the
    single-device one, so the sharded row's quality numbers EQUAL the
    dynamic_sbm row's."""
    doc = json.load(open(os.path.join(REPO, "QUALITY_r03.json")))
    sc = doc["scenarios"]["dynamic_sbm_sharded"]
    assert sc["backend"] == "tpu-sharded"
    assert sc["epoch"] == sc["recipe"]["dynamic"]["epochs"]
    assert sc["anchored_drift"] <= sc["recipe"]["dynamic"]["bound"]
    assert "bound_exceeded" not in sc
    host = dict(doc["scenarios"]["dynamic_sbm"])
    for k in ("cut_ratio", "edge_cut", "balance", "oneshot_cut_ratio",
              "anchored_drift", "total_edges"):
        assert sc[k] == host[k], k
    r02 = json.load(open(os.path.join(REPO, "QUALITY_r02.json")))
    for name, row in r02["scenarios"].items():
        assert doc["scenarios"][name] == row, name
