"""Seeded cross-backend fuzz: many random graph shapes, every backend,
exact equality of cut/assignment/comm-volume (SURVEY.md §4.3 taken to
its limit — the elimination forest is unique given the order, and the
split/score semantics are shared, so equality is exact, not tolerant).

The quick tier (always on) runs a handful of shapes; SHEEP_FUZZ=1 runs
the full sweep. Shapes mix RMAT skew, uniform noise, self-loops,
duplicate edges, isolated vertices, and tiny k up to k > V.
"""

import os

import numpy as np
import pytest

from sheep_tpu.backends.base import get_backend, list_backends
from sheep_tpu.io.edgestream import EdgeStream

FULL = os.environ.get("SHEEP_FUZZ") == "1"


def _random_graph(rng):
    kind = rng.integers(0, 4)
    if kind == 0:  # uniform
        n = int(rng.integers(2, 400))
        m = int(rng.integers(1, 4 * n))
        e = rng.integers(0, n, size=(m, 2))
    elif kind == 1:  # skewed (hub-heavy)
        n = int(rng.integers(10, 400))
        m = int(rng.integers(n, 6 * n))
        hub = rng.integers(0, max(1, n // 10), size=m)
        other = rng.integers(0, n, size=m)
        e = np.stack([hub, other], axis=1)
    elif kind == 2:  # sparse forest-ish + noise
        n = int(rng.integers(3, 300))
        parents = rng.integers(0, np.maximum(1, np.arange(1, n)))
        e = np.stack([np.arange(1, n), parents], axis=1)
        noise = rng.integers(0, n, size=(int(rng.integers(0, n)), 2))
        e = np.concatenate([e, noise])
    else:  # dense-ish small
        n = int(rng.integers(2, 60))
        m = int(rng.integers(1, n * n // 2 + 1))
        e = rng.integers(0, n, size=(m, 2))
    # sprinkle self-loops and exact duplicates
    if len(e) > 2 and rng.random() < 0.5:
        e[rng.integers(0, len(e))] = [e[0][0], e[0][0]]
        e[rng.integers(0, len(e))] = e[1]
    return e.astype(np.int64), n


@pytest.mark.parametrize("seed", range(40 if FULL else 8))
def test_backends_agree_on_random_graphs(seed):
    rng = np.random.default_rng(1000 + seed)
    e, n = _random_graph(rng)
    k = int(rng.integers(1, n + 3))  # includes k = 1 and k > V
    chunk = int(rng.integers(8, max(9, len(e) + 1)))
    backends = [b for b in ("pure", "cpu", "tpu") if b in list_backends()]
    results = {}
    for b in backends:
        es = EdgeStream.from_array(e, n_vertices=n)
        results[b] = get_backend(b, chunk_edges=chunk).partition(
            es, k, comm_volume=True)
    ref = results[backends[0]]
    a = np.asarray(ref.assignment)
    assert len(a) == n and (a >= 0).all() and (a < max(k, 1)).all()
    for b in backends[1:]:
        r = results[b]
        assert r.edge_cut == ref.edge_cut, (seed, b)
        assert r.comm_volume == ref.comm_volume, (seed, b)
        np.testing.assert_array_equal(np.asarray(r.assignment), a,
                                      err_msg=f"seed {seed} backend {b}")


@pytest.mark.parametrize("seed", range(12 if FULL else 4))
def test_multidevice_backends_agree_on_random_graphs(seed):
    """Same exact-equality bar for the multi-device backends (8-device
    virtual mesh). Fixed chunk size: every random width would compile a
    fresh mesh program set."""
    import jax

    if jax.device_count() < 8:
        pytest.skip("needs 8 virtual devices")
    rng = np.random.default_rng(2000 + seed)
    e, n = _random_graph(rng)
    k = int(rng.integers(1, n + 3))
    targets = [b for b in ("tpu-sharded", "tpu-bigv")
               if b in list_backends()]
    # don't pass vacuously if an import regression unregistered both
    # (backends/__init__.py guards those imports with except Exception)
    assert targets, "no multi-device backend registered"
    ref_es = EdgeStream.from_array(e, n_vertices=n)
    ref = get_backend("tpu", chunk_edges=256).partition(
        ref_es, k, comm_volume=True)
    for b in targets:
        es = EdgeStream.from_array(e, n_vertices=n)
        r = get_backend(b, chunk_edges=256).partition(
            es, k, comm_volume=True)
        assert r.edge_cut == ref.edge_cut, (seed, b)
        assert r.comm_volume == ref.comm_volume, (seed, b)
        np.testing.assert_array_equal(
            np.asarray(r.assignment), np.asarray(ref.assignment),
            err_msg=f"seed {seed} backend {b}")
