"""ASan/UBSan pass over the native core (SURVEY.md §5 sanitizers).

Builds and runs the standalone sanitized selftest binary
(``make -C sheep_tpu/core/csrc sanitize``); any heap overflow, UB, or
failed invariant aborts the binary with a nonzero exit.
"""

import os
import shutil
import subprocess

import pytest

CSRC = os.path.join(os.path.dirname(__file__), "..", "sheep_tpu", "core", "csrc")


@pytest.mark.skipif(shutil.which("g++") is None or shutil.which("make") is None,
                    reason="C++ toolchain unavailable")
def test_native_core_under_sanitizers():
    proc = subprocess.run(
        ["make", "-C", CSRC, "sanitize"],
        capture_output=True, text=True, timeout=300,
    )
    # only a link/compile failure for a missing sanitizer runtime is a
    # skip; a sanitizer *report* (runtime crash) must fail the test
    if proc.returncode != 0 and "cannot find" in proc.stderr \
            and ("asan" in proc.stderr or "ubsan" in proc.stderr):
        pytest.skip(f"sanitizer runtime unavailable: {proc.stderr[-200:]}")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "selftest OK" in proc.stdout
