"""TPU-path ops vs the numpy oracle (run on CPU-jax; SURVEY.md §4.3).

The elimination tree is unique given the order, so the device fixpoint
must reproduce the oracle's parent array exactly on every graph shape —
including adversarial ones (paths, stars) that stress fixpoint depth.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from sheep_tpu.core import pure
from sheep_tpu.io import generators
from sheep_tpu.io.edgestream import EdgeStream
from sheep_tpu.ops import degrees as degrees_ops
from sheep_tpu.ops import elim as elim_ops
from sheep_tpu.ops import order as order_ops
from sheep_tpu.ops import score as score_ops
from sheep_tpu.backends.tpu_backend import TpuBackend, pad_chunk


def _cases():
    return {
        "karate": (generators.karate_club(), 34),
        "path": (generators.path_graph(64), 64),
        "star": (generators.star_graph(50), 50),
        "grid": (generators.grid_graph(8, 8), 64),
        "random": (generators.random_graph(200, 1600, seed=11), 200),
        "rmat": (generators.rmat(9, 8, seed=12), 512),
        "two_components": (
            np.concatenate([generators.path_graph(30),
                            30 + generators.grid_graph(5, 6)]), 60),
    }


@pytest.fixture(params=list(_cases()))
def graph(request):
    return _cases()[request.param]


def _device_order(e, n):
    deg = degrees_ops.init_degrees(n)
    deg = degrees_ops.degree_chunk(deg, pad_chunk(e, len(e), n), n)
    return order_ops.elimination_order(deg, n)


def test_degrees_and_order_match_oracle(graph):
    e, n = graph
    pos, order = _device_order(e, n)
    np.testing.assert_array_equal(np.asarray(pos[:n]),
                                  pure.elimination_order(pure.degrees(e, n)))
    assert int(pos[n]) == n and int(order[n]) == n


@pytest.mark.parametrize("lift_levels", [1, 0])
def test_fixpoint_tree_matches_oracle(graph, lift_levels):
    e, n = graph
    pos, order = _device_order(e, n)
    minp, rounds = elim_ops.build_chunk_step(
        jnp.full(n + 1, n, dtype=jnp.int32), pad_chunk(e, len(e), n),
        pos, order, n, lift_levels=lift_levels)
    parent = elim_ops.minp_to_parent(minp, order, n)
    expect = pure.build_elim_tree(e, pure.elimination_order(pure.degrees(e, n))).parent
    np.testing.assert_array_equal(parent, expect)
    assert int(rounds) < n  # converged well before the trivial bound


@pytest.mark.parametrize("descent", ["exact", "stream"])
def test_fixpoint_descent_modes_match_oracle(graph, descent):
    e, n = graph
    pos, order = _device_order(e, n)
    lo, hi = elim_ops.orient_edges(
        jnp.asarray(pad_chunk(e, len(e), n)), pos, n)
    minp, rounds = elim_ops.elim_fixpoint(lo, hi, pos, order, n,
                                          descent=descent)
    parent = elim_ops.minp_to_parent(minp, order, n)
    expect = pure.build_elim_tree(
        e, pure.elimination_order(pure.degrees(e, n))).parent
    np.testing.assert_array_equal(parent, expect)


@pytest.mark.parametrize("segment_rounds", [1, 3, 32])
def test_segmented_fixpoint_bit_identical(graph, segment_rounds):
    """Host-driven bounded segments (the watchdog-safe device path) must
    reproduce the monolithic while_loop fixpoint bit-for-bit, including
    the total round count."""
    e, n = graph
    pos, order = _device_order(e, n)
    padded = pad_chunk(e, len(e), n)
    whole, rounds_mono = elim_ops.build_chunk_step(
        jnp.full(n + 1, n, dtype=jnp.int32), padded, pos, order, n)
    seg, rounds_seg = elim_ops.build_chunk_step_segmented(
        jnp.full(n + 1, n, dtype=jnp.int32), padded, pos, order, n,
        segment_rounds=segment_rounds)
    np.testing.assert_array_equal(np.asarray(seg), np.asarray(whole))
    assert rounds_seg == int(rounds_mono)


def test_segmented_honors_max_rounds_exactly(graph):
    """A binding max_rounds must stop the segmented fixpoint at the same
    round as the monolithic one (review r2: the tail segment used to
    overshoot by up to segment_rounds-1)."""
    e, n = graph
    pos, order = _device_order(e, n)
    padded = pad_chunk(e, len(e), n)
    clo, chi = elim_ops.orient_edges(jnp.asarray(padded), pos, n)
    for cap in (1, 3, 7):
        mono, r_mono = elim_ops.fold_edges(
            jnp.full(n + 1, n, dtype=jnp.int32), clo, chi, pos, order, n,
            max_rounds=cap)
        seg, r_seg = elim_ops.fold_edges_segmented(
            jnp.full(n + 1, n, dtype=jnp.int32), clo, chi, pos, order, n,
            segment_rounds=2, max_rounds=cap)
        assert r_seg == int(r_mono)
        np.testing.assert_array_equal(np.asarray(seg), np.asarray(mono))


def test_adaptive_fixpoint_matches_monolithic(graph):
    """Compaction + jump-mode tail must produce the identical forest (the
    elimination forest is unique given the order; compaction preserves the
    active multiset and jump-mode rounds are closure-preserving rewrites).
    small_size=8 forces the compaction path and jump-mode tail even on
    tiny graphs; streaming in two chunks also exercises a non-empty
    carried table."""
    e, n = graph
    pos, order = _device_order(e, n)
    padded = pad_chunk(e, len(e), n)
    whole, _ = elim_ops.build_chunk_step(
        jnp.full(n + 1, n, dtype=jnp.int32), padded, pos, order, n)
    clo, chi = elim_ops.orient_edges(jnp.asarray(padded), pos, n)
    got, _ = elim_ops.fold_edges_adaptive(
        jnp.full(n + 1, n, dtype=jnp.int32), clo, chi, pos, order, n,
        segment_rounds=4, small_size=8, small_jumps=2)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(whole))

    half = len(e) // 2
    minp = jnp.full(n + 1, n, dtype=jnp.int32)
    for part in (e[:half], e[half:]):
        c = pad_chunk(part, max(half, len(e) - half), n)
        clo, chi = elim_ops.orient_edges(jnp.asarray(c), pos, n)
        minp, _ = elim_ops.fold_edges_adaptive(
            minp, clo, chi, pos, order, n,
            segment_rounds=4, small_size=8, small_jumps=2)
    np.testing.assert_array_equal(np.asarray(minp), np.asarray(whole))


def test_compact_actives_preserves_multiset():
    lo = jnp.asarray(np.array([5, 3, 5, 3, 1, 5], np.int32))
    hi = jnp.asarray(np.array([2, 4, 2, 4, 0, 2], np.int32))
    n = 5  # treat vertex id 5 as the sentinel
    clo, chi = elim_ops.compact_actives(lo, hi, n, 4)
    pairs = sorted(zip(np.asarray(clo).tolist(), np.asarray(chi).tolist()))
    assert pairs == [(1, 0), (3, 4), (3, 4), (5, 5)]


def test_compact_actives_dedup_drops_duplicates():
    lo = jnp.asarray(np.array([5, 3, 5, 3, 1, 5], np.int32))
    hi = jnp.asarray(np.array([2, 4, 2, 4, 0, 2], np.int32))
    n = 5
    clo, chi = elim_ops.compact_actives(lo, hi, n, 4, dedup=True)
    pairs = sorted(zip(np.asarray(clo).tolist(), np.asarray(chi).tolist()))
    assert pairs == [(1, 0), (3, 4), (5, 5), (5, 5)]
    live, distinct = elim_ops.count_live_distinct(lo, hi, n)
    assert int(live) == 3 and int(distinct) == 2


def test_adaptive_warm_schedule_and_thresholds(graph):
    """Warm low-lift rounds, dedup compaction, and every host-tail
    handoff point must all produce the identical unique forest."""
    e, n = graph
    pos, order = _device_order(e, n)
    padded = pad_chunk(e, len(e), n)
    whole, _ = elim_ops.build_chunk_step(
        jnp.full(n + 1, n, dtype=jnp.int32), padded, pos, order, n)
    for warm, tail_at in [(((1, 2),), 0), (((1, 4), (1, 8)), len(e) // 2),
                          (((2, 3),), len(e)), ((), len(e) // 2)]:
        got, _ = elim_ops.build_chunk_step_adaptive(
            jnp.full(n + 1, n, dtype=jnp.int32), padded, pos, order, n,
            segment_rounds=2, warm_schedule=warm,
            host_tail_threshold=tail_at)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(whole))


def test_cut_pair_compact_matches_dense(graph):
    """Device-deduped cv rows must yield the same distinct key set as the
    dense pull, and the tiny-cap overflow path must fall back cleanly."""
    e, n = graph
    k = 4
    rng = np.random.default_rng(5)
    assign = jnp.asarray(
        np.concatenate([rng.integers(0, k, n), [0]]).astype(np.int32))
    padded = jnp.asarray(pad_chunk(e, len(e), n))
    dense = np.asarray(score_ops.cut_pairs(padded, assign, n))
    dense = dense[dense[:, 0] < n]
    expect = np.unique(dense[:, 0].astype(np.int64) * k + dense[:, 1])

    compact, count = score_ops.cut_pair_rows_compact(padded, assign, n,
                                                     cap=2 * len(e))
    rows = np.asarray(compact)
    rows = rows[rows[:, 0] < n]
    got = rows[:, 0].astype(np.int64) * k + rows[:, 1]
    assert int(count) == len(expect)
    np.testing.assert_array_equal(np.sort(got), expect)

    # overflow: cap smaller than the distinct count -> count says so
    if len(expect) > 2:
        _, count2 = score_ops.cut_pair_rows_compact(padded, assign, n,
                                                    cap=2)
        assert int(count2) == len(expect) > 2

    keys = score_ops.cut_pair_keys_host(np.asarray(padded), assign, n, k)
    np.testing.assert_array_equal(np.unique(keys), expect)


def test_streaming_chunks_match_batch(graph):
    e, n = graph
    pos, order = _device_order(e, n)
    whole, _ = elim_ops.build_chunk_step(
        jnp.full(n + 1, n, dtype=jnp.int32), pad_chunk(e, len(e), n), pos, order, n)
    minp = jnp.full(n + 1, n, dtype=jnp.int32)
    size = 37
    for off in range(0, len(e), size):
        minp, _ = elim_ops.build_chunk_step(
            minp, pad_chunk(e[off:off + size], size, n), pos, order, n)
    np.testing.assert_array_equal(np.asarray(minp), np.asarray(whole))


def test_streaming_worst_case_displacement_order(graph):
    """Stream edges in DESCENDING pos[hi] order: every later chunk offers
    earlier parents, maximizing in-place displacement chains in
    fold_edges (the slot-reuse path of the displacement fixpoint)."""
    e, n = graph
    pos_np = pure.elimination_order(pure.degrees(e, n))
    key = np.maximum(pos_np[e[:, 0]], pos_np[e[:, 1]])
    e_desc = e[np.argsort(-key, kind="stable")]
    pos, order = _device_order(e, n)
    minp = jnp.full(n + 1, n, dtype=jnp.int32)
    size = 23
    for off in range(0, len(e_desc), size):
        minp, _ = elim_ops.build_chunk_step(
            minp, pad_chunk(e_desc[off:off + size], size, n), pos, order, n)
    parent = elim_ops.minp_to_parent(minp, order, n)
    expect = pure.build_elim_tree(e, pos_np).parent
    np.testing.assert_array_equal(parent, expect)


def test_duplicate_heavy_multigraph_streaming():
    """Many duplicate edges retire simultaneously; their duplicate
    displacements must stay harmless."""
    base = generators.random_graph(50, 120, seed=17)
    e = np.concatenate([base] * 5)  # 5 copies of every edge
    rng = np.random.default_rng(3)
    e = e[rng.permutation(len(e))]
    n = 50
    pos, order = _device_order(e, n)
    minp = jnp.full(n + 1, n, dtype=jnp.int32)
    for off in range(0, len(e), 41):
        minp, _ = elim_ops.build_chunk_step(
            minp, pad_chunk(e[off:off + 41], 41, n), pos, order, n)
    parent = elim_ops.minp_to_parent(minp, order, n)
    expect = pure.build_elim_tree(
        e, pure.elimination_order(pure.degrees(e, n))).parent
    np.testing.assert_array_equal(parent, expect)


def test_merge_forests_matches_whole(graph):
    e, n = graph
    pos, order = _device_order(e, n)
    half = len(e) // 2
    a, _ = elim_ops.build_chunk_step(
        jnp.full(n + 1, n, dtype=jnp.int32), pad_chunk(e[:half], max(half, 1), n),
        pos, order, n)
    b, _ = elim_ops.build_chunk_step(
        jnp.full(n + 1, n, dtype=jnp.int32),
        pad_chunk(e[half:], max(len(e) - half, 1), n), pos, order, n)
    merged = elim_ops.merge_forests(a, b, pos, order, n)
    whole, _ = elim_ops.build_chunk_step(
        jnp.full(n + 1, n, dtype=jnp.int32), pad_chunk(e, len(e), n), pos, order, n)
    np.testing.assert_array_equal(np.asarray(merged), np.asarray(whole))


def test_merge_forests_commutative_and_associative(graph):
    """merge(A,B) == merge(B,A) and any merge order of three shards
    yields the identical table — the property that makes the
    distributed algorithm correct (SURVEY.md §4.1: the single most
    important property test)."""
    e, n = graph
    pos, order = _device_order(e, n)
    third = max(1, len(e) // 3)
    shards = [e[:third], e[third:2 * third], e[2 * third:]]
    forests = []
    for s in shards:
        f, _ = elim_ops.build_chunk_step(
            jnp.full(n + 1, n, dtype=jnp.int32),
            pad_chunk(s, max(len(s), 1), n), pos, order, n)
        forests.append(f)
    a, b, c = forests
    ab = elim_ops.merge_forests(a, b, pos, order, n)
    ba = elim_ops.merge_forests(b, a, pos, order, n)
    np.testing.assert_array_equal(np.asarray(ab), np.asarray(ba))
    left = elim_ops.merge_forests(ab, c, pos, order, n)
    right = elim_ops.merge_forests(a, elim_ops.merge_forests(
        b, c, pos, order, n), pos, order, n)
    np.testing.assert_array_equal(np.asarray(left), np.asarray(right))
    rotated = elim_ops.merge_forests(c, elim_ops.merge_forests(
        a, b, pos, order, n), pos, order, n)
    np.testing.assert_array_equal(np.asarray(left), np.asarray(rotated))


def test_minp_parent_roundtrip(graph):
    e, n = graph
    pos, order = _device_order(e, n)
    minp, _ = elim_ops.build_chunk_step(
        jnp.full(n + 1, n, dtype=jnp.int32), pad_chunk(e, len(e), n), pos, order, n)
    parent = elim_ops.minp_to_parent(minp, order, n)
    back = elim_ops.parent_to_minp(parent, np.asarray(pos[:n]), n)
    np.testing.assert_array_equal(np.asarray(back), np.asarray(minp))


def test_score_ops_match_oracle(graph):
    e, n = graph
    k = 4
    rng = np.random.default_rng(2)
    assign_np = rng.integers(0, k, n).astype(np.int32)
    assign = jnp.concatenate([jnp.asarray(assign_np), jnp.zeros(1, jnp.int32)])
    cut, total = (int(x) for x in
                  score_ops.score_chunk(pad_chunk(e, len(e) + 5, n), assign, n))
    ecut, etotal, _, ecv = pure.edge_cut_score(e, assign_np, k)
    assert (cut, total) == (ecut, etotal)
    rows = np.asarray(score_ops.cut_pairs(pad_chunk(e, len(e) + 5, n), assign, n))
    rows = rows[rows[:, 0] < n]
    got_cv = len(np.unique(rows[:, 0].astype(np.int64) * k + rows[:, 1]))
    assert got_cv == ecv


@pytest.mark.parametrize("k", [2, 8])
def test_tpu_backend_end_to_end(k):
    e = generators.rmat(9, 8, seed=13)
    n = int(e.max()) + 1
    be = TpuBackend(chunk_edges=1024)
    res = be.partition(EdgeStream.from_array(e), k)
    res.validate(n)
    ref = pure.partition_arrays(e, k)
    # identical tree + identical split semantics => identical scores
    assert res.edge_cut == ref.edge_cut
    assert res.total_edges == ref.total_edges
    assert res.comm_volume == ref.comm_volume
    np.testing.assert_array_equal(res.assignment, ref.assignment)


@pytest.mark.parametrize("k", [1, 1024, 4096, 5000])
def test_extreme_k_cross_backend(k):
    """k spanning 1 .. > V (BASELINE config 5 uses k=1024): no backend
    may crash, scores must agree exactly, and k=1 means zero cut."""
    from sheep_tpu.backends.base import get_backend, list_backends

    e = generators.rmat(12, 8, seed=3)
    es = EdgeStream.from_array(e, n_vertices=4096)
    ref = get_backend("pure").partition(es, k, comm_volume=False)
    if k == 1:
        assert ref.edge_cut == 0
    assert ref.assignment.min() >= 0 and ref.assignment.max() < max(k, 1)
    for b in ("tpu", "tpu-bigv"):
        if b not in list_backends():
            continue
        got = get_backend(b, chunk_edges=2048).partition(
            es, k, comm_volume=False)
        assert got.edge_cut == ref.edge_cut
        np.testing.assert_array_equal(got.assignment, ref.assignment)


def test_sorted_lookup_matches_gather(graph):
    """sorted_lookup (sort-join table read) == plain gather, elementwise,
    for multiple tables in one call."""
    import jax

    e, n = graph
    key = jax.random.PRNGKey(3)
    k1, k2, k3 = jax.random.split(key, 3)
    t1 = jax.random.randint(k1, (n + 1,), 0, n + 1, dtype=jnp.int32)
    t2 = jax.random.randint(k2, (n + 1,), 0, n + 1, dtype=jnp.int32)
    idx = jax.random.randint(k3, (257,), 0, n + 1, dtype=jnp.int32)
    a, b = elim_ops.sorted_lookup((t1, t2), idx, n)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(t1[idx]))
    np.testing.assert_array_equal(np.asarray(b), np.asarray(t2[idx]))


@pytest.mark.parametrize("jumps", [1, 4])
def test_sortmerge_round_bit_identical(graph, jumps):
    """The sort-merge prototype (VERDICT r2 item 2) must reproduce the
    jump-mode round's full state trajectory bit-for-bit — same
    retire/displace/climb semantics, different primitive mix — so the
    keep/reject decision is purely the measured-throughput question
    recorded in BASELINE.md."""
    e, n = graph
    pos, order = _device_order(e, n)
    padded = pad_chunk(e, len(e), n)
    loP, hiP = elim_ops.orient_edges_pos(jnp.asarray(padded), pos, n)
    P0 = jnp.full(n + 1, n, dtype=jnp.int32)
    for rounds in (1, 5, 300):
        a = elim_ops.fold_segment_small_pos(
            P0, loP, hiP, n, jumps=jumps, segment_rounds=rounds)
        b = elim_ops.fold_segment_sortmerge_pos(
            P0, loP, hiP, n, jumps=jumps, segment_rounds=rounds)
        for name, x, y in zip(("loP", "hiP", "P", "stats"), a, b):
            np.testing.assert_array_equal(np.asarray(x), np.asarray(y),
                                          err_msg=f"{name} diverged")


def test_streaming_carry_matches_batch(graph):
    """Carry-over streaming (intermediate chunks hand their live tail to
    the NEXT chunk's fold instead of host-finishing) must converge to the
    identical forest: the fixpoint is a property of the inserted
    constraint multiset, not of when each constraint resolves."""
    e, n = graph
    pos, order = _device_order(e, n)
    pos_host = np.asarray(pos[:n])
    whole, _ = elim_ops.build_chunk_step(
        jnp.full(n + 1, n, dtype=jnp.int32), pad_chunk(e, len(e), n),
        pos, order, n)
    P = jnp.full(n + 1, n, dtype=jnp.int32)
    carry = None
    size = 37
    # tiny threshold + tiny small_size force the carry branch to trigger
    # on every chunk rather than converging within the chunk
    for off in range(0, len(e), size):
        P, _, carry = elim_ops.build_chunk_step_adaptive_pos(
            P, pad_chunk(e[off:off + size], size, n), pos, pos_host, n,
            warm_schedule=((1, 2),), host_tail_threshold=size,
            small_size=8, carry=carry, carry_out=True)
    if int(carry[0].shape[0]):
        P, _ = elim_ops.fold_edges_adaptive_pos(
            P, carry[0], carry[1], n, pos_host=pos_host)
    np.testing.assert_array_equal(np.asarray(P[pos]), np.asarray(whole))


@pytest.mark.parametrize("carry_tail", [True, False])
def test_tpu_backend_carry_modes_match_oracle(graph, carry_tail):
    """End-to-end backend equality in both tail modes on multi-chunk
    streams (cpu-jax default is carry_tail=False, so True is forced)."""
    e, n = graph
    es = EdgeStream.from_array(e, n_vertices=n)
    res = TpuBackend(chunk_edges=64, carry_tail=carry_tail).partition(
        es, 4, comm_volume=True)
    ref = pure.partition_arrays(e, 4, n=n)
    np.testing.assert_array_equal(res.assignment, ref.assignment)
    assert res.edge_cut == ref.edge_cut
    assert res.comm_volume == ref.comm_volume


@pytest.mark.parametrize("stale_reuse", [2, 4])
def test_stale_reuse_matches_oracle(graph, stale_reuse):
    """Cross-segment stale-stack reuse (stale_reuse > 1) must reach the
    same unique fixpoint as the fresh/per-segment paths: level 0 stays
    current, stale jumps land on genuine ancestors, and the no-change
    exit is a fixpoint regardless of stack freshness
    (elim.py fold_segment_pos_stale). Multi-chunk backend run so stack
    rebuild cadence spans chunk boundaries and host tails interleave."""
    e, n = graph
    from sheep_tpu.io.edgestream import EdgeStream

    es = EdgeStream.from_array(e, n_vertices=n)
    base = TpuBackend(chunk_edges=64, segment_rounds=3).partition(es, 4)
    reused = TpuBackend(chunk_edges=64, segment_rounds=3,
                        stale_reuse=stale_reuse).partition(es, 4)
    np.testing.assert_array_equal(base.assignment, reused.assignment)
    assert base.edge_cut == reused.edge_cut
    assert base.comm_volume == reused.comm_volume


def test_stale_reuse_rebuild_cadence():
    """The stack rebuild counter fires every K full segments (stats
    diagnostic), and the forest equals the fresh-table fold."""
    e, n = _cases()["rmat"]
    pos, order = _device_order(e, n)
    pos_host = np.asarray(pos[:n])
    loP, hiP = elim_ops.orient_edges_pos(
        jnp.asarray(pad_chunk(e, len(e), n)), pos, n)
    stats: dict = {}
    P0 = jnp.full(n + 1, n, dtype=jnp.int32)
    P_fresh, _ = elim_ops.fold_edges_adaptive_pos(
        P0, loP, hiP, n, segment_rounds=2, small_size=8, host_tail=False,
        stale_tables=False)
    P_reuse, _ = elim_ops.fold_edges_adaptive_pos(
        P0, loP, hiP, n, segment_rounds=2, small_size=8, host_tail=False,
        stale_reuse=3, stats=stats)
    np.testing.assert_array_equal(np.asarray(P_fresh), np.asarray(P_reuse))
    full = stats.get("full_segments", 0)
    assert full > 0, "config must exercise the full-segment stale path"
    assert stats.get("stack_rebuilds", 0) == -(-full // 3)


def test_fold_stats_wall_attribution():
    """Every segment kind executed must leave its t_* wall key in
    stats, each key non-negative and summing to (well under) the call's
    own wall — the contract bench.py's 'build wall attribution' line
    and BASELINE.md's round-5 decomposition read from."""
    import time as _time

    e, n = _cases()["rmat"]
    pos, order = _device_order(e, n)
    loP, hiP = elim_ops.orient_edges_pos(
        jnp.asarray(pad_chunk(e, len(e), n)), pos, n)
    stats: dict = {}
    P0 = jnp.full(n + 1, n, dtype=jnp.int32)
    t0 = _time.perf_counter()
    elim_ops.fold_edges_adaptive_pos(
        P0, loP, hiP, n, segment_rounds=2, small_size=8, host_tail=False,
        warm_schedule=((1, 1),), stats=stats)
    wall = _time.perf_counter() - t0
    kinds = {"warm_segments": "t_warm_s", "full_segments": "t_full_s",
             "small_segments": "t_small_s"}
    seen = 0
    for count_key, t_key in kinds.items():
        if stats.get(count_key, 0):
            seen += 1
            assert t_key in stats, f"{count_key} ran but {t_key} missing"
            assert stats[t_key] >= 0
    assert seen > 0, "config must exercise at least one segment kind"
    assert sum(stats.get(t, 0) for t in kinds.values()) <= wall + 1e-6


def test_pipeline_runs_under_debug_nans():
    """SURVEY.md §5 race-detection line: the JAX path is functional/pure,
    so the structural check is that a full partition runs clean under
    jax_debug_nans (plus the cross-backend equivalence suite). The
    pipeline is integer-only; this pins that no float NaN can sneak in
    via scoring/balance math."""
    import jax

    import sheep_tpu
    from sheep_tpu.io import formats, generators

    prev = jax.config.jax_debug_nans
    jax.config.update("jax_debug_nans", True)
    try:
        import tempfile

        with tempfile.TemporaryDirectory() as d:
            p = f"{d}/k.edges"
            formats.write_edges(p, generators.karate_club())
            res = sheep_tpu.partition(p, 2, backend="tpu")
            assert res.edge_cut > 0
    finally:
        jax.config.update("jax_debug_nans", prev)
