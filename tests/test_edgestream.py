"""EdgeStream chunking/sharding invariants (SURVEY.md §2 #1)."""

import numpy as np
import pytest

from sheep_tpu.io import formats, generators
from sheep_tpu.io.edgestream import EdgeStream


@pytest.fixture(params=[".edges", ".bin32", ".bin64"])
def stream(request, tmp_path):
    e = generators.random_graph(100, 997, seed=3)
    p = str(tmp_path / f"g{request.param}")
    formats.write_edges(p, e)
    return EdgeStream.open(p), e


def test_metadata(stream):
    es, e = stream
    assert es.num_edges == len(e)
    assert es.num_vertices == int(e.max()) + 1


def test_chunks_cover_exactly(stream):
    es, e = stream
    got = np.concatenate(list(es.chunks(chunk_edges=64)))
    np.testing.assert_array_equal(got, e)


def test_shards_partition_the_stream(stream):
    """Union of shards == file, disjoint, any num_shards."""
    es, e = stream
    for s in (2, 3, 8):
        parts = [list(es.chunks(chunk_edges=50, shard=i, num_shards=s)) for i in range(s)]
        sizes = sum(len(c) for p in parts for c in p)
        assert sizes == len(e)
        # round-robin interleave reconstructs the exact stream
        allchunks = [c for p in parts for c in p]
        order = []
        idx = [0] * s
        n_chunks = len(allchunks)
        rebuilt = []
        per_shard = [p[:] for p in parts]
        i = 0
        while len(rebuilt) < n_chunks:
            sh = i % s
            if per_shard[sh]:
                rebuilt.append(per_shard[sh].pop(0))
            i += 1
        np.testing.assert_array_equal(np.concatenate(rebuilt), e)


def test_start_chunk_resume(stream):
    es, e = stream
    first = list(es.chunks(chunk_edges=100))
    resumed = list(es.chunks(chunk_edges=100, start_chunk=3))
    np.testing.assert_array_equal(
        np.concatenate(resumed), np.concatenate(first[3:])
    )


def test_memory_stream():
    e = generators.karate_club()
    es = EdgeStream.from_array(e)
    assert es.num_edges == 78
    assert es.num_vertices == 34
    np.testing.assert_array_equal(es.read_all(), e)
