"""EdgeStream chunking/sharding invariants (SURVEY.md §2 #1)."""

import os

import numpy as np
import pytest

from sheep_tpu.io import formats, generators
from sheep_tpu.io.edgestream import EdgeStream


@pytest.fixture(params=[".edges", ".bin32", ".bin64"])
def stream(request, tmp_path):
    e = generators.random_graph(100, 997, seed=3)
    p = str(tmp_path / f"g{request.param}")
    formats.write_edges(p, e)
    return EdgeStream.open(p), e


def test_metadata(stream):
    es, e = stream
    assert es.num_edges == len(e)
    assert es.num_vertices == int(e.max()) + 1


def test_chunks_cover_exactly(stream):
    es, e = stream
    got = np.concatenate(list(es.chunks(chunk_edges=64)))
    np.testing.assert_array_equal(got, e)


def test_shards_partition_the_stream(stream):
    """Union of shards == file, disjoint, any num_shards."""
    es, e = stream
    for s in (2, 3, 8):
        parts = [list(es.chunks(chunk_edges=50, shard=i, num_shards=s)) for i in range(s)]
        sizes = sum(len(c) for p in parts for c in p)
        assert sizes == len(e)
        # round-robin interleave reconstructs the exact stream
        allchunks = [c for p in parts for c in p]
        order = []
        idx = [0] * s
        n_chunks = len(allchunks)
        rebuilt = []
        per_shard = [p[:] for p in parts]
        i = 0
        while len(rebuilt) < n_chunks:
            sh = i % s
            if per_shard[sh]:
                rebuilt.append(per_shard[sh].pop(0))
            i += 1
        np.testing.assert_array_equal(np.concatenate(rebuilt), e)


def test_start_chunk_resume(stream):
    es, e = stream
    first = list(es.chunks(chunk_edges=100))
    resumed = list(es.chunks(chunk_edges=100, start_chunk=3))
    np.testing.assert_array_equal(
        np.concatenate(resumed), np.concatenate(first[3:])
    )


class TestByteRangeTextSharding:
    """Byte-span text sharding (VERDICT r1 item 7): worker p parses only
    ~file/P bytes; the union of spans is exactly the edge multiset."""

    def _write(self, tmp_path, e, name="g.edges"):
        p = str(tmp_path / name)
        formats.write_edges(p, e)
        return p

    @pytest.mark.parametrize("num_shards", [1, 2, 3, 7, 8])
    def test_spans_cover_exactly(self, tmp_path, num_shards):
        e = generators.random_graph(100, 997, seed=3)
        es = EdgeStream.open(self._write(tmp_path, e))
        got = [c for i in range(num_shards)
               for c in es.chunks(chunk_edges=64, shard=i,
                                  num_shards=num_shards, byte_range=True)]
        cat = np.concatenate(got) if got else np.zeros((0, 2), np.int64)
        assert len(cat) == len(e)
        # spans reorder edges across workers but preserve the multiset
        key = lambda a: np.sort(a[:, 0] * (1 << 32) + a[:, 1], kind="stable")
        np.testing.assert_array_equal(key(cat), key(e))

    def test_comments_and_no_trailing_newline(self, tmp_path):
        p = str(tmp_path / "g.edges")
        body = "# comment\n0 1\n\n% other\n1 2\n2 3\n3 4\n4 5"  # no final \n
        open(p, "w").write(body)
        es = EdgeStream.open(p)
        expect = np.array([[0, 1], [1, 2], [2, 3], [3, 4], [4, 5]])
        for s in (1, 2, 3, 5):
            got = [c for i in range(s)
                   for c in es.chunks(chunk_edges=2, shard=i, num_shards=s,
                                      byte_range=True)]
            cat = np.concatenate(got)
            key = lambda a: np.sort(a[:, 0] * 10 + a[:, 1])
            np.testing.assert_array_equal(key(cat), key(expect))

    def test_boundary_exactly_at_newline(self, tmp_path):
        """Spans engineered so a boundary lands exactly after a newline:
        the first line of the next span must not be dropped."""
        p = str(tmp_path / "g.edges")
        # each line "i j\n" = 4 bytes; 8 lines = 32 bytes; 2 shards split at 16
        lines = [f"{i} {i + 1}\n" for i in range(8)]
        open(p, "w").write("".join(lines))
        es = EdgeStream.open(p)
        got = [c for i in range(2)
               for c in es.chunks(chunk_edges=100, shard=i, num_shards=2,
                                  byte_range=True)]
        assert sum(len(c) for c in got) == 8

    def test_line_longer_than_span(self, tmp_path):
        """A single line straddling several tiny spans is parsed exactly
        once, by the span holding its first byte."""
        p = str(tmp_path / "g.edges")
        open(p, "w").write("1000000000 2000000000\n7 8\n")
        es = EdgeStream.open(p)
        for s in (4, 8, 16):
            got = [c for i in range(s)
                   for c in es.chunks(chunk_edges=10, shard=i, num_shards=s,
                                      byte_range=True)]
            cat = np.concatenate(got)
            assert len(cat) == 2
            assert {tuple(r) for r in cat.tolist()} == {
                (1000000000, 2000000000), (7, 8)}

    def test_count_edges_in_span(self, tmp_path):
        e = generators.random_graph(80, 500, seed=9)
        es = EdgeStream.open(self._write(tmp_path, e))
        total = sum(es.count_edges_in_span(i, 4) for i in range(4))
        assert total == len(e)

    def test_start_chunk_resume_interleaved(self, tmp_path):
        """Global index of local chunk j is j*P + p; skipping start_chunk
        drops exactly the chunks with smaller global index."""
        e = generators.random_graph(60, 400, seed=11)
        es = EdgeStream.open(self._write(tmp_path, e))
        P, cs = 3, 32
        full = {i: list(es.chunks(cs, shard=i, num_shards=P, byte_range=True))
                for i in range(P)}
        start = 4
        for i in range(P):
            resumed = list(es.chunks(cs, shard=i, num_shards=P,
                                     byte_range=True, start_chunk=start))
            skip = max(0, (start - i + P - 1) // P)
            expect = full[i][skip:]
            assert len(resumed) == len(expect)
            for a, b in zip(resumed, expect):
                np.testing.assert_array_equal(a, b)


def test_memory_stream():
    e = generators.karate_club()
    es = EdgeStream.from_array(e)
    assert es.num_edges == 78
    assert es.num_vertices == 34
    np.testing.assert_array_equal(es.read_all(), e)


class TestCorruptStreamFuzz:
    """ISSUE 9 satellite: corrupt/truncated inputs are
    quarantine-or-raise per SHEEP_IO_POLICY — never a silently wrong
    edge multiset (and therefore never a wrong forest)."""

    def _bin(self, tmp_path, e, fmt="bin64"):
        p = str(tmp_path / f"g.{fmt}")
        formats.write_edges(p, e)
        return p

    @pytest.mark.parametrize("fmt,extra", [("bin64", 3), ("bin64", 8),
                                           ("bin32", 1), ("bin32", 4)])
    def test_torn_trailing_pair(self, tmp_path, fmt, extra):
        """'short chunk': a record torn mid-pair at EOF. num_edges
        floors it away, so without validation the damage is silent."""
        from sheep_tpu.io.edgestream import CorruptStreamError

        e = generators.random_graph(64, 300, seed=5)
        p = self._bin(tmp_path, e, fmt)
        with open(p, "ab") as f:
            f.write(b"\xff" * extra)
        with pytest.raises(CorruptStreamError):
            list(EdgeStream.open(p).chunks(64))

    @pytest.mark.parametrize("fmt", ["bin64", "bin32"])
    def test_torn_tail_quarantines(self, tmp_path, fmt, monkeypatch):
        e = generators.random_graph(64, 300, seed=5)
        p = self._bin(tmp_path, e, fmt)
        with open(p, "ab") as f:
            f.write(b"\xff" * 3)
        monkeypatch.setenv("SHEEP_IO_POLICY", "quarantine")
        got = np.concatenate(list(EdgeStream.open(p).chunks(64)))
        # the torn bytes are DROPPED, the intact prefix is exact
        np.testing.assert_array_equal(got, e)

    def test_midstream_eof_strict_raises(self, tmp_path):
        """The file shrinks under a live stream (concurrent truncation):
        the short read must raise, not fold garbage."""
        from sheep_tpu.io.edgestream import CorruptStreamError

        e = generators.random_graph(64, 300, seed=6)
        p = self._bin(tmp_path, e)
        es = EdgeStream.open(p)
        assert es.num_edges == 300  # cache the pre-truncation size
        with open(p, "r+b") as f:
            f.truncate(100 * 16)
        with pytest.raises(CorruptStreamError):
            list(es.chunks(64))

    def test_midstream_eof_quarantines_prefix(self, tmp_path,
                                              monkeypatch):
        e = generators.random_graph(64, 300, seed=6)
        p = self._bin(tmp_path, e)
        es = EdgeStream.open(p)
        assert es.num_edges == 300
        with open(p, "r+b") as f:
            f.truncate(100 * 16)
        monkeypatch.setenv("SHEEP_IO_POLICY", "quarantine")
        got = np.concatenate(list(es.chunks(64)))
        np.testing.assert_array_equal(got, e[:100])  # intact prefix only

    def test_flipped_csr_header_raises(self, tmp_path):
        """Flipped/garbage header magic: a clean ValueError diagnosis,
        never a parse of garbage as edges."""
        from sheep_tpu.io import csr as csr_mod

        e = generators.random_graph(32, 100, seed=7)
        p = str(tmp_path / "g.csr")
        csr_mod.write_csr(p, EdgeStream.from_array(e, n_vertices=32))
        raw = bytearray(open(p, "rb").read())
        raw[0] ^= 0xFF  # flip the first magic byte
        open(p, "wb").write(bytes(raw))
        with pytest.raises(ValueError):
            EdgeStream.open(p).num_edges

    def test_transient_read_fault_is_retried(self, tmp_path,
                                             monkeypatch):
        """An injected transient read failure is absorbed by the
        bounded retry: the stream is byte-exact, no fault escapes."""
        e = generators.random_graph(64, 300, seed=8)
        p = self._bin(tmp_path, e)
        monkeypatch.setenv("SHEEP_FAULT_INJECT", "read@read:2")
        monkeypatch.setenv("SHEEP_RETRY_BASE_S", "0.0")
        from sheep_tpu.utils import fault

        fault.reset()
        got = np.concatenate(list(EdgeStream.open(p).chunks(50)))
        np.testing.assert_array_equal(got, e)

    def test_text_read_fault_is_retried(self, tmp_path, monkeypatch):
        e = generators.random_graph(64, 300, seed=9)
        p = str(tmp_path / "g.edges")
        formats.write_edges(p, e)
        monkeypatch.setenv("SHEEP_FAULT_INJECT", "read@read:1")
        monkeypatch.setenv("SHEEP_RETRY_BASE_S", "0.0")
        from sheep_tpu.utils import fault

        fault.reset()
        got = np.concatenate(list(EdgeStream.open(p).chunks(50)))
        np.testing.assert_array_equal(got, e)

    def test_bad_policy_value_rejected(self, tmp_path, monkeypatch):
        e = generators.random_graph(16, 50, seed=1)
        p = self._bin(tmp_path, e)
        with open(p, "ab") as f:
            f.write(b"\x01")
        monkeypatch.setenv("SHEEP_IO_POLICY", "yolo")
        with pytest.raises(ValueError):
            list(EdgeStream.open(p).chunks(64))

    def test_quarantined_build_never_wrong_forest(self, tmp_path,
                                                  monkeypatch):
        """End-to-end: a quarantined (truncated) stream builds the
        forest OF THE INTACT PREFIX — equal to a clean build over that
        prefix, not some third thing."""
        from sheep_tpu.backends.base import get_backend

        e = generators.random_graph(64, 300, seed=10)
        p = self._bin(tmp_path, e)
        with open(p, "ab") as f:
            f.write(b"\xff" * 5)
        monkeypatch.setenv("SHEEP_IO_POLICY", "quarantine")
        res = get_backend("tpu", chunk_edges=128).partition(
            EdgeStream.open(p, n_vertices=64), 4, comm_volume=False)
        ref = get_backend("tpu", chunk_edges=128).partition(
            EdgeStream.from_array(e, n_vertices=64), 4,
            comm_volume=False)
        np.testing.assert_array_equal(res.assignment, ref.assignment)


class TestSizeBounds:
    def test_upper_bound_exact_for_binary(self, tmp_path):
        e = np.array([[0, 1], [1, 2], [2, 3]], np.int64)
        p = str(tmp_path / "g.bin32")
        formats.write_edges(p, e)
        es = EdgeStream.open(p)
        assert es.num_edges_upper_bound == 3

    def test_upper_bound_covers_text_without_trailing_newline(self, tmp_path):
        # minimal 4-byte lines, last line unterminated: 7 bytes, 2 edges;
        # the bound must still be >= the true count (review r2 finding)
        p = tmp_path / "g.edges"
        p.write_bytes(b"0 1\n0 1")
        es = EdgeStream.open(str(p))
        assert es.num_edges_upper_bound >= es.num_edges == 2

    def test_upper_bound_none_for_unsized_generator(self):
        es = EdgeStream.from_generator(
            lambda: iter([np.array([[0, 1]], np.int64)]), n_vertices=2)
        assert es.num_edges_upper_bound is None
        assert es.clamp_chunk_edges(1 << 20) == 1 << 20

    def test_clamp_chunk_edges(self, tmp_path):
        e = np.arange(2000, dtype=np.int64).reshape(1000, 2)
        es = EdgeStream.from_array(e, n_vertices=2000)
        assert es.clamp_chunk_edges(1 << 20) == 1024  # floor
        assert es.clamp_chunk_edges(1 << 20, floor=100) == 1000
        assert es.clamp_chunk_edges(1 << 20, parts=4, floor=100) == 250
        assert es.clamp_chunk_edges(512) == 512  # never grows


class TestDeltaLogDamage:
    """ISSUE 15 satellite: the delta-log format joins the
    quarantine-or-raise contract — a torn trailing record, a mid-log
    short read (the log shrank under a live reader) and an epoch
    rewind are never silently folded into a resident partition."""

    def _log(self, tmp_path, n_epochs=2, per=40):
        from sheep_tpu.io import deltalog as dl

        e = generators.random_graph(64, n_epochs * per, seed=15)
        p = str(tmp_path / "g.dlog")
        base = str(tmp_path / "base.bin64")
        formats.write_edges(base, generators.random_graph(64, 50,
                                                          seed=16))
        with dl.DeltaLogWriter(p, base_spec=base) as w:
            for i in range(n_epochs):
                w.append(e[i * per: (i + 1) * per])
        return p, e

    @pytest.mark.parametrize("extra", [1, 7, 23])
    def test_torn_trailing_record_strict_raises(self, tmp_path, extra):
        from sheep_tpu.io import deltalog as dl
        from sheep_tpu.io.edgestream import CorruptStreamError

        p, _ = self._log(tmp_path)
        with open(p, "ab") as f:
            f.write(b"\xff" * extra)
        with pytest.raises(CorruptStreamError):
            dl.DeltaLogReader(p).records()

    def test_torn_trailing_record_quarantines_prefix(self, tmp_path,
                                                     monkeypatch):
        from sheep_tpu.io import deltalog as dl

        p, e = self._log(tmp_path)
        with open(p, "ab") as f:
            f.write(b"\xff" * 5)
        monkeypatch.setenv("SHEEP_IO_POLICY", "quarantine")
        recs = dl.DeltaLogReader(p).records()
        got = np.stack([recs["u"].astype(np.int64),
                        recs["v"].astype(np.int64)], axis=1)
        np.testing.assert_array_equal(got, e)  # intact prefix exact

    def test_midlog_short_read_strict_raises(self, tmp_path,
                                             monkeypatch):
        from sheep_tpu.io import deltalog as dl
        from sheep_tpu.io.edgestream import CorruptStreamError

        p, _ = self._log(tmp_path)
        real = os.path.getsize(p)
        # the log "shrank under us": the size check saw more records
        # than the read returns (metadata lied / concurrent truncate)
        monkeypatch.setattr(dl.os.path, "getsize",
                            lambda _p, real=real: real + 24
                            if _p == p else os.stat(_p).st_size)
        with pytest.raises(CorruptStreamError):
            dl.DeltaLogReader(p).records()

    def test_midlog_short_read_quarantines_prefix(self, tmp_path,
                                                  monkeypatch):
        from sheep_tpu.io import deltalog as dl

        p, e = self._log(tmp_path)
        real = os.path.getsize(p)
        monkeypatch.setattr(dl.os.path, "getsize",
                            lambda _p, real=real: real + 24
                            if _p == p else os.stat(_p).st_size)
        monkeypatch.setenv("SHEEP_IO_POLICY", "quarantine")
        recs = dl.DeltaLogReader(p).records()
        got = np.stack([recs["u"].astype(np.int64),
                        recs["v"].astype(np.int64)], axis=1)
        np.testing.assert_array_equal(got, e)  # the intact records

    def test_epoch_rewind_is_corruption(self, tmp_path, monkeypatch):
        from sheep_tpu.io import deltalog as dl
        from sheep_tpu.io.edgestream import CorruptStreamError

        p, e = self._log(tmp_path)
        # flip the SECOND epoch's stamps backwards on disk
        hdr = dl.read_header(p)
        recs = np.fromfile(p, dtype=dl.RECORD_DTYPE,
                           offset=hdr["header_len"])
        recs["epoch"][40:] = 0
        with open(p, "r+b") as f:
            f.seek(hdr["header_len"])
            f.write(recs.tobytes())
        with pytest.raises(CorruptStreamError):
            dl.DeltaLogReader(p).records()
        monkeypatch.setenv("SHEEP_IO_POLICY", "quarantine")
        kept = dl.DeltaLogReader(p).records()
        assert len(kept) == 40  # the intact (monotone) prefix

    def test_quarantined_delta_build_equals_intact_prefix(
            self, tmp_path, monkeypatch):
        """End-to-end: a torn delta: input under quarantine builds
        exactly the partition of the intact-prefix log — never a
        forest from garbage bytes."""
        from sheep_tpu.io import deltalog as dl
        from sheep_tpu.io.edgestream import open_input

        import sheep_tpu

        p, _ = self._log(tmp_path)
        intact = sheep_tpu.partition(f"delta:{p}", 4, backend="tpu",
                                     chunk_edges=64, comm_volume=False)
        with open(p, "ab") as f:
            f.write(b"\xee" * 9)
        monkeypatch.setenv("SHEEP_IO_POLICY", "quarantine")
        torn = sheep_tpu.partition(f"delta:{p}", 4, backend="tpu",
                                   chunk_edges=64, comm_volume=False)
        np.testing.assert_array_equal(torn.assignment,
                                      intact.assignment)
