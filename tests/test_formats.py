"""Golden/round-trip tests for on-disk formats (SURVEY.md §4.2)."""

import numpy as np
import pytest

from sheep_tpu.io import formats, generators


@pytest.mark.parametrize("ext,fmt", [(".edges", "text"), (".bin32", "bin32"), (".bin64", "bin64")])
def test_roundtrip(tmp_path, ext, fmt):
    e = generators.karate_club()
    p = str(tmp_path / f"g{ext}")
    formats.write_edges(p, e)
    assert formats.detect_format(p) == fmt
    back = formats.read_edges(p)
    np.testing.assert_array_equal(back, e)


def test_text_comments_and_blanks(tmp_path):
    p = str(tmp_path / "g.edges")
    with open(p, "w") as f:
        f.write("# SNAP-style comment\n\n0 1\n% matrix-market comment\n1 2\n")
    e = formats.read_edges(p)
    np.testing.assert_array_equal(e, [[0, 1], [1, 2]])


def test_binary_bytes_stable(tmp_path):
    """bin32 layout is contractual: raw LE uint32 pairs, no header."""
    p = str(tmp_path / "g.bin32")
    formats.write_edges(p, np.array([[1, 2], [3, 4]]))
    raw = open(p, "rb").read()
    assert raw == np.array([1, 2, 3, 4], dtype="<u4").tobytes()


def test_partition_roundtrip(tmp_path):
    a = np.array([0, 1, 1, 0, 2], dtype=np.int32)
    for name in ("p.parts", "p.pbin"):
        p = str(tmp_path / name)
        formats.write_partition(p, a)
        np.testing.assert_array_equal(formats.read_partition(p), a)


def test_gzip_text_roundtrip_and_stream(tmp_path):
    """SNAP-style .edges.gz: byte-exact round-trip, streamed chunks
    equal the plain-text stream, round-robin shards cover disjointly,
    and the size bound honestly declines to guess (compressed size is
    not an upper bound on edges)."""
    from sheep_tpu.io.edgestream import EdgeStream

    e = generators.karate_club()
    plain = str(tmp_path / "g.edges")
    gz = str(tmp_path / "g.edges.gz")
    formats.write_edges(plain, e)
    formats.write_edges(gz, e)
    assert formats.detect_format(gz) == "text-gz"
    np.testing.assert_array_equal(formats.read_edges(gz), e)
    s = EdgeStream.open(gz)
    assert s.num_edges_upper_bound is None
    np.testing.assert_array_equal(s.read_all(), e)
    chunks = {sh: list(s.chunks(16, sh, 2)) for sh in (0, 1)}
    got = [None] * (-(-len(e) // 16))
    for sh, cs in chunks.items():
        for j, c in enumerate(cs):
            got[j * 2 + sh] = c
    np.testing.assert_array_equal(np.concatenate(got), e)
    assert s.num_edges == len(e)  # counting pass


def test_gzip_binary_rejected(tmp_path):
    with pytest.raises(ValueError, match="text edge lists only"):
        formats.detect_format(str(tmp_path / "g.bin32.gz"))
