"""Checkpoint/resume + fault-injection recovery (SURVEY.md §5, §4).

The core property: a run killed mid-stream and resumed from its last
checkpoint produces the *identical* partition to an uninterrupted run —
sound because the carried state (degree counts, partial forests, score
counters) is mergeable across chunk boundaries.
"""

import os

import numpy as np
import pytest

from sheep_tpu.backends.base import get_backend, list_backends
from sheep_tpu.io.edgestream import EdgeStream
from sheep_tpu.io import generators
from sheep_tpu.utils.checkpoint import Checkpointer, resume_state, stream_meta
from sheep_tpu.utils.fault import ENV_VAR, InjectedFault

K = 4
CHUNK = 256  # small so even tiny graphs span many chunks


def graph():
    e = generators.rmat(10, 8, seed=3)
    return EdgeStream.from_array(e, n_vertices=1 << 10)


STREAMING_BACKENDS = [b for b in ("cpu", "tpu", "tpu-sharded", "tpu-bigv")
                      if b in list_backends()]


# ---------------------------------------------------------------- unit level

def test_save_load_roundtrip(tmp_path):
    ck = Checkpointer(str(tmp_path), every=2)
    arrays = {"deg": np.arange(10, dtype=np.int64), "cut": np.int64(7)}
    ck.save("build", 6, arrays, {"k": 4})
    state = ck.load()
    assert state.phase == "build" and state.chunk_idx == 6
    assert np.array_equal(state.arrays["deg"], arrays["deg"])
    assert int(state.arrays["cut"]) == 7
    assert state.meta == {"k": 4}


def test_sweep_keeps_latest_and_previous(tmp_path):
    """Two steps are retained (multi-host skew fallback needs the previous
    one); older steps are swept."""
    ck = Checkpointer(str(tmp_path), every=1)
    for idx in (1, 2, 3):
        ck.save("degrees", idx, {"deg": np.zeros(4, np.int64)})
    npz = sorted(f for f in os.listdir(tmp_path) if f.endswith(".npz"))
    assert len(npz) == 2
    assert any("_2" in f for f in npz) and any("_3" in f for f in npz)
    assert ck.load().chunk_idx == 3
    assert ck.load_at("degrees", 2).chunk_idx == 2
    assert ck.load_at("degrees", 1) is None


def test_clear(tmp_path):
    ck = Checkpointer(str(tmp_path), every=1)
    ck.save("degrees", 1, {"deg": np.zeros(4, np.int64)})
    ck.clear()
    assert ck.load() is None


def test_per_process_isolation(tmp_path):
    a = Checkpointer(str(tmp_path), every=1, process=0)
    b = Checkpointer(str(tmp_path), every=1, process=1)
    a.save("degrees", 1, {"deg": np.zeros(4, np.int64)})
    b.save("build", 9, {"deg": np.ones(4, np.int64)})
    assert a.load().phase == "degrees"
    assert b.load().phase == "build" and b.load().chunk_idx == 9


def _meta(es, **over):
    kw = dict(k=8, chunk_edges=CHUNK, weights="unit", alpha=1.0,
              comm_volume=True)
    kw.update(over)
    return stream_meta(es, **kw)


@pytest.mark.parametrize("change", [
    {"k": 4}, {"alpha": 0.9}, {"comm_volume": False}, {"weights": "degree"},
    {"chunk_edges": CHUNK * 2},
])
def test_resume_refuses_mismatched_options(tmp_path, change):
    ck = Checkpointer(str(tmp_path), every=1)
    es = graph()
    ck.save("build", 2, {"deg": np.zeros(4, np.int64)}, _meta(es))
    with pytest.raises(ValueError, match="does not match"):
        resume_state(ck, _meta(es, **change), resume=True)


def test_resume_refuses_cross_backend_state(tmp_path):
    """A sharded checkpoint resumed by the single-device backend must be a
    clean refusal, not a KeyError deep in partition()."""
    es = graph()
    ck = Checkpointer(str(tmp_path), every=1)
    ck.save("build", 2, {"deg": np.zeros(4, np.int64)},
            _meta(es, state_format="sharded", devices=8))
    with pytest.raises(ValueError, match="does not match"):
        resume_state(ck, _meta(es, state_format="minp"), resume=True)


def test_pure_backend_rejects_checkpointer(tmp_path):
    ck = Checkpointer(str(tmp_path), every=1)
    with pytest.raises(ValueError, match="does not checkpoint"):
        get_backend("pure").partition(graph(), K, checkpointer=ck)


def test_cadence(tmp_path):
    ck = Checkpointer(str(tmp_path), every=3)
    assert [i for i in range(1, 10) if ck.due(i)] == [3, 6, 9]


# ------------------------------------------------------- recovery end-to-end

@pytest.mark.parametrize("backend", STREAMING_BACKENDS)
@pytest.mark.parametrize("phase", ["degrees", "build", "score"])
def test_fault_then_resume_matches_uninterrupted(tmp_path, backend, phase,
                                                 monkeypatch):
    es = graph()
    kw = {"chunk_edges": CHUNK}
    expect = get_backend(backend, **kw).partition(es, K, comm_volume=True)

    ck = Checkpointer(str(tmp_path), every=1)
    monkeypatch.setenv(ENV_VAR, f"{phase}:2")
    with pytest.raises(InjectedFault):
        get_backend(backend, **kw).partition(
            es, K, comm_volume=True, checkpointer=ck)
    monkeypatch.delenv(ENV_VAR)
    saved = ck.load()
    assert saved is not None, "no checkpoint written before the fault"

    res = get_backend(backend, **kw).partition(
        es, K, comm_volume=True, checkpointer=ck, resume=True)
    assert np.array_equal(res.assignment, expect.assignment)
    assert res.edge_cut == expect.edge_cut
    assert res.total_edges == expect.total_edges
    assert res.comm_volume == expect.comm_volume


def test_successful_run_clears_checkpoint(tmp_path):
    es = graph()
    ck = Checkpointer(str(tmp_path), every=1)
    get_backend(STREAMING_BACKENDS[0], chunk_edges=CHUNK).partition(
        es, K, checkpointer=ck)
    assert ck.load() is None, "completed run left a stale checkpoint"
    assert not [f for f in os.listdir(tmp_path) if f.endswith(".npz")]


def test_resume_refuses_different_inmemory_graph(tmp_path):
    """Two in-memory graphs with identical (V, E) but different edges must
    not cross-resume: the fingerprint hashes sampled edge content."""
    a = EdgeStream.from_array(generators.rmat(10, 8, seed=3), n_vertices=1 << 10)
    b = EdgeStream.from_array(generators.rmat(10, 8, seed=4), n_vertices=1 << 10)
    ck = Checkpointer(str(tmp_path), every=1)
    ck.save("build", 2, {"deg": np.zeros(4, np.int64)}, _meta(a))
    with pytest.raises(ValueError, match="does not match"):
        resume_state(ck, _meta(b), resume=True)


def test_resume_refuses_regenerated_input_file(tmp_path):
    """Same path + same shape but different bytes must not resume: the
    fingerprint includes file size/mtime (content identity)."""
    from sheep_tpu.io import formats

    gpath = str(tmp_path / "g.bin64")
    formats.write_edges(gpath, generators.rmat(9, 8, seed=1))
    with EdgeStream.open(gpath) as es:
        meta_a = _meta(es)
    ck = Checkpointer(str(tmp_path / "ck"), every=1)
    ck.save("build", 2, {"deg": np.zeros(4, np.int64)}, meta_a)

    os.utime(gpath, ns=(1, 1))  # same bytes, different mtime
    with EdgeStream.open(gpath) as es:
        with pytest.raises(ValueError, match="does not match"):
            resume_state(ck, _meta(es), resume=True)


@pytest.mark.parametrize("backend", STREAMING_BACKENDS[:1])
def test_resume_without_checkpoint_is_fresh_run(tmp_path, backend):
    es = graph()
    kw = {"chunk_edges": CHUNK}
    ck = Checkpointer(str(tmp_path), every=4)
    expect = get_backend(backend, **kw).partition(es, K)
    res = get_backend(backend, **kw).partition(es, K, checkpointer=ck,
                                               resume=True)
    assert np.array_equal(res.assignment, expect.assignment)


def test_cli_checkpoint_resume(tmp_path, monkeypatch):
    from sheep_tpu import cli
    from sheep_tpu.io import formats

    e = generators.rmat(9, 8, seed=5)
    gpath = str(tmp_path / "g.bin64")
    formats.write_edges(gpath, e)
    ckdir = str(tmp_path / "ck")

    out1 = str(tmp_path / "full.parts")
    assert cli.main(["--input", gpath, "--k", "4", "--backend",
                     STREAMING_BACKENDS[0], "--chunk-edges", str(CHUNK),
                     "--output", out1, "--json"]) == 0

    monkeypatch.setenv(ENV_VAR, "build:2")
    with pytest.raises(InjectedFault):
        cli.main(["--input", gpath, "--k", "4", "--backend",
                  STREAMING_BACKENDS[0], "--chunk-edges", str(CHUNK),
                  "--checkpoint-dir", ckdir, "--checkpoint-every", "1",
                  "--json"])
    monkeypatch.delenv(ENV_VAR)

    out2 = str(tmp_path / "resumed.parts")
    assert cli.main(["--input", gpath, "--k", "4", "--backend",
                     STREAMING_BACKENDS[0], "--chunk-edges", str(CHUNK),
                     "--checkpoint-dir", ckdir, "--resume",
                     "--output", out2, "--json"]) == 0
    assert np.array_equal(formats.read_partition(out1),
                          formats.read_partition(out2))


@pytest.mark.parametrize("phase", ["build", "score"])
def test_fault_then_resume_carry_mode(tmp_path, phase, monkeypatch):
    """Kill+resume with carry-over tails: the in-flight carried actives
    are checkpointed state, so the resumed run must still match the
    uninterrupted one exactly."""
    if "tpu" not in list_backends():
        pytest.skip("tpu backend unavailable")
    es = graph()
    kw = {"chunk_edges": CHUNK, "carry_tail": True}
    expect = get_backend("tpu", **kw).partition(es, K, comm_volume=True)

    ck = Checkpointer(str(tmp_path), every=1)
    monkeypatch.setenv(ENV_VAR, f"{phase}:2")
    with pytest.raises(InjectedFault):
        get_backend("tpu", **kw).partition(
            es, K, comm_volume=True, checkpointer=ck)
    monkeypatch.delenv(ENV_VAR)
    assert ck.load() is not None

    res = get_backend("tpu", **kw).partition(
        es, K, comm_volume=True, checkpointer=ck, resume=True)
    assert np.array_equal(res.assignment, expect.assignment)
    assert res.edge_cut == expect.edge_cut
    assert res.comm_volume == expect.comm_volume


# ------------------------------------- graceful degradation (ISSUE 8)

def _truncate_mid_byte(path):
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.truncate(max(1, size // 2))


def _manifest_data(ck):
    import json

    with open(ck._manifest_path) as f:
        return json.load(f)["data"]


def test_corrupt_latest_falls_back_to_previous(tmp_path, capsys):
    """A truncated newest .npz degrades to the retained previous step
    with a warning — never a traceback mid-recovery."""
    ck = Checkpointer(str(tmp_path), every=1)
    ck.save("build", 1, {"deg": np.arange(4, dtype=np.int64)}, {"k": 4})
    ck.save("build", 2, {"deg": np.arange(4, dtype=np.int64) * 2}, {"k": 4})
    _truncate_mid_byte(str(tmp_path / _manifest_data(ck)))
    state = ck.load()
    assert state is not None and state.chunk_idx == 1
    assert np.array_equal(state.arrays["deg"], np.arange(4))
    assert "unreadable" in capsys.readouterr().err


def test_corrupt_all_degrades_to_clean_start(tmp_path, capsys):
    ck = Checkpointer(str(tmp_path), every=1)
    ck.save("build", 1, {"deg": np.zeros(4, np.int64)}, {"k": 4})
    ck.save("build", 2, {"deg": np.zeros(4, np.int64)}, {"k": 4})
    for f in os.listdir(tmp_path):
        if f.endswith(".npz"):
            _truncate_mid_byte(str(tmp_path / f))
    assert ck.load() is None
    err = capsys.readouterr().err
    assert "unreadable" in err and "clean start" in err


def test_torn_manifest_degrades_to_clean_start(tmp_path, capsys):
    ck = Checkpointer(str(tmp_path), every=1)
    ck.save("build", 1, {"deg": np.zeros(4, np.int64)}, {"k": 4})
    with open(ck._manifest_path, "r+") as f:
        raw = f.read()
        f.seek(0)
        f.truncate(len(raw) // 2)  # torn mid-write
    assert ck.load() is None
    assert "torn" in capsys.readouterr().err


def test_resume_with_corrupt_checkpoint_completes(tmp_path, monkeypatch):
    """End-to-end: fault a run, corrupt EVERY data file mid-byte, resume.
    Recovery degrades to a clean start (warning, no raise) and the final
    partition still matches the uninterrupted run bit for bit."""
    es = graph()
    kw = {"chunk_edges": CHUNK}
    backend = STREAMING_BACKENDS[0]
    expect = get_backend(backend, **kw).partition(es, K, comm_volume=True)

    ck = Checkpointer(str(tmp_path), every=1)
    monkeypatch.setenv(ENV_VAR, "build:4")
    with pytest.raises(InjectedFault):
        get_backend(backend, **kw).partition(
            es, K, comm_volume=True, checkpointer=ck)
    monkeypatch.delenv(ENV_VAR)
    for f in os.listdir(tmp_path):
        if f.endswith(".npz"):
            _truncate_mid_byte(str(tmp_path / f))

    res = get_backend(backend, **kw).partition(
        es, K, comm_volume=True, checkpointer=ck, resume=True)
    assert np.array_equal(res.assignment, expect.assignment)
    assert res.edge_cut == expect.edge_cut
    assert res.comm_volume == expect.comm_volume


# --------------------------- hierarchy survival drills (ISSUE 8 tentpole)

def _hier_graph(tmp_path):
    from sheep_tpu.io import formats

    p = str(tmp_path / "hg.bin64")
    formats.write_edges(p, generators.rmat(9, 8, seed=3))
    return p


HIER_KW = dict(refine=1, comm_volume=False, chunk_edges=CHUNK)


def test_hier_fault_resume_mid_level0_bit_identical(tmp_path, monkeypatch):
    """Kill the hierarchical run INSIDE level 0 (chunk granularity: the
    level-0 flat partition checkpoints into the nested level0/ domain),
    resume, and require a bit-identical final assignment."""
    import sheep_tpu

    p = _hier_graph(tmp_path)
    backend = STREAMING_BACKENDS[0]
    expect = sheep_tpu.partition_hierarchical(p, [2, 2], backend=backend,
                                              **HIER_KW)
    ck = Checkpointer(str(tmp_path / "ck"), every=1)
    monkeypatch.setenv(ENV_VAR, "level0:2")
    with pytest.raises(InjectedFault):
        sheep_tpu.partition_hierarchical(p, [2, 2], backend=backend,
                                         checkpointer=ck, **HIER_KW)
    monkeypatch.delenv(ENV_VAR)
    sub = Checkpointer(str(tmp_path / "ck" / "level0"), every=1)
    assert sub.load() is not None, \
        "no chunk-level checkpoint inside level 0 before the fault"

    res = sheep_tpu.partition_hierarchical(p, [2, 2], backend=backend,
                                           checkpointer=ck, resume=True,
                                           **HIER_KW)
    assert np.array_equal(res.assignment, expect.assignment)
    assert res.edge_cut == expect.edge_cut
    # success clears the whole recovery domain, spill shards included
    assert ck.load() is None
    assert os.listdir(tmp_path / "ck") == []


def test_hier_fault_resume_level_boundary_bit_identical(tmp_path,
                                                        monkeypatch):
    """Kill the run AT a level boundary (one part's subtree finished and
    checkpointed), resume, and require bit-identity; the saved state
    must record the queue position and the spill manifest."""
    import sheep_tpu

    p = _hier_graph(tmp_path)
    backend = STREAMING_BACKENDS[0]
    expect = sheep_tpu.partition_hierarchical(p, [2, 2], backend=backend,
                                              **HIER_KW)
    ck = Checkpointer(str(tmp_path / "ck"), every=1)
    monkeypatch.setenv(ENV_VAR, "level:1")
    with pytest.raises(InjectedFault):
        sheep_tpu.partition_hierarchical(p, [2, 2], backend=backend,
                                         checkpointer=ck, **HIER_KW)
    monkeypatch.delenv(ENV_VAR)
    st = ck.load()
    assert st is not None and st.phase == "hier" and st.chunk_idx == 1
    assert {"assign", "final", "spill_names", "spill_sizes"} <= set(st.arrays)
    # part 0's shard was consumed at its boundary; part 1's is pending
    assert int(st.arrays["spill_sizes"][0]) == -1
    assert int(st.arrays["spill_sizes"][1]) >= 0

    res = sheep_tpu.partition_hierarchical(p, [2, 2], backend=backend,
                                           checkpointer=ck, resume=True,
                                           **HIER_KW)
    assert np.array_equal(res.assignment, expect.assignment)
    assert res.edge_cut == expect.edge_cut
    assert ck.load() is None


def test_hier_resume_reuses_spill_manifest(tmp_path, monkeypatch):
    """A level-boundary resume must REUSE the spill shards named in the
    manifest, not re-stream the graph: _spill_intra is replaced with a
    bomb for the resumed run."""
    import sheep_tpu
    from sheep_tpu import hierarchy

    p = _hier_graph(tmp_path)
    backend = STREAMING_BACKENDS[0]
    expect = sheep_tpu.partition_hierarchical(p, [2, 2], backend=backend,
                                              **HIER_KW)
    ck = Checkpointer(str(tmp_path / "ck"), every=1)
    monkeypatch.setenv(ENV_VAR, "level:1")
    with pytest.raises(InjectedFault):
        sheep_tpu.partition_hierarchical(p, [2, 2], backend=backend,
                                         checkpointer=ck, **HIER_KW)
    monkeypatch.delenv(ENV_VAR)

    def bomb(*a, **kw):
        raise AssertionError("resume re-spilled instead of reusing the "
                             "manifest's shards")

    monkeypatch.setattr(hierarchy, "_spill_intra", bomb)
    res = sheep_tpu.partition_hierarchical(p, [2, 2], backend=backend,
                                           checkpointer=ck, resume=True,
                                           **HIER_KW)
    assert np.array_equal(res.assignment, expect.assignment)


def test_hier_resume_corrupt_spill_degrades(tmp_path, monkeypatch, capsys):
    """A pending spill shard that went missing/torn degrades the resume
    to a from-scratch level rebuild (warning, no raise) that still
    matches the uninterrupted run."""
    import sheep_tpu

    p = _hier_graph(tmp_path)
    backend = STREAMING_BACKENDS[0]
    expect = sheep_tpu.partition_hierarchical(p, [2, 2], backend=backend,
                                              **HIER_KW)
    ck = Checkpointer(str(tmp_path / "ck"), every=1)
    monkeypatch.setenv(ENV_VAR, "level:1")
    with pytest.raises(InjectedFault):
        sheep_tpu.partition_hierarchical(p, [2, 2], backend=backend,
                                         checkpointer=ck, **HIER_KW)
    monkeypatch.delenv(ENV_VAR)
    st = ck.load()
    pending = str(st.arrays["spill_names"][1])
    _truncate_mid_byte(str(tmp_path / "ck" / "hier_spill_p0"
                           / "level0_shards" / pending))

    res = sheep_tpu.partition_hierarchical(p, [2, 2], backend=backend,
                                           checkpointer=ck, resume=True,
                                           **HIER_KW)
    assert np.array_equal(res.assignment, expect.assignment)
    assert "spill shard" in capsys.readouterr().err


def test_hier_corrupt_latest_falls_back_to_previous_boundary(tmp_path,
                                                             monkeypatch,
                                                             capsys):
    """A corrupt LATEST level-boundary .npz falls back to the retained
    previous step — whose manifest still names shards the latest step
    marked consumed. Shard files outlive their manifest entry by one
    save for exactly this fallback, so the resume replays from the
    shards (no re-spill: _spill_intra is bombed) bit-identically."""
    import sheep_tpu
    from sheep_tpu import hierarchy

    p = _hier_graph(tmp_path)
    backend = STREAMING_BACKENDS[0]
    expect = sheep_tpu.partition_hierarchical(p, [2, 2], backend=backend,
                                              **HIER_KW)
    ck = Checkpointer(str(tmp_path / "ck"), every=1)
    monkeypatch.setenv(ENV_VAR, "level:1")
    with pytest.raises(InjectedFault):
        sheep_tpu.partition_hierarchical(p, [2, 2], backend=backend,
                                         checkpointer=ck, **HIER_KW)
    monkeypatch.delenv(ENV_VAR)
    _truncate_mid_byte(str(tmp_path / "ck" / _manifest_data(ck)))
    st = ck.load()  # previous step: nothing recursed yet
    assert st is not None and st.chunk_idx == 0
    capsys.readouterr()

    def bomb(*a, **kw):
        raise AssertionError("previous-step fallback re-spilled instead "
                             "of reusing the retained shards")

    monkeypatch.setattr(hierarchy, "_spill_intra", bomb)
    res = sheep_tpu.partition_hierarchical(p, [2, 2], backend=backend,
                                           checkpointer=ck, resume=True,
                                           **HIER_KW)
    assert np.array_equal(res.assignment, expect.assignment)
    assert "unreadable" in capsys.readouterr().err


def test_hier_boundary_checkpoint_without_chunk_support(tmp_path,
                                                        monkeypatch):
    """The pure backend cannot chunk-checkpoint (supports_checkpoint is
    False), but hierarchy still gives it level-BOUNDARY recovery instead
    of refusing the checkpointer outright."""
    import sheep_tpu

    p = _hier_graph(tmp_path)
    expect = sheep_tpu.partition_hierarchical(p, [2, 2], backend="pure",
                                              **HIER_KW)
    ck = Checkpointer(str(tmp_path / "ck"), every=1)
    monkeypatch.setenv(ENV_VAR, "level:1")
    with pytest.raises(InjectedFault):
        sheep_tpu.partition_hierarchical(p, [2, 2], backend="pure",
                                         checkpointer=ck, **HIER_KW)
    monkeypatch.delenv(ENV_VAR)
    assert ck.load() is not None

    res = sheep_tpu.partition_hierarchical(p, [2, 2], backend="pure",
                                           checkpointer=ck, resume=True,
                                           **HIER_KW)
    assert np.array_equal(res.assignment, expect.assignment)


def test_cli_k_levels_checkpoint_resume(tmp_path, monkeypatch):
    """The CLI drill: --k-levels + --checkpoint-dir killed at a level
    boundary, resumed with --resume, written map identical to an
    uninterrupted run's."""
    from sheep_tpu import cli
    from sheep_tpu.io import formats

    p = _hier_graph(tmp_path)
    base = ["--input", p, "--k-levels", "2,2", "--backend",
            STREAMING_BACKENDS[0], "--refine", "1", "--chunk-edges",
            str(CHUNK), "--no-comm-volume", "--json"]
    out1 = str(tmp_path / "full.parts")
    assert cli.main(base + ["--output", out1]) == 0

    ckdir = str(tmp_path / "ck")
    monkeypatch.setenv(ENV_VAR, "level:1")
    with pytest.raises(InjectedFault):
        cli.main(base + ["--checkpoint-dir", ckdir,
                         "--checkpoint-every", "1"])
    monkeypatch.delenv(ENV_VAR)

    out2 = str(tmp_path / "resumed.parts")
    assert cli.main(base + ["--checkpoint-dir", ckdir, "--resume",
                            "--output", out2]) == 0
    assert np.array_equal(formats.read_partition(out1),
                          formats.read_partition(out2))


def test_carry_checkpoint_gated_from_no_carry_resume(tmp_path, monkeypatch):
    """state_format distinguishes carry-mode checkpoints, so a checkpoint
    written with carry_tail=True refuses a carry_tail=False resume
    (different in-flight state shape) instead of silently dropping the
    carried constraints."""
    if "tpu" not in list_backends():
        pytest.skip("tpu backend unavailable")
    es = graph()
    ck = Checkpointer(str(tmp_path), every=1)
    monkeypatch.setenv(ENV_VAR, "build:2")
    with pytest.raises(InjectedFault):
        get_backend("tpu", chunk_edges=CHUNK, carry_tail=True).partition(
            es, K, checkpointer=ck)
    monkeypatch.delenv(ENV_VAR)
    with pytest.raises(ValueError, match="does not match"):
        get_backend("tpu", chunk_edges=CHUNK, carry_tail=False).partition(
            es, K, checkpointer=ck, resume=True)
