"""Checkpoint/resume + fault-injection recovery (SURVEY.md §5, §4).

The core property: a run killed mid-stream and resumed from its last
checkpoint produces the *identical* partition to an uninterrupted run —
sound because the carried state (degree counts, partial forests, score
counters) is mergeable across chunk boundaries.
"""

import os

import numpy as np
import pytest

from sheep_tpu.backends.base import get_backend, list_backends
from sheep_tpu.io.edgestream import EdgeStream
from sheep_tpu.io import generators
from sheep_tpu.utils.checkpoint import Checkpointer, resume_state, stream_meta
from sheep_tpu.utils.fault import ENV_VAR, InjectedFault

K = 4
CHUNK = 256  # small so even tiny graphs span many chunks


def graph():
    e = generators.rmat(10, 8, seed=3)
    return EdgeStream.from_array(e, n_vertices=1 << 10)


STREAMING_BACKENDS = [b for b in ("cpu", "tpu", "tpu-sharded", "tpu-bigv")
                      if b in list_backends()]


# ---------------------------------------------------------------- unit level

def test_save_load_roundtrip(tmp_path):
    ck = Checkpointer(str(tmp_path), every=2)
    arrays = {"deg": np.arange(10, dtype=np.int64), "cut": np.int64(7)}
    ck.save("build", 6, arrays, {"k": 4})
    state = ck.load()
    assert state.phase == "build" and state.chunk_idx == 6
    assert np.array_equal(state.arrays["deg"], arrays["deg"])
    assert int(state.arrays["cut"]) == 7
    assert state.meta == {"k": 4}


def test_sweep_keeps_latest_and_previous(tmp_path):
    """Two steps are retained (multi-host skew fallback needs the previous
    one); older steps are swept."""
    ck = Checkpointer(str(tmp_path), every=1)
    for idx in (1, 2, 3):
        ck.save("degrees", idx, {"deg": np.zeros(4, np.int64)})
    npz = sorted(f for f in os.listdir(tmp_path) if f.endswith(".npz"))
    assert len(npz) == 2
    assert any("_2" in f for f in npz) and any("_3" in f for f in npz)
    assert ck.load().chunk_idx == 3
    assert ck.load_at("degrees", 2).chunk_idx == 2
    assert ck.load_at("degrees", 1) is None


def test_clear(tmp_path):
    ck = Checkpointer(str(tmp_path), every=1)
    ck.save("degrees", 1, {"deg": np.zeros(4, np.int64)})
    ck.clear()
    assert ck.load() is None


def test_per_process_isolation(tmp_path):
    a = Checkpointer(str(tmp_path), every=1, process=0)
    b = Checkpointer(str(tmp_path), every=1, process=1)
    a.save("degrees", 1, {"deg": np.zeros(4, np.int64)})
    b.save("build", 9, {"deg": np.ones(4, np.int64)})
    assert a.load().phase == "degrees"
    assert b.load().phase == "build" and b.load().chunk_idx == 9


def _meta(es, **over):
    kw = dict(k=8, chunk_edges=CHUNK, weights="unit", alpha=1.0,
              comm_volume=True)
    kw.update(over)
    return stream_meta(es, **kw)


@pytest.mark.parametrize("change", [
    {"k": 4}, {"alpha": 0.9}, {"comm_volume": False}, {"weights": "degree"},
    {"chunk_edges": CHUNK * 2},
])
def test_resume_refuses_mismatched_options(tmp_path, change):
    ck = Checkpointer(str(tmp_path), every=1)
    es = graph()
    ck.save("build", 2, {"deg": np.zeros(4, np.int64)}, _meta(es))
    with pytest.raises(ValueError, match="does not match"):
        resume_state(ck, _meta(es, **change), resume=True)


def test_resume_refuses_cross_backend_state(tmp_path):
    """A sharded checkpoint resumed by the single-device backend must be a
    clean refusal, not a KeyError deep in partition()."""
    es = graph()
    ck = Checkpointer(str(tmp_path), every=1)
    ck.save("build", 2, {"deg": np.zeros(4, np.int64)},
            _meta(es, state_format="sharded", devices=8))
    with pytest.raises(ValueError, match="does not match"):
        resume_state(ck, _meta(es, state_format="minp"), resume=True)


def test_pure_backend_rejects_checkpointer(tmp_path):
    ck = Checkpointer(str(tmp_path), every=1)
    with pytest.raises(ValueError, match="does not checkpoint"):
        get_backend("pure").partition(graph(), K, checkpointer=ck)


def test_cadence(tmp_path):
    ck = Checkpointer(str(tmp_path), every=3)
    assert [i for i in range(1, 10) if ck.due(i)] == [3, 6, 9]


# ------------------------------------------------------- recovery end-to-end

@pytest.mark.parametrize("backend", STREAMING_BACKENDS)
@pytest.mark.parametrize("phase", ["degrees", "build", "score"])
def test_fault_then_resume_matches_uninterrupted(tmp_path, backend, phase,
                                                 monkeypatch):
    es = graph()
    kw = {"chunk_edges": CHUNK}
    expect = get_backend(backend, **kw).partition(es, K, comm_volume=True)

    ck = Checkpointer(str(tmp_path), every=1)
    monkeypatch.setenv(ENV_VAR, f"{phase}:2")
    with pytest.raises(InjectedFault):
        get_backend(backend, **kw).partition(
            es, K, comm_volume=True, checkpointer=ck)
    monkeypatch.delenv(ENV_VAR)
    saved = ck.load()
    assert saved is not None, "no checkpoint written before the fault"

    res = get_backend(backend, **kw).partition(
        es, K, comm_volume=True, checkpointer=ck, resume=True)
    assert np.array_equal(res.assignment, expect.assignment)
    assert res.edge_cut == expect.edge_cut
    assert res.total_edges == expect.total_edges
    assert res.comm_volume == expect.comm_volume


def test_successful_run_clears_checkpoint(tmp_path):
    es = graph()
    ck = Checkpointer(str(tmp_path), every=1)
    get_backend(STREAMING_BACKENDS[0], chunk_edges=CHUNK).partition(
        es, K, checkpointer=ck)
    assert ck.load() is None, "completed run left a stale checkpoint"
    assert not [f for f in os.listdir(tmp_path) if f.endswith(".npz")]


def test_resume_refuses_different_inmemory_graph(tmp_path):
    """Two in-memory graphs with identical (V, E) but different edges must
    not cross-resume: the fingerprint hashes sampled edge content."""
    a = EdgeStream.from_array(generators.rmat(10, 8, seed=3), n_vertices=1 << 10)
    b = EdgeStream.from_array(generators.rmat(10, 8, seed=4), n_vertices=1 << 10)
    ck = Checkpointer(str(tmp_path), every=1)
    ck.save("build", 2, {"deg": np.zeros(4, np.int64)}, _meta(a))
    with pytest.raises(ValueError, match="does not match"):
        resume_state(ck, _meta(b), resume=True)


def test_resume_refuses_regenerated_input_file(tmp_path):
    """Same path + same shape but different bytes must not resume: the
    fingerprint includes file size/mtime (content identity)."""
    from sheep_tpu.io import formats

    gpath = str(tmp_path / "g.bin64")
    formats.write_edges(gpath, generators.rmat(9, 8, seed=1))
    with EdgeStream.open(gpath) as es:
        meta_a = _meta(es)
    ck = Checkpointer(str(tmp_path / "ck"), every=1)
    ck.save("build", 2, {"deg": np.zeros(4, np.int64)}, meta_a)

    os.utime(gpath, ns=(1, 1))  # same bytes, different mtime
    with EdgeStream.open(gpath) as es:
        with pytest.raises(ValueError, match="does not match"):
            resume_state(ck, _meta(es), resume=True)


@pytest.mark.parametrize("backend", STREAMING_BACKENDS[:1])
def test_resume_without_checkpoint_is_fresh_run(tmp_path, backend):
    es = graph()
    kw = {"chunk_edges": CHUNK}
    ck = Checkpointer(str(tmp_path), every=4)
    expect = get_backend(backend, **kw).partition(es, K)
    res = get_backend(backend, **kw).partition(es, K, checkpointer=ck,
                                               resume=True)
    assert np.array_equal(res.assignment, expect.assignment)


def test_cli_checkpoint_resume(tmp_path, monkeypatch):
    from sheep_tpu import cli
    from sheep_tpu.io import formats

    e = generators.rmat(9, 8, seed=5)
    gpath = str(tmp_path / "g.bin64")
    formats.write_edges(gpath, e)
    ckdir = str(tmp_path / "ck")

    out1 = str(tmp_path / "full.parts")
    assert cli.main(["--input", gpath, "--k", "4", "--backend",
                     STREAMING_BACKENDS[0], "--chunk-edges", str(CHUNK),
                     "--output", out1, "--json"]) == 0

    monkeypatch.setenv(ENV_VAR, "build:2")
    with pytest.raises(InjectedFault):
        cli.main(["--input", gpath, "--k", "4", "--backend",
                  STREAMING_BACKENDS[0], "--chunk-edges", str(CHUNK),
                  "--checkpoint-dir", ckdir, "--checkpoint-every", "1",
                  "--json"])
    monkeypatch.delenv(ENV_VAR)

    out2 = str(tmp_path / "resumed.parts")
    assert cli.main(["--input", gpath, "--k", "4", "--backend",
                     STREAMING_BACKENDS[0], "--chunk-edges", str(CHUNK),
                     "--checkpoint-dir", ckdir, "--resume",
                     "--output", out2, "--json"]) == 0
    assert np.array_equal(formats.read_partition(out1),
                          formats.read_partition(out2))


@pytest.mark.parametrize("phase", ["build", "score"])
def test_fault_then_resume_carry_mode(tmp_path, phase, monkeypatch):
    """Kill+resume with carry-over tails: the in-flight carried actives
    are checkpointed state, so the resumed run must still match the
    uninterrupted one exactly."""
    if "tpu" not in list_backends():
        pytest.skip("tpu backend unavailable")
    es = graph()
    kw = {"chunk_edges": CHUNK, "carry_tail": True}
    expect = get_backend("tpu", **kw).partition(es, K, comm_volume=True)

    ck = Checkpointer(str(tmp_path), every=1)
    monkeypatch.setenv(ENV_VAR, f"{phase}:2")
    with pytest.raises(InjectedFault):
        get_backend("tpu", **kw).partition(
            es, K, comm_volume=True, checkpointer=ck)
    monkeypatch.delenv(ENV_VAR)
    assert ck.load() is not None

    res = get_backend("tpu", **kw).partition(
        es, K, comm_volume=True, checkpointer=ck, resume=True)
    assert np.array_equal(res.assignment, expect.assignment)
    assert res.edge_cut == expect.edge_cut
    assert res.comm_volume == expect.comm_volume


def test_carry_checkpoint_gated_from_no_carry_resume(tmp_path, monkeypatch):
    """state_format distinguishes carry-mode checkpoints, so a checkpoint
    written with carry_tail=True refuses a carry_tail=False resume
    (different in-flight state shape) instead of silently dropping the
    carried constraints."""
    if "tpu" not in list_backends():
        pytest.skip("tpu backend unavailable")
    es = graph()
    ck = Checkpointer(str(tmp_path), every=1)
    monkeypatch.setenv(ENV_VAR, "build:2")
    with pytest.raises(InjectedFault):
        get_backend("tpu", chunk_edges=CHUNK, carry_tail=True).partition(
            es, K, checkpointer=ck)
    monkeypatch.delenv(ENV_VAR)
    with pytest.raises(ValueError, match="does not match"):
        get_backend("tpu", chunk_edges=CHUNK, carry_tail=False).partition(
            es, K, checkpointer=ck, resume=True)
