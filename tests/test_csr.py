"""CSR storage engine tests (SURVEY.md §2 #13): lossless conversion,
O(1)/O(log V) access, EdgeStream parity, and end-to-end partition
equivalence with the flat formats."""

import numpy as np
import pytest

from sheep_tpu.io import csr, formats, generators
from sheep_tpu.io.edgestream import EdgeStream, open_input


def _sorted_rows(e):
    e = np.asarray(e, dtype=np.int64).reshape(-1, 2)
    return e[np.lexsort((e[:, 1], e[:, 0]))]


@pytest.fixture
def karate_csr(tmp_path):
    e = generators.karate_club()
    p = str(tmp_path / "karate.csr")
    csr.write_csr(p, EdgeStream.from_array(e))
    return p, e


def test_roundtrip_edge_multiset(karate_csr):
    p, e = karate_csr
    back = EdgeStream.open(p).read_all()
    np.testing.assert_array_equal(_sorted_rows(back), _sorted_rows(e))


def test_detect_and_o1_metadata(karate_csr):
    p, e = karate_csr
    assert formats.detect_format(p) == "csr"
    s = EdgeStream.open(p)
    assert s.num_edges_cheap == len(e)
    assert s.num_vertices == int(e.max()) + 1
    assert s.num_edges_upper_bound == len(e)


def test_grouped_by_source_input_order_kept(tmp_path):
    # duplicates + self loop survive; within a vertex, input order holds
    e = np.array([[2, 0], [0, 5], [2, 9], [0, 3], [2, 9], [1, 1]])
    p = str(tmp_path / "g.csr")
    csr.write_csr(p, EdgeStream.from_array(e), n_vertices=10)
    g = csr.CsrGraph(p)
    np.testing.assert_array_equal(g.neighbors(0), [5, 3])
    np.testing.assert_array_equal(g.neighbors(1), [1])
    np.testing.assert_array_equal(g.neighbors(2), [0, 9, 9])
    assert g.out_degree(5) == 0
    np.testing.assert_array_equal(g.out_degrees(),
                                  [2, 1, 3, 0, 0, 0, 0, 0, 0, 0])
    g.close()


def test_adjacency_matches_bruteforce(karate_csr):
    p, e = karate_csr
    g = csr.CsrGraph(p)
    for u in range(int(e.max()) + 1):
        expect = e[e[:, 0] == u][:, 1]
        np.testing.assert_array_equal(np.sort(g.neighbors(u)),
                                      np.sort(expect))
    g.close()


def test_edge_slice_random_access(karate_csr):
    p, _ = karate_csr
    g = csr.CsrGraph(p)
    full = g.edge_slice(0, g.n_edges)
    for s, t in [(0, 1), (3, 17), (g.n_edges - 2, g.n_edges),
                 (5, 5), (0, g.n_edges)]:
        np.testing.assert_array_equal(g.edge_slice(s, t), full[s:t])
    g.close()


def test_chunked_stream_shard_and_resume(karate_csr):
    p, _ = karate_csr
    s = EdgeStream.open(p)
    whole = s.read_all()
    # small chunks, round-robin over 3 shards: disjoint cover, in order
    parts = [list(s.chunks(8, shard, 3)) for shard in range(3)]
    seen = [None] * (-(-len(whole) // 8))
    for shard, chunks in enumerate(parts):
        for j, c in enumerate(chunks):
            seen[j * 3 + shard] = c
    np.testing.assert_array_equal(np.concatenate(seen), whole)
    # start_chunk resume skips exactly the first chunks
    np.testing.assert_array_equal(
        np.concatenate(list(s.chunks(8, start_chunk=2))), whole[16:])


def test_empty_and_isolated_vertices(tmp_path):
    p = str(tmp_path / "empty.csr")
    csr.write_csr(p, EdgeStream.from_array(np.zeros((0, 2), int)),
                  n_vertices=4)
    s = EdgeStream.open(p)
    assert s.num_edges == 0 and s.num_vertices == 4
    assert list(s.chunks(4)) == []


def test_header_rejects_garbage(tmp_path):
    p = str(tmp_path / "bad.csr")
    with open(p, "wb") as f:
        f.write(b"NOTSHEEP" + b"\0" * 40)
    with pytest.raises(ValueError, match="not a SHEEPCSR"):
        csr.read_header(p)


def test_endpoint_range_validated(tmp_path):
    p = str(tmp_path / "g.csr")
    with pytest.raises(ValueError, match="out of range"):
        csr.write_csr(p, EdgeStream.from_array(np.array([[0, 7]])),
                      n_vertices=4)


def test_wide_dtype_selection():
    assert csr.CsrHeader(1 << 20, 0, False).indices_dtype == np.dtype("<i4")
    assert csr.CsrHeader(1 << 32, 0, True).indices_dtype == np.dtype("<i8")


def test_converter_main(tmp_path, capsys):
    e = generators.karate_club()
    src = str(tmp_path / "g.bin32")
    formats.write_edges(src, e)
    dst = str(tmp_path / "g.csr")
    assert csr.main([src, dst]) == 0
    assert "34 vertices" in capsys.readouterr().out
    np.testing.assert_array_equal(
        _sorted_rows(EdgeStream.open(dst).read_all()), _sorted_rows(e))


def test_partition_equivalent_to_bin32(tmp_path):
    """Stream order changes under CSR regrouping; the partition must not
    (the forest is a function of the constraint multiset — ops/elim.py)."""
    from sheep_tpu.backends.base import get_backend

    e = generators.rmat(8, 8, seed=3)
    src = str(tmp_path / "g.bin32")
    formats.write_edges(src, e)
    dst = str(tmp_path / "g.csr")
    csr.write_csr(dst, EdgeStream.open(src))
    res_bin = get_backend("pure").partition(open_input(src), 4)
    res_csr = get_backend("pure").partition(open_input(dst), 4)
    np.testing.assert_array_equal(res_bin.assignment, res_csr.assignment)
    assert res_bin.edge_cut == res_csr.edge_cut
    assert res_bin.balance == res_csr.balance
