"""Observability layer (ISSUE 3): span nesting/parent ids, counter
registry, heartbeat cadence + final flush, manifest completeness, CLI
--trace/--heartbeat-secs end-to-end."""

import io
import json
import threading
import time

import numpy as np

from sheep_tpu import cli, obs
from sheep_tpu.io import formats, generators
from sheep_tpu.obs import CounterRegistry, Heartbeat, Tracer, collect_manifest


def _records(buf):
    return [json.loads(l) for l in buf.getvalue().splitlines()]


# -- spans -----------------------------------------------------------------

def test_span_nesting_parent_ids():
    buf = io.StringIO()
    with obs.tracing(buf):
        with obs.span("a"):
            with obs.span("b", i=1):
                pass
            with obs.span("b", i=2):
                with obs.span("c"):
                    pass
    recs = _records(buf)
    starts = {r["id"]: r for r in recs if r["event"] == "span_start"}
    ends = {r["id"]: r for r in recs if r["event"] == "span_end"}
    assert set(starts) == set(ends), "every start has a matching end"
    by_name = {}
    for r in ends.values():
        by_name.setdefault(r["span"], []).append(r)
    a = by_name["a"][0]
    assert a["parent"] is None
    assert all(b["parent"] == a["id"] for b in by_name["b"])
    assert by_name["c"][0]["parent"] == by_name["b"][1]["id"]
    # start/end agree on parent, and attrs ride both
    for r in ends.values():
        assert starts[r["id"]]["parent"] == r["parent"]
    assert sorted(b["i"] for b in by_name["b"]) == [1, 2]
    assert all(e["secs"] >= 0 for e in ends.values())


def test_span_explicit_begin_end_and_extra_fields():
    buf = io.StringIO()
    with obs.tracing(buf):
        sp = obs.begin("seg", i=7)
        sp.end(rounds=3)
        sp.end(rounds=99)  # double end is a no-op, not a duplicate record
    ends = [r for r in _records(buf) if r["event"] == "span_end"]
    assert len(ends) == 1 and ends[0]["rounds"] == 3 and ends[0]["i"] == 7


def test_span_counter_deltas_at_boundaries():
    buf = io.StringIO()
    with obs.tracing(buf):
        with obs.span("outer"):
            obs.inc("syncs")
            with obs.span("inner"):
                obs.inc("syncs")
                obs.absorb({"rounds": 5, "mode": "compact"})
    recs = _records(buf)
    ends = {r["span"]: r for r in recs if r["event"] == "span_end"}
    assert ends["inner"]["counters"] == {"syncs": 1, "rounds": 5,
                                         "mode": "compact"}
    assert ends["outer"]["counters"]["syncs"] == 2
    # close() flushed the final registry totals as one counters event
    final = [r for r in recs if r["event"] == "counters"]
    assert final and final[0]["syncs"] == 2 and final[0]["rounds"] == 5


def test_disabled_tracing_is_noop():
    assert obs.get_tracer() is None
    with obs.span("x", i=1) as sp:
        sp.end()
    obs.inc("c")
    obs.absorb({"a": 1})
    obs.progress(chunks_done=3)
    obs.chunk_progress(1, 10)
    obs.event("whatever", x=1)
    assert obs.get_tracer() is None


def test_error_inside_span_is_recorded_and_closed():
    buf = io.StringIO()
    try:
        with obs.tracing(buf):
            with obs.span("doomed"):
                raise RuntimeError("boom")
    except RuntimeError:
        pass
    ends = [r for r in _records(buf) if r["event"] == "span_end"]
    assert ends and ends[0]["error"] == "RuntimeError"


def test_stats_accumulator_sums_across_runs():
    """Each partition call starts a FRESH cumulative stats dict; two
    runs under one tracer must sum into the registry (not overwrite),
    and span deltas must never go negative (review finding)."""
    buf = io.StringIO()
    with obs.tracing(buf):
        for run in range(2):
            acc = obs.stats_accumulator()  # fresh per run, like backends
            stats = {}
            with obs.span("build", run=run):
                for syncs in (1, 2, 3):
                    stats["host_syncs"] = syncs
                    stats["mode"] = "compact"
                    acc.absorb(stats)
    recs = _records(buf)
    builds = [r for r in recs if r["event"] == "span_end"]
    assert builds[0]["counters"]["host_syncs"] == 3
    assert builds[1]["counters"]["host_syncs"] == 3, \
        "second run's delta is its own +3, not 3-overwrites-3 = nothing"
    final = [r for r in recs if r["event"] == "counters"][0]
    assert final["host_syncs"] == 6 and final["mode"] == "compact"


def test_registry_inc_gauge_absorb_delta():
    reg = CounterRegistry()
    reg.inc("a")
    reg.inc("a", 4)
    reg.gauge("mode", "dense")
    before = reg.snapshot()
    reg.absorb({"a": 9, "b": 2.5, "mode": "compact"})
    d = CounterRegistry.delta(before, reg.snapshot())
    assert d == {"a": 4, "b": 2.5, "mode": "compact"}
    # absorb is overwrite-merge: re-absorbing is idempotent
    reg.absorb({"a": 9, "b": 2.5})
    assert reg["a"] == 9 and reg["b"] == 2.5


def test_writer_is_thread_safe():
    buf = io.StringIO()
    tr = Tracer(buf)

    def hammer(tid):
        for i in range(50):
            tr.emit("e", tid=tid, i=i)

    threads = [threading.Thread(target=hammer, args=(t,)) for t in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    recs = _records(buf)  # raises if any line interleaved/corrupted
    assert len(recs) == 200


# -- heartbeat -------------------------------------------------------------

def test_heartbeat_cadence_and_final_flush():
    buf = io.StringIO()
    tr = Tracer(buf)
    obs.install(tr)
    try:
        hb = Heartbeat(tr, 0.05).start()
        obs.progress(phase="build", edges_done=0, edges_total=1000)
        for i in range(4):
            time.sleep(0.15)
            obs.progress(edges_done=(i + 1) * 250)
            obs.inc("host_syncs")
        hb.stop()
    finally:
        obs.uninstall()
        tr.close()
    beats = [r for r in _records(buf) if r["event"] == "heartbeat"]
    # ~600ms of work at a 50ms cadence: even a heavily-loaded 1-core
    # host lands several periodic beats plus the final flush
    assert len(beats) >= 3, beats
    assert [b["seq"] for b in beats] == list(range(len(beats)))
    assert beats[-1]["final"] is True
    assert beats[-1]["edges_done"] == 1000
    assert beats[-1]["counters"]["host_syncs"] == 4
    assert any("edges_per_sec" in b for b in beats)
    assert any("eta_s" in b for b in beats)


def test_heartbeat_final_flush_even_when_faster_than_cadence():
    buf = io.StringIO()
    tr = Tracer(buf)
    hb = Heartbeat(tr, 60.0).start()  # would never fire on its own
    hb.stop()
    tr.close()
    beats = [r for r in _records(buf) if r["event"] == "heartbeat"]
    assert len(beats) == 1 and beats[0]["final"] is True


# -- manifest --------------------------------------------------------------

def test_manifest_completeness():
    m = collect_manifest(config={"input": "g.edges", "k": 8,
                                 "weird": object()},
                         backend="pure")
    for key in ("argv", "python", "hostname", "pid", "git_sha", "backend",
                "config", "jax_version", "jaxlib_version", "platform",
                "device_count", "local_device_count", "process_count",
                "devices"):
        assert key in m, key
    assert m["git_sha"], "repo is a git checkout; sha must resolve"
    assert m["platform"] == "cpu" and m["device_count"] >= 1
    assert m["config"]["k"] == 8
    json.dumps(m)  # the whole record must be JSON-clean


# -- numpy scalar serialization (satellite) --------------------------------

def test_jsonable_numpy_scalar_subtypes():
    buf = io.StringIO()
    from sheep_tpu.utils.metrics import MetricsWriter

    mw = MetricsWriter(buf)
    mw.emit("diag", flag=np.bool_(True), f32=np.float32(1.5),
            i16=np.int16(-3), s=np.str_("hi"), b=np.bytes_(b"raw"),
            dt=np.datetime64("2026-08-03"),
            arr=np.array([np.bool_(False)]))
    rec = _records(buf)[0]
    assert rec["flag"] is True and rec["f32"] == 1.5 and rec["i16"] == -3
    assert rec["s"] == "hi" and rec["arr"] == [False]
    assert rec["b"] == "raw", "np.bytes_ degrades to text, not a crash"
    assert "2026-08-03" in rec["dt"]


def test_heartbeat_survives_emit_failures():
    """One transient sink failure must not kill the thread: silenced
    heartbeats read as a dead run (review finding)."""
    buf = io.StringIO()
    tr = Tracer(buf)
    fails = {"n": 2}
    real_emit = tr.emit

    def flaky_emit(event, **fields):
        if event == "heartbeat" and fails["n"] > 0:
            fails["n"] -= 1
            raise OSError("disk blip")
        real_emit(event, **fields)

    tr.emit = flaky_emit
    hb = Heartbeat(tr, 0.03).start()
    deadline = time.time() + 5
    while fails["n"] > 0 and time.time() < deadline:
        time.sleep(0.03)
    time.sleep(0.1)  # at least one post-failure periodic beat
    hb.stop()
    tr.close()
    beats = [r for r in _records(buf) if r["event"] == "heartbeat"]
    assert fails["n"] == 0, "both injected failures fired"
    assert len(beats) >= 2 and beats[-1]["final"] is True


# -- CLI end-to-end (the acceptance criterion, in miniature) ---------------

def test_cli_trace_and_heartbeat(tmp_path):
    gpath = str(tmp_path / "g.edges")
    formats.write_edges(gpath, generators.karate_club())
    tpath = str(tmp_path / "trace.jsonl")
    rc = cli.main(["--input", gpath, "--k", "2", "--backend", "pure",
                   "--trace", tpath, "--heartbeat-secs", "0.1", "--json"])
    assert rc == 0
    recs = [json.loads(l) for l in open(tpath)]
    events = [r["event"] for r in recs]
    assert events[0] == "manifest"
    m = recs[0]
    assert m["config"]["k"] == "2" and m["git_sha"]
    starts = {r["id"]: r for r in recs if r["event"] == "span_start"}
    ends = {r["id"]: r for r in recs if r["event"] == "span_end"}
    assert set(starts) == set(ends) and starts, "complete span tree"
    for r in ends.values():  # every parent resolves within the trace
        assert r["parent"] is None or r["parent"] in starts
    names = {r["span"] for r in ends.values()}
    assert {"run", "partition", "degrees", "build", "split",
            "score"} <= names
    assert sum(1 for r in recs if r["event"] == "heartbeat") >= 1
    assert any(r["event"] == "scores" for r in recs)
    assert obs.get_tracer() is None, "CLI uninstalled its tracer"


def test_cli_heartbeat_requires_trace(tmp_path, capsys):
    import pytest

    with pytest.raises(SystemExit):
        cli.main(["--input", "x", "--k", "2", "--heartbeat-secs", "1"])


def test_cli_trace_appends_across_runs(tmp_path):
    """--trace opens append-mode (like --metrics-out): two runs into one
    file yield two manifests, and ids stay resolvable per run."""
    gpath = str(tmp_path / "g.edges")
    formats.write_edges(gpath, generators.karate_club())
    tpath = str(tmp_path / "trace.jsonl")
    for _ in range(2):
        assert cli.main(["--input", gpath, "--k", "2", "--backend",
                         "pure", "--trace", tpath, "--json"]) == 0
    recs = [json.loads(l) for l in open(tpath)]
    assert sum(1 for r in recs if r["event"] == "manifest") == 2
