"""tools/obs_smoke.sh wired as a fast tier-1 gate (ISSUE 3): a tiny
traced RMAT build through the real CLI must produce a parseable trace
with a manifest, a complete span tree, and >= 1 heartbeat."""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_obs_smoke_script(tmp_path):
    env = {**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONPATH": REPO}
    r = subprocess.run(
        ["bash", os.path.join(REPO, "tools", "obs_smoke.sh"),
         str(tmp_path)],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=480)
    assert r.returncode == 0, f"stdout:\n{r.stdout}\nstderr:\n{r.stderr}"
    assert "obs smoke OK" in r.stdout
    report = open(tmp_path / "report.txt").read()
    assert "UNCLOSED" not in report
    assert "heartbeats:" in report


def test_obs_smoke_report_check_gate(tmp_path):
    """The --check gate the smoke relies on actually fails a trace with
    a hole in it (guards against the gate rotting into a no-op)."""
    bad = tmp_path / "bad.jsonl"
    bad.write_text('{"event": "span_start", "ts": 1.0, "span": "x", '
                   '"id": 1, "parent": null}\n')
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "trace_report.py"),
         str(bad), "--check"],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert r.returncode == 3
