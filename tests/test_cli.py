"""CLI driver tests (SURVEY.md §2 #12)."""

import json
import subprocess
import sys

import numpy as np
import pytest

from sheep_tpu.io import formats, generators


@pytest.fixture
def karate_file(tmp_path):
    p = str(tmp_path / "karate.edges")
    formats.write_edges(p, generators.karate_club())
    return p


def run_cli(*argv):
    from sheep_tpu import cli

    return cli.main(list(argv))


def test_end_to_end(karate_file, tmp_path, capsys):
    out = str(tmp_path / "karate.parts")
    rc = run_cli("--input", karate_file, "--k", "2", "--backend", "pure",
                 "--output", out)
    assert rc == 0
    printed = capsys.readouterr().out
    assert "edge cut" in printed
    summary = json.loads(printed.strip().splitlines()[-1])
    assert summary["k"] == 2 and summary["total_edges"] == 78
    parts = formats.read_partition(out)
    assert parts.shape == (34,) and set(np.unique(parts)) <= {0, 1}


def test_json_only(karate_file, capsys):
    rc = run_cli("--input", karate_file, "--k", "2", "--backend", "pure", "--json")
    assert rc == 0
    lines = capsys.readouterr().out.strip().splitlines()
    assert len(lines) == 1
    s = json.loads(lines[0])
    assert s["backend"] == "pure" and s["edges_per_sec"] > 0


def test_list_backends(capsys):
    rc = run_cli("--list-backends")
    assert rc == 0
    assert "pure" in capsys.readouterr().out


def test_subprocess_invocation(karate_file):
    """The real user surface: python -m sheep_tpu.cli."""
    r = subprocess.run(
        [sys.executable, "-m", "sheep_tpu.cli", "--input", karate_file,
         "--k", "2", "--backend", "pure", "--json"],
        capture_output=True, text=True, cwd="/root/repo",
    )
    assert r.returncode == 0, r.stderr
    assert json.loads(r.stdout.strip().splitlines()[-1])["total_edges"] == 78


def test_profile_dir_writes_trace(karate_file, tmp_path, capsys):
    """--profile-dir must produce a trace artifact (VERDICT r1 weak #6:
    the profiler path had never been exercised, even on cpu-jax)."""
    import os

    prof = str(tmp_path / "trace")
    rc = run_cli("--input", karate_file, "--k", "2", "--backend", "tpu",
                 "--profile-dir", prof, "--json")
    assert rc == 0
    capsys.readouterr()
    found = [os.path.join(dp, f) for dp, _, fs in os.walk(prof) for f in fs]
    assert found, f"no trace files written under {prof}"


def test_sharded_backend_comm_volume_default_matches(karate_file):
    """All backends default comm_volume on (VERDICT r1 weak #5) — call
    partition() without the kwarg so the backend's own default is what
    is under test (the CLI always passes it explicitly)."""
    from sheep_tpu.backends.base import get_backend
    from sheep_tpu.io.edgestream import EdgeStream

    with EdgeStream.open(karate_file) as es:
        res = get_backend("tpu-sharded").partition(es, 2)
    assert res.comm_volume is not None


def test_missing_required_args():
    with pytest.raises(SystemExit):
        run_cli("--k", "2")


def test_partition_api_rejects_unknown_opts(karate_file):
    import sheep_tpu

    with pytest.raises(TypeError, match="unknown option"):
        sheep_tpu.partition(karate_file, 2, backend="pure", bogus=1)
    # constructor opts route through
    res = sheep_tpu.partition(karate_file, 2, backend="pure", chunk_edges=10,
                              comm_volume=False)
    assert res.comm_volume is None


def test_partition_multi_rejects_unknown_opts(karate_file):
    # ADVICE r3: partition_multi must validate options like partition()
    import sheep_tpu

    with pytest.raises(TypeError, match="unknown option"):
        sheep_tpu.partition_multi(karate_file, [2, 4], backend="pure",
                                  bogus=1)


def test_duplicate_ks_deduped(karate_file, capsys):
    # ADVICE r3: --k 2,2 must not alias output paths / wall accounting
    rc = run_cli("--input", karate_file, "--k", "2,2", "--backend", "pure",
                 "--json")
    assert rc == 0
    lines = [json.loads(x) for x in capsys.readouterr().out.strip()
             .splitlines()]
    assert [r["k"] for r in lines] == [2]


def test_score_only_rejects_k_list(karate_file, tmp_path, capsys):
    # ADVICE r3: a comma list with --score-only is a clean usage error,
    # not a ValueError traceback
    out = str(tmp_path / "karate.parts")
    assert run_cli("--input", karate_file, "--k", "2", "--backend", "pure",
                   "--output", out) == 0
    capsys.readouterr()
    with pytest.raises(SystemExit) as e:
        run_cli("--input", karate_file, "--k", "2,4", "--score-only", out)
    assert e.value.code == 2
