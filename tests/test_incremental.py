"""Incremental repartitioning tests (ISSUE 15).

The acceptance pins:

- **Adds are exact**: the shuffled two-halves replay — build half the
  stream, fold the other half as delta epochs — is BIT-IDENTICAL to a
  one-shot build of the ``delta:`` input on the pure/cpu/tpu backends,
  through the CLI ``--deltas`` replay, and through the served
  ``update`` verb (the anchored-order contract + fixpoint uniqueness,
  sheep_tpu/incremental.py module docstring).
- **Delete + full compaction** matches a clean rebuild of the
  surviving edges bit-identically (full compaction IS a clean rebuild
  of the survivor stream, re-anchored); **subtree compaction** ships
  with a tested score bound instead.
- **Anchored-order drift** is score-bounded against the fresh-order
  one-shot build (the quality gate's dynamic scenario enforces the
  same bound in CI).
- The delta-log format survives damage under the SHEEP_IO_POLICY
  contract (tests/test_edgestream.py TestDeltaLogDamage) and a
  resident served partition survives kill + restart at its journaled
  epoch (tests/test_journal.py drill).
"""

import os
import threading

import numpy as np
import pytest

import sheep_tpu
from sheep_tpu import incremental as inc
from sheep_tpu.backends.base import get_backend, list_backends
from sheep_tpu.io import deltalog as dl
from sheep_tpu.io.edgestream import EdgeStream, open_input

N = 512
SEED = 5


def _graph(m=4000, n=N, seed=SEED):
    rng = np.random.default_rng(seed)
    return rng.integers(0, n, (m, 2)).astype(np.int64)


def _base_file(tmp_path, edges, name="base.bin64"):
    p = str(tmp_path / name)
    with open(p, "wb") as f:
        f.write(np.asarray(edges, np.int64).astype("<u8").tobytes())
    return p


def _backends():
    avail = list_backends()
    return [b for b in ("pure", "cpu", "tpu") if b in avail]


# ----------------------------------------------------------------------
# delta-log format
# ----------------------------------------------------------------------
class TestDeltaLog:
    def test_header_round_trip(self, tmp_path):
        log = str(tmp_path / "g.dlog")
        dl.write_header(log, "base.bin64")
        hdr = dl.read_header(log)
        assert hdr["base_spec"] == "base.bin64"
        # an un-compacted log (floor 0) stays on the v1 bytes so v1
        # readers keep working; the v2 layout appears only once a
        # compaction stamps an epoch floor
        assert hdr["version"] == 1
        assert hdr["epoch_floor"] == 0
        dl.write_header(log, "base.bin64", epoch_floor=3)
        hdr2 = dl.read_header(log)
        assert hdr2["version"] == dl.VERSION
        assert hdr2["epoch_floor"] == 3

    def test_not_a_delta_log(self, tmp_path):
        p = str(tmp_path / "junk")
        with open(p, "wb") as f:
            f.write(b"not a log at all")
        with pytest.raises(ValueError, match="bad magic"):
            dl.read_header(p)

    def test_writer_epochs_and_reopen(self, tmp_path):
        log = str(tmp_path / "g.dlog")
        e = _graph(64)
        with dl.DeltaLogWriter(log, base_spec="b") as w:
            assert w.append(e[:10]) == 1
            assert w.append(e[10:20], op=dl.OP_DEL, epoch=1) == 1
            assert w.append(e[20:30]) == 2
        with dl.DeltaLogWriter(log) as w2:  # reopen: no base_spec
            assert w2.last_epoch == 2
            assert w2.append_epoch(adds=e[30:40], dels=e[40:45]) == 3
        r = dl.DeltaLogReader(log)
        eps = list(r.epochs())
        assert [ep for ep, _, _ in eps] == [1, 2, 3]
        ep1_adds, ep1_dels = eps[0][1], eps[0][2]
        assert np.array_equal(ep1_adds, e[:10])
        assert np.array_equal(ep1_dels, e[10:20])
        assert r.max_epoch == 3
        # start/up_to windows
        assert [ep for ep, _, _ in r.epochs(start_epoch=2)] == [3]
        r2 = dl.DeltaLogReader(log)
        assert [ep for ep, _, _ in r2.epochs(up_to=2)] == [1, 2]

    def test_writer_validation(self, tmp_path):
        log = str(tmp_path / "g.dlog")
        with pytest.raises(ValueError, match="base_spec"):
            dl.DeltaLogWriter(log)
        with dl.DeltaLogWriter(log, base_spec="b") as w:
            with pytest.raises(ValueError, match="bad delta op"):
                w.append(_graph(4), op=9)
            with pytest.raises(ValueError, match="non-negative"):
                w.append(np.array([[-1, 2]]))
            w.append(_graph(4), epoch=5)
            with pytest.raises(ValueError, match="never rewind"):
                w.append(_graph(4), epoch=4)
            with pytest.raises(ValueError,
                               match="logs deltas over"):
                dl.DeltaLogWriter(log, base_spec="other")

    def test_net_effect_cancels_adds_then_tombstones_base(self):
        adds = np.array([[1, 2], [3, 4], [2, 1]], np.int64)
        rec = np.zeros(5, dtype=dl.RECORD_DTYPE)
        rec["u"][:3] = adds[:, 0]
        rec["v"][:3] = adds[:, 1]
        rec["epoch"] = 1
        # two DELs of {1,2}: one cancels an add (undirected match),
        # one tombstones the base; one DEL of {7,8} tombstones base
        rec["u"][3:] = [2, 7]
        rec["v"][3:] = [1, 8]
        rec["op"][3:] = dl.OP_DEL
        surv, tombs = dl.net_effect(rec)
        keys = {tuple(r) for r in surv.tolist()}
        assert keys == {(1, 2), (3, 4)}  # one {1,2} copy cancelled
        assert sorted(map(tuple, tombs.tolist())) == [(7, 8)]

    def test_del_never_cancels_a_later_add(self, tmp_path):
        """In-order resolution: deleting an edge the graph does not
        have removes nothing — it must NOT reach forward and erase an
        add from a later epoch, on either the one-shot or the
        incremental path (they'd diverge otherwise)."""
        e = _graph(600)
        base = _base_file(tmp_path, e[:300])
        absent = np.array([[N - 1, N - 2]], np.int64)
        assert not any(tuple(sorted(r)) == (N - 2, N - 1)
                       for r in e[:300].tolist())
        log = str(tmp_path / "g.dlog")
        with dl.DeltaLogWriter(log, base_spec=base) as w:
            w.append_epoch(dels=absent)       # epoch 1: no-op delete
            w.append(absent)                  # epoch 2: ADD it
            w.append(e[300:])                 # epoch 3
        st = open_input(f"delta:{log}", n_vertices=N)
        keys = [tuple(sorted(r)) for r in st.read_all().tolist()]
        assert keys.count((N - 2, N - 1)) == 1  # the add SURVIVES
        # and the incremental replay lands bit-identical
        be = get_backend("tpu", chunk_edges=777)
        one = be.partition(open_input(f"delta:{log}", n_vertices=N),
                           4, comm_volume=False)
        state, _ = inc.begin_incremental(
            open_input(base, n_vertices=N), 4, backend=be)
        be.partition_update(state, deletes=absent, epoch=1,
                            score=False, compact="never")
        be.partition_update(state, adds=absent, epoch=2, score=False)
        r = be.partition_update(state, adds=e[300:], epoch=3,
                                score=True)
        assert np.array_equal(r.assignment, one.assignment)
        assert (r.edge_cut, r.total_edges) == (one.edge_cut,
                                               one.total_edges)

    def test_filter_tombstones_multiset(self):
        chunks = [np.array([[1, 2], [3, 4]], np.int64),
                  np.array([[2, 1], [5, 6]], np.int64)]
        out = list(dl.filter_tombstones(chunks,
                                        np.array([[1, 2]], np.int64)))
        flat = np.concatenate(out)
        # exactly ONE {1,2} occurrence removed
        assert len(flat) == 3
        assert sum(1 for r in flat.tolist()
                   if tuple(sorted(r)) == (1, 2)) == 1

    def test_delta_spec_parsing(self, tmp_path):
        e = _graph()
        base = _base_file(tmp_path, e[:2000])
        log = str(tmp_path / "g.dlog")
        with dl.DeltaLogWriter(log, base_spec=base) as w:
            w.append(e[2000:3000])
            w.append(e[3000:])
        st = open_input(f"delta:{log}")
        assert st.epoch == 2
        assert len(st.read_all()) == len(e)
        capped = open_input(f"delta:{log}@1")
        assert capped.epoch == 1
        assert len(capped.read_all()) == 3000
        with pytest.raises(ValueError, match="does not exist"):
            open_input(f"delta:{tmp_path}/nope.dlog")
        with pytest.raises(ValueError, match="below the"):
            open_input(f"delta:{log}", n_vertices=4)
        with pytest.raises(NotImplementedError):
            list(st.chunks(64, shard=0, num_shards=2))

    def test_delta_logs_do_not_nest(self, tmp_path):
        e = _graph(100)
        base = _base_file(tmp_path, e)
        inner = str(tmp_path / "inner.dlog")
        with dl.DeltaLogWriter(inner, base_spec=base) as w:
            w.append(e[:10])
        outer = str(tmp_path / "outer.dlog")
        dl.write_header(outer, f"delta:{inner}")
        with pytest.raises(ValueError, match="do not nest"):
            open_input(f"delta:{outer}")


# ----------------------------------------------------------------------
# the exactness contract: adds == one-shot, per backend
# ----------------------------------------------------------------------
@pytest.mark.parametrize("backend", _backends())
def test_two_halves_replay_bit_identical(tmp_path, backend):
    e = _graph()
    half = len(e) // 2
    base = _base_file(tmp_path, e[:half])
    log = str(tmp_path / "g.dlog")
    with dl.DeltaLogWriter(log, base_spec=base) as w:
        w.append(e[half: half + 1000])
        w.append(e[half + 1000:])
    be = get_backend(backend, chunk_edges=777)
    one = be.partition(open_input(f"delta:{log}", n_vertices=N), 8,
                       comm_volume=False)
    state, res0 = inc.begin_incremental(
        open_input(base, n_vertices=N), 8, backend=be)
    r1 = be.partition_update(state, adds=e[half: half + 1000],
                             score=False)
    assert r1 is None  # score=False returns nothing, folds silently
    r2 = be.partition_update(state, adds=e[half + 1000:], score=True)
    assert state.epoch == 2
    assert np.array_equal(r2.assignment, one.assignment)
    assert (r2.edge_cut, r2.total_edges) == (one.edge_cut,
                                             one.total_edges)
    assert r2.balance == pytest.approx(one.balance)
    assert r2.diagnostics["epoch"] == 2.0


def test_incremental_state_round_trips_through_snapshot(tmp_path):
    e = _graph()
    half = len(e) // 2
    base = _base_file(tmp_path, e[:half])
    be = get_backend("tpu", chunk_edges=777)
    state, _ = inc.begin_incremental(
        open_input(base, n_vertices=N), 8, backend=be)
    be.partition_update(state, adds=e[half:-500], score=False)
    be.partition_update(state, deletes=e[:100], score=False,
                        compact="never")
    path = str(tmp_path / "st.npz")
    inc.save_state(state, path)
    loaded = inc.load_state(path)
    assert loaded.epoch == state.epoch
    assert np.array_equal(loaded.minp, state.minp)
    # the reloaded state continues BIT-identically
    ra = be.partition_update(state, adds=e[-500:], score=True,
                             compact="never")
    rb = be.partition_update(loaded, adds=e[-500:], score=True,
                             compact="never")
    assert np.array_equal(ra.assignment, rb.assignment)
    assert ra.edge_cut == rb.edge_cut


def test_epoch_idempotency_and_vertex_space_guard(tmp_path):
    e = _graph()
    base = _base_file(tmp_path, e[:2000])
    be = get_backend("tpu", chunk_edges=777)
    state, _ = inc.begin_incremental(
        open_input(base, n_vertices=N), 4, backend=be)
    assert be.partition_update(state, adds=e[2000:2100],
                               epoch=1) is not None
    # replaying an applied epoch is a silent no-op (the served retry
    # contract)
    assert be.partition_update(state, adds=e[2000:2100],
                               epoch=1) is None
    assert state.epoch == 1
    with pytest.raises(ValueError, match="outside the resident"):
        be.partition_update(state, adds=np.array([[0, N + 7]]))
    with pytest.raises(ValueError, match="bad compact mode"):
        be.partition_update(state, adds=e[:4], compact="later")


def test_multidevice_backends_accept_delta_reject_multihost(tmp_path):
    """ISSUE 19 flips the ISSUE-15 rejection: a single-process
    multi-device mesh folds delta epochs through the lockstep
    machinery (full parity coverage in
    test_incremental_multidevice.py). The one rejection left is a
    multi-HOST mesh, which cannot byte-range an anchored log."""
    e = _graph(200)
    base = _base_file(tmp_path, e)
    log = str(tmp_path / "g.dlog")
    with dl.DeltaLogWriter(log, base_spec=base) as w:
        w.append(e[:10])
    from sheep_tpu.types import UnsupportedGraphError

    oracle = get_backend("tpu", chunk_edges=777).partition(
        open_input(f"delta:{log}", n_vertices=N), 4, comm_volume=False)
    for name in ("tpu-sharded", "tpu-bigv"):
        if name not in list_backends():
            continue
        be = get_backend(name)
        assert be.supports_incremental
        r = be.partition(open_input(f"delta:{log}", n_vertices=N), 4,
                         comm_volume=False)
        assert np.array_equal(r.assignment, oracle.assignment)
        import jax

        with pytest.MonkeyPatch.context() as mp:
            mp.setattr(jax, "process_count", lambda: 2)
            with pytest.raises(UnsupportedGraphError,
                               match="multi-host"):
                be.partition(open_input(f"delta:{log}", n_vertices=N),
                             4)


# ----------------------------------------------------------------------
# deletions: tombstones, compaction, staleness
# ----------------------------------------------------------------------
def test_delete_full_compact_matches_clean_rebuild(tmp_path):
    e = _graph()
    base = _base_file(tmp_path, e[:2000])
    be = get_backend("tpu", chunk_edges=777)
    state, _ = inc.begin_incremental(
        open_input(base, n_vertices=N), 8, backend=be)
    be.partition_update(state, adds=e[2000:], score=False)
    dels = e[np.random.default_rng(9).permutation(len(e))[:600]]
    r_stale = be.partition_update(state, deletes=dels, score=True,
                                  compact="never")
    assert state.stale_deletes == 600
    mode = inc.compact_state(be, state, mode="full")
    assert mode == "full"
    assert state.stale_deletes == 0
    assert state.anchored_at_epoch == state.epoch
    r = inc.refresh(be, state)
    surv = np.concatenate(list(dl.filter_tombstones([e], dels)))
    clean = be.partition(EdgeStream.from_array(surv, n_vertices=N), 8,
                         comm_volume=False)
    assert np.array_equal(r.assignment, clean.assignment)
    assert (r.edge_cut, r.total_edges) == (clean.edge_cut,
                                           clean.total_edges)
    # the stale pre-compact score already counted the right multiset
    assert r_stale.total_edges == clean.total_edges


def test_subtree_compact_is_local_and_score_bounded(tmp_path):
    e = _graph(6000)
    base = _base_file(tmp_path, e[:3000])
    be = get_backend("tpu", chunk_edges=777)
    state, _ = inc.begin_incremental(
        open_input(base, n_vertices=N), 8, backend=be)
    be.partition_update(state, adds=e[3000:], score=False)
    # a few localized deletes: the dirty set stays small
    dels = e[:30]
    be.partition_update(state, deletes=dels, score=False,
                        compact="never")
    mode = inc.compact_state(be, state, mode="subtree")
    assert mode == "subtree"
    assert state.stats["compact_subtree"] == 1
    # locality: the refold touched a subset, not the whole stream
    assert 0 < state.stats["compact_refolded_edges"] < len(e)
    r = inc.refresh(be, state)
    surv = np.concatenate(list(dl.filter_tombstones([e], dels)))
    clean = be.partition(EdgeStream.from_array(surv, n_vertices=N), 8,
                         comm_volume=False)
    assert r.total_edges == clean.total_edges
    # the explicit, tested score bound of the approximate mode
    assert r.cut_ratio <= clean.cut_ratio + 0.05


def test_staleness_counter_forces_compaction(tmp_path):
    e = _graph()
    base = _base_file(tmp_path, e)
    be = get_backend("tpu", chunk_edges=777)
    state, _ = inc.begin_incremental(
        open_input(base, n_vertices=N), 8, backend=be)
    state.compact_threshold = 50
    be.partition_update(state, deletes=e[:40], score=False)
    assert state.compactions == 0  # under threshold: tombstones ride
    be.partition_update(state, deletes=e[40:100], score=False)
    assert state.compactions == 1  # past threshold: forced
    assert state.stale_deletes == 0


def test_compact_noop_when_nothing_changed(tmp_path):
    e = _graph(1000)
    base = _base_file(tmp_path, e)
    be = get_backend("tpu", chunk_edges=777)
    state, _ = inc.begin_incremental(
        open_input(base, n_vertices=N), 4, backend=be)
    assert inc.compact_state(be, state, mode="auto") == "noop"
    assert state.compactions == 0


def test_anchored_drift_is_score_bounded():
    """The order-anchoring cost on a structured graph stays inside the
    bound the quality gate's dynamic scenario enforces in CI."""
    with open_input("sbm-hash:9:8:0.05:16:3") as es:
        edges = es.read_all()
        n = es.num_vertices
    rng = np.random.default_rng(7)
    e = edges[rng.permutation(len(edges))]
    half = len(e) // 2
    be = get_backend("tpu", chunk_edges=1 << 12)
    state, _ = inc.begin_incremental(
        EdgeStream.from_array(e[:half], n_vertices=n), 8, backend=be)
    res = be.partition_update(state, adds=e[half:], score=True)
    oneshot = be.partition(EdgeStream.from_array(e, n_vertices=n), 8,
                           comm_volume=False)
    assert res.total_edges == oneshot.total_edges
    assert res.cut_ratio <= oneshot.cut_ratio + 0.05


# ----------------------------------------------------------------------
# CLI: --deltas replay and validation
# ----------------------------------------------------------------------
def test_cli_deltas_replay_matches_one_shot(tmp_path, capsys):
    import json

    from sheep_tpu import cli

    e = _graph()
    half = len(e) // 2
    base = _base_file(tmp_path, e[:half])
    log = str(tmp_path / "g.dlog")
    with dl.DeltaLogWriter(log, base_spec=base) as w:
        w.append(e[half:-700])
        w.append(e[-700:])
    rc = cli.main(["--input", base, "--k", "4", "--backend", "tpu",
                   "--num-vertices", str(N), "--chunk-edges", "777",
                   "--deltas", log, "--json"])
    assert rc == 0
    incr = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    rc = cli.main(["--input", f"delta:{log}", "--k", "4",
                   "--backend", "tpu", "--num-vertices", str(N),
                   "--chunk-edges", "777", "--json"])
    assert rc == 0
    one = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert incr["edge_cut"] == one["edge_cut"]
    assert incr["total_edges"] == one["total_edges"]
    assert incr["diagnostics"]["epoch"] == 2.0


def test_cli_deltas_validation(tmp_path):
    from sheep_tpu import cli

    e = _graph(100)
    base = _base_file(tmp_path, e)
    log = str(tmp_path / "g.dlog")
    with dl.DeltaLogWriter(log, base_spec=base) as w:
        w.append(e[:10])
    for extra in (["--refine", "2"], ["--k", "4,8"],
                  ["--checkpoint-dir", str(tmp_path / "ck")],
                  ["--auto-recipe"]):
        argv = ["--input", base, "--k", "4", "--deltas", log] + extra
        if extra == ["--k", "4,8"]:
            argv = ["--input", base, "--deltas", log] + extra
        with pytest.raises(SystemExit):
            cli.main(argv)
    with pytest.raises(SystemExit):
        cli.main(["--input", base, "--k-levels", "2,2",
                  "--deltas", log])
    with pytest.raises(SystemExit):
        cli.main(["--input", base, "--k", "4", "--deltas",
                  str(tmp_path / "missing.dlog")])


# ----------------------------------------------------------------------
# served surface: resident partitions, update/epoch/compact verbs
# ----------------------------------------------------------------------
def _spec(input, n=N, ks=(4,), resident=True, **fields):
    from sheep_tpu.server.protocol import JobSpec

    body = {"input": input, "k": list(ks), "chunk_edges": 512,
            "num_vertices": n, "resident": resident}
    body.update(fields)
    return JobSpec.from_request(body, tenant="inc")


def _run_scheduler(**kw):
    from sheep_tpu.server.scheduler import Scheduler

    sched = Scheduler(**kw)
    t = threading.Thread(target=sched.run, daemon=True)
    t.start()
    return sched, t


def test_served_update_verb_bit_identical(tmp_path):
    e = _graph(3000)
    base = _base_file(tmp_path, e[:1500])
    sched, t = _run_scheduler()
    try:
        job = sched.submit(_spec(base))
        assert sched.wait(job.id, timeout_s=120).state == "done"
        assert sched.stats()["resident_partitions"] == 1
        r1 = sched.update(job.id, adds=e[1500:2200], epoch=1)
        assert r1["applied"] and r1["epoch"] == 1
        r2 = sched.update(job.id, adds=e[2200:], epoch=2, score=True)
        assert r2["epoch"] == 2
        # idempotent replay answers applied=false
        r1b = sched.update(job.id, adds=e[1500:2200], epoch=1)
        assert r1b["applied"] is False
        info = sched.epoch_info(job.id)
        assert info["epoch"] == 2
        assert 0 < info["total_edges"] <= len(e)
        # the served result bit-equals the one-shot delta: build
        log = str(tmp_path / "g.dlog")
        with dl.DeltaLogWriter(log, base_spec=base) as w:
            w.append(e[1500:2200])
            w.append(e[2200:])
        be = get_backend("tpu", chunk_edges=512)
        one = be.partition(open_input(f"delta:{log}", n_vertices=N),
                           4, comm_volume=False)
        assert np.array_equal(job.results[0].assignment,
                              one.assignment)
        assert r2["results"][0]["edge_cut"] == one.edge_cut
        # metrics joined the catalog
        text = sched.render_metrics()
        assert 'sheep_updates_total{tenant="inc"} 2' in text
        assert "sheep_update_latency_seconds_bucket" in text
        # cancel releases the residency + its reservation
        sched.cancel(job.id)
        assert sched.stats()["resident_partitions"] == 0
        with pytest.raises(Exception, match="released"):
            sched.epoch_info(job.id)
    finally:
        sched.shutdown()
        t.join(timeout=60)


def test_served_update_log_form_and_deletes(tmp_path):
    e = _graph(3000)
    base = _base_file(tmp_path, e[:1500])
    log = str(tmp_path / "g.dlog")
    with dl.DeltaLogWriter(log, base_spec=base) as w:
        w.append(e[1500:])
        w.append_epoch(dels=e[:50])
    sched, t = _run_scheduler()
    try:
        job = sched.submit(_spec(base))
        assert sched.wait(job.id, timeout_s=120).state == "done"
        r = sched.update(job.id, log=log, score=True)
        assert r["epochs_applied"] == 2 and r["epoch"] == 2
        assert r["stale_deletes"] == 50
        c = sched.compact_resident(job.id, mode="full", score=True)
        assert c["mode"] == "full" and c["compactions"] == 1
        surv = np.concatenate(list(dl.filter_tombstones([e], e[:50])))
        be = get_backend("tpu", chunk_edges=512)
        clean = be.partition(EdgeStream.from_array(surv, n_vertices=N),
                             4, comm_volume=False)
        assert c["results"][0]["edge_cut"] == clean.edge_cut
        assert np.array_equal(job.results[0].assignment,
                              clean.assignment)
    finally:
        sched.shutdown()
        t.join(timeout=60)


def test_served_update_rejects_non_resident_and_unknown(tmp_path):
    from sheep_tpu.server import protocol

    e = _graph(500)
    base = _base_file(tmp_path, e)
    sched, t = _run_scheduler()
    try:
        job = sched.submit(_spec(base, resident=False))
        assert sched.wait(job.id, timeout_s=120).state == "done"
        with pytest.raises(protocol.ProtocolError,
                           match="not submitted resident"):
            sched.update(job.id, adds=e[:4], epoch=1)
        with pytest.raises(protocol.ProtocolError, match="unknown"):
            sched.epoch_info("j999")
    finally:
        sched.shutdown()
        t.join(timeout=60)


def test_resident_reservation_charges_admission(tmp_path):
    """A held resident partition keeps its modeled bytes reserved, so
    headroom-short jobs queue behind it and releasing it admits them
    (the membudget charge of ISSUE 15 (c))."""
    e = _graph(1000)
    base = _base_file(tmp_path, e)
    sched, t = _run_scheduler()
    try:
        job = sched.submit(_spec(base))
        assert sched.wait(job.id, timeout_s=120).state == "done"
        with sched._lock:
            reserved = sched._reserved_locked()
        assert reserved == (job.modeled_bytes or 0)
        # shrink the budget so the next identical job cannot fit
        # beside the resident reservation: it must QUEUE
        if job.modeled_bytes:
            sched.budget = int(job.modeled_bytes * 1.5)
            j2 = sched.submit(_spec(base, resident=False))
            import time as _t

            _t.sleep(0.3)
            assert sched.get(j2.id).state == "queued"
            sched.cancel(job.id)  # release the residency
            assert sched.wait(j2.id, timeout_s=120).state == "done"
    finally:
        sched.shutdown()
        t.join(timeout=60)


def test_protocol_edge_codec_round_trip():
    from sheep_tpu.server import protocol

    e = _graph(123)
    doc = protocol.encode_edges(e)
    back = protocol.decode_edges(doc)
    assert np.array_equal(back, e)
    assert protocol.decode_edges(None).shape == (0, 2)
    with pytest.raises(protocol.ProtocolError):
        protocol.decode_edges({"nope": 1})
    bad = dict(doc)
    bad["m"] = 7
    with pytest.raises(protocol.ProtocolError):
        protocol.decode_edges(bad)


def test_jobspec_resident_field():
    from sheep_tpu.server.protocol import JobSpec

    spec = JobSpec.from_request({"input": "x", "k": 4,
                                 "resident": True})
    assert spec.resident is True
    assert JobSpec.from_request({"input": "x", "k": 4}).resident \
        is False


# ----------------------------------------------------------------------
# incremental scoring (ISSUE 17): O(Δ) rescoring bit-equals full passes
# ----------------------------------------------------------------------
@pytest.mark.parametrize("backend", _backends())
def test_incremental_rescore_bit_equals_full(tmp_path, backend,
                                             monkeypatch):
    """Add / delete / compaction churn under SHEEP_SCORE_AUDIT (every
    incremental rescore cross-checked against a full score_stream
    pass, raising on ANY divergence), then the same state rescored
    with the cache dropped: the two paths must be bit-equal — same
    ints, same floats, not approx."""
    monkeypatch.setenv("SHEEP_SCORE_AUDIT", "1")
    e = _graph(4000)
    base = _base_file(tmp_path, e[:2000])
    be = get_backend(backend, chunk_edges=512)
    state, _ = inc.begin_incremental(
        open_input(base, n_vertices=N), [4, 8], backend=be)
    inc.refresh(be, state)  # the one-time full pass seeds the index
    assert state.stats.get("score_full", 0) >= 1
    rng = np.random.default_rng(11)
    adds1 = rng.integers(0, N, (700, 2)).astype(np.int64)
    adds1[:6] = adds1[6:12]        # duplicate adds
    adds1[20, 1] = adds1[20, 0]    # self-loop
    be.partition_update(state, adds=adds1, score=True)
    dels = np.concatenate([
        e[100:160], e[100:110],    # base hits + duplicated deletes
        adds1[:30],                # cancel pending adds
        np.array([[0, 0]], np.int64),            # self-loop delete
        rng.integers(0, N, (20, 2)),             # mostly unmatched
    ]).astype(np.int64)
    be.partition_update(state, deletes=dels, score=True,
                        compact="never")
    be.partition_update(
        state, adds=rng.integers(0, N, (400, 2)).astype(np.int64),
        score=True, compact="force")  # compaction, then more churn
    res_inc = be.partition_update(
        state, adds=rng.integers(0, N, (200, 2)).astype(np.int64),
        score=True)
    assert state.stats.get("score_incremental", 0) >= 3
    state._score = None  # drop the cache: force the full path
    res_full = inc.refresh(be, state)
    for a, b in zip(res_inc, res_full):
        assert a.k == b.k
        assert a.edge_cut == b.edge_cut
        assert a.total_edges == b.total_edges
        assert a.balance == b.balance
        assert a.cut_ratio == b.cut_ratio
        np.testing.assert_array_equal(a.assignment, b.assignment)


def test_audit_catches_a_poisoned_cache(tmp_path, monkeypatch):
    """Negative control: the audit must actually RUN and raise — a
    deliberately corrupted cut accumulator cannot survive a scored
    refresh under SHEEP_SCORE_AUDIT."""
    e = _graph(2000)
    base = _base_file(tmp_path, e)
    be = get_backend("tpu", chunk_edges=512)
    state, _ = inc.begin_incremental(
        open_input(base, n_vertices=N), 4, backend=be)
    inc.refresh(be, state)
    assert state._score is not None and "prev" in state._score
    state._score["cut"][4] += 1  # sabotage
    monkeypatch.setenv("SHEEP_SCORE_AUDIT", "1")
    with pytest.raises(RuntimeError, match="SHEEP_SCORE_AUDIT"):
        inc.refresh(be, state)


def test_comm_volume_requests_run_the_full_pass(tmp_path):
    """comm_volume needs per-part neighbor sets the O(Δ) accumulators
    don't carry: such a refresh takes the full pass (and re-seeds the
    cache) instead of silently answering without the volume."""
    e = _graph(1500)
    base = _base_file(tmp_path, e)
    be = get_backend("tpu", chunk_edges=512)
    state, _ = inc.begin_incremental(
        open_input(base, n_vertices=N), 4, backend=be)
    inc.refresh(be, state)
    f0 = state.stats["score_full"]
    r = inc.refresh(be, state, comm_volume=True)
    assert state.stats["score_full"] == f0 + 1
    assert r.comm_volume is not None
    # and the cache is re-seeded: the next plain refresh is O(Δ)
    i0 = state.stats.get("score_incremental", 0)
    inc.refresh(be, state)
    assert state.stats["score_incremental"] == i0 + 1


# ----------------------------------------------------------------------
# log compaction (ISSUE 17): DeltaLogWriter.rewrite_base
# ----------------------------------------------------------------------
class TestRewriteBase:
    def test_round_trip_floor_and_epoch_continuation(self, tmp_path):
        e = _graph(1200)
        base = _base_file(tmp_path, e[:600])
        log = str(tmp_path / "g.dlog")
        with dl.DeltaLogWriter(log, base_spec=base) as w:
            w.append(e[600:900])
            w.append_epoch(dels=e[:100])
        with open_input(f"delta:{log}", n_vertices=N) as es:
            before = np.sort(es.read_all().view("i8,i8"), axis=0)
        nb = str(tmp_path / "rebased.csr")
        with dl.DeltaLogWriter(log) as w:
            w.rewrite_base(nb, n_vertices=N)
            assert (w.base_spec, w.epoch_floor, w.last_epoch) \
                == (nb, 2, 2)
            w.append(e[900:1000])  # epochs continue PAST the floor
            assert w.last_epoch == 3
        hdr = dl.read_header(log)
        assert hdr["version"] == 2
        assert hdr["epoch_floor"] == 2
        assert hdr["base_spec"] == nb
        # the surviving multiset is preserved exactly
        with open_input(f"delta:{log}", n_vertices=N) as es:
            after = np.sort(es.read_all().view("i8,i8"), axis=0)
        want = np.sort(np.concatenate(
            [before.view(np.int64).reshape(-1, 2),
             e[900:1000]]).view("i8,i8"), axis=0)
        assert np.array_equal(after, want)
        # readers respect the floor
        r = dl.DeltaLogReader(log)
        assert r.max_epoch == 3
        assert [ep for ep, _, _ in r.epochs(start_epoch=2)] == [3]
        with pytest.raises(ValueError, match="compaction floor"):
            dl.DeltaLogStream(log, up_to=1)
        # a reopened writer resumes past the floor, not at 0
        with dl.DeltaLogWriter(log) as w2:
            assert (w2.last_epoch, w2.epoch_floor) == (3, 2)
        # and a build over the rewritten log still works end to end
        # (total_edges counts VALID edges: self-loops score nothing)
        be = get_backend("tpu", chunk_edges=512)
        res = be.partition(open_input(f"delta:{log}", n_vertices=N),
                           4, comm_volume=False)
        aa = after.view(np.int64).reshape(-1, 2)
        assert res.total_edges \
            == int(np.count_nonzero(aa[:, 0] != aa[:, 1]))

    def test_rewrite_equals_filtered_multiset(self, tmp_path):
        """The rewritten base holds exactly filter_tombstones' answer
        — matched tombstones remove ONE occurrence, unmatched remove
        nothing — so duplicate and unmatched deletes round-trip."""
        e = _graph(800)
        dup = np.concatenate([e, e[:50]])  # duplicated edges
        base = _base_file(tmp_path, dup)
        log = str(tmp_path / "g.dlog")
        dels = np.concatenate([e[:60], e[:10],  # 10 doubled deletes
                               np.array([[N - 1, N - 1]], np.int64)])
        with dl.DeltaLogWriter(log, base_spec=base) as w:
            w.append_epoch(dels=dels)
        nb = str(tmp_path / "rb.csr")
        with dl.DeltaLogWriter(log) as w:
            w.rewrite_base(nb, n_vertices=N)
        with open_input(f"delta:{log}", n_vertices=N) as es:
            got = np.sort(es.read_all().view("i8,i8"), axis=0)
        surv = np.concatenate(list(dl.filter_tombstones([dup], dels)))
        want = np.sort(surv.view("i8,i8"), axis=0)
        assert np.array_equal(got, want)

    def test_leftover_rewrite_tmp_is_harmless(self, tmp_path):
        """A crash BEFORE the header os.replace leaves `.rewrite.tmp`
        beside an untouched v1 log: readers and writers ignore it."""
        e = _graph(300)
        base = _base_file(tmp_path, e)
        log = str(tmp_path / "g.dlog")
        with dl.DeltaLogWriter(log, base_spec=base) as w:
            w.append(e[:20])
        with open(log + ".rewrite.tmp", "wb") as f:
            f.write(b"torn header bytes")
        r = dl.DeltaLogReader(log)
        assert r.max_epoch == 1
        assert r.header["epoch_floor"] == 0
        with dl.DeltaLogWriter(log) as w2:
            w2.append(e[20:40])
            assert w2.last_epoch == 2


# ----------------------------------------------------------------------
# streaming delta framing (ISSUE 17): chunked update wire form
# ----------------------------------------------------------------------
def _start_daemon(tmp_path, *extra):
    import time

    from sheep_tpu.server.daemon import Daemon, build_parser

    sock = str(tmp_path / "d.sock")
    d = Daemon(build_parser().parse_args(["--socket", sock,
                                          *extra]))
    t = threading.Thread(target=d.serve, daemon=True)
    t.start()
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        if os.path.exists(sock) and d.scheduler is not None:
            return d, t, sock
        time.sleep(0.05)
    raise AssertionError("daemon never bound")


def test_chunked_update_applies_one_epoch_and_torn_stream_is_noop(
        tmp_path):
    import json
    import socket as socket_mod

    from sheep_tpu.server import protocol
    from sheep_tpu.server.client import SheepClient

    e = _graph(3000)
    base = _base_file(tmp_path, e[:1500])
    d, t, sock = _start_daemon(tmp_path)
    with SheepClient(sock, timeout_s=120) as c:
        jid = c.submit(base, k=[4], tenant="inc", resident=True,
                       chunk_edges=512, num_vertices=N)["job_id"]
        assert c.wait(jid, timeout_s=120)["state"] == "done"
        # tiny chunk_edges forces the chunked form: 1500 edges stream
        # as 6 chunks, fold + score as ONE epoch at commit
        r = c.update(jid, adds=e[1500:], epoch=1, score=True,
                     chunk_edges=256)
        assert r["applied"] and r["epoch"] == 1 and r["txn"]
        assert r["epochs_applied"] == 1
        # ...bit-identical to the one-shot build of the same delta
        log = str(tmp_path / "g.dlog")
        with dl.DeltaLogWriter(log, base_spec=base) as w:
            w.append(e[1500:])
        one = get_backend("tpu", chunk_edges=512).partition(
            open_input(f"delta:{log}", n_vertices=N), 4,
            comm_volume=False)
        assert r["results"][0]["edge_cut"] == one.edge_cut
        # idempotent chunked replay of an applied epoch
        assert c.update(jid, adds=e[1500:], epoch=1,
                        chunk_edges=256)["applied"] is False
        # torn stream: begin + one chunk on a RAW connection, then
        # the client dies with no commit — the resident must stay at
        # its prior epoch, nothing staged survives the connection
        s = socket_mod.socket(socket_mod.AF_UNIX)
        s.connect(sock)
        rf = s.makefile("rb")
        s.sendall(protocol.dumps({"op": "update", "job_id": jid,
                                  "stream": "begin"}))
        txn = json.loads(rf.readline())["txn"]
        s.sendall(protocol.dumps({
            "op": "update", "stream": "chunk", "txn": txn,
            "adds": protocol.encode_edges(e[:200])}))
        assert json.loads(rf.readline())["adds"] == 200
        rf.close()
        s.close()  # torn: no commit ever sent
        assert c.epoch(jid)["epoch"] == 1
        # transactions are connection-scoped: the dead txn cannot be
        # committed from anywhere else
        from sheep_tpu.server.client import ServerError

        with pytest.raises(ServerError, match="unknown update txn"):
            c.request({"op": "update", "stream": "commit",
                       "txn": txn, "epoch": 2})
        with pytest.raises(ServerError, match="stream must be one"):
            c.request({"op": "update", "stream": "flush",
                       "job_id": jid})
        with pytest.raises(ServerError, match="begin needs job_id"):
            c.request({"op": "update", "stream": "begin"})
        # abort discards explicitly
        txn2 = c.request({"op": "update", "job_id": jid,
                          "stream": "begin"})["txn"]
        assert c.request({"op": "update", "stream": "abort",
                          "txn": txn2})["aborted"] is True
        # ...and the torn/aborted chunks changed nothing: the whole
        # epoch 2 retries cleanly from scratch
        r2 = c.update(jid, adds=e[:400], epoch=2, chunk_edges=128)
        assert r2["applied"] and r2["epoch"] == 2
        c.shutdown()
    t.join(timeout=60)
    assert not t.is_alive()


def test_chunked_update_txn_byte_cap(tmp_path, monkeypatch):
    from sheep_tpu.server import protocol
    from sheep_tpu.server.client import ServerError, SheepClient

    e = _graph(1000)
    base = _base_file(tmp_path, e[:500])
    monkeypatch.setattr(protocol, "MAX_UPDATE_TXN_BYTES", 2048)
    d, t, sock = _start_daemon(tmp_path)
    with SheepClient(sock, timeout_s=120) as c:
        jid = c.submit(base, k=[4], tenant="inc", resident=True,
                       chunk_edges=512, num_vertices=N)["job_id"]
        assert c.wait(jid, timeout_s=120)["state"] == "done"
        txn = c.request({"op": "update", "job_id": jid,
                         "stream": "begin"})["txn"]
        with pytest.raises(ServerError, match="staged bytes"):
            c.request({"op": "update", "stream": "chunk",
                       "txn": txn,
                       "adds": protocol.encode_edges(e[:200])})
        # the oversized txn was aborted server-side
        with pytest.raises(ServerError, match="unknown update txn"):
            c.request({"op": "update", "stream": "commit",
                       "txn": txn, "epoch": 1})
        assert c.epoch(jid)["epoch"] == 0
        c.shutdown()
    t.join(timeout=60)


# ----------------------------------------------------------------------
# per-tenant fairness (ISSUE 17): byte budgets in the update drain
# ----------------------------------------------------------------------
def test_update_byte_budget_defers_backlog_and_counts(tmp_path,
                                                      monkeypatch):
    """White-box drain-cycle semantics: with a byte budget armed, one
    _service_updates cycle admits a tenant's items only up to the
    budget, DEFERS the rest (counted in
    sheepd_update_throttled_total), and the deferred items complete
    in later cycles because budgets reset per cycle."""
    import time

    from sheep_tpu.server.scheduler import Scheduler

    # 1000-edge epochs are 16000 bytes: the first admitted item
    # exhausts this budget for the cycle
    monkeypatch.setenv("SHEEP_UPDATE_BYTES_PER_CYCLE", "4096")
    e = _graph(3000)
    base = _base_file(tmp_path, e[:1500])
    sched = Scheduler()
    job = sched.submit(_spec(base))
    with sched._lock:
        sched._admit_locked()
    for _ in range(20000):  # drive the build inline: no dispatch
        if job.state != "running":  # thread exists to race the drain
            break
        sched._step(job)
    assert job.state == "done", (job.state, job.error)
    results = []

    def push(ep):
        results.append(sched.update(job.id, adds=e[1500:2500],
                                    epoch=ep, timeout_s=120))

    ths = [threading.Thread(target=push, args=(ep,), daemon=True)
           for ep in (1, 2, 3)]
    for th in ths:
        th.start()
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        with sched._lock:
            if len(sched._updates) == 3:
                break
        time.sleep(0.01)
    with sched._lock:
        assert len(sched._updates) == 3
    sched._service_updates()  # cycle 1: budget admits exactly one
    with sched._lock:
        left = len(sched._updates)
    assert left == 2
    assert 'sheepd_update_throttled_total{tenant="inc"} 2' \
        in sched.render_metrics()
    for _ in range(4):  # later cycles drain the rest (budget resets)
        sched._service_updates()
    with sched._lock:
        assert not sched._updates
    for th in ths:
        th.join(timeout=120)
    assert len(results) == 3
    assert job.resident_state.epoch == 3
