"""Incremental repartitioning tests (ISSUE 15).

The acceptance pins:

- **Adds are exact**: the shuffled two-halves replay — build half the
  stream, fold the other half as delta epochs — is BIT-IDENTICAL to a
  one-shot build of the ``delta:`` input on the pure/cpu/tpu backends,
  through the CLI ``--deltas`` replay, and through the served
  ``update`` verb (the anchored-order contract + fixpoint uniqueness,
  sheep_tpu/incremental.py module docstring).
- **Delete + full compaction** matches a clean rebuild of the
  surviving edges bit-identically (full compaction IS a clean rebuild
  of the survivor stream, re-anchored); **subtree compaction** ships
  with a tested score bound instead.
- **Anchored-order drift** is score-bounded against the fresh-order
  one-shot build (the quality gate's dynamic scenario enforces the
  same bound in CI).
- The delta-log format survives damage under the SHEEP_IO_POLICY
  contract (tests/test_edgestream.py TestDeltaLogDamage) and a
  resident served partition survives kill + restart at its journaled
  epoch (tests/test_journal.py drill).
"""

import os
import threading

import numpy as np
import pytest

import sheep_tpu
from sheep_tpu import incremental as inc
from sheep_tpu.backends.base import get_backend, list_backends
from sheep_tpu.io import deltalog as dl
from sheep_tpu.io.edgestream import EdgeStream, open_input

N = 512
SEED = 5


def _graph(m=4000, n=N, seed=SEED):
    rng = np.random.default_rng(seed)
    return rng.integers(0, n, (m, 2)).astype(np.int64)


def _base_file(tmp_path, edges, name="base.bin64"):
    p = str(tmp_path / name)
    with open(p, "wb") as f:
        f.write(np.asarray(edges, np.int64).astype("<u8").tobytes())
    return p


def _backends():
    avail = list_backends()
    return [b for b in ("pure", "cpu", "tpu") if b in avail]


# ----------------------------------------------------------------------
# delta-log format
# ----------------------------------------------------------------------
class TestDeltaLog:
    def test_header_round_trip(self, tmp_path):
        log = str(tmp_path / "g.dlog")
        dl.write_header(log, "base.bin64")
        hdr = dl.read_header(log)
        assert hdr["base_spec"] == "base.bin64"
        assert hdr["version"] == dl.VERSION

    def test_not_a_delta_log(self, tmp_path):
        p = str(tmp_path / "junk")
        with open(p, "wb") as f:
            f.write(b"not a log at all")
        with pytest.raises(ValueError, match="bad magic"):
            dl.read_header(p)

    def test_writer_epochs_and_reopen(self, tmp_path):
        log = str(tmp_path / "g.dlog")
        e = _graph(64)
        with dl.DeltaLogWriter(log, base_spec="b") as w:
            assert w.append(e[:10]) == 1
            assert w.append(e[10:20], op=dl.OP_DEL, epoch=1) == 1
            assert w.append(e[20:30]) == 2
        with dl.DeltaLogWriter(log) as w2:  # reopen: no base_spec
            assert w2.last_epoch == 2
            assert w2.append_epoch(adds=e[30:40], dels=e[40:45]) == 3
        r = dl.DeltaLogReader(log)
        eps = list(r.epochs())
        assert [ep for ep, _, _ in eps] == [1, 2, 3]
        ep1_adds, ep1_dels = eps[0][1], eps[0][2]
        assert np.array_equal(ep1_adds, e[:10])
        assert np.array_equal(ep1_dels, e[10:20])
        assert r.max_epoch == 3
        # start/up_to windows
        assert [ep for ep, _, _ in r.epochs(start_epoch=2)] == [3]
        r2 = dl.DeltaLogReader(log)
        assert [ep for ep, _, _ in r2.epochs(up_to=2)] == [1, 2]

    def test_writer_validation(self, tmp_path):
        log = str(tmp_path / "g.dlog")
        with pytest.raises(ValueError, match="base_spec"):
            dl.DeltaLogWriter(log)
        with dl.DeltaLogWriter(log, base_spec="b") as w:
            with pytest.raises(ValueError, match="bad delta op"):
                w.append(_graph(4), op=9)
            with pytest.raises(ValueError, match="non-negative"):
                w.append(np.array([[-1, 2]]))
            w.append(_graph(4), epoch=5)
            with pytest.raises(ValueError, match="never rewind"):
                w.append(_graph(4), epoch=4)
            with pytest.raises(ValueError,
                               match="logs deltas over"):
                dl.DeltaLogWriter(log, base_spec="other")

    def test_net_effect_cancels_adds_then_tombstones_base(self):
        adds = np.array([[1, 2], [3, 4], [2, 1]], np.int64)
        rec = np.zeros(5, dtype=dl.RECORD_DTYPE)
        rec["u"][:3] = adds[:, 0]
        rec["v"][:3] = adds[:, 1]
        rec["epoch"] = 1
        # two DELs of {1,2}: one cancels an add (undirected match),
        # one tombstones the base; one DEL of {7,8} tombstones base
        rec["u"][3:] = [2, 7]
        rec["v"][3:] = [1, 8]
        rec["op"][3:] = dl.OP_DEL
        surv, tombs = dl.net_effect(rec)
        keys = {tuple(r) for r in surv.tolist()}
        assert keys == {(1, 2), (3, 4)}  # one {1,2} copy cancelled
        assert sorted(map(tuple, tombs.tolist())) == [(7, 8)]

    def test_del_never_cancels_a_later_add(self, tmp_path):
        """In-order resolution: deleting an edge the graph does not
        have removes nothing — it must NOT reach forward and erase an
        add from a later epoch, on either the one-shot or the
        incremental path (they'd diverge otherwise)."""
        e = _graph(600)
        base = _base_file(tmp_path, e[:300])
        absent = np.array([[N - 1, N - 2]], np.int64)
        assert not any(tuple(sorted(r)) == (N - 2, N - 1)
                       for r in e[:300].tolist())
        log = str(tmp_path / "g.dlog")
        with dl.DeltaLogWriter(log, base_spec=base) as w:
            w.append_epoch(dels=absent)       # epoch 1: no-op delete
            w.append(absent)                  # epoch 2: ADD it
            w.append(e[300:])                 # epoch 3
        st = open_input(f"delta:{log}", n_vertices=N)
        keys = [tuple(sorted(r)) for r in st.read_all().tolist()]
        assert keys.count((N - 2, N - 1)) == 1  # the add SURVIVES
        # and the incremental replay lands bit-identical
        be = get_backend("tpu", chunk_edges=777)
        one = be.partition(open_input(f"delta:{log}", n_vertices=N),
                           4, comm_volume=False)
        state, _ = inc.begin_incremental(
            open_input(base, n_vertices=N), 4, backend=be)
        be.partition_update(state, deletes=absent, epoch=1,
                            score=False, compact="never")
        be.partition_update(state, adds=absent, epoch=2, score=False)
        r = be.partition_update(state, adds=e[300:], epoch=3,
                                score=True)
        assert np.array_equal(r.assignment, one.assignment)
        assert (r.edge_cut, r.total_edges) == (one.edge_cut,
                                               one.total_edges)

    def test_filter_tombstones_multiset(self):
        chunks = [np.array([[1, 2], [3, 4]], np.int64),
                  np.array([[2, 1], [5, 6]], np.int64)]
        out = list(dl.filter_tombstones(chunks,
                                        np.array([[1, 2]], np.int64)))
        flat = np.concatenate(out)
        # exactly ONE {1,2} occurrence removed
        assert len(flat) == 3
        assert sum(1 for r in flat.tolist()
                   if tuple(sorted(r)) == (1, 2)) == 1

    def test_delta_spec_parsing(self, tmp_path):
        e = _graph()
        base = _base_file(tmp_path, e[:2000])
        log = str(tmp_path / "g.dlog")
        with dl.DeltaLogWriter(log, base_spec=base) as w:
            w.append(e[2000:3000])
            w.append(e[3000:])
        st = open_input(f"delta:{log}")
        assert st.epoch == 2
        assert len(st.read_all()) == len(e)
        capped = open_input(f"delta:{log}@1")
        assert capped.epoch == 1
        assert len(capped.read_all()) == 3000
        with pytest.raises(ValueError, match="does not exist"):
            open_input(f"delta:{tmp_path}/nope.dlog")
        with pytest.raises(ValueError, match="below the"):
            open_input(f"delta:{log}", n_vertices=4)
        with pytest.raises(NotImplementedError):
            list(st.chunks(64, shard=0, num_shards=2))

    def test_delta_logs_do_not_nest(self, tmp_path):
        e = _graph(100)
        base = _base_file(tmp_path, e)
        inner = str(tmp_path / "inner.dlog")
        with dl.DeltaLogWriter(inner, base_spec=base) as w:
            w.append(e[:10])
        outer = str(tmp_path / "outer.dlog")
        dl.write_header(outer, f"delta:{inner}")
        with pytest.raises(ValueError, match="do not nest"):
            open_input(f"delta:{outer}")


# ----------------------------------------------------------------------
# the exactness contract: adds == one-shot, per backend
# ----------------------------------------------------------------------
@pytest.mark.parametrize("backend", _backends())
def test_two_halves_replay_bit_identical(tmp_path, backend):
    e = _graph()
    half = len(e) // 2
    base = _base_file(tmp_path, e[:half])
    log = str(tmp_path / "g.dlog")
    with dl.DeltaLogWriter(log, base_spec=base) as w:
        w.append(e[half: half + 1000])
        w.append(e[half + 1000:])
    be = get_backend(backend, chunk_edges=777)
    one = be.partition(open_input(f"delta:{log}", n_vertices=N), 8,
                       comm_volume=False)
    state, res0 = inc.begin_incremental(
        open_input(base, n_vertices=N), 8, backend=be)
    r1 = be.partition_update(state, adds=e[half: half + 1000],
                             score=False)
    assert r1 is None  # score=False returns nothing, folds silently
    r2 = be.partition_update(state, adds=e[half + 1000:], score=True)
    assert state.epoch == 2
    assert np.array_equal(r2.assignment, one.assignment)
    assert (r2.edge_cut, r2.total_edges) == (one.edge_cut,
                                             one.total_edges)
    assert r2.balance == pytest.approx(one.balance)
    assert r2.diagnostics["epoch"] == 2.0


def test_incremental_state_round_trips_through_snapshot(tmp_path):
    e = _graph()
    half = len(e) // 2
    base = _base_file(tmp_path, e[:half])
    be = get_backend("tpu", chunk_edges=777)
    state, _ = inc.begin_incremental(
        open_input(base, n_vertices=N), 8, backend=be)
    be.partition_update(state, adds=e[half:-500], score=False)
    be.partition_update(state, deletes=e[:100], score=False,
                        compact="never")
    path = str(tmp_path / "st.npz")
    inc.save_state(state, path)
    loaded = inc.load_state(path)
    assert loaded.epoch == state.epoch
    assert np.array_equal(loaded.minp, state.minp)
    # the reloaded state continues BIT-identically
    ra = be.partition_update(state, adds=e[-500:], score=True,
                             compact="never")
    rb = be.partition_update(loaded, adds=e[-500:], score=True,
                             compact="never")
    assert np.array_equal(ra.assignment, rb.assignment)
    assert ra.edge_cut == rb.edge_cut


def test_epoch_idempotency_and_vertex_space_guard(tmp_path):
    e = _graph()
    base = _base_file(tmp_path, e[:2000])
    be = get_backend("tpu", chunk_edges=777)
    state, _ = inc.begin_incremental(
        open_input(base, n_vertices=N), 4, backend=be)
    assert be.partition_update(state, adds=e[2000:2100],
                               epoch=1) is not None
    # replaying an applied epoch is a silent no-op (the served retry
    # contract)
    assert be.partition_update(state, adds=e[2000:2100],
                               epoch=1) is None
    assert state.epoch == 1
    with pytest.raises(ValueError, match="outside the resident"):
        be.partition_update(state, adds=np.array([[0, N + 7]]))
    with pytest.raises(ValueError, match="bad compact mode"):
        be.partition_update(state, adds=e[:4], compact="later")


def test_unsupported_backends_reject_incremental_and_delta(tmp_path):
    e = _graph(200)
    base = _base_file(tmp_path, e)
    log = str(tmp_path / "g.dlog")
    with dl.DeltaLogWriter(log, base_spec=base) as w:
        w.append(e[:10])
    from sheep_tpu.types import UnsupportedGraphError

    for name in ("tpu-sharded", "tpu-bigv"):
        if name not in list_backends():
            continue
        be = get_backend(name)
        with pytest.raises(ValueError,
                           match="does not support incremental"):
            be.partition_update(None, adds=e[:2])
        with pytest.raises(UnsupportedGraphError,
                           match="single-device"):
            be.partition(open_input(f"delta:{log}", n_vertices=N), 4)


# ----------------------------------------------------------------------
# deletions: tombstones, compaction, staleness
# ----------------------------------------------------------------------
def test_delete_full_compact_matches_clean_rebuild(tmp_path):
    e = _graph()
    base = _base_file(tmp_path, e[:2000])
    be = get_backend("tpu", chunk_edges=777)
    state, _ = inc.begin_incremental(
        open_input(base, n_vertices=N), 8, backend=be)
    be.partition_update(state, adds=e[2000:], score=False)
    dels = e[np.random.default_rng(9).permutation(len(e))[:600]]
    r_stale = be.partition_update(state, deletes=dels, score=True,
                                  compact="never")
    assert state.stale_deletes == 600
    mode = inc.compact_state(be, state, mode="full")
    assert mode == "full"
    assert state.stale_deletes == 0
    assert state.anchored_at_epoch == state.epoch
    r = inc.refresh(be, state)
    surv = np.concatenate(list(dl.filter_tombstones([e], dels)))
    clean = be.partition(EdgeStream.from_array(surv, n_vertices=N), 8,
                         comm_volume=False)
    assert np.array_equal(r.assignment, clean.assignment)
    assert (r.edge_cut, r.total_edges) == (clean.edge_cut,
                                           clean.total_edges)
    # the stale pre-compact score already counted the right multiset
    assert r_stale.total_edges == clean.total_edges


def test_subtree_compact_is_local_and_score_bounded(tmp_path):
    e = _graph(6000)
    base = _base_file(tmp_path, e[:3000])
    be = get_backend("tpu", chunk_edges=777)
    state, _ = inc.begin_incremental(
        open_input(base, n_vertices=N), 8, backend=be)
    be.partition_update(state, adds=e[3000:], score=False)
    # a few localized deletes: the dirty set stays small
    dels = e[:30]
    be.partition_update(state, deletes=dels, score=False,
                        compact="never")
    mode = inc.compact_state(be, state, mode="subtree")
    assert mode == "subtree"
    assert state.stats["compact_subtree"] == 1
    # locality: the refold touched a subset, not the whole stream
    assert 0 < state.stats["compact_refolded_edges"] < len(e)
    r = inc.refresh(be, state)
    surv = np.concatenate(list(dl.filter_tombstones([e], dels)))
    clean = be.partition(EdgeStream.from_array(surv, n_vertices=N), 8,
                         comm_volume=False)
    assert r.total_edges == clean.total_edges
    # the explicit, tested score bound of the approximate mode
    assert r.cut_ratio <= clean.cut_ratio + 0.05


def test_staleness_counter_forces_compaction(tmp_path):
    e = _graph()
    base = _base_file(tmp_path, e)
    be = get_backend("tpu", chunk_edges=777)
    state, _ = inc.begin_incremental(
        open_input(base, n_vertices=N), 8, backend=be)
    state.compact_threshold = 50
    be.partition_update(state, deletes=e[:40], score=False)
    assert state.compactions == 0  # under threshold: tombstones ride
    be.partition_update(state, deletes=e[40:100], score=False)
    assert state.compactions == 1  # past threshold: forced
    assert state.stale_deletes == 0


def test_compact_noop_when_nothing_changed(tmp_path):
    e = _graph(1000)
    base = _base_file(tmp_path, e)
    be = get_backend("tpu", chunk_edges=777)
    state, _ = inc.begin_incremental(
        open_input(base, n_vertices=N), 4, backend=be)
    assert inc.compact_state(be, state, mode="auto") == "noop"
    assert state.compactions == 0


def test_anchored_drift_is_score_bounded():
    """The order-anchoring cost on a structured graph stays inside the
    bound the quality gate's dynamic scenario enforces in CI."""
    with open_input("sbm-hash:9:8:0.05:16:3") as es:
        edges = es.read_all()
        n = es.num_vertices
    rng = np.random.default_rng(7)
    e = edges[rng.permutation(len(edges))]
    half = len(e) // 2
    be = get_backend("tpu", chunk_edges=1 << 12)
    state, _ = inc.begin_incremental(
        EdgeStream.from_array(e[:half], n_vertices=n), 8, backend=be)
    res = be.partition_update(state, adds=e[half:], score=True)
    oneshot = be.partition(EdgeStream.from_array(e, n_vertices=n), 8,
                           comm_volume=False)
    assert res.total_edges == oneshot.total_edges
    assert res.cut_ratio <= oneshot.cut_ratio + 0.05


# ----------------------------------------------------------------------
# CLI: --deltas replay and validation
# ----------------------------------------------------------------------
def test_cli_deltas_replay_matches_one_shot(tmp_path, capsys):
    import json

    from sheep_tpu import cli

    e = _graph()
    half = len(e) // 2
    base = _base_file(tmp_path, e[:half])
    log = str(tmp_path / "g.dlog")
    with dl.DeltaLogWriter(log, base_spec=base) as w:
        w.append(e[half:-700])
        w.append(e[-700:])
    rc = cli.main(["--input", base, "--k", "4", "--backend", "tpu",
                   "--num-vertices", str(N), "--chunk-edges", "777",
                   "--deltas", log, "--json"])
    assert rc == 0
    incr = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    rc = cli.main(["--input", f"delta:{log}", "--k", "4",
                   "--backend", "tpu", "--num-vertices", str(N),
                   "--chunk-edges", "777", "--json"])
    assert rc == 0
    one = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert incr["edge_cut"] == one["edge_cut"]
    assert incr["total_edges"] == one["total_edges"]
    assert incr["diagnostics"]["epoch"] == 2.0


def test_cli_deltas_validation(tmp_path):
    from sheep_tpu import cli

    e = _graph(100)
    base = _base_file(tmp_path, e)
    log = str(tmp_path / "g.dlog")
    with dl.DeltaLogWriter(log, base_spec=base) as w:
        w.append(e[:10])
    for extra in (["--refine", "2"], ["--k", "4,8"],
                  ["--checkpoint-dir", str(tmp_path / "ck")],
                  ["--auto-recipe"]):
        argv = ["--input", base, "--k", "4", "--deltas", log] + extra
        if extra == ["--k", "4,8"]:
            argv = ["--input", base, "--deltas", log] + extra
        with pytest.raises(SystemExit):
            cli.main(argv)
    with pytest.raises(SystemExit):
        cli.main(["--input", base, "--k-levels", "2,2",
                  "--deltas", log])
    with pytest.raises(SystemExit):
        cli.main(["--input", base, "--k", "4", "--deltas",
                  str(tmp_path / "missing.dlog")])


# ----------------------------------------------------------------------
# served surface: resident partitions, update/epoch/compact verbs
# ----------------------------------------------------------------------
def _spec(input, n=N, ks=(4,), resident=True, **fields):
    from sheep_tpu.server.protocol import JobSpec

    body = {"input": input, "k": list(ks), "chunk_edges": 512,
            "num_vertices": n, "resident": resident}
    body.update(fields)
    return JobSpec.from_request(body, tenant="inc")


def _run_scheduler(**kw):
    from sheep_tpu.server.scheduler import Scheduler

    sched = Scheduler(**kw)
    t = threading.Thread(target=sched.run, daemon=True)
    t.start()
    return sched, t


def test_served_update_verb_bit_identical(tmp_path):
    e = _graph(3000)
    base = _base_file(tmp_path, e[:1500])
    sched, t = _run_scheduler()
    try:
        job = sched.submit(_spec(base))
        assert sched.wait(job.id, timeout_s=120).state == "done"
        assert sched.stats()["resident_partitions"] == 1
        r1 = sched.update(job.id, adds=e[1500:2200], epoch=1)
        assert r1["applied"] and r1["epoch"] == 1
        r2 = sched.update(job.id, adds=e[2200:], epoch=2, score=True)
        assert r2["epoch"] == 2
        # idempotent replay answers applied=false
        r1b = sched.update(job.id, adds=e[1500:2200], epoch=1)
        assert r1b["applied"] is False
        info = sched.epoch_info(job.id)
        assert info["epoch"] == 2
        assert 0 < info["total_edges"] <= len(e)
        # the served result bit-equals the one-shot delta: build
        log = str(tmp_path / "g.dlog")
        with dl.DeltaLogWriter(log, base_spec=base) as w:
            w.append(e[1500:2200])
            w.append(e[2200:])
        be = get_backend("tpu", chunk_edges=512)
        one = be.partition(open_input(f"delta:{log}", n_vertices=N),
                           4, comm_volume=False)
        assert np.array_equal(job.results[0].assignment,
                              one.assignment)
        assert r2["results"][0]["edge_cut"] == one.edge_cut
        # metrics joined the catalog
        text = sched.render_metrics()
        assert 'sheep_updates_total{tenant="inc"} 2' in text
        assert "sheep_update_latency_seconds_bucket" in text
        # cancel releases the residency + its reservation
        sched.cancel(job.id)
        assert sched.stats()["resident_partitions"] == 0
        with pytest.raises(Exception, match="released"):
            sched.epoch_info(job.id)
    finally:
        sched.shutdown()
        t.join(timeout=60)


def test_served_update_log_form_and_deletes(tmp_path):
    e = _graph(3000)
    base = _base_file(tmp_path, e[:1500])
    log = str(tmp_path / "g.dlog")
    with dl.DeltaLogWriter(log, base_spec=base) as w:
        w.append(e[1500:])
        w.append_epoch(dels=e[:50])
    sched, t = _run_scheduler()
    try:
        job = sched.submit(_spec(base))
        assert sched.wait(job.id, timeout_s=120).state == "done"
        r = sched.update(job.id, log=log, score=True)
        assert r["epochs_applied"] == 2 and r["epoch"] == 2
        assert r["stale_deletes"] == 50
        c = sched.compact_resident(job.id, mode="full", score=True)
        assert c["mode"] == "full" and c["compactions"] == 1
        surv = np.concatenate(list(dl.filter_tombstones([e], e[:50])))
        be = get_backend("tpu", chunk_edges=512)
        clean = be.partition(EdgeStream.from_array(surv, n_vertices=N),
                             4, comm_volume=False)
        assert c["results"][0]["edge_cut"] == clean.edge_cut
        assert np.array_equal(job.results[0].assignment,
                              clean.assignment)
    finally:
        sched.shutdown()
        t.join(timeout=60)


def test_served_update_rejects_non_resident_and_unknown(tmp_path):
    from sheep_tpu.server import protocol

    e = _graph(500)
    base = _base_file(tmp_path, e)
    sched, t = _run_scheduler()
    try:
        job = sched.submit(_spec(base, resident=False))
        assert sched.wait(job.id, timeout_s=120).state == "done"
        with pytest.raises(protocol.ProtocolError,
                           match="not submitted resident"):
            sched.update(job.id, adds=e[:4], epoch=1)
        with pytest.raises(protocol.ProtocolError, match="unknown"):
            sched.epoch_info("j999")
    finally:
        sched.shutdown()
        t.join(timeout=60)


def test_resident_reservation_charges_admission(tmp_path):
    """A held resident partition keeps its modeled bytes reserved, so
    headroom-short jobs queue behind it and releasing it admits them
    (the membudget charge of ISSUE 15 (c))."""
    e = _graph(1000)
    base = _base_file(tmp_path, e)
    sched, t = _run_scheduler()
    try:
        job = sched.submit(_spec(base))
        assert sched.wait(job.id, timeout_s=120).state == "done"
        with sched._lock:
            reserved = sched._reserved_locked()
        assert reserved == (job.modeled_bytes or 0)
        # shrink the budget so the next identical job cannot fit
        # beside the resident reservation: it must QUEUE
        if job.modeled_bytes:
            sched.budget = int(job.modeled_bytes * 1.5)
            j2 = sched.submit(_spec(base, resident=False))
            import time as _t

            _t.sleep(0.3)
            assert sched.get(j2.id).state == "queued"
            sched.cancel(job.id)  # release the residency
            assert sched.wait(j2.id, timeout_s=120).state == "done"
    finally:
        sched.shutdown()
        t.join(timeout=60)


def test_protocol_edge_codec_round_trip():
    from sheep_tpu.server import protocol

    e = _graph(123)
    doc = protocol.encode_edges(e)
    back = protocol.decode_edges(doc)
    assert np.array_equal(back, e)
    assert protocol.decode_edges(None).shape == (0, 2)
    with pytest.raises(protocol.ProtocolError):
        protocol.decode_edges({"nope": 1})
    bad = dict(doc)
    bad["m"] = 7
    with pytest.raises(protocol.ProtocolError):
        protocol.decode_edges(bad)


def test_jobspec_resident_field():
    from sheep_tpu.server.protocol import JobSpec

    spec = JobSpec.from_request({"input": "x", "k": 4,
                                 "resident": True})
    assert spec.resident is True
    assert JobSpec.from_request({"input": "x", "k": 4}).resident \
        is False
