"""Prefetch wrapper invariants (VERDICT r1 item 6) and the batched
staging form used by the batched segment dispatch."""

import threading
import time

import numpy as np
import pytest

from sheep_tpu.utils.prefetch import prefetch, prefetch_batched


def test_order_and_completeness():
    items = list(range(100))
    assert list(prefetch(iter(items))) == items


def test_exception_propagates():
    def gen():
        yield 1
        raise RuntimeError("boom")

    it = prefetch(gen())
    assert next(it) == 1
    with pytest.raises(RuntimeError, match="boom"):
        list(it)


def test_exception_carries_worker_traceback():
    """ISSUE 9 satellite: the re-raise at next() must carry the
    ORIGINAL worker-side frames, so the log names the failing reader
    function, not the prefetch machinery."""
    import traceback

    def injected_reader_fault():
        yield 1
        raise OSError("injected reader fault")

    it = prefetch(injected_reader_fault())
    assert next(it) == 1
    with pytest.raises(OSError) as ei:
        next(it)
    frames = "".join(traceback.format_exception(
        ei.type, ei.value, ei.tb))
    assert "injected_reader_fault" in frames


def test_close_does_not_hang_on_dead_worker():
    """close() after the worker died (here: on an injected reader
    fault) must return promptly — the join is timeout-bounded and the
    thread is already gone."""
    def gen():
        raise OSError("dead on arrival")
        yield  # pragma: no cover

    it = prefetch(gen())
    with pytest.raises(OSError):
        next(it)
    t0 = time.perf_counter()
    it.close()
    assert time.perf_counter() - t0 < 1.0
    assert it.closed


def test_next_raises_on_sentinelless_worker_death():
    """A worker that dies WITHOUT delivering its end/exception sentinel
    (thread killed out-of-band) surfaces as a prompt RuntimeError at
    next(), never an eternal blocking get."""
    import queue

    from sheep_tpu.utils.prefetch import Prefetcher

    pf = Prefetcher.__new__(Prefetcher)
    pf._q = queue.Queue(maxsize=2)
    pf._stop = threading.Event()
    pf._closed = pf._done = False
    pf._thread = threading.Thread(target=lambda: None)
    pf._thread.start()
    pf._thread.join()  # dead, queue empty, no sentinel
    t0 = time.perf_counter()
    with pytest.raises(RuntimeError, match="died without"):
        next(pf)
    assert time.perf_counter() - t0 < 3.0
    pf.close()


def test_early_exit_stops_worker():
    produced = []

    def gen():
        for i in range(10_000):
            produced.append(i)
            yield i

    for x in prefetch(gen()):
        if x == 5:
            break
    time.sleep(0.3)  # worker should have noticed the stop event
    assert len(produced) < 100


def test_overlap_actually_happens():
    """Total wall ~ max(producer, consumer), not sum: with both sides
    sleeping, depth-2 prefetch halves the serial time."""
    N, d = 10, 0.02

    def gen():
        for i in range(N):
            time.sleep(d)
            yield i

    t0 = time.perf_counter()
    for _ in prefetch(gen()):
        time.sleep(d)
    wall = time.perf_counter() - t0
    serial = 2 * N * d
    assert wall < serial * 0.8, f"no overlap: {wall:.3f}s vs serial {serial:.3f}s"


def test_arrays_pass_through_unchanged():
    chunks = [np.arange(10) * i for i in range(5)]
    out = list(prefetch(iter(chunks)))
    for a, b in zip(chunks, out):
        np.testing.assert_array_equal(a, b)


def test_batched_groups_order_and_tail():
    """Groups of exactly ``batch`` items in order, final group short."""
    assert list(prefetch_batched(iter(range(10)), 4)) == \
        [[0, 1, 2, 3], [4, 5, 6, 7], [8, 9]]
    assert list(prefetch_batched(iter(range(8)), 4)) == \
        [[0, 1, 2, 3], [4, 5, 6, 7]]
    assert list(prefetch_batched(iter([]), 4)) == []
    assert list(prefetch_batched(iter(range(3)), 1)) == [[0], [1], [2]]


def test_batched_exception_propagates():
    def gen():
        yield 1
        yield 2
        raise RuntimeError("boom")

    it = prefetch_batched(gen(), 2)
    assert next(it) == [1, 2]
    with pytest.raises(RuntimeError, match="boom"):
        list(it)


def test_batched_validates_batch():
    with pytest.raises(ValueError):
        prefetch_batched(iter([1]), 0)


def test_close_cancels_blocked_worker():
    """A worker blocked on the full bounded queue must wake and exit on
    close() — the in-flight pipeline's discard path abandons the stream
    mid-iteration, and a forever-blocked worker thread would pin the
    producer's file handle (ISSUE 4 satellite)."""
    started = threading.Event()

    def gen():
        for i in range(10_000):
            started.set()
            yield i

    pf = prefetch(gen(), depth=2)
    started.wait(timeout=5)
    assert next(pf) == 0
    pf.close()
    assert pf.closed
    assert not pf._thread.is_alive(), "worker not joined by close()"


def test_close_is_idempotent_and_ends_iteration():
    pf = prefetch(iter(range(100)))
    assert next(pf) == 0
    pf.close()
    pf.close()
    with pytest.raises(StopIteration):
        next(pf)
    assert list(pf) == []


def test_close_after_exhaustion_is_clean():
    pf = prefetch(iter(range(3)))
    assert list(pf) == [0, 1, 2]
    pf.close()
    assert not pf._thread.is_alive()


def test_context_manager_closes():
    with prefetch(iter(range(1000))) as pf:
        assert next(pf) == 0
    assert pf.closed
    with pytest.raises(StopIteration):
        next(pf)


def test_batched_close_cancels_worker():
    produced = []

    def gen():
        for i in range(10_000):
            produced.append(i)
            yield i

    pf = prefetch_batched(gen(), 4)
    assert next(pf) == [0, 1, 2, 3]
    pf.close()
    assert not pf._thread.is_alive()
    n_after_close = len(produced)
    time.sleep(0.2)
    assert len(produced) == n_after_close, "worker kept producing"


def test_batched_overlap_stages_full_group():
    """With a slow producer and a slow consumer, grouped depth-2
    prefetch still overlaps: wall ~ max(sides), not their sum."""
    N, d = 12, 0.01

    def gen():
        for i in range(N):
            time.sleep(d)
            yield i

    t0 = time.perf_counter()
    for group in prefetch_batched(gen(), 3):
        time.sleep(d * len(group))
    wall = time.perf_counter() - t0
    serial = 2 * N * d
    assert wall < serial * 0.8, f"no overlap: {wall:.3f}s vs {serial:.3f}s"
