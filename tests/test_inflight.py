"""Asynchronous in-flight dispatch pipeline + donated buffers (the
ISSUE 4 tentpole).

The acceptance properties, all assertable on the CPU mesh:

  (a) forest bit-identity — ``--inflight D`` for D in {1, 2, 3} produces
      the identical elimination forest to the synchronous path, across
      the driver, backend, sharded and CLI entry points, including runs
      that hit early-convergence discard and budget-exhaustion resume
      (the fixpoint is the unique forest of the constraint multiset,
      independent of fold order and of which speculations ran);
  (b) donation equivalence — the donated programs are pure buffer
      aliasing: enabled/disabled runs are bit-identical, and donated
      inputs really are consumed;
  (c) counter flow — ``host_blocked_ms``/``device_gap_ms`` exist on
      every driver run, flow into obs span deltas, and (with
      tests/test_bench_contract.py and tests/test_trace_tools.py) ride
      the bench contract into the bench_regress gate;
  (d) HBM model — D in-flight staging blocks multiply the staging term
      and donation credits state back (tests/test_membudget.py holds
      the sizing assertions).
"""

import numpy as np
import pytest

import jax.numpy as jnp

from sheep_tpu.backends.tpu_backend import TpuBackend, pad_chunk
from sheep_tpu.io import generators
from sheep_tpu.io.edgestream import EdgeStream
from sheep_tpu.ops import degrees as degrees_ops
from sheep_tpu.ops import elim as elim_ops
from sheep_tpu.ops import order as order_ops


def _order(e, n):
    deg = degrees_ops.init_degrees(n)
    deg = degrees_ops.degree_chunk(deg, pad_chunk(e, len(e), n), n)
    return order_ops.elimination_order(deg, n)


def _oracle(e, n, pos, order):
    whole, _ = elim_ops.build_chunk_step(
        jnp.full(n + 1, n, dtype=jnp.int32), pad_chunk(e, len(e), n),
        pos, order, n)
    return np.asarray(whole)


def _staged(e, cs, n, pos, batch):
    """Generator of (loB, hiB, tag) staged oriented blocks, fresh per
    call (the pipelined driver consumes/donates its inputs)."""
    chunks = [pad_chunk(e[off:off + cs], cs, n)
              for off in range(0, len(e), cs)]
    while len(chunks) % batch:
        chunks.append(np.full((cs, 2), n, np.int32))
    for i in range(0, len(chunks), batch):
        loB, hiB = elim_ops.orient_chunks_batch_pos(
            jnp.asarray(np.stack(chunks[i:i + batch])), pos, n)
        yield loB, hiB, batch


@pytest.mark.parametrize("inflight", [1, 2, 3])
@pytest.mark.parametrize("donate", [False, True])
def test_pipelined_matches_oracle_rmat14(inflight, donate):
    """Oracle equality at RMAT-14 for D in {1, 2, 3}, donation on and
    off (acceptance criterion of the in-flight pipeline)."""
    e = generators.rmat(14, 4, seed=7)
    n = 1 << 14
    pos, order = _order(e, n)
    whole = _oracle(e, n, pos, order)
    stats: dict = {}
    P, _ = elim_ops.fold_segments_pipelined(
        jnp.full(n + 1, n, dtype=jnp.int32),
        _staged(e, 1 << 13, n, pos, 2), n,
        inflight=inflight, segment_rounds=2, donate=donate, stats=stats)
    np.testing.assert_array_equal(np.asarray(P[pos]), whole)
    assert stats["host_syncs"] > 0
    assert stats["host_blocked_ms"] >= 0.0
    assert stats["device_gap_ms"] >= 0.0
    assert "batch_incomplete_segments" not in stats


def test_pipelined_sync_depth_matches_batched_driver():
    """``inflight=1`` degenerates to the synchronous driver exactly:
    same executions in the same order, so the sync/round counters (not
    just the forest) agree with fold_segments_batch over the groups."""
    e = generators.rmat(12, 8, seed=5)
    n = 1 << 12
    pos, _ = _order(e, n)
    sa: dict = {}
    Pa = jnp.full(n + 1, n, dtype=jnp.int32)
    for loB, hiB, _tag in _staged(e, 1 << 10, n, pos, 2):
        Pa, _ = elim_ops.fold_segments_batch(Pa, loB, hiB, n,
                                             segment_rounds=2, stats=sa)
    sb: dict = {}
    Pb, _ = elim_ops.fold_segments_pipelined(
        jnp.full(n + 1, n, dtype=jnp.int32),
        _staged(e, 1 << 10, n, pos, 2), n,
        inflight=1, segment_rounds=2, donate=False, stats=sb)
    np.testing.assert_array_equal(np.asarray(Pa), np.asarray(Pb))
    assert sb["host_syncs"] == sa["host_syncs"]
    assert sb["device_rounds"] == sa["device_rounds"]
    assert sb["inflight_discards"] == 0


def test_early_convergence_discards_speculation():
    """A stream whose final blocks converge in one execution forces the
    stream-end speculation to be wrong: the speculative re-dispatches
    are discarded UNREAD (no extra host syncs) and the adopted chain
    tip is the bit-identical re-confirmation of the converged table."""
    e = generators.rmat(12, 8, seed=3)
    n = 1 << 12
    pos, order = _order(e, n)
    whole = _oracle(e, n, pos, order)
    stats: dict = {}
    P, _ = elim_ops.fold_segments_pipelined(
        jnp.full(n + 1, n, dtype=jnp.int32),
        _staged(e, len(e), n, pos, 1), n,   # one group, one execution
        inflight=3, batch_rounds=1 << 14, donate=True, stats=stats)
    np.testing.assert_array_equal(np.asarray(P[pos]), whole)
    assert stats["inflight_discards"] == 2   # both speculations wasted
    assert stats["host_syncs"] == 1          # their svs were never read


@pytest.mark.parametrize("inflight", [1, 2, 3])
def test_budget_exhaustion_resumes_to_oracle(inflight):
    """A per-execution round budget far below the need forces repeated
    mid-block exhaustion: the leftover blocks are re-queued onto the
    live chain and the stream still converges to the oracle forest
    (the budget-exhaustion resume path)."""
    e = generators.rmat(12, 8, seed=11)
    n = 1 << 12
    pos, order = _order(e, n)
    whole = _oracle(e, n, pos, order)
    stats: dict = {}
    P, _ = elim_ops.fold_segments_pipelined(
        jnp.full(n + 1, n, dtype=jnp.int32),
        _staged(e, 1 << 10, n, pos, 2), n,
        inflight=inflight, batch_rounds=3, donate=True, stats=stats)
    np.testing.assert_array_equal(np.asarray(P[pos]), whole)
    assert "batch_incomplete_segments" not in stats


def test_max_rounds_backstop_flags_incomplete():
    """The round backstop must not exit silently: in-flight executions
    are drained (and counted) and the undrained remainder is flagged."""
    e = generators.rmat(11, 8, seed=2)
    n = 1 << 11
    pos, _ = _order(e, n)
    stats: dict = {}
    _, total = elim_ops.fold_segments_pipelined(
        jnp.full(n + 1, n, dtype=jnp.int32),
        _staged(e, 256, n, pos, 2), n,
        inflight=2, max_rounds=4, donate=True, stats=stats)
    assert total >= 4
    assert stats["batch_incomplete_segments"] > 0


def test_donated_program_consumes_inputs():
    """The donated fold really donates: its inputs are invalidated, so
    the membudget credit corresponds to actual buffer reuse."""
    e = generators.rmat(10, 8, seed=1)
    n = 1 << 10
    pos, _ = _order(e, n)
    (loB, hiB, _tag), = list(_staged(e, len(e), n, pos, 1))
    P = jnp.full(n + 1, n, dtype=jnp.int32)
    elim_ops.fold_segments_batch_pos_donated(P, loB, hiB, n)
    with pytest.raises(RuntimeError, match="deleted"):
        np.asarray(P)


def test_fold_segments_batch_donate_resumes_after_exhaustion():
    """The donated program composes with budget-exhaustion resume at
    the fold_segments_batch level (the synchronous driver's donate
    knob): repeated donated executions on the returned state converge
    to the oracle."""
    e = generators.rmat(10, 8, seed=3)
    n = 1 << 10
    pos, order = _order(e, n)
    whole = _oracle(e, n, pos, order)
    (loB, hiB, _tag), = list(_staged(e, len(e), n, pos, 1))
    stats: dict = {}
    P, _ = elim_ops.fold_segments_batch(
        jnp.full(n + 1, n, dtype=jnp.int32), loB, hiB, n,
        batch_rounds=3, stats=stats, donate=True)
    np.testing.assert_array_equal(np.asarray(P[pos]), whole)
    assert stats["batch_execs"] > 1  # the tiny budget really exhausted


def test_pipelined_rejects_bad_depth():
    with pytest.raises(ValueError, match="inflight"):
        elim_ops.fold_segments_pipelined(
            jnp.full(8, 7, dtype=jnp.int32), iter(()), 7, inflight=0)


# -- backend / sharded / CLI entry points ----------------------------------


@pytest.mark.parametrize("inflight", [2, 3])
def test_backend_inflight_bit_identical(inflight):
    """End-to-end TpuBackend equality: pipelined dispatch vs the
    synchronous default, multi-chunk stream with a sentinel-padded tail
    group, donation on (the default) and off."""
    e = generators.rmat(11, 8, seed=9)
    n = 1 << 11
    es = EdgeStream.from_array(e, n_vertices=n)
    base = TpuBackend(chunk_edges=512).partition(es, 8)
    got = TpuBackend(chunk_edges=512, dispatch_batch=2,
                     inflight=inflight).partition(es, 8)
    np.testing.assert_array_equal(got.assignment, base.assignment)
    assert got.edge_cut == base.edge_cut
    assert got.comm_volume == base.comm_volume
    assert got.diagnostics["inflight_depth"] == inflight
    assert got.diagnostics["host_blocked_ms"] >= 0
    assert got.diagnostics["device_gap_ms"] >= 0
    nod = TpuBackend(chunk_edges=512, dispatch_batch=2, inflight=inflight,
                     donate_buffers=False).partition(es, 8)
    np.testing.assert_array_equal(nod.assignment, base.assignment)
    assert nod.edge_cut == base.edge_cut


def test_backend_inflight_without_batching():
    """--inflight alone engages the pipeline even where dispatch_batch
    auto-resolves to 1 (cpu-jax): N=1 staged blocks, same forest."""
    e = generators.rmat(11, 8, seed=9)
    n = 1 << 11
    es = EdgeStream.from_array(e, n_vertices=n)
    base = TpuBackend(chunk_edges=512).partition(es, 8)
    got = TpuBackend(chunk_edges=512, inflight=2).partition(es, 8)
    np.testing.assert_array_equal(got.assignment, base.assignment)
    assert got.diagnostics["dispatch_batch"] == 1
    assert got.diagnostics["inflight_depth"] == 2


def test_backend_inflight_excludes_tail_strategies():
    with pytest.raises(ValueError, match="inflight"):
        TpuBackend(inflight=2, carry_tail=True)
    with pytest.raises(ValueError, match="inflight"):
        TpuBackend(inflight=2, tail_overlap=True)
    with pytest.raises(ValueError, match="inflight"):
        TpuBackend(inflight=-1)


def test_adaptive_driver_emits_overlap_counters():
    """The synchronous per-segment driver emits the same counter pair,
    so an --inflight A/B is readable from any two runs' diagnostics."""
    e = generators.rmat(11, 8, seed=9)
    n = 1 << 11
    es = EdgeStream.from_array(e, n_vertices=n)
    res = TpuBackend(chunk_edges=512).partition(es, 8)
    assert res.diagnostics["host_blocked_ms"] >= 0
    assert res.diagnostics["device_gap_ms"] >= 0


@pytest.mark.parametrize("inflight", [2, 3])
def test_sharded_pipeline_inflight_matches(inflight):
    """The sharded batched path's speculative one-behind pipelining
    (pmin-done lockstep, discard-unread on convergence) must match the
    per-segment sharded run on the 8-device virtual mesh."""
    from sheep_tpu.backends.base import get_backend, list_backends

    if "tpu-sharded" not in list_backends():
        pytest.skip("sharded backend unavailable")
    e = generators.rmat(11, 8, seed=9)
    n = 1 << 11
    es = EdgeStream.from_array(e, n_vertices=n)
    base = get_backend("tpu-sharded", chunk_edges=256).partition(
        es, 8, comm_volume=False)
    got = get_backend("tpu-sharded", chunk_edges=256, dispatch_batch=2,
                      inflight=inflight).partition(es, 8,
                                                   comm_volume=False)
    np.testing.assert_array_equal(got.assignment, base.assignment)
    assert got.edge_cut == base.edge_cut
    assert got.diagnostics["inflight_depth"] == inflight
    assert got.diagnostics["host_blocked_ms"] >= 0


@pytest.mark.parametrize("inflight", [2, 3])
def test_checkpoint_resume_through_pipeline(tmp_path, monkeypatch,
                                            inflight):
    """Checkpoints are FLUSH BARRIERS (regression test): mid-pipeline
    the tip table can under-represent a confirmed group whose
    budget-exhausted leftovers are still queued host-side, so a naive
    cut loses constraints on resume. segment_rounds=1 keeps the
    per-execution budget tight enough that leftovers genuinely occur;
    fault -> resume must still land on the oracle forest."""
    from sheep_tpu.utils.checkpoint import Checkpointer
    from sheep_tpu.utils.fault import InjectedFault

    e = generators.rmat(11, 8, seed=9)
    n = 1 << 11
    es = EdgeStream.from_array(e, n_vertices=n)
    base = TpuBackend(chunk_edges=256).partition(es, 8)
    ck_dir = str(tmp_path / f"ck{inflight}")
    monkeypatch.setenv("SHEEP_FAULT_INJECT", "build:9")
    with pytest.raises(InjectedFault):
        TpuBackend(chunk_edges=256, dispatch_batch=2, segment_rounds=1,
                   inflight=inflight).partition(
            es, 8, checkpointer=Checkpointer(ck_dir, every=4))
    monkeypatch.delenv("SHEEP_FAULT_INJECT")
    res = TpuBackend(chunk_edges=256, dispatch_batch=2, segment_rounds=1,
                     inflight=inflight).partition(
        es, 8, checkpointer=Checkpointer(ck_dir, every=4), resume=True)
    np.testing.assert_array_equal(res.assignment, base.assignment)
    assert res.edge_cut == base.edge_cut


def test_obs_span_deltas_absorb_overlap_counters(tmp_path):
    """Counter flow hop 2: the stats-dict counters surface as obs span
    counter deltas on a traced run. Pinned at depth 1, where BOTH
    counters are guaranteed nonzero (the tracer omits zero deltas, and
    at D >= 2 a collapsed-to-zero device_gap_ms is the success mode)."""
    import json

    from sheep_tpu import obs

    e = generators.rmat(10, 8, seed=4)
    n = 1 << 10
    es = EdgeStream.from_array(e, n_vertices=n)
    trace = tmp_path / "t.jsonl"
    with obs.tracing(str(trace)):
        TpuBackend(chunk_edges=256, dispatch_batch=2,
                   inflight=1).partition(es, 4)
    merged: dict = {}
    for line in trace.read_text().splitlines():
        rec = json.loads(line)
        if rec.get("event") == "span_end":
            merged.update(rec.get("counters", {}))
        if rec.get("event") == "counters":
            merged.update(rec)
    assert merged["host_blocked_ms"] > 0
    assert merged["device_gap_ms"] > 0
    assert merged["inflight_depth"] == 1


def test_cli_inflight_flag(tmp_path, capsys):
    """--inflight plumbs through the CLI to the backend and the
    pipelined run scores identically to the synchronous default."""
    import json

    from sheep_tpu.cli import main as cli_main
    from sheep_tpu.io import formats

    p = tmp_path / "g.edges"
    formats.write_edges(str(p), generators.rmat(9, 8, seed=2))
    assert cli_main(["--input", str(p), "--k", "4", "--backend", "tpu",
                     "--json", "--chunk-edges", "128",
                     "--inflight", "1"]) == 0
    base = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    for d in ("2", "3"):
        assert cli_main(["--input", str(p), "--k", "4", "--backend",
                         "tpu", "--json", "--chunk-edges", "128",
                         "--dispatch-batch", "2", "--inflight", d]) == 0
        got = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert got["edge_cut"] == base["edge_cut"]
        assert got["comm_volume"] == base["comm_volume"]


def test_cli_inflight_validation(tmp_path):
    from sheep_tpu.cli import main as cli_main
    from sheep_tpu.io import formats

    p = tmp_path / "g.edges"
    formats.write_edges(str(p), generators.rmat(8, 4, seed=2))
    with pytest.raises(SystemExit):
        cli_main(["--input", str(p), "--k", "4", "--inflight", "-1"])
    with pytest.raises(SystemExit):
        cli_main(["--input", str(p), "--k", "4", "--inflight", "2",
                  "--carry-tail"])
