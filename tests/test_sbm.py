"""Planted-partition (SBM) stream tests (VERDICT r3 item 5; SURVEY.md §1
"low communication volume" / §4.5 quality evidence).

The counter-hash SBM gives a KNOWN optimal cut at any scale: cross
edges are inter-block by construction, so the planted assignment scores
cut_ratio == (observed Bernoulli(p_out) rate) exactly. Quality evidence:
the streaming pass alone does not recover blocks on a degree-flat SBM
(it optimizes communication volume via degree/elimination structure —
measured ~0.87 cut at k=8 where random is 0.875), and the refine
post-pass (capacity-constrained label propagation) recovers the planted
structure to near-optimal where block density supports it
(BASELINE.md "SBM quality" table).
"""

import numpy as np
import pytest

from sheep_tpu.io import generators
from sheep_tpu.io.edgestream import open_input


def test_range_determinism_and_chunk_consistency():
    s = generators.SbmHashStream(10, 8, 0.07, edge_factor=4, seed=9)
    full = s.read_all()
    assert full.shape == (4 << 10, 2)
    again = np.concatenate(list(s.chunks(chunk_edges=1000)))
    assert np.array_equal(full, again)
    # random access: any range equals the slice of the full stream
    assert np.array_equal(
        generators.sbm_hash_range(10, 777, 500, 8, 0.07, seed=9),
        full[777:1277])


def test_ids_in_range_and_cross_rate():
    s = generators.SbmHashStream(12, 16, 0.10, edge_factor=8, seed=3)
    e = s.read_all()
    assert e.min() >= 0 and e.max() < (1 << 12)
    gt = s.ground_truth()
    assert gt.shape == (1 << 12,) and gt.max() == 15
    cross = (gt[e[:, 0]] != gt[e[:, 1]]).mean()
    # 32768 edges: 5 sigma ~ 0.0083
    assert abs(cross - 0.10) < 0.01, cross
    # blocks are contiguous id ranges
    assert np.array_equal(gt, np.arange(1 << 12) >> 8)


def test_ground_truth_grouping_and_validation():
    s = generators.SbmHashStream(8, 8, 0.05)
    gt8 = s.ground_truth()
    gt2 = s.ground_truth(2)
    assert np.array_equal(gt2, gt8 // 4)
    with pytest.raises(ValueError, match="divide"):
        s.ground_truth(3)
    with pytest.raises(ValueError, match="power of two"):
        generators.SbmHashStream(8, 6, 0.05)
    with pytest.raises(ValueError, match="p_out"):
        generators.SbmHashStream(8, 4, 1.5)
    with pytest.raises(ValueError, match="scale"):
        generators.SbmHashStream(32, 4, 0.1)


def test_native_range_matches_numpy():
    from sheep_tpu.core import native

    if not (native.available() and native.has_sbm_hash()):
        pytest.skip("native core without sbm hash")
    # count >= 4096 dispatches native; force the numpy body for the twin
    keys = generators._sbm_hash_keys(7)
    start, count = (1 << 32) - 2048, 8192  # crosses the 32-bit counter
    idx = start + np.arange(count, dtype=np.int64)
    u, v = generators._sbm_hash_uv(
        np, (idx & 0xFFFFFFFF).astype(np.uint32),
        (idx >> 32).astype(np.uint32), keys,
        generators._sbm_t_out(0.07), 16, 8, np.int64)
    nat = native.sbm_hash_range(start, count, keys,
                                generators._rmat_hash_keys2(keys),
                                generators._sbm_t_out(0.07), 16, 8)
    assert np.array_equal(nat, np.stack([u, v], axis=1))


def test_device_chunk_matches_host():
    s = generators.SbmHashStream(9, 4, 0.2, edge_factor=4, seed=5)
    n = 1 << 9
    cs = 600
    host = s.read_all()
    for idx in range(s.num_device_chunks(cs)):
        dev = np.asarray(s.device_chunk(idx, cs, n))
        count = min(cs, s.num_edges - idx * cs)
        assert np.array_equal(dev[:count].astype(np.int64),
                              host[idx * cs: idx * cs + count])
        assert (dev[count:] == n).all()  # sentinel padding


def test_open_input_spec():
    with open_input("sbm-hash:10:8:0.05") as s:
        assert isinstance(s, generators.SbmHashStream)
        assert s.num_vertices == 1 << 10 and s.p_out == 0.05
    with open_input("sbm-hash:10:8:0.05:4:7") as s:
        assert s.edge_factor == 4 and s.seed == 7
    for bad in ("sbm-hash:10", "sbm-hash:10:8", "sbm-hash:10:8:x",
                "sbm-hash:10:8:0.05:0", "sbm-hash:10:6:0.05"):
        with pytest.raises(ValueError):
            open_input(bad)
    with pytest.raises(ValueError, match="contradicts"):
        open_input("sbm-hash:10:8:0.05", n_vertices=55)


def test_planted_assignment_scores_planted_ratio():
    """Scoring the ground truth against the stream recovers the observed
    cross rate exactly — the known-optimal-cut yardstick."""
    from sheep_tpu.backends.base import score_stream

    s = generators.SbmHashStream(11, 8, 0.05, edge_factor=16, seed=1)
    gt = s.ground_truth()
    cut, total, balance, _ = score_stream(s, {8: gt.astype(np.int32)},
                                          chunk_edges=1 << 14,
                                          comm_volume=False)[8]
    e = s.read_all()
    expect = int((gt[e[:, 0]] != gt[e[:, 1]]).sum())
    assert cut == expect
    # the scorer's total excludes self-loops (never cuttable; the SBM
    # produces ~2^-block_bits of them among intra edges)
    assert total == int((e[:, 0] != e[:, 1]).sum())
    assert abs(cut / total - 0.05) < 0.01
    assert abs(balance - 1.0) < 1e-6  # equal blocks => perfect balance


def test_refine_recovers_planted_structure():
    """The headline quality property: base streaming pass ~random on a
    degree-flat SBM, refine recovers near-planted cut (measured 0.13 at
    scale 11 / k=8 / p_out=0.05 / 8 rounds; planted 0.05, random 0.875,
    base 0.87)."""
    import sheep_tpu

    be = "cpu" if "cpu" in sheep_tpu.list_backends() else "pure"
    base = sheep_tpu.partition("sbm-hash:11:8:0.05:16:1", 8, backend=be,
                               comm_volume=False)
    refined = sheep_tpu.partition("sbm-hash:11:8:0.05:16:1", 8, backend=be,
                                  comm_volume=False, refine=8)
    assert base.cut_ratio < 0.93            # sane, if not structured
    assert refined.cut_ratio <= 0.30, refined.cut_ratio
    assert refined.cut_ratio <= base.cut_ratio / 2
    assert refined.balance <= 1.11          # refine alpha default 1.10
