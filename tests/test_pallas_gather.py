"""Semantics of the Pallas VMEM-gather probe (interpreter mode — the
on-chip lowering/perf question is the microbench's to answer)."""

import numpy as np

from sheep_tpu.ops.pallas_gather import vmem_gather


def test_interpret_matches_numpy():
    import jax.numpy as jnp

    rng = np.random.default_rng(0)
    table = rng.integers(0, 1 << 20, size=1 << 12, dtype=np.int32)
    idx = rng.integers(0, 1 << 12, size=1 << 14, dtype=np.int32)
    out = np.asarray(vmem_gather(jnp.asarray(table), jnp.asarray(idx),
                                 block=4096, interpret=True))
    assert np.array_equal(out, table[idx])


def test_block_validation():
    import jax.numpy as jnp
    import pytest

    t = jnp.zeros(16, jnp.int32)
    with pytest.raises(ValueError, match="multiple"):
        vmem_gather(t, jnp.zeros(100, jnp.int32), block=64)
