"""Streaming-scale tests wiring ``rmat_stream`` into the pipeline
(VERDICT r1 item 4: it was dead code; the largest graph any test touched
was RMAT-14).

The smoke tests run at RMAT-14/16 on every backend so the generator
EdgeStream path is exercised in CI. The LiveJournal-scale soak (>=64M
edges, driver eval config 2's size class) is gated behind SHEEP_SOAK=1
because it takes minutes; run it with

    SHEEP_SOAK=1 python -m pytest tests/test_scale_soak.py -k soak -s
"""

import os

import numpy as np
import pytest

from sheep_tpu.core import native, pure
from sheep_tpu.io import generators
from sheep_tpu.io.edgestream import EdgeStream


def _stream(scale, ef, seed=42, chunk=1 << 18):
    m = ef << scale
    return EdgeStream.from_generator(
        lambda: generators.rmat_stream(scale, ef, seed=seed, chunk=chunk),
        n_vertices=1 << scale, num_edges=m)


def test_generator_stream_replays_deterministically():
    es = _stream(12, 8)
    a = np.concatenate(list(es.chunks(1000)))
    b = np.concatenate(list(es.chunks(1000)))
    np.testing.assert_array_equal(a, b)
    assert len(a) == 8 << 12
    # matches the materializing generator exactly (same per-chunk seeding)
    full = np.concatenate(
        list(generators.rmat_stream(12, 8, seed=42, chunk=1 << 18)))
    np.testing.assert_array_equal(a, full)


def test_generator_stream_shards_partition():
    es = _stream(12, 4)
    parts = [sum(len(c) for c in es.chunks(500, shard=i, num_shards=3))
             for i in range(3)]
    assert sum(parts) == 4 << 12


@pytest.mark.parametrize("backend", ["pure", "cpu", "tpu", "tpu-sharded"])
def test_rmat_stream_partition_smoke(backend):
    """Every backend consumes a generator stream; results agree with the
    materialized oracle exactly."""
    from sheep_tpu.backends.base import get_backend, list_backends

    if backend not in list_backends():
        pytest.skip(f"{backend} unavailable")
    scale, ef = 12, 8
    es = _stream(scale, ef)
    res = get_backend(backend, chunk_edges=1 << 14).partition(
        es, 8, comm_volume=False)
    e = np.concatenate(list(generators.rmat_stream(scale, ef, seed=42,
                                                   chunk=1 << 18)))
    ref = pure.partition_arrays(e, 8, n=1 << scale)
    assert res.total_edges == ref.total_edges
    assert res.edge_cut == ref.edge_cut
    np.testing.assert_array_equal(res.assignment, ref.assignment)


@pytest.mark.skipif(os.environ.get("SHEEP_SOAK") != "1",
                    reason="set SHEEP_SOAK=1 for the 67M-edge soak")
def test_soak_livejournal_scale():
    """LiveJournal-size streaming soak (SURVEY.md §4.5, BASELINE config 2
    class): RMAT-22 x16 = 67M edges through the native cpu backend and the
    jax streaming build, O(V + chunk) memory, no recompilation."""
    scale, ef = 22, 16
    es = _stream(scale, ef, chunk=1 << 22)
    be = "cpu" if native.available() else "tpu"
    from sheep_tpu.backends.base import get_backend

    res = get_backend(be, chunk_edges=1 << 22).partition(
        es, 8, comm_volume=False)
    assert res.total_edges > 66_000_000
    assert 0 < res.edge_cut <= res.total_edges
    assert res.balance < 1.6
    # every vertex with degree > 0 got a part in [0, 8)
    assert res.assignment.min() >= 0 and res.assignment.max() < 8


@pytest.mark.skipif(os.environ.get("SHEEP_SOAK") != "1",
                    reason="set SHEEP_SOAK=1 for the big-V soak")
def test_soak_big_v_stream_descent():
    """Big-V soak: V=2^26 vertex tables through the jax streaming build.

    At this V the exact-descent lifting stack (27 tables x 268 MB) blows
    the EXACT_TABLE_BYTES budget, so fold_edges auto-selects the STREAM
    descent (one live table) — the path RMAT-30-class configs rely on —
    while the edge count stays small enough to run in CI-minutes. The
    tree must still match the oracle exactly."""
    scale, ef = 26, 1  # 67M vertices, 67M edges would be heavy; ef=1
    n = 1 << scale
    m = 1 << 22  # 4M edges over 67M vertices
    rng = np.random.default_rng(7)
    e = rng.integers(0, n, size=(m, 2), dtype=np.int64)
    es = EdgeStream.from_array(e, n_vertices=n)
    from sheep_tpu.backends.base import get_backend

    res = get_backend("tpu", chunk_edges=1 << 21).partition(
        es, 8, comm_volume=False)
    assert res.assignment.min() >= 0 and res.assignment.max() < 8
    if native.available():
        ref = get_backend("cpu", chunk_edges=1 << 21).partition(
            es, 8, comm_volume=False)
        assert res.edge_cut == ref.edge_cut
        np.testing.assert_array_equal(res.assignment, ref.assignment)


@pytest.mark.skipif(os.environ.get("SHEEP_SOAK") != "1",
                    reason="set SHEEP_SOAK=1 for the sharded soak")
def test_soak_sharded_pipeline_mid_scale():
    """Sharded-pipeline soak: RMAT-18 (4.2M edges) across the 8-device
    mesh — the existing sharded tests top out at RMAT-9, so this is the
    first time the butterfly merge sees millions-scale per-device
    forests. Must agree exactly with the single-device tpu backend."""
    import jax

    if jax.device_count() < 8:
        pytest.skip("needs 8 virtual devices")
    e = generators.rmat(18, 16, seed=31)
    n = 1 << 18
    from sheep_tpu.backends.base import get_backend

    es = EdgeStream.from_array(e, n_vertices=n)
    sharded = get_backend("tpu-sharded", chunk_edges=1 << 18).partition(
        es, 64, comm_volume=False)
    es = EdgeStream.from_array(e, n_vertices=n)
    single = get_backend("tpu", chunk_edges=1 << 20).partition(
        es, 64, comm_volume=False)
    assert sharded.edge_cut == single.edge_cut
    np.testing.assert_array_equal(sharded.assignment, single.assignment)


@pytest.mark.skipif(os.environ.get("SHEEP_SOAK") != "1",
                    reason="set SHEEP_SOAK=1 for the bigv mesh soak")
def test_soak_bigv_mesh_mid_scale():
    """Vertex-sharded soak on the full 8-device mesh: RMAT-20x16 (16.7M
    edges) through tpu-bigv with the bulk-phase lifting rounds and a
    kill+resume in the middle of the build — the routed-fixpoint
    recovery path at a scale the default matrix (RMAT-10) never
    reaches. Must agree exactly with the native cpu backend."""
    import jax

    if jax.device_count() < 8:
        pytest.skip("needs 8 virtual devices")
    import tempfile

    from sheep_tpu.backends.base import get_backend
    from sheep_tpu.utils.checkpoint import Checkpointer
    from sheep_tpu.utils.fault import ENV_VAR, InjectedFault

    scale, ef = 20, 16
    es = _stream(scale, ef, chunk=1 << 20)
    res = None
    with tempfile.TemporaryDirectory() as d:
        ck = Checkpointer(d, every=8)
        # build:2, not build:1 — maybe_fail("build", nb) runs BEFORE the
        # nb-th save, so a fault at nb=1 would fire before ANY build
        # checkpoint exists and resume would restore the degrees phase,
        # skipping the ptable_local build-restore branch this test covers
        os.environ[ENV_VAR] = "build:2"
        try:
            with pytest.raises(InjectedFault):
                get_backend("tpu-bigv", chunk_edges=1 << 20,
                            n_devices=8).partition(
                    es, 64, comm_volume=False, checkpointer=ck)
        finally:
            del os.environ[ENV_VAR]
        res = get_backend("tpu-bigv", chunk_edges=1 << 20,
                          n_devices=8).partition(
            es, 64, comm_volume=False, checkpointer=ck, resume=True)
    if native.available():
        ref = get_backend("cpu", chunk_edges=1 << 22).partition(
            _stream(scale, ef, chunk=1 << 20), 64, comm_volume=False)
        assert res.edge_cut == ref.edge_cut
        np.testing.assert_array_equal(res.assignment, ref.assignment)
    assert res.diagnostics.get("collective_bytes", 0) > 0
