"""tools/tpu_probe_quick.bank(): the per-window link-state banking must
rewrite the CURRENT window's line in place (one line per window, even
though it banks after every leg) and append across windows — this is
the partial-evidence mechanism VERDICT r4 item 8 asked for, so its
file-handling is pinned host-only (no jax, no tunnel)."""

import importlib.util
import json
import os


def _load(tmp_path, monkeypatch):
    spec = importlib.util.spec_from_file_location(
        "tpu_probe_quick",
        os.path.join(os.path.dirname(__file__), "..", "tools",
                     "tpu_probe_quick.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    monkeypatch.setattr(mod, "PATH", str(tmp_path / "linkstate.jsonl"))
    return mod


def test_bank_rewrites_within_window_appends_across(tmp_path, monkeypatch):
    mod = _load(tmp_path, monkeypatch)
    w1 = {"probe": "linkstate", "utc": "20260801T040000"}
    mod.bank(w1)
    w1["rtt_ms"] = 73.0
    mod.bank(w1)
    w1["h2d_mbs"] = 43.2
    mod.bank(w1)
    lines = [json.loads(l) for l in
             open(mod.PATH).read().splitlines() if l.strip()]
    assert len(lines) == 1 and lines[0]["h2d_mbs"] == 43.2

    w2 = {"probe": "linkstate", "utc": "20260801T050000", "rtt_ms": 5.0}
    mod.bank(w2)
    lines = [json.loads(l) for l in
             open(mod.PATH).read().splitlines() if l.strip()]
    assert len(lines) == 2
    assert lines[0]["utc"] == "20260801T040000"  # prior window untouched
    assert lines[1]["rtt_ms"] == 5.0


def test_bank_first_write_creates_parent(tmp_path, monkeypatch):
    mod = _load(tmp_path, monkeypatch)
    monkeypatch.setattr(mod, "PATH", str(tmp_path / "sub" / "ls.jsonl"))
    mod.bank({"probe": "linkstate", "utc": "x"})
    assert os.path.exists(mod.PATH)
