"""Native vs pure tree_split equality (VERDICT round 1 item 2).

The TPU backends route their host-side split through the C++
sheep_tree_split (sheep_tpu/ops/split.py); the numpy/heapq reference in
core/pure.py is the executable spec. Both must produce BIT-IDENTICAL
assignments — same stable descending child sort, same least-loaded-part
heap tie-breaking — so that routing the TPU path through native never
changes cross-backend equivalence results.
"""

import numpy as np
import pytest

from sheep_tpu.core import native, pure
from sheep_tpu.io import generators
from sheep_tpu.ops.split import tree_split_host

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native core unavailable")


def _tree(edges, n):
    deg = pure.degrees(edges, n)
    pos = pure.elimination_order(deg)
    return pure.build_elim_tree(edges, pos), deg


GRAPHS = [
    ("karate", generators.karate_club(), 34, 2),
    ("karate_k5", generators.karate_club(), 34, 5),
    ("path", generators.path_graph(257), 257, 4),
    ("star", generators.star_graph(200), 200, 8),
    ("grid", generators.grid_graph(17, 23), 17 * 23, 6),
    ("random", generators.random_graph(500, 2000, seed=3), 500, 8),
    ("random_multi", generators.random_graph(100, 5000, seed=7), 100, 16),
    ("rmat12", generators.rmat(12, 8, seed=11), 1 << 12, 64),
    ("rmat10_k100", generators.rmat(10, 16, seed=5), 1 << 10, 100),
]


@pytest.mark.parametrize("name,edges,n,k", GRAPHS, ids=[g[0] for g in GRAPHS])
@pytest.mark.parametrize("weighted", [False, True])
def test_native_split_matches_pure(name, edges, n, k, weighted):
    tree, deg = _tree(edges, n)
    w = deg.astype(np.float64) if weighted else None
    a_pure = pure.tree_split(tree, k, weights=w)
    a_native = native.tree_split(tree.parent, tree.pos, k, weights=w)
    np.testing.assert_array_equal(a_native, a_pure)


@pytest.mark.parametrize("alpha", [0.8, 1.0, 1.5])
def test_native_split_matches_pure_alpha(alpha):
    edges = generators.rmat(11, 8, seed=13)
    tree, _ = _tree(edges, 1 << 11)
    a_pure = pure.tree_split(tree, 32, alpha=alpha)
    a_native = native.tree_split(tree.parent, tree.pos, 32, alpha=alpha)
    np.testing.assert_array_equal(a_native, a_pure)


def test_dispatch_uses_native():
    """tree_split_host must hit the native path when the lib is built —
    this is the TPU backends' split (VERDICT: the interpreted fallback is
    unusable at the 41M-vertex eval configs)."""
    edges = generators.random_graph(300, 1200, seed=1)
    tree, _ = _tree(edges, 300)
    got = tree_split_host(tree.parent, tree.pos, 8)
    np.testing.assert_array_equal(
        got, native.tree_split(tree.parent, tree.pos, 8))
    assert got.dtype == np.int32


def test_disconnected_forest():
    """Multiple roots (disconnected components) split identically."""
    a = generators.random_graph(100, 300, seed=2)
    b = generators.random_graph(100, 300, seed=4) + 100
    edges = np.concatenate([a, b])
    tree, _ = _tree(edges, 200)
    assert (tree.parent < 0).sum() >= 2
    np.testing.assert_array_equal(
        native.tree_split(tree.parent, tree.pos, 8),
        pure.tree_split(tree, 8))
