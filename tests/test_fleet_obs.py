"""Fleet observability tests (ISSUE 18): the wire trace-context codec,
remote-parent span attrs, cross-replica metric federation (exact
histogram merge, loud boundary mismatch, degrade-with-warning), the
--stitch cross-process tree, the SLO gate's rule evaluation and exit
codes, and the fleet client's routing-scrape TTL cache. The live
two-replica failover leg (same trace id in the client and BOTH
replicas' traces, stitched green) is tools/obs_smoke.sh leg 14."""

import importlib.util
import io
import json
import os
import random
import sys

import pytest

from sheep_tpu import obs
from sheep_tpu.obs import federate as federate_mod
from sheep_tpu.obs.metrics import MetricRegistry, parse_prometheus
from sheep_tpu.server import protocol

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


trace_report = _load_tool("trace_report")
slo_check = _load_tool("slo_check")


# ---------------------------------------------------------------------------
# traceparent codec (protocol.py)
# ---------------------------------------------------------------------------

def test_mint_trace_id_shape_and_uniqueness():
    ids = {protocol.mint_trace_id() for _ in range(64)}
    assert len(ids) == 64
    for tid in ids:
        assert len(tid) == 32
        int(tid, 16)  # pure hex


def test_traceparent_round_trip_with_span():
    tid = protocol.mint_trace_id()
    tp = protocol.make_traceparent(tid, 7)
    assert tp == f"00-{tid}-0000000000000007-01"
    assert protocol.parse_traceparent(tp) == (tid, "0000000000000007")


def test_traceparent_no_span_parses_to_none():
    """An all-zero span id means 'the client had no span of its own' —
    the trace id still propagates."""
    tid = protocol.mint_trace_id()
    tp = protocol.make_traceparent(tid)
    assert protocol.parse_traceparent(tp) == (tid, None)


@pytest.mark.parametrize("bad", [
    123, None, "", "garbage",
    "00-zz-0000000000000001-01",                       # not hex
    "00-" + "0" * 32 + "-0000000000000001-01",          # all-zero trace
    "00-" + "a" * 31 + "-0000000000000001-01",          # short trace
])
def test_traceparent_rejects_malformed(bad):
    with pytest.raises(protocol.ProtocolError):
        protocol.parse_traceparent(bad)


def test_request_trace_field_is_not_a_job_field():
    """``trace`` rides at the request top level: JobSpec must keep
    rejecting unknown job fields, and a traced submit must parse."""
    req = {"op": "submit", "tenant": "t", "trace":
           protocol.make_traceparent(protocol.mint_trace_id()),
           "job": {"input": "x.txt", "k": 2}}
    protocol.parse_request(json.dumps(req).encode() + b"\n")
    with pytest.raises(protocol.ProtocolError):
        protocol.JobSpec.from_request({"input": "x.txt", "k": 2,
                                       "trace": "00-..."}, "t")


# ---------------------------------------------------------------------------
# tracer: remote_parent + current_span_id
# ---------------------------------------------------------------------------

def _spans_of(buf):
    return [json.loads(ln) for ln in buf.getvalue().splitlines()
            if json.loads(ln).get("event") == "span_start"]


def test_begin_detached_remote_parent_attrs():
    buf = io.StringIO()
    tid = protocol.mint_trace_id()
    with obs.tracing(buf):
        sp = obs.begin_detached(
            "job:j1", remote_parent={"trace": tid,
                                     "span": "00000000000000ab"})
        sp.end()
    rec = _spans_of(buf)[0]
    assert rec["trace"] == tid
    assert rec["remote_parent"] == "00000000000000ab"
    assert rec["parent"] is None  # the LOCAL tree is untouched


def test_begin_detached_all_zero_remote_span_drops_parent_only():
    buf = io.StringIO()
    tid = protocol.mint_trace_id()
    with obs.tracing(buf):
        obs.begin_detached("job:j1",
                           remote_parent={"trace": tid,
                                          "span": "0" * 16}).end()
    rec = _spans_of(buf)[0]
    assert rec["trace"] == tid
    assert "remote_parent" not in rec


def test_current_span_id_tracks_the_stack():
    assert obs.current_span_id() is None  # untraced
    buf = io.StringIO()
    with obs.tracing(buf):
        assert obs.current_span_id() is None  # traced, at root
        with obs.span("outer") as sp:
            assert obs.current_span_id() == sp.id


# ---------------------------------------------------------------------------
# federation: exact merge, loud mismatch, graceful degrade
# ---------------------------------------------------------------------------

def _replica_scrapes(n=3, per=150, seed=11):
    """n registries with shared metric shapes; returns (texts,
    all_observations)."""
    rng = random.Random(seed)
    texts, all_obs = [], []
    for i in range(n):
        reg = MetricRegistry()
        c = reg.counter("sheepd_requests_total", "r",
                        ("verb", "outcome"))
        c.inc(10 + i, verb="submit", outcome="ok")
        c.inc(i, verb="wait", outcome="error")
        reg.gauge("sheepd_queue_depth", "d").set(i + 1)
        h = reg.histogram("sheepd_request_latency_seconds", "lat",
                          ("tenant",))
        for _ in range(per):
            v = rng.expovariate(1.5)
            h.observe(v, tenant="t0")
            all_obs.append(v)
        texts.append(reg.render())
    return texts, all_obs


def test_federated_histogram_quantiles_are_exact():
    """The fleet quantile from merged buckets equals the quantile of
    ONE histogram fed every replica's observations — same-boundary
    cumulative buckets add exactly (to bucket resolution, which is
    identical by construction)."""
    texts, all_obs = _replica_scrapes()
    fed = federate_mod.federate(
        [(f"r{i}", t) for i, t in enumerate(texts)])
    ref = MetricRegistry().histogram("ref", "x", ("tenant",))
    for v in all_obs:
        ref.observe(v, tenant="t0")
    for q in (0.1, 0.5, 0.9, 0.99):
        got = federate_mod.fleet_quantile(
            fed, "sheepd_request_latency_seconds", q, {"tenant": "t0"})
        want = ref.quantile(q, tenant="t0")
        assert got == pytest.approx(want, abs=1e-12), q


def test_federated_counters_sum_and_gauges_get_replica_label():
    texts, _ = _replica_scrapes(n=2)
    fed = federate_mod.federate([("A", texts[0]), ("B", texts[1])])
    totals = {(ls["verb"], ls["outcome"]): v
              for ls, v in fed["samples"]["sheepd_requests_total"]}
    assert totals[("submit", "ok")] == 21   # 10 + 11
    assert totals[("wait", "error")] == 1   # 0 + 1
    depths = {ls["replica"]: v
              for ls, v in fed["samples"]["sheepd_queue_depth"]}
    assert depths == {"A": 1.0, "B": 2.0}


def test_federation_boundary_mismatch_is_a_loud_error():
    texts, _ = _replica_scrapes(n=1)
    other = MetricRegistry()
    h = other.histogram("sheepd_request_latency_seconds", "lat",
                        ("tenant",), buckets=(0.1, 1.0))
    h.observe(0.5, tenant="t0")
    with pytest.raises(federate_mod.FederationError,
                       match="MISMATCHED bucket boundaries"):
        federate_mod.federate([("A", texts[0]), ("B", other.render())])


def test_federation_partial_and_empty_scrapes_degrade_with_warning():
    texts, _ = _replica_scrapes(n=2)
    fed = federate_mod.federate(
        [("A", texts[0]), ("B", None), ("C", "   ")])
    assert fed["answered"] == ["A"]
    assert len(fed["warnings"]) == 2
    assert any("B" in w for w in fed["warnings"])
    up = {ls["replica"]: v
          for ls, v in fed["samples"]["sheep_federated_up"]}
    assert up == {"A": 1.0, "B": 0.0, "C": 0.0}
    # the single answering replica's data still merges
    assert fed["samples"]["sheepd_requests_total"]


def test_federated_render_round_trips_through_the_parser():
    texts, _ = _replica_scrapes(n=2)
    fed = federate_mod.federate([("A", texts[0]), ("B", texts[1])])
    rt = parse_prometheus(federate_mod.render_federated(fed))
    refed = {"samples": rt}
    for q in (0.5, 0.99):
        assert federate_mod.fleet_quantile(
            refed, "sheepd_request_latency_seconds", q,
            {"tenant": "t0"}) == pytest.approx(
            federate_mod.fleet_quantile(
                fed, "sheepd_request_latency_seconds", q,
                {"tenant": "t0"}), abs=1e-12)


def test_fleet_metrics_cli_merges_saved_scrapes(tmp_path, capsys):
    texts, _ = _replica_scrapes(n=2)
    paths = []
    for i, t in enumerate(texts):
        p = tmp_path / f"r{i}.txt"
        p.write_text(t)
        paths.append(str(p))
    rc = federate_mod.main(paths + [
        "--quantile", "sheepd_request_latency_seconds:0.5:tenant=t0"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "sheepd_requests_total" in out
    assert "# quantile sheepd_request_latency_seconds:0.5" in out


# ---------------------------------------------------------------------------
# --stitch: cross-process trace trees
# ---------------------------------------------------------------------------

TID = "ab" * 16


def _write_jsonl(path, events):
    path.write_text("".join(json.dumps(e) + "\n" for e in events))
    return str(path)


def _failover_files(tmp_path):
    client = [
        {"event": "manifest", "ts": 1.0},
        {"event": "span_start", "ts": 1.0, "span": "fleet_request",
         "id": 1, "parent": None, "trace": TID, "tenant": "t0"},
        {"event": "span_start", "ts": 3.0, "span": "fleet_failover",
         "id": 2, "parent": 1, "trace": TID, "from_endpoint": "A",
         "from_job": "j1"},
        {"event": "span_end", "ts": 4.0, "span": "fleet_failover",
         "id": 2, "parent": 1, "secs": 1.0, "endpoint": "B"},
        {"event": "span_end", "ts": 5.0, "span": "fleet_request",
         "id": 1, "parent": None, "secs": 4.0},
    ]
    killed = [
        {"event": "manifest", "ts": 1.5},
        {"event": "span_start", "ts": 1.5, "span": "job:j1", "id": 1,
         "parent": None, "trace": TID,
         "remote_parent": "0000000000000001", "job": "j1"},
        {"event": "span_start", "ts": 1.6, "span": "build", "id": 2,
         "parent": 1, "trace": TID},
    ]  # no span_end: SIGKILL mid-build
    survivor = [
        {"event": "manifest", "ts": 3.2},
        {"event": "span_start", "ts": 3.2, "span": "job:j1", "id": 1,
         "parent": None, "trace": TID,
         "remote_parent": "0000000000000001", "job": "j1"},
        {"event": "span_end", "ts": 4.1, "span": "job:j1", "id": 1,
         "parent": None, "secs": 0.9, "state": "DONE"},
    ]
    return [_write_jsonl(tmp_path / "client.jsonl", client),
            _write_jsonl(tmp_path / "replica_a.jsonl", killed),
            _write_jsonl(tmp_path / "replica_b.jsonl", survivor)]


def _trace_report():
    return trace_report


def test_stitch_builds_one_tree_with_failover_seam(tmp_path):
    tr = _trace_report()
    trees = tr.stitch_traces(_failover_files(tmp_path))
    assert list(trees) == [TID]
    t = trees[TID]
    assert tr.stitch_check(trees) == []
    assert len(t["roots"]) == 1
    root = t["roots"][0]
    assert root["node"]["name"] == "fleet_request"
    assert root["file"] == "client.jsonl"
    kids = sorted(root["stitch_children"],
                  key=lambda e: e["node"]["ts"])
    names = [(e["node"]["name"], e["file"]) for e in kids]
    assert names == [("job:j1", "replica_a.jsonl"),
                     ("fleet_failover", "client.jsonl"),
                     ("job:j1", "replica_b.jsonl")]
    assert kids[0]["node"].get("unclosed")        # the killed replica
    assert not kids[2]["node"].get("unclosed")    # the survivor
    # the killed job's local child rode along via the parent link
    sub = [c["node"]["name"] for c in kids[0]["stitch_children"]]
    assert sub == ["build"]


def test_stitch_cli_check_green_and_missing_file_fails(tmp_path,
                                                       capsys):
    tr = _trace_report()
    files = _failover_files(tmp_path)
    assert tr.main(["--stitch"] + files + ["--check"]) == 0
    out = capsys.readouterr().out
    assert "fleet_request [client.jsonl]" in out
    assert out.count("job:j1") == 2
    # drop the client file: both job spans' remote parents dangle
    assert tr.main(["--stitch", files[1], files[2], "--check"]) == 3


def test_stitch_reads_every_appended_run(tmp_path):
    """A restarted daemon appends a second run to the same trace file;
    a graft living in run 2 must still stitch (parse_trace alone only
    reads the last run)."""
    tr = _trace_report()
    files = _failover_files(tmp_path)
    # prepend an unrelated earlier run to the survivor's file
    earlier = [
        {"event": "manifest", "ts": 0.1},
        {"event": "span_start", "ts": 0.1, "span": "serve", "id": 1,
         "parent": None},
        {"event": "span_end", "ts": 0.2, "span": "serve", "id": 1,
         "parent": None, "secs": 0.1},
    ]
    merged = "".join(json.dumps(e) + "\n" for e in earlier)
    merged += (tmp_path / "replica_b.jsonl").read_text()
    (tmp_path / "replica_b.jsonl").write_text(merged)
    trees = tr.stitch_traces(files)
    assert tr.stitch_check(trees) == []
    assert len(trees[TID]["roots"]) == 1


def test_last_errors_names_the_fleet_trace(capsys):
    tr = _trace_report()
    rep = {"path": "x.jsonl", "parsed": {"flight_dumps": [
        {"event": "flight_dump", "job": "j1", "reason": "job_failed",
         "trace": TID, "events": [{"t": 1.0, "ev": "job_phase"}]}]}}
    tr.print_last_errors([rep], 8, sys.stdout)
    assert f"trace={TID}" in capsys.readouterr().out


# ---------------------------------------------------------------------------
# SLO gate
# ---------------------------------------------------------------------------

def _slo():
    return slo_check


def _slo_fed(lat=(0.02, 0.2, 1.4), errors=5, ok=95, throttled=3):
    reg = MetricRegistry()
    c = reg.counter("sheepd_requests_total", "r", ("verb", "outcome"))
    if ok:
        c.inc(ok, verb="submit", outcome="ok")
    if errors:
        c.inc(errors, verb="wait", outcome="error")
    h = reg.histogram("sheepd_request_latency_seconds", "lat",
                      ("tenant",))
    for v in lat:
        h.observe(v, tenant="t0")
    t = reg.counter("sheepd_update_throttled_total", "t", ("tenant",))
    if throttled:
        t.inc(throttled, tenant="t0")
    return federate_mod.federate([("A", reg.render())])


def test_slo_evaluate_pass_and_burn():
    slo = _slo()
    fed = _slo_fed()
    rules = {"tenants": {"t0": {"p99_latency_s": 10.0,
                                "max_update_throttled": 10},
                         "*": {"max_error_rate": 0.2}}}
    verdicts = slo.evaluate(rules, fed)
    assert all(v["ok"] for v in verdicts)
    rate = next(v for v in verdicts if v["bound"] == "max_error_rate")
    assert rate["value"] == pytest.approx(0.05)
    tight = slo.evaluate(
        {"tenants": {"t0": {"p99_latency_s": 0.001}}}, fed)
    assert not tight[0]["ok"]


def test_slo_no_data_passes_with_note_not_silently():
    slo = _slo()
    fed = _slo_fed(lat=(), errors=0, ok=0, throttled=0)
    verdicts = slo.evaluate(
        {"tenants": {"t9": {"p99_latency_s": 1.0},
                     "*": {"max_error_rate": 0.1}}}, fed)
    for v in verdicts:
        assert v["ok"] and v["value"] is None and v["note"]


def test_slo_unknown_bound_is_a_rule_error():
    slo = _slo()
    with pytest.raises(ValueError, match="unknown bound"):
        slo.evaluate({"tenants": {"t0": {"p99_latnecy_s": 1.0}}},
                     _slo_fed())


def test_slo_cli_exit_codes(tmp_path, capsys):
    slo = _slo()
    reg = MetricRegistry()
    h = reg.histogram("sheepd_request_latency_seconds", "lat",
                      ("tenant",))
    h.observe(0.3, tenant="t0")
    scrape = tmp_path / "a.txt"
    scrape.write_text(reg.render())
    ok_rules = tmp_path / "ok.json"
    ok_rules.write_text(json.dumps(
        {"tenants": {"t0": {"p99_latency_s": 60.0}}}))
    tight = tmp_path / "tight.json"
    tight.write_text(json.dumps(
        {"tenants": {"t0": {"p99_latency_s": 0.001}}}))
    assert slo.main(["--rules", str(ok_rules), str(scrape)]) == 0
    assert slo.main(["--rules", str(tight), str(scrape)]) == 2
    out = capsys.readouterr().out
    assert "BURN" in out


# ---------------------------------------------------------------------------
# fleet client: routing-scrape TTL cache
# ---------------------------------------------------------------------------

class _StubClient:
    def __init__(self, text):
        self.text = text
        self.metrics_calls = 0

    def metrics(self):
        self.metrics_calls += 1
        return self.text


def test_fleet_load_ttl_cache_coalesces_scrapes(monkeypatch):
    from sheep_tpu.server.client import FleetClient

    reg = MetricRegistry()
    reg.gauge("sheepd_queue_depth", "d").set(2)
    reg.gauge("sheepd_active_jobs", "a").set(1)
    stub = _StubClient(reg.render())
    fc = FleetClient(["ep-a"])
    monkeypatch.setattr(fc, "_client", lambda ep: stub)

    fc.scrape_ttl_s = 60.0
    buf = io.StringIO()
    with obs.tracing(buf) as tracer:
        first = fc._load("ep-a")
        for _ in range(4):
            assert fc._load("ep-a") == first  # served from cache
        assert stub.metrics_calls == 1
        assert tracer.counters.get("fleet_scrape_cache_hits") == 4
        assert tracer.counters.get("fleet_scrape_ms", 0) > 0

    fc.scrape_ttl_s = 0.0  # TTL off: every call scrapes
    fc._load_cache.clear()
    fc._load("ep-a")
    fc._load("ep-a")
    assert stub.metrics_calls == 3


def test_fleet_load_caches_failures_too(monkeypatch):
    from sheep_tpu.server.client import FleetClient

    calls = {"n": 0}

    class _Dead:
        def metrics(self):
            calls["n"] += 1
            raise OSError("down")

    fc = FleetClient(["ep-a"])
    monkeypatch.setattr(fc, "_client", lambda ep: _Dead())
    fc.scrape_ttl_s = 60.0
    assert fc._load("ep-a") is None
    assert fc._load("ep-a") is None  # cached verdict, no re-dial
    assert calls["n"] == 1
