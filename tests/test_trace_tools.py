"""tools/trace_report.py + tools/bench_regress.py (ISSUE 3 toolchain):
golden render, well-formedness checks, dispatch attribution, regression
gate pass/fail."""

import importlib.util
import io
import json
import os
import sys
from contextlib import redirect_stdout

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
GOLDEN = os.path.join(REPO, "tests", "golden")


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


trace_report = _load_tool("trace_report")
bench_regress = _load_tool("bench_regress")


def _run_report(argv):
    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = trace_report.main(argv)
    return rc, buf.getvalue()


# -- trace_report ----------------------------------------------------------

def test_trace_report_golden():
    """Pinned render of a recorded trace: self/total decomposition,
    x-count aggregation, counter deltas net of children, heartbeat and
    final-counter summaries. The golden path is relative, so run with
    the repo-relative path the fixture was recorded with."""
    rel = os.path.join("tests", "golden", "trace_small.jsonl")
    cwd = os.getcwd()
    os.chdir(REPO)
    try:
        rc, out = _run_report([rel, "--check"])
    finally:
        os.chdir(cwd)
    assert rc == 0
    expect = open(os.path.join(GOLDEN, "trace_report.txt")).read()
    assert out == expect


def test_trace_report_json_tree_structure():
    rc, out = _run_report(
        [os.path.join(GOLDEN, "trace_small.jsonl"), "--json"])
    assert rc == 0
    doc = json.loads(out)
    t = doc["traces"][0]
    assert t["heartbeats"] == 2 and not t["unclosed"]
    run = t["spans"][0]
    assert run["name"] == "run" and run["total_s"] == 4.6
    part = run["children"][0]
    build = next(c for c in part["children"] if c["name"] == "build")
    seg = build["children"][0]
    assert seg["count"] == 2 and seg["total_s"] == 3.0
    assert seg["counters"] == {"device_rounds": 22, "host_syncs": 2}
    # build's self-delta nets out its children's counters entirely
    assert build["counters"] == {}
    assert abs(build["self_s"] - 0.2) < 1e-9


def test_trace_report_appended_runs_not_merged(tmp_path):
    """--trace appends and span ids restart per run: a two-run file must
    report the LAST run (with an n_runs note), never merge both trees
    under colliding ids (review finding)."""
    src = open(os.path.join(GOLDEN, "trace_small.jsonl")).read()
    p = str(tmp_path / "two.jsonl")
    open(p, "w").write(src + src)  # rerun appended to the same file
    rc, out = _run_report([p, "--check"])
    assert rc == 0, "each run alone is complete; no merge corruption"
    assert "holds 2 appended runs" in out
    rc, out = _run_report([p, "--json"])
    t = json.loads(out)["traces"][0]
    assert t["n_runs"] == 2 and not t["unclosed"]
    run = t["spans"][0]
    assert run["count"] == 1 and run["total_s"] == 4.6, \
        "one run's tree, not two runs summed"


def test_trace_report_deferred_manifest_not_split(tmp_path):
    """Multi-host CLI traces open the root span BEFORE the manifest
    (deferred until after jax.distributed.initialize): that ordering is
    ONE run, not two — splitting there orphaned the root's span_end and
    mis-reported a valid trace as malformed (review finding)."""
    p = str(tmp_path / "mh.jsonl")
    with open(p, "w") as f:
        for rec in [
            {"event": "span_start", "ts": 1.0, "span": "run", "id": 1,
             "parent": None},
            {"event": "manifest", "ts": 1.2, "backend": "tpu-sharded"},
            {"event": "span_start", "ts": 1.3, "span": "partition",
             "id": 2, "parent": 1},
            {"event": "span_end", "ts": 2.0, "span": "partition", "id": 2,
             "parent": 1, "secs": 0.7},
            {"event": "heartbeat", "ts": 2.0, "seq": 0, "final": True},
            {"event": "span_end", "ts": 2.1, "span": "run", "id": 1,
             "parent": None, "secs": 1.1},
        ]:
            f.write(json.dumps(rec) + "\n")
    rc, out = _run_report([p, "--check"])
    assert rc == 0, out
    assert "appended runs" not in out and "UNCLOSED" not in out


def test_trace_report_appended_runs_keep_their_manifest(tmp_path):
    """When a DEAD run (unclosed spans) is rerun into the same file, the
    second run's manifest precedes its first span; the split on span-id
    collision must carry that manifest into the new segment."""
    p = str(tmp_path / "dead_then_ok.jsonl")
    dead = [
        {"event": "manifest", "ts": 1.0, "backend": "tpu", "git_sha": "a"},
        {"event": "span_start", "ts": 1.0, "span": "run", "id": 1,
         "parent": None},
    ]
    ok = [
        {"event": "manifest", "ts": 9.0, "backend": "tpu", "git_sha": "b"},
        {"event": "span_start", "ts": 9.1, "span": "run", "id": 1,
         "parent": None},
        {"event": "span_end", "ts": 9.9, "span": "run", "id": 1,
         "parent": None, "secs": 0.8},
        {"event": "heartbeat", "ts": 9.9, "seq": 0, "final": True},
    ]
    with open(p, "w") as f:
        for rec in dead + ok:
            f.write(json.dumps(rec) + "\n")
    rc, out = _run_report([p, "--json"])
    assert rc == 0
    t = json.loads(out)["traces"][0]
    assert t["n_runs"] == 2 and t["manifest"]["git_sha"] == "b"
    assert not t["unclosed"] and not t["check_failures"]


def test_trace_report_flags_unclosed_spans(tmp_path):
    """A killed run leaves span_starts without ends; the report must
    say so (that is the dead-vs-slow distinction) and --check must
    fail."""
    p = str(tmp_path / "dead.jsonl")
    with open(p, "w") as f:
        f.write(json.dumps({"event": "manifest", "ts": 10.0}) + "\n")
        f.write(json.dumps({"event": "span_start", "ts": 10.0,
                            "span": "build", "id": 1,
                            "parent": None}) + "\n")
        f.write(json.dumps({"event": "heartbeat", "ts": 55.0,
                            "seq": 0}) + "\n")
    rc, out = _run_report([p])
    assert rc == 0 and "UNCLOSED" in out and "45.0" in out
    rc, _ = _run_report([p, "--check"])
    assert rc == 3


def test_trace_report_orphan_end_is_malformed(tmp_path):
    p = str(tmp_path / "bad.jsonl")
    with open(p, "w") as f:
        f.write(json.dumps({"event": "span_end", "ts": 1.0, "span": "x",
                            "id": 9, "secs": 1.0}) + "\n")
    rc, _ = _run_report([p])
    assert rc == 2


def test_trace_report_tolerates_truncated_last_line(tmp_path):
    src = open(os.path.join(GOLDEN, "trace_small.jsonl")).read()
    p = str(tmp_path / "cut.jsonl")
    open(p, "w").write(src + '{"event": "span_start", "ts": 99')
    rc, out = _run_report([p, "--check"])
    assert rc == 0 and "warning" not in out


def _mini_trace(path, wall_s, syncs, rounds):
    with open(path, "w") as f:
        for rec in [
            {"event": "manifest", "ts": 0.0},
            {"event": "span_start", "ts": 0.0, "span": "build", "id": 1,
             "parent": None},
            {"event": "span_end", "ts": wall_s, "span": "build", "id": 1,
             "parent": None, "secs": wall_s},
            {"event": "counters", "ts": wall_s, "host_syncs": syncs,
             "device_rounds": rounds},
        ]:
            f.write(json.dumps(rec) + "\n")


def test_trace_report_dispatch_attribution(tmp_path):
    """Two traces at different dispatch mixes solve the 2x2 count x
    round-cost system exactly: A(10s, 8 syncs, 20 rounds) and
    B(7s, 2 syncs, 20 rounds) -> 0.5 s/dispatch, 0.3 s/round."""
    a, b = str(tmp_path / "a.jsonl"), str(tmp_path / "b.jsonl")
    _mini_trace(a, 10.0, 8, 20)
    _mini_trace(b, 7.0, 2, 20)
    rc, out = _run_report([a, b, "--json"])
    assert rc == 0
    att = json.loads(out)["attribution"]
    assert att["per_dispatch_s"] == pytest.approx(0.5)
    assert att["per_round_s"] == pytest.approx(0.3)


def test_trace_report_attribution_degenerate(tmp_path):
    a, b = str(tmp_path / "a.jsonl"), str(tmp_path / "b.jsonl")
    _mini_trace(a, 10.0, 8, 20)
    _mini_trace(b, 5.0, 8, 20)  # same mix: nothing to attribute
    rc, out = _run_report([a, b, "--json"])
    assert rc == 0 and json.loads(out)["attribution"] is None


# -- bench_regress ---------------------------------------------------------

BASE = {"metric": "edges/sec partitioned (RMAT-20, k=64, tpu vs CPU)",
        "value": 1.0e6, "unit": "edges/sec", "vs_baseline": 2.0,
        "r_colo_est": 2.4, "host_syncs": 10, "device_rounds": 40,
        "rtt_ms": 5.0}


def _write(tmp_path, name, doc):
    p = str(tmp_path / name)
    json.dump(doc, open(p, "w"))
    return p


def test_bench_regress_pass(tmp_path):
    old = _write(tmp_path, "old.json", {"n": 1, "parsed": BASE})
    new = _write(tmp_path, "new.json",
                 {**BASE, "value": 1.05e6, "rtt_ms": 50.0})
    rc = bench_regress.main([new, old, "--threshold", "0.15"])
    assert rc == 0, "faster run + environmental rtt swing is a pass"


def test_bench_regress_detects_value_drop(tmp_path):
    old = _write(tmp_path, "old.json", BASE)
    new = _write(tmp_path, "new.json", {**BASE, "value": 0.7e6})
    assert bench_regress.main([new, old, "--threshold", "0.15"]) == 2
    # same drop passes a looser gate
    assert bench_regress.main([new, old, "--threshold", "0.40"]) == 0


def test_bench_regress_detects_dispatch_count_rise(tmp_path):
    old = _write(tmp_path, "old.json", BASE)
    new = _write(tmp_path, "new.json", {**BASE, "host_syncs": 30})
    assert bench_regress.main([new, old]) == 2


def test_bench_regress_gates_host_blocked_ms(tmp_path):
    """The dispatch-overlap contract field (ISSUE 4): host_blocked_ms
    is gated higher-is-worse like host_syncs; device_gap_ms is
    environmental (link-quality-coupled) and never gates."""
    old = _write(tmp_path, "old.json",
                 {**BASE, "host_blocked_ms": 100.0, "device_gap_ms": 10.0})
    new = _write(tmp_path, "new.json",
                 {**BASE, "host_blocked_ms": 200.0, "device_gap_ms": 10.0})
    assert bench_regress.main([new, old]) == 2
    drop = _write(tmp_path, "drop.json",
                  {**BASE, "host_blocked_ms": 40.0, "device_gap_ms": 10.0})
    assert bench_regress.main([drop, old]) == 0
    gap = _write(tmp_path, "gap.json",
                 {**BASE, "host_blocked_ms": 100.0,
                  "device_gap_ms": 900.0})
    assert bench_regress.main([gap, old]) == 0


def test_bench_regress_gates_warm_path(tmp_path):
    """The warm-vs-cold served-request contract (ISSUE 10): warm_up_s
    (the cold jit tax) and warm_request_s (the warm served wall) gate
    lower-is-better like host_blocked_ms; cold_request_s rides as info
    (it is warm_up_s under another name — double-gating one quantity
    would double-alarm one regression)."""
    old = _write(tmp_path, "old.json",
                 {**BASE, "warm_up_s": 10.0, "warm_request_s": 6.0,
                  "cold_request_s": 10.0})
    slow_warm = _write(tmp_path, "slow_warm.json",
                       {**BASE, "warm_up_s": 10.0,
                        "warm_request_s": 9.0, "cold_request_s": 10.0})
    assert bench_regress.main([slow_warm, old]) == 2
    slow_cold = _write(tmp_path, "slow_cold.json",
                       {**BASE, "warm_up_s": 20.0,
                        "warm_request_s": 6.0, "cold_request_s": 20.0})
    assert bench_regress.main([slow_cold, old]) == 2
    ok = _write(tmp_path, "ok.json",
                {**BASE, "warm_up_s": 9.0, "warm_request_s": 5.5,
                 "cold_request_s": 9.0})
    assert bench_regress.main([ok, old]) == 0


def test_bench_regress_rise_from_zero_is_gated(tmp_path):
    """old host_syncs == 0 has no relative change, but 0 -> 500 is a
    real scheduling regression and must not slip through the undefined
    ratio (review finding)."""
    old = _write(tmp_path, "old.json", {**BASE, "host_syncs": 0})
    new = _write(tmp_path, "new.json", {**BASE, "host_syncs": 500})
    assert bench_regress.main([new, old]) == 2
    same = _write(tmp_path, "same.json", {**BASE, "host_syncs": 0})
    assert bench_regress.main([same, old]) == 0


def test_bench_regress_gates_dispatch_retries(tmp_path):
    """ISSUE 9 contract: dispatch_retries is higher-is-worse. A healthy
    capture has 0, so any movement off zero gates absolutely (the
    old==0 rule); the degradation info fields report but never gate."""
    old = _write(tmp_path, "old.json", {**BASE, "dispatch_retries": 0})
    new = _write(tmp_path, "new.json", {**BASE, "dispatch_retries": 3})
    assert bench_regress.main([new, old]) == 2
    same = _write(tmp_path, "same.json", {**BASE, "dispatch_retries": 0})
    assert bench_regress.main([same, old]) == 0


def test_bench_regress_degradation_fields_are_info_only(tmp_path):
    """degraded_dispatch_batch / device_loss_recoveries /
    checkpoint_degraded are consequences of environmental faults, not
    code regressions: visible in the rows, never gating."""
    old = _write(tmp_path, "old.json",
                 {**BASE, "degraded_dispatch_batch": 8,
                  "device_loss_recoveries": 0,
                  "checkpoint_degraded": 0})
    new = _write(tmp_path, "new.json",
                 {**BASE, "degraded_dispatch_batch": 1,
                  "device_loss_recoveries": 2,
                  "checkpoint_degraded": 1})
    assert bench_regress.main([new, old]) == 0
    import io
    from contextlib import redirect_stdout

    buf = io.StringIO()
    with redirect_stdout(buf):
        bench_regress.main([new, old])
    out = buf.getvalue()
    assert "degraded_dispatch_batch" in out and "info" in out


def test_bench_regress_skipped_incomparable_fields_reported(tmp_path):
    """ISSUE 13 satellite: a field present in exactly one capture (the
    cpu-jax fallback emits fewer contract fields than a real-chip run)
    compares NOTHING — the pass must say so instead of reading as full
    coverage."""
    old = _write(tmp_path, "old.json",
                 {**BASE, "host_blocked_ms": 120.0, "warm_up_s": 9.0})
    new = _write(tmp_path, "new.json", BASE)  # fallback: fields absent
    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = bench_regress.main([new, old])
    out = buf.getvalue()
    assert rc == 0
    assert "skipped-incomparable: host_blocked_ms, warm_up_s" in out
    # json shape carries them too
    buf = io.StringIO()
    with redirect_stdout(buf):
        bench_regress.main([new, old, "--json"])
    doc = json.loads(buf.getvalue())
    assert doc["skipped"] == ["host_blocked_ms", "warm_up_s"]
    # fields absent from BOTH captures are not "skipped" — there was
    # nothing to compare and nothing partial about it
    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = bench_regress.main([_write(tmp_path, "n2.json", BASE),
                                 _write(tmp_path, "o2.json", BASE)])
    assert rc == 0 and "skipped-incomparable" not in buf.getvalue()


def test_bench_regress_incomparable_metrics_pass(tmp_path):
    """A cpu-jax fallback row must never false-alarm against a real
    accelerator row — different metric strings are vacuously PASS."""
    old = _write(tmp_path, "old.json", BASE)
    new = _write(tmp_path, "new.json",
                 {**BASE, "metric": "edges/sec (RMAT-18, k=64, cpu)",
                  "value": 100.0})
    assert bench_regress.main([new, old]) == 0


def test_bench_regress_null_parsed_is_error(tmp_path):
    old = _write(tmp_path, "old.json", {"n": 1, "parsed": None})
    new = _write(tmp_path, "new.json", BASE)
    assert bench_regress.main([new, old]) == 1


def test_bench_regress_raw_jsonl_capture(tmp_path):
    """bench.py stdout shape (stderr noise + one contract line) loads
    too."""
    p = str(tmp_path / "raw.json")
    with open(p, "w") as f:
        f.write("some stderr-ish noise\n")
        f.write(json.dumps(BASE) + "\n")
    old = _write(tmp_path, "old.json", BASE)
    assert bench_regress.main([p, old]) == 0
