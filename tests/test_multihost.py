"""Multi-host distributed execution (SURVEY.md §4.4, §7 step 6).

Spawns real jax.distributed processes on localhost (the standard
no-cluster trick: N CPU processes x M virtual CPU devices each) and checks
the sharded pipeline produces the exact same tree/partition/scores as the
single-process oracle — the rebuild's equivalent of the reference's
``mpirun -n N`` localhost runs. Also covers per-process checkpointing
with fault injection and the one-step-skew resume reconciliation.
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


_MP_PROBE = r"""
import os, sys
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
import jax
jax.config.update("jax_platforms", "cpu")
addr, pid = sys.argv[1], int(sys.argv[2])
jax.distributed.initialize(coordinator_address=addr, num_processes=2,
                           process_id=pid)
import numpy as np
from jax.experimental import multihost_utils
out = multihost_utils.process_allgather(np.array([pid], np.int32))
assert sorted(np.asarray(out).ravel().tolist()) == [0, 1]
print("MULTIPROC_OK")
"""


def _cpu_multiprocess_supported():
    """Some jaxlib CPU builds reject every cross-process computation
    with 'Multiprocess computations aren't implemented on the CPU
    backend' — in such environments ALL of this module's tests fail
    for the same environmental reason. Probe once with a tiny
    2-process allgather; on failure the module skips with the probe's
    last error line as the reason (importorskip-style)."""
    addr = f"127.0.0.1:{_free_port()}"
    env = {**os.environ, "PYTHONPATH": REPO}
    env.pop("JAX_PLATFORMS", None)
    procs = [subprocess.Popen(
        [sys.executable, "-c", _MP_PROBE, addr, str(pid)],
        cwd=REPO, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True)
        for pid in range(2)]
    outs = []
    ok = True
    for p in procs:
        try:
            out, _ = p.communicate(timeout=180)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            return False, "2-process CPU probe timed out"
        outs.append(out or "")
        ok = ok and p.returncode == 0 and "MULTIPROC_OK" in out
    if ok:
        return True, ""
    tail = next((ln for o in outs
                 for ln in reversed(o.strip().splitlines())
                 if ln.strip()), "no output")
    return False, tail[:300]


@pytest.fixture(scope="module", autouse=True)
def _require_cpu_multiprocess():
    """Module-wide skip gate, evaluated LAZILY: fixtures only run when a
    test here actually executes, so --collect-only and deselected runs
    never pay the probe's subprocess spawns."""
    ok, why = _cpu_multiprocess_supported()
    if not ok:
        pytest.skip(f"jaxlib lacks CPU multiprocess support here: {why}")

WORKER = r"""
import json, os, sys
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
import jax
jax.config.update("jax_platforms", "cpu")
(addr, pid, nprocs, out_path, ckdir, fault, resume) = sys.argv[1:8]
graph_path = sys.argv[8] if len(sys.argv) > 8 else ""
kind = sys.argv[9] if len(sys.argv) > 9 else "sharded"
pid, nprocs = int(pid), int(nprocs)
jax.distributed.initialize(coordinator_address=addr, num_processes=nprocs,
                           process_id=pid)
assert jax.process_count() == nprocs
assert jax.device_count() == 2 * nprocs

import numpy as np
from sheep_tpu.io.edgestream import EdgeStream
from sheep_tpu.io import generators
from sheep_tpu.parallel.mesh import shards_mesh
from sheep_tpu.parallel.pipeline import ShardedPipeline
from sheep_tpu.utils.checkpoint import Checkpointer
from sheep_tpu.utils.fault import ENV_VAR, InjectedFault

if fault:
    os.environ[ENV_VAR] = fault

kw = {}
if ckdir:
    kw = {"checkpointer": Checkpointer(ckdir, every=1, process=pid),
          "resume": resume == "1"}

n = 1 << 9
if graph_path:
    stream = EdgeStream.open(graph_path, n_vertices=n)
else:
    stream = EdgeStream.from_array(generators.rmat(9, 8, seed=21), n_vertices=n)
if kind == "bigv":
    from sheep_tpu.parallel.bigv import BigVPipeline

    pipe = BigVPipeline(n, chunk_edges=128, mesh=shards_mesh())
else:
    pipe = ShardedPipeline(n, chunk_edges=128, mesh=shards_mesh())
try:
    out = pipe.run(stream, k=8, comm_volume=True, **kw)
except InjectedFault:
    sys.exit(42)
except ValueError as exc:
    print("ValueError:", exc, flush=True)
    sys.exit(43)
json.dump({
    "process": pid,
    "edge_cut": int(out["edge_cut"]),
    "total_edges": int(out["total_edges"]),
    "comm_volume": int(out["comm_volume"]),
    "balance": float(out["balance"]),
    "assignment": out["assignment"].tolist(),
    "parent": out["parent"].tolist(),
}, open(out_path, "w"))
"""


def _spawn(nprocs, tmp_path, tag, ckdir="", fault="", resume="0", graph="",
           kind="sharded"):
    addr = f"127.0.0.1:{_free_port()}"
    env = {**os.environ, "PYTHONPATH": REPO}
    env.pop("JAX_PLATFORMS", None)
    procs, outs, logs = [], [], []
    for pid in range(nprocs):
        out_path = str(tmp_path / f"out_{tag}_{pid}.json")
        log_path = str(tmp_path / f"log_{tag}_{pid}.txt")
        outs.append(out_path)
        logs.append(log_path)
        # log to files, not pipes: a worker that fills a pipe buffer would
        # stall its collectives and deadlock the whole rendezvous
        log_f = open(log_path, "w")
        procs.append(subprocess.Popen(
            [sys.executable, "-c", WORKER, addr, str(pid), str(nprocs),
             out_path, ckdir, fault, resume, graph, kind],
            cwd=REPO, env=env, stdout=log_f, stderr=subprocess.STDOUT))
    rcs = []
    for p in procs:
        try:
            p.wait(timeout=420)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("multihost worker timed out")
        rcs.append(p.returncode)
    errs = [open(lg).read()[-2000:] for lg in logs]
    return rcs, outs, errs


def _oracle():
    from sheep_tpu.core import pure
    from sheep_tpu.io import generators

    e = generators.rmat(9, 8, seed=21)
    n = 1 << 9
    ref = pure.partition_arrays(e, 8, n=n)
    parent = pure.build_elim_tree(
        e, pure.elimination_order(pure.degrees(e, n))).parent
    return ref, parent


def _check(outs, ref, expect_parent):
    results = [json.load(open(o)) for o in outs]
    for r in results:
        assert r["total_edges"] == ref.total_edges
        assert r["edge_cut"] == ref.edge_cut
        assert r["comm_volume"] == ref.comm_volume
        assert np.array_equal(np.asarray(r["parent"]), expect_parent), \
            "multi-host tree != sequential oracle"
        assert np.array_equal(np.asarray(r["assignment"]), ref.assignment)
    for r in results:
        r.pop("process")
    assert all(r == results[0] for r in results[1:])


@pytest.mark.parametrize("nprocs", [2, 3])
def test_two_process_run_matches_single_process(tmp_path, nprocs):
    rcs, outs, errs = _spawn(nprocs, tmp_path, "plain")
    assert rcs == [0] * nprocs, errs
    ref, expect_parent = _oracle()
    _check(outs, ref, expect_parent)


@pytest.mark.parametrize("kind", ["sharded", "bigv"])
def test_text_byte_range_sharding_matches_oracle(tmp_path, kind):
    """Multi-process TEXT ingestion takes the byte-span path (each process
    parses ~file/P, VERDICT r1 item 7) and must reproduce the oracle's
    tree/scores exactly — byte spans regroup edges into different chunks
    than round-robin, which the order-independent build must not notice.
    Covered for both the replicated-table and vertex-sharded pipelines."""
    from sheep_tpu.io import formats, generators

    gp = str(tmp_path / "g.edges")
    formats.write_edges(gp, generators.rmat(9, 8, seed=21))
    rcs, outs, errs = _spawn(2, tmp_path, f"textspan-{kind}", graph=gp,
                             kind=kind)
    assert rcs == [0, 0], errs
    ref, expect_parent = _oracle()
    _check(outs, ref, expect_parent)


@pytest.mark.parametrize("nprocs", [2, 3])
def test_bigv_multihost_matches_oracle(tmp_path, nprocs):
    """The vertex-sharded bigv pipeline across real processes: every table
    is block-sharded over ALL processes' devices and the routed
    collectives ride the distributed mesh, yet the tree/partition/scores
    must equal the sequential oracle exactly (3 procs x 2 devices also
    exercises a non-power-of-2 routing fan-out)."""
    rcs, outs, errs = _spawn(nprocs, tmp_path, f"bigv{nprocs}", kind="bigv")
    assert rcs == [0] * nprocs, errs
    ref, expect_parent = _oracle()
    _check(outs, ref, expect_parent)


@pytest.mark.parametrize("kind", ["sharded", "bigv"])
def test_multihost_fault_then_resume(tmp_path, kind):
    """Kill both workers mid-build via fault injection, then resume; the
    result must match the uninterrupted oracle exactly. For bigv the
    checkpoint state is each process's O(V/P) local block."""
    ckdir = str(tmp_path / "ck")
    rcs, _, errs = _spawn(2, tmp_path, "fault", ckdir=ckdir, fault="build:2",
                          kind=kind)
    assert rcs == [42, 42], errs

    rcs, outs, errs = _spawn(2, tmp_path, "resume", ckdir=ckdir, resume="1",
                             kind=kind)
    assert rcs == [0, 0], errs
    ref, expect_parent = _oracle()
    _check(outs, ref, expect_parent)


def test_multihost_resume_mismatch_fails_collectively(tmp_path):
    """A checkpoint fingerprint mismatch on ONE process must raise on ALL
    processes (via the reconcile ok-allgather), not kill that process alone
    and leave the rest hanging in their first collective (ADVICE round 1)."""
    import json as _json

    from sheep_tpu.utils.checkpoint import Checkpointer

    ckdir = str(tmp_path / "ck")
    rcs, _, errs = _spawn(2, tmp_path, "fault", ckdir=ckdir, fault="build:2")
    assert rcs == [42, 42], errs

    # corrupt process 1's fingerprint only: its resume_state mismatches
    # while process 0's is intact
    mpath = Checkpointer(ckdir, every=1, process=1)._manifest_path
    manifest = _json.load(open(mpath))
    manifest["meta"]["k"] = 99
    _json.dump(manifest, open(mpath, "w"))

    rcs, _, errs = _spawn(2, tmp_path, "mismatch", ckdir=ckdir, resume="1")
    assert rcs == [43, 43], f"expected collective ValueError on both: {errs}"


def test_multihost_resume_reconciles_one_step_skew(tmp_path):
    """If one process's manifest is a step ahead (crash between two
    processes' saves), resume must fall back to the common step via the
    retained previous checkpoint instead of desynchronizing."""
    from sheep_tpu.utils.checkpoint import Checkpointer

    ckdir = str(tmp_path / "ck")
    rcs, _, errs = _spawn(2, tmp_path, "fault", ckdir=ckdir, fault="build:3")
    assert rcs == [42, 42], errs

    # fabricate skew: process 1 "saved" one extra step before the crash
    ck1 = Checkpointer(ckdir, every=1, process=1)
    st = ck1.load()
    assert st is not None
    ck1.save(st.phase, st.chunk_idx + 4, st.arrays, st.meta)

    rcs, outs, errs = _spawn(2, tmp_path, "resume", ckdir=ckdir, resume="1")
    assert rcs == [0, 0], errs
    ref, expect_parent = _oracle()
    _check(outs, ref, expect_parent)


HIER_WORKER = r"""
import os, sys
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
import jax
jax.config.update("jax_platforms", "cpu")
addr, pid, nprocs, out_path, graph_path = sys.argv[1:6]
from sheep_tpu import cli
sys.exit(cli.main([
    "--input", graph_path, "--k-levels", "2,2", "--backend", "tpu-sharded",
    "--refine", "1", "--chunk-edges", "128", "--num-vertices", "512",
    "--no-comm-volume", "--json", "--output", out_path,
    "--coordinator", addr, "--num-processes", nprocs,
    "--process-id", pid]))
"""


def test_hierarchy_multihost_level0_matches_single_process(tmp_path):
    """--k-levels now composes with multi-host (ISSUE 8): level 0 runs
    flat through the sharded backend across processes and the recursion
    replays deterministically in lockstep on every process. Rank 0's
    written map must equal a single-process hierarchical run (the forest
    is backend-exact, so the cheap local backend is a valid oracle)."""
    import sheep_tpu
    from sheep_tpu.io import formats, generators

    gp = str(tmp_path / "hier_g.edges")
    formats.write_edges(gp, generators.rmat(9, 8, seed=21))
    out_path = str(tmp_path / "hier.parts")

    addr = f"127.0.0.1:{_free_port()}"
    env = {**os.environ, "PYTHONPATH": REPO}
    env.pop("JAX_PLATFORMS", None)
    procs, logs = [], []
    for pid in range(2):
        log_path = str(tmp_path / f"hier_log_{pid}.txt")
        logs.append(log_path)
        log_f = open(log_path, "w")
        procs.append(subprocess.Popen(
            [sys.executable, "-c", HIER_WORKER, addr, str(pid), "2",
             out_path, gp],
            cwd=REPO, env=env, stdout=log_f, stderr=subprocess.STDOUT))
    for p in procs:
        try:
            p.wait(timeout=420)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("hierarchy multihost worker timed out")
    errs = [open(lg).read()[-2000:] for lg in logs]
    assert [p.returncode for p in procs] == [0, 0], errs

    local_be = "cpu" if "cpu" in sheep_tpu.list_backends() else "pure"
    expect = sheep_tpu.partition_hierarchical(
        gp, [2, 2], backend=local_be, refine=1, chunk_edges=128,
        n_vertices=512, comm_volume=False)
    got = formats.read_partition(out_path)
    assert np.array_equal(got, np.asarray(expect.assignment)), errs
