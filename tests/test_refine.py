"""Refinement post-pass (ops/refine.py) — an extension beyond the
reference's surface, so the contract here is self-imposed: the refined
cut must NEVER exceed the unrefined cut (round-level rollback), no part
may grow past the balance cap, and the assignment stays valid.
"""

import numpy as np
import pytest

import sheep_tpu
from sheep_tpu.backends.base import get_backend
from sheep_tpu.io import formats, generators
from sheep_tpu.io.edgestream import EdgeStream
from sheep_tpu.ops.refine import refine_assignment


CASES = {
    "karate": (generators.karate_club(), 34, 2),
    "grid": (generators.grid_graph(16, 16), 256, 4),
    "rmat": (generators.rmat(12, 8, seed=3), 4096, 8),
    "random": (generators.random_graph(500, 4000, seed=9), 500, 5),
}


@pytest.fixture(params=list(CASES))
def case(request):
    return CASES[request.param]


def test_refine_never_regresses_and_respects_cap(case):
    e, n, k = case
    es = EdgeStream.from_array(e, n_vertices=n)
    res = get_backend("pure").partition(es, k, comm_volume=False)
    alpha = 1.10
    cap = int(alpha * (-(-n // k)))

    new_assign, stats = refine_assignment(
        res.assignment, es, n, k, rounds=4, alpha=alpha,
        chunk_edges=1 << 12)
    assert stats["refine_cut_after"] <= stats["refine_cut_before"]
    assert new_assign.min() >= 0 and new_assign.max() < k
    loads = np.bincount(new_assign, minlength=k)
    start_loads = np.bincount(res.assignment, minlength=k)
    # parts under the cap stay under it; overfull parts only shrink
    assert np.all(loads <= np.maximum(start_loads, cap))
    # recomputed cut agrees with the reported one
    pu, pv = new_assign[e[:, 0]], new_assign[e[:, 1]]
    cut = int(np.sum((pu != pv) & (e[:, 0] != e[:, 1])))
    assert cut == stats["refine_cut_after"]


def test_refine_improves_rmat_cut():
    """On a power-law graph the greedy tree split leaves easy wins; the
    propagation pass must actually find some (strict improvement)."""
    e, n, k = CASES["rmat"]
    es = EdgeStream.from_array(e, n_vertices=n)
    res = get_backend("pure").partition(es, k, comm_volume=False)
    _, stats = refine_assignment(res.assignment, es, n, k, rounds=4,
                                 chunk_edges=1 << 12)
    assert stats["refine_cut_after"] < stats["refine_cut_before"]


def test_refine_blocked_histogram_matches_full():
    """A histogram over budget switches to vertex-blocked passes; the
    result must be identical to the single full-width histogram (the
    blocks partition the same rows)."""
    e, n, k = CASES["rmat"]
    es = EdgeStream.from_array(e, n_vertices=n)
    res = get_backend("pure").partition(es, k, comm_volume=False)
    full, fs = refine_assignment(res.assignment, es, n, k, rounds=3,
                                 chunk_edges=1 << 12)
    blocked, bs = refine_assignment(res.assignment, es, n, k, rounds=3,
                                    chunk_edges=1 << 12,
                                    budget_bytes=4 * 64 * k,
                                    min_block=64)
    assert bs["refine_hist_blocks"] > 1 and fs["refine_hist_blocks"] == 1
    np.testing.assert_array_equal(blocked, full)
    assert bs["refine_cut_after"] == fs["refine_cut_after"]


def test_refine_weighted_caps_by_degree():
    """Degree-weighted refinement: cut still never regresses and no part
    grows past the weighted cap (alpha * total_degree / k)."""
    e, n, k = CASES["rmat"]
    es = EdgeStream.from_array(e, n_vertices=n)
    res = get_backend("pure").partition(es, k, weights="degree",
                                        comm_volume=False)
    deg = np.bincount(e.ravel(), minlength=n)[:n]
    alpha = 1.10
    cap_w = alpha * deg.sum() / k
    new_assign, stats = refine_assignment(
        res.assignment, es, n, k, rounds=3, alpha=alpha,
        chunk_edges=1 << 12, weights=deg)
    assert stats["refine_cut_after"] <= stats["refine_cut_before"]
    loads_w = np.bincount(new_assign, weights=deg, minlength=k)
    start_w = np.bincount(res.assignment, weights=deg, minlength=k)
    assert np.all(loads_w <= np.maximum(start_w, cap_w * (1 + 1e-5)))


def test_partition_api_refine(tmp_path):
    e, n, k = CASES["rmat"]
    gp = str(tmp_path / "g.edges")
    formats.write_edges(gp, e)
    base = sheep_tpu.partition(gp, k, backend="pure", comm_volume=True)
    ref = sheep_tpu.partition(gp, k, backend="pure", comm_volume=True,
                              refine=4)
    assert ref.edge_cut <= base.edge_cut
    assert ref.total_edges == base.total_edges
    assert ref.comm_volume is not None
    assert ref.diagnostics["refine_rounds_run"] >= 0
    # cut_ratio/balance rescored consistently
    assert ref.cut_ratio == ref.edge_cut / base.total_edges
