"""Refinement post-pass (ops/refine.py) — an extension beyond the
reference's surface, so the contract here is self-imposed: the refined
cut must NEVER exceed the unrefined cut (round-level rollback), no part
may grow past the balance cap, and the assignment stays valid.
"""

import numpy as np
import pytest

import sheep_tpu
from sheep_tpu.backends.base import get_backend
from sheep_tpu.io import formats, generators
from sheep_tpu.io.edgestream import EdgeStream
from sheep_tpu.ops.refine import refine_assignment


CASES = {
    "karate": (generators.karate_club(), 34, 2),
    "grid": (generators.grid_graph(16, 16), 256, 4),
    "rmat": (generators.rmat(12, 8, seed=3), 4096, 8),
    "random": (generators.random_graph(500, 4000, seed=9), 500, 5),
}


@pytest.fixture(params=list(CASES))
def case(request):
    return CASES[request.param]


def test_refine_never_regresses_and_respects_cap(case):
    e, n, k = case
    es = EdgeStream.from_array(e, n_vertices=n)
    res = get_backend("pure").partition(es, k, comm_volume=False)
    alpha = 1.10
    cap = int(alpha * (-(-n // k)))

    new_assign, stats = refine_assignment(
        res.assignment, es, n, k, rounds=4, alpha=alpha,
        chunk_edges=1 << 12)
    assert stats["refine_cut_after"] <= stats["refine_cut_before"]
    assert new_assign.min() >= 0 and new_assign.max() < k
    loads = np.bincount(new_assign, minlength=k)
    start_loads = np.bincount(res.assignment, minlength=k)
    # parts under the cap stay under it; overfull parts only shrink
    assert np.all(loads <= np.maximum(start_loads, cap))
    # recomputed cut agrees with the reported one
    pu, pv = new_assign[e[:, 0]], new_assign[e[:, 1]]
    cut = int(np.sum((pu != pv) & (e[:, 0] != e[:, 1])))
    assert cut == stats["refine_cut_after"]


def test_refine_improves_rmat_cut():
    """On a power-law graph the greedy tree split leaves easy wins; the
    propagation pass must actually find some (strict improvement)."""
    e, n, k = CASES["rmat"]
    es = EdgeStream.from_array(e, n_vertices=n)
    res = get_backend("pure").partition(es, k, comm_volume=False)
    _, stats = refine_assignment(res.assignment, es, n, k, rounds=4,
                                 chunk_edges=1 << 12)
    assert stats["refine_cut_after"] < stats["refine_cut_before"]


def test_refine_blocked_histogram_matches_full():
    """A histogram over budget switches to vertex-blocked passes; the
    result must be identical to the single full-width histogram (the
    blocks partition the same rows)."""
    e, n, k = CASES["rmat"]
    es = EdgeStream.from_array(e, n_vertices=n)
    res = get_backend("pure").partition(es, k, comm_volume=False)
    full, fs = refine_assignment(res.assignment, es, n, k, rounds=3,
                                 chunk_edges=1 << 12)
    blocked, bs = refine_assignment(res.assignment, es, n, k, rounds=3,
                                    chunk_edges=1 << 12,
                                    budget_bytes=4 * 64 * k,
                                    min_block=64)
    assert bs["refine_hist_blocks"] > 1 and fs["refine_hist_blocks"] == 1
    np.testing.assert_array_equal(blocked, full)
    assert bs["refine_cut_after"] == fs["refine_cut_after"]


def test_refine_weighted_caps_by_degree():
    """Degree-weighted refinement: cut still never regresses and no part
    grows past the weighted cap (alpha * total_degree / k)."""
    e, n, k = CASES["rmat"]
    es = EdgeStream.from_array(e, n_vertices=n)
    res = get_backend("pure").partition(es, k, weights="degree",
                                        comm_volume=False)
    deg = np.bincount(e.ravel(), minlength=n)[:n]
    alpha = 1.10
    cap_w = alpha * deg.sum() / k
    new_assign, stats = refine_assignment(
        res.assignment, es, n, k, rounds=3, alpha=alpha,
        chunk_edges=1 << 12, weights=deg)
    assert stats["refine_cut_after"] <= stats["refine_cut_before"]
    loads_w = np.bincount(new_assign, weights=deg, minlength=k)
    start_w = np.bincount(res.assignment, weights=deg, minlength=k)
    assert np.all(loads_w <= np.maximum(start_w, cap_w * (1 + 1e-5)))


def test_refine_host_planning_matches_device():
    """Past the O(V) device planning budget, moves are planned on host
    (numpy mirror) — results must be bit-identical to device planning,
    for both unit and degree weights."""
    e, n, k = CASES["rmat"]
    es = EdgeStream.from_array(e, n_vertices=n)
    res = get_backend("pure").partition(es, k, comm_volume=False)
    deg = np.bincount(e.ravel(), minlength=n)[:n]
    for w in (None, deg):
        dev, ds = refine_assignment(res.assignment, es, n, k, rounds=3,
                                    chunk_edges=1 << 12, weights=w)
        host, hs = refine_assignment(res.assignment, es, n, k, rounds=3,
                                     chunk_edges=1 << 12, weights=w,
                                     plan_budget_bytes=64)
        assert ds["refine_host_plan"] == 0 and hs["refine_host_plan"] == 1
        np.testing.assert_array_equal(host, dev)


def test_refine_error_skips_gracefully(tmp_path):
    """A refinement failure must return the UNREFINED result with a
    diagnostic instead of losing the run."""
    e, n, k = CASES["rmat"]
    gp = str(tmp_path / "g.edges")
    formats.write_edges(gp, e)
    import unittest.mock as mock

    from sheep_tpu.ops import refine as refine_mod

    base = sheep_tpu.partition(gp, k, backend="pure", comm_volume=False)
    with mock.patch.object(
            refine_mod, "refine_assignment",
            side_effect=ValueError("past the single-device refine ceiling")):
        res = sheep_tpu.partition(gp, k, backend="pure",
                                  comm_volume=False, refine=2)
    np.testing.assert_array_equal(res.assignment, base.assignment)
    assert "ceiling" in res.diagnostics["refine_skipped"]


def test_accumulate_cv_keys_not_quadratic_past_distinct_cap(monkeypatch):
    """Once the compacted head alone exceeds the cap, further appends
    must NOT recompact every chunk (review r2 finding #3)."""
    from sheep_tpu.ops import score as score_ops
    from sheep_tpu.utils import checkpoint as ckpt

    monkeypatch.setattr(score_ops, "CV_COMPACT_ENTRIES", 8)
    calls = {"n": 0}
    real = ckpt.compact_cv_keys

    def counting(chunks):
        calls["n"] += 1
        return real(chunks)

    monkeypatch.setattr(ckpt, "compact_cv_keys", counting)
    acc = [np.arange(100, dtype=np.int64)]  # compacted head > cap
    for i in range(20):
        score_ops.accumulate_cv_keys(
            acc, np.array([i], dtype=np.int64))
    # tail of 1-element chunks only crosses the cap ~twice in 20 appends
    assert calls["n"] <= 3
    assert set(real(acc)) == set(range(100))


def test_partition_api_refine(tmp_path):
    e, n, k = CASES["rmat"]
    gp = str(tmp_path / "g.edges")
    formats.write_edges(gp, e)
    base = sheep_tpu.partition(gp, k, backend="pure", comm_volume=True)
    ref = sheep_tpu.partition(gp, k, backend="pure", comm_volume=True,
                              refine=4)
    assert ref.edge_cut <= base.edge_cut
    assert ref.total_edges == base.total_edges
    assert ref.comm_volume is not None
    assert ref.diagnostics["refine_rounds_run"] >= 0
    # cut_ratio/balance rescored consistently
    assert ref.cut_ratio == ref.edge_cut / base.total_edges


def test_spool_equivalence_and_cleanup(tmp_path, monkeypatch):
    """A generator stream refines to the IDENTICAL result with and
    without spooling (the spool is a byte-faithful copy), and the temp
    file is removed afterwards."""
    import glob

    from sheep_tpu.io.edgestream import open_input
    from sheep_tpu.ops.refine import refine_assignment

    monkeypatch.setenv("TMPDIR", str(tmp_path))
    import tempfile

    tempfile.tempdir = None  # re-read TMPDIR
    try:
        with open_input("sbm-hash:10:8:0.05:16:2") as es:
            n = es.num_vertices
            base = sheep_tpu.partition("sbm-hash:10:8:0.05:16:2", 8,
                                       backend="pure", comm_volume=False)
            a1, s1 = refine_assignment(base.assignment, es, n, 8,
                                       rounds=3, spool=True)
            a2, s2 = refine_assignment(base.assignment, es, n, 8,
                                       rounds=3, spool=False)
        # the spool must actually have engaged (a silent fallback would
        # make this test vacuous — review finding)
        assert s1["refine_spooled"] == 1 and s2["refine_spooled"] == 0
        assert np.array_equal(a1, a2)
        assert s1["refine_cut_after"] == s2["refine_cut_after"]
        assert glob.glob(str(tmp_path / "sheep_spool_*")) == []
    finally:
        tempfile.tempdir = None
