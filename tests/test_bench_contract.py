"""bench.py JSON contract tests (VERDICT r3 item 6, r5 item 7).

Three properties the driver relies on:
  (a) the multi-chip leg — the exact code path that will emit
      ``vs_baseline_8chip`` on real multi-chip hardware — compiles and
      runs on the 8-device virtual mesh (``SHEEP_BENCH_MULTICHIP=1``
      forces it on cpu-jax);
  (b) a cpu-jax fallback run emits ``vs_baseline: null`` (the cpu-jax vs
      native-CPU ratio is framework overhead, not the north-star metric,
      and lives under ``cpu_jax_vs_native_cpu``);
  (c) every emitted line carries the per-window link-state fields
      ``{rtt_ms, h2d_mbs, d2h_mbs}`` plus ``r_colo_est`` and the
      dispatch-count attribution inputs, so headline numbers are
      comparable across link-quality swings.
"""

import json
import os
import subprocess
import sys

import jax

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(REPO, "bench.py")


def test_measure_multichip_leg_on_virtual_mesh(monkeypatch):
    assert jax.device_count() == 8, "conftest should force 8 virtual devices"
    monkeypatch.setenv("SHEEP_BENCH_MULTICHIP", "1")
    monkeypatch.setenv("SHEEP_BENCH_K", "8")
    sys.path.insert(0, REPO)
    try:
        import bench
        out = bench.measure(12, "cpu")
    finally:
        sys.path.remove(REPO)
    assert out["n_devices"] == 8
    assert out["sharded_eps"] > 0
    assert out["ratio_multichip"] > 0
    # link-state + co-located-R contract fields (VERDICT r5 item 7)
    for f in ("rtt_ms", "h2d_mbs", "d2h_mbs", "r_colo_est"):
        assert out[f] > 0, f
    assert out["host_syncs"] >= 0 and out["device_rounds"] > 0
    # dispatch-overlap contract pair (ISSUE 4): on every measured row,
    # so --inflight A/Bs and the bench_regress host_blocked_ms gate
    # have their inputs even on cpu-jax windows
    assert out["host_blocked_ms"] >= 0
    assert out["device_gap_ms"] >= 0
    # the sharded path partitions the same counter-hash graph: its cut
    # must be in the same regime as the baselines (not degenerate)
    assert 0.0 < out["sharded_cut_ratio"] <= 1.0
    assert abs(out["sharded_cut_ratio"] - out["cpu_cut_ratio"]) < 0.2


def test_fallback_emits_null_vs_baseline():
    env = dict(os.environ)
    env.update(SHEEP_BENCH_PLATFORM="cpu", SHEEP_BENCH_SCALE="12",
               SHEEP_BENCH_K="8", SHEEP_BENCH_ATTEMPT_TIMEOUT="600")
    r = subprocess.run([sys.executable, BENCH], capture_output=True,
                       text=True, env=env, timeout=900, cwd=REPO)
    assert r.returncode == 0, r.stderr[-2000:]
    line = json.loads(r.stdout.strip().splitlines()[-1])
    assert line["vs_baseline"] is None
    assert line["value"] > 0
    assert line["cpu_jax_vs_native_cpu"] > 0
    assert "error" in line
    # the link-state + r_colo_est contract rides on EVERY emitted line,
    # fallback included — that is what makes a degraded-window capture
    # normalizable after the fact
    for f in ("rtt_ms", "h2d_mbs", "d2h_mbs", "r_colo_est"):
        assert line[f] > 0, f
    # the overlap counters ride the emitted line too (ISSUE 4)
    for f in ("host_blocked_ms", "device_gap_ms"):
        assert line[f] >= 0, f
    # the fault-tolerance contract (ISSUE 9): dispatch_retries is
    # ALWAYS emitted (0 on a healthy run) so the regression gate can
    # see 0 -> N movement instead of an incomparable missing field
    assert line["dispatch_retries"] == 0
    # the warm-vs-cold served-request contract (ISSUE 10): warm_up_s
    # (the cold first-request jit tax bench.py printed for three
    # rounds but never emitted) and the cold/warm request walls ride
    # every measured line so bench_regress gates warm-path latency
    assert line["warm_up_s"] > 0
    assert line["cold_request_s"] > 0 and line["warm_request_s"] > 0
    # the incremental contract (ISSUE 15): update_request_s — one
    # resident-partition delta fold — rides every measured line with
    # its compactions companion, so bench_regress can gate the O(Δ)
    # update wall like the warm path
    assert line["update_request_s"] > 0
    assert line["compactions"] == 0
    # the multi-device incremental contract (ISSUE 19): the same scored
    # delta epoch through the tpu-sharded fold + distributed rescore
    # rides every measured line, gated lower-better by bench_regress
    assert line["sharded_update_request_s"] > 0


def test_skip_probe_short_circuits():
    """SHEEP_SKIP_PROBE=1 must skip the (2 x 180 s on dead-tunnel
    hosts) subprocess probe entirely and return the cpu fallback."""
    import importlib

    sys.path.insert(0, REPO)
    try:
        import bench
        importlib.reload(bench)
        calls = []
        orig = bench._probe_accelerator_uncached
        bench._probe_accelerator_uncached = \
            lambda tries, timeout: calls.append(1) or "tpu"
        try:
            os.environ["SHEEP_SKIP_PROBE"] = "1"
            assert bench.probe_accelerator() is None
            assert calls == []
            os.environ.pop("SHEEP_SKIP_PROBE")
            # and without the skip, the verdict is cached per process
            assert bench.probe_accelerator() == "tpu"
            assert bench.probe_accelerator() == "tpu"
            assert len(calls) == 1
        finally:
            bench._probe_accelerator_uncached = orig
            bench._PROBE_CACHE.clear()
            os.environ.pop("SHEEP_SKIP_PROBE", None)
    finally:
        sys.path.remove(REPO)
