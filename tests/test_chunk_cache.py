"""Device chunk cache invariants (tpu_backend._device_chunks).

The cache is budget-gated off on cpu-jax in production, so these tests
construct _ChunkCache with explicit budgets and drive _device_chunks
directly — the prefix/fill/resume invariants must hold no matter what
the platform is, because a violation silently changes WHICH edges a
pass processes (double-count or skip), not just how fast.
"""

import numpy as np
import pytest

from sheep_tpu.backends.tpu_backend import (_ChunkCache, _device_chunks,
                                            pad_chunk)
from sheep_tpu.io.edgestream import EdgeStream
from sheep_tpu.io.generators import rmat

CS = 64
N = 1 << 8


@pytest.fixture()
def stream():
    e = rmat(8, 3, seed=21)  # 768 edges -> 12 chunks of 64
    return EdgeStream.from_array(e, n_vertices=N)


def _expected(stream, start=0):
    return [pad_chunk(c, CS, N) for c in stream.chunks(CS, start_chunk=start)]


def _collect(stream, cache, start=0):
    return [np.asarray(d) for d in _device_chunks(stream, CS, N, cache, start)]


def test_unlimited_budget_caches_all_and_reserves(stream):
    cache = _ChunkCache(1 << 30)
    first = _collect(stream, cache)
    exp = _expected(stream)
    assert len(first) == len(exp) and cache.complete
    assert len(cache.chunks) == len(exp)
    for a, b in zip(first, exp):
        np.testing.assert_array_equal(a, b)
    # second pass serves purely from cache, identically
    second = _collect(stream, cache)
    for a, b in zip(second, exp):
        np.testing.assert_array_equal(a, b)


def test_partial_budget_keeps_prefix_and_streams_rest(stream):
    chunk_bytes = CS * 2 * 4
    cache = _ChunkCache(3 * chunk_bytes)  # room for exactly 3 chunks
    first = _collect(stream, cache)
    exp = _expected(stream)
    assert len(cache.chunks) == 3 and not cache.complete
    for a, b in zip(first, exp):
        np.testing.assert_array_equal(a, b)
    # second pass: 3 served from cache, rest re-streamed, order intact;
    # the budget stays exhausted so the prefix does not grow
    second = _collect(stream, cache)
    assert len(second) == len(exp) and len(cache.chunks) == 3
    for a, b in zip(second, exp):
        np.testing.assert_array_equal(a, b)


def test_resume_start_chunk_bypasses_cache(stream):
    cache = _ChunkCache(1 << 30)
    _collect(stream, cache)  # fill fully
    got = _collect(stream, cache, start=5)
    exp = _expected(stream, start=5)
    assert len(got) == len(exp)
    for a, b in zip(got, exp):
        np.testing.assert_array_equal(a, b)
    # bypass must not have mutated the cache
    assert cache.complete and len(cache.chunks) == len(_expected(stream))


def test_exception_mid_fill_leaves_valid_prefix(stream):
    cache = _ChunkCache(1 << 30)
    exp = _expected(stream)
    it = _device_chunks(stream, CS, N, cache, 0)
    for _ in range(4):  # consume 4 chunks, then abandon the pass
        next(it)
    it.close()
    assert not cache.complete
    assert 0 < len(cache.chunks) <= 5  # a valid prefix, nothing past it
    for a, b in zip(cache.chunks, exp):
        np.testing.assert_array_equal(np.asarray(a), b)
    # the next full pass serves the prefix and finishes the fill
    got = _collect(stream, cache)
    assert len(got) == len(exp) and cache.complete
    for a, b in zip(got, exp):
        np.testing.assert_array_equal(a, b)


def test_interrupted_growth_second_pass_continues(stream):
    chunk_bytes = CS * 2 * 4
    cache = _ChunkCache(10 * chunk_bytes)
    it = _device_chunks(stream, CS, N, cache, 0)
    for _ in range(2):
        next(it)
    it.close()
    k = len(cache.chunks)
    assert 0 < k <= 3 and not cache.complete
    got = _collect(stream, cache)
    exp = _expected(stream)
    assert len(got) == len(exp)
    for a, b in zip(got, exp):
        np.testing.assert_array_equal(a, b)
    assert len(cache.chunks) == 10 and not cache.complete  # budget-capped
