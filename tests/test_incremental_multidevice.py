"""Multi-device incremental repartitioning tests (ISSUE 19).

The acceptance pins:

- **Parity across all five backends**: ``partition_update`` on
  tpu-sharded and tpu-bigv is bit-identical to the one-shot anchored
  build at the same epoch (adds exact; delete + full compaction ==
  clean survivor rebuild), and to the single-device backends.
- **Distributed score cache**: a scored epoch on the multi-device
  backends rescores device-side with ONE all-reduce
  (``score_distributed``), bit-equal to the host scorer —
  ``SHEEP_SCORE_AUDIT=1`` shadow-checks every refresh here.
- **Measured O(Δ)**: the counter-instrumented per-epoch cost
  (``device_rounds`` / ``host_syncs`` / ``folded_bytes``) of a small
  delta is >= 10x below a full rebuild of the same graph.
- **Zero-copy anchor ingest**: a ``delta:`` anchor over a DeviceStream
  base still reports ``device_stream_chunks > 0`` with
  ``h2d_staged_bytes == 0`` (PR-12's win survives the new path).
"""

import numpy as np
import pytest

from sheep_tpu import incremental as inc
from sheep_tpu.backends.base import get_backend, list_backends
from sheep_tpu.io import deltalog as dl
from sheep_tpu.io.edgestream import EdgeStream, open_input

N = 512
SEED = 5


def _graph(m=4000, n=N, seed=SEED):
    rng = np.random.default_rng(seed)
    return rng.integers(0, n, (m, 2)).astype(np.int64)


def _base_file(tmp_path, edges, name="base.bin64"):
    p = str(tmp_path / name)
    with open(p, "wb") as f:
        f.write(np.asarray(edges, np.int64).astype("<u8").tobytes())
    return p


def _md_backends():
    avail = list_backends()
    return [b for b in ("tpu-sharded", "tpu-bigv") if b in avail]


# ----------------------------------------------------------------------
# the exactness contract, now spanning all five backends
# ----------------------------------------------------------------------
@pytest.mark.parametrize("backend", _md_backends())
def test_two_halves_replay_bit_identical_multidevice(tmp_path, backend):
    """Adds are exact on the multi-device backends too — and equal to
    the cpu oracle, so the contract is pinned across the whole backend
    matrix (pure/cpu/tpu are covered in test_incremental.py)."""
    e = _graph()
    half = len(e) // 2
    base = _base_file(tmp_path, e[:half])
    log = str(tmp_path / "g.dlog")
    with dl.DeltaLogWriter(log, base_spec=base) as w:
        w.append(e[half: half + 1000])
        w.append(e[half + 1000:])
    be = get_backend(backend, chunk_edges=4096)
    one = be.partition(open_input(f"delta:{log}", n_vertices=N), 8,
                       comm_volume=False)
    oracle = get_backend("cpu", chunk_edges=777).partition(
        open_input(f"delta:{log}", n_vertices=N), 8, comm_volume=False)
    np.testing.assert_array_equal(one.assignment, oracle.assignment)
    state, _ = inc.begin_incremental(
        open_input(base, n_vertices=N), 8, backend=be)
    assert be.partition_update(state, adds=e[half: half + 1000],
                               score=False) is None
    r2 = be.partition_update(state, adds=e[half + 1000:], score=True)
    assert state.epoch == 2
    assert state.stats["update_folds"] == 2
    np.testing.assert_array_equal(r2.assignment, one.assignment)
    assert (r2.edge_cut, r2.total_edges) == (one.edge_cut,
                                             one.total_edges)
    assert r2.balance == pytest.approx(one.balance)


@pytest.mark.parametrize("backend", _md_backends())
def test_delete_full_compact_matches_clean_rebuild_multidevice(
        tmp_path, backend):
    e = _graph()
    base = _base_file(tmp_path, e[:2000])
    be = get_backend(backend, chunk_edges=4096)
    state, _ = inc.begin_incremental(
        open_input(base, n_vertices=N), 8, backend=be)
    be.partition_update(state, adds=e[2000:], score=False)
    dels = e[np.random.default_rng(9).permutation(len(e))[:600]]
    r_stale = be.partition_update(state, deletes=dels, score=True,
                                  compact="never")
    assert state.stale_deletes == 600
    assert inc.compact_state(be, state, mode="full") == "full"
    assert state.stale_deletes == 0
    assert state.anchored_at_epoch == state.epoch
    r = inc.refresh(be, state)
    surv = np.concatenate(list(dl.filter_tombstones([e], dels)))
    # the clean-rebuild oracle on the CPU backend: post-compact parity
    # AND cross-backend parity in one assert (all backends produce the
    # identical table for the identical stream)
    clean = get_backend("cpu", chunk_edges=777).partition(
        EdgeStream.from_array(surv, n_vertices=N), 8,
        comm_volume=False)
    np.testing.assert_array_equal(r.assignment, clean.assignment)
    assert (r.edge_cut, r.total_edges) == (clean.edge_cut,
                                           clean.total_edges)
    # the stale pre-compact score already counted the right multiset
    assert r_stale.total_edges == clean.total_edges


# ----------------------------------------------------------------------
# distributed score cache
# ----------------------------------------------------------------------
# the bigv leg rides the slow tier: its _move_rescore delegates to the
# same move_rescore_sharded program the sharded leg pins, so tier-1
# keeps the audit coverage at a third of the wall
@pytest.mark.parametrize("backend", [
    pytest.param(b, marks=[pytest.mark.slow] if b == "tpu-bigv" else [])
    for b in _md_backends()])
def test_distributed_rescore_fires_and_survives_audit(
        tmp_path, backend, monkeypatch):
    """A SPARSE graph (dense random forests are totally stable — no
    labels move, so the rescore hook correctly never fires) whose
    epochs reassign vertices: the scored refresh must take the
    device-side path (``score_distributed``) under the full-pass
    shadow audit, and land the same cut the host scorer computes on
    the cpu backend."""
    monkeypatch.setenv("SHEEP_SCORE_AUDIT", "1")
    n = 2048
    e = np.random.default_rng(15).integers(0, n, (13000, 2)).astype(
        np.int64)
    base = _base_file(tmp_path, e[:6000])
    be = get_backend(backend, chunk_edges=8192)
    state, _ = inc.begin_incremental(
        open_input(base, n_vertices=n), 4, backend=be)
    # epoch 1 seeds the score cache (a full pass); epoch 2 rescores
    # incrementally — device-side on these backends
    r1 = be.partition_update(state, adds=e[6000:10000], score=True)
    r2 = be.partition_update(state, adds=e[10000:], score=True)
    assert state.stats["score_full"] >= 1
    assert state.stats["score_distributed"] >= 1
    host = get_backend("cpu", chunk_edges=2048)
    hs, _ = inc.begin_incremental(
        open_input(base, n_vertices=n), 4, backend=host)
    h1 = host.partition_update(hs, adds=e[6000:10000], score=True)
    h2 = host.partition_update(hs, adds=e[10000:], score=True)
    assert hs.stats.get("score_distributed", 0) == 0  # host path
    assert (r1.edge_cut, r2.edge_cut) == (h1.edge_cut, h2.edge_cut)
    np.testing.assert_array_equal(r2.assignment, h2.assignment)


# ----------------------------------------------------------------------
# measured O(Δ): the acceptance ratio, by counters
# ----------------------------------------------------------------------
def test_small_delta_epoch_is_ten_x_below_full_rebuild(tmp_path):
    """The whole point of the PR: on a resident sharded partition the
    counter-instrumented cost of folding + scoring a small delta
    (``device_rounds`` / ``host_syncs`` / ``folded_bytes`` — the same
    triple the build path reports) is >= 10x below a full rebuild of
    the same graph. Measured at ~25x here, asserted at 10x so noise in
    the adaptive confirmation cadence can't flake the gate."""
    n, m, dm = 1024, 200_000, 128
    rng = np.random.default_rng(11)
    e = rng.integers(0, n, (m + dm, 2)).astype(np.int64)
    base = _base_file(tmp_path, e[:m])
    log = str(tmp_path / "g.dlog")
    with dl.DeltaLogWriter(log, base_spec=base) as w:
        w.append(e[m:])
    be = get_backend("tpu-sharded", chunk_edges=1024)
    one = be.partition(open_input(f"delta:{log}", n_vertices=n), 8,
                       comm_volume=False)
    rebuild = one.diagnostics
    state, _ = inc.begin_incremental(
        open_input(base, n_vertices=n), 8, backend=be)
    keys = ("device_rounds", "host_syncs", "folded_bytes")
    before = {k: state.stats.get(k, 0) for k in keys}
    r = be.partition_update(state, adds=e[m:], score=True)
    cost = {k: state.stats.get(k, 0) - before[k] for k in keys}
    for k in keys:
        assert cost[k] > 0, k  # the counters actually instrument it
        assert 10 * cost[k] <= rebuild[k], \
            f"{k}: epoch cost {cost[k]} vs rebuild {rebuild[k]}"
    # and the cheap epoch still lands the exact one-shot answer
    np.testing.assert_array_equal(r.assignment, one.assignment)
    assert r.edge_cut == one.edge_cut


# ----------------------------------------------------------------------
# delta-log x devicestream: zero-copy anchor ingest (PR-12 guard)
# ----------------------------------------------------------------------
def test_delta_anchor_over_devicestream_base_pays_zero_host_bytes(
        tmp_path):
    """A ``delta:`` log whose base_spec is a counter-hash generator
    keeps the DeviceStream protocol for the anchor (degrees) pass:
    chunks synthesize on device (``device_stream_chunks > 0``) and no
    host bytes cross per chunk (``h2d_staged_bytes == 0``) — while the
    build still lands bit-identical to the tpu backend over the same
    log."""
    spec = "rmat-hash:9:4:1"
    with open_input(spec) as s:
        n = s.num_vertices
    log = str(tmp_path / "g.dlog")
    extra = _graph(300, n=n, seed=3)
    with dl.DeltaLogWriter(log, base_spec=spec) as w:
        w.append(extra)
    st = open_input(f"delta:{log}")
    from sheep_tpu.io.devicestream import is_device_stream

    assert is_device_stream(st.anchor_stream())
    be = get_backend("tpu-sharded", chunk_edges=1024)
    got = be.partition(st, 8, comm_volume=False)
    assert got.diagnostics["device_stream_chunks"] > 0
    assert got.diagnostics["h2d_staged_bytes"] == 0
    oracle = get_backend("tpu", chunk_edges=1024).partition(
        open_input(f"delta:{log}"), 8, comm_volume=False)
    np.testing.assert_array_equal(got.assignment, oracle.assignment)
    assert got.edge_cut == oracle.edge_cut
