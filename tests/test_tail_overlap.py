"""Overlapped host tails (tpu backend ``tail_overlap=True``).

The tail of each chunk's fixpoint is resolved by the native Liu pass in
a worker thread while the device folds the NEXT chunk; the resolved
links re-enter a later fold as delta constraints
(``ops/elim.py host_tail_delta``). The forest must be bit-identical to
the serialized default on every graph shape: the fixpoint is a function
of the inserted constraint multiset, and a resolved link is a derived
tree edge of a sub-multiset (the ``merge_forests`` property).
"""

import numpy as np
import pytest

import jax.numpy as jnp

from sheep_tpu.core import native, pure
from sheep_tpu.io import generators
from sheep_tpu.io.edgestream import EdgeStream
from sheep_tpu.backends.tpu_backend import TpuBackend, pad_chunk
from sheep_tpu.ops import degrees as degrees_ops
from sheep_tpu.ops import elim as elim_ops
from sheep_tpu.ops import order as order_ops
from sheep_tpu.utils.checkpoint import Checkpointer
from sheep_tpu.utils.fault import ENV_VAR, InjectedFault

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="tail overlap needs the native core")


def _cases():
    return {
        "karate": (generators.karate_club(), 34),
        "path": (generators.path_graph(64), 64),
        "star": (generators.star_graph(50), 50),
        "random": (generators.random_graph(200, 1600, seed=11), 200),
        "rmat": (generators.rmat(9, 8, seed=12), 512),
    }


@pytest.fixture(params=list(_cases()))
def graph(request):
    return _cases()[request.param]


def test_delta_job_matches_serial_finish(graph):
    """host_tail_delta + a delta re-fold == _host_tail_finish_pos."""
    e, n = graph
    deg = degrees_ops.init_degrees(n)
    deg = degrees_ops.degree_chunk(deg, pad_chunk(e, len(e), n), n)
    pos, order = order_ops.elimination_order(deg, n)
    pos_host = np.asarray(pos[:n])
    loP, hiP = elim_ops.orient_edges_pos(
        jnp.asarray(pad_chunk(e, len(e), n)), pos, n)
    P0 = jnp.full(n + 1, n, dtype=jnp.int32)
    # a couple of cheap rounds, then treat ALL still-live slots as tail
    loP, hiP, P, _ = elim_ops.fold_segment_pos(P0, loP, hiP, n,
                                               lift_levels=2,
                                               segment_rounds=2)
    serial = elim_ops._host_tail_finish_pos(
        P, loP, hiP, n, int(loP.shape[0]), pos_host)
    dlo, dhi = elim_ops.host_tail_delta(P, loP, hiP, n, pos_host)
    inj = elim_ops.pad_actives_pow2(dlo, dhi, n, floor=16)
    refolded, _ = elim_ops.fold_edges_adaptive_pos(
        P, inj[0], inj[1], n, pos_host=pos_host)
    np.testing.assert_array_equal(np.asarray(serial), np.asarray(refolded))


@pytest.mark.parametrize("threshold", [-1, 8])
def test_overlap_matches_default_end_to_end(graph, threshold):
    """Many small chunks -> several tails in flight; scores and
    assignment must match the serialized backend exactly."""
    e, n = graph
    kw = dict(chunk_edges=64, host_tail_threshold=threshold)
    ref = TpuBackend(**kw).partition(
        EdgeStream.from_array(e, n_vertices=n), 4, comm_volume=True)
    res = TpuBackend(tail_overlap=True, **kw).partition(
        EdgeStream.from_array(e, n_vertices=n), 4, comm_volume=True)
    np.testing.assert_array_equal(res.assignment, ref.assignment)
    assert res.edge_cut == ref.edge_cut
    assert res.comm_volume == ref.comm_volume
    oracle = pure.partition_arrays(e, 4, n=n)
    np.testing.assert_array_equal(res.assignment, oracle.assignment)


def test_overlap_checkpoint_fault_resume(tmp_path, monkeypatch):
    """The drain-before-save flush makes overlap checkpoints complete:
    kill mid-build with tails in flight, resume (in either tail mode),
    match the uninterrupted run exactly."""
    e, n = generators.rmat(13, 8, seed=5), 1 << 13
    kw = dict(chunk_edges=1 << 15, segment_rounds=2, tail_overlap=True)
    es = EdgeStream.from_array(e, n_vertices=n)
    expect = TpuBackend(**kw).partition(es, 4, comm_volume=True)
    assert expect.diagnostics.get("overlap_tails", 0) >= 2

    ck = Checkpointer(str(tmp_path), every=1)
    monkeypatch.setenv(ENV_VAR, "build:2")
    with pytest.raises(InjectedFault):
        TpuBackend(**kw).partition(es, 4, comm_volume=True, checkpointer=ck)
    monkeypatch.delenv(ENV_VAR)
    assert ck.load() is not None

    res = TpuBackend(**kw).partition(es, 4, comm_volume=True,
                                     checkpointer=ck, resume=True)
    np.testing.assert_array_equal(res.assignment, expect.assignment)
    assert res.edge_cut == expect.edge_cut
    assert res.comm_volume == expect.comm_volume


def test_overlap_excludes_carry():
    with pytest.raises(ValueError):
        TpuBackend(carry_tail=True, tail_overlap=True)


def test_overlap_tails_actually_fire_and_match():
    """Buffers above small_size (2^14) cut tails after each short full
    segment, so several host resolutions are genuinely in flight across
    chunks; result must still match the serialized default exactly.
    (The tiny-graph matrix above mostly converges on device — this is
    the case where the overlap machinery does real work.)"""
    e, n = generators.rmat(13, 8, seed=5), 1 << 13
    kw = dict(chunk_edges=1 << 15, segment_rounds=2)
    ref = TpuBackend(**kw).partition(
        EdgeStream.from_array(e, n_vertices=n), 8, comm_volume=False)
    res = TpuBackend(tail_overlap=True, **kw).partition(
        EdgeStream.from_array(e, n_vertices=n), 8, comm_volume=False)
    np.testing.assert_array_equal(res.assignment, ref.assignment)
    assert res.edge_cut == ref.edge_cut
    assert res.diagnostics.get("overlap_tails", 0) >= 2
    assert "host_tails" not in res.diagnostics
    assert ref.diagnostics.get("host_tails", 0) >= 2
