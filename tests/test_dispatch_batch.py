"""Batched segment dispatch (the ISSUE 1 tentpole).

Two properties, both assertable on the CPU mesh:

  (a) forest bit-identity — N staged streaming segments folded inside
      one bounded device program (ops/elim.py batch_segment_fixpoint)
      must reproduce the per-segment path's elimination forest exactly,
      at every batch size including the N=1 degenerate batch (the
      fixpoint is unique given the constraint multiset);
  (b) dispatch-count drop — host->device syncs per chunk fall from
      O(segments) to O(segments / N), asserted from the deterministic
      ``host_syncs``/``device_rounds`` counters that feed the
      count x round-cost A/B attribution
      (sheep_tpu.utils.metrics.solve_dispatch_attribution).
"""

import numpy as np
import pytest

import jax.numpy as jnp

from sheep_tpu.backends.tpu_backend import TpuBackend, pad_chunk
from sheep_tpu.core import pure
from sheep_tpu.io import generators
from sheep_tpu.io.edgestream import EdgeStream
from sheep_tpu.ops import degrees as degrees_ops
from sheep_tpu.ops import elim as elim_ops
from sheep_tpu.ops import order as order_ops
from sheep_tpu.utils.membudget import build_phase_bytes, dispatch_batch_for
from sheep_tpu.utils.metrics import solve_dispatch_attribution


def _order(e, n):
    deg = degrees_ops.init_degrees(n)
    deg = degrees_ops.degree_chunk(deg, pad_chunk(e, len(e), n), n)
    return order_ops.elimination_order(deg, n)


def _staged_blocks(e, cs, n, pos, batch):
    """Pad the edge stream into [batch, cs] oriented position blocks
    (sentinel rows fill the tail group, as the backend does)."""
    chunks = [pad_chunk(e[off:off + cs], cs, n)
              for off in range(0, len(e), cs)]
    while len(chunks) % batch:
        chunks.append(np.full((cs, 2), n, np.int32))
    return [elim_ops.orient_chunks_batch_pos(
                jnp.asarray(np.stack(chunks[i:i + batch])), pos, n)
            for i in range(0, len(chunks), batch)]


@pytest.mark.parametrize("batch", [1, 2, 4])
def test_batched_dispatch_matches_oracle_rmat14(batch):
    """Oracle equality at RMAT-14 across batch sizes, including the N=1
    degenerate batch (acceptance criterion of the batched dispatch)."""
    e = generators.rmat(14, 4, seed=7)
    n = 1 << 14
    pos, order = _order(e, n)
    whole, _ = elim_ops.build_chunk_step(
        jnp.full(n + 1, n, dtype=jnp.int32), pad_chunk(e, len(e), n),
        pos, order, n)
    P = jnp.full(n + 1, n, dtype=jnp.int32)
    for loB, hiB in _staged_blocks(e, 1 << 13, n, pos, batch):
        P, _ = elim_ops.fold_segments_batch(P, loB, hiB, n,
                                            segment_rounds=2)
    np.testing.assert_array_equal(np.asarray(P[pos]), np.asarray(whole))


def test_batch_program_resumes_after_budget_exhaustion():
    """A round budget too small to finish one execution must leave
    resumable blocks: re-dispatching the returned state converges to the
    identical forest (the on-device stop condition contract)."""
    e = generators.rmat(10, 8, seed=3)
    n = 1 << 10
    pos, order = _order(e, n)
    whole, _ = elim_ops.build_chunk_step(
        jnp.full(n + 1, n, dtype=jnp.int32), pad_chunk(e, len(e), n),
        pos, order, n)
    (loB, hiB), = _staged_blocks(e, len(e), n, pos, 1)
    P = jnp.full(n + 1, n, dtype=jnp.int32)
    execs = 0
    while True:
        loB, hiB, P, sv = elim_ops.fold_segments_batch_pos(
            P, loB, hiB, n, batch_rounds=3)  # far below the round need
        execs += 1
        if int(np.asarray(sv)[0]) >= 1:
            break
        assert execs < 1000
    assert execs > 1  # the tiny budget really did exhaust mid-segment
    np.testing.assert_array_equal(np.asarray(P[pos]), np.asarray(whole))


def test_batched_stats_word_shape():
    """The packed stats word is int32[4] = (segments_done, rounds, live,
    retired): done == N and live == 0 after convergence, retires equal
    the slots that went dead."""
    e = generators.rmat(9, 8, seed=1)
    n = 512
    pos, order = _order(e, n)
    (loB, hiB), = _staged_blocks(e, len(e), n, pos, 2)
    live0 = int(jnp.sum(loB != n))
    P = jnp.full(n + 1, n, dtype=jnp.int32)
    loB, hiB, P, sv = elim_ops.fold_segments_batch_pos(
        P, loB, hiB, n, batch_rounds=1 << 14)
    done, rounds, live, retired = (int(x) for x in np.asarray(sv))
    assert done == 2 and live == 0
    assert 0 < rounds < 1 << 14
    # every initially-live slot dies exactly once; displacement reuse can
    # add deaths but never remove one
    assert retired >= live0 > 0


def test_small_explicit_batch_rounds_still_converges():
    """An explicit per-execution round budget below N used to stall the
    segment cursor forever on already-converged prefixes (each costs one
    confirmation round, and every execution restarts at segment 0), then
    silently return an unconverged forest at the max_rounds backstop —
    the budget is now clamped to N (review finding)."""
    e = generators.rmat(10, 8, seed=9)
    n = 1 << 10
    pos, order = _order(e, n)
    cs = 256
    N = 4
    oracle = None
    P = jnp.full(n + 1, n, dtype=jnp.int32)
    for loB, hiB in _staged_blocks(e, cs, n, pos, N):
        stats: dict = {}
        P, _ = elim_ops.fold_segments_batch(P, loB, hiB, n,
                                            batch_rounds=1, stats=stats)
        assert "batch_incomplete_segments" not in stats, stats
    ref = jnp.full(n + 1, n, dtype=jnp.int32)
    for loB, hiB in _staged_blocks(e, cs, n, pos, N):
        ref, _ = elim_ops.fold_segments_batch(ref, loB, hiB, n,
                                              segment_rounds=2)
    np.testing.assert_array_equal(np.asarray(P), np.asarray(ref))


def test_dispatch_count_drops_o_segments_over_n():
    """The acceptance criterion: host syncs per chunk drop from
    O(segments) to O(segments / N). A = the per-segment driver (one sv
    pull per bounded fold_segment_pos execution), B = the batched
    dispatch at N=4 with the same per-segment round allowance. Counters
    are deterministic on the CPU mesh, so the assertion needs no timing."""
    e = generators.rmat(12, 8, seed=5)
    n = 1 << 12
    pos, order = _order(e, n)
    cs = 1024
    chunks = [pad_chunk(e[off:off + cs], cs, n)
              for off in range(0, len(e), cs)]

    sa = {"host_syncs": 0, "device_rounds": 0}
    P = jnp.full(n + 1, n, dtype=jnp.int32)
    for c in chunks:
        loP, hiP = elim_ops.orient_edges_pos(jnp.asarray(c), pos, n)
        while True:
            loP, hiP, P, sv = elim_ops.fold_segment_pos(
                P, loP, hiP, n, segment_rounds=2)
            changed, r, live = (int(x) for x in np.asarray(sv))
            sa["host_syncs"] += 1
            sa["device_rounds"] += r
            if not changed or live == 0:
                break

    N = 4
    sb: dict = {}
    Pb = jnp.full(n + 1, n, dtype=jnp.int32)
    for loB, hiB in _staged_blocks(e, cs, n, pos, N):
        Pb, _ = elim_ops.fold_segments_batch(Pb, loB, hiB, n,
                                             segment_rounds=2, stats=sb)

    np.testing.assert_array_equal(np.asarray(P), np.asarray(Pb))
    assert sa["host_syncs"] >= len(chunks)  # O(segments): >= 1 per chunk
    # O(segments / N): comfortably under half at N=4 (segment-transition
    # rounds cost the batched path a little, so not exactly 1/4)
    assert sb["host_syncs"] * 2 <= sa["host_syncs"], (sa, sb)


def test_solve_dispatch_attribution_exact():
    """The count x round-cost solver recovers planted coefficients
    exactly and reports degenerate systems as None."""
    pd, pr = 0.073, 0.0021  # per-dispatch RTT, per-round device cost
    a = {"syncs": 200, "rounds": 420}
    b = {"syncs": 55, "rounds": 460}
    a["wall_s"] = a["syncs"] * pd + a["rounds"] * pr
    b["wall_s"] = b["syncs"] * pd + b["rounds"] * pr
    out = solve_dispatch_attribution(a, b)
    assert abs(out["per_dispatch_s"] - pd) < 1e-12
    assert abs(out["per_round_s"] - pr) < 1e-12
    assert solve_dispatch_attribution(a, a) is None


@pytest.mark.parametrize("db", [2, 4])
def test_backend_dispatch_batch_bit_identical(db):
    """End-to-end TpuBackend equality: batched dispatch vs the default
    per-segment driver (auto resolves to 1 on cpu-jax), multi-chunk
    stream with a sentinel-padded tail group."""
    e = generators.rmat(11, 8, seed=9)
    n = 1 << 11
    es = EdgeStream.from_array(e, n_vertices=n)
    base = TpuBackend(chunk_edges=512).partition(es, 8)
    ref = pure.partition_arrays(e, 8, n=n)
    np.testing.assert_array_equal(base.assignment, ref.assignment)
    got = TpuBackend(chunk_edges=512, dispatch_batch=db).partition(es, 8)
    np.testing.assert_array_equal(got.assignment, base.assignment)
    assert got.edge_cut == base.edge_cut
    assert got.comm_volume == base.comm_volume
    assert got.diagnostics["dispatch_batch"] == db
    assert got.diagnostics["host_syncs"] > 0


def test_backend_dispatch_batch_excludes_tail_strategies():
    with pytest.raises(ValueError, match="dispatch_batch"):
        TpuBackend(dispatch_batch=2, carry_tail=True)
    with pytest.raises(ValueError, match="dispatch_batch"):
        TpuBackend(dispatch_batch=-1)


def test_sharded_pipeline_dispatch_batch_matches():
    """The sharded pipeline's batch staging (one replicated stats pull
    per bounded execution, pmin-done lockstep) must match the
    per-segment sharded run on the 8-device virtual mesh."""
    from sheep_tpu.backends.base import get_backend, list_backends

    if "tpu-sharded" not in list_backends():
        pytest.skip("sharded backend unavailable")
    e = generators.rmat(11, 8, seed=9)
    n = 1 << 11
    es = EdgeStream.from_array(e, n_vertices=n)
    base = get_backend("tpu-sharded", chunk_edges=256).partition(
        es, 8, comm_volume=False)
    got = get_backend("tpu-sharded", chunk_edges=256,
                      dispatch_batch=2).partition(es, 8, comm_volume=False)
    np.testing.assert_array_equal(got.assignment, base.assignment)
    assert got.edge_cut == base.edge_cut
    assert got.diagnostics["dispatch_batch"] == 2
    assert got.diagnostics["host_syncs"] > 0


def test_membudget_staging_model():
    """The [N, C] staging blocks are counted (the O(C) transient
    invariant becomes O(N*C)) and the auto-sizer returns the largest
    power-of-two N that fits."""
    n, cs = 1 << 20, 1 << 16
    base = build_phase_bytes(n, cs)
    b4 = build_phase_bytes(n, cs, dispatch_batch=4)
    assert b4["staging_bytes"] == 4 * 4 * cs * 4
    assert b4["total_bytes"] == base["total_bytes"] + b4["staging_bytes"]
    exactly4 = build_phase_bytes(n, cs, dispatch_batch=4)["total_bytes"]
    assert dispatch_batch_for(exactly4, n, cs) == 4
    assert dispatch_batch_for(0, n, cs) == 1
    big = build_phase_bytes(n, cs, dispatch_batch=1 << 10)["total_bytes"]
    assert dispatch_batch_for(big, n, cs) == 16  # capped


def test_cli_dispatch_batch_flag(tmp_path, capsys):
    """--dispatch-batch plumbs through the CLI to the backend and the
    batched run scores identically to the default."""
    import json

    from sheep_tpu.cli import main as cli_main
    from sheep_tpu.io import formats

    p = tmp_path / "g.edges"
    formats.write_edges(str(p), generators.rmat(9, 8, seed=2))
    assert cli_main(["--input", str(p), "--k", "4", "--backend", "tpu",
                     "--json", "--chunk-edges", "128"]) == 0
    base = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert cli_main(["--input", str(p), "--k", "4", "--backend", "tpu",
                     "--json", "--chunk-edges", "128",
                     "--dispatch-batch", "4"]) == 0
    got = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert got["edge_cut"] == base["edge_cut"]
    assert got["comm_volume"] == base["comm_volume"]
