"""sheepd / sheep_tpu.server tests (ISSUE 10).

The acceptance pins, against the in-process Scheduler (the daemon's
socket layer is exercised end-to-end by tools/obs_smoke.sh leg 6 via
test_obs_smoke, and the fault legs by tools/served_soak.py):

- a served job's forest bit-equals the cold CLI build of the same
  input, and a repeat request reuses every compiled program
  (jit_compiles == 0 — the warm-server guarantee);
- two concurrently submitted jobs INTERLEAVE on one dispatch chain
  and each bit-equals its solo run (per-job fixpoint independence);
- admission: a job over a tiny SHEEP_CACHE_BYTES budget is rejected
  with a modeled-bytes diagnosis; jobs that fit the budget but not
  the headroom queue and run serially;
- cancellation frees the queue (a queued job admits the moment the
  blocking job is cancelled);
- a deadline-expired job reports deadline_exceeded without poisoning
  the dispatch chain (the jobs around it stay bit-identical);
- the per-job fault layer: an injected OOM and an injected read fault
  each degrade the job on record, bit-identically, with the daemon
  (scheduler) still serving afterwards.
"""

import json
import os
import subprocess
import sys
import threading
import time
from contextlib import contextmanager

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

from sheep_tpu.server import protocol  # noqa: E402
from sheep_tpu.server.protocol import JobSpec, ProtocolError  # noqa: E402
from sheep_tpu.server.scheduler import Scheduler  # noqa: E402

INPUT_A = "rmat:10:8:1"
INPUT_B = "rmat:10:8:2"
CHUNK = 1024


@contextmanager
def running_scheduler(**kw):
    sched = Scheduler(**kw)
    t = threading.Thread(target=sched.run, daemon=True,
                         name="test-sheepd-dispatch")
    t.start()
    try:
        yield sched
    finally:
        sched.shutdown()
        t.join(timeout=30)
        assert not t.is_alive(), "dispatch loop failed to shut down"


def spec(input=INPUT_A, ks=(4,), tenant="t", **fields):
    body = {"input": input, "k": list(ks), "chunk_edges": CHUNK}
    body.update(fields)
    return JobSpec.from_request(body, tenant=tenant)


def serve_one(sched, sp, timeout=240):
    job = sched.submit(sp)
    job = sched.wait(job.id, timeout_s=timeout)
    return job


def solo_assignment(input, k, chunk_edges=CHUNK):
    import sheep_tpu

    return sheep_tpu.partition(input, k, backend="tpu",
                               chunk_edges=chunk_edges,
                               comm_volume=False).assignment


def test_served_bit_equals_cli_build(tmp_path):
    """Acceptance: the served forest is bit-identical to the cold CLI
    build of the same input, and the scores agree."""
    out = tmp_path / "cli.parts"
    from sheep_tpu import cli

    rc = cli.main(["--input", INPUT_A, "--k", "4", "--backend", "tpu",
                   "--chunk-edges", str(CHUNK), "--no-comm-volume",
                   "--output", str(out), "--json"])
    assert rc == 0
    from sheep_tpu.io.formats import read_partition

    cli_assign = read_partition(str(out))
    with running_scheduler() as sched:
        job = serve_one(sched, spec())
        assert job.state == "done", job.error
        res = job.results[0]
        assert np.array_equal(res.assignment, cli_assign)
        assert res.backend == "sheepd"
        assert res.edge_cut > 0 and res.total_edges > 0


def test_warm_repeat_request_zero_recompiles():
    """Acceptance: a warm sheepd serves a repeat request with ZERO jit
    recompilation — the compile-cache counter on the job descriptor
    proves the fixpoint/degree/order/score programs were reused."""
    with running_scheduler() as sched:
        first = serve_one(sched, spec())
        repeat = serve_one(sched, spec(tenant="again"))
        assert first.state == "done" and repeat.state == "done"
        assert repeat.jit_compiles == 0, \
            f"repeat shape recompiled {repeat.jit_compiles} programs"
        assert np.array_equal(first.results[0].assignment,
                              repeat.results[0].assignment)


def test_interleaved_jobs_bit_equal_solo_runs():
    """Acceptance: two concurrently submitted jobs interleave on one
    dispatch chain and EACH produces the forest of its solo run."""
    ref_a = solo_assignment(INPUT_A, 4)
    ref_b = solo_assignment(INPUT_B, 4)
    with running_scheduler() as sched:
        ja = sched.submit(spec(INPUT_A, tenant="alice"))
        jb = sched.submit(spec(INPUT_B, tenant="bob"))
        ja = sched.wait(ja.id, timeout_s=240)
        jb = sched.wait(jb.id, timeout_s=240)
        assert ja.state == "done" and jb.state == "done"
        # genuinely concurrent: each started before the other finished
        assert ja.start_t < jb.end_t and jb.start_t < ja.end_t
        assert np.array_equal(ja.results[0].assignment, ref_a)
        assert np.array_equal(jb.results[0].assignment, ref_b)


def test_multi_k_query_one_shared_tree():
    """Multi-k from one shared tree is one served query: one build,
    one scoring pass, per-k results matching the solo builds."""
    with running_scheduler() as sched:
        job = serve_one(sched, spec(ks=(4, 8)))
        assert job.state == "done"
        assert [r.k for r in job.results] == [4, 8]
        for r in job.results:
            assert np.array_equal(r.assignment,
                                  solo_assignment(INPUT_A, r.k))
        # one build amortized: the per-k phase walls are shared
        assert job.results[0].total_edges == job.results[1].total_edges


def test_admission_rejects_over_tiny_budget(monkeypatch):
    """Acceptance: under a tiny SHEEP_CACHE_BYTES budget the job's
    modeled footprint cannot fit even at dispatch_batch=1 — REJECTED
    with the modeled-bytes diagnosis, not queued forever."""
    monkeypatch.setenv("SHEEP_CACHE_BYTES", "10000")
    with running_scheduler() as sched:
        assert sched.budget == 10000
        job = serve_one(sched, spec(), timeout=30)
        assert job.state == "rejected"
        assert "admission budget" in (job.error or "")
        assert "10,000" in job.error


def test_admission_queues_on_headroom_then_serializes():
    """Two jobs that each fit the budget but not together: the second
    queues and starts only after the first releases its reservation."""
    from sheep_tpu.utils import membudget

    n = 1 << 10
    m = membudget.build_phase_bytes(n, CHUNK,
                                    dispatch_batch=1)["total_bytes"]
    with running_scheduler(budget_bytes=int(1.5 * m)) as sched:
        ja = sched.submit(spec(INPUT_A, dispatch_batch=1))
        jb = sched.submit(spec(INPUT_B, dispatch_batch=1))
        ja = sched.wait(ja.id, timeout_s=240)
        jb = sched.wait(jb.id, timeout_s=240)
        assert ja.state == "done" and jb.state == "done"
        assert jb.start_t >= ja.end_t, \
            "second job admitted before the first released its bytes"


def test_cancellation_frees_the_queue():
    """Acceptance: cancelling the running job admits the queued one
    immediately; cancelling a queued job removes it outright."""
    from sheep_tpu.utils import membudget

    # budget fits the big victim alone; the small jobs queue behind it
    mv = membudget.build_phase_bytes(1 << 12, 256,
                                     dispatch_batch=1)["total_bytes"]
    with running_scheduler(budget_bytes=int(1.1 * mv)) as sched:
        victim = sched.submit(JobSpec.from_request(
            {"input": "rmat:12:8:3", "k": [4], "chunk_edges": 256,
             "dispatch_batch": 1}, tenant="victim"))
        jb = sched.submit(spec(INPUT_B, dispatch_batch=1))
        jc = sched.submit(spec(INPUT_A, dispatch_batch=1))
        # cancel the queued c first: it must leave the queue now
        assert sched.cancel(jc.id) == "cancelled"
        deadline = time.monotonic() + 30
        while sched.get(victim.id).state == "queued" \
                and time.monotonic() < deadline:
            time.sleep(0.005)
        sched.cancel(victim.id)
        victim = sched.wait(victim.id, timeout_s=60)
        jb = sched.wait(jb.id, timeout_s=240)
        assert victim.state == "cancelled"
        assert jb.state == "done", jb.error
        assert np.array_equal(jb.results[0].assignment,
                              solo_assignment(INPUT_B, 4))


def test_deadline_exceeded_does_not_poison_the_chain():
    """Acceptance: a deadline-expired job reports deadline_exceeded;
    the jobs interleaved around it finish bit-identical — the dispatch
    chain is not poisoned."""
    ref_b = solo_assignment(INPUT_B, 4)
    with running_scheduler() as sched:
        doomed = sched.submit(JobSpec.from_request(
            {"input": "rmat:12:8:3", "k": [4], "chunk_edges": 256,
             "deadline_s": 0.005}, tenant="doomed"))
        jb = sched.submit(spec(INPUT_B, tenant="bob"))
        doomed = sched.wait(doomed.id, timeout_s=120)
        jb = sched.wait(jb.id, timeout_s=240)
        assert doomed.state == "deadline_exceeded"
        assert jb.state == "done", jb.error
        assert np.array_equal(jb.results[0].assignment, ref_b)
        # and the daemon keeps serving: one more job end-to-end
        again = serve_one(sched, spec(INPUT_B))
        assert again.state == "done"
        assert np.array_equal(again.results[0].assignment, ref_b)


def test_served_job_absorbs_oom_and_read_faults(tmp_path, monkeypatch):
    """The served mini-soak's tier-1 twin (the full daemon-subprocess
    version is tools/served_soak.py, pinned @slow below): one injected
    OOM at the first dispatch and one injected read fault each degrade
    the JOB on record — bit-identical result, retry trail in the
    diagnostics — with the scheduler still serving afterwards."""
    from sheep_tpu.io import formats, generators
    from sheep_tpu.utils import fault

    graph = str(tmp_path / "soak.bin64")
    formats.write_edges(graph,
                        generators.random_graph(512, 4096, seed=7))
    ref = None
    with running_scheduler() as sched:
        clean = serve_one(sched, JobSpec.from_request(
            {"input": graph, "k": [4], "chunk_edges": 512,
             "num_vertices": 512}, tenant="clean"))
        assert clean.state == "done"
        ref = clean.results[0].assignment
        for inject, want_retry in (("oom@dispatch:1", True),
                                   ("read@read:2", False)):
            monkeypatch.setenv("SHEEP_FAULT_INJECT", inject)
            monkeypatch.setenv("SHEEP_RETRY_BASE_S", "0.01")
            fault.reset()
            try:
                job = serve_one(sched, JobSpec.from_request(
                    {"input": graph, "k": [4], "chunk_edges": 512,
                     "num_vertices": 512}, tenant=inject))
            finally:
                monkeypatch.delenv("SHEEP_FAULT_INJECT")
                fault.reset()
            assert job.state == "done", (inject, job.error)
            assert np.array_equal(job.results[0].assignment, ref), inject
            if want_retry:
                assert job.stats.get("dispatch_retries", 0) >= 1, \
                    "OOM injection left no retry trail"


def test_job_fault_budget_exhaustion_fails_job_not_daemon(monkeypatch):
    """A fault storm beyond the retry budget fails THAT job; the
    scheduler answers the next request normally."""
    from sheep_tpu.utils import fault

    monkeypatch.setenv("SHEEP_FAULT_INJECT", "oom@dispatch:1:99")
    monkeypatch.setenv("SHEEP_RETRY_BASE_S", "0.001")
    monkeypatch.setenv("SHEEP_RETRY_MAX", "2")
    fault.reset()
    with running_scheduler() as sched:
        doomed = serve_one(sched, spec(tenant="doomed"))
        assert doomed.state == "failed"
        assert "RESOURCE_EXHAUSTED" in doomed.error
        monkeypatch.delenv("SHEEP_FAULT_INJECT")
        fault.reset()
        ok = serve_one(sched, spec(tenant="after"))
        assert ok.state == "done", ok.error


def test_protocol_validation_and_codec():
    with pytest.raises(ProtocolError):
        JobSpec.from_request({"k": [4]})          # no input
    with pytest.raises(ProtocolError):
        JobSpec.from_request({"input": "g", "k": []})
    with pytest.raises(ProtocolError):
        JobSpec.from_request({"input": "g", "k": [0]})
    with pytest.raises(ProtocolError):
        JobSpec.from_request({"input": "g", "k": 4, "bogus": 1})
    with pytest.raises(ProtocolError):
        JobSpec.from_request({"input": "g", "k": 4, "deadline_s": -1})
    sp = JobSpec.from_request({"input": "g", "k": [8, 8, 4]})
    assert sp.ks == [8, 4]  # dupes dropped, order kept
    # update_backend (ISSUE 19): resident epochs may fold multi-device
    assert sp.update_backend == "tpu"  # the single-device default
    sh = JobSpec.from_request({"input": "g", "k": 4, "resident": True,
                               "update_backend": "tpu-sharded"})
    assert sh.update_backend == "tpu-sharded"
    with pytest.raises(ProtocolError, match="update_backend"):
        JobSpec.from_request({"input": "g", "k": 4,
                              "update_backend": "gpu"})
    a = np.arange(1000, dtype=np.int32) % 7
    assert np.array_equal(
        protocol.decode_assignment(protocol.encode_assignment(a)), a)
    with pytest.raises(ProtocolError):
        protocol.parse_request(b'{"op": "frobnicate"}')
    req = protocol.parse_request(b'{"op": "ping"}')
    assert req["op"] == "ping"


def test_terminal_jobs_evicted_beyond_retention_cap(monkeypatch):
    """A resident daemon must not grow host memory monotonically with
    traffic: terminal jobs (and their result arrays) beyond the
    retention cap are evicted oldest-first (review finding)."""
    monkeypatch.setattr(Scheduler, "MAX_TERMINAL_RETAINED", 3)
    with running_scheduler() as sched:
        ids = [serve_one(sched, spec(tenant=f"t{i}")).id
               for i in range(5)]
        assert sched.get(ids[0]) is None and sched.get(ids[1]) is None
        for jid in ids[2:]:
            assert sched.get(jid) is not None
            assert sched.get(jid).state == "done"


def test_submit_unopenable_input_is_answered_not_enqueued():
    with running_scheduler() as sched:
        with pytest.raises(ProtocolError, match="cannot open"):
            sched.submit(spec("/nonexistent/graph.bin64"))
        assert sched.stats()["jobs"]["submitted"] == 0


# ---------------------------------------------------------------------------
# live telemetry plane (ISSUE 11)
# ---------------------------------------------------------------------------

def test_metrics_expose_per_tenant_latency_under_two_jobs():
    """Acceptance: with two concurrent tenant jobs served, the
    scheduler's Prometheus rendering carries per-tenant request-latency
    histograms, live queue/reservation gauges, and the submitted/
    terminal counters — the series a replica router would route on."""
    from sheep_tpu.obs.metrics import parse_prometheus

    with running_scheduler() as sched:
        ja = sched.submit(spec(INPUT_A, tenant="alice"))
        jb = sched.submit(spec(INPUT_B, tenant="bob"))
        ja = sched.wait(ja.id, timeout_s=240)
        jb = sched.wait(jb.id, timeout_s=240)
        assert ja.state == "done" and jb.state == "done"
        assert ja.start_t < jb.end_t and jb.start_t < ja.end_t
        parsed = parse_prometheus(sched.render_metrics())
    counts = dict()
    for labels, v in parsed["sheepd_request_latency_seconds_count"]:
        counts[labels["tenant"]] = v
    assert counts == {"alice": 1.0, "bob": 1.0}
    assert ({"le": "+Inf", "tenant": "alice"}, 1.0) in \
        parsed["sheepd_request_latency_seconds_bucket"]
    assert parsed["sheepd_queue_depth"][0][1] == 0.0
    assert parsed["sheepd_active_jobs"][0][1] == 0.0
    submitted = {lb["tenant"]: v
                 for lb, v in parsed["sheepd_jobs_submitted_total"]}
    assert submitted == {"alice": 1.0, "bob": 1.0}
    done = {(lb["tenant"], lb["state"]): v
            for lb, v in parsed["sheepd_jobs_terminal_total"]}
    assert done[("alice", "done")] == 1.0
    # queue-wait observed for both admissions
    qw = {lb["tenant"]: v
          for lb, v in parsed["sheepd_queue_wait_seconds_count"]}
    assert qw == {"alice": 1.0, "bob": 1.0}
    # live progress surfaced while running: phase/steps on descriptors
    assert ja.phase == "score" and ja.steps > 0
    assert ja.descriptor()["phase"] == "score"
    # the quality plane (ISSUE 13): per-tenant cut/balance
    # distributions observed at DONE, per-job gauges for recent
    # results, and the engine's job_quality value matching the
    # scraped gauge exactly
    qcut = {lb["tenant"]: v
            for lb, v in parsed["sheep_quality_cut_ratio_count"]}
    assert qcut == {"alice": 1.0, "bob": 1.0}
    qbal = {lb["tenant"]: v
            for lb, v in parsed["sheep_quality_balance_count"]}
    assert qbal == {"alice": 1.0, "bob": 1.0}
    jobs_cut = {lb["job"]: v
                for lb, v in parsed["sheep_quality_job_cut_ratio"]}
    assert jobs_cut[ja.id] == pytest.approx(
        float(ja.results[0].cut_ratio), abs=1e-6)
    jobs_bal = {(lb["job"], lb["k"]): v
                for lb, v in parsed["sheep_quality_job_balance"]}
    assert jobs_bal[(jb.id, "4")] == pytest.approx(
        float(jb.results[0].balance), abs=1e-4)


def test_active_job_progress_gauges_live_mid_build():
    """Mid-build scrape shows the per-active-job progress gauges and a
    nonzero active count; the gauges leave the scrape once the job is
    terminal (no frozen series)."""
    from sheep_tpu.obs.metrics import parse_prometheus

    with running_scheduler() as sched:
        job = sched.submit(JobSpec.from_request(
            {"input": "rmat:12:8:3", "k": [4], "chunk_edges": 256},
            tenant="alice"))
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if sched.get(job.id).steps > 0:
                break
            time.sleep(0.01)
        parsed = parse_prometheus(sched.render_metrics())
        assert parsed["sheepd_active_jobs"][0][1] >= 1.0
        rows = parsed.get("sheepd_job_steps", [])
        assert any(lb == {"job": job.id, "tenant": "alice"} and v >= 1
                   for lb, v in rows), rows
        job = sched.wait(job.id, timeout_s=240)
        assert job.state == "done"
        parsed = parse_prometheus(sched.render_metrics())
        assert not parsed.get("sheepd_job_steps")


def test_failed_job_leaves_flight_dump_with_fault_event(tmp_path,
                                                        monkeypatch):
    """Acceptance: a job failed by an injected fault leaves a
    flight-recorder dump in the trace containing the fault event —
    and trace_report --last-errors renders it."""
    from sheep_tpu import obs
    from sheep_tpu.utils import fault

    trace = tmp_path / "served.jsonl"
    monkeypatch.setenv("SHEEP_FAULT_INJECT", "oom@dispatch:1:99")
    monkeypatch.setenv("SHEEP_RETRY_BASE_S", "0.001")
    monkeypatch.setenv("SHEEP_RETRY_MAX", "2")
    fault.reset()
    try:
        with obs.tracing(str(trace)):
            with running_scheduler() as sched:
                doomed = serve_one(sched, spec(tenant="doomed"))
                assert doomed.state == "failed"
    finally:
        monkeypatch.delenv("SHEEP_FAULT_INJECT")
        fault.reset()
    dumps = [json.loads(line) for line in
             trace.read_text().splitlines()
             if '"flight_dump"' in line]
    failed = [d for d in dumps if d["job"] == doomed.id
              and d["reason"].startswith("job_failed")]
    assert failed, [d.get("reason") for d in dumps]
    kinds = [e["ev"] for e in failed[-1]["events"]]
    assert "fault_inject" in kinds and "retry" in kinds
    assert "job_done" in kinds  # the terminal event made the ring
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "trace_report.py"),
         str(trace), "--last-errors", "6"],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert r.returncode == 0
    assert "job_failed" in r.stdout and "fault_inject" in r.stdout


def test_daemon_metrics_verb_http_scrape_and_profile(tmp_path):
    """The daemon end of the tentpole, in-process: the `metrics` verb
    and HTTP GET /metrics answer the same exposition, and the
    `profile` verb captures the next K dispatch steps into the
    requested directory."""
    import urllib.request

    from sheep_tpu.server.client import SheepClient, ServerError
    from sheep_tpu.server.daemon import Daemon, build_parser

    sock = str(tmp_path / "d.sock")
    prof_dir = str(tmp_path / "prof")
    args = build_parser().parse_args(
        ["--socket", sock, "--metrics-port", "0"])
    d = Daemon(args)
    t = threading.Thread(target=d.serve, daemon=True,
                         name="test-sheepd")
    t.start()
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        if os.path.exists(sock) and d.metrics_port:
            break
        time.sleep(0.05)
    assert os.path.exists(sock), "daemon never bound its socket"
    try:
        with SheepClient(sock) as c:
            prof = c.profile(prof_dir, steps=2)
            assert prof["state"] == "armed"
            with pytest.raises(ServerError, match="already"):
                c.profile(prof_dir, steps=2)
            jid = c.submit(INPUT_A, k=4, tenant="alice",
                           chunk_edges=CHUNK)["job_id"]
            job = c.wait(jid, timeout_s=240)
            assert job["state"] == "done"
            verb_text = c.metrics()
            http_text = urllib.request.urlopen(
                f"http://127.0.0.1:{d.metrics_port}/metrics",
                timeout=10).read().decode()
            for text in (verb_text, http_text):
                assert 'sheepd_request_latency_seconds_count' \
                       '{tenant="alice"} 1' in text
                assert "sheepd_queue_depth" in text
            assert c.stats()["profile"]["state"] == "done"
            with pytest.raises(urllib.error.HTTPError):
                urllib.request.urlopen(
                    f"http://127.0.0.1:{d.metrics_port}/nope",
                    timeout=10)
            c.shutdown()
    finally:
        t.join(timeout=60)
    assert not t.is_alive(), "daemon failed to shut down"
    captured = [f for _, _, fs in os.walk(prof_dir) for f in fs]
    assert captured, "profile verb captured nothing into the dir"


def test_profile_arm_validation():
    with running_scheduler() as sched:
        with pytest.raises(ProtocolError):
            sched.arm_profile("/tmp/x", steps=0)
        with pytest.raises(ProtocolError):
            sched.arm_profile("/tmp/x", steps="nope")


def test_profile_capture_stops_when_jobs_drain(tmp_path):
    """Regression: a capture armed for more steps than the job set
    will ever take must STOP when the daemon goes idle (an open
    jax.profiler capture grows host memory forever and blocks every
    re-arm) — and the next arm succeeds."""
    with running_scheduler() as sched:
        sched.arm_profile(str(tmp_path / "p1"), steps=10_000)
        job = serve_one(sched, spec())
        assert job.state == "done"
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            prof = sched.stats()["profile"]
            if prof and prof.get("state") in ("aborted", "done",
                                              "error"):
                break
            time.sleep(0.05)
        assert prof["state"] == "aborted", prof
        assert prof["steps_captured"] >= 1
        assert "remaining" not in prof  # internals stay internal
        # the slot is free again
        assert sched.arm_profile(str(tmp_path / "p2"),
                                 steps=5)["state"] == "armed"


@pytest.mark.slow
def test_served_soak_tool():
    """The full daemon-subprocess mini-soak: one oom + one read leg,
    plus the durable restart (SIGKILL) and drain (SIGTERM) legs,
    through real sheepds on unix sockets (see tools/served_soak.py);
    the tier-1 twins (here and tests/test_journal.py) cover the same
    faults in-process."""
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "served_soak.py")],
        cwd=REPO, env={**os.environ, "JAX_PLATFORMS": "cpu",
                       "PYTHONPATH": REPO},
        capture_output=True, text=True, timeout=1200)
    assert r.returncode == 0, f"stdout:\n{r.stdout}\nstderr:\n{r.stderr}"
    verdicts = [json.loads(line) for line in r.stdout.splitlines()]
    assert verdicts[-1]["ok"] is True


def _await_published(sched, digest, timeout=30.0):
    """The store publish runs post-terminal on the dispatch thread —
    poll the advisory lookup until the digest lands."""
    deadline = time.monotonic() + timeout
    while not sched.lookup_digest(digest) \
            and time.monotonic() < deadline:
        time.sleep(0.01)
    assert sched.lookup_digest(digest), "store publish never landed"


def test_result_cache_hit_zero_steps_bit_identical(tmp_path):
    """Acceptance (ISSUE 16): a repeat submit of the same digest is
    answered FROM THE STORE — zero dispatch steps, zero compiles —
    and the decoded assignment + scores bit-equal the original."""
    with running_scheduler(result_store=str(tmp_path / "rs")) as sched:
        first = serve_one(sched, spec())
        assert first.state == "done", first.error
        assert first.stats.get("result_cache_hit") is None
        _await_published(sched, first.digest)
        repeat = serve_one(sched, spec())
        assert repeat.state == "done", repeat.error
        assert repeat.stats.get("result_cache_hit") == 1
        assert repeat.steps == 0, "a cache hit must never dispatch"
        assert repeat.jit_compiles == 0
        fr, rr = first.results[0], repeat.results[0]
        assert np.array_equal(fr.assignment, rr.assignment)
        assert (fr.edge_cut, fr.total_edges, fr.balance) \
            == (rr.edge_cut, rr.total_edges, rr.balance)
        # metrics plane: the hit and the miss both counted
        text = sched.metrics.render()
        assert "sheepd_result_cache_hits_total" in text
        assert "sheepd_result_cache_misses_total" in text


def test_result_cache_digest_sensitivity(tmp_path):
    """A different spec (other k) must MISS: content addressing keys
    the full spec digest, not the input alone."""
    with running_scheduler(result_store=str(tmp_path / "rs")) as sched:
        first = serve_one(sched, spec(ks=(4,)))
        _await_published(sched, first.digest)
        other = serve_one(sched, spec(ks=(8,)))
        assert other.state == "done", other.error
        assert other.stats.get("result_cache_hit") is None
        assert other.steps > 0


def test_resident_jobs_bypass_result_cache(tmp_path):
    """Resident submits carry incremental state a cached answer lacks
    — they must build even when the digest is stored."""
    with running_scheduler(result_store=str(tmp_path / "rs")) as sched:
        first = serve_one(sched, spec())
        _await_published(sched, first.digest)
        res = serve_one(sched, spec(resident=True))
        assert res.state == "done", res.error
        assert res.stats.get("result_cache_hit") is None
        assert res.steps > 0
        sched.cancel(res.id)  # release the residency reservation


@pytest.mark.parametrize("depth", (2, 3))
def test_pipelined_dispatch_bit_identical_to_depth_1(depth):
    """Acceptance (ISSUE 16): depth-D in-job pipelining reorders only
    WHEN host syncs happen, never what is computed — the forest
    bit-equals the depth-1 build."""
    with running_scheduler() as sched:
        base = serve_one(sched, spec(INPUT_B, inflight=1))
        piped = serve_one(sched, spec(INPUT_B, inflight=depth,
                                      tenant=f"d{depth}"))
        assert base.state == "done" and piped.state == "done", \
            (base.error, piped.error)
        assert piped.stats.get("inflight_depth") == depth
        assert np.array_equal(base.results[0].assignment,
                              piped.results[0].assignment)
        assert base.results[0].edge_cut == piped.results[0].edge_cut


def test_pipelined_checkpoint_resume_bit_identical(tmp_path):
    """A checkpoint taken mid-pipeline only covers CONFIRMED groups;
    resume re-folds the unconfirmed tail and still bit-equals the
    uninterrupted build."""
    ref = solo_assignment(INPUT_A, 4)
    with running_scheduler(checkpoint_every=2,
                           checkpoint_dir=str(tmp_path)) as sched:
        job = serve_one(sched, spec(inflight=2))
        assert job.state == "done", job.error
        assert np.array_equal(job.results[0].assignment, ref)


def test_concurrent_same_input_jobs_share_chunk_cache():
    """Two live jobs on ONE input: the second rides the first's device
    chunk cache as a reader (no duplicate device residency), both
    bit-equal the solo run."""
    ref = solo_assignment(INPUT_A, 4)
    with running_scheduler() as sched:
        ja = sched.submit(spec(INPUT_A, tenant="alice"))
        jb = sched.submit(spec(INPUT_A, tenant="bob", ks=(4,)))
        ja = sched.wait(ja.id, timeout_s=240)
        jb = sched.wait(jb.id, timeout_s=240)
        assert ja.state == "done" and jb.state == "done", \
            (ja.error, jb.error)
        assert np.array_equal(ja.results[0].assignment, ref)
        assert np.array_equal(jb.results[0].assignment, ref)


def test_pipelined_interleaved_overlap(tmp_path):
    """Acceptance (ISSUE 16): depth-2 pipelining turns an engine step
    into one CONFIRMED execution instead of one drained group, so two
    interleaved jobs overlap one job's host staging with the other's
    device folds — the interleaved wall lands under the sum of the
    solo walls. Host-format (text) inputs make staging real host
    work, and every serve gets a fresh path so the shared chunk
    cache cannot hide it. Wall-clock is noisy under CI load: any of
    three attempts under the 0.9 bar passes; a true serialization
    regression (ratio pinned at ~1.0) fails all three."""
    from sheep_tpu.io import formats, generators

    def fresh(seed, tag):
        st = generators.RmatHashStream(14, 8, seed=seed)
        es = np.concatenate([np.asarray(c)
                             for c in st.chunks(1 << 20)])
        p = str(tmp_path / f"{tag}.edges")
        formats.write_edges(p, es)
        return p

    def sp(path, tenant):
        return JobSpec.from_request(
            {"input": path, "k": [4], "chunk_edges": 4096,
             "inflight": 2}, tenant=tenant)

    with running_scheduler() as sched:
        def serve(s):
            job = sched.submit(s)
            job = sched.wait(job.id, timeout_s=240)
            assert job.state == "done", job.error

        serve(sp(fresh(9, "warm"), "warm"))  # compile warm-up
        ratios = []
        for attempt in range(3):
            t0 = time.perf_counter()
            serve(sp(fresh(1, f"solo_a{attempt}"), f"sa{attempt}"))
            solo_a = time.perf_counter() - t0
            t0 = time.perf_counter()
            serve(sp(fresh(2, f"solo_b{attempt}"), f"sb{attempt}"))
            solo_b = time.perf_counter() - t0
            pa = fresh(1, f"int_a{attempt}")
            pb = fresh(2, f"int_b{attempt}")
            t0 = time.perf_counter()
            ja = sched.submit(sp(pa, f"ia{attempt}"))
            jb = sched.submit(sp(pb, f"ib{attempt}"))
            ja = sched.wait(ja.id, timeout_s=240)
            jb = sched.wait(jb.id, timeout_s=240)
            wall = time.perf_counter() - t0
            assert ja.state == "done" and jb.state == "done", \
                (ja.error, jb.error)
            ratios.append(round(wall / (solo_a + solo_b), 3))
            if ratios[-1] < 0.9:
                return
        pytest.fail(
            f"no dispatch overlap measured: interleaved/sum ratios "
            f"{ratios} (expected < 0.9 in at least one attempt)")

def test_fleet_job_handles_survive_replica_id_collision():
    """Daemon job ids are per-process counters, so two replicas
    routinely both mint "j1". The fleet client must never guess
    between them: descriptors (endpoint + job_id) resolve exactly,
    and a bare id is honored only while unambiguous."""
    from sheep_tpu.server.client import FleetClient, ServerError

    fleet = FleetClient(["/run/a.sock", "/run/b.sock"])
    fleet._jobs[("/run/a.sock", "j1")] = (INPUT_A, [4], "alice", {})
    assert fleet._resolve("j1") == ("/run/a.sock", "j1")
    fleet._jobs[("/run/b.sock", "j1")] = (INPUT_B, [4], "bob", {})
    with pytest.raises(ServerError, match="ambiguous"):
        fleet._resolve("j1")
    assert fleet._resolve(
        {"endpoint": "/run/b.sock", "job_id": "j1"}) \
        == ("/run/b.sock", "j1")
    assert fleet._resolve(
        {"endpoint": "/run/a.sock", "job_id": "j1"}) \
        == ("/run/a.sock", "j1")
    with pytest.raises(ServerError, match="unknown fleet job"):
        fleet._resolve("j9")
