"""sheepd / sheep_tpu.server tests (ISSUE 10).

The acceptance pins, against the in-process Scheduler (the daemon's
socket layer is exercised end-to-end by tools/obs_smoke.sh leg 6 via
test_obs_smoke, and the fault legs by tools/served_soak.py):

- a served job's forest bit-equals the cold CLI build of the same
  input, and a repeat request reuses every compiled program
  (jit_compiles == 0 — the warm-server guarantee);
- two concurrently submitted jobs INTERLEAVE on one dispatch chain
  and each bit-equals its solo run (per-job fixpoint independence);
- admission: a job over a tiny SHEEP_CACHE_BYTES budget is rejected
  with a modeled-bytes diagnosis; jobs that fit the budget but not
  the headroom queue and run serially;
- cancellation frees the queue (a queued job admits the moment the
  blocking job is cancelled);
- a deadline-expired job reports deadline_exceeded without poisoning
  the dispatch chain (the jobs around it stay bit-identical);
- the per-job fault layer: an injected OOM and an injected read fault
  each degrade the job on record, bit-identically, with the daemon
  (scheduler) still serving afterwards.
"""

import json
import os
import subprocess
import sys
import threading
import time
from contextlib import contextmanager

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

from sheep_tpu.server import protocol  # noqa: E402
from sheep_tpu.server.protocol import JobSpec, ProtocolError  # noqa: E402
from sheep_tpu.server.scheduler import Scheduler  # noqa: E402

INPUT_A = "rmat:10:8:1"
INPUT_B = "rmat:10:8:2"
CHUNK = 1024


@contextmanager
def running_scheduler(**kw):
    sched = Scheduler(**kw)
    t = threading.Thread(target=sched.run, daemon=True,
                         name="test-sheepd-dispatch")
    t.start()
    try:
        yield sched
    finally:
        sched.shutdown()
        t.join(timeout=30)
        assert not t.is_alive(), "dispatch loop failed to shut down"


def spec(input=INPUT_A, ks=(4,), tenant="t", **fields):
    body = {"input": input, "k": list(ks), "chunk_edges": CHUNK}
    body.update(fields)
    return JobSpec.from_request(body, tenant=tenant)


def serve_one(sched, sp, timeout=240):
    job = sched.submit(sp)
    job = sched.wait(job.id, timeout_s=timeout)
    return job


def solo_assignment(input, k, chunk_edges=CHUNK):
    import sheep_tpu

    return sheep_tpu.partition(input, k, backend="tpu",
                               chunk_edges=chunk_edges,
                               comm_volume=False).assignment


def test_served_bit_equals_cli_build(tmp_path):
    """Acceptance: the served forest is bit-identical to the cold CLI
    build of the same input, and the scores agree."""
    out = tmp_path / "cli.parts"
    from sheep_tpu import cli

    rc = cli.main(["--input", INPUT_A, "--k", "4", "--backend", "tpu",
                   "--chunk-edges", str(CHUNK), "--no-comm-volume",
                   "--output", str(out), "--json"])
    assert rc == 0
    from sheep_tpu.io.formats import read_partition

    cli_assign = read_partition(str(out))
    with running_scheduler() as sched:
        job = serve_one(sched, spec())
        assert job.state == "done", job.error
        res = job.results[0]
        assert np.array_equal(res.assignment, cli_assign)
        assert res.backend == "sheepd"
        assert res.edge_cut > 0 and res.total_edges > 0


def test_warm_repeat_request_zero_recompiles():
    """Acceptance: a warm sheepd serves a repeat request with ZERO jit
    recompilation — the compile-cache counter on the job descriptor
    proves the fixpoint/degree/order/score programs were reused."""
    with running_scheduler() as sched:
        first = serve_one(sched, spec())
        repeat = serve_one(sched, spec(tenant="again"))
        assert first.state == "done" and repeat.state == "done"
        assert repeat.jit_compiles == 0, \
            f"repeat shape recompiled {repeat.jit_compiles} programs"
        assert np.array_equal(first.results[0].assignment,
                              repeat.results[0].assignment)


def test_interleaved_jobs_bit_equal_solo_runs():
    """Acceptance: two concurrently submitted jobs interleave on one
    dispatch chain and EACH produces the forest of its solo run."""
    ref_a = solo_assignment(INPUT_A, 4)
    ref_b = solo_assignment(INPUT_B, 4)
    with running_scheduler() as sched:
        ja = sched.submit(spec(INPUT_A, tenant="alice"))
        jb = sched.submit(spec(INPUT_B, tenant="bob"))
        ja = sched.wait(ja.id, timeout_s=240)
        jb = sched.wait(jb.id, timeout_s=240)
        assert ja.state == "done" and jb.state == "done"
        # genuinely concurrent: each started before the other finished
        assert ja.start_t < jb.end_t and jb.start_t < ja.end_t
        assert np.array_equal(ja.results[0].assignment, ref_a)
        assert np.array_equal(jb.results[0].assignment, ref_b)


def test_multi_k_query_one_shared_tree():
    """Multi-k from one shared tree is one served query: one build,
    one scoring pass, per-k results matching the solo builds."""
    with running_scheduler() as sched:
        job = serve_one(sched, spec(ks=(4, 8)))
        assert job.state == "done"
        assert [r.k for r in job.results] == [4, 8]
        for r in job.results:
            assert np.array_equal(r.assignment,
                                  solo_assignment(INPUT_A, r.k))
        # one build amortized: the per-k phase walls are shared
        assert job.results[0].total_edges == job.results[1].total_edges


def test_admission_rejects_over_tiny_budget(monkeypatch):
    """Acceptance: under a tiny SHEEP_CACHE_BYTES budget the job's
    modeled footprint cannot fit even at dispatch_batch=1 — REJECTED
    with the modeled-bytes diagnosis, not queued forever."""
    monkeypatch.setenv("SHEEP_CACHE_BYTES", "10000")
    with running_scheduler() as sched:
        assert sched.budget == 10000
        job = serve_one(sched, spec(), timeout=30)
        assert job.state == "rejected"
        assert "admission budget" in (job.error or "")
        assert "10,000" in job.error


def test_admission_queues_on_headroom_then_serializes():
    """Two jobs that each fit the budget but not together: the second
    queues and starts only after the first releases its reservation."""
    from sheep_tpu.utils import membudget

    n = 1 << 10
    m = membudget.build_phase_bytes(n, CHUNK,
                                    dispatch_batch=1)["total_bytes"]
    with running_scheduler(budget_bytes=int(1.5 * m)) as sched:
        ja = sched.submit(spec(INPUT_A, dispatch_batch=1))
        jb = sched.submit(spec(INPUT_B, dispatch_batch=1))
        ja = sched.wait(ja.id, timeout_s=240)
        jb = sched.wait(jb.id, timeout_s=240)
        assert ja.state == "done" and jb.state == "done"
        assert jb.start_t >= ja.end_t, \
            "second job admitted before the first released its bytes"


def test_cancellation_frees_the_queue():
    """Acceptance: cancelling the running job admits the queued one
    immediately; cancelling a queued job removes it outright."""
    from sheep_tpu.utils import membudget

    # budget fits the big victim alone; the small jobs queue behind it
    mv = membudget.build_phase_bytes(1 << 12, 256,
                                     dispatch_batch=1)["total_bytes"]
    with running_scheduler(budget_bytes=int(1.1 * mv)) as sched:
        victim = sched.submit(JobSpec.from_request(
            {"input": "rmat:12:8:3", "k": [4], "chunk_edges": 256,
             "dispatch_batch": 1}, tenant="victim"))
        jb = sched.submit(spec(INPUT_B, dispatch_batch=1))
        jc = sched.submit(spec(INPUT_A, dispatch_batch=1))
        # cancel the queued c first: it must leave the queue now
        assert sched.cancel(jc.id) == "cancelled"
        deadline = time.monotonic() + 30
        while sched.get(victim.id).state == "queued" \
                and time.monotonic() < deadline:
            time.sleep(0.005)
        sched.cancel(victim.id)
        victim = sched.wait(victim.id, timeout_s=60)
        jb = sched.wait(jb.id, timeout_s=240)
        assert victim.state == "cancelled"
        assert jb.state == "done", jb.error
        assert np.array_equal(jb.results[0].assignment,
                              solo_assignment(INPUT_B, 4))


def test_deadline_exceeded_does_not_poison_the_chain():
    """Acceptance: a deadline-expired job reports deadline_exceeded;
    the jobs interleaved around it finish bit-identical — the dispatch
    chain is not poisoned."""
    ref_b = solo_assignment(INPUT_B, 4)
    with running_scheduler() as sched:
        doomed = sched.submit(JobSpec.from_request(
            {"input": "rmat:12:8:3", "k": [4], "chunk_edges": 256,
             "deadline_s": 0.005}, tenant="doomed"))
        jb = sched.submit(spec(INPUT_B, tenant="bob"))
        doomed = sched.wait(doomed.id, timeout_s=120)
        jb = sched.wait(jb.id, timeout_s=240)
        assert doomed.state == "deadline_exceeded"
        assert jb.state == "done", jb.error
        assert np.array_equal(jb.results[0].assignment, ref_b)
        # and the daemon keeps serving: one more job end-to-end
        again = serve_one(sched, spec(INPUT_B))
        assert again.state == "done"
        assert np.array_equal(again.results[0].assignment, ref_b)


def test_served_job_absorbs_oom_and_read_faults(tmp_path, monkeypatch):
    """The served mini-soak's tier-1 twin (the full daemon-subprocess
    version is tools/served_soak.py, pinned @slow below): one injected
    OOM at the first dispatch and one injected read fault each degrade
    the JOB on record — bit-identical result, retry trail in the
    diagnostics — with the scheduler still serving afterwards."""
    from sheep_tpu.io import formats, generators
    from sheep_tpu.utils import fault

    graph = str(tmp_path / "soak.bin64")
    formats.write_edges(graph,
                        generators.random_graph(512, 4096, seed=7))
    ref = None
    with running_scheduler() as sched:
        clean = serve_one(sched, JobSpec.from_request(
            {"input": graph, "k": [4], "chunk_edges": 512,
             "num_vertices": 512}, tenant="clean"))
        assert clean.state == "done"
        ref = clean.results[0].assignment
        for inject, want_retry in (("oom@dispatch:1", True),
                                   ("read@read:2", False)):
            monkeypatch.setenv("SHEEP_FAULT_INJECT", inject)
            monkeypatch.setenv("SHEEP_RETRY_BASE_S", "0.01")
            fault.reset()
            try:
                job = serve_one(sched, JobSpec.from_request(
                    {"input": graph, "k": [4], "chunk_edges": 512,
                     "num_vertices": 512}, tenant=inject))
            finally:
                monkeypatch.delenv("SHEEP_FAULT_INJECT")
                fault.reset()
            assert job.state == "done", (inject, job.error)
            assert np.array_equal(job.results[0].assignment, ref), inject
            if want_retry:
                assert job.stats.get("dispatch_retries", 0) >= 1, \
                    "OOM injection left no retry trail"


def test_job_fault_budget_exhaustion_fails_job_not_daemon(monkeypatch):
    """A fault storm beyond the retry budget fails THAT job; the
    scheduler answers the next request normally."""
    from sheep_tpu.utils import fault

    monkeypatch.setenv("SHEEP_FAULT_INJECT", "oom@dispatch:1:99")
    monkeypatch.setenv("SHEEP_RETRY_BASE_S", "0.001")
    monkeypatch.setenv("SHEEP_RETRY_MAX", "2")
    fault.reset()
    with running_scheduler() as sched:
        doomed = serve_one(sched, spec(tenant="doomed"))
        assert doomed.state == "failed"
        assert "RESOURCE_EXHAUSTED" in doomed.error
        monkeypatch.delenv("SHEEP_FAULT_INJECT")
        fault.reset()
        ok = serve_one(sched, spec(tenant="after"))
        assert ok.state == "done", ok.error


def test_protocol_validation_and_codec():
    with pytest.raises(ProtocolError):
        JobSpec.from_request({"k": [4]})          # no input
    with pytest.raises(ProtocolError):
        JobSpec.from_request({"input": "g", "k": []})
    with pytest.raises(ProtocolError):
        JobSpec.from_request({"input": "g", "k": [0]})
    with pytest.raises(ProtocolError):
        JobSpec.from_request({"input": "g", "k": 4, "bogus": 1})
    with pytest.raises(ProtocolError):
        JobSpec.from_request({"input": "g", "k": 4, "deadline_s": -1})
    sp = JobSpec.from_request({"input": "g", "k": [8, 8, 4]})
    assert sp.ks == [8, 4]  # dupes dropped, order kept
    a = np.arange(1000, dtype=np.int32) % 7
    assert np.array_equal(
        protocol.decode_assignment(protocol.encode_assignment(a)), a)
    with pytest.raises(ProtocolError):
        protocol.parse_request(b'{"op": "frobnicate"}')
    req = protocol.parse_request(b'{"op": "ping"}')
    assert req["op"] == "ping"


def test_terminal_jobs_evicted_beyond_retention_cap(monkeypatch):
    """A resident daemon must not grow host memory monotonically with
    traffic: terminal jobs (and their result arrays) beyond the
    retention cap are evicted oldest-first (review finding)."""
    monkeypatch.setattr(Scheduler, "MAX_TERMINAL_RETAINED", 3)
    with running_scheduler() as sched:
        ids = [serve_one(sched, spec(tenant=f"t{i}")).id
               for i in range(5)]
        assert sched.get(ids[0]) is None and sched.get(ids[1]) is None
        for jid in ids[2:]:
            assert sched.get(jid) is not None
            assert sched.get(jid).state == "done"


def test_submit_unopenable_input_is_answered_not_enqueued():
    with running_scheduler() as sched:
        with pytest.raises(ProtocolError, match="cannot open"):
            sched.submit(spec("/nonexistent/graph.bin64"))
        assert sched.stats()["jobs"]["submitted"] == 0


# ---------------------------------------------------------------------------
# live telemetry plane (ISSUE 11)
# ---------------------------------------------------------------------------

def test_metrics_expose_per_tenant_latency_under_two_jobs():
    """Acceptance: with two concurrent tenant jobs served, the
    scheduler's Prometheus rendering carries per-tenant request-latency
    histograms, live queue/reservation gauges, and the submitted/
    terminal counters — the series a replica router would route on."""
    from sheep_tpu.obs.metrics import parse_prometheus

    with running_scheduler() as sched:
        ja = sched.submit(spec(INPUT_A, tenant="alice"))
        jb = sched.submit(spec(INPUT_B, tenant="bob"))
        ja = sched.wait(ja.id, timeout_s=240)
        jb = sched.wait(jb.id, timeout_s=240)
        assert ja.state == "done" and jb.state == "done"
        assert ja.start_t < jb.end_t and jb.start_t < ja.end_t
        parsed = parse_prometheus(sched.render_metrics())
    counts = dict()
    for labels, v in parsed["sheepd_request_latency_seconds_count"]:
        counts[labels["tenant"]] = v
    assert counts == {"alice": 1.0, "bob": 1.0}
    assert ({"le": "+Inf", "tenant": "alice"}, 1.0) in \
        parsed["sheepd_request_latency_seconds_bucket"]
    assert parsed["sheepd_queue_depth"][0][1] == 0.0
    assert parsed["sheepd_active_jobs"][0][1] == 0.0
    submitted = {lb["tenant"]: v
                 for lb, v in parsed["sheepd_jobs_submitted_total"]}
    assert submitted == {"alice": 1.0, "bob": 1.0}
    done = {(lb["tenant"], lb["state"]): v
            for lb, v in parsed["sheepd_jobs_terminal_total"]}
    assert done[("alice", "done")] == 1.0
    # queue-wait observed for both admissions
    qw = {lb["tenant"]: v
          for lb, v in parsed["sheepd_queue_wait_seconds_count"]}
    assert qw == {"alice": 1.0, "bob": 1.0}
    # live progress surfaced while running: phase/steps on descriptors
    assert ja.phase == "score" and ja.steps > 0
    assert ja.descriptor()["phase"] == "score"
    # the quality plane (ISSUE 13): per-tenant cut/balance
    # distributions observed at DONE, per-job gauges for recent
    # results, and the engine's job_quality value matching the
    # scraped gauge exactly
    qcut = {lb["tenant"]: v
            for lb, v in parsed["sheep_quality_cut_ratio_count"]}
    assert qcut == {"alice": 1.0, "bob": 1.0}
    qbal = {lb["tenant"]: v
            for lb, v in parsed["sheep_quality_balance_count"]}
    assert qbal == {"alice": 1.0, "bob": 1.0}
    jobs_cut = {lb["job"]: v
                for lb, v in parsed["sheep_quality_job_cut_ratio"]}
    assert jobs_cut[ja.id] == pytest.approx(
        float(ja.results[0].cut_ratio), abs=1e-6)
    jobs_bal = {(lb["job"], lb["k"]): v
                for lb, v in parsed["sheep_quality_job_balance"]}
    assert jobs_bal[(jb.id, "4")] == pytest.approx(
        float(jb.results[0].balance), abs=1e-4)


def test_active_job_progress_gauges_live_mid_build():
    """Mid-build scrape shows the per-active-job progress gauges and a
    nonzero active count; the gauges leave the scrape once the job is
    terminal (no frozen series)."""
    from sheep_tpu.obs.metrics import parse_prometheus

    with running_scheduler() as sched:
        job = sched.submit(JobSpec.from_request(
            {"input": "rmat:12:8:3", "k": [4], "chunk_edges": 256},
            tenant="alice"))
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if sched.get(job.id).steps > 0:
                break
            time.sleep(0.01)
        parsed = parse_prometheus(sched.render_metrics())
        assert parsed["sheepd_active_jobs"][0][1] >= 1.0
        rows = parsed.get("sheepd_job_steps", [])
        assert any(lb == {"job": job.id, "tenant": "alice"} and v >= 1
                   for lb, v in rows), rows
        job = sched.wait(job.id, timeout_s=240)
        assert job.state == "done"
        parsed = parse_prometheus(sched.render_metrics())
        assert not parsed.get("sheepd_job_steps")


def test_failed_job_leaves_flight_dump_with_fault_event(tmp_path,
                                                        monkeypatch):
    """Acceptance: a job failed by an injected fault leaves a
    flight-recorder dump in the trace containing the fault event —
    and trace_report --last-errors renders it."""
    from sheep_tpu import obs
    from sheep_tpu.utils import fault

    trace = tmp_path / "served.jsonl"
    monkeypatch.setenv("SHEEP_FAULT_INJECT", "oom@dispatch:1:99")
    monkeypatch.setenv("SHEEP_RETRY_BASE_S", "0.001")
    monkeypatch.setenv("SHEEP_RETRY_MAX", "2")
    fault.reset()
    try:
        with obs.tracing(str(trace)):
            with running_scheduler() as sched:
                doomed = serve_one(sched, spec(tenant="doomed"))
                assert doomed.state == "failed"
    finally:
        monkeypatch.delenv("SHEEP_FAULT_INJECT")
        fault.reset()
    dumps = [json.loads(line) for line in
             trace.read_text().splitlines()
             if '"flight_dump"' in line]
    failed = [d for d in dumps if d["job"] == doomed.id
              and d["reason"].startswith("job_failed")]
    assert failed, [d.get("reason") for d in dumps]
    kinds = [e["ev"] for e in failed[-1]["events"]]
    assert "fault_inject" in kinds and "retry" in kinds
    assert "job_done" in kinds  # the terminal event made the ring
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "trace_report.py"),
         str(trace), "--last-errors", "6"],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert r.returncode == 0
    assert "job_failed" in r.stdout and "fault_inject" in r.stdout


def test_daemon_metrics_verb_http_scrape_and_profile(tmp_path):
    """The daemon end of the tentpole, in-process: the `metrics` verb
    and HTTP GET /metrics answer the same exposition, and the
    `profile` verb captures the next K dispatch steps into the
    requested directory."""
    import urllib.request

    from sheep_tpu.server.client import SheepClient, ServerError
    from sheep_tpu.server.daemon import Daemon, build_parser

    sock = str(tmp_path / "d.sock")
    prof_dir = str(tmp_path / "prof")
    args = build_parser().parse_args(
        ["--socket", sock, "--metrics-port", "0"])
    d = Daemon(args)
    t = threading.Thread(target=d.serve, daemon=True,
                         name="test-sheepd")
    t.start()
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        if os.path.exists(sock) and d.metrics_port:
            break
        time.sleep(0.05)
    assert os.path.exists(sock), "daemon never bound its socket"
    try:
        with SheepClient(sock) as c:
            prof = c.profile(prof_dir, steps=2)
            assert prof["state"] == "armed"
            with pytest.raises(ServerError, match="already"):
                c.profile(prof_dir, steps=2)
            jid = c.submit(INPUT_A, k=4, tenant="alice",
                           chunk_edges=CHUNK)["job_id"]
            job = c.wait(jid, timeout_s=240)
            assert job["state"] == "done"
            verb_text = c.metrics()
            http_text = urllib.request.urlopen(
                f"http://127.0.0.1:{d.metrics_port}/metrics",
                timeout=10).read().decode()
            for text in (verb_text, http_text):
                assert 'sheepd_request_latency_seconds_count' \
                       '{tenant="alice"} 1' in text
                assert "sheepd_queue_depth" in text
            assert c.stats()["profile"]["state"] == "done"
            with pytest.raises(urllib.error.HTTPError):
                urllib.request.urlopen(
                    f"http://127.0.0.1:{d.metrics_port}/nope",
                    timeout=10)
            c.shutdown()
    finally:
        t.join(timeout=60)
    assert not t.is_alive(), "daemon failed to shut down"
    captured = [f for _, _, fs in os.walk(prof_dir) for f in fs]
    assert captured, "profile verb captured nothing into the dir"


def test_profile_arm_validation():
    with running_scheduler() as sched:
        with pytest.raises(ProtocolError):
            sched.arm_profile("/tmp/x", steps=0)
        with pytest.raises(ProtocolError):
            sched.arm_profile("/tmp/x", steps="nope")


def test_profile_capture_stops_when_jobs_drain(tmp_path):
    """Regression: a capture armed for more steps than the job set
    will ever take must STOP when the daemon goes idle (an open
    jax.profiler capture grows host memory forever and blocks every
    re-arm) — and the next arm succeeds."""
    with running_scheduler() as sched:
        sched.arm_profile(str(tmp_path / "p1"), steps=10_000)
        job = serve_one(sched, spec())
        assert job.state == "done"
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            prof = sched.stats()["profile"]
            if prof and prof.get("state") in ("aborted", "done",
                                              "error"):
                break
            time.sleep(0.05)
        assert prof["state"] == "aborted", prof
        assert prof["steps_captured"] >= 1
        assert "remaining" not in prof  # internals stay internal
        # the slot is free again
        assert sched.arm_profile(str(tmp_path / "p2"),
                                 steps=5)["state"] == "armed"


@pytest.mark.slow
def test_served_soak_tool():
    """The full daemon-subprocess mini-soak: one oom + one read leg,
    plus the durable restart (SIGKILL) and drain (SIGTERM) legs,
    through real sheepds on unix sockets (see tools/served_soak.py);
    the tier-1 twins (here and tests/test_journal.py) cover the same
    faults in-process."""
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "served_soak.py")],
        cwd=REPO, env={**os.environ, "JAX_PLATFORMS": "cpu",
                       "PYTHONPATH": REPO},
        capture_output=True, text=True, timeout=1200)
    assert r.returncode == 0, f"stdout:\n{r.stdout}\nstderr:\n{r.stderr}"
    verdicts = [json.loads(line) for line in r.stdout.splitlines()]
    assert verdicts[-1]["ok"] is True
