"""Sparse-id relabeling: dense rewrite + inverse map, partition
translates back, id order (and so degree-tie ordering) preserved."""

import subprocess
import sys

import numpy as np

from sheep_tpu.backends.base import get_backend
from sheep_tpu.io import formats, generators, relabel
from sheep_tpu.io.edgestream import EdgeStream, open_input


def _sparse_graph():
    # karate club with ids spread out by a sparse, order-preserving map
    e = np.asarray(generators.karate_club())
    old_ids = np.sort(np.random.default_rng(3).choice(
        10_000, size=34, replace=False))
    return old_ids[e], old_ids


def test_relabel_roundtrip_and_partition(tmp_path):
    sparse_e, old_ids = _sparse_graph()
    src = str(tmp_path / "sparse.bin32")
    formats.write_edges(src, sparse_e)
    dense = str(tmp_path / "dense.bin32")
    v_used, n_old, m = relabel.relabel_to(EdgeStream.open(src), dense)
    assert (v_used, m) == (34, len(sparse_e))
    assert n_old == int(sparse_e.max()) + 1
    # inverse map: new -> old, ascending (order preserved)
    mapping = np.fromfile(dense + ".map", dtype="<i8")
    np.testing.assert_array_equal(mapping, old_ids)
    # the dense graph is exactly karate club again (order-preserving
    # relabel of an order-preserved spread is the identity)
    back = EdgeStream.open(dense).read_all()
    karate = generators.karate_club()
    np.testing.assert_array_equal(back, karate)
    # partition of the dense graph equals the karate partition
    res = get_backend("pure").partition(open_input(dense), 2)
    want = get_backend("pure").partition(
        EdgeStream.from_array(karate), 2)
    np.testing.assert_array_equal(res.assignment, want.assignment)


def test_relabel_cli(tmp_path):
    sparse_e, _ = _sparse_graph()
    src = str(tmp_path / "s.bin32")
    formats.write_edges(src, sparse_e)
    dst = str(tmp_path / "d.bin32")
    r = subprocess.run([sys.executable, "-m", "sheep_tpu.io.relabel",
                        src, dst], capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    assert "34 used ids" in r.stdout
    assert EdgeStream.open(dst).num_vertices == 34


def test_relabel_rejects_text_output(tmp_path):
    sparse_e, _ = _sparse_graph()
    src = str(tmp_path / "s.bin32")
    formats.write_edges(src, sparse_e)
    try:
        relabel.relabel_to(EdgeStream.open(src), str(tmp_path / "d.edges"))
    except ValueError as e:
        assert "binary" in str(e)
    else:
        raise AssertionError("text output should be rejected")


def test_relabel_rejects_negative_ids(tmp_path):
    import pytest

    s = EdgeStream.from_array(np.array([[0, 5]]), n_vertices=6)
    s._edges = np.array([[0, -1]])  # bypass validation upstream
    with pytest.raises(ValueError, match="negative"):
        relabel.relabel_to(s, str(tmp_path / "d.bin32"))


def test_relabel_large_block_boundary(tmp_path):
    # ids straddling the map-writer's bitmap block boundary (2^23 ids)
    ids = np.array([0, 7, (1 << 23) - 1, 1 << 23, (1 << 23) + 9])
    e = np.stack([ids, np.roll(ids, 1)], axis=1)
    src = str(tmp_path / "s.bin64")
    formats.write_edges(src, e)
    dense = str(tmp_path / "d.bin32")
    v_used, n_old, m = relabel.relabel_to(EdgeStream.open(src), dense)
    assert v_used == 5 and m == 5
    mapping = np.fromfile(dense + ".map", dtype="<i8")
    np.testing.assert_array_equal(mapping, np.sort(ids))
    back = EdgeStream.open(dense).read_all()
    lookup = {o: n for n, o in enumerate(np.sort(ids))}
    np.testing.assert_array_equal(
        back, np.vectorize(lookup.get)(e))
