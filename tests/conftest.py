"""Test env: force an 8-device virtual CPU mesh before jax import.

SURVEY.md §4.4 — the standard JAX trick for testing multi-chip sharding
without a TPU slice. Must run before anything imports jax.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "1")
