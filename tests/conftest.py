"""Test env: force an 8-device virtual CPU mesh (SURVEY.md §4.4).

jax is pre-imported at interpreter startup in this environment (axon TPU
platform plugin), so env vars alone are too late — use config.update,
which works as long as no arrays have been created yet.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def pytest_report_header(config):
    return f"jax devices: {jax.device_count()} ({jax.devices()[0].platform})"
