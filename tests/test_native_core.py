"""Cross-implementation equivalence: native C++ core vs numpy oracle.

SURVEY.md §4.3 — backends must agree on tree structure exactly (same
elimination order => the elimination tree is unique) and on partition
quality within tolerance (split tie-breaks may differ).
"""

import numpy as np
import pytest

from sheep_tpu.core import native, pure
from sheep_tpu.io import generators
from sheep_tpu.io.edgestream import EdgeStream

pytestmark = pytest.mark.skipif(not native.available(), reason="native lib not built")


def _cases():
    return {
        "karate": (generators.karate_club(), 34),
        "path": (generators.path_graph(60), 60),
        "star": (generators.star_graph(50), 50),
        "grid": (generators.grid_graph(9, 8), 72),
        "random": (generators.random_graph(250, 2000, seed=5), 250),
        "rmat": (generators.rmat(9, 8, seed=6), 512),
    }


@pytest.fixture(params=list(_cases()))
def graph(request):
    return _cases()[request.param]


def test_degrees_match(graph):
    e, n = graph
    np.testing.assert_array_equal(native.degrees(e, n), pure.degrees(e, n))


def test_order_matches(graph):
    e, n = graph
    deg = pure.degrees(e, n)
    np.testing.assert_array_equal(native.elim_order(deg), pure.elimination_order(deg))


def test_tree_matches_oracle(graph):
    """The elimination tree is unique given the order: exact match required,
    even though C++ uses incremental insertion and numpy uses sorted
    Kruskal — two independent algorithms."""
    e, n = graph
    pos = pure.elimination_order(pure.degrees(e, n))
    expect = pure.build_elim_tree(e, pos).parent
    got = native.build_elim_tree(e, pos)
    np.testing.assert_array_equal(got, expect)


def test_tree_streaming_order_invariant(graph):
    """Chunked + shuffled insertion gives the same tree as one-shot."""
    e, n = graph
    pos = pure.elimination_order(pure.degrees(e, n))
    expect = native.build_elim_tree(e, pos)
    rng = np.random.default_rng(0)
    shuf = e[rng.permutation(len(e))]
    parent = None
    for off in range(0, len(shuf), 23):
        parent = native.build_elim_tree(shuf[off : off + 23], pos, parent=parent)
    np.testing.assert_array_equal(parent, expect)


def test_merge_matches_whole(graph):
    e, n = graph
    pos = pure.elimination_order(pure.degrees(e, n))
    expect = native.build_elim_tree(e, pos)
    half = len(e) // 2
    a = native.build_elim_tree(e[:half], pos)
    b = native.build_elim_tree(e[half:], pos)
    merged = native.merge_trees(a.copy(), b, pos)
    np.testing.assert_array_equal(merged, expect)


@pytest.mark.parametrize("k", [2, 8])
def test_split_quality_close_to_oracle(graph, k):
    e, n = graph
    pos = pure.elimination_order(pure.degrees(e, n))
    parent = native.build_elim_tree(e, pos)
    a_cpp = native.tree_split(parent, pos, k)
    assert a_cpp.min() >= 0 and a_cpp.max() < k
    from sheep_tpu.types import ElimTree

    a_py = pure.tree_split(ElimTree(parent=parent, pos=pos, n=n), k)
    cut_cpp, tot, bal_cpp, _ = pure.edge_cut_score(e, a_cpp, k, comm_volume=False)
    cut_py, _, bal_py, _ = pure.edge_cut_score(e, a_py, k, comm_volume=False)
    # same algorithm, tie-breaks may differ: quality within 10% of each other
    assert cut_cpp <= max(cut_py * 1.10, cut_py + 3)
    assert bal_cpp <= max(bal_py * 1.10, 2.0)


def test_scoring_matches(graph):
    e, n = graph
    k = 4
    rng = np.random.default_rng(1)
    assign = rng.integers(0, k, n).astype(np.int32)
    cut, total = native.score_chunk(e, assign, n)
    ecut, etotal, _, ecv = pure.edge_cut_score(e, assign, k)
    assert (cut, total) == (ecut, etotal)
    pairs = native.cut_pairs(e, assign, n, k)
    assert len(np.unique(pairs)) == ecv


def test_parse_text():
    data = b"# comment\n1 2\n3\t4\n\n% other\n5 6 extra\n7 8"  # no trailing \n
    edges, consumed = native.parse_text(data)
    np.testing.assert_array_equal(edges, [[1, 2], [3, 4], [5, 6]])
    # "7 8" has no newline: left unconsumed for the next block
    assert data[consumed:] == b"7 8"


def test_cpu_backend_end_to_end():
    from sheep_tpu.backends.base import get_backend

    e = generators.rmat(10, 8, seed=9)
    res = get_backend("cpu", chunk_edges=1000).partition(EdgeStream.from_array(e), 8)
    res.validate(int(e.max()) + 1)
    ref = get_backend("pure").partition(EdgeStream.from_array(e), 8)
    assert res.total_edges == ref.total_edges
    # backend-equivalence bound (north star: <=2% edge-cut regression)
    assert res.edge_cut <= ref.edge_cut * 1.02 + 3
