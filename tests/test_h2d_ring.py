"""Zero-copy ingest (ISSUE 12): device-side stream synthesis and the
staged H2D ring.

The acceptance properties, all assertable on the CPU mesh:

  (a) device-stream bit-identity — a counter-hash DeviceStream build
      equals the host-stream build of the same edges across the
      backend, sharded, bigv, CLI and served entry points, with ZERO
      per-chunk host staging bytes on the record;
  (b) ring bit-identity — the staged H2D ring at depth D in {1, 2, 3}
      produces the identical result to the synchronous path (the ring
      changes WHEN transfers are issued, never what bits arrive),
      including kill+resume through a partially-staged stream;
  (c) degradation — an OOM-class fault shrinks the ring depth through
      membudget.degraded_dispatch like dispatch_batch/inflight, and
      the HBM model counts ring staging (depth x blocks);
  (d) counters — h2d_staged_ms / h2d_blocked_ms / h2d_staged_bytes /
      device_stream_chunks flow from the ring (or its absence) into
      backend diagnostics, and the new sheeplint ``h2d`` rule keeps
      the synchronous-upload regression class out of the drivers.
"""

import threading

import numpy as np
import pytest

import jax.numpy as jnp

from sheep_tpu.analysis.runner import lint_source
from sheep_tpu.backends.tpu_backend import TpuBackend, resolve_h2d_ring
from sheep_tpu.io import generators
from sheep_tpu.io.devicestream import DeviceStream, is_device_stream
from sheep_tpu.io.edgestream import EdgeStream
from sheep_tpu.utils.membudget import build_phase_bytes, degraded_dispatch
from sheep_tpu.utils.prefetch import H2DRing, prefetch

CHUNK = 512


def _streams(scale=10, ef=8, seed=3):
    """(device_stream, host_stream) over the IDENTICAL edge set."""
    dev = generators.RmatHashStream(scale, ef, seed=seed)
    es = EdgeStream.from_array(dev.read_all(), n_vertices=1 << scale)
    return dev, es


# -- H2DRing unit behavior --------------------------------------------------


def _blocks(k=6, c=16):
    return [np.full((c, 2), i, np.int32) for i in range(k)]


@pytest.mark.parametrize("depth", [1, 2, 3])
def test_ring_preserves_order_and_bits(depth):
    stats: dict = {}
    out = list(H2DRing(iter(_blocks()), depth=depth, stats=stats))
    assert len(out) == 6
    for i, dev in enumerate(out):
        np.testing.assert_array_equal(np.asarray(dev), _blocks()[i])
    assert stats["h2d_ring_depth"] == depth
    assert stats["h2d_staged_bytes"] == sum(b.nbytes for b in _blocks())
    # an always-ready source never underruns the ring: the startup fill
    # is staged (the device_gap_ms convention), so blocked is EXACTLY 0
    assert stats["h2d_blocked_ms"] == 0.0
    assert stats["h2d_staged_ms"] > 0.0


def test_ring_stages_block_groups():
    """Group staging (the batched dispatch's unit): a list of host
    chunks is one ring block, transferred as one staged pytree."""
    groups = [[np.full((8, 2), 3 * i + j, np.int32) for j in range(3)]
              for i in range(4)]
    stats: dict = {}
    out = list(H2DRing(iter(groups), depth=2, stats=stats))
    assert [len(g) for g in out] == [3, 3, 3, 3]
    np.testing.assert_array_equal(np.asarray(out[2][1]), groups[2][1])
    assert stats["h2d_staged_bytes"] == 12 * 8 * 2 * 4


def test_ring_blocked_counts_mid_stream_underrun():
    """A producer that stalls mid-stream shows up as h2d_blocked_ms —
    the underrun tax — while the startup fill stays attributed to
    staged."""
    gate = threading.Event()

    def slow():
        yield np.zeros((4, 2), np.int32)
        gate.wait(10.0)
        yield np.ones((4, 2), np.int32)

    stats: dict = {}
    with prefetch(slow(), depth=2) as pf:
        ring = H2DRing(pf, depth=2, stats=stats)
        next(ring)  # startup fill: staged, not blocked
        assert stats["h2d_blocked_ms"] == 0.0
        threading.Timer(0.05, gate.set).start()
        next(ring)  # ring empty, producer gated: a real underrun
        assert stats["h2d_blocked_ms"] > 0.0
        ring.close()


def test_ring_close_contract():
    ring = H2DRing(iter(_blocks()), depth=2)
    next(ring)
    ring.close()
    ring.close()  # idempotent
    with pytest.raises(StopIteration):
        next(ring)
    # with-support closes, and closing the ring closes a closeable
    # source (the prefetch worker drains instead of leaking)
    pf = prefetch(iter(_blocks()), depth=2)
    with H2DRing(pf, depth=2) as r2:
        next(r2)
    assert r2.closed and pf.closed


def test_ring_rejects_bad_depth():
    with pytest.raises(ValueError, match="depth"):
        H2DRing(iter(()), depth=0)


def test_ring_propagates_worker_exceptions():
    def bad():
        yield np.zeros((4, 2), np.int32)
        raise RuntimeError("reader died")

    with prefetch(bad(), depth=2) as pf:
        with H2DRing(pf, depth=1) as ring:
            # the worker error may surface on the very first next()
            # (opportunistic refill already polled it) or on a later
            # one — either way it reaches the consumer with the
            # original traceback, never a hang
            with pytest.raises(RuntimeError, match="reader died"):
                for _ in ring:
                    pass


# -- DeviceStream protocol --------------------------------------------------


def test_is_device_stream_recognition():
    dev, es = _streams()
    assert is_device_stream(dev)
    assert isinstance(dev, DeviceStream)
    assert is_device_stream(generators.SbmHashStream(8, 4, 0.05))
    assert not is_device_stream(es)


def test_device_chunk_bit_equals_host_pad():
    from sheep_tpu.backends.tpu_backend import pad_chunk

    dev, _ = _streams()
    n = dev.num_vertices
    host = list(dev.chunks(CHUNK))
    for i in range(dev.num_device_chunks(CHUNK)):
        np.testing.assert_array_equal(
            np.asarray(dev.device_chunk(i, CHUNK, n)),
            pad_chunk(host[i], CHUNK, n))
    # past-the-end chunks are inert all-sentinel (the lockstep padding
    # contract of device_lockstep_batches)
    np.testing.assert_array_equal(
        np.asarray(dev.device_chunk(10_000, CHUNK, n)),
        np.full((CHUNK, 2), n, np.int32))


def test_resolve_h2d_ring_auto():
    assert resolve_h2d_ring(0) == 1  # cpu-jax auto
    assert resolve_h2d_ring(3) == 3


# -- backend equality: device stream + ring depths --------------------------


def test_backend_device_stream_bit_equals_host_stream():
    dev, es = _streams()
    base = TpuBackend(chunk_edges=CHUNK).partition(es, 8)
    got = TpuBackend(chunk_edges=CHUNK, dispatch_batch=2,
                     inflight=2).partition(dev, 8)
    np.testing.assert_array_equal(got.assignment, base.assignment)
    assert got.edge_cut == base.edge_cut
    assert got.comm_volume == base.comm_volume
    # the zero-host-bytes record: chunks were synthesized on device
    assert got.diagnostics["h2d_staged_bytes"] == 0
    assert got.diagnostics["device_stream_chunks"] > 0
    assert got.diagnostics["h2d_blocked_ms"] == 0.0


@pytest.mark.parametrize("depth", [1, 2, 3])
def test_backend_ring_depth_sweep_oracle_equality(depth):
    dev, es = _streams()
    base = TpuBackend(chunk_edges=CHUNK).partition(es, 8)
    got = TpuBackend(chunk_edges=CHUNK, dispatch_batch=2, inflight=2,
                     h2d_ring=depth).partition(es, 8)
    np.testing.assert_array_equal(got.assignment, base.assignment)
    assert got.edge_cut == base.edge_cut
    assert got.diagnostics["h2d_ring_depth"] == depth
    assert got.diagnostics["h2d_staged_bytes"] > 0
    assert got.diagnostics["h2d_staged_ms"] >= 0.0
    assert got.diagnostics["h2d_blocked_ms"] >= 0.0


def test_rmat14_device_and_ringed_builds_match_host_oracle():
    """The acceptance shape by name: at RMAT-14, the device-stream
    build and the ringed file-stream build at every depth D in
    {1, 2, 3} are bit-identical to the host-path oracle."""
    dev = generators.RmatHashStream(14, 4, seed=7)
    es = EdgeStream.from_array(dev.read_all(), n_vertices=1 << 14)
    oracle = TpuBackend(chunk_edges=1 << 13).partition(es, 8,
                                                       comm_volume=False)
    got = TpuBackend(chunk_edges=1 << 13, dispatch_batch=2,
                     inflight=2).partition(dev, 8, comm_volume=False)
    np.testing.assert_array_equal(got.assignment, oracle.assignment)
    assert got.edge_cut == oracle.edge_cut
    assert got.diagnostics["h2d_staged_bytes"] == 0
    for depth in (1, 2, 3):
        ringed = TpuBackend(chunk_edges=1 << 13, dispatch_batch=2,
                            inflight=2, h2d_ring=depth).partition(
            es, 8, comm_volume=False)
        np.testing.assert_array_equal(ringed.assignment,
                                      oracle.assignment)
        assert ringed.edge_cut == oracle.edge_cut


def test_backend_ring_on_adaptive_driver():
    """The ring also feeds the per-segment adaptive driver (no
    batching/pipelining) — ingestion staging is orthogonal to the
    dispatch shape."""
    dev, es = _streams()
    base = TpuBackend(chunk_edges=CHUNK).partition(es, 8)
    got = TpuBackend(chunk_edges=CHUNK, h2d_ring=3).partition(es, 8)
    np.testing.assert_array_equal(got.assignment, base.assignment)
    assert got.diagnostics["h2d_ring_depth"] == 3


@pytest.mark.parametrize("inflight", [2, 3])
def test_checkpoint_resume_through_partially_staged_ring(tmp_path,
                                                         monkeypatch,
                                                         inflight):
    """Kill mid-build with ring blocks staged ahead; the abandoned
    suppliers drain their staged HBM on unwind, and resume lands on the
    oracle forest (the checkpoint cut never includes un-dispatched
    staged blocks — they restream)."""
    from sheep_tpu.utils.checkpoint import Checkpointer
    from sheep_tpu.utils.fault import InjectedFault

    dev, es = _streams(scale=11, ef=8, seed=9)
    base = TpuBackend(chunk_edges=256).partition(es, 8)
    ck_dir = str(tmp_path / f"ck{inflight}")
    monkeypatch.setenv("SHEEP_FAULT_INJECT", "build:9")
    with pytest.raises(InjectedFault):
        TpuBackend(chunk_edges=256, dispatch_batch=2, segment_rounds=1,
                   inflight=inflight, h2d_ring=2).partition(
            es, 8, checkpointer=Checkpointer(ck_dir, every=4))
    monkeypatch.delenv("SHEEP_FAULT_INJECT")
    res = TpuBackend(chunk_edges=256, dispatch_batch=2, segment_rounds=1,
                     inflight=inflight, h2d_ring=2).partition(
        es, 8, checkpointer=Checkpointer(ck_dir, every=4), resume=True)
    np.testing.assert_array_equal(res.assignment, base.assignment)
    assert res.edge_cut == base.edge_cut


# -- membudget + degradation ------------------------------------------------


def test_membudget_counts_ring_staging():
    n, cs = 1 << 20, 1 << 16
    off = build_phase_bytes(n, cs, dispatch_batch=4)
    two = build_phase_bytes(n, cs, dispatch_batch=4, h2d_ring=2)
    three = build_phase_bytes(n, cs, dispatch_batch=4, h2d_ring=3)
    assert off["h2d_ring_bytes"] == 0
    # depth x (batch chunks x 8 bytes/edge-pair) staging, linear in D
    assert two["h2d_ring_bytes"] == 2 * 4 * 8 * cs
    assert three["total_bytes"] - two["total_bytes"] == 4 * 8 * cs
    assert two["total_bytes"] == off["total_bytes"] + two["h2d_ring_bytes"]


def test_degraded_dispatch_shrinks_ring():
    n, cs = 1 << 20, 1 << 18
    # nothing but the ring left to shed: it halves
    assert degraded_dispatch(n, cs, 1, 1, h2d_ring=4) == (1, 1, 2)
    # fully degraded: nothing left
    assert degraded_dispatch(n, cs, 1, 1, h2d_ring=1) is None
    # legacy pair-callers are unchanged
    assert degraded_dispatch(n, cs, 1, 1) is None
    assert degraded_dispatch(n, cs, 4, 2) == (2, 2)
    # the biggest modeled term goes first: at batch 4 the staging block
    # dwarfs a depth-2 ring, so the batch halves and the ring survives
    nxt = degraded_dispatch(n, cs, 4, 2, h2d_ring=2)
    assert nxt == (2, 2, 2)


def test_backend_oom_degrades_ring(monkeypatch):
    """An injected RESOURCE fault with batch == inflight == 1 leaves
    only the ring to shed: the retry degrades its depth, re-folds
    bit-identically, and the degraded knob lands in diagnostics."""
    dev, es = _streams(scale=11, ef=8, seed=9)
    base = TpuBackend(chunk_edges=256).partition(es, 8)
    monkeypatch.setenv("SHEEP_FAULT_INJECT", "oom@dispatch:2")
    monkeypatch.setenv("SHEEP_RETRY_BASE_S", "0.01")
    res = TpuBackend(chunk_edges=256, dispatch_batch=1, inflight=2,
                     h2d_ring=4).partition(es, 8)
    np.testing.assert_array_equal(res.assignment, base.assignment)
    assert res.diagnostics["dispatch_retries"] >= 1
    assert res.diagnostics["degraded_h2d_ring"] < 4


# -- sharded / bigv / CLI / served entry points -----------------------------


def test_sharded_device_stream_bit_equals_host():
    from sheep_tpu.backends.base import get_backend, list_backends

    if "tpu-sharded" not in list_backends():
        pytest.skip("sharded backend unavailable")
    dev, es = _streams(scale=11, ef=8, seed=9)
    base = get_backend("tpu-sharded", chunk_edges=256).partition(
        es, 8, comm_volume=False)
    for kw in ({}, {"dispatch_batch": 2, "inflight": 2}):
        got = get_backend("tpu-sharded", chunk_edges=256, **kw).partition(
            dev, 8, comm_volume=False)
        np.testing.assert_array_equal(got.assignment, base.assignment)
        assert got.edge_cut == base.edge_cut
        assert got.diagnostics["device_stream_chunks"] > 0


def test_bigv_device_stream_bit_equals_host():
    from sheep_tpu.backends.base import get_backend, list_backends

    if "tpu-bigv" not in list_backends():
        pytest.skip("bigv backend unavailable")
    dev, es = _streams(scale=11, ef=8, seed=9)
    base = get_backend("tpu-bigv", chunk_edges=256).partition(
        es, 8, comm_volume=False)
    got = get_backend("tpu-bigv", chunk_edges=256).partition(
        dev, 8, comm_volume=False)
    np.testing.assert_array_equal(got.assignment, base.assignment)
    assert got.edge_cut == base.edge_cut
    assert got.diagnostics["device_stream_chunks"] > 0


def test_cli_device_stream_and_ring_flag(tmp_path, capsys):
    """rmat-hash: input (device stream) and the same edges from a file
    through --h2d-ring score identically; --h2d-ring validates."""
    import json

    from sheep_tpu.cli import main as cli_main
    from sheep_tpu.io import formats

    dev = generators.RmatHashStream(9, 4, seed=1)
    p = tmp_path / "g.bin64"
    formats.write_edges(str(p), dev.read_all())
    assert cli_main(["--input", str(p), "--num-vertices", str(1 << 9),
                     "--k", "4", "--backend", "tpu", "--json",
                     "--chunk-edges", "128", "--dispatch-batch", "2",
                     "--inflight", "2", "--h2d-ring", "2"]) == 0
    ringed = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert cli_main(["--input", "rmat-hash:9:4:1", "--k", "4",
                     "--backend", "tpu", "--json",
                     "--chunk-edges", "128", "--dispatch-batch", "2",
                     "--inflight", "2"]) == 0
    devline = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert devline["edge_cut"] == ringed["edge_cut"]
    assert devline["comm_volume"] == ringed["comm_volume"]
    with pytest.raises(SystemExit):
        cli_main(["--input", str(p), "--k", "4", "--h2d-ring", "-1"])


def test_served_device_stream_bit_equals_host(tmp_path):
    """The served engine recognizes device streams: an rmat-hash job
    equals the file-backed job of the same edges, with zero host
    staging bytes on its stats."""
    import threading as _threading
    from contextlib import contextmanager

    from sheep_tpu.io import formats
    from sheep_tpu.server.protocol import JobSpec
    from sheep_tpu.server.scheduler import Scheduler

    @contextmanager
    def running_scheduler():
        sched = Scheduler()
        t = _threading.Thread(target=sched.run, daemon=True)
        t.start()
        try:
            yield sched
        finally:
            sched.shutdown()
            t.join(timeout=30)

    dev = generators.RmatHashStream(10, 8, seed=1)
    p = tmp_path / "g.bin64"
    formats.write_edges(str(p), dev.read_all())
    with running_scheduler() as sched:
        a = sched.submit(JobSpec.from_request(
            {"input": "rmat-hash:10:8:1", "k": 4, "chunk_edges": 1024}))
        b = sched.submit(JobSpec.from_request(
            {"input": str(p), "k": 4, "chunk_edges": 1024,
             "num_vertices": 1 << 10, "h2d_ring": 2}))
        ja = sched.wait(a.id, timeout_s=240)
        jb = sched.wait(b.id, timeout_s=240)
    assert ja.state == "done", ja.error
    assert jb.state == "done", jb.error
    np.testing.assert_array_equal(ja.results[0].assignment,
                                  jb.results[0].assignment)
    assert ja.results[0].edge_cut == jb.results[0].edge_cut
    assert ja.stats["h2d_staged_bytes"] == 0
    assert ja.stats["device_stream_chunks"] > 0
    assert jb.stats["h2d_staged_bytes"] > 0


def test_jobspec_validates_h2d_ring():
    from sheep_tpu.server.protocol import JobSpec, ProtocolError

    with pytest.raises(ProtocolError, match="h2d_ring"):
        JobSpec.from_request({"input": "x", "k": 4, "h2d_ring": -1})


# -- sheeplint h2d rule -----------------------------------------------------


_H2D_BAD = """
import jax.numpy as jnp

def f(chunks, n):
    for c in chunks:
        yield jnp.asarray(c)
"""

_H2D_PUT = """
import jax

def f(chunks):
    while chunks:
        jax.device_put(chunks.pop())
"""


def test_sheeplint_h2d_flags_loop_uploads():
    assert any(f.rule == "h2d" for f in lint_source(_H2D_BAD))
    assert any(f.rule == "h2d" for f in lint_source(_H2D_PUT))


def test_sheeplint_h2d_pragma_and_non_loop_clean():
    ok = _H2D_BAD.replace("jnp.asarray(c)",
                          "jnp.asarray(c)  # sheeplint: h2d-ok")
    assert not any(f.rule == "h2d" for f in lint_source(ok))
    outside = """
import jax.numpy as jnp

def f(c):
    return jnp.asarray(c)
"""
    assert not any(f.rule == "h2d" for f in lint_source(outside))
    # a jnp-valued operand moves no host bytes (the sync rule's domain)
    device_valued = """
import jax.numpy as jnp

def f(n):
    for _ in range(n):
        x = jnp.asarray(jnp.zeros(4))
    return x
"""
    assert not any(f.rule == "h2d" for f in lint_source(device_valued))
