"""partition_multi: one elimination-tree build split for many k — the
tree is k-independent [PAPER], so every result must equal the
corresponding independent single-k run exactly."""

import json
import subprocess
import sys

import numpy as np
import pytest

from sheep_tpu.backends.base import get_backend, list_backends
from sheep_tpu.io import formats, generators
from sheep_tpu.io.edgestream import EdgeStream

KS = [2, 8, 5]


def _stream():
    return EdgeStream.from_array(generators.rmat(10, 8, seed=6),
                                 n_vertices=1 << 10)


@pytest.mark.parametrize("backend", ["pure", "cpu", "tpu"])
def test_multi_equals_independent(backend):
    if backend not in list_backends():
        pytest.skip(f"{backend} unavailable")
    be = get_backend(backend, chunk_edges=1024)
    multi = be.partition_multi(_stream(), KS)
    assert [r.k for r in multi] == KS
    for r in multi:
        single = get_backend(backend, chunk_edges=1024).partition(
            _stream(), r.k)
        np.testing.assert_array_equal(r.assignment, single.assignment)
        assert r.edge_cut == single.edge_cut
        assert r.comm_volume == single.comm_volume
        assert r.balance == pytest.approx(single.balance)


def test_sharded_multi_equals_independent():
    """tpu-sharded exposes its merged tree too: multi-k must equal
    independent sharded runs exactly."""
    be = get_backend("tpu-sharded", chunk_edges=1024)
    multi = be.partition_multi(_stream(), [2, 4])
    for r in multi:
        single = get_backend("tpu-sharded", chunk_edges=1024).partition(
            _stream(), r.k)
        np.testing.assert_array_equal(r.assignment, single.assignment)
        assert r.edge_cut == single.edge_cut


def test_bigv_multi_equals_independent():
    """tpu-bigv exposes its (host-materialized) tree too."""
    be = get_backend("tpu-bigv", chunk_edges=1024)
    multi = be.partition_multi(_stream(), [2, 4])
    for r in multi:
        single = get_backend("tpu-bigv", chunk_edges=1024).partition(
            _stream(), r.k)
        np.testing.assert_array_equal(r.assignment, single.assignment)
        assert r.edge_cut == single.edge_cut


def test_fallback_without_tree(capsys):
    """A backend that ignores keep_tree still yields correct results via
    independent runs, with a stderr note about the downgrade."""
    from sheep_tpu.backends.base import Partitioner

    class NoTree(Partitioner):
        name = "no-tree-test"
        chunk_edges = 1024

        def partition(self, stream, k, **opts):
            from sheep_tpu.core import pure

            return pure.partition_arrays(stream.read_all(), k,
                                         n=stream.num_vertices)

    multi = NoTree().partition_multi(_stream(), [2, 4])
    assert "independent full partitions" in capsys.readouterr().err
    for r, k in zip(multi, [2, 4]):
        assert r.k == k
        r.validate(1 << 10)


def test_multi_rejects_checkpointer(tmp_path):
    from sheep_tpu.utils.checkpoint import Checkpointer

    be = get_backend("pure")
    with pytest.raises(ValueError, match="checkpoint"):
        be.partition_multi(_stream(), [2, 4],
                           checkpointer=Checkpointer(str(tmp_path)))


def test_cli_multi_k(tmp_path):
    e = generators.karate_club()
    src = str(tmp_path / "g.edges")
    formats.write_edges(src, e)
    out = str(tmp_path / "g.parts")
    r = subprocess.run(
        [sys.executable, "-m", "sheep_tpu.cli", "--input", src,
         "--k", "2,4", "--backend", "pure", "--output", out, "--json"],
        capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    lines = [json.loads(x) for x in r.stdout.strip().splitlines()]
    assert [d["k"] for d in lines] == [2, 4]
    for k in (2, 4):
        a = formats.read_partition(str(tmp_path / f"g.k{k}.parts"))
        single = subprocess.run(
            [sys.executable, "-m", "sheep_tpu.cli", "--input", src,
             "--k", str(k), "--backend", "pure", "--json"],
            capture_output=True, text=True)
        d = json.loads(single.stdout.strip().splitlines()[-1])
        got = next(x for x in lines if x["k"] == k)
        assert got["edge_cut"] == d["edge_cut"]
        assert len(a) == 34 and a.max() < k


def test_cli_k_validation():
    for bad in ("0", "2,,x", "-3", "2,0"):
        r = subprocess.run(
            [sys.executable, "-m", "sheep_tpu.cli", "--input", "x.edges",
             "--k", bad, "--backend", "pure"],
            capture_output=True, text=True)
        assert r.returncode == 2, bad
        assert "--k" in r.stderr


def test_library_partition_multi(tmp_path):
    import sheep_tpu

    e = generators.karate_club()
    src = str(tmp_path / "g.edges")
    formats.write_edges(src, e)
    res = sheep_tpu.partition_multi(src, [2, 4], backend="pure")
    assert [r.k for r in res] == [2, 4]
    single = sheep_tpu.partition(src, 4, backend="pure")
    np.testing.assert_array_equal(res[1].assignment, single.assignment)


def test_cli_score_only(tmp_path):
    """--score-only reproduces the partitioner's own scores for its own
    output map, and infers k when omitted."""
    e = generators.karate_club()
    src = str(tmp_path / "g.edges")
    formats.write_edges(src, e)
    out = str(tmp_path / "g.parts")
    run = subprocess.run(
        [sys.executable, "-m", "sheep_tpu.cli", "--input", src, "--k", "2",
         "--backend", "pure", "--output", out, "--json"],
        capture_output=True, text=True)
    want = json.loads(run.stdout.strip().splitlines()[-1])
    score = subprocess.run(
        [sys.executable, "-m", "sheep_tpu.cli", "--input", src,
         "--score-only", out, "--json"],
        capture_output=True, text=True)
    assert score.returncode == 0, score.stderr
    got = json.loads(score.stdout.strip().splitlines()[-1])
    assert got["backend"] == "score-only"
    for f in ("k", "edge_cut", "total_edges", "comm_volume"):
        assert got[f] == want[f], f
    assert got["balance"] == pytest.approx(want["balance"])


def test_cli_score_only_rejects_bad_map(tmp_path):
    e = generators.karate_club()
    src = str(tmp_path / "g.edges")
    formats.write_edges(src, e)
    bad = str(tmp_path / "bad.parts")
    formats.write_partition(bad, np.zeros(7, dtype=np.int32))  # wrong V
    r = subprocess.run(
        [sys.executable, "-m", "sheep_tpu.cli", "--input", src,
         "--score-only", bad, "--json"],
        capture_output=True, text=True)
    assert r.returncode == 2 and "entries" in r.stderr
