"""Counter-based R-MAT (generators.rmat_hash_*, RmatHashStream).

The contract that makes the device fast path sound: the numpy twin and
the jnp device generator produce IDENTICAL bits, any chunking of the
edge-index range concatenates to the same sequence, and the stream
plugs into every backend with exact cross-backend equality (SURVEY.md
§4.3 — here exact, not tolerance-based, because both sides consume the
same edge multiset).
"""

import numpy as np
import pytest

from sheep_tpu.io import generators
from sheep_tpu.io.generators import (RmatHashStream, rmat_hash_chunk_device,
                                     rmat_hash_range)


def test_range_chunking_invariance():
    full = rmat_hash_range(8, 0, 4096, seed=3)
    pieces = [rmat_hash_range(8, s, c, seed=3)
              for s, c in ((0, 1000), (1000, 96), (1096, 3000))]
    np.testing.assert_array_equal(full, np.concatenate(pieces))


def test_determinism_and_seed_sensitivity():
    a = rmat_hash_range(10, 500, 2048, seed=7)
    b = rmat_hash_range(10, 500, 2048, seed=7)
    c = rmat_hash_range(10, 500, 2048, seed=8)
    np.testing.assert_array_equal(a, b)
    assert not np.array_equal(a, c)
    assert a.dtype == np.int64 and a.shape == (2048, 2)
    assert a.min() >= 0 and a.max() < 1 << 10


def test_device_chunk_bit_identical_to_host_twin():
    cs, n = 1 << 12, 1 << 9
    stream = RmatHashStream(9, edge_factor=16, seed=11)
    host = list(stream.chunks(cs))
    for i, h in enumerate(host):
        d = np.asarray(stream.device_chunk(i, cs, n))
        assert d.shape == (cs, 2) and d.dtype == np.int32
        np.testing.assert_array_equal(d[: len(h)], h)
        assert np.all(d[len(h):] == n)  # sentinel padding


def test_device_chunk_64bit_counter_carry():
    # a start index straddling the 2^32 boundary must hash the same as
    # the numpy twin (the device carries the counter as two uint32 words)
    start = (1 << 32) - 100
    host = rmat_hash_range(20, start, 256, seed=5)
    dev = np.asarray(rmat_hash_chunk_device(20, start, 256, 256, 1 << 20,
                                            seed=5))
    np.testing.assert_array_equal(dev, host)


def test_power_law_degree_skew():
    # Graph500 parameters concentrate edges on hub vertices: the max
    # degree must dwarf the mean by orders of magnitude
    e = rmat_hash_range(14, 0, 16 << 14, seed=1)
    deg = np.bincount(e.ravel(), minlength=1 << 14)
    assert deg.max() > 40 * deg.mean()
    # both uniform halves are exercised: u and v marginals differ but
    # both cover the low id range densely (a-quadrant recursion)
    assert (deg[: 1 << 7] > 0).mean() > 0.9


def test_stream_edgestream_surface():
    s = RmatHashStream(8, edge_factor=4, seed=2)
    assert s.num_edges == 4 << 8
    assert s.num_vertices == 1 << 8
    assert s.num_edges_cheap == s.num_edges_upper_bound == s.num_edges
    assert s.clamp_chunk_edges(1 << 22) == 4 << 8
    # round-robin sharding covers every edge exactly once
    cs = 128
    all_edges = np.concatenate(list(s.chunks(cs)))
    shard_union = np.concatenate(
        [c for p in range(3) for c in s.chunks(cs, shard=p, num_shards=3)])
    assert len(shard_union) == len(all_edges)
    np.testing.assert_array_equal(
        np.sort(all_edges.view("i8,i8"), axis=0),
        np.sort(shard_union.view("i8,i8"), axis=0))
    # start_chunk resume skips exactly the first chunks
    resumed = np.concatenate(list(s.chunks(cs, start_chunk=2)))
    np.testing.assert_array_equal(resumed, all_edges[2 * cs:])
    np.testing.assert_array_equal(s.read_all(), all_edges)


def test_count_edges_in_span_matches_replay():
    # the O(1) arithmetic must equal what summing owned chunks yields
    from sheep_tpu.io.edgestream import DEFAULT_CHUNK_EDGES

    s = RmatHashStream(8, edge_factor=5, seed=13)  # 1280 edges
    for num_shards in (1, 2, 3, 8):
        for shard in range(num_shards):
            replay = sum(len(c) for c in s.chunks(
                DEFAULT_CHUNK_EDGES, shard=shard, num_shards=num_shards))
            assert s.count_edges_in_span(shard, num_shards) == replay


def test_device_chunk_fn_is_cached():
    # one jitted wrapper for all chunks (a per-call closure would
    # retrace + recompile the scale-deep hash for every chunk)
    from sheep_tpu.io.generators import _device_chunk_fn

    rmat_hash_chunk_device(8, 0, 64, 64, 256, seed=1)
    assert _device_chunk_fn() is _device_chunk_fn()


@pytest.mark.parametrize("backend_name", ["pure", "tpu"])
def test_backends_partition_hash_stream(backend_name):
    from sheep_tpu.backends.base import get_backend, list_backends

    if backend_name not in list_backends():
        pytest.skip(f"{backend_name} unavailable")
    s = RmatHashStream(9, edge_factor=8, seed=4)
    res = get_backend(backend_name, chunk_edges=1 << 10).partition(s, k=4)
    e = s.read_all()
    assert res.total_edges == int((e[:, 0] != e[:, 1]).sum())  # non-loops
    assert len(res.assignment) == s.num_vertices
    assert res.assignment.min() >= 0 and res.assignment.max() < 4


def test_cross_backend_exact_equality_on_hash_stream():
    """pure vs tpu on the same RmatHashStream: same edges -> same scores
    (the tpu side reads device_chunk, the pure side host chunks)."""
    from sheep_tpu.backends.base import get_backend, list_backends

    if "tpu" not in list_backends():
        pytest.skip("tpu backend unavailable")
    s1 = RmatHashStream(9, edge_factor=8, seed=6)
    s2 = RmatHashStream(9, edge_factor=8, seed=6)
    a = get_backend("pure", chunk_edges=1 << 10).partition(s1, k=8)
    b = get_backend("tpu", chunk_edges=1 << 10).partition(s2, k=8)
    assert a.edge_cut == b.edge_cut
    assert a.total_edges == b.total_edges
    assert a.comm_volume == b.comm_volume
    np.testing.assert_array_equal(a.assignment, b.assignment)


def test_checkpoint_resume_on_hash_stream(tmp_path, monkeypatch):
    """Fault mid-build, then resume from the checkpoint and match the
    uninterrupted result (the stream's random-access chunks make resume
    replay-free)."""
    from sheep_tpu.backends.base import get_backend, list_backends
    from sheep_tpu.utils.checkpoint import Checkpointer
    from sheep_tpu.utils.fault import ENV_VAR, InjectedFault

    if "cpu" not in list_backends():
        pytest.skip("native cpu backend unavailable")
    s = RmatHashStream(9, edge_factor=8, seed=9)
    ref = get_backend("cpu", chunk_edges=1 << 10).partition(s, k=4)

    ck = Checkpointer(str(tmp_path), every=1)
    monkeypatch.setenv(ENV_VAR, "build:2")
    with pytest.raises(InjectedFault):
        get_backend("cpu", chunk_edges=1 << 10).partition(
            s, k=4, checkpointer=ck)
    monkeypatch.delenv(ENV_VAR)
    res = get_backend("cpu", chunk_edges=1 << 10).partition(
        s, k=4, checkpointer=Checkpointer(str(tmp_path), every=1),
        resume=True)
    assert res.edge_cut == ref.edge_cut
    np.testing.assert_array_equal(res.assignment, ref.assignment)


def test_open_input_specs():
    from sheep_tpu.io.edgestream import EdgeStream, open_input

    s = open_input("rmat-hash:9:4:3")
    assert isinstance(s, RmatHashStream)
    assert (s.num_vertices, s.num_edges, s.seed) == (512, 2048, 3)
    g = open_input("rmat:8:2")
    assert isinstance(g, EdgeStream) and g.num_edges == 512
    np.testing.assert_array_equal(  # defaults: ef=16, seed=0
        open_input("rmat-hash:8").read_all(),
        RmatHashStream(8, 16, seed=0).read_all())
    with pytest.raises(ValueError, match="synthetic input spec"):
        open_input("rmat-hash:notanint")
    with pytest.raises(ValueError, match="SCALE"):
        open_input("rmat:99:1")
    with pytest.raises(ValueError, match="contradicts"):
        open_input("rmat-hash:9:4", n_vertices=100)
    with pytest.raises(FileNotFoundError):
        open_input("/does/not/exist.bin32").num_edges


def test_cli_accepts_synthetic_spec(tmp_path):
    import json as _json
    import subprocess
    import sys

    out = tmp_path / "p.parts"
    r = subprocess.run(
        [sys.executable, "-m", "sheep_tpu.cli", "--input", "rmat-hash:8:4:1",
         "--k", "4", "--backend", "pure", "--json", "--output", str(out)],
        capture_output=True, text=True, timeout=300,
        env={**__import__("os").environ, "JAX_PLATFORMS": "cpu"})
    assert r.returncode == 0, r.stderr
    line = _json.loads(r.stdout.strip().splitlines()[-1])
    assert line["k"] == 4 and line["n_vertices"] == 256
    assert len(out.read_text().splitlines()) == 256


def test_api_accepts_synthetic_spec():
    import sheep_tpu

    res = sheep_tpu.partition("rmat-hash:8:4:1", 4, backend="pure")
    assert res.k == 4 and len(res.assignment) == 256


def test_scale_bounds_and_path_inputs(tmp_path):
    from pathlib import Path

    from sheep_tpu.io import formats
    from sheep_tpu.io.edgestream import open_input

    # uint32 bit accumulation caps rmat-hash at scale 32 (33 would
    # silently confine ids below 2^32); the int64 PCG spec goes further
    with pytest.raises(ValueError, match="SCALE"):
        open_input("rmat-hash:33")
    with pytest.raises(ValueError, match="1..32"):
        RmatHashStream(33)
    assert open_input("rmat:33:1").num_vertices == 1 << 33
    # pathlib.Path inputs must keep working through open_input
    p = tmp_path / "tiny.edges"
    formats.write_edges(str(p), generators.karate_club())
    assert open_input(Path(p)).num_edges == 78


def test_native_generator_bit_identical_and_fast():
    from sheep_tpu.core import native
    from sheep_tpu.io.generators import (_rmat_hash_keys, _rmat_hash_keys2,
                                         _rmat_hash_thresholds,
                                         _rmat_hash_uv)

    if not native.available():
        pytest.skip("native core unavailable")
    scale, seed, start, count = 20, 17, (1 << 32) - 500, 20000
    keys = _rmat_hash_keys(scale, seed)
    th = _rmat_hash_thresholds(0.57, 0.19, 0.19)
    nat = native.rmat_hash_range(scale, start, count, keys,
                                 _rmat_hash_keys2(keys), th)
    idx = start + np.arange(count, dtype=np.int64)
    u, v = _rmat_hash_uv(np, (idx & 0xFFFFFFFF).astype(np.uint32),
                         (idx >> 32).astype(np.uint32), keys, th, np.int64)
    np.testing.assert_array_equal(nat, np.stack([u, v], axis=1))
    # and the public entry point (which routes large counts natively)
    np.testing.assert_array_equal(
        nat, rmat_hash_range(scale, start, count, seed=17))
