"""sheeplint static rules + SHEEP_SANITIZE runtime sanitizer (ISSUE 6).

One known-bad snippet per rule class (the canonical hazard each rule
exists for), pragma and baseline suppression, a clean-file case, the
whole-repo gate as tier-1, and sanitizer tests proving an injected
stray sync and an injected use-after-donate are caught at runtime.
"""

import io
import json
import os
import subprocess
import sys

import numpy as np
import pytest

from sheep_tpu.analysis import lint_source
from sheep_tpu.analysis.core import load_baseline, write_baseline
from sheep_tpu.analysis.runner import lint_paths

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def rules_of(findings):
    return {f.rule for f in findings}


# ---------------------------------------------------------------------------
# rule fixtures: each canonical bad pattern fires
# ---------------------------------------------------------------------------

SYNC_BAD = """
import numpy as np
import jax
import jax.numpy as jnp
from functools import partial

@partial(jax.jit, static_argnames=("n",))
def fold_step(P, lo, n):
    return P.at[lo].min(lo, mode="drop"), jnp.sum(lo != n)

def driver(P, lo, n):
    P, live = fold_step(P, lo, n)
    if int(live) > 0:          # stray sync: reverts pipeline to lockstep
        P, live = fold_step(P, lo, n)
    return P
"""


def test_sync_rule_fires_on_stray_int():
    findings = lint_source(SYNC_BAD)
    assert "sync" in rules_of(findings)
    assert any(f.severity == "error" and "int()" in f.message
               for f in findings)


def test_sync_rule_fires_on_branch_and_asarray():
    src = SYNC_BAD.replace(
        "    if int(live) > 0:          # stray sync: reverts pipeline to lockstep\n"
        "        P, live = fold_step(P, lo, n)\n",
        "    h = np.asarray(P)\n"
        "    while live > 0:\n"
        "        P, live = fold_step(P, lo, n)\n")
    msgs = [f.message for f in lint_source(src) if f.rule == "sync"]
    assert any("np.asarray" in m for m in msgs)
    assert any("`while`" in m for m in msgs)


def test_sync_pragma_suppresses():
    src = SYNC_BAD.replace(
        "if int(live) > 0:          # stray sync: reverts pipeline to lockstep",
        "if int(live) > 0:  # sheeplint: sync-ok")
    assert "sync" not in rules_of(lint_source(src))


def test_branch_finding_not_suppressed_by_pragma_inside_body():
    # the branch finding anchors to the TEST expression: a pragma on an
    # unrelated line inside the body must not swallow it
    src = """
import numpy as np
import jax
import jax.numpy as jnp
from functools import partial

@partial(jax.jit, static_argnames=("n",))
def fold_step(P, lo, n):
    return P, jnp.sum(lo != n)

def driver(P, lo, n):
    P, live = fold_step(P, lo, n)
    while live > 0:
        h = np.asarray(live)  # sheeplint: sync-ok
        P, live = fold_step(P, lo, n)
    return P
"""
    msgs = [f.message for f in lint_source(src) if f.rule == "sync"]
    assert any("`while`" in m for m in msgs)


DONATE_BAD = """
import jax
import jax.numpy as jnp
from functools import partial

@partial(jax.jit, static_argnames=("n",), donate_argnums=(0,))
def fold_donated(P, lo, n):
    return P.at[lo].min(lo, mode="drop")

def driver(P, lo, n):
    out = fold_donated(P, lo, n)
    return out + P[0]           # use-after-donate: P is dead
"""


def test_donate_rule_fires_on_use_after_donate():
    findings = lint_source(DONATE_BAD)
    assert any(f.rule == "donate" and "'P'" in f.message
               for f in findings)


def test_donate_rebind_is_clean():
    src = DONATE_BAD.replace("out = fold_donated(P, lo, n)",
                             "P = fold_donated(P, lo, n)") \
                    .replace("return out + P[0]           "
                             "# use-after-donate: P is dead",
                             "return P[0]")
    assert "donate" not in rules_of(lint_source(src))


def test_donate_rule_tracks_donating_suffix_convention():
    # callee defined elsewhere: the *_donated naming convention alone
    # must poison the positional args
    src = """
from somewhere import fold_segments_batch_pos_donated

def driver(P, loB, hiB, n):
    out = fold_segments_batch_pos_donated(P, loB, hiB, n)
    return loB.shape, P
"""
    findings = lint_source(src)
    assert any(f.rule == "donate" and "'P'" in f.message for f in findings)


JIT_IN_LOOP_BAD = """
import jax

def sweep(xs):
    outs = []
    for x in xs:
        f = jax.jit(lambda a: a + 1)   # fresh program every iteration
        outs.append(f(x))
    return outs
"""

JIT_STATIC_LIST_BAD = """
import jax

f = jax.jit(lambda a, n: a + n, static_argnums=[1])
"""

JIT_TRACED_BRANCH_BAD = """
import jax
import jax.numpy as jnp
from functools import partial

@partial(jax.jit, static_argnames=("n",))
def bad(P, n):
    if P[0] > 0:                 # Python branch on a traced value
        return P + 1
    return P
"""


def test_jit_rule_fires_on_construction_in_loop():
    findings = lint_source(JIT_IN_LOOP_BAD)
    assert any(f.rule == "jit" and "loop" in f.message for f in findings)


def test_jit_rule_fires_on_nontuple_static():
    findings = lint_source(JIT_STATIC_LIST_BAD)
    assert any(f.rule == "jit" and "static_argnums" in f.message
               for f in findings)


def test_jit_rule_fires_on_traced_branch():
    findings = lint_source(JIT_TRACED_BRANCH_BAD)
    assert any(f.rule == "jit" and "`if`" in f.message for f in findings)
    # static params are exempt: branching on n is fine
    src = JIT_TRACED_BRANCH_BAD.replace("if P[0] > 0:", "if n > 0:")
    assert "jit" not in rules_of(lint_source(src))


RESOURCE_PREFETCH_BAD = """
from sheep_tpu.utils.prefetch import prefetch

def consume(stream):
    pf = prefetch(stream)        # no close() on any path
    for item in pf:
        if item is None:
            return 0             # abandons pf: worker thread leaks
    return 1
"""

RESOURCE_SPAN_BAD = """
from sheep_tpu import obs

def build(chunks):
    sp = obs.begin("build")      # never ended
    for c in chunks:
        pass
"""

RESOURCE_COUNTERS_BAD = """
def bump(tracer):
    tracer.counters["host_syncs"] = 99   # bypasses the registry API
"""


def test_resource_rule_fires_on_uncloseable_prefetcher():
    findings = lint_source(RESOURCE_PREFETCH_BAD)
    assert any(f.rule == "resource" and "close()" in f.message
               for f in findings)


def test_resource_rule_accepts_with_and_close():
    ok_with = RESOURCE_PREFETCH_BAD.replace(
        "    pf = prefetch(stream)        # no close() on any path\n"
        "    for item in pf:\n"
        "        if item is None:\n"
        "            return 0             # abandons pf: worker thread leaks\n"
        "    return 1\n",
        "    with prefetch(stream) as pf:\n"
        "        for item in pf:\n"
        "            pass\n"
        "    return 1\n")
    assert "resource" not in rules_of(lint_source(ok_with))
    ok_close = RESOURCE_PREFETCH_BAD.replace(
        "    return 1", "    pf.close()\n    return 1")
    assert "resource" not in rules_of(lint_source(ok_close))


def test_resource_rule_fires_on_unended_span():
    findings = lint_source(RESOURCE_SPAN_BAD)
    assert any(f.rule == "resource" and "span" in f.message
               for f in findings)
    ok = RESOURCE_SPAN_BAD.replace("    for c in chunks:\n        pass\n",
                                   "    sp.end()\n")
    assert "resource" not in rules_of(lint_source(ok))


def test_resource_rule_fires_on_counter_subscript():
    findings = lint_source(RESOURCE_COUNTERS_BAD)
    assert any(f.rule == "resource" and "CounterRegistry" in f.message
               for f in findings)


LOCK_BAD = """
import threading

class Writer:
    def __init__(self, fh):
        self._fh = fh
        self._lock = threading.Lock()

    def emit(self, line):
        with self._lock:
            self._fh.write(line)

    def close(self):
        self._fh.close()         # races a concurrent emit
"""


def test_lock_rule_fires_on_unlocked_write():
    findings = lint_source(LOCK_BAD)
    assert any(f.rule == "lock" and "_fh" in f.message for f in findings)
    ok = LOCK_BAD.replace(
        "    def close(self):\n        self._fh.close()         "
        "# races a concurrent emit\n",
        "    def close(self):\n        with self._lock:\n"
        "            self._fh.close()\n")
    assert "lock" not in rules_of(lint_source(ok))


FOLD_BAD = """
import numpy as np
import jax

def _fold_delta(self, state, edges):
    pipe = ShardedPipeline(state.n, 1024, mesh)   # per-epoch rebuild
    for c in chunk(edges):
        x = np.asarray(pipe.step(c))              # per-chunk host pull
    return x

def move_rescore(src, dst):
    return jax.jit(lambda a: a + 1)(src)          # per-epoch recompile
"""


def test_fold_rule_fires_on_recompile_and_loop_pull():
    findings = [f for f in lint_source(FOLD_BAD) if f.rule == "fold"]
    assert any("ShardedPipeline" in f.message for f in findings)
    assert any("host pull inside a loop" in f.message for f in findings)
    assert any("recompile" in f.message.replace("recompiles", "recompile")
               for f in findings)
    assert all(f.severity == "error" for f in findings)


def test_fold_rule_pragma_and_make_builder_clean():
    ok = FOLD_BAD.replace(
        "ShardedPipeline(state.n, 1024, mesh)   # per-epoch rebuild",
        "ShardedPipeline(state.n, 1024, mesh)  # sheeplint: fold-ok"
    ).replace(
        "np.asarray(pipe.step(c))              # per-chunk host pull",
        "np.asarray(pipe.step(c))  # sheeplint: fold-ok"
    ).replace(
        "jax.jit(lambda a: a + 1)(src)          # per-epoch recompile",
        "jax.jit(lambda a: a + 1)(src)  # sheeplint: fold-ok")
    assert "fold" not in rules_of(lint_source(ok))
    # _make_* builders are the cached-construction fix the rule
    # recommends — the one place a compile belongs
    builder = """
import jax

def _make_move_rescore(mesh):
    return jax.jit(lambda a: a + 1)

def _fold_delta(state, edges):
    total = 0
    for c in edges:
        total += len(c)            # host arithmetic, not a device pull
    return total
"""
    assert "fold" not in rules_of(lint_source(builder))
    # the same calls OUTSIDE a fold-path function are the other rules'
    # business, not this one's
    elsewhere = FOLD_BAD.replace("_fold_delta", "_ingest").replace(
        "move_rescore", "rescale")
    assert "fold" not in rules_of(lint_source(elsewhere))


SPILL_BAD = """
import numpy as np
import jax
import jax.numpy as jnp

def drain(view):
    return np.asarray(view._indices)          # whole mmap region pulled

def drain_slice(view):
    return np.array(view.indices[:])          # same, via full slice

def upload(stream, cs, n):
    out = []
    for i in range(8):
        out.append(jax.device_put(stream.device_chunk(i, cs, n)))
        out.append(jnp.asarray(pad_chunk(next(stream), cs, n)))
    return out
"""


def test_spill_rule_fires_on_full_pull_and_loose_upload():
    findings = [f for f in lint_source(SPILL_BAD) if f.rule == "spill"]
    assert sum("mmap region" in f.message for f in findings) == 2
    assert sum("outside the residency manager" in f.message
               for f in findings) == 2
    assert all(f.severity == "error" for f in findings)


def test_spill_rule_pragma_suppresses():
    ok = SPILL_BAD.replace(
        "# whole mmap region pulled", "# sheeplint: spill-ok"
    ).replace(
        "# same, via full slice", "# sheeplint: spill-ok"
    ).replace(
        "out.append(jax.device_put(stream.device_chunk(i, cs, n)))",
        "out.append(jax.device_put(stream.device_chunk(i, cs, n)))  "
        "# sheeplint: spill-ok"
    ).replace(
        "out.append(jnp.asarray(pad_chunk(next(stream), cs, n)))",
        "out.append(jnp.asarray(pad_chunk(next(stream), cs, n)))  "
        "# sheeplint: spill-ok")
    assert "spill" not in rules_of(lint_source(ok))


def test_spill_rule_sliced_pull_and_depth0_upload_clean():
    # an element/range subscript is the mmap contract working as
    # designed; a one-shot upload outside a loop is not the per-chunk
    # bypass the rule hunts
    clean = """
import numpy as np
import jax

def rows(view, eid):
    return np.asarray(view._indices[eid], dtype=np.int64)

def span(view, a, b):
    return np.asarray(view.indices[a:b])

def place_one(stream, cs, n):
    return jax.device_put(stream.device_chunk(0, cs, n))
"""
    assert "spill" not in rules_of(lint_source(clean))


CLEAN = """
import numpy as np
import jax
import jax.numpy as jnp
from functools import partial

@partial(jax.jit, static_argnames=("n",))
def fold(P, lo, n):
    return P.at[lo].min(lo, mode="drop"), jnp.sum(lo != n)

def driver(P, lo, n, stats):
    size = int(lo.shape[0])               # metadata: no sync
    P, live = fold(P, lo, n)
    live_h = int(np.asarray(live))  # sheeplint: sync-ok
    stats["live"] = live_h
    return P, size
"""


def test_clean_file_has_no_findings():
    assert lint_source(CLEAN) == []


# ---------------------------------------------------------------------------
# baseline ratchet
# ---------------------------------------------------------------------------

def test_baseline_suppresses_known_findings(tmp_path):
    bad = tmp_path / "legacy.py"
    bad.write_text(SYNC_BAD)
    findings, baselined, _ = lint_paths([str(bad)])
    assert findings and baselined == 0
    bl = tmp_path / "baseline.json"
    write_baseline(str(bl), findings)
    again, baselined, _ = lint_paths([str(bad)],
                                     baseline=load_baseline(str(bl)))
    assert again == [] and baselined == len(findings)
    # the ratchet: a NEW violation still fails against the old baseline
    bad.write_text(SYNC_BAD + "\n\ndef more(P, lo, n):\n"
                   "    _, live = fold_step(P, lo, n)\n"
                   "    return float(live)\n")
    newf, _, _ = lint_paths([str(bad)], baseline=load_baseline(str(bl)))
    assert any("float()" in f.message for f in newf)


# ---------------------------------------------------------------------------
# the repo gate (tier-1 wiring): zero non-baselined findings
# ---------------------------------------------------------------------------

def test_repo_gate_is_clean():
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "sheeplint.py"),
         "--check", "sheep_tpu", "tools"],
        cwd=REPO, capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, f"stdout:\n{r.stdout}\nstderr:\n{r.stderr}"


def test_cli_json_and_exit_codes(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(SYNC_BAD)
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "sheeplint.py"),
         "--json", "--no-baseline", str(bad)],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert r.returncode == 1  # errors present
    payload = json.loads(r.stdout)
    assert payload and payload[0]["rule"] == "sync"
    warn = tmp_path / "warn.py"
    warn.write_text(JIT_IN_LOOP_BAD)
    r2 = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "sheeplint.py"),
         "--no-baseline", str(warn)],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert r2.returncode == 2  # warnings only


def test_cli_missing_path_is_not_vacuously_green(tmp_path):
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "sheeplint.py"),
         "--check", str(tmp_path / "no_such_pkg")],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert r.returncode == 3
    assert "no such path" in r.stderr


def test_rules_filter_keeps_parse_errors(tmp_path):
    broken = tmp_path / "broken.py"
    broken.write_text("def oops(:\n")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "sheeplint.py"),
         "--no-baseline", "--rules", "sync", str(broken)],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert r.returncode == 1
    assert "syntax error" in r.stdout


# ---------------------------------------------------------------------------
# runtime sanitizer (SHEEP_SANITIZE=1)
# ---------------------------------------------------------------------------

@pytest.fixture
def sanitized(monkeypatch):
    monkeypatch.setenv("SHEEP_SANITIZE", "1")
    from sheep_tpu.analysis import sanitize
    return sanitize


def test_sanitizer_catches_injected_stray_sync(sanitized):
    import jax.numpy as jnp

    x = jnp.arange(8, dtype=jnp.int32)
    with pytest.raises(sanitized.SanitizeError, match="implicit"):
        with sanitized.guard("test"):
            bool(x.sum() > 0)
    # the annotated window allows the same read
    with sanitized.guard("test"):
        with sanitized.sync_ok("test"):
            assert int(x.sum()) == 28
    # and outside any guard, conversions behave normally
    assert int(x.sum()) == 28


def test_sanitizer_off_is_inert(monkeypatch):
    monkeypatch.delenv("SHEEP_SANITIZE", raising=False)
    import jax.numpy as jnp

    from sheep_tpu.analysis import sanitize

    with sanitize.guard("test"):
        assert bool(jnp.int32(1) > 0)


def test_sanitizer_catches_injected_use_after_donate(sanitized):
    import jax.numpy as jnp

    from sheep_tpu.ops import elim as elim_ops

    n = 64
    P = jnp.full(n + 1, n, jnp.int32)
    loB = jnp.full((2, 32), n, jnp.int32)
    hiB = jnp.full((2, 32), n, jnp.int32)
    elim_ops.fold_segments_batch_pos_donated(P, loB, hiB, n)
    with pytest.raises(RuntimeError, match="deleted"):
        np.asarray(P)  # the donated table is poisoned
    # and a donation silently dropped (live buffer) is itself an error
    with pytest.raises(sanitized.SanitizeError, match="donated"):
        sanitized.check_donated(jnp.arange(4), origin="test")


def test_sanitized_pipelined_fold_passes_and_matches(sanitized):
    """The real dispatch pipeline runs clean under the armed guard
    (whitelist complete) and still produces the exact fixpoint."""
    import jax.numpy as jnp

    from sheep_tpu.ops import elim as elim_ops

    rng = np.random.default_rng(7)
    n, C = 256, 128
    edges = rng.integers(0, n, (4, C, 2)).astype(np.int32)
    pos = jnp.arange(n + 1, dtype=jnp.int32)
    loB, hiB = elim_ops.orient_chunks_batch_pos(jnp.asarray(edges), pos, n)
    P0 = jnp.full(n + 1, n, jnp.int32)
    P_pipe, _ = elim_ops.fold_segments_pipelined(
        P0, iter([(loB, hiB)]), n, inflight=2, segment_rounds=2,
        donate=True)
    # the pipelined call donated loB/hiB — orient fresh blocks for the
    # undonated reference fold
    loB2, hiB2 = elim_ops.orient_chunks_batch_pos(jnp.asarray(edges), pos, n)
    P_ref, _ = elim_ops.fold_segments_batch(
        jnp.full(n + 1, n, jnp.int32), loB2, hiB2, n, segment_rounds=2,
        donate=False)
    np.testing.assert_array_equal(np.asarray(P_pipe), np.asarray(P_ref))


def test_sanitizer_span_balance_at_close(sanitized):
    from sheep_tpu.obs.tracer import Tracer

    tr = Tracer(io.StringIO())
    tr.begin("leaked")
    with pytest.raises(sanitized.SanitizeError, match="never ended"):
        tr.close()
    # balanced traces close clean
    tr2 = Tracer(io.StringIO())
    sp = tr2.begin("ok")
    sp.end()
    tr2.close()
