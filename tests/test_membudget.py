"""Memory-model sanity (BASELINE.md "HBM budget")."""

from sheep_tpu.ops.elim import EXACT_TABLE_BYTES
from sheep_tpu.utils.membudget import build_phase_bytes, max_vertices_for

GIB = 1 << 30


def test_descent_auto_selection_matches_elim():
    small = build_phase_bytes(1 << 14, 1 << 12)
    assert small["descent"] == "exact"
    big = build_phase_bytes(1 << 28, 1 << 24)
    assert big["descent"] == "stream"
    assert big["lift_bytes"] == 4 * ((1 << 28) + 1)  # one table live


def test_exact_stack_is_capped():
    b = build_phase_bytes(1 << 26, 1 << 20, descent="exact")
    assert b["lift_bytes"] <= EXACT_TABLE_BYTES


def test_single_chip_ceiling_is_2_29():
    """16 GiB v5e chip: V=2^29 fits, V=2^30 does not (the documented
    single-chip ceiling with the O(C)-transient displacement fixpoint)."""
    assert max_vertices_for(16 * GIB, 1 << 24) == 1 << 29
    assert build_phase_bytes(1 << 30, 1 << 24)["total_bytes"] > 16 * GIB


def test_model_monotone_in_v_and_chunk():
    f = lambda v, c: build_phase_bytes(v, c)["total_bytes"]
    assert f(1 << 20, 1 << 16) < f(1 << 24, 1 << 16) < f(1 << 24, 1 << 20)


def test_inflight_multiplies_staging_and_donation_credits_state():
    """ISSUE 4 sizing: D in-flight executions hold D staging blocks;
    donation aliases one minp table and one oriented block pair back."""
    from sheep_tpu.utils.membudget import dispatch_batch_for

    n, cs = 1 << 20, 1 << 16
    one = build_phase_bytes(n, cs, dispatch_batch=4)
    two = build_phase_bytes(n, cs, dispatch_batch=4, inflight=2)
    three = build_phase_bytes(n, cs, dispatch_batch=4, inflight=3)
    assert two["staging_bytes"] == 2 * one["staging_bytes"]
    assert three["staging_bytes"] == 3 * one["staging_bytes"]
    # the pipelined driver stages its blocks even at N == 1 (inflight
    # alone selects it); only the fully synchronous path is staging-free
    assert build_phase_bytes(n, cs, inflight=3)["staging_bytes"] == \
        3 * 4 * 4 * cs
    assert build_phase_bytes(n, cs)["staging_bytes"] == 0

    table = 4 * (n + 1)
    unit = one["staging_bytes"]
    don = build_phase_bytes(n, cs, dispatch_batch=4, inflight=2,
                            donate=True)
    assert don["persistent_bytes"] == two["persistent_bytes"] - table
    assert don["staging_bytes"] == two["staging_bytes"] - unit // 2
    assert don["total_bytes"] < two["total_bytes"]

    # auto-sizing: a deeper pipeline fits a smaller N in the same HBM,
    # and donation buys some of it back
    hbm = build_phase_bytes(n, cs, dispatch_batch=8)["total_bytes"]
    assert dispatch_batch_for(hbm, n, cs) == 8
    assert dispatch_batch_for(hbm, n, cs, inflight=2) < 8
    assert dispatch_batch_for(hbm, n, cs, inflight=2, donate=True) >= \
        dispatch_batch_for(hbm, n, cs, inflight=2)
