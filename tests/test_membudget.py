"""Memory-model sanity (BASELINE.md "HBM budget")."""

from sheep_tpu.ops.elim import EXACT_TABLE_BYTES
from sheep_tpu.utils.membudget import build_phase_bytes, max_vertices_for

GIB = 1 << 30


def test_descent_auto_selection_matches_elim():
    small = build_phase_bytes(1 << 14, 1 << 12)
    assert small["descent"] == "exact"
    big = build_phase_bytes(1 << 28, 1 << 24)
    assert big["descent"] == "stream"
    assert big["lift_bytes"] == 4 * ((1 << 28) + 1)  # one table live


def test_exact_stack_is_capped():
    b = build_phase_bytes(1 << 26, 1 << 20, descent="exact")
    assert b["lift_bytes"] <= EXACT_TABLE_BYTES


def test_single_chip_ceiling_is_2_29():
    """16 GiB v5e chip: V=2^29 fits, V=2^30 does not (the documented
    single-chip ceiling with the O(C)-transient displacement fixpoint)."""
    assert max_vertices_for(16 * GIB, 1 << 24) == 1 << 29
    assert build_phase_bytes(1 << 30, 1 << 24)["total_bytes"] > 16 * GIB


def test_model_monotone_in_v_and_chunk():
    f = lambda v, c: build_phase_bytes(v, c)["total_bytes"]
    assert f(1 << 20, 1 << 16) < f(1 << 24, 1 << 16) < f(1 << 24, 1 << 20)
