"""Fault-tolerant execution (ISSUE 9): retry policy, fault
classification, graceful OOM degradation, device-loss recovery, the
straggler watchdog, and the chaos harness.

The two pinned acceptance drills live here:

- an injected RESOURCE_EXHAUSTED at dispatch time completes the build
  IN THE SAME PROCESS at a reduced dispatch_batch, bit-identical to the
  unfaulted run;
- randomized chaos schedules through tools/chaos_soak.py end
  bit-identical to the clean oracle or documented-degraded, with zero
  unhandled crashes (2 schedules tier-1; the full 20 is @slow).
"""

import os
import subprocess
import sys
import time

import numpy as np
import pytest

from sheep_tpu.backends.base import get_backend
from sheep_tpu.io import generators
from sheep_tpu.io.edgestream import EdgeStream
from sheep_tpu.utils import fault, retry
from sheep_tpu.utils.membudget import degraded_dispatch
from sheep_tpu.utils.watchdog import (NULL_WATCHDOG, StallWatchdog,
                                      maybe_watchdog, watched)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def graph():
    e = generators.random_graph(300, 3000, seed=1)
    return e, (lambda: EdgeStream.from_array(e, n_vertices=300))


@pytest.fixture
def oracle(graph):
    _, es = graph
    return get_backend("tpu", chunk_edges=512).partition(
        es(), 4, comm_volume=False)


# -- classification --------------------------------------------------------

class TestClassify:
    def test_injected_faults_carry_their_class(self):
        assert retry.classify(fault.InjectedResourceExhausted("x")) \
            == retry.RESOURCE
        assert retry.classify(fault.InjectedDeviceLoss("x")) \
            == retry.DEVICE_LOSS
        assert retry.classify(fault.InjectedReadError("x")) \
            == retry.TRANSIENT
        assert retry.classify(fault.InjectedFault("x")) == retry.FATAL

    def test_xla_style_messages(self):
        # real PJRT errors surface as RuntimeError subclasses whose
        # MESSAGE carries the gRPC status — match on the text
        assert retry.classify(RuntimeError(
            "RESOURCE_EXHAUSTED: Out of memory while trying to "
            "allocate 137438953472 bytes")) == retry.RESOURCE
        assert retry.classify(RuntimeError(
            "INTERNAL: Failed to connect to TPU worker")) \
            == retry.DEVICE_LOSS
        assert retry.classify(RuntimeError(
            "UNAVAILABLE: socket closed")) == retry.TRANSIENT

    def test_memory_and_os_errors(self):
        assert retry.classify(MemoryError()) == retry.RESOURCE
        assert retry.classify(OSError("disk hiccup")) == retry.TRANSIENT

    def test_everything_else_is_fatal(self):
        assert retry.classify(ValueError("bad input")) == retry.FATAL
        assert retry.classify(KeyError("x")) == retry.FATAL


class TestRetryPolicy:
    def test_bounded_per_class(self):
        p = retry.RetryPolicy(max_retries=2, base_delay_s=0.0)
        assert p.admit(retry.RESOURCE)
        p.record(retry.RESOURCE, RuntimeError("x"), "t")
        p.record(retry.RESOURCE, RuntimeError("x"), "t")
        assert not p.admit(retry.RESOURCE)
        # budgets are PER CLASS: resource exhaustion leaves the
        # transient budget intact
        assert p.admit(retry.TRANSIENT)
        assert not p.admit(retry.FATAL)

    def test_backoff_grows_and_caps(self):
        p = retry.RetryPolicy(max_retries=9, base_delay_s=0.1,
                              max_delay_s=0.5, jitter=0.0)
        delays = [p.delay_s(a) for a in range(5)]
        assert delays == sorted(delays)
        assert delays[0] == pytest.approx(0.1)
        assert delays[-1] == pytest.approx(0.5)

    def test_jitter_bounded_and_seeded(self):
        p1 = retry.RetryPolicy(max_retries=3, base_delay_s=0.1,
                               jitter=0.5, seed=7)
        p2 = retry.RetryPolicy(max_retries=3, base_delay_s=0.1,
                               jitter=0.5, seed=7)
        d1 = [p1.delay_s(0) for _ in range(8)]
        assert d1 == [p2.delay_s(0) for _ in range(8)]  # deterministic
        assert all(0.05 <= d <= 0.15 for d in d1)

    def test_run_retries_then_returns(self):
        p = retry.RetryPolicy(max_retries=3, base_delay_s=0.0)
        calls = {"n": 0}

        def flaky():
            calls["n"] += 1
            if calls["n"] < 3:
                raise OSError("blip")
            return "ok"

        assert p.run(flaky, where="t") == "ok"
        assert calls["n"] == 3

    def test_run_reraises_fatal_and_exhausted(self):
        p = retry.RetryPolicy(max_retries=1, base_delay_s=0.0)
        with pytest.raises(ValueError):
            p.run(lambda: (_ for _ in ()).throw(ValueError("bug")), "t")
        with pytest.raises(OSError):
            p.run(lambda: (_ for _ in ()).throw(OSError("always")), "t")

    def test_env_knob_zero_disables(self, monkeypatch):
        monkeypatch.setenv("SHEEP_RETRY_MAX", "0")
        p = retry.RetryPolicy()
        assert not p.admit(retry.RESOURCE)


# -- membudget degrade picker ----------------------------------------------

class TestDegradedDispatch:
    def test_halves_toward_one_and_stops(self):
        n, cs = 1 << 20, 1 << 18
        b, d = 8, 2
        seen = []
        while True:
            nxt = degraded_dispatch(n, cs, b, d)
            if nxt is None:
                break
            assert nxt != (b, d)
            # exactly one knob halves per step
            assert (nxt[0] == b // 2 and nxt[1] == d) or \
                (nxt[0] == b and nxt[1] == d // 2)
            b, d = nxt
            seen.append(nxt)
        assert (b, d) == (1, 1)
        assert len(seen) >= 4  # 8x2 -> 1x1 takes four halvings

    def test_picks_the_bigger_saving(self):
        from sheep_tpu.utils.membudget import build_phase_bytes
        n, cs = 1 << 20, 1 << 18
        nxt = degraded_dispatch(n, cs, 4, 2, donate=False)
        other = (2, 2) if nxt == (4, 1) else (4, 1)
        total = lambda b, d: build_phase_bytes(  # noqa: E731
            n, cs, dispatch_batch=b, inflight=d)["total_bytes"]
        assert total(*nxt) <= total(*other)

    def test_none_when_nothing_to_shed(self):
        assert degraded_dispatch(1 << 20, 1 << 18, 1, 1) is None


# -- chaos grammar ---------------------------------------------------------

class TestChaosGrammar:
    def test_deterministic_schedule(self, monkeypatch):
        spec = "chaos:123:1:0.2"
        monkeypatch.setenv(fault.ENV_VAR, spec)

        def first_fire():
            fault.reset()
            for i in range(200):
                try:
                    fault.maybe_fail("build", i, kinds=("oom",))
                except fault.InjectedResourceExhausted:
                    return i
            return None

        a = first_fire()
        assert a is not None
        assert first_fire() == a  # same seed -> same point

    def test_kinds_restrict_what_fires(self, monkeypatch):
        spec = "chaos:123:5:1.0"  # fire at every point
        monkeypatch.setenv(fault.ENV_VAR, spec)
        fault.reset()
        with pytest.raises(fault.InjectedReadError):
            fault.maybe_fail("read", 1, kinds=("read",))
        # a point that declares NO kinds draws but never injects
        fault.maybe_fail("degrees", 1, kinds=())

    def test_budget_exhausts(self, monkeypatch):
        spec = "chaos:9:2:1.0"
        monkeypatch.setenv(fault.ENV_VAR, spec)
        fault.reset()
        fired = 0
        for i in range(50):
            try:
                fault.maybe_fail("build", i, kinds=("oom",))
            except fault.InjectedResourceExhausted:
                fired += 1
        assert fired == 2

    def test_typed_shots(self, monkeypatch):
        spec = "oom@build:3:2"
        monkeypatch.setenv(fault.ENV_VAR, spec)
        fault.reset()
        fired = 0
        for _ in range(4):
            try:
                fault.maybe_fail("build", 5)
            except fault.InjectedResourceExhausted:
                fired += 1
        assert fired == 2  # bounded shots, then inert

    def test_stall_kind_sleeps_not_raises(self, monkeypatch):
        monkeypatch.setattr(fault, "STALL_S", 0.05)
        spec = "chaos:123:1:1.0"
        monkeypatch.setenv(fault.ENV_VAR, spec)
        fault.reset()
        t0 = time.perf_counter()
        fault.maybe_fail("build", 1, kinds=("stall",))
        assert time.perf_counter() - t0 >= 0.05

    def test_bad_specs_raise(self, monkeypatch):
        for bad in ("chaos:", "chaos:x", "wat@build:1", "oom@build:z"):
            monkeypatch.setenv(fault.ENV_VAR, bad)
            with pytest.raises(ValueError):
                fault.maybe_fail("build", 1)


# -- pinned in-process recovery drills -------------------------------------

class TestInProcessRecovery:
    def test_oom_at_dispatch_degrades_bit_identical(self, graph, oracle,
                                                    monkeypatch):
        """THE acceptance drill: two injected RESOURCE_EXHAUSTED at
        dispatch time -> the build completes in the same process at a
        REDUCED dispatch_batch, bit-identical to the unfaulted run,
        with the dispatch_retries / degraded_dispatch_batch trail."""
        _, es = graph
        monkeypatch.setenv(fault.ENV_VAR, "oom@dispatch:2:2")
        fault.reset()
        monkeypatch.setenv("SHEEP_RETRY_BASE_S", "0.0")
        res = get_backend("tpu", chunk_edges=512, dispatch_batch=4,
                          inflight=2).partition(es(), 4,
                                                comm_volume=False)
        np.testing.assert_array_equal(res.assignment, oracle.assignment)
        assert res.edge_cut == oracle.edge_cut
        d = res.diagnostics
        assert d.get("dispatch_retries", 0) >= 2
        assert 1 <= d["degraded_dispatch_batch"] < 4
        assert d["degraded_inflight"] >= 1

    def test_device_loss_snapshots_and_recovers(self, graph, oracle,
                                                tmp_path, monkeypatch):
        _, es = graph
        from sheep_tpu.utils.checkpoint import Checkpointer

        monkeypatch.setenv(fault.ENV_VAR, "device@build:2")
        fault.reset()
        monkeypatch.setenv("SHEEP_RETRY_BASE_S", "0.0")
        ck = Checkpointer(str(tmp_path / "ck"), every=1)
        res = get_backend("tpu", chunk_edges=512).partition(
            es(), 4, comm_volume=False, checkpointer=ck)
        np.testing.assert_array_equal(res.assignment, oracle.assignment)
        assert res.diagnostics.get("device_loss_recoveries", 0) >= 1
        assert res.diagnostics.get("dispatch_retries", 0) >= 1

    def test_adaptive_branch_oom_retries(self, graph, oracle,
                                         monkeypatch):
        _, es = graph
        monkeypatch.setenv(fault.ENV_VAR, "oom@build:3")
        fault.reset()
        monkeypatch.setenv("SHEEP_RETRY_BASE_S", "0.0")
        res = get_backend("tpu", chunk_edges=512).partition(
            es(), 4, comm_volume=False)
        np.testing.assert_array_equal(res.assignment, oracle.assignment)
        assert res.diagnostics.get("dispatch_retries", 0) >= 1

    def test_kill_faults_still_propagate(self, graph, monkeypatch):
        """The legacy kill grammar is FATAL to the retry layer: the
        PR-8 checkpoint/kill+resume drills must keep seeing the
        process-killing exception, not a silent in-process retry."""
        _, es = graph
        monkeypatch.setenv(fault.ENV_VAR, "build:2")
        with pytest.raises(fault.InjectedFault):
            get_backend("tpu", chunk_edges=512).partition(
                es(), 4, comm_volume=False)

    def test_retry_budget_exhaustion_reraises(self, graph, monkeypatch):
        _, es = graph
        monkeypatch.setenv(fault.ENV_VAR, "oom@build:2:99")
        fault.reset()
        monkeypatch.setenv("SHEEP_RETRY_MAX", "2")
        monkeypatch.setenv("SHEEP_RETRY_BASE_S", "0.0")
        with pytest.raises(fault.InjectedResourceExhausted):
            get_backend("tpu", chunk_edges=512).partition(
                es(), 4, comm_volume=False)

    def test_sharded_oom_degrades_bit_identical(self, monkeypatch):
        e = generators.random_graph(200, 2000, seed=2)

        def es():
            return EdgeStream.from_array(e, n_vertices=200)

        clean = get_backend("tpu-sharded", chunk_edges=256).partition(
            es(), 4, comm_volume=False)
        monkeypatch.setenv(fault.ENV_VAR, "oom@dispatch:2")
        fault.reset()
        monkeypatch.setenv("SHEEP_RETRY_BASE_S", "0.0")
        res = get_backend("tpu-sharded", chunk_edges=256,
                          dispatch_batch=2, inflight=2).partition(
            es(), 4, comm_volume=False)
        np.testing.assert_array_equal(res.assignment, clean.assignment)
        assert res.diagnostics.get("dispatch_retries", 0) >= 1

    def test_checkpoint_degraded_surfaces_in_diagnostics(self, graph,
                                                         tmp_path):
        """A torn manifest at resume is a lossy recovery: the run
        completes clean-start AND carries checkpoint_degraded in its
        diagnostics so the degradation shows in the perf trajectory."""
        _, es = graph
        from sheep_tpu.utils.checkpoint import Checkpointer

        ck = Checkpointer(str(tmp_path / "ck"), every=1)
        with open(ck._manifest_path, "w") as f:
            f.write('{"version": 3, "phase": "build"')  # torn JSON
        res = get_backend("tpu", chunk_edges=512).partition(
            es(), 4, comm_volume=False, checkpointer=ck, resume=True)
        assert res.diagnostics.get("checkpoint_degraded", 0) >= 1


# -- watchdog --------------------------------------------------------------

class TestWatchdog:
    def test_interrupts_stalled_main(self, capsys):
        wd = StallWatchdog(0.3, label="drill", poll_s=0.05)
        wd.start()
        try:
            with pytest.raises(KeyboardInterrupt):
                time.sleep(10)  # "hung collective"
        finally:
            wd.stop()
        assert wd.fired_at is not None and wd.fired_at >= 0.3
        assert "no progress in 'drill'" in capsys.readouterr().err

    def test_touch_keeps_it_quiet(self):
        wd = StallWatchdog(0.4, label="t", poll_s=0.05)
        wd.start()
        try:
            for _ in range(12):
                wd.touch("batch")
                time.sleep(0.05)
        finally:
            wd.stop()
        assert wd.fired_at is None

    def test_env_gate(self, monkeypatch):
        monkeypatch.delenv("SHEEP_PEER_TIMEOUT_S", raising=False)
        assert maybe_watchdog(2, "t") is None
        monkeypatch.setenv("SHEEP_PEER_TIMEOUT_S", "junk")
        assert maybe_watchdog(2, "t") is None
        with watched(1, "t") as wd:
            assert wd is NULL_WATCHDOG
            wd.touch("free")  # inert

    def test_watched_stops_on_exit(self, monkeypatch):
        monkeypatch.setenv("SHEEP_PEER_TIMEOUT_S", "0.2")
        with watched(1, "t") as wd:
            assert wd is not NULL_WATCHDOG
        # stopped: a stall AFTER scope exit must not interrupt us
        time.sleep(0.5)

    def test_stall_chaos_plus_watchdog_end_to_end(self, graph,
                                                  monkeypatch):
        """A chaos stall ages the clock but progress resumes before the
        (generous) timeout: the run completes untouched."""
        _, es = graph
        monkeypatch.setattr(fault, "STALL_S", 0.05)
        monkeypatch.setenv("SHEEP_PEER_TIMEOUT_S", "30")
        monkeypatch.setenv(fault.ENV_VAR, "chaos:5:2:0.3")
        fault.reset()
        res = get_backend("tpu-sharded", chunk_edges=512).partition(
            es(), 4, comm_volume=False)
        assert res.edge_cut >= 0


# -- chaos soak (subprocess, through the real CLI) -------------------------

def _run_soak(schedules, tmp_path, extra=()):
    cmd = [sys.executable, os.path.join(REPO, "tools", "chaos_soak.py"),
           "--schedules", str(schedules), "--scale", "8", "--ef", "8",
           "--chunk-edges", "256", "--out", str(tmp_path / "soak"),
           "--json", *extra]
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    env.pop("SHEEP_FAULT_INJECT", None)
    proc = subprocess.run(cmd, capture_output=True, text=True, env=env,
                          timeout=560)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    import json

    return json.loads(proc.stdout.splitlines()[-1])


def test_chaos_soak_small(tmp_path):
    """Two seeded schedules end-to-end through the CLI: every verdict
    identical-or-documented, zero unhandled crashes."""
    summary = _run_soak(2, tmp_path)
    assert summary["failed"] == 0
    assert sum(summary["verdicts"].values()) == 2


@pytest.mark.slow
def test_chaos_soak_acceptance(tmp_path):
    """The full ISSUE 9 acceptance criterion: >= 20 seeded randomized
    fault schedules, zero unhandled crashes."""
    summary = _run_soak(20, tmp_path)
    assert summary["failed"] == 0
    assert sum(summary["verdicts"].values()) == 20
    assert summary["total_injected"] >= 20
