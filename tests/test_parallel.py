"""Sharded pipeline on the 8-device virtual CPU mesh (SURVEY.md §4.4).

The distributed result must match the sequential oracle exactly: the
elimination tree is order-determined, and the butterfly merge is an
allreduce with an associative/commutative combiner.
"""

import numpy as np
import pytest

import jax

from sheep_tpu.core import pure
from sheep_tpu.io import generators
from sheep_tpu.io.edgestream import EdgeStream
from sheep_tpu.parallel.mesh import shards_mesh
from sheep_tpu.parallel.pipeline import ShardedPipeline, chunk_batches

pytestmark = pytest.mark.skipif(jax.device_count() < 8,
                                reason="needs 8 virtual devices")


def _run(e, n, k=8, n_devices=8, chunk_edges=256):
    mesh = shards_mesh(n_devices)
    pipe = ShardedPipeline(n, chunk_edges, mesh)
    return pipe.run(EdgeStream.from_array(e, n_vertices=n), k=k)


def _cases():
    return {
        "karate": (generators.karate_club(), 34),
        "rmat": (generators.rmat(9, 8, seed=31), 512),
        "grid": (generators.grid_graph(16, 16), 256),
        "path": (generators.path_graph(200), 200),
    }


@pytest.fixture(params=list(_cases()))
def graph(request):
    return _cases()[request.param]


def test_sharded_tree_matches_oracle(graph):
    e, n = graph
    out = _run(e, n)
    expect = pure.build_elim_tree(e, pure.elimination_order(pure.degrees(e, n)))
    np.testing.assert_array_equal(out["parent"], expect.parent)


def test_sharded_scores_match_oracle(graph):
    e, n = graph
    out = _run(e, n)
    ref = pure.partition_arrays(e, 8, n=n)
    assert out["total_edges"] == ref.total_edges
    assert out["edge_cut"] == ref.edge_cut
    np.testing.assert_array_equal(out["assignment"], ref.assignment)


@pytest.mark.parametrize("n_devices", [1, 2, 3, 5, 8])
def test_device_count_invariance(n_devices):
    """Same result on any mesh size, including non-powers-of-2."""
    e = generators.rmat(8, 8, seed=33)
    n = 256
    out = _run(e, n, n_devices=n_devices)
    expect = pure.build_elim_tree(e, pure.elimination_order(pure.degrees(e, n)))
    np.testing.assert_array_equal(out["parent"], expect.parent)


def test_compact_merge_sparse_shards():
    """Sparse shards (few edges, big V) take the boundary-compacted merge
    path and still reproduce the oracle exactly; payload is far below the
    dense 8 x O(V) butterfly (SURVEY.md §7 hard part #4)."""
    n = 1 << 14
    e = generators.random_graph(n, 1500, seed=41)
    out = _run(e, n, chunk_edges=256)
    expect = pure.build_elim_tree(e, pure.elimination_order(pure.degrees(e, n)))
    np.testing.assert_array_equal(out["parent"], expect.parent)
    stats = out["merge_stats"]
    assert stats["merge_mode"] == "compact"
    dense_bytes = 3 * 8 * 4 * (n + 1)  # 3 rounds x 8 links x int32 table
    assert stats["merge_payload_bytes"] < dense_bytes / 3


def test_dense_merge_when_occupancy_high():
    """Near-full forests keep the dense butterfly (compact would ship
    more than the table itself)."""
    e = generators.rmat(9, 8, seed=31)
    out = _run(e, 512)
    assert out["merge_stats"]["merge_mode"] == "dense"


def test_compact_merge_nonpow2_devices():
    """Out-of-range XOR partners must stay inert in the compact payload
    path too (they arrive as zeros, which index vertex 0 if unmasked)."""
    n = 1 << 14
    e = generators.random_graph(n, 1200, seed=43)
    for d in (3, 5, 6):
        out = _run(e, n, n_devices=d, chunk_edges=256)
        expect = pure.build_elim_tree(
            e, pure.elimination_order(pure.degrees(e, n)))
        np.testing.assert_array_equal(out["parent"], expect.parent)
        assert out["merge_stats"]["merge_mode"] == "compact"


def test_chunk_batches_cover_stream():
    e = generators.rmat(8, 8, seed=34)
    n = 256
    es = EdgeStream.from_array(e, n_vertices=n)
    seen = 0
    for batch, filled in chunk_batches(es, 100, 8, n):
        assert batch.shape == (8, 100, 2)
        valid = (batch[:, :, 0] != n) | (batch[:, :, 1] != n)
        seen += int(valid.sum())
    # self-loops at the sentinel row are padding; all real edges present
    assert seen == len(e)


def test_backend_registration():
    from sheep_tpu.backends.base import get_backend

    e = generators.rmat(8, 8, seed=35)
    n = 256
    be = get_backend("tpu-sharded", chunk_edges=300)
    res = be.partition(EdgeStream.from_array(e, n_vertices=n), 8,
                       comm_volume=True)
    ref = pure.partition_arrays(e, 8, n=n)
    assert res.edge_cut == ref.edge_cut
    assert res.comm_volume == ref.comm_volume
    np.testing.assert_array_equal(res.assignment, ref.assignment)


def test_graft_entry_dryrun():
    import __graft_entry__ as ge

    fn, args = ge.entry()
    out = jax.jit(fn)(*args)
    assert out.shape == (1025,)
    ge.dryrun_multichip(8)
