"""Durable sheepd tests (ISSUE 14).

The acceptance pins, against the in-process Scheduler (the daemon
subprocess ends — SIGKILL restart and SIGTERM drain — are exercised
by tools/served_soak.py's restart/drain legs and obs_smoke leg 10):

- journal replay edge cases: missing/empty journal = clean start,
  torn trailing record tolerated (quarantine-style), duplicate
  terminal record skipped, unknown-kind and newer-version records
  skipped with a warning, mid-file damage honoring SHEEP_IO_POLICY;
- THE kill+resume drill: a scheduler abandoned mid-build (no
  finalize, no terminal record — the crash shape) replays into a new
  scheduler that RESUMES the job from its per-job checkpoint and
  finishes bit-identical to the uninterrupted build;
- graceful drain: shutdown_suspend checkpoints the running job at its
  next flush barrier, run() returns with the job NON-terminal, and
  the journal replays to the same state — which then resumes
  bit-identically;
- idempotent reattach: a digest-matched resubmit returns the existing
  (live, journaled, or done) job instead of double-building;
- terminal replay keeps scores queryable; per-job checkpoint dirs are
  cleared at terminal; the daemon lockfile excludes a second daemon.
"""

import json
import os
import threading
import time
from contextlib import contextmanager

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

from sheep_tpu.server import journal as journal_mod  # noqa: E402
from sheep_tpu.server.journal import (JobJournal, JournalError,  # noqa: E402
                                      job_digest)
from sheep_tpu.server.protocol import JobSpec  # noqa: E402
from sheep_tpu.server.scheduler import Scheduler  # noqa: E402

INPUT = "rmat:10:8:1"
CHUNK = 512


def spec(input=INPUT, ks=(4,), tenant="t", **fields):
    body = {"input": input, "k": list(ks), "chunk_edges": CHUNK}
    body.update(fields)
    return JobSpec.from_request(body, tenant=tenant)


def solo_assignment(input=INPUT, k=4, chunk_edges=CHUNK):
    import sheep_tpu

    return sheep_tpu.partition(input, k, backend="tpu",
                               chunk_edges=chunk_edges,
                               comm_volume=False).assignment


@contextmanager
def running_scheduler(**kw):
    sched = Scheduler(**kw)
    t = threading.Thread(target=sched.run, daemon=True,
                         name="test-durable-dispatch")
    t.start()
    try:
        yield sched
    finally:
        sched.shutdown()
        t.join(timeout=60)
        assert not t.is_alive(), "dispatch loop failed to shut down"


def durable_paths(tmp_path):
    return str(tmp_path / "journal.jsonl"), str(tmp_path / "ckpt")


def crash_mid_build(jpath, ck, sp, min_build_steps=4,
                    checkpoint_every=1):
    """Drive a fresh durable scheduler to mid-build, then abandon it
    the way a SIGKILL would look from disk: resources unwound but NO
    finalize, NO terminal journal record, checkpoints left in place.
    Returns the crashed job's id."""
    sched = Scheduler(journal=jpath, checkpoint_dir=ck,
                      checkpoint_every=checkpoint_every)
    job = sched.submit(sp)
    with sched._lock:
        sched._admit_locked()
    for _ in range(2000):
        sched._step(job)
        if job.phase == "build" and job.steps >= min_build_steps \
                and job.stats.get("ckpt_saves"):
            break
        assert job.state == "running", (job.state, job.error)
    assert job.phase == "build", "never reached the build phase"
    job.gen.close()  # a real kill reaps the threads; tests must too
    sched.journal.close()
    return job.id


# ----------------------------------------------------------------------
# journal replay edge cases
# ----------------------------------------------------------------------
def test_replay_missing_and_empty_journal_clean_start(tmp_path):
    missing = str(tmp_path / "nope.jsonl")
    rep = journal_mod.replay(missing)
    assert rep.jobs == [] and rep.next_id == 1 \
        and rep.daemon_starts == 0 and rep.warnings == []
    empty = tmp_path / "empty.jsonl"
    empty.write_text("")
    rep = journal_mod.replay(str(empty))
    assert rep.jobs == [] and rep.next_id == 1 and rep.warnings == []


def test_replay_round_trip_submit_state_terminal(tmp_path):
    jpath = str(tmp_path / "j.jsonl")
    j = JobJournal(jpath)
    sp = spec()
    j.append({"rec": "daemon_start", "t": 1.0, "pid": 42}, fsync=True)
    j.append({"rec": "submit", "job_id": "j1", "t": 2.0, "tenant": "t",
              "digest": job_digest(sp), "n_vertices": 1024,
              "modeled_bytes": 1000, "state": "queued",
              "spec": {"input": sp.input, "ks": list(sp.ks),
                       "chunk_edges": sp.chunk_edges}}, fsync=True)
    j.append({"rec": "state", "job_id": "j1", "state": "running",
              "t": 3.0})
    j.append({"rec": "submit", "job_id": "j2", "t": 4.0, "tenant": "u",
              "digest": "d2", "n_vertices": 10, "state": "queued",
              "spec": {"input": "x.bin64", "ks": [8]}})
    j.append({"rec": "terminal", "job_id": "j2", "state": "failed",
              "t": 5.0, "error": "boom"}, fsync=True)
    j.close()
    rep = journal_mod.replay(jpath)
    assert rep.daemon_starts == 1 and rep.next_id == 3
    assert [(r.job_id, r.state) for r in rep.jobs] == \
        [("j1", "running"), ("j2", "failed")]
    assert rep.jobs[1].error == "boom" and rep.jobs[1].terminal
    assert not rep.jobs[0].terminal
    assert rep.warnings == []


def test_replay_torn_trailing_record_tolerated(tmp_path, monkeypatch):
    # the expected crash artifact: the append died mid-line — always
    # dropped with a warning, even under the strict IO policy
    monkeypatch.setenv("SHEEP_IO_POLICY", "strict")
    jpath = tmp_path / "torn.jsonl"
    good = json.dumps({"v": 1, "rec": "submit", "job_id": "j1",
                       "t": 1.0, "tenant": "t", "n_vertices": 8,
                       "state": "queued",
                       "spec": {"input": "g.bin64", "ks": [4]}})
    jpath.write_text(good + "\n" + '{"v": 1, "rec": "termi')
    rep = journal_mod.replay(str(jpath))
    assert [r.job_id for r in rep.jobs] == ["j1"]
    assert rep.jobs[0].state == "queued"
    assert any("torn trailing" in w for w in rep.warnings)


def test_torn_tail_survives_two_restarts_under_strict(tmp_path,
                                                      monkeypatch):
    """Regression: appending after a torn tail used to GLUE the next
    record onto the fragment, turning the tolerated torn-tail into
    permanent mid-file damage — restart 1 worked, restart 2 raised
    JournalError under the default strict policy forever. The journal
    now heals its tail before the first append: garbage fragments are
    truncated, a parseable unterminated record just gets its
    newline."""
    monkeypatch.setenv("SHEEP_IO_POLICY", "strict")
    jpath = str(tmp_path / "torn.jsonl")
    good = json.dumps({"v": 1, "rec": "submit", "job_id": "j1",
                       "t": 1.0, "tenant": "t", "n_vertices": 8,
                       "state": "queued",
                       "spec": {"input": "g.bin64", "ks": [4]}})
    with open(jpath, "w") as f:
        f.write(good + "\n" + '{"v": 1, "rec": "termi')  # the crash
    # restart 1: open-for-append heals the tail, then appends
    j = JobJournal(jpath)
    j.append({"rec": "daemon_start", "t": 2.0, "pid": 1}, fsync=True)
    j.close()
    # restart 2: the journal must still replay cleanly under strict
    rep = journal_mod.replay(jpath)
    assert [r.job_id for r in rep.jobs] == ["j1"]
    assert rep.daemon_starts == 1
    # and a parseable-but-unterminated tail keeps its DATA: the repair
    # completes the line instead of truncating it
    with open(jpath, "a") as f:
        f.write(json.dumps({"v": 1, "rec": "state", "job_id": "j1",
                            "state": "running", "t": 3.0}))  # no \n
    j = JobJournal(jpath)
    j.append({"rec": "daemon_start", "t": 4.0, "pid": 2}, fsync=True)
    j.close()
    rep = journal_mod.replay(jpath)
    assert rep.jobs[0].state == "running"
    assert rep.daemon_starts == 2


def test_replay_mid_file_damage_honors_io_policy(tmp_path, monkeypatch):
    jpath = tmp_path / "damaged.jsonl"
    sub = json.dumps({"v": 1, "rec": "submit", "job_id": "j1",
                      "t": 1.0, "tenant": "t", "n_vertices": 8,
                      "state": "queued",
                      "spec": {"input": "g.bin64", "ks": [4]}})
    done = json.dumps({"v": 1, "rec": "terminal", "job_id": "j1",
                       "state": "done", "t": 2.0})
    jpath.write_text(sub + "\n" + "GARBAGE NOT JSON\n" + done + "\n")
    monkeypatch.setenv("SHEEP_IO_POLICY", "strict")
    with pytest.raises(JournalError, match="line 2"):
        journal_mod.replay(str(jpath))
    monkeypatch.setenv("SHEEP_IO_POLICY", "quarantine")
    rep = journal_mod.replay(str(jpath))
    assert rep.jobs[0].state == "done"
    assert any("line 2" in w for w in rep.warnings)


def test_replay_duplicate_terminal_first_wins(tmp_path):
    jpath = tmp_path / "dup.jsonl"
    recs = [
        {"v": 1, "rec": "submit", "job_id": "j1", "t": 1.0,
         "tenant": "t", "n_vertices": 8, "state": "queued",
         "spec": {"input": "g.bin64", "ks": [4]}},
        {"v": 1, "rec": "terminal", "job_id": "j1", "state": "done",
         "t": 2.0},
        # crash between the journal write and the client ack re-runs
        # the finalize: the duplicate must not flip done -> cancelled
        {"v": 1, "rec": "terminal", "job_id": "j1",
         "state": "cancelled", "t": 3.0},
        {"v": 1, "rec": "state", "job_id": "j1", "state": "running",
         "t": 4.0},
    ]
    jpath.write_text("".join(json.dumps(r) + "\n" for r in recs))
    rep = journal_mod.replay(str(jpath))
    assert rep.jobs[0].state == "done"
    assert sum("already-terminal" in w for w in rep.warnings) == 2


def test_replay_unknown_and_newer_records_skip_with_warning(tmp_path):
    jpath = tmp_path / "fwd.jsonl"
    recs = [
        {"v": 1, "rec": "submit", "job_id": "j1", "t": 1.0,
         "tenant": "t", "n_vertices": 8, "state": "queued",
         "spec": {"input": "g.bin64", "ks": [4]}},
        # a record kind from a future sheepd: skip, never crash
        {"v": 1, "rec": "replica_handoff", "job_id": "j1"},
        # a whole record from a future journal VERSION
        {"v": 99, "rec": "submit", "job_id": "j9", "t": 9.0,
         "spec": {"input": "g.bin64", "ks": [4]}},
        {"v": 1, "rec": "state", "job_id": "jX", "state": "running"},
    ]
    jpath.write_text("".join(json.dumps(r) + "\n" for r in recs))
    rep = journal_mod.replay(str(jpath))
    assert [r.job_id for r in rep.jobs] == ["j1"]
    assert any("unknown record kind 'replica_handoff'" in w
               for w in rep.warnings)
    assert any("v99" in w and "newer" in w for w in rep.warnings)
    assert any("unjournaled job jX" in w for w in rep.warnings)


def test_job_digest_spec_and_content_sensitivity(tmp_path):
    assert job_digest(spec()) == job_digest(spec())
    assert job_digest(spec()) != job_digest(spec(ks=(8,)))
    assert job_digest(spec()) != job_digest(spec(tenant="other"))
    # file-backed inputs fold content identity (size/mtime) in: a
    # regenerated file at the same path must not reattach
    g = tmp_path / "g.bin64"
    g.write_bytes(b"\x00" * 64)
    d1 = job_digest(spec(input=str(g), num_vertices=4))
    g.write_bytes(b"\x00" * 128)
    d2 = job_digest(spec(input=str(g), num_vertices=4))
    assert d1 != d2


# ----------------------------------------------------------------------
# scheduler-level durability drills
# ----------------------------------------------------------------------
def test_restart_requeues_queued_job_and_floors_ids(tmp_path):
    jpath, ck = durable_paths(tmp_path)
    s1 = Scheduler(journal=jpath, checkpoint_dir=ck)
    job = s1.submit(spec())  # no dispatch thread: stays queued
    assert job.state == "queued"
    s1.journal.close()
    with running_scheduler(journal=jpath, checkpoint_dir=ck) as s2:
        j2 = s2.wait(job.id, timeout_s=240)
        assert j2 is not None and j2.state == "done", \
            (j2 and j2.state, j2 and j2.error)
        assert np.array_equal(j2.results[0].assignment,
                              solo_assignment())
        # the id counter floors past journaled ids — no reuse
        fresh = s2.submit(spec(ks=(8,)))
        assert int(fresh.id[1:]) > int(job.id[1:])


def test_killed_mid_build_resumes_bit_identical(tmp_path):
    """THE acceptance drill: kill -9 shaped abandonment mid-build,
    restart on the same journal/checkpoints, the job RESUMES (counter
    + stats trail on the record) and the final forest bit-equals the
    uninterrupted build's."""
    jpath, ck = durable_paths(tmp_path)
    jid = crash_mid_build(jpath, ck, spec())
    with running_scheduler(journal=jpath, checkpoint_dir=ck,
                           checkpoint_every=1) as s2:
        job = s2.wait(jid, timeout_s=240)
        assert job.state == "done", job.error
        assert np.array_equal(job.results[0].assignment,
                              solo_assignment())
        # the resume is ON RECORD, not inferred: the job replayed as
        # resumable and its engine loaded a checkpoint
        assert job.stats.get("journal_resumed") == 1
        assert job.stats.get("resume_chunk_idx", -1) >= 0
        text = s2.render_metrics()
        assert "sheepd_jobs_resumed_total 1" in text
        assert "sheepd_restarts_total 1" in text


def test_killed_mid_score_resumes_bit_identical(tmp_path):
    """Kill past the build (score phase): the resumed run restores
    the per-k counters + host forest and still bit-equals."""
    jpath, ck = durable_paths(tmp_path)
    sched = Scheduler(journal=jpath, checkpoint_dir=ck,
                      checkpoint_every=2)
    job = sched.submit(spec(ks=(4, 8)))
    with sched._lock:
        sched._admit_locked()
    for _ in range(4000):
        sched._step(job)
        if job.phase == "score" and job.steps and \
                job.stats.get("ckpt_saves"):
            break
        assert job.state == "running", (job.state, job.error)
    assert job.phase == "score", "never reached the score phase"
    job.gen.close()
    sched.journal.close()
    with running_scheduler(journal=jpath, checkpoint_dir=ck,
                           checkpoint_every=2) as s2:
        j2 = s2.wait(job.id, timeout_s=240)
        assert j2.state == "done", j2.error
        assert np.array_equal(j2.results[0].assignment,
                              solo_assignment(k=4))
        assert np.array_equal(j2.results[1].assignment,
                              solo_assignment(k=8))


def test_graceful_drain_suspends_then_resumes_bit_identical(tmp_path):
    jpath, ck = durable_paths(tmp_path)
    sched = Scheduler(journal=jpath, checkpoint_dir=ck,
                      checkpoint_every=4)
    t = threading.Thread(target=sched.run, daemon=True)
    t.start()
    job = sched.submit(spec())
    deadline = time.monotonic() + 60
    while job.steps < 3 and time.monotonic() < deadline:
        time.sleep(0.005)
    assert job.steps >= 3, "job never started stepping"
    sched.shutdown_suspend(grace_s=60)
    t.join(timeout=120)
    assert not t.is_alive(), "drain never finished"
    # the handoff: job parked NON-terminal with its state on disk
    assert job.state == "running" and job.suspended
    assert job.stats.get("ckpt_saves"), "drain saved no checkpoint"
    rep = journal_mod.replay(jpath)
    assert [(r.job_id, r.state) for r in rep.jobs] == \
        [(job.id, "running")]
    # and a second drain of the SAME journal state replays identically
    # (the drain record itself mutates no job)
    assert journal_mod.replay(jpath).jobs[0].state == "running"
    with running_scheduler(journal=jpath, checkpoint_dir=ck,
                           checkpoint_every=4) as s2:
        j2 = s2.wait(job.id, timeout_s=240)
        assert j2.state == "done", j2.error
        assert np.array_equal(j2.results[0].assignment,
                              solo_assignment())


def test_suspending_scheduler_refuses_new_submits(tmp_path):
    from sheep_tpu.server.protocol import ProtocolError

    jpath, ck = durable_paths(tmp_path)
    sched = Scheduler(journal=jpath, checkpoint_dir=ck)
    t = threading.Thread(target=sched.run, daemon=True)
    t.start()
    sched.shutdown_suspend(grace_s=5)
    with pytest.raises(ProtocolError, match="shutting down"):
        sched.submit(spec())
    t.join(timeout=60)
    assert not t.is_alive()


def test_reattach_matches_live_then_journaled_then_done(tmp_path):
    jpath, ck = durable_paths(tmp_path)
    with running_scheduler(journal=jpath, checkpoint_dir=ck) as s1:
        job = s1.submit(spec())
        twin, reattached = s1.reattach_or_submit(spec())
        assert reattached and twin.id == job.id
        other, reattached = s1.reattach_or_submit(spec(ks=(8,)))
        assert not reattached and other.id != job.id
        done = s1.wait(job.id, timeout_s=240)
        assert done.state == "done"
        # DONE still reattaches (idempotent result)
        twin, reattached = s1.reattach_or_submit(spec())
        assert reattached and twin.id == job.id
    # across a restart, the journaled twin reattaches too
    with running_scheduler(journal=jpath, checkpoint_dir=ck) as s2:
        twin, reattached = s2.reattach_or_submit(spec())
        assert reattached and twin.id == job.id
        # a cancelled twin does NOT reattach — a fresh submit is the
        # retry path for non-done terminals
        victim = s2.submit(spec(ks=(16,)))
        s2.cancel(victim.id)
        s2.wait(victim.id, timeout_s=60)
        fresh, reattached = s2.reattach_or_submit(spec(ks=(16,)))
        assert not reattached and fresh.id != victim.id


def test_terminal_replay_keeps_scores_queryable(tmp_path):
    jpath, ck = durable_paths(tmp_path)
    with running_scheduler(journal=jpath, checkpoint_dir=ck) as s1:
        job = s1.wait(s1.submit(spec()).id, timeout_s=240)
        assert job.state == "done"
        want_cut = job.results[0].edge_cut
        ckpt_dir = os.path.join(ck, job.id)
        # per-job checkpoint dirs are cleared at terminal
        assert not os.path.exists(ckpt_dir), os.listdir(ckpt_dir)
    with running_scheduler(journal=jpath, checkpoint_dir=ck) as s2:
        j2 = s2.get(job.id)
        assert j2 is not None and j2.state == "done"
        desc = j2.descriptor(with_results=True)
        assert desc["results"][0]["edge_cut"] == want_cut
        # journaled summaries carry no assignment payload
        assert "assignment" not in desc["results"][0]


def test_daemon_lockfile_excludes_second_daemon(tmp_path):
    from sheep_tpu.server.daemon import Daemon, build_parser

    state = str(tmp_path / "state")
    os.makedirs(state)
    args = build_parser().parse_args(
        ["--socket", str(tmp_path / "a.sock"), "--state-dir", state])
    d1 = Daemon(args)
    d1._acquire_lock()
    try:
        d2 = Daemon(build_parser().parse_args(
            ["--socket", str(tmp_path / "b.sock"),
             "--state-dir", state]))
        with pytest.raises(SystemExit,
                           match=f"pid {os.getpid()}"):
            d2._acquire_lock()
    finally:
        d1._release_lock()
    # released: the next daemon acquires cleanly
    d3 = Daemon(build_parser().parse_args(
        ["--socket", str(tmp_path / "c.sock"), "--state-dir", state]))
    d3._acquire_lock()
    d3._release_lock()


def test_client_failover_rides_daemon_bounce(tmp_path):
    """The --watch fix, in-process: a client with reconnect armed
    keeps polling through a daemon bounce (stop + fresh daemon on the
    same socket/journal) and sees the SAME job id go to done."""
    from sheep_tpu.server.client import SheepClient
    from sheep_tpu.server.daemon import Daemon, build_parser

    sock = str(tmp_path / "d.sock")
    state = str(tmp_path / "state")

    def start_daemon():
        args = build_parser().parse_args(
            ["--socket", sock, "--state-dir", state,
             "--checkpoint-every", "1"])
        d = Daemon(args)
        t = threading.Thread(target=d.serve, daemon=True)
        t.start()
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if os.path.exists(sock) and d.scheduler is not None:
                return d, t
            time.sleep(0.05)
        raise AssertionError("daemon never bound")

    d1, t1 = start_daemon()
    c = SheepClient(sock, reconnect=40, reconnect_base_s=0.1)
    try:
        jid = c.submit(INPUT, k=4, chunk_edges=CHUNK)["job_id"]
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if c.status(jid).get("steps", 0) >= 2:
                break
            time.sleep(0.005)
        # bounce: graceful drain (in-process stand-in for SIGTERM),
        # then a fresh daemon on the same socket/journal
        d1.scheduler.shutdown_suspend(grace_s=60)
        t1.join(timeout=120)
        assert not t1.is_alive()
        # in a real bounce the connection dies WITH the process; both
        # daemons share this test process, so sever it explicitly —
        # the client must transparently reconnect to the new daemon
        c._drop()
        d2, t2 = start_daemon()
        job = c.wait(jid, timeout_s=240)
        assert job["state"] == "done", job
        assert job["job_id"] == jid
        # a reattach submit against the restarted daemon answers the
        # SAME job instead of double-building
        resp = c.submit(INPUT, k=4, chunk_edges=CHUNK, reattach=True)
        assert resp["job_id"] == jid and resp.get("reattached")
        c.shutdown()
        t2.join(timeout=60)
        assert not t2.is_alive()
    finally:
        c.close()


# ----------------------------------------------------------------------
# resident partitions (ISSUE 15): kill + resume mid-delta-epoch
# ----------------------------------------------------------------------
def test_resident_partition_kill_resumes_at_journaled_epoch(tmp_path):
    """THE mid-delta-epoch drill: a durable daemon holding a resident
    partition dies between epochs 1 and 2; the restarted scheduler
    must resume the partition at its journaled epoch (idempotent
    replays of epoch 1 are no-ops), absorb epoch 2, and land
    BIT-IDENTICAL to an uninterrupted replay of the same log."""
    from sheep_tpu.io import deltalog as dl
    from sheep_tpu.io.edgestream import open_input

    jp, ck = durable_paths(tmp_path)
    rng = np.random.default_rng(21)
    n = 512
    E = rng.integers(0, n, (3000, 2)).astype(np.int64)
    base = str(tmp_path / "base.bin64")
    with open(base, "wb") as f:
        f.write(E[:1500].astype("<u8").tobytes())
    sp = spec(input=base, ks=(4,), chunk_edges=CHUNK,
              num_vertices=n, resident=True)

    with running_scheduler(journal=jp, checkpoint_dir=ck,
                           checkpoint_every=1) as sched:
        job = sched.submit(sp)
        assert sched.wait(job.id, timeout_s=120).state == "done"
        jid = job.id
        r1 = sched.update(jid, adds=E[1500:2200], epoch=1)
        assert r1["applied"] and r1["epoch"] == 1
        # the epoch is journaled (fsync'd AFTER the state snapshot)
        recs = [json.loads(ln) for ln in open(jp)]
        assert any(r.get("rec") == "delta_epoch"
                   and r.get("epoch") == 1 for r in recs)
    # <- the daemon is gone here, mid-way through the delta stream

    with running_scheduler(journal=jp, checkpoint_dir=ck,
                           checkpoint_every=1) as sched2:
        info = sched2.epoch_info(jid)
        assert info["epoch"] == 1  # resumed at the journaled epoch
        # an idempotent client replay of epoch 1 must be a no-op
        assert sched2.update(jid, adds=E[1500:2200],
                             epoch=1)["applied"] is False
        r2 = sched2.update(jid, adds=E[2200:], epoch=2, score=True)
        assert r2["epoch"] == 2
        resumed_assign = sched2.get(jid).results[0].assignment.copy()

    # the uninterrupted reference: the one-shot build of the same log
    log = str(tmp_path / "ref.dlog")
    with dl.DeltaLogWriter(log, base_spec=base) as w:
        w.append(E[1500:2200])
        w.append(E[2200:])
    from sheep_tpu.backends.base import get_backend

    one = get_backend("tpu", chunk_edges=CHUNK).partition(
        open_input(f"delta:{log}", n_vertices=n), 4,
        comm_volume=False)
    np.testing.assert_array_equal(resumed_assign, one.assignment)


def test_resident_release_survives_replay(tmp_path):
    """A released residency must stay released across restart (the
    journal's resident_release record): its reservation never comes
    back and updates are refused."""
    jp, ck = durable_paths(tmp_path)
    n = 512
    E = np.random.default_rng(22).integers(0, n, (1000, 2))
    base = str(tmp_path / "b.bin64")
    with open(base, "wb") as f:
        f.write(E.astype("<u8").tobytes())
    sp = spec(input=base, ks=(4,), num_vertices=n, resident=True)
    with running_scheduler(journal=jp, checkpoint_dir=ck) as sched:
        job = sched.submit(sp)
        assert sched.wait(job.id, timeout_s=120).state == "done"
        jid = job.id
        assert sched.stats()["resident_partitions"] == 1
        sched.cancel(jid)  # release
        assert sched.stats()["resident_partitions"] == 0
    with running_scheduler(journal=jp, checkpoint_dir=ck) as sched2:
        assert sched2.stats()["resident_partitions"] == 0
        from sheep_tpu.server.protocol import ProtocolError

        with pytest.raises(ProtocolError, match="released"):
            sched2.epoch_info(jid)


# ----------------------------------------------------------------------
# rebase compaction + torn chunked streams (ISSUE 17)
# ----------------------------------------------------------------------
def test_rebase_compaction_rewrites_base_and_survives_restart(
        tmp_path):
    """compact mode='rebase': the base+log rewrite lands a fresh base
    artifact under the checkpoint dir, scores identically to a clean
    rebuild of the survivors, and the rebased resident survives a
    daemon bounce — epochs keep counting past the compaction floor."""
    from sheep_tpu.backends.base import get_backend
    from sheep_tpu.io import deltalog as dl
    from sheep_tpu.io.edgestream import open_input

    jp, ck = durable_paths(tmp_path)
    rng = np.random.default_rng(37)
    n = 512
    E = rng.integers(0, n, (3000, 2)).astype(np.int64)
    base = str(tmp_path / "base.bin64")
    with open(base, "wb") as f:
        f.write(E[:1500].astype("<u8").tobytes())
    sp = spec(input=base, ks=(4,), chunk_edges=CHUNK,
              num_vertices=n, resident=True)

    with running_scheduler(journal=jp, checkpoint_dir=ck,
                           checkpoint_every=1) as sched:
        job = sched.submit(sp)
        assert sched.wait(job.id, timeout_s=120).state == "done"
        jid = job.id
        sched.update(jid, adds=E[1500:2400], epoch=1)
        sched.update(jid, dels=E[200:500], epoch=2)
        r = sched.compact_resident(jid, mode="rebase", score=True)
        assert r["mode"] == "rebase"
        newbase = r["base"]
        assert os.path.dirname(newbase) == ck and os.path.isfile(
            newbase)
        # the rebased score IS a clean rebuild of the survivors
        surv = np.concatenate(list(dl.filter_tombstones(
            [E[:2400]], E[200:500])))
        ref_file = str(tmp_path / "ref.bin64")
        with open(ref_file, "wb") as f:
            f.write(surv.astype("<u8").tobytes())
        one = get_backend("tpu", chunk_edges=CHUNK).partition(
            open_input(ref_file, n_vertices=n), 4, comm_volume=False)
        assert r["results"][0]["edge_cut"] == one.edge_cut
    # <- daemon gone; the rebase must already be durable

    with running_scheduler(journal=jp, checkpoint_dir=ck,
                           checkpoint_every=1) as sched2:
        assert sched2.epoch_info(jid)["epoch"] == 2
        # numbering continues past the floor after restart
        r3 = sched2.update(jid, adds=E[2400:], epoch=3, score=True)
        assert r3["applied"] and r3["epoch"] == 3


def test_torn_chunked_stream_then_restart_is_retryable(tmp_path):
    """A client that dies mid-chunked-stream (no commit) leaves the
    resident at its prior epoch — across a daemon bounce too — and
    the whole epoch retries cleanly as a fresh transaction."""
    import socket as socket_mod

    from sheep_tpu.server import protocol as proto
    from sheep_tpu.server.client import SheepClient
    from sheep_tpu.server.daemon import Daemon, build_parser

    sock = str(tmp_path / "d.sock")
    state = str(tmp_path / "state")
    rng = np.random.default_rng(43)
    n = 512
    E = rng.integers(0, n, (3000, 2)).astype(np.int64)
    base = str(tmp_path / "base.bin64")
    with open(base, "wb") as f:
        f.write(E[:1500].astype("<u8").tobytes())

    def start_daemon():
        d = Daemon(build_parser().parse_args(
            ["--socket", sock, "--state-dir", state,
             "--checkpoint-every", "1"]))
        t = threading.Thread(target=d.serve, daemon=True)
        t.start()
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if os.path.exists(sock) and d.scheduler is not None:
                return d, t
            time.sleep(0.05)
        raise AssertionError("daemon never bound")

    d1, t1 = start_daemon()
    c = SheepClient(sock, timeout_s=120)
    try:
        jid = c.submit(base, k=[4], tenant="inc", resident=True,
                       chunk_edges=CHUNK, num_vertices=n)["job_id"]
        assert c.wait(jid, timeout_s=120)["state"] == "done"
        assert c.update(jid, adds=E[1500:2000], epoch=1)["applied"]
        # torn stream: begin + chunk on a raw connection, then die
        s = socket_mod.socket(socket_mod.AF_UNIX)
        s.connect(sock)
        rf = s.makefile("rb")
        s.sendall(proto.dumps({"op": "update", "job_id": jid,
                               "stream": "begin"}))
        txn = json.loads(rf.readline())["txn"]
        s.sendall(proto.dumps({
            "op": "update", "stream": "chunk", "txn": txn,
            "adds": proto.encode_edges(E[2000:2600])}))
        assert json.loads(rf.readline())["adds"] == 600
        rf.close()
        s.close()  # no commit, ever
        assert c.epoch(jid)["epoch"] == 1
        # bounce the daemon: staged chunks must not resurrect
        d1.scheduler.shutdown_suspend(grace_s=60)
        t1.join(timeout=120)
        assert not t1.is_alive()
        c._drop()
        d2, t2 = start_daemon()
        assert c.epoch(jid)["epoch"] == 1
        # the whole epoch retries as a fresh chunked transaction
        r = c.update(jid, adds=E[2000:2600], epoch=2, score=True,
                     chunk_edges=128)
        assert r["applied"] and r["epoch"] == 2 and r["txn"]
        c.shutdown()
        t2.join(timeout=60)
        assert not t2.is_alive()
    finally:
        c.close()
