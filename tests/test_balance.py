"""Balance-bound contract tests (SURVEY.md §4.3, VERDICT r3 item 4).

The greedy split's proven envelope (see ``core/pure.py tree_split``):
every flushed bag weighs at most ``cap + max_w`` (cap = max(alpha *
total/k, 1); the mid-pack flush fires BEFORE an overflowing child is
added, and the final flush adds only v itself on top of a bag < cap),
and LPT placement puts it on a part whose load is <= total/k at that
moment (the min is <= the mean). Hence

    max part load <= total/k + cap + max_w
    balance       <= 1 + max(alpha, k/total) + max_w * k / total

This file pins that bound across the eval shapes for both split
implementations, and pins the ``--balance BETA`` contract flag
(alpha = BETA - 1) actually delivering <= BETA + max_w*k/total.
"""

import numpy as np
import pytest

import sheep_tpu
from sheep_tpu.core import pure
from sheep_tpu.io import formats, generators
from sheep_tpu.types import ElimTree


def build_tree(edges, n):
    pos = pure.elimination_order(pure.degrees(edges, n))
    return pure.build_elim_tree(edges, pos)


def split_balance(edges, n, k, alpha, weights=None):
    """Build tree + split via the pure spec; return (balance, bound)."""
    tree = build_tree(edges, n)
    w = weights if weights is not None else np.ones(n, dtype=np.int64)
    a = pure.tree_split(tree, k, weights=weights, alpha=alpha)
    assert a.min() >= 0 and a.max() < k          # every vertex assigned
    total = float(w.sum())
    loads = np.bincount(a, weights=w.astype(np.float64), minlength=k)
    balance = loads.max() / (total / k)
    bound = 1.0 + max(alpha, k / total) + float(w.max()) * k / total
    return balance, bound


GRAPHS = [
    ("karate", lambda: (generators.karate_club(), 34)),
    ("grid32", lambda: (generators.grid_graph(32, 32), 1024)),
    ("star", lambda: (generators.star_graph(1000), 1000)),
    ("rmat12", lambda: (generators.rmat(12, 8, seed=3), 1 << 12)),
    ("sbm10", lambda: (generators.sbm_hash_range(10, 0, 8 << 10, 8, 0.05,
                                                 seed=1), 1 << 10)),
]


@pytest.mark.parametrize("name,mk", GRAPHS)
@pytest.mark.parametrize("k", [2, 8, 64])
@pytest.mark.parametrize("alpha", [1.0, 0.5, 0.1])
def test_unit_weight_balance_bound(name, mk, k, alpha):
    edges, n = mk()
    balance, bound = split_balance(edges, n, k, alpha)
    assert balance <= bound + 1e-9, (name, k, alpha, balance, bound)


@pytest.mark.parametrize("name,mk", GRAPHS)
def test_degree_weight_balance_bound(name, mk):
    edges, n = mk()
    w = np.bincount(np.asarray(edges, np.int64).ravel(), minlength=n)[:n]
    w = np.maximum(w, 1).astype(np.int64)
    balance, bound = split_balance(edges, n, 8, 1.0, weights=w)
    # the star's hub carries ~half the degree weight: the bound's max_w
    # term is what keeps the contract honest there
    assert balance <= bound + 1e-9, (name, balance, bound)


def test_native_split_same_bound():
    from sheep_tpu.core import native

    if not native.available():
        pytest.skip("native core not built")
    edges, n = generators.rmat(12, 8, seed=7), 1 << 12
    tree = build_tree(edges, n)
    for alpha in (1.0, 0.25):
        a = native.tree_split(tree.parent.astype(np.int64),
                              tree.pos.astype(np.int64), 64, alpha=alpha)
        loads = np.bincount(a, minlength=64)
        balance = loads.max() / (n / 64)
        assert balance <= 1.0 + max(alpha, 64 / n) + 64 / n + 1e-9


def test_balance_flag_contract(tmp_path, capsys):
    """--balance BETA delivers balance <= BETA (+ unit max_w slack)."""
    import json

    from sheep_tpu import cli

    p = str(tmp_path / "r.edges")
    formats.write_edges(p, generators.rmat(12, 8, seed=3))
    for beta in (1.3, 1.1):
        rc = cli.main(["--input", p, "--k", "64", "--backend",
                       "cpu" if "cpu" in sheep_tpu.list_backends()
                       else "pure", "--balance", str(beta), "--json",
                       "--no-comm-volume"])
        assert rc == 0
        line = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert line["balance"] <= beta + 64 / (1 << 12) + 1e-9, \
            (beta, line["balance"])


def test_balance_flag_validation(tmp_path):
    from sheep_tpu import cli

    p = str(tmp_path / "k.edges")
    formats.write_edges(p, generators.karate_club())
    with pytest.raises(SystemExit):
        cli.main(["--input", p, "--k", "2", "--balance", "0.9"])
    with pytest.raises(SystemExit):
        cli.main(["--input", p, "--k", "2", "--balance", "1.3",
                  "--alpha", "0.5"])
