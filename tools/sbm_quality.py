"""LiveJournal-class SBM quality run (VERDICT r3 items 5+8).

Partitions a scale-22 planted-partition stream (4.2M vertices, 67M
edges, 64 blocks, p_out inter-block rate) with the cpu-native backend
and the tpu-sharded 8-device virtual mesh, scores the planted ground
truth as the known optimum, and measures the refine post-pass delta
where cut structure actually exists (the round-3 refine measurement was
on an expander).

Results -> tools/out/soak/sbm_s22.json. Wall-bounded: the refine rounds
dominate (one host stream pass each); --refine 6 keeps the run in
CI-hours on one core.

Usage:
    python tools/sbm_quality.py [--scale 22] [--blocks 64] [--p-out 0.05]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--scale", type=int, default=22)
    ap.add_argument("--blocks", type=int, default=64)
    ap.add_argument("--p-out", type=float, default=0.05)
    ap.add_argument("--edge-factor", type=int, default=16)
    ap.add_argument("--k", type=int, default=64)
    ap.add_argument("--refine", type=int, default=6)
    ap.add_argument("--seed", type=int, default=1)
    ap.add_argument("--skip-sharded", action="store_true")
    args = ap.parse_args()

    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8"
                               ).strip()
    from sheep_tpu.utils.platform import pin_platform

    pin_platform("cpu")
    import sheep_tpu
    from sheep_tpu.backends.base import score_stream
    from sheep_tpu.io import generators

    spec = (f"sbm-hash:{args.scale}:{args.blocks}:{args.p_out}:"
            f"{args.edge_factor}:{args.seed}")
    s = generators.SbmHashStream(args.scale, args.blocks, args.p_out,
                                 args.edge_factor, seed=args.seed)
    result = {"spec": spec, "n_vertices": s.num_vertices,
              "n_edges": s.num_edges, "k": args.k,
              "refine_rounds": args.refine}
    print(f"{spec}: V={s.num_vertices:,} E={s.num_edges:,} k={args.k}",
          flush=True)

    # known optimum: the planted assignment scored against the stream
    t0 = time.perf_counter()
    gt = s.ground_truth(args.k)
    cut, total, balance, _ = score_stream(
        s, {args.k: gt.astype(np.int32)}, chunk_edges=1 << 22,
        comm_volume=False)[args.k]
    result["planted"] = {"cut_ratio": round(cut / total, 6),
                        "balance": round(float(balance), 4),
                        "score_s": round(time.perf_counter() - t0, 1)}
    print("planted:", json.dumps(result["planted"]), flush=True)

    be = "cpu" if "cpu" in sheep_tpu.list_backends() else "pure"
    for label, refine in (("base", 0), ("refined", args.refine)):
        t0 = time.perf_counter()
        r = sheep_tpu.partition(spec, args.k, backend=be,
                                comm_volume=False, refine=refine)
        result[f"{be}_{label}"] = {
            "cut_ratio": round(float(r.cut_ratio), 6),
            "balance": round(float(r.balance), 4),
            "wall_s": round(time.perf_counter() - t0, 1),
        }
        print(f"{be} {label}:", json.dumps(result[f"{be}_{label}"]),
              flush=True)

    if not args.skip_sharded:
        t0 = time.perf_counter()
        r = sheep_tpu.partition(spec, args.k, backend="tpu-sharded",
                                comm_volume=False)
        result["tpu_sharded_base"] = {
            "cut_ratio": round(float(r.cut_ratio), 6),
            "balance": round(float(r.balance), 4),
            "wall_s": round(time.perf_counter() - t0, 1),
        }
        print("tpu-sharded base:",
              json.dumps(result["tpu_sharded_base"]), flush=True)

    # key the artifact by every quality-relevant knob so reruns at a
    # different k/refine depth do not clobber committed evidence
    tag = f"sbm_s{args.scale}" + (f"_k{args.k}" if args.k != 64 else "") \
        + (f"_r{args.refine}" if args.refine != 6 else "")
    out = os.path.join(REPO, "tools", "out", "soak", f"{tag}.json")
    os.makedirs(os.path.dirname(out), exist_ok=True)
    with open(out, "w") as f:
        json.dump(result, f, indent=1)
    print("wrote", out, flush=True)


if __name__ == "__main__":
    main()
