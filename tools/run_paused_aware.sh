#!/usr/bin/env bash
# Run a long CPU job that yields the (single) host core to TPU captures:
# SIGSTOP the whole process group while tools/out/CAPTURING exists
# (raised by tpu_watch2.sh), SIGCONT when it clears. The soak pipeline
# is checkpointed and kill-tolerant, so a pause is strictly safe.
#
# Auto-resume (ISSUE 9 satellite, ROADMAP item 5's dangling artifact):
# when the job exits nonzero AND its command line carries a
# --checkpoint-dir, the wrapper re-launches it with --resume appended
# (idempotent: appended once) up to SHEEP_AUTO_RESUME times (default 8,
# 0 disables). That is exactly what the V=2^30 bigv run needed — it
# died at rc=143 ~5h in and sat dead for want of an unattended retry;
# with this wrapper the kill (OOM-killer, session teardown, watchdog
# exit 121) becomes a resume instead of a lost session:
#
#   tools/run_paused_aware.sh s30.log python tools/bigv_scale30.py \
#       --checkpoint-dir tools/out/soak/s30_ckpt
#
# Usage: run_paused_aware.sh LOGFILE CMD ARGS...
set -u
cd "$(dirname "$0")/.."
log=$1; shift
flag=tools/out/CAPTURING
max_resumes=${SHEEP_AUTO_RESUME:-8}

run_once() {
  setsid "$@" >>"$log" 2>&1 &
  pid=$!
  # setsid makes the child its own process-group leader, so pgid == pid —
  # race-free, unlike reading ps before the exec has happened
  pgid=$pid
  stopped=0
  while kill -0 "$pid" 2>/dev/null; do
    if [ -e "$flag" ] && [ "$stopped" = 0 ]; then
      kill -STOP -- "-$pgid" 2>/dev/null && stopped=1
      echo "[pause-wrapper] STOPPED for capture $(date -u +%H:%M:%S)" >>"$log"
    elif [ ! -e "$flag" ] && [ "$stopped" = 1 ]; then
      kill -CONT -- "-$pgid" 2>/dev/null && stopped=0
      echo "[pause-wrapper] RESUMED $(date -u +%H:%M:%S)" >>"$log"
    fi
    sleep 5
  done
  wait "$pid"
}

: >"$log"
run_once "$@"
rc=$?
echo "[pause-wrapper] job exited rc=$rc" >>"$log"

# auto-resume loop: only for checkpointed jobs (without --checkpoint-dir
# a blind rerun would restart from scratch, silently discarding hours),
# and only for nonzero exits
resumable=0
for a in "$@"; do
  [ "$a" = "--checkpoint-dir" ] && resumable=1
done
attempt=0
while [ "$rc" -ne 0 ] && [ "$resumable" = 1 ] && [ "$attempt" -lt "$max_resumes" ]; do
  attempt=$((attempt + 1))
  case " $* " in
    *" --resume "*) ;;  # idempotent: append once
    *) set -- "$@" --resume ;;
  esac
  echo "[pause-wrapper] auto-resume $attempt/$max_resumes: $*" >>"$log"
  run_once "$@"
  rc=$?
  echo "[pause-wrapper] job exited rc=$rc (resume $attempt)" >>"$log"
done
exit "$rc"
