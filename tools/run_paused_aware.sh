#!/usr/bin/env bash
# Run a long CPU job that yields the (single) host core to TPU captures:
# SIGSTOP the whole process group while tools/out/CAPTURING exists
# (raised by tpu_watch2.sh), SIGCONT when it clears. The soak pipeline
# is checkpointed and kill-tolerant, so a pause is strictly safe.
# Usage: run_paused_aware.sh LOGFILE CMD ARGS...
set -u
cd "$(dirname "$0")/.."
log=$1; shift
flag=tools/out/CAPTURING
setsid "$@" >"$log" 2>&1 &
pid=$!
# setsid makes the child its own process-group leader, so pgid == pid —
# race-free, unlike reading ps before the exec has happened
pgid=$pid
stopped=0
while kill -0 "$pid" 2>/dev/null; do
  if [ -e "$flag" ] && [ "$stopped" = 0 ]; then
    kill -STOP -- "-$pgid" 2>/dev/null && stopped=1
    echo "[pause-wrapper] STOPPED for capture $(date -u +%H:%M:%S)" >>"$log"
  elif [ ! -e "$flag" ] && [ "$stopped" = 1 ]; then
    kill -CONT -- "-$pgid" 2>/dev/null && stopped=0
    echo "[pause-wrapper] RESUMED $(date -u +%H:%M:%S)" >>"$log"
  fi
  sleep 5
done
wait "$pid"
echo "[pause-wrapper] job exited rc=$?" >>"$log"
