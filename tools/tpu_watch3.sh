#!/usr/bin/env bash
# Round-5 tunnel watcher. Inherits tpu_watch2's hard-learned rules
# (bench FIRST once the window is confirmed; 75s subprocess probes so a
# wedged tunnel never hangs a client at jax init; CAPTURING flag yields
# the single host core; repo-local compilation cache) and adds the two
# VERDICT r4 asks:
#   - leg 0 "linkstate" (tools/tpu_probe_quick.py, ~90s) runs in EVERY
#     healthy window and appends to tools/out/linkstate.jsonl — any
#     window long enough for one warm phase banks a number, so an
#     0-full-capture round still moves evidence (item 8);
#   - a seconds-cheap Mosaic lowering smoke (tools/pallas_smoke.py)
#     decides the Pallas question BEFORE the 25-min microbench leg can
#     burn a window on a kernel that doesn't compile (weak #6).
# Leg order per window: linkstate -> bench (headline) -> pallas smoke
# -> microbench+xprof -> tune A/B sweep. Each leg counts done only on
# rc=0; completed legs never re-run; linkstate always re-runs (its
# per-window value IS the point).
set -u
cd "$(dirname "$0")/.."
export JAX_COMPILATION_CACHE_DIR="${JAX_COMPILATION_CACHE_DIR:-$PWD/.jax_cache}"
interval=${SHEEP_WATCH_INTERVAL:-150}
deadline=$(( $(date +%s) + ${SHEEP_WATCH_HOURS:-11} * 3600 ))
flag=tools/out/CAPTURING
pidfile=tools/out/watcher.pid

# exactly ONE watcher may run: two fighting over the CAPTURING flag and
# the single host core would contaminate the CPU-baseline denominator
# (tpu_watch2.sh is retired; this guard also protects against double
# arms of this script)
if [ -f "$pidfile" ] && kill -0 "$(cat "$pidfile")" 2>/dev/null; then
  echo "another watcher (pid $(cat "$pidfile")) is alive; refusing to start"
  exit 2
fi
echo $$ >"$pidfile"

probe() {
  timeout 75 python -c "
import jax, jax.numpy as jnp, numpy as np
assert int(np.asarray(jnp.sum(jnp.arange(8)))) == 28
print('ok')" 2>/dev/null | grep -q ok
}

cleanup() { rm -f "$flag" "$pidfile"; }
trap cleanup EXIT

have_bench=""
have_pallas=""
have_micro=""
have_tune=""
while [ "$(date +%s)" -lt "$deadline" ]; do
  if probe; then
    ts=$(date -u +%Y%m%dT%H%M%S)
    out="tools/out/$ts"
    mkdir -p "$out"
    touch "$flag"
    echo "tunnel healthy at $ts; capturing" | tee "$out/watch.log"

    # leg 0: link state — banks a number in ANY window, ~90s
    timeout 150 python tools/tpu_probe_quick.py \
      >"$out/linkstate.json" 2>>"$out/watch.log"
    echo "linkstate rc=$? $(cat "$out/linkstate.json" 2>/dev/null)" \
      | tee -a "$out/watch.log"

    # leg 1: the headline bench (bench-first rule from round 3)
    if [ -z "$have_bench" ]; then
      timeout 2400 python bench.py >"$out/bench.json" 2>"$out/bench.stderr"
      cat "$out/bench.json" | tee -a "$out/watch.log"
      if grep -q '"vs_baseline"' "$out/bench.json" && \
         ! grep -q '"value": 0.0' "$out/bench.json" && \
         ! grep -q '"platform": "cpu"' "$out/bench.json"; then
        have_bench=yes
        echo "HEADLINE LANDED in $out" | tee -a "$out/watch.log"
      else
        echo "bench incomplete; resuming poll" | tee -a "$out/watch.log"
        rm -f "$flag"
        sleep "$interval"
        continue
      fi
    fi

    # leg 2: Mosaic lowering smoke — decides Pallas go/no-go in seconds
    if [ -z "$have_pallas" ]; then
      timeout 420 python tools/pallas_smoke.py \
        >"$out/pallas_smoke.json" 2>>"$out/watch.log"
      rc=$?
      echo "pallas_smoke rc=$rc $(cat "$out/pallas_smoke.json" 2>/dev/null)" \
        | tee -a "$out/watch.log"
      [ "$rc" = 0 ] && have_pallas=yes
    fi

    # leg 3: microbench + xprof (incl. pallas_vmem_gather_C full probe,
    # device-only round-cost probes that pin R)
    if [ -z "$have_micro" ]; then
      timeout 1500 python tools/microbench_fixpoint.py --scale 22 \
        --chunk-log 23 --profile-dir "$out/xprof" \
        >"$out/microbench.jsonl" 2>>"$out/watch.log"
      rc=$?
      echo "microbench rc=$rc" | tee -a "$out/watch.log"
      [ "$rc" = 0 ] && [ -s "$out/microbench.jsonl" ] && have_micro=yes
    fi

    # leg 4: the stale/carry/overlap A/B sweep (decides three defaults)
    if [ -z "$have_tune" ]; then
      timeout 3600 python tools/tune_fixpoint.py --scale 22 --ef 16 \
        --chunk-logs 23 --warm w1,w8 --segment-rounds 2 \
        --lift-levels 0 --tail-divisors 2 --stale 1,0 --stale-reuse 1,4 \
        --carry 0,1 --overlap 0,1 \
        >"$out/tune22_post.jsonl" 2>>"$out/watch.log"
      rc=$?
      echo "tune rc=$rc" | tee -a "$out/watch.log"
      [ "$rc" = 0 ] && [ -s "$out/tune22_post.jsonl" ] && have_tune=yes
    fi

    if [ -n "$have_pallas" ] && [ -n "$have_micro" ] && [ -n "$have_tune" ]; then
      echo "full capture complete (bench+pallas+microbench+tune)" \
        | tee -a "$out/watch.log"
      rm -f "$flag"
      exit 0
    fi
    rm -f "$flag"
  fi
  sleep "$interval"
done
echo "deadline reached: bench=${have_bench:-no} pallas=${have_pallas:-no}" \
     "micro=${have_micro:-no} tune=${have_tune:-no}"
[ -n "$have_bench" ] && exit 0
exit 1
