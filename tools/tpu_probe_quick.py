"""Bank partial evidence in ANY healthy window (VERDICT r4 item 8).

Round 4 had zero healthy tunnel windows; rounds 3/3b each saw windows
too short for a full capture. This probe is the "one warm phase" that
banks a number in under ~90 s: per-window LINK STATE (h2d rate, d2h
rate, per-call RTT) plus the gather roofline datum — the quantities
that explained the 0.215 -> 0.064 headline swing (BASELINE.md round-3b:
same code, link state differed ~8x). With a per-window link-state line
on file, any e2e capture from the same window can be normalized to the
co-located-host bound even if nothing else lands.

Appends ONE JSON line to tools/out/linkstate.jsonl (and stdout). Cheap
by construction: largest transfer is 64 MB, gather probe is 16M
indices, everything warm-measured once. Timing forces a tiny host pull
(np.asarray(x[:1])) because block_until_ready() does not block through
the tunnel (BASELINE.md round-2 fact 4).
"""

from __future__ import annotations

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def pull(x):
    import numpy as np

    return np.asarray(x[:1])


PATH = os.path.join(REPO, "tools", "out", "linkstate.jsonl")


def bank(out):
    """Rewrite this probe's line after every leg: a mid-probe wedge (or
    the watcher's timeout kill) must not lose the numbers already
    measured — partial link state is exactly the evidence this tool
    exists to bank."""
    line = json.dumps(out)
    print(line, flush=True)
    os.makedirs(os.path.dirname(PATH), exist_ok=True)
    lines = []
    if os.path.exists(PATH):
        with open(PATH) as f:
            lines = [l for l in f.read().splitlines() if l.strip()]
    if lines and json.loads(lines[-1]).get("utc") == out["utc"]:
        lines[-1] = line
    else:
        lines.append(line)
    tmp = PATH + ".tmp"
    with open(tmp, "w") as f:
        f.write("\n".join(lines) + "\n")
    os.replace(tmp, PATH)


def main():
    import numpy as np

    out = {"probe": "linkstate", "utc": time.strftime("%Y%m%dT%H%M%S",
                                                      time.gmtime())}
    import jax
    import jax.numpy as jnp

    out["platform"] = jax.default_backend()
    bank(out)

    # per-call RTT: median of 9 tiny put+pull round trips
    rtts = []
    for _ in range(9):
        t0 = time.perf_counter()
        pull(jax.device_put(np.zeros(1, np.int32)))  # sheeplint: h2d-ok (the RTT probe measures exactly this)
        rtts.append(time.perf_counter() - t0)
    out["rtt_ms"] = round(1e3 * sorted(rtts)[len(rtts) // 2], 1)
    bank(out)

    # h2d: one 64 MB upload (forced by a dependent 4-byte pull)
    host = np.arange(1 << 24, dtype=np.int32)  # 64 MB
    t0 = time.perf_counter()
    dev = jax.device_put(host)
    pull(dev)
    h2d_s = time.perf_counter() - t0
    out["h2d_mbs"] = round(64 / h2d_s, 1)
    bank(out)

    # d2h: pull the same 64 MB back
    t0 = time.perf_counter()
    back = np.asarray(dev)
    d2h_s = time.perf_counter() - t0
    assert back[-1] == host[-1]
    out["d2h_mbs"] = round(64 / d2h_s, 1)
    bank(out)

    # gather roofline: 16M random indices from a 4M-entry table (the
    # round-2 probe shape: measured 121 ms = ~135 M elem/s on v5e)
    table = jnp.arange(1 << 22, dtype=jnp.int32)
    idx = jax.device_put(
        np.random.default_rng(0).integers(0, 1 << 22, 1 << 24,
                                          dtype=np.int32))
    f = jax.jit(lambda t, i: jnp.take(t, i, mode="clip"))
    pull(f(table, idx))  # compile warm-up
    t0 = time.perf_counter()
    pull(f(table, idx))
    g_s = time.perf_counter() - t0
    out["gather_melems"] = round((1 << 24) / g_s / 1e6, 1)
    out["complete"] = True
    bank(out)
    return 0


if __name__ == "__main__":
    sys.exit(main())
