#!/usr/bin/env python
"""Second-round Mosaic lowering smoke: 2D gather forms.

The round-5 first smoke (tools/pallas_smoke.py) got a definitive
rejection for the 1D form: ``NotImplementedError: Only 2D gather is
supported`` (tools/out/20260801T083204/pallas_smoke.json). That error
names the supported surface, so this probe enumerates the candidate 2D
forms and tries to LOWER each on the real chip (seconds apiece, no
execution beyond a tiny correctness check for the ones that compile):

  A. row-take:        table (R,128), idx (B,)    -> out (B,128)
                      jnp.take(table, idx, axis=0)
  B. sublane-gather:  table (R,128), idx (8,128) -> out (8,128)
                      take_along_axis(table, idx, axis=0)
  C. lane-gather:     x (8,128), idx (8,128)     -> out (8,128)
                      take_along_axis(x, idx, axis=1)
  D. composite scalar gather: arbitrary 1D idx via row=idx>>7 /
     col=idx&127 — sublane-gather the rows (B broadcast across lanes),
     then lane-gather the column (col broadcast), then take lane 0.
     8 arbitrary gathers per two (8,128) VPU gathers from a
     VMEM-resident table; if this lowers AND beats ~150 M elem/s it is
     the single-chip R >= 1 escape hatch (BASELINE.md re-negotiation).

Writes one JSON line per form: {form, lowered, error?, ok?, melems?}.
"""

import json
import sys
import time

import numpy as np


def _specs(pl, pltpu, shapes, out_shape):
    kw = {"memory_space": pltpu.VMEM} if pltpu else {}
    in_specs = [pl.BlockSpec(s, lambda i, r=len(s): (0,) * r, **kw)
                for s in shapes]
    out_specs = pl.BlockSpec(out_shape,
                             lambda i, r=len(out_shape): (0,) * r, **kw)
    return in_specs, out_specs


INTERPRET = "--interpret" in sys.argv


def try_form(name, kernel, in_arrays, out_shape_dtype, check=None):
    import jax
    from jax.experimental import pallas as pl

    pltpu = None
    if not INTERPRET:
        try:
            from jax.experimental.pallas import tpu as pltpu
        except Exception:
            pltpu = None

    rec = {"form": name}
    try:
        in_specs, out_specs = _specs(
            pl, pltpu, [a.shape for a in in_arrays], out_shape_dtype.shape)
        call = pl.pallas_call(
            kernel, grid=(1,), in_specs=in_specs, out_specs=out_specs,
            out_shape=out_shape_dtype, interpret=INTERPRET)
        t0 = time.perf_counter()
        lowered = jax.jit(call).lower(*in_arrays)
        compiled = lowered.compile()
        rec["lowered"] = True
        rec["compile_s"] = round(time.perf_counter() - t0, 2)
        out = np.asarray(compiled(*in_arrays))
        if check is not None:
            rec["ok"] = bool(check(out))
    except Exception as e:
        msg = f"{type(e).__name__}: {e}".splitlines()[0][:300]
        if rec.get("lowered"):
            # lowering succeeded; the failure is at run time — that is a
            # different (and better) answer than "does not lower"
            rec["run_error"] = msg
        else:
            rec["lowered"] = False
            rec["error"] = msg
    print(json.dumps(rec), flush=True)
    return rec


def main():
    import jax
    import jax.numpy as jnp

    plat = jax.devices()[0].platform
    print(json.dumps({"platform": plat,
                      "device": str(jax.devices()[0])}), flush=True)

    R, B = 4096, 1024
    rng = np.random.default_rng(0)
    table2 = jnp.asarray(
        rng.integers(0, 1 << 30, (R, 128), dtype=np.int32))
    tnp = np.asarray(table2)  # sheeplint: sync-ok

    # A: row-take
    idxA = jnp.asarray(rng.integers(0, R, (B,), dtype=np.int32))
    try_form(
        "A_row_take",
        lambda t, i, o: o.__setitem__(
            ..., jnp.take(t[...], i[...], axis=0, mode="clip")),
        [table2, idxA],
        jax.ShapeDtypeStruct((B, 128), jnp.int32),
        check=lambda out: np.array_equal(out, tnp[np.asarray(idxA)]))  # sheeplint: sync-ok

    # B: sublane gather (axis=0), idx same shape as a (8,128) tile
    idxB = jnp.asarray(rng.integers(0, R, (8, 128), dtype=np.int32))
    try_form(
        "B_sublane_gather",
        lambda t, i, o: o.__setitem__(
            ..., jnp.take_along_axis(t[...], i[...], axis=0)),
        [table2, idxB],
        jax.ShapeDtypeStruct((8, 128), jnp.int32),
        check=lambda out: np.array_equal(
            out, np.take_along_axis(tnp, np.asarray(idxB), axis=0)))  # sheeplint: sync-ok

    # C: lane gather (axis=1) on one (8,128) tile
    x8 = jnp.asarray(rng.integers(0, 1 << 30, (8, 128), dtype=np.int32))
    idxC = jnp.asarray(rng.integers(0, 128, (8, 128), dtype=np.int32))
    try_form(
        "C_lane_gather",
        lambda x, i, o: o.__setitem__(
            ..., jnp.take_along_axis(x[...], i[...], axis=1)),
        [x8, idxC],
        jax.ShapeDtypeStruct((8, 128), jnp.int32),
        check=lambda out: np.array_equal(
            out, np.take_along_axis(np.asarray(x8), np.asarray(idxC),  # sheeplint: sync-ok
                                    axis=1)))

    # D: composite arbitrary-index scalar gather, 8 per two 2D gathers.
    # idx (S, 8) int32 in [0, R*128); out (S, 8).
    S = 64
    idxD = jnp.asarray(rng.integers(0, R * 128, (S, 8), dtype=np.int32))

    def kernel_D(t, i, o):
        def one(s, _):
            g = i[s, :]                        # (8,) arbitrary indices
            row = (g >> 7).reshape(8, 1)       # broadcast rows across lanes
            col = (g & 127).reshape(8, 1)
            rows8 = jnp.take_along_axis(
                t[...], jnp.broadcast_to(row, (8, 128)), axis=0)
            z = jnp.take_along_axis(
                rows8, jnp.broadcast_to(col, (8, 128)), axis=1)
            o[s, :] = z[:, 0]
            return _

        import jax.lax as lax

        lax.fori_loop(0, S, one, 0)

    try_form(
        "D_composite_scalar",
        kernel_D,
        [table2, idxD],
        jax.ShapeDtypeStruct((S, 8), jnp.int32),
        check=lambda out: np.array_equal(
            out, tnp.reshape(-1)[np.asarray(idxD)]))  # sheeplint: sync-ok

    # E: lane-routed bulk gather. Indices PRE-ROUTED so lane j only
    # holds indices with (idx & 127) == j (the router is an XLA sort by
    # idx&127 OUTSIDE the kernel, ~4-8 GB/s measured on-chip); then ONE
    # sublane dynamic gather does a full (SB,128) tile of arbitrary
    # lookups: out[i,j] = t[idx[i,j] >> 7, j]. 1024 gathers per two VPU
    # ops at SB=8 — 128x the density of form D.
    SB = 64
    lanes = np.arange(128, dtype=np.int32)[None, :]
    rowsE = rng.integers(0, R, (SB, 128), dtype=np.int32)
    idxE = jnp.asarray(rowsE * 128 + lanes)    # pre-routed by construction

    def kernel_E(t, i, o):
        o[...] = jnp.take_along_axis(t[...], i[...] >> 7, axis=0)

    try_form(
        "E_lane_routed_bulk",
        kernel_E,
        [table2, idxE],
        jax.ShapeDtypeStruct((SB, 128), jnp.int32),
        check=lambda out: np.array_equal(
            out, tnp.reshape(-1)[np.asarray(idxE)]))  # sheeplint: sync-ok

    if "--perf" in sys.argv and plat == "tpu":
        perf(jax, jnp, rng)


def _time(f, *a):
    import jax

    jax.block_until_ready(f(*a))               # warm-up / compile
    t0 = time.perf_counter()
    for _ in range(5):
        r = f(*a)
    jax.block_until_ready(r)
    return (time.perf_counter() - t0) / 5


def perf(jax, jnp, rng):
    """Throughput of the forms that lowered vs XLA's 1D gather, matched
    shapes: table 2^20 int32 (4 MB — VMEM-resident territory), 2^20
    lookups per call. Reports M elem/s; the XLA row is the ~100-150
    M elem/s incumbent the re-negotiation cites."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    R, NI = 1 << 13, 1 << 20                   # table (8192,128) = 2^20
    table2 = jnp.asarray(
        rng.integers(0, 1 << 30, (R, 128), dtype=np.int32))
    flat = table2.reshape(-1)
    # balanced residues BY CONSTRUCTION (NI/128 indices per lane class,
    # randomly interleaved): the block-routing reshape below is exact
    # only for balanced counts; arbitrary input would need per-bucket
    # padding, which is an integration concern, not a lowering probe's
    rows1 = rng.integers(0, R, (NI,), dtype=np.int32)
    res1 = np.repeat(np.arange(128, dtype=np.int32), NI // 128)
    rng.shuffle(res1)
    idx1 = jnp.asarray(rows1 * 128 + res1)

    xla = jax.jit(lambda t, i: jnp.take(t, i, mode="clip"))
    s = _time(xla, flat, idx1)
    print(json.dumps({"perf": "xla_take_1d", "n": NI,
                      "melems": round(NI / s / 1e6, 1)}), flush=True)

    # E + its XLA router (sort by idx&127, then in-kernel sublane gather)
    SB = NI // 128
    vm = {"memory_space": pltpu.VMEM}
    callE = pl.pallas_call(
        lambda t, i, o: o.__setitem__(
            ..., jnp.take_along_axis(t[...], i[...] >> 7, axis=0)),
        grid=(1,),
        in_specs=[pl.BlockSpec((R, 128), lambda g: (0, 0), **vm),
                  pl.BlockSpec((SB, 128), lambda g: (0, 0), **vm)],
        out_specs=pl.BlockSpec((SB, 128), lambda g: (0, 0), **vm),
        out_shape=jax.ShapeDtypeStruct((SB, 128), jnp.int32))
    # gate the E legs on the kernel actually lowering (on the 2026-08
    # toolchain it does NOT — multi-row sublane gather asserts in
    # Mosaic; this keeps the perf artifact complete instead of dying
    # mid-run like the first capture did)
    try:
        probeE = jnp.zeros((SB, 128), jnp.int32)
        jax.jit(callE).lower(table2, probeE).compile()
    except Exception as e:
        print(json.dumps({
            "perf": "E_kernel_only", "lowered": False,
            "error": f"{type(e).__name__}: {e}".splitlines()[0][:300]}),
            flush=True)
        return

    # routing: element with residue j must land in LANE j. After the
    # sort the array is contiguous residue blocks; with BALANCED residue
    # counts (true for the synthetic idx below, NOT for arbitrary input
    # — a real integration pads each bucket to the max count) the
    # column-major reshape(128, SB).T puts block j into column j.
    def routed(t2, i):
        order = jnp.argsort(i & 127)           # the router (XLA sort)
        z = callE(t2, i[order].reshape(128, SB).T)
        return z.T.reshape(-1)                 # values in ROUTED order

    def routed_unrouted(t2, i):
        order = jnp.argsort(i & 127)
        z = callE(t2, i[order].reshape(128, SB).T).T.reshape(-1)
        return jnp.zeros_like(z).at[order].set(z)  # original order

    # correctness of kernel-only leg on routed input
    rowsE = rng.integers(0, R, (SB, 128), dtype=np.int32)
    lanes = np.arange(128, dtype=np.int32)[None, :]
    idxE = jnp.asarray(rowsE * 128 + lanes)
    outE = np.asarray(callE(table2, idxE))
    okE = np.array_equal(outE, np.asarray(flat)[np.asarray(idxE)])  # sheeplint: sync-ok
    s = _time(callE, table2, idxE)
    print(json.dumps({"perf": "E_kernel_only", "ok": bool(okE), "n": NI,
                      "melems": round(NI / s / 1e6, 1)}), flush=True)
    okR = np.array_equal(
        np.sort(np.asarray(routed(table2, idx1))),
        np.sort(np.asarray(flat)[np.asarray(idx1)]))  # sheeplint: sync-ok
    s = _time(jax.jit(routed), table2, idx1)
    print(json.dumps({"perf": "E_with_router", "ok": bool(okR), "n": NI,
                      "melems": round(NI / s / 1e6, 1)}), flush=True)
    okU = np.array_equal(np.asarray(routed_unrouted(table2, idx1)),
                         np.asarray(flat)[np.asarray(idx1)])  # sheeplint: sync-ok
    s = _time(jax.jit(routed_unrouted), table2, idx1)
    print(json.dumps({"perf": "E_router_unroute", "ok": bool(okU),
                      "n": NI,
                      "melems": round(NI / s / 1e6, 1)}), flush=True)


if __name__ == "__main__":
    main()
