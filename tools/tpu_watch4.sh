#!/usr/bin/env bash
# Round-5 post-queue watcher: the decision queue is closed
# (tools/out/20260801T083204/ + BASELINE.md round-5 capture section);
# the one remaining chip prize is a GOOD-LINK headline re-capture. The
# fresh banked number (vs_baseline 0.067) was taken at h2d 5.1 MB/s;
# the good-link regime (43 MB/s, r3) gave 0.215. So: probe the link
# every cycle, bank its state, and spend a bench run ONLY when h2d
# clears a threshold — an 0.067-class window has nothing left to give.
# Inherits watch3's rules: subprocess probes with hard timeouts,
# CAPTURING flag to quiesce the (pause-aware) CPU jobs during the
# bench so the native denominator is honest, single-instance pidfile.
set -u
cd "$(dirname "$0")/.."
export JAX_COMPILATION_CACHE_DIR="${JAX_COMPILATION_CACHE_DIR:-$PWD/.jax_cache}"
interval=${SHEEP_WATCH_INTERVAL:-600}
h2d_min=${SHEEP_H2D_MIN:-15}
deadline=$(( $(date +%s) + ${SHEEP_WATCH_HOURS:-10} * 3600 ))
flag=tools/out/CAPTURING
pidfile=tools/out/watcher.pid

if [ -f "$pidfile" ] && kill -0 "$(cat "$pidfile")" 2>/dev/null; then
  echo "another watcher (pid $(cat "$pidfile")) is alive; refusing to start"
  exit 2
fi
echo $$ >"$pidfile"
cleanup() { rm -f "$flag" "$pidfile"; }
trap cleanup EXIT

probe() {
  timeout 75 python -c "
import jax, jax.numpy as jnp, numpy as np
assert int(np.asarray(jnp.sum(jnp.arange(8)))) == 28
print('ok')" 2>/dev/null | grep -q ok
}

while [ "$(date +%s)" -lt "$deadline" ]; do
  if probe; then
    ts=$(date -u +%Y%m%dT%H%M%S)
    link=$(timeout 150 python tools/tpu_probe_quick.py 2>/dev/null | tail -1)
    echo "$link" >> tools/out/watch4_link.log
    h2d=$(printf '%s' "$link" | python -c "
import json,sys
try: print(json.load(sys.stdin).get('h2d_mbs', 0))
except Exception: print(0)")
    good=$(python -c "print(1 if float('${h2d:-0}' or 0) >= $h2d_min else 0)")
    if [ "$good" = 1 ]; then
      out="tools/out/$ts"
      mkdir -p "$out"
      printf '%s\n' "$link" > "$out/linkstate.json"
      touch "$flag"
      echo "good link (h2d ${h2d} MB/s) at $ts; benching" | tee "$out/watch.log"
      timeout "${SHEEP_BENCH_TIMEOUT:-3300}" python bench.py \
        >"$out/bench.json" 2>"$out/bench.stderr"
      rc=$?
      rm -f "$flag"
      cat "$out/bench.json" | tee -a "$out/watch.log"
      # armed probe (VERDICT r5 item 8): the gather-concurrency leg is
      # the last falsifiable R lever — K independent gathers in one
      # program vs K programs. Cheap (<2 min warm), runs in EVERY good
      # window the bench used, win or lose, so even a window that dies
      # mid-bench can still close R with an artifact.
      timeout 300 python tools/microbench_fixpoint.py --only-gather-conc \
        > "$out/gather_conc.jsonl" 2>>"$out/watch.log"
      echo "gather-concurrency rows banked in $out/gather_conc.jsonl" \
        | tee -a "$out/watch.log"
      if [ "$rc" = 0 ] && grep -q '"platform": "tpu"' "$out/bench.json"; then
        echo "GOOD-LINK HEADLINE LANDED in $out" | tee -a "$out/watch.log"
        exit 0
      fi
      echo "bench rc=$rc; continuing to poll" | tee -a "$out/watch.log"
    fi
  fi
  sleep "$interval"
done
