#!/usr/bin/env bash
# One-shot real-TPU capture: run the moment the axon tunnel is healthy.
# Produces, under tools/out/: the headline bench JSON + stderr, the
# micro-roofline JSON (XLA-vs-Pallas decision data, SURVEY.md §7 step 7),
# and an xprof trace of the fixpoint round — everything VERDICT r1 item 3
# asked for. Safe to re-run; each artifact is timestamped.
set -u
cd "$(dirname "$0")/.."
ts=$(date -u +%Y%m%dT%H%M%S)
out="tools/out/$ts"
mkdir -p "$out"

echo "== probe ==" | tee "$out/session.log"
timeout 120 python -c "
import jax, jax.numpy as jnp
(jnp.arange(8)+1).block_until_ready()
print('platform:', jax.default_backend())
" 2>&1 | tail -2 | tee -a "$out/session.log"
if ! grep -q "platform: tpu" "$out/session.log"; then
  echo "TPU not reachable; aborting (artifacts in $out)" | tee -a "$out/session.log"
  exit 1
fi

echo "== microbench (scale 22) ==" | tee -a "$out/session.log"
timeout 900 python tools/microbench_fixpoint.py --scale 22 --chunk-log 24 \
  --profile-dir "$out/xprof" >"$out/microbench.jsonl" 2>>"$out/session.log"

echo "== headline bench ==" | tee -a "$out/session.log"
timeout 3000 python bench.py >"$out/bench.json" 2>"$out/bench.stderr"
cat "$out/bench.json" | tee -a "$out/session.log"
tail -5 "$out/bench.stderr" | tee -a "$out/session.log"

echo "artifacts in $out" | tee -a "$out/session.log"
