#!/usr/bin/env python
"""Quality CI gate (ISSUE 13): run the fixed scenario sweep and/or
compare two committed ``QUALITY_*.json`` artifacts — cut regressions
get caught like perf ones (the ``bench_regress`` pattern).

    python tools/quality_regress.py --run NEW.json     # run the sweep
    python tools/quality_regress.py NEW.json OLD.json  # compare
    python tools/quality_regress.py                    # latest two QUALITY_*.json

The sweep covers graph CLASSES, not one generator: planted SBM (with
the per-level cut ledger + residual attribution against the planted
optimum), power-law SBM, R-MAT (the expander control), and the new
bipartite and near-clique streams (``io/generators.py``). Every
scenario is a fixed recipe over a fixed seed on the deterministic
partitioners, so two artifacts from the same code are bit-equal and
the gate can run tight: a ``cut_ratio`` or ``balance`` rise beyond
``--threshold`` on any shared scenario exits 2.

Scenarios present in exactly one artifact compare nothing — they are
listed on a ``skipped-incomparable: <names>`` line (the bench_regress
satellite's rule: a partial pass must read as partial) and the gate
stays vacuously green for them, because a sweep that grew a scenario
must not fail every older artifact retroactively.

Artifact shape::

    {"tool": "quality_regress", "suite": 1,
     "scenarios": {name: {"spec", "recipe", "cut_ratio", "balance",
                          "planted", "levels", "residual", ...}}}

Exit codes: 0 pass (or not comparable), 1 usage/IO error,
2 quality regression detected.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# bump when a scenario's spec/recipe changes: artifacts from different
# suites are not comparable (the bench_regress metric-string rule)
SUITE = 1

# Fixed sweep. Sized for CI: tiny streams, native-cpu partitioners,
# per-level refine 0 in the hierarchical scenarios (per-level refine
# re-jits one histogram per distinct subgraph shape — minutes of
# compile for zero gate value; final_refine at the one full-k shape
# carries the repair). The two hierarchical scenarios record the
# per-level cut ledger; the planted ones also record the residual
# attribution against the planted optimum.
SCENARIOS = (
    {"name": "sbm_planted", "spec": "sbm-hash:10:16:0.05:16:1",
     "k_levels": [4, 4], "refine": 0, "final_refine": 4,
     "balance": 1.05},
    {"name": "sbm_powerlaw", "spec": "plsbm-hash:11:16:0.05:16:1",
     "k": 16, "refine": 3},
    {"name": "rmat_expander", "spec": "rmat-hash:11:8:1",
     "k": 8, "refine": 2},
    {"name": "bipartite", "spec": "bipartite-hash:11:8:0.02:16:1",
     "k": 8, "refine": 2},
    {"name": "near_clique", "spec": "nearclique-hash:11:4:0.01:16:1",
     "k_levels": [4, 2], "refine": 0, "final_refine": 2,
     "balance": 1.1},
    # dynamic-graph scenario (ISSUE 15): half the shuffled stream
    # builds the base, the other half arrives as delta epochs through
    # the incremental path (sheep_tpu/incremental.py). cut_ratio is
    # the INCREMENTAL result's — gated against the committed artifact
    # like every row — and the run itself enforces the anchored-order
    # drift bound against the fresh-order one-shot build of the same
    # edges (cut_ratio <= oneshot + bound, else the sweep exits 2).
    {"name": "dynamic_sbm", "spec": "sbm-hash:10:16:0.05:16:2",
     "k": 16, "dynamic": {"epochs": 2, "bound": 0.05, "seed": 7}},
    # multi-device variant (ISSUE 19): the SAME dynamic recipe pinned
    # to the sharded backend — epochs fold through the lockstep
    # pipeline and every scored refresh rescores device-side (the
    # distributed score cache), still under the audit. The `backend`
    # key overrides the sweep-level choice for this row only.
    {"name": "dynamic_sbm_sharded", "spec": "sbm-hash:10:16:0.05:16:2",
     "k": 16, "backend": "tpu-sharded",
     "dynamic": {"epochs": 2, "bound": 0.05, "seed": 7}},
)


def run_dynamic_scenario(sc: dict, backend: str) -> dict:
    """Half-stream + deltas through the REAL incremental path; the
    one-shot build of the identical multiset rides along as the drift
    reference."""
    import numpy as np

    from sheep_tpu import incremental
    from sheep_tpu.backends.base import get_backend
    from sheep_tpu.io.edgestream import EdgeStream, open_input

    import os

    dyn = sc["dynamic"]
    with open_input(sc["spec"]) as es:
        edges = es.read_all()
        n = int(es.num_vertices)
    rng = np.random.default_rng(int(dyn.get("seed", 7)))
    e = edges[rng.permutation(len(edges))]
    half = len(e) // 2
    be = get_backend(backend)
    state, _ = incremental.begin_incremental(
        EdgeStream.from_array(e[:half], n_vertices=n), sc["k"],
        backend=be, comm_volume=False)
    res = None
    # the epochs run under SHEEP_SCORE_AUDIT (ISSUE 17): every
    # incremental rescore is cross-checked against a full score_stream
    # pass and RAISES on any divergence — so the gated cut_ratio below
    # is simultaneously a proof the O(delta) score path is exact here
    prev_audit = os.environ.get("SHEEP_SCORE_AUDIT")
    os.environ["SHEEP_SCORE_AUDIT"] = "1"
    try:
        for batch in np.array_split(e[half:],
                                    int(dyn.get("epochs", 2))):
            res = be.partition_update(state, adds=batch, score=True)
    finally:
        if prev_audit is None:
            os.environ.pop("SHEEP_SCORE_AUDIT", None)
        else:
            os.environ["SHEEP_SCORE_AUDIT"] = prev_audit
    if int(state.stats.get("score_incremental", 0)) < 1:
        # the first scored refresh seeds the cache (full pass); every
        # later epoch must take the incremental path — a silent
        # fallback to full rescoring would void the audit's coverage
        raise RuntimeError(
            f"dynamic scenario never exercised the incremental-score "
            f"path (stats={state.stats})")
    if hasattr(be, "_move_rescore") \
            and int(state.stats.get("score_distributed", 0)) < 1:
        # a multi-device backend must have taken the rescore
        # device-side at least once (ISSUE 19) — a silent fall-back
        # to the host scorer would leave the distributed path ungated
        raise RuntimeError(
            f"dynamic scenario never exercised the distributed-score "
            f"path on {be.name} (stats={state.stats})")
    oneshot = be.partition(EdgeStream.from_array(e, n_vertices=n),
                           sc["k"], comm_volume=False)
    row = {"spec": sc["spec"], "recipe": {"k": sc["k"],
                                          "dynamic": dict(dyn)},
           **({"backend": be.name} if "backend" in sc else {}),
           "k": int(res.k),
           "cut_ratio": round(float(res.cut_ratio), 6),
           "edge_cut": int(res.edge_cut),
           "total_edges": int(res.total_edges),
           "balance": round(float(res.balance), 4),
           "oneshot_cut_ratio": round(float(oneshot.cut_ratio), 6),
           "epoch": int(state.epoch)}
    bound = float(dyn.get("bound", 0.05))
    drift = float(res.cut_ratio) - float(oneshot.cut_ratio)
    row["anchored_drift"] = round(drift, 6)
    if drift > bound:
        row["bound_exceeded"] = True
    return row


def run_scenario(sc: dict, backend: str) -> dict:
    """One scenario -> its artifact row (deterministic: fixed spec,
    fixed recipe, deterministic partitioners)."""
    import sheep_tpu
    from sheep_tpu.io.edgestream import open_input
    from sheep_tpu.utils.metrics import ledger_residual

    if "dynamic" in sc:
        return run_dynamic_scenario(sc, backend)
    recipe = {k: sc[k] for k in ("k", "k_levels", "refine",
                                 "final_refine", "balance") if k in sc}
    if "k_levels" in sc:
        res = sheep_tpu.partition_hierarchical(
            sc["spec"], sc["k_levels"], backend=backend,
            refine=sc["refine"], final_refine=sc["final_refine"],
            balance=sc["balance"], comm_volume=False)
    else:
        res = sheep_tpu.partition(sc["spec"], sc["k"], backend=backend,
                                  comm_volume=False, refine=sc["refine"])
    row = {"spec": sc["spec"], "recipe": recipe, "k": int(res.k),
           "cut_ratio": round(float(res.cut_ratio), 6),
           "edge_cut": int(res.edge_cut),
           "total_edges": int(res.total_edges),
           "balance": round(float(res.balance), 4)}
    d = res.diagnostics or {}
    levels = {k: v for k, v in d.items()
              if str(k).startswith(("cut_level", "cut_ratio_level",
                                    "ledger_", "final_refine_"))}
    if levels:
        row["levels"] = levels
    with open_input(sc["spec"]) as es:
        planted_fn = getattr(es, "planted_cut_ratio", None)
        if planted_fn is not None:
            row["planted"] = round(planted_fn(), 6)
            if "k_levels" in sc:
                # the ledger vs the planted per-level optimum: which
                # level owns the residual (the ROADMAP item 4 question)
                residual = ledger_residual(d, sc["k_levels"],
                                           planted_fn, res.total_edges)
                if residual is not None:
                    row["residual"] = residual
    return row


def run_sweep(out_path: str, names=None, backend: str = None) -> dict:
    import sheep_tpu

    if backend is None:
        avail = sheep_tpu.list_backends()
        backend = next(b for b in ("cpu", "tpu", "pure") if b in avail)
    doc = {"tool": "quality_regress", "suite": SUITE,
           "backend": backend, "scenarios": {}}
    for sc in SCENARIOS:
        if names and sc["name"] not in names:
            continue
        # a scenario may pin its own backend (the multi-device rows);
        # everything else rides the sweep-level choice
        row = run_scenario(sc, sc.get("backend", backend))
        doc["scenarios"][sc["name"]] = row
        print(f"{sc['name']:<14} cut_ratio {row['cut_ratio']:.4f}  "
              f"balance {row['balance']:.3f}"
              + (f"  planted {row['planted']:.4f}"
                 if "planted" in row else ""), file=sys.stderr)
    exceeded = sorted(name for name, row in doc["scenarios"].items()
                      if row.get("bound_exceeded"))
    if exceeded:
        doc["bound_exceeded"] = exceeded
        print(f"BOUND EXCEEDED in: {', '.join(exceeded)} (anchored "
              f"drift past the scenario bound)", file=sys.stderr)
    with open(out_path, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
    print(f"wrote {out_path}", file=sys.stderr)
    return doc


def load_artifact(path: str):
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        return None, f"cannot load {path}: {e}"
    if not isinstance(doc, dict) or "scenarios" not in doc:
        return None, f"{path}: not a quality_regress artifact"
    return doc, None


def compare(new: dict, old: dict, threshold: float) -> dict:
    """Gate ``cut_ratio`` and ``balance`` per shared scenario (both
    higher-is-worse; an old value of 0 gates any rise absolutely, the
    bench_regress rule). Scenario sets may differ — the difference is
    reported as skipped, never gated."""
    out = {"comparable": True, "reason": None, "rows": [],
           "regressions": [], "skipped": []}
    if new.get("suite") != old.get("suite"):
        out["comparable"] = False
        out["reason"] = (f"suite mismatch: new={new.get('suite')!r} vs "
                         f"old={old.get('suite')!r} (scenario "
                         f"definitions differ — no fair compare)")
        return out
    sn, so = new["scenarios"], old["scenarios"]
    out["skipped"] = sorted(set(sn) ^ set(so))
    for name in sorted(set(sn) & set(so)):
        for field in ("cut_ratio", "balance"):
            a, b = sn[name].get(field), so[name].get(field)
            if not isinstance(a, (int, float)) \
                    or not isinstance(b, (int, float)):
                continue
            rel = (a - b) / abs(b) if b else None
            row = {"scenario": name, "field": field, "old": b, "new": a,
                   "rel_change": round(rel, 4) if rel is not None
                   else None}
            regressed = (a > b) if rel is None else rel > threshold
            row["verdict"] = "REGRESSION" if regressed else "ok"
            if regressed:
                out["regressions"].append(row)
            out["rows"].append(row)
    return out


def find_latest_pair(pattern: str):
    files = sorted(glob.glob(pattern))
    if len(files) < 2:
        return None
    return files[-1], files[-2]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Quality CI gate: sweep fixed scenarios and flag "
                    "cut/balance regressions between QUALITY artifacts.")
    ap.add_argument("new", nargs="?", default=None,
                    help="newer artifact (default: latest QUALITY_*.json)")
    ap.add_argument("old", nargs="?", default=None,
                    help="older artifact (default: second-latest)")
    ap.add_argument("--run", default=None, metavar="OUT.json",
                    help="run the scenario sweep, write the artifact, "
                         "exit (no compare)")
    ap.add_argument("--scenarios", default=None, metavar="A,B",
                    help="with --run: comma list of scenario names "
                         "(default: all)")
    ap.add_argument("--backend", default=None,
                    help="with --run: partitioner backend (default: "
                         "best native available; results are "
                         "backend-invariant by the cross-backend "
                         "equality contract)")
    ap.add_argument("--threshold", type=float, default=0.02,
                    help="relative rise tolerated in cut_ratio/balance "
                         "before a scenario regresses (default 0.02 — "
                         "the sweep is deterministic, so the gate runs "
                         "tight)")
    ap.add_argument("--glob", default=None,
                    help="artifact pattern for auto-discovery "
                         "(default: QUALITY_*.json next to this repo)")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)

    if args.run:
        if args.new or args.old:
            ap.error("--run does not take NEW/OLD positionals")
        # quality runs are platform-invariant and must never contend
        # for an accelerator tunnel (tools/hier_quality.py's rule)
        from sheep_tpu.utils.platform import pin_platform

        pin_platform(os.environ.get("SHEEP_QUALITY_PLATFORM") or "cpu")
        names = set(args.scenarios.split(",")) if args.scenarios else None
        doc = run_sweep(args.run, names=names, backend=args.backend)
        return 2 if doc.get("bound_exceeded") else 0

    if (args.new is None) != (args.old is None):
        ap.error("pass both NEW and OLD, or neither (auto-discovery)")
    if args.new is None:
        pattern = args.glob or os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "QUALITY_*.json")
        pair = find_latest_pair(pattern)
        if pair is None:
            print(f"error: need >= 2 artifacts matching {pattern}",
                  file=sys.stderr)
            return 1
        args.new, args.old = pair

    new, err = load_artifact(args.new)
    if err:
        print(f"error: {err}", file=sys.stderr)
        return 1
    old, err = load_artifact(args.old)
    if err:
        print(f"error: {err}", file=sys.stderr)
        return 1
    res = compare(new, old, args.threshold)

    if args.json:
        json.dump({"new": args.new, "old": args.old,
                   "threshold": args.threshold, **res},
                  sys.stdout, indent=1)
        print()
    else:
        print(f"new: {args.new}")
        print(f"old: {args.old}")
        if not res["comparable"]:
            print(f"not comparable: {res['reason']}")
            print("verdict: PASS (vacuous — nothing gated)")
            return 0
        print(f"{'scenario':<16}{'field':<11}{'old':>10}{'new':>10}"
              f"{'change':>9}  verdict")
        for row in res["rows"]:
            change = (f"{100 * row['rel_change']:>8.2f}%"
                      if row["rel_change"] is not None else f"{'n/a':>9}")
            print(f"{row['scenario']:<16}{row['field']:<11}"
                  f"{row['old']:>10.4f}{row['new']:>10.4f}{change}"
                  f"  {row['verdict']}")
        if res["skipped"]:
            print(f"skipped-incomparable: {', '.join(res['skipped'])}")
        if res["regressions"]:
            names = ", ".join(f"{r['scenario']}.{r['field']}"
                              for r in res["regressions"])
            print(f"verdict: QUALITY REGRESSION beyond "
                  f"{args.threshold:.0%} in: {names}")
        else:
            print(f"verdict: PASS (no scenario moved beyond "
                  f"{args.threshold:.0%})")
    if not res["comparable"]:
        return 0
    return 2 if res["regressions"] else 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:  # |head et al. closing stdout is not an error
        sys.exit(0)
