#!/usr/bin/env python
"""Fixpoint micro-roofline: measure the primitive ops that bound the
build phase, on whatever platform initializes (real TPU or cpu-jax).

The build fixpoint has no MXU work — it is bound by random int32
gathers, scatter-min, and streaming bandwidth (BASELINE.md roofline
note). This tool times each primitive at partition-realistic shapes and
reports effective bytes/sec vs the HBM roofline (v5e ~ 820 GB/s), which
is the data SURVEY.md §7 step 7 requires before deciding XLA-vs-Pallas
for the inner loop: if XLA's gather sustains a healthy fraction of HBM
bandwidth, a hand-written kernel has nothing to win (Pallas TPU has no
vectorized arbitrary-index gather primitive to beat it with — the VPU
is an 8x128 elementwise engine).

Usage:
    python tools/microbench_fixpoint.py [--scale 22] [--chunk-log 24]
        [--profile-dir DIR] [--platform cpu]

One JSON line per measurement on stdout; human summary on stderr.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def emit(**kw):
    print(json.dumps(kw), flush=True)


_CALL_LATENCY = [0.0]


def timeit(fn, *args, reps=5):
    """Median wall seconds of fn(*args), completion forced by pulling a
    4-byte reduction of the output to host, minus the measured per-call
    round-trip latency.

    ``block_until_ready()`` is NOT a reliable completion barrier on a
    tunneled device (measured on the axon v5e: 16M-element gathers
    "finished" in 32 us = 6 TB/s, 7.5x the HBM roofline — the round-1
    artifact preserved in tools/out/*/microbench_broken_timing.jsonl).
    A host pull of a scalar cannot lie; the tunnel's ~70 ms round-trip
    is measured once by :func:`calibrate_latency` and subtracted."""
    import numpy as np
    import jax.numpy as jnp

    def pull(out):
        x = out[0] if isinstance(out, tuple) else out
        return np.asarray(jnp.sum(x.ravel()[:8]))  # sheeplint: sync-ok

    pull(fn(*args))  # warm-up/compile
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        pull(fn(*args))
        times.append(time.perf_counter() - t0)
    return max(sorted(times)[len(times) // 2] - _CALL_LATENCY[0], 1e-9)


def calibrate_latency(reps=9):
    """Median round-trip of a trivial call + 4-byte pull (subtracted from
    every measurement)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    tiny = jax.jit(lambda x: x + 1)
    one = jnp.zeros((8,), jnp.int32)
    np.asarray(tiny(one))  # sheeplint: sync-ok
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        np.asarray(jnp.sum(tiny(one)))  # sheeplint: sync-ok
        ts.append(time.perf_counter() - t0)
    _CALL_LATENCY[0] = sorted(ts)[len(ts) // 2]
    return _CALL_LATENCY[0]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--scale", type=int, default=22, help="V = 2^scale")
    ap.add_argument("--chunk-log", type=int, default=24, help="C = 2^this")
    ap.add_argument("--profile-dir", default=None,
                    help="also capture a jax.profiler trace of one "
                         "full fixpoint round")
    ap.add_argument("--platform", default=None,
                    help="pin a platform (e.g. cpu) before jax init")
    ap.add_argument("--hbm-gbps", type=float, default=820.0,
                    help="roofline bandwidth for the ratio column")
    ap.add_argument("--only-gather-conc", action="store_true",
                    help="run ONLY the gather-concurrency leg (VERDICT "
                         "r5 item 8) — cheap enough for a short healthy "
                         "tunnel window; the watcher arms this form")
    args = ap.parse_args()

    if args.platform:
        from sheep_tpu.utils.platform import pin_platform

        pin_platform(args.platform)

    import jax
    import jax.numpy as jnp
    import numpy as np

    plat = jax.default_backend()
    n = 1 << args.scale
    c = 1 << args.chunk_log
    log(f"platform={plat}  V=2^{args.scale}={n:,}  C=2^{args.chunk_log}={c:,}")
    lat = calibrate_latency()
    emit(bench="call_latency", seconds=round(lat, 6), platform=plat)
    log(f"per-call round-trip latency: {lat * 1e3:.1f} ms (subtracted)")

    def report(name, seconds, bytes_moved, extra=None):
        gbps = bytes_moved / seconds / 1e9
        line = {"bench": name, "seconds": round(seconds, 6),
                "effective_GBps": round(gbps, 2),
                "vs_hbm_roofline": round(gbps / args.hbm_gbps, 4),
                "platform": plat}
        if extra:
            line.update(extra)
        emit(**line)
        log(f"{name:28s} {seconds * 1e3:9.2f} ms   {gbps:8.1f} GB/s "
            f"({100 * gbps / args.hbm_gbps:5.1f}% of roofline)")

    def gather_concurrency_leg():
        """The last falsifiable R probe (VERDICT r5 item 8): XLA's
        ~120 M elem/s gather is 0.2% of HBM roofline — if per-op LATENCY
        (not bandwidth) binds, K independent C-from-V gathers inside one
        XLA program overlap and the K=4 one-program row beats 4x the
        K=1 row; if the rows are flat per gather, R is formally closed.
        Both forms measured: one fused program vs K separate program
        dispatches (completion forced once at the end either way)."""
        for K in (1, 2, 4):
            tabs = [jax.random.randint(jax.random.PRNGKey(10 + j),
                                       (n + 1,), 0, n, dtype=jnp.int32)
                    for j in range(K)]
            idxs = [jax.random.randint(jax.random.PRNGKey(20 + j),
                                       (c,), 0, n, dtype=jnp.int32)
                    for j in range(K)]

            def fused(*ops):
                ts, is_ = ops[:K], ops[K:]
                return sum(jnp.sum(t[i], dtype=jnp.int64)
                           for t, i in zip(ts, is_))

            s = timeit(jax.jit(fused), *tabs, *idxs)  # sheeplint: jit-ok
            report(f"gather_conc_K{K}_one_program", s, 4 * 3 * c * K,
                   {"K": K, "melems_per_s": round(K * c / s / 1e6, 1)})

            g = jax.jit(lambda t, i: jnp.sum(t[i], dtype=jnp.int64))  # sheeplint: jit-ok

            def k_programs():
                acc = None
                for t, i in zip(tabs, idxs):
                    o = g(t, i)
                    acc = o if acc is None else acc + o
                return acc

            s = timeit(k_programs)
            report(f"gather_conc_K{K}_k_programs", s, 4 * 3 * c * K,
                   {"K": K, "melems_per_s": round(K * c / s / 1e6, 1)})

    if args.only_gather_conc:
        gather_concurrency_leg()
        return

    # transfer bandwidth: the tunnel's h2d/d2h rate bounds every phase
    # that streams chunks from host (64 MiB probes)
    import numpy as np

    host_buf = np.zeros(1 << 24, np.int32)
    t0 = time.perf_counter()
    dev_buf = jax.device_put(host_buf)
    np.asarray(jnp.sum(dev_buf.ravel()[:8]))  # sheeplint: sync-ok
    h2d = time.perf_counter() - t0
    t0 = time.perf_counter()
    np.asarray(dev_buf)
    d2h = time.perf_counter() - t0
    emit(bench="h2d_64MiB", seconds=round(h2d, 4),
         effective_GBps=round(64e-3 / h2d, 3), platform=plat)
    emit(bench="d2h_64MiB", seconds=round(d2h, 4),
         effective_GBps=round(64e-3 / d2h, 3), platform=plat)
    log(f"h2d 64MiB: {h2d:.2f}s ({64 / h2d:.0f} MB/s)   "
        f"d2h 64MiB: {d2h:.2f}s ({64 / d2h:.0f} MB/s)")

    key = jax.random.PRNGKey(0)
    k1, k2, k3 = jax.random.split(key, 3)
    table = jax.random.randint(k1, (n + 1,), 0, n, dtype=jnp.int32)
    idx_c = jax.random.randint(k2, (c,), 0, n, dtype=jnp.int32)
    vals = jax.random.randint(k3, (c,), 0, n, dtype=jnp.int32)

    # 1. random gather, C indices into a V-table (the climb's dominant op)
    g = jax.jit(lambda t, i: t[i])
    s = timeit(g, table, idx_c)
    # bytes: C index reads + C random table reads + C writes
    report("gather_C_from_V", s, 4 * (3 * c))

    # 1b. Pallas VMEM-staged gather (SURVEY.md §7 step 7, VERDICT r3
    # weak #3): the XLA gather above runs ~50x under roofline; if
    # staging the table in VMEM wins >= 2x, a Pallas round body is the
    # first credible path to single-chip R >= 1. Table capped at 2^21
    # entries (8 MB; VMEM ~16 MB/core). A Mosaic lowering rejection is
    # ALSO a result — it closes the escape hatch with an artifact.
    try:
        from sheep_tpu.ops.pallas_gather import vmem_gather

        tscale = min(args.scale, 21)
        tn = 1 << tscale
        table_s = jax.lax.slice(table, (0,), (tn,))
        idx_s = jnp.bitwise_and(idx_c, jnp.int32(tn - 1))
        s = timeit(jax.jit(lambda t, i: vmem_gather(t, i)), table_s, idx_s)
        report("pallas_vmem_gather_C", s, 4 * (3 * c),
               {"table_scale": tscale})
        g_ref = jax.jit(lambda t, i: t[i])
        s = timeit(g_ref, table_s, idx_s)
        report("xla_gather_C_matched", s, 4 * (3 * c),
               {"table_scale": tscale})
    except Exception as e:  # lowering rejection or OOM: record, move on
        emit(bench="pallas_vmem_gather_C", error=str(e)[:400],
             platform=plat)
        log(f"pallas_vmem_gather_C FAILED: {str(e)[:200]}")

    # 2. table self-gather t[t] (lifting-table squaring, V-sized)
    g2 = jax.jit(lambda t: t[t])
    s = timeit(g2, table)
    report("gather_V_from_V", s, 4 * (3 * (n + 1)))

    # 3. scatter-min, C updates into a V-table
    sm = jax.jit(lambda t, i, v: t.at[i].min(v, mode="drop"))
    s = timeit(sm, table, idx_c, vals)
    report("scatter_min_C_into_V", s, 4 * (2 * c + 2 * (n + 1)))

    # 3b. sorts at active-buffer shapes — the cost of dedup compaction
    # and of any sort-based alternative to scatter/gather
    srt = jax.jit(lambda i: jax.lax.sort(i))
    s = timeit(srt, idx_c)
    report("sort_C_int32", s, 4 * 2 * c)
    srt2 = jax.jit(lambda a, b: jax.lax.sort((a, b), num_keys=2))
    s = timeit(srt2, idx_c, vals)
    report("sort2key_C_int32", s, 4 * 4 * c)

    # 4. streaming copy baseline (pure-bandwidth reference point)
    cp = jax.jit(lambda t: t + 1)
    big = jnp.zeros(max(n + 1, c), jnp.int32)
    s = timeit(cp, big)
    report("stream_add_V", s, 4 * 2 * big.shape[0])

    # 5. one full lifting fixpoint round at partition-realistic shapes
    from sheep_tpu.ops import elim as elim_ops

    pos = jnp.concatenate([jax.random.permutation(
        k1, jnp.arange(n, dtype=jnp.int32)), jnp.full(1, n, jnp.int32)])
    order = jnp.zeros(n + 1, jnp.int32).at[pos].set(
        jnp.arange(n + 1, dtype=jnp.int32)).at[n].set(n)
    minp = jnp.full(n + 1, n, dtype=jnp.int32)
    lo = jnp.minimum(idx_c, vals)
    hi = jnp.maximum(idx_c, vals)
    lo = jnp.where(lo == hi, n, lo)
    hi = jnp.where(lo == n, n, hi)

    def one_round(minp_, lo_, hi_):
        out = elim_ops.fold_edges_segment(minp_, lo_, hi_, pos, order, n,
                                          segment_rounds=1)
        return out[2]

    s = timeit(jax.jit(one_round), minp, lo, hi)
    levels = max(1, int(n).bit_length())
    # bytes model from BASELINE.md: ~4*L*(V+C) gathered per round
    report("full_fixpoint_round", s, 4 * levels * (n + 1 + c),
           {"lift_levels": levels})

    # 5b. sort-based round prototype vs the gather round it would replace
    # (VERDICT r2 item 2): matched shapes, one round each. sorted_lookup
    # alone vs the plain gather it replaces is the primitive-level pair.
    loP = pos[lo]
    hiP = pos[hi]
    s = timeit(jax.jit(lambda m, l, h: elim_ops.fold_segment_small_pos(
        m, l, h, n, jumps=4, segment_rounds=1)[2]), minp, loP, hiP)
    report("jump_round_C", s, 4 * 4 * 2 * c, {"jumps": 4})
    s = timeit(jax.jit(lambda m, l, h: elim_ops.fold_segment_sortmerge_pos(
        m, l, h, n, jumps=4, segment_rounds=1)[2]), minp, loP, hiP)
    report("sortmerge_round_C", s, 4 * 4 * 2 * c, {"jumps": 4})
    s = timeit(jax.jit(lambda t, i: elim_ops.sorted_lookup((t,), i, n)[0]),
               table, idx_c)
    report("sorted_lookup_C_from_V", s, 4 * 3 * c)

    # 6. one jump-mode round at tail shapes (16k actives) — measured on
    # the position-space core directly, so no O(V) vertex<->position
    # conversion gathers pollute the O(C')-per-round datum
    small = 1 << 14
    s = timeit(jax.jit(lambda m, l, h: elim_ops.fold_segment_small_pos(
        m, l, h, n, segment_rounds=1)[2]),
        minp, pos[lo[:small]], pos[hi[:small]])
    report("jump_round_16k", s, 4 * 16 * 2 * small)

    # 7. gather concurrency (VERDICT r5 item 8) — see the leg's docstring
    gather_concurrency_leg()

    if args.profile_dir:
        with jax.profiler.trace(args.profile_dir):
            for _ in range(3):
                one_round(minp, lo, hi).block_until_ready()
        log(f"trace written to {args.profile_dir}")


if __name__ == "__main__":
    main()
