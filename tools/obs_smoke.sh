#!/usr/bin/env bash
# obs smoke: the observability acceptance gate, small enough for tier-1.
#
# Runs a tiny RMAT build through the real CLI with tracing + heartbeat
# on, then asserts (via trace_report --check) that the trace parses and
# contains a manifest, a COMPLETE span tree (every start has its end,
# parents intact), and >= 1 heartbeat. Wired as a fast tier-1 test by
# tests/test_obs_smoke.py.
#
# Usage: tools/obs_smoke.sh [OUT_DIR]   (default: a fresh mktemp dir)
set -euo pipefail

cd "$(dirname "$0")/.."
OUT="${1:-$(mktemp -d /tmp/sheep_obs_smoke.XXXXXX)}"
mkdir -p "$OUT"
TRACE="$OUT/trace.jsonl"
rm -f "$TRACE"

# pure backend: no device warm-up, runs in seconds on any host; the
# heartbeat's final flush guarantees >= 1 record even this fast
JAX_PLATFORMS=cpu python -m sheep_tpu.cli \
    --input rmat:10:8:1 --k 4 --backend pure \
    --trace "$TRACE" --heartbeat-secs 0.2 --json \
    > "$OUT/result.json"

# the gate: parseable + manifest + complete span tree + >= 1 heartbeat
python tools/trace_report.py "$TRACE" --check > "$OUT/report.txt"

# and the render is non-trivial: the tree shows the partition phases
grep -q "partition" "$OUT/report.txt"
grep -q "heartbeats:" "$OUT/report.txt"

# second leg: the in-flight dispatch pipeline (ISSUE 4) through the tpu
# backend on cpu-jax — the traced smoke must show a complete span tree
# with the pipelined dispatch spans AND the overlap counters
# (host_blocked_ms / device_gap_ms) flowing into the trace
TRACE2="$OUT/trace_inflight.jsonl"
rm -f "$TRACE2"
JAX_PLATFORMS=cpu python -m sheep_tpu.cli \
    --input rmat:10:8:1 --k 4 --backend tpu \
    --dispatch-batch 2 --inflight 2 --chunk-edges 1024 \
    --trace "$TRACE2" --heartbeat-secs 0.2 --json \
    > "$OUT/result_inflight.json"
python tools/trace_report.py "$TRACE2" --check > "$OUT/report_inflight.txt"
grep -q "dispatch" "$OUT/report_inflight.txt"
grep -q "host_blocked_ms" "$TRACE2"
grep -q "inflight_depth" "$TRACE2"

# third leg: the same pipelined build under SHEEP_SANITIZE=1 (ISSUE 6)
# — stray-sync traps armed around the dispatch chain, donation
# poisoning checks live, span balance asserted at tracer close. A
# stray int()/bool() on a device value anywhere in the fold/dispatch
# path, a silently dropped donation, or a leaked span fails this leg.
TRACE3="$OUT/trace_sanitized.jsonl"
rm -f "$TRACE3"
JAX_PLATFORMS=cpu SHEEP_SANITIZE=1 python -m sheep_tpu.cli \
    --input rmat:10:8:1 --k 4 --backend tpu \
    --dispatch-batch 2 --inflight 2 --chunk-edges 1024 \
    --trace "$TRACE3" --heartbeat-secs 0.2 --json \
    > "$OUT/result_sanitized.json"
python tools/trace_report.py "$TRACE3" --check > "$OUT/report_sanitized.txt"
grep -q "dispatch" "$OUT/report_sanitized.txt"

echo "obs smoke OK: $TRACE $TRACE2 $TRACE3"
