#!/usr/bin/env bash
# obs smoke: the observability acceptance gate, small enough for tier-1.
#
# Runs a tiny RMAT build through the real CLI with tracing + heartbeat
# on, then asserts (via trace_report --check) that the trace parses and
# contains a manifest, a COMPLETE span tree (every start has its end,
# parents intact), and >= 1 heartbeat. Wired as a fast tier-1 test by
# tests/test_obs_smoke.py.
#
# Usage: tools/obs_smoke.sh [OUT_DIR]   (default: a fresh mktemp dir)
set -euo pipefail

cd "$(dirname "$0")/.."
OUT="${1:-$(mktemp -d /tmp/sheep_obs_smoke.XXXXXX)}"
mkdir -p "$OUT"
TRACE="$OUT/trace.jsonl"
rm -f "$TRACE"

# pure backend: no device warm-up, runs in seconds on any host; the
# heartbeat's final flush guarantees >= 1 record even this fast
JAX_PLATFORMS=cpu python -m sheep_tpu.cli \
    --input rmat:10:8:1 --k 4 --backend pure \
    --trace "$TRACE" --heartbeat-secs 0.2 --json \
    > "$OUT/result.json"

# the gate: parseable + manifest + complete span tree + >= 1 heartbeat
python tools/trace_report.py "$TRACE" --check > "$OUT/report.txt"

# and the render is non-trivial: the tree shows the partition phases
grep -q "partition" "$OUT/report.txt"
grep -q "heartbeats:" "$OUT/report.txt"

# second leg: the in-flight dispatch pipeline (ISSUE 4) through the tpu
# backend on cpu-jax — the traced smoke must show a complete span tree
# with the pipelined dispatch spans AND the overlap counters
# (host_blocked_ms / device_gap_ms) flowing into the trace
TRACE2="$OUT/trace_inflight.jsonl"
rm -f "$TRACE2"
JAX_PLATFORMS=cpu python -m sheep_tpu.cli \
    --input rmat:10:8:1 --k 4 --backend tpu \
    --dispatch-batch 2 --inflight 2 --chunk-edges 1024 \
    --trace "$TRACE2" --heartbeat-secs 0.2 --json \
    > "$OUT/result_inflight.json"
python tools/trace_report.py "$TRACE2" --check > "$OUT/report_inflight.txt"
grep -q "dispatch" "$OUT/report_inflight.txt"
grep -q "host_blocked_ms" "$TRACE2"
grep -q "inflight_depth" "$TRACE2"

# third leg: the same pipelined build under SHEEP_SANITIZE=1 (ISSUE 6)
# — stray-sync traps armed around the dispatch chain, donation
# poisoning checks live, span balance asserted at tracer close. A
# stray int()/bool() on a device value anywhere in the fold/dispatch
# path, a silently dropped donation, or a leaked span fails this leg.
TRACE3="$OUT/trace_sanitized.jsonl"
rm -f "$TRACE3"
JAX_PLATFORMS=cpu SHEEP_SANITIZE=1 python -m sheep_tpu.cli \
    --input rmat:10:8:1 --k 4 --backend tpu \
    --dispatch-batch 2 --inflight 2 --chunk-edges 1024 \
    --trace "$TRACE3" --heartbeat-secs 0.2 --json \
    > "$OUT/result_sanitized.json"
python tools/trace_report.py "$TRACE3" --check > "$OUT/report_sanitized.txt"
grep -q "dispatch" "$OUT/report_sanitized.txt"

# fourth leg: production survival (ISSUE 8) — a tiny --k-levels build
# killed at a level boundary by SHEEP_FAULT_INJECT, then resumed from
# its checkpoint into the SAME trace file. The resumed run must pass
# the --check gate and the report must show the resume seam.
TRACE4="$OUT/trace_resume.jsonl"
CKPT4="$OUT/ckpt_resume"
rm -rf "$TRACE4" "$CKPT4"
# native cpu backend when built (no jit warm-up); tpu-on-cpu-jax otherwise
BK=$(JAX_PLATFORMS=cpu python -c \
    "from sheep_tpu import list_backends; bs = list_backends(); \
     print('cpu' if 'cpu' in bs else 'tpu')")
if JAX_PLATFORMS=cpu SHEEP_FAULT_INJECT=level:1 python -m sheep_tpu.cli \
    --input rmat:9:8:1 --k-levels 2,2 --backend "$BK" --refine 1 \
    --chunk-edges 512 --no-comm-volume \
    --checkpoint-dir "$CKPT4" --checkpoint-every 1 \
    --trace "$TRACE4" --heartbeat-secs 0.2 --json \
    > /dev/null 2> "$OUT/fault.err"; then
    echo "fault-injected run unexpectedly succeeded" >&2
    exit 1
fi
JAX_PLATFORMS=cpu python -m sheep_tpu.cli \
    --input rmat:9:8:1 --k-levels 2,2 --backend "$BK" --refine 1 \
    --chunk-edges 512 --no-comm-volume \
    --checkpoint-dir "$CKPT4" --resume \
    --trace "$TRACE4" --heartbeat-secs 0.2 --json \
    > "$OUT/result_resume.json"
python tools/trace_report.py "$TRACE4" --check > "$OUT/report_resume.txt"
grep -q "resume:" "$OUT/report_resume.txt"
grep -q '"event": "resume"' "$TRACE4"

# fifth leg: chaos (ISSUE 9) — one seeded chaos schedule through the
# CLI over a real file stream (so read-error points are live), absorbed
# IN-PROCESS by the retry/degrade layer: the run must exit 0, the trace
# must pass the --check gate, and the injected faults + their handling
# (retry / dispatch_degraded / device_reinit) must be on the record.
# Seed 46 is pinned: on this code's point sequence it injects two read
# faults, both absorbed by the edgestream's bounded retry (read points
# dominate the chaos draw — three passes touch every chunk). The
# OOM-degrade and device-reinit paths are pinned deterministically by
# tests/test_chaos.py instead; the grep below accepts either shape so
# a shifted point sequence only needs a seed with >= 1 absorbed fault.
TRACE5="$OUT/trace_chaos.jsonl"
GRAPH5="$OUT/chaos.bin64"
rm -f "$TRACE5"
JAX_PLATFORMS=cpu python - "$GRAPH5" <<'PYEOF'
import sys
from sheep_tpu.io import formats, generators
formats.write_edges(sys.argv[1], generators.random_graph(512, 4096, seed=7))
PYEOF
JAX_PLATFORMS=cpu SHEEP_FAULT_INJECT=chaos:46:2:0.15 SHEEP_RETRY_BASE_S=0.01 \
    python -m sheep_tpu.cli \
    --input "$GRAPH5" --num-vertices 512 --k 4 --backend tpu \
    --dispatch-batch 2 --inflight 2 --chunk-edges 512 --no-comm-volume \
    --trace "$TRACE5" --heartbeat-secs 0.2 --json \
    > "$OUT/result_chaos.json" 2> "$OUT/chaos.err"
python tools/trace_report.py "$TRACE5" --check > "$OUT/report_chaos.txt"
grep -q '"event": "chaos_inject"' "$TRACE5"
grep -q '"event": "retry"' "$TRACE5"
grep -qE '"event": "(dispatch_degraded|device_reinit)"' "$TRACE5" || \
    grep -q '"kind": "read"' "$TRACE5"

# sixth leg: partition-as-a-service (ISSUE 10) — sheepd on a unix
# socket, two concurrent tiny jobs from different tenants plus a
# multi-k query, one job cancelled mid-flight, clean shutdown. The
# gate: trace_report --check green (zero UNCLOSED spans survive the
# cancel + shutdown paths), per-job span trees + tenant cost rows in
# the report, and the repeat-shape job proving warm program reuse
# (jit_compiles == 0).
TRACE6="$OUT/trace_served.jsonl"
SOCK6="$OUT/sheepd.sock"
rm -f "$TRACE6" "$SOCK6"
JAX_PLATFORMS=cpu python -m sheep_tpu.server.daemon \
    --socket "$SOCK6" --trace "$TRACE6" --heartbeat-secs 0.2 \
    2> "$OUT/sheepd.err" &
SHEEPD_PID=$!
for _ in $(seq 1 100); do [ -S "$SOCK6" ] && break; sleep 0.2; done
[ -S "$SOCK6" ] || { echo "sheepd never bound $SOCK6" >&2; exit 1; }
if ! JAX_PLATFORMS=cpu python - "$SOCK6" > "$OUT/served.json" \
        2> "$OUT/served.err" <<'PYEOF'
import json
import sys

from sheep_tpu.server.client import SheepClient

with SheepClient(sys.argv[1]) as c:
    # two concurrent tenants + a multi-k query on a shared tree
    a = c.submit("rmat:10:8:1", k=4, tenant="alice", chunk_edges=1024)
    b = c.submit("rmat:10:8:2", k=[4, 8], tenant="bob",
                 chunk_edges=1024)
    # third job with many small chunks: cancelled mid-flight — poll
    # until the scheduler actually started stepping it so the cancel
    # exercises the running-job path (generator close -> prefetcher
    # cancel -> span end), not the cheap queued-job dequeue
    import time

    v = c.submit("rmat:12:8:3", k=4, tenant="victim", chunk_edges=512)
    for _ in range(500):
        st = c.status(v["job_id"])
        if st["state"] != "queued" or st["steps"]:
            break
        time.sleep(0.01)
    assert st["state"] == "running", st
    c.cancel(v["job_id"])  # async for running jobs; wait observes it
    cancelled = c.wait(v["job_id"], timeout_s=60)["state"]
    ja = c.wait(a["job_id"], timeout_s=120)
    jb = c.wait(b["job_id"], timeout_s=120)
    # repeat of job a's shape: must reuse every compiled program
    w = c.submit("rmat:10:8:1", k=4, tenant="alice", chunk_edges=1024)
    jw = c.wait(w["job_id"], timeout_s=120)
    assert ja["state"] == "done", ja
    assert jb["state"] == "done" and len(jb["results"]) == 2, jb
    assert cancelled == "cancelled", cancelled
    assert jw["state"] == "done", jw
    assert jw.get("jit_compiles") == 0, \
        f"repeat shape recompiled: {jw.get('jit_compiles')}"
    print(json.dumps({"a": ja["state"], "b": jb["state"],
                      "victim": cancelled,
                      "warm_jit_compiles": jw["jit_compiles"]}))
    c.shutdown()
PYEOF
then
    echo "served smoke client failed:" >&2
    cat "$OUT/served.err" >&2
    kill "$SHEEPD_PID" 2>/dev/null || true
    exit 1
fi
wait "$SHEEPD_PID"
python tools/trace_report.py "$TRACE6" --check > "$OUT/report_served.txt"
grep -q "job:j1" "$OUT/report_served.txt"      # per-job span trees
grep -q "tenant alice:" "$OUT/report_served.txt"   # cost attribution
grep -q "tenant bob:" "$OUT/report_served.txt"
grep -q "state=cancelled" "$OUT/report_served.txt" # the mid-flight cancel
grep -q "jit_compiles=0" "$OUT/report_served.txt"  # warm program reuse

# seventh leg: the live telemetry plane (ISSUE 11) — sheepd with
# --metrics-port under an admission budget sized so the second job
# QUEUES: mid-build the HTTP scrape must show a non-zero queue-depth
# gauge and live per-job progress gauges; after both jobs finish, the
# per-tenant request-latency histogram series; one on-demand `profile`
# capture must land files in the requested directory; and a
# `sheep-submit --watch` submission must render live progress lines.
# Part B: a fault-storm daemon whose job FAILS must leave a
# flight-recorder dump in the trace, rendered by --last-errors.
# Finally sheeplint stays at zero over sheep_tpu + tools (the new
# telemetry modules included).
TRACE7="$OUT/trace_telemetry.jsonl"
SOCK7="$OUT/sheepd_tele.sock"
PROF7="$OUT/profile_capture"
rm -f "$TRACE7" "$SOCK7"
rm -rf "$PROF7"
# budget: 1.1x the BIG job's modeled footprint at dispatch_batch=1 —
# the big job reserves almost all of it, so the small job (~25% of the
# big one's model) queues behind the reservation until release
BUDGET7=$(JAX_PLATFORMS=cpu python -c \
    "from sheep_tpu.utils import membudget; \
     print(int(1.1 * membudget.build_phase_bytes( \
         1 << 12, 512, dispatch_batch=1)['total_bytes']))")
JAX_PLATFORMS=cpu python -m sheep_tpu.server.daemon \
    --socket "$SOCK7" --trace "$TRACE7" --heartbeat-secs 0.2 \
    --metrics-port 0 --budget-bytes "$BUDGET7" \
    2> "$OUT/sheepd_tele.err" &
SHEEPD7_PID=$!
SHEEPD7B_PID=""
# any failure below must not leak a resident daemon holding the
# harness's pipes open (a leaked sheepd turns one failed assert into
# a hung CI job)
trap 'kill $SHEEPD7_PID $SHEEPD7B_PID 2>/dev/null || true' EXIT
for _ in $(seq 1 100); do [ -S "$SOCK7" ] && break; sleep 0.2; done
[ -S "$SOCK7" ] || { echo "telemetry sheepd never bound $SOCK7" >&2; exit 1; }
MPORT7=$(grep -oE 'metrics on http://[^/]+' "$OUT/sheepd_tele.err" \
    | grep -oE '[0-9]+$')
[ -n "$MPORT7" ] || { echo "no metrics port in sheepd stderr" >&2; exit 1; }
if ! JAX_PLATFORMS=cpu python - "$SOCK7" "$MPORT7" "$PROF7" \
        > "$OUT/telemetry.json" 2> "$OUT/telemetry.err" <<'PYEOF'
import json
import sys
import time
import urllib.request

from sheep_tpu.obs.metrics import parse_prometheus
from sheep_tpu.server.client import SheepClient

sock, port, prof_dir = sys.argv[1], sys.argv[2], sys.argv[3]


def scrape():
    url = f"http://127.0.0.1:{port}/metrics"
    return parse_prometheus(
        urllib.request.urlopen(url, timeout=10).read().decode())


with SheepClient(sock) as c:
    # big job fills the budget; small job must queue behind it
    a = c.submit("rmat:12:8:3", k=4, tenant="alice", chunk_edges=512,
                 dispatch_batch=1)
    b = c.submit("rmat:10:8:2", k=4, tenant="bob", chunk_edges=512,
                 dispatch_batch=1)
    for _ in range(500):
        st = c.status(a["job_id"])
        if st["state"] == "running" and st["steps"]:
            break
        time.sleep(0.01)
    mid = scrape()
    assert mid["sheepd_queue_depth"][0][1] >= 1, \
        f"queued job not visible: {mid.get('sheepd_queue_depth')}"
    assert any(lb.get("job") == a["job_id"] and v >= 1
               for lb, v in mid.get("sheepd_job_steps", [])), \
        "no live per-job progress gauge mid-build"
    prof = c.profile(prof_dir, steps=2)
    assert prof["state"] == "armed", prof
    ja = c.wait(a["job_id"], timeout_s=240)
    jb = c.wait(b["job_id"], timeout_s=240)
    assert ja["state"] == "done" and jb["state"] == "done", (ja, jb)
    done = scrape()
    lat = {lb["tenant"]: v for lb, v in
           done.get("sheepd_request_latency_seconds_count", [])}
    assert lat.get("alice") == 1 and lat.get("bob") == 1, lat
    # the metrics VERB answers the same families as the HTTP scrape
    verb = parse_prometheus(c.metrics())
    assert "sheepd_request_latency_seconds_bucket" in verb
    assert c.stats()["profile"]["state"] == "done"
    print(json.dumps({"mid_queue_depth": mid["sheepd_queue_depth"][0][1],
                      "latency_counts": lat}))
PYEOF
then
    echo "telemetry smoke client failed:" >&2
    cat "$OUT/telemetry.err" >&2
    kill "$SHEEPD7_PID" 2>/dev/null || true
    exit 1
fi
# --watch renders live progress lines on stderr, descriptor on stdout
# (small chunk/batch: the job must FIT the deliberately tiny budget)
JAX_PLATFORMS=cpu python -m sheep_tpu.server.client \
    --server "$SOCK7" --input rmat:10:8:1 --k 4 --tenant carol \
    --chunk-edges 512 --dispatch-batch 1 \
    --watch --poll 0.1 > "$OUT/watch.json" 2> "$OUT/watch.err"
grep -qE "running|done" "$OUT/watch.err"
python -c "import json,sys; d=json.load(open(sys.argv[1])); \
    assert d['state']=='done', d" "$OUT/watch.json"
JAX_PLATFORMS=cpu python -m sheep_tpu.server.client \
    --server "$SOCK7" --shutdown > /dev/null
wait "$SHEEPD7_PID"
[ -n "$(find "$PROF7" -type f 2>/dev/null)" ] || {
    echo "profile capture left no files in $PROF7" >&2; exit 1; }
python tools/trace_report.py "$TRACE7" --check > "$OUT/report_tele.txt"
grep -q '"queue_depth"' "$TRACE7"   # heartbeat carries service pressure

# part B: a failed job's flight-recorder dump, rendered by --last-errors
TRACE7B="$OUT/trace_flight.jsonl"
SOCK7B="$OUT/sheepd_flight.sock"
rm -f "$TRACE7B" "$SOCK7B"
JAX_PLATFORMS=cpu SHEEP_FAULT_INJECT=oom@dispatch:1:99 \
    SHEEP_RETRY_MAX=2 SHEEP_RETRY_BASE_S=0.01 \
    python -m sheep_tpu.server.daemon \
    --socket "$SOCK7B" --trace "$TRACE7B" --heartbeat-secs 0.2 \
    2> "$OUT/sheepd_flight.err" &
SHEEPD7B_PID=$!
for _ in $(seq 1 100); do [ -S "$SOCK7B" ] && break; sleep 0.2; done
[ -S "$SOCK7B" ] || { echo "flight sheepd never bound $SOCK7B" >&2; exit 1; }
if JAX_PLATFORMS=cpu python -m sheep_tpu.server.client \
    --server "$SOCK7B" --input rmat:10:8:1 --k 4 --tenant doomed \
    --wait > "$OUT/flight_job.json" 2>&1; then
    echo "fault-storm served job unexpectedly succeeded" >&2
    kill "$SHEEPD7B_PID" 2>/dev/null || true
    exit 1
fi
JAX_PLATFORMS=cpu python -m sheep_tpu.server.client \
    --server "$SOCK7B" --shutdown > /dev/null
wait "$SHEEPD7B_PID"
grep -q '"event": "flight_dump"' "$TRACE7B"
python tools/trace_report.py "$TRACE7B" --last-errors 8 \
    > "$OUT/report_flight.txt"
grep -q "job_failed" "$OUT/report_flight.txt"
grep -q "fault_inject" "$OUT/report_flight.txt"

# eighth leg: zero-copy ingest (ISSUE 12) — (a) a host-format stream
# through the pipelined tpu backend with an explicit staged H2D ring:
# the trace's diagnostics must carry the new ingest counters and the
# ringed result must bit-equal the unringed pipelined run (leg 2's
# result, same rmat: input); (b) a device-generated rmat-hash stream
# (a DIFFERENT generator — no cross-leg score compare; device==host
# bit-equality is pinned by tests/test_h2d_ring.py): zero per-chunk
# host staging bytes on the record.
TRACE8="$OUT/trace_ring.jsonl"
rm -f "$TRACE8"
JAX_PLATFORMS=cpu python -m sheep_tpu.cli \
    --input rmat:10:8:1 --k 4 --backend tpu \
    --dispatch-batch 2 --inflight 2 --h2d-ring 2 --chunk-edges 1024 \
    --trace "$TRACE8" --heartbeat-secs 0.2 --json \
    > "$OUT/result_ring.json"
python tools/trace_report.py "$TRACE8" --check > "$OUT/report_ring.txt"
grep -q '"h2d_staged_ms"' "$TRACE8"
grep -q '"h2d_blocked_ms"' "$TRACE8"
grep -q '"h2d_ring_depth"' "$TRACE8"
TRACE8B="$OUT/trace_devstream.jsonl"
rm -f "$TRACE8B"
JAX_PLATFORMS=cpu python -m sheep_tpu.cli \
    --input rmat-hash:10:8:1 --k 4 --backend tpu \
    --dispatch-batch 2 --inflight 2 --chunk-edges 1024 \
    --trace "$TRACE8B" --heartbeat-secs 0.2 --json \
    > "$OUT/result_devstream.json"
python tools/trace_report.py "$TRACE8B" --check > "$OUT/report_devstream.txt"
grep -q '"device_stream_chunks"' "$TRACE8B"
python - "$OUT/result_inflight.json" "$OUT/result_ring.json" "$TRACE8B" <<'PYEOF'
import json
import sys

ringed = json.load(open(sys.argv[2]))
base = json.load(open(sys.argv[1]))
assert ringed["edge_cut"] == base["edge_cut"], (base, ringed)
for line in open(sys.argv[3]):
    rec = json.loads(line)
    if rec.get("event") == "diagnostics":
        assert rec.get("h2d_staged_bytes") == 0, rec
        assert rec.get("device_stream_chunks", 0) > 0, rec
        break
else:
    raise SystemExit("no diagnostics record in the device-stream trace")
PYEOF

# ninth leg: the quality observability plane (ISSUE 13) — (a) a tiny
# --k-levels build must emit the cut ledger (per-level attribution +
# refine-round + split-balance events) into the trace, with
# trace_report rendering the quality tree and --check staying green;
# (b) the naive low-signal flat invocation must PRINT the advisor's
# recipe; (c) quality_regress's fresh full sweep must pass the gate
# against the committed QUALITY_r01.json seed artifact — cut
# regressions caught like perf ones.
TRACE9="$OUT/trace_quality.jsonl"
rm -f "$TRACE9"
JAX_PLATFORMS=cpu python -m sheep_tpu.cli \
    --input sbm-hash:10:16:0.05:8:1 --k-levels 4,4 --backend pure \
    --refine 0 --final-refine 2 --no-comm-volume \
    --trace "$TRACE9" --heartbeat-secs 0.2 --json \
    > "$OUT/result_quality.json"
python tools/trace_report.py "$TRACE9" --check > "$OUT/report_quality.txt"
grep -q '"event": "quality_ledger"' "$TRACE9"
grep -q '"event": "refine_round"' "$TRACE9"
grep -q '"event": "split_balance"' "$TRACE9"
grep -q "quality ledger:" "$OUT/report_quality.txt"
grep -q "level0 (fragmentation)" "$OUT/report_quality.txt"
JAX_PLATFORMS=cpu python -m sheep_tpu.cli \
    --input sbm-hash:10:16:0.05:4:1 --k 16 --backend pure --refine 0 \
    --no-comm-volume --json > /dev/null 2> "$OUT/advisor.err"
grep -q "quality advisor" "$OUT/advisor.err"
grep -q -- "--k-levels 4,4" "$OUT/advisor.err"
QUAL9="$OUT/QUALITY_fresh.json"
JAX_PLATFORMS=cpu python tools/quality_regress.py --run "$QUAL9" \
    2> "$OUT/quality_sweep.err"
python tools/quality_regress.py "$QUAL9" QUALITY_r02.json \
    > "$OUT/quality_gate.txt"
grep -q "verdict: PASS" "$OUT/quality_gate.txt"

# tenth leg: durable sheepd (ISSUE 14) — kill -9 the daemon mid-build
# through the real CLI, restart it on the same socket/journal/state
# dir: the journaled job must RESUME from its per-job checkpoint (the
# resume event on the record, rendered by trace_report), the restart
# counters must be exported at /metrics, and --check must stay green
# across the appended daemon runs.
TRACE10="$OUT/trace_durable.jsonl"
SOCK10="$OUT/sheepd_durable.sock"
STATE10="$OUT/sheepd_state"
rm -f "$TRACE10" "$SOCK10"
rm -rf "$STATE10"
JAX_PLATFORMS=cpu python -m sheep_tpu.server.daemon \
    --socket "$SOCK10" --trace "$TRACE10" --heartbeat-secs 0.2 \
    --state-dir "$STATE10" --checkpoint-every 1 --metrics-port 0 \
    2> "$OUT/sheepd_durable.err" &
SHEEPD10_PID=$!
trap 'kill $SHEEPD7_PID $SHEEPD7B_PID $SHEEPD10_PID 2>/dev/null || true' EXIT
for _ in $(seq 1 100); do [ -S "$SOCK10" ] && break; sleep 0.2; done
[ -S "$SOCK10" ] || { echo "durable sheepd never bound $SOCK10" >&2; exit 1; }
# submit through the real CLI (small chunks + batch 1: many observable
# build steps), then poll until the kill window is INSIDE the build
JID10=$(JAX_PLATFORMS=cpu python -m sheep_tpu.server.client \
    --server "$SOCK10" --input rmat:12:8:3 --k 4 --tenant durable \
    --chunk-edges 512 --dispatch-batch 1 \
    | python -c "import json,sys; print(json.load(sys.stdin)['job_id'])")
JAX_PLATFORMS=cpu python - "$SOCK10" "$JID10" <<'PYEOF'
import sys
import time

from sheep_tpu.server.client import SheepClient

with SheepClient(sys.argv[1]) as c:
    for _ in range(4000):
        st = c.status(sys.argv[2])
        if st.get("phase") == "build" and st.get("steps", 0) >= 3:
            sys.exit(0)
        if st.get("state") not in ("queued", "running"):
            raise SystemExit(f"job left the kill window: {st}")
        time.sleep(0.005)
raise SystemExit("job never reached the build phase")
PYEOF
kill -9 "$SHEEPD10_PID"
wait "$SHEEPD10_PID" 2>/dev/null || true
JAX_PLATFORMS=cpu python -m sheep_tpu.server.daemon \
    --socket "$SOCK10" --trace "$TRACE10" --heartbeat-secs 0.2 \
    --state-dir "$STATE10" --checkpoint-every 1 --metrics-port 0 \
    2>> "$OUT/sheepd_durable.err" &
SHEEPD10_PID=$!
trap 'kill $SHEEPD7_PID $SHEEPD7B_PID $SHEEPD10_PID 2>/dev/null || true' EXIT
# the client failover path rides out the restart window (stale socket
# file, then the rebinding daemon) and the resumed job must finish;
# the restart counters come from the HTTP /metrics scrape
JAX_PLATFORMS=cpu python - "$SOCK10" "$JID10" "$OUT/sheepd_durable.err" \
    > "$OUT/durable.json" 2> "$OUT/durable.err" <<'PYEOF'
import json
import re
import sys
import urllib.request

from sheep_tpu.obs.metrics import parse_prometheus
from sheep_tpu.server.client import SheepClient

sock, jid, err_path = sys.argv[1], sys.argv[2], sys.argv[3]
with SheepClient(sock, reconnect=40, reconnect_base_s=0.3) as c:
    job = c.wait(jid, timeout_s=300)
    assert job["state"] == "done", job
    ports = re.findall(r"metrics on http://[^:]+:(\d+)",
                       open(err_path).read())
    url = f"http://127.0.0.1:{ports[-1]}/metrics"
    m = parse_prometheus(
        urllib.request.urlopen(url, timeout=10).read().decode())
    restarts = sum(v for _, v in m.get("sheepd_restarts_total", []))
    resumed = sum(v for _, v in m.get("sheepd_jobs_resumed_total", []))
    assert restarts >= 1, m.get("sheepd_restarts_total")
    assert resumed >= 1, m.get("sheepd_jobs_resumed_total")
    print(json.dumps({"state": job["state"], "restarts": restarts,
                      "jobs_resumed": resumed}))
    c.shutdown()
PYEOF
wait "$SHEEPD10_PID"
python tools/trace_report.py "$TRACE10" --check > "$OUT/report_durable.txt"
grep -q '"event": "resume"' "$TRACE10"        # the checkpoint resume seam
grep -q '"event": "job_recovered"' "$TRACE10" # the journal replay seam
grep -q "resume:" "$OUT/report_durable.txt"

# eleventh leg: incremental repartitioning served end-to-end (ISSUE
# 15) — a resident partition built through the real CLI/clients, two
# delta epochs streamed at it with the `sheep update` verb, a
# compaction, a kill -9 + restart on the same state dir: the resident
# partition must resume at its journaled epoch, sheep_updates_total /
# sheep_update_latency_seconds must join the /metrics catalog, the
# scored update must bit-match the one-shot delta: build, and --check
# must stay green across the appended daemon runs.
TRACE11="$OUT/trace_incremental.jsonl"
SOCK11="$OUT/sheepd_inc.sock"
STATE11="$OUT/sheepd_inc_state"
rm -f "$TRACE11" "$SOCK11"
rm -rf "$STATE11"
JAX_PLATFORMS=cpu python - "$OUT" <<'PYEOF'
import os
import sys

import numpy as np

from sheep_tpu.io import deltalog as dl

out = sys.argv[1]
rng = np.random.default_rng(11)
E = rng.integers(0, 512, (6000, 2))
base = os.path.join(out, "inc_base.bin64")
with open(base, "wb") as f:
    f.write(E[:3000].astype("<u8").tobytes())
with dl.DeltaLogWriter(os.path.join(out, "inc.dlog"),
                       base_spec=base) as w:
    w.append(E[3000:4500])
    w.append(E[4500:])
PYEOF
JAX_PLATFORMS=cpu python -m sheep_tpu.server.daemon \
    --socket "$SOCK11" --trace "$TRACE11" --heartbeat-secs 0.2 \
    --state-dir "$STATE11" --checkpoint-every 4 --metrics-port 0 \
    2> "$OUT/sheepd_inc.err" &
SHEEPD11_PID=$!
trap 'kill $SHEEPD7_PID $SHEEPD7B_PID $SHEEPD10_PID $SHEEPD11_PID 2>/dev/null || true' EXIT
for _ in $(seq 1 100); do [ -S "$SOCK11" ] && break; sleep 0.2; done
[ -S "$SOCK11" ] || { echo "inc sheepd never bound $SOCK11" >&2; exit 1; }
JID11=$(JAX_PLATFORMS=cpu python -m sheep_tpu.server.client \
    --server "$SOCK11" --input "$OUT/inc_base.bin64" --k 4 \
    --num-vertices 512 --chunk-edges 512 --tenant inc --resident --wait \
    | python -c "import json,sys; print(json.load(sys.stdin)['job_id'])")
# stream the log's two epochs at the resident partition (the real
# `sheep update` CLI verb), scoring the final state
JAX_PLATFORMS=cpu python -m sheep_tpu.cli update "$JID11" \
    --server "$SOCK11" --deltas "$OUT/inc.dlog" --score \
    > "$OUT/inc_update.json"
JAX_PLATFORMS=cpu python -m sheep_tpu.server.client --server "$SOCK11" \
    --compact "$JID11" > "$OUT/inc_compact.json"
# metrics BEFORE the kill (counters are per-process):
# sheep_updates_total and the latency series must be in the catalog
JAX_PLATFORMS=cpu python - "$OUT/sheepd_inc.err" <<'PYEOF'
import re
import sys
import urllib.request

from sheep_tpu.obs.metrics import parse_prometheus

ports = re.findall(r"metrics on http://[^:]+:(\d+)",
                   open(sys.argv[1]).read())
url = f"http://127.0.0.1:{ports[-1]}/metrics"
text = urllib.request.urlopen(url, timeout=10).read().decode()
m = parse_prometheus(text)
updates = sum(v for _, v in m.get("sheep_updates_total", []))
assert updates >= 2, m.get("sheep_updates_total")
assert "sheep_update_latency_seconds_bucket" in text, \
    "update latency histogram missing from /metrics"
assert sum(v for _, v in m.get("sheepd_resident_partitions", [])) >= 1
PYEOF
kill -9 "$SHEEPD11_PID"
wait "$SHEEPD11_PID" 2>/dev/null || true
JAX_PLATFORMS=cpu python -m sheep_tpu.server.daemon \
    --socket "$SOCK11" --trace "$TRACE11" --heartbeat-secs 0.2 \
    --state-dir "$STATE11" --checkpoint-every 4 --metrics-port 0 \
    2>> "$OUT/sheepd_inc.err" &
SHEEPD11_PID=$!
trap 'kill $SHEEPD7_PID $SHEEPD7B_PID $SHEEPD10_PID $SHEEPD11_PID 2>/dev/null || true' EXIT
# the resident partition resumes at its journaled epoch (2) across
# the SIGKILL, and the scored update bit-matches the one-shot build
# of the same delta: input through the plain CLI
JAX_PLATFORMS=cpu python - "$SOCK11" "$JID11" "$OUT" \
    > "$OUT/inc_resume.json" <<'PYEOF'
import json
import os
import subprocess
import sys

from sheep_tpu.server.client import SheepClient

sock, jid, out = sys.argv[1], sys.argv[2], sys.argv[3]
with SheepClient(sock, reconnect=40, reconnect_base_s=0.3) as c:
    ep = c.epoch(jid)
    assert ep["epoch"] == 2, ep
    upd = json.load(open(os.path.join(out, "inc_update.json")))
    assert upd["epoch"] == 2 and upd["applied"], upd
    served_cut = upd["results"][0]["edge_cut"]
    one = subprocess.run(
        [sys.executable, "-m", "sheep_tpu.cli", "--input",
         f"delta:{os.path.join(out, 'inc.dlog')}", "--k", "4",
         "--num-vertices", "512", "--chunk-edges", "512", "--json"],
        capture_output=True, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert one.returncode == 0, one.stderr[-800:]
    oneshot = json.loads(one.stdout.strip().splitlines()[-1])
    assert served_cut == oneshot["edge_cut"], (served_cut, oneshot)
    print(json.dumps({"epoch": ep["epoch"],
                      "served_cut": served_cut,
                      "oneshot_cut": oneshot["edge_cut"]}))
    c.shutdown()
PYEOF
wait "$SHEEPD11_PID"
python tools/trace_report.py "$TRACE11" --check \
    > "$OUT/report_incremental.txt"
grep -q '"event": "delta_epoch_applied"' "$TRACE11"
grep -q '"event": "resident_resumed"' "$TRACE11"

# twelfth leg: fleet serving (ISSUE 16) — two replicas, each with a
# content-addressed result store under its state dir. A cold submit
# through the new `--endpoints` CLI plumbing builds and publishes on
# replica A; a repeat submit pointed at B FIRST is digest-routed back
# to the store holder and answered with ZERO build steps and ZERO
# compiles (sheepd_result_cache_{hits,misses}_total and
# sheepd_result_cache_bytes on A's /metrics record it); then replica
# B is SIGKILLed mid-build of a third job and the fleet client must
# fail over (reattach-idempotent resubmit) to A, completing the job —
# with every routing decision (cache_hit / headroom / failover) in
# the CLIENT-side trace as fleet_route events.
TRACE12A="$OUT/trace_fleet_a.jsonl"
TRACE12B="$OUT/trace_fleet_b.jsonl"
TRACE12C="$OUT/trace_fleet_client.jsonl"
SOCK12A="$OUT/sheepd_fleet_a.sock"
SOCK12B="$OUT/sheepd_fleet_b.sock"
STATE12A="$OUT/fleet_state_a"
STATE12B="$OUT/fleet_state_b"
rm -f "$TRACE12A" "$TRACE12B" "$TRACE12C" "$SOCK12A" "$SOCK12B"
rm -rf "$STATE12A" "$STATE12B"
JAX_PLATFORMS=cpu python -m sheep_tpu.server.daemon \
    --socket "$SOCK12A" --trace "$TRACE12A" --heartbeat-secs 0.2 \
    --state-dir "$STATE12A" --checkpoint-every 1 --metrics-port 0 \
    2> "$OUT/sheepd_fleet_a.err" &
SHEEPD12A_PID=$!
JAX_PLATFORMS=cpu python -m sheep_tpu.server.daemon \
    --socket "$SOCK12B" --trace "$TRACE12B" --heartbeat-secs 0.2 \
    --state-dir "$STATE12B" --checkpoint-every 1 --metrics-port 0 \
    2> "$OUT/sheepd_fleet_b.err" &
SHEEPD12B_PID=$!
trap 'kill $SHEEPD7_PID $SHEEPD7B_PID $SHEEPD10_PID $SHEEPD11_PID $SHEEPD12A_PID $SHEEPD12B_PID 2>/dev/null || true' EXIT
for _ in $(seq 1 100); do
    [ -S "$SOCK12A" ] && [ -S "$SOCK12B" ] && break; sleep 0.2
done
[ -S "$SOCK12A" ] || { echo "fleet sheepd A never bound" >&2; exit 1; }
[ -S "$SOCK12B" ] || { echo "fleet sheepd B never bound" >&2; exit 1; }
# cold fill through the --endpoints CLI (fleet of one): builds on A
JAX_PLATFORMS=cpu python -m sheep_tpu.server.client \
    --endpoints "$SOCK12A" --input rmat:10:8:1 --k 4 --tenant fleet \
    --chunk-edges 1024 --wait > "$OUT/fleet_cold.json"
if ! JAX_PLATFORMS=cpu python - "$SOCK12A" "$SOCK12B" \
        "$SHEEPD12B_PID" "$OUT/fleet_cold.json" "$TRACE12C" \
        > "$OUT/fleet.json" 2> "$OUT/fleet.err" <<'PYEOF'
import json
import os
import signal
import sys
import time

from sheep_tpu import obs
from sheep_tpu.obs.metrics import parse_prometheus
from sheep_tpu.server.client import FleetClient, SheepClient, fleet_digest

sock_a, sock_b, pid_b, cold_path, trace = sys.argv[1:6]
cold = json.load(open(cold_path))
assert cold["state"] == "done", cold
dg = fleet_digest("rmat:10:8:1", [4], tenant="fleet", chunk_edges=1024)
# the store publish is post-terminal on A's dispatch thread
with SheepClient(sock_a) as ca:
    deadline = time.monotonic() + 30
    while not ca.lookup(dg) and time.monotonic() < deadline:
        time.sleep(0.05)
    assert ca.lookup(dg), "cold result never published to A's store"
with obs.tracing(trace):
    with FleetClient([sock_b, sock_a]) as fleet:
        # digest hit on A short-circuits routing even though B is
        # listed first — answered from the store, zero work
        rep = fleet.submit("rmat:10:8:1", k=[4], tenant="fleet",
                           chunk_edges=1024)
        assert rep["endpoint"] == sock_a, rep
        desc = fleet.wait(rep, timeout_s=120)
        assert desc["state"] == "done", desc
        assert desc.get("steps", 0) == 0, \
            f"cache hit dispatched {desc.get('steps')} steps"
        assert desc.get("jit_compiles") == 0, desc
        assert desc["results"][0]["edge_cut"] \
            == cold["results"][0]["edge_cut"], (cold, desc)
        # third job (new digest): headroom routing ties break to B
        # (listed first); SIGKILL it mid-build and fail over to A
        third = fleet.submit("rmat:12:8:5", k=[4], tenant="fleet",
                             chunk_edges=512, dispatch_batch=1)
        assert third["endpoint"] == sock_b, third
        with SheepClient(sock_b) as cb:
            for _ in range(4000):
                st = cb.status(third["job_id"])
                if st.get("phase") == "build" and st.get("steps", 0) >= 3:
                    break
                time.sleep(0.005)
            else:
                raise SystemExit("third job never reached build")
        os.kill(int(pid_b), signal.SIGKILL)
        fin = fleet.wait(third, timeout_s=300)
        assert fin["state"] == "done", fin
        counts = dict(fleet.route_counts)
        assert counts[sock_a] >= 1 and counts[sock_b] >= 1, counts
with SheepClient(sock_a) as ca:
    m = parse_prometheus(ca.metrics())
    hits = sum(v for _, v in m.get("sheepd_result_cache_hits_total", []))
    misses = sum(v for _, v in
                 m.get("sheepd_result_cache_misses_total", []))
    rc_bytes = sum(v for _, v in m.get("sheepd_result_cache_bytes", []))
    assert hits >= 1, m.get("sheepd_result_cache_hits_total")
    assert misses >= 1, m.get("sheepd_result_cache_misses_total")
    assert rc_bytes > 0, m.get("sheepd_result_cache_bytes")
    ca.shutdown()
print(json.dumps({"cache_hits": hits, "cache_misses": misses,
                  "route_counts": counts}))
PYEOF
then
    echo "fleet smoke client failed:" >&2
    cat "$OUT/fleet.err" >&2
    exit 1
fi
wait "$SHEEPD12A_PID"
wait "$SHEEPD12B_PID" 2>/dev/null || true
python tools/trace_report.py "$TRACE12A" --check > "$OUT/report_fleet.txt"
grep -q '"event": "result_cache_store"' "$TRACE12A"  # the publish
grep -q '"event": "result_cache_hit"' "$TRACE12A"    # the served hit
grep -q '"why": "cache_hit"' "$TRACE12C"   # client-side route record
grep -q '"why": "headroom"' "$TRACE12C"
grep -q '"why": "failover"' "$TRACE12C"

# thirteenth leg: O(delta) end-to-end epochs (ISSUE 17) — a resident
# partition absorbs concurrent un-epoched updates under a tiny
# per-cycle byte budget (sheepd_update_throttled_total must tick and
# sheepd_update_score_seconds must join the HTTP /metrics catalog),
# then streams a >1 MiB epoch through the chunked update wire form
# (one txn, folded + scored as ONE epoch), then the daemon is
# SIGKILLed while a rebase compaction is in flight: the restart must
# come back at the same epoch — with SHEEP_SCORE_AUDIT cross-checking
# every incremental score — and a final scored epoch must bit-match
# the one-shot build of the reconstructed delta log.
TRACE13="$OUT/trace_odelta.jsonl"
SOCK13="$OUT/sheepd_odelta.sock"
STATE13="$OUT/sheepd_odelta_state"
rm -f "$TRACE13" "$SOCK13"
rm -rf "$STATE13"
JAX_PLATFORMS=cpu SHEEP_SCORE_AUDIT=1 SHEEP_UPDATE_BYTES_PER_CYCLE=16384 \
python -m sheep_tpu.server.daemon \
    --socket "$SOCK13" --trace "$TRACE13" --heartbeat-secs 0.2 \
    --state-dir "$STATE13" --checkpoint-every 1 --metrics-port 0 \
    2> "$OUT/sheepd_odelta.err" &
SHEEPD13_PID=$!
trap 'kill $SHEEPD7_PID $SHEEPD7B_PID $SHEEPD10_PID $SHEEPD11_PID $SHEEPD12A_PID $SHEEPD12B_PID $SHEEPD13_PID 2>/dev/null || true' EXIT
for _ in $(seq 1 100); do [ -S "$SOCK13" ] && break; sleep 0.2; done
[ -S "$SOCK13" ] || { echo "odelta sheepd never bound $SOCK13" >&2; exit 1; }
JAX_PLATFORMS=cpu python - "$SOCK13" "$OUT" "$OUT/sheepd_odelta.err" \
    > "$OUT/odelta_stream.json" <<'PYEOF'
import json
import os
import re
import sys
import threading
import urllib.request

import numpy as np

from sheep_tpu.server.client import SheepClient

sock, out, errf = sys.argv[1:4]
rng = np.random.default_rng(13)
n = 2048
E = rng.integers(0, n, (200000, 2)).astype(np.int64)
base = os.path.join(out, "odelta_base.bin64")
with open(base, "wb") as f:
    f.write(E[:40000].astype("<u8").tobytes())
np.save(os.path.join(out, "odelta_edges.npy"), E)


def metrics_text():
    ports = re.findall(r"metrics on http://[^:]+:(\d+)",
                       open(errf).read())
    url = f"http://127.0.0.1:{ports[-1]}/metrics"
    return urllib.request.urlopen(url, timeout=10).read().decode()


def throttled():
    m = re.search(
        r'sheepd_update_throttled_total\{tenant="odelta"\} (\d+)',
        metrics_text())
    return int(m.group(1)) if m else 0


applied = []
lock = threading.Lock()
with SheepClient(sock, timeout_s=600) as c:
    jid = c.submit(base, k=[4], tenant="odelta", resident=True,
                   chunk_edges=4096, num_vertices=n)["job_id"]
    assert c.wait(jid, timeout_s=600)["state"] == "done"

    def push(lo, hi):
        with SheepClient(sock, timeout_s=600) as cc:
            r = cc.update(jid, adds=E[lo:hi])
            assert r["applied"], r
            with lock:
                applied.append((int(r["epoch"]), lo, hi))

    # concurrent un-epoched updates against a 16 KiB/cycle budget:
    # each 2000-edge item stages 32 KB, so any drain cycle that sees
    # a backlog defers all but one item and ticks the throttle
    # counter; bounded retry rounds make the race with the drain
    # loop benign (a fast drain just means another round)
    nxt = 40000
    for _ in range(10):
        ths = [threading.Thread(
            target=push, args=(nxt + 2000 * i, nxt + 2000 * (i + 1)))
            for i in range(3)]
        for t in ths:
            t.start()
        for t in ths:
            t.join()
        nxt += 6000
        if throttled() >= 1:
            break
    assert throttled() >= 1, "update drain never throttled a backlog"

    # >1 MiB epoch through the chunked wire form: auto-chunking kicks
    # in past UPDATE_CHUNK_EDGES and the commit answers with the txn
    big_lo, big_hi = 100000, 184000
    big = E[big_lo:big_hi]
    assert big.nbytes > (1 << 20), big.nbytes
    r = c.update(jid, adds=big, score=True)
    assert r["applied"] and r.get("txn"), r
    applied.append((int(r["epoch"]), big_lo, big_hi))
    assert 'sheepd_update_score_seconds_count{tenant="odelta"}' \
        in metrics_text(), "scored-refresh histogram missing"
    eps = sorted(e for e, _, _ in applied)
    assert eps == list(range(1, len(applied) + 1)), applied
    json.dump({"job_id": jid, "epochs": sorted(applied)},
              open(os.path.join(out, "odelta_plan.json"), "w"))
    print(json.dumps({"job_id": jid, "last_epoch": eps[-1],
                      "scored_cut": r["results"][0]["edge_cut"],
                      "throttled": throttled()}))
PYEOF
JID13=$(python -c "import json,sys; \
print(json.load(open(sys.argv[1]))['job_id'])" "$OUT/odelta_plan.json")
# SIGKILL the daemon while a rebase compaction is in flight: whether
# the base rewrite committed or not, the restart must be consistent
(JAX_PLATFORMS=cpu python -m sheep_tpu.server.client \
    --server "$SOCK13" --compact "$JID13" --compact-mode rebase \
    > "$OUT/odelta_compact.json" 2>&1 || true) &
COMPACT13_PID=$!
sleep 0.6
kill -9 "$SHEEPD13_PID"
wait "$SHEEPD13_PID" 2>/dev/null || true
wait "$COMPACT13_PID" 2>/dev/null || true
JAX_PLATFORMS=cpu SHEEP_SCORE_AUDIT=1 SHEEP_UPDATE_BYTES_PER_CYCLE=16384 \
python -m sheep_tpu.server.daemon \
    --socket "$SOCK13" --trace "$TRACE13" --heartbeat-secs 0.2 \
    --state-dir "$STATE13" --checkpoint-every 1 --metrics-port 0 \
    2>> "$OUT/sheepd_odelta.err" &
SHEEPD13_PID=$!
trap 'kill $SHEEPD7_PID $SHEEPD7B_PID $SHEEPD10_PID $SHEEPD11_PID $SHEEPD12A_PID $SHEEPD12B_PID $SHEEPD13_PID 2>/dev/null || true' EXIT
JAX_PLATFORMS=cpu python - "$SOCK13" "$OUT" \
    > "$OUT/odelta_resume.json" <<'PYEOF'
import json
import os
import subprocess
import sys

import numpy as np

from sheep_tpu.io import deltalog as dl
from sheep_tpu.server.client import SheepClient

sock, out = sys.argv[1], sys.argv[2]
plan = json.load(open(os.path.join(out, "odelta_plan.json")))
jid = plan["job_id"]
E = np.load(os.path.join(out, "odelta_edges.npy"))
last = max(e for e, _, _ in plan["epochs"])
fin_lo, fin_hi = 184000, 186000
with SheepClient(sock, reconnect=40, reconnect_base_s=0.3,
                 timeout_s=600) as c:
    ep = c.epoch(jid)
    assert ep["epoch"] == last, (ep, last)  # the SIGKILL lost nothing
    r = c.update(jid, adds=E[fin_lo:fin_hi], epoch=last + 1,
                 score=True)
    assert r["applied"] and r["epoch"] == last + 1, r
    served_cut = r["results"][0]["edge_cut"]
    c.shutdown()
# the one-shot reference: replay the exact applied epoch order into a
# fresh delta log and build it cold — served must bit-match, straight
# through the backlog, the chunked epoch, the (maybe-torn) rebase
# compaction, and the restart
log = os.path.join(out, "odelta_ref.dlog")
with dl.DeltaLogWriter(
        log, base_spec=os.path.join(out, "odelta_base.bin64")) as w:
    for _, lo, hi in sorted(plan["epochs"]):
        w.append(E[lo:hi])
    w.append(E[fin_lo:fin_hi])
one = subprocess.run(
    [sys.executable, "-m", "sheep_tpu.cli", "--input",
     f"delta:{log}", "--k", "4", "--num-vertices", "2048",
     "--chunk-edges", "4096", "--json"],
    capture_output=True, text=True,
    env={**os.environ, "JAX_PLATFORMS": "cpu"})
assert one.returncode == 0, one.stderr[-800:]
oneshot = json.loads(one.stdout.strip().splitlines()[-1])
assert served_cut == oneshot["edge_cut"], (served_cut, oneshot)
print(json.dumps({"epoch": last + 1, "served_cut": served_cut,
                  "oneshot_cut": oneshot["edge_cut"]}))
PYEOF
wait "$SHEEPD13_PID"
python tools/trace_report.py "$TRACE13" --check \
    > "$OUT/report_odelta.txt"
grep -q '"event": "delta_epoch_applied"' "$TRACE13"

# fourteenth leg: fleet observability (ISSUE 18) — a fleet submit
# mints ONE trace id, the wire `trace` field carries it to whichever
# replica takes the job, and a mid-build SIGKILL + failover leaves the
# SAME id in the client trace and BOTH replicas' traces; `--stitch`
# renders the three files as one tree (the killed replica's job span
# UNCLOSED under the client request span, the survivor's closed beside
# it) with --check green; `sheep-fleet-metrics` federates the two
# saved scrapes with the merged p99 matching a hand-summed bucket
# merge exactly; and the SLO gate passes sane rules / exits 2 on a
# deliberately-tight one.
TRACE14A="$OUT/trace_fobs_a.jsonl"
TRACE14B="$OUT/trace_fobs_b.jsonl"
TRACE14C="$OUT/trace_fobs_client.jsonl"
SOCK14A="$OUT/sheepd_fobs_a.sock"
SOCK14B="$OUT/sheepd_fobs_b.sock"
STATE14A="$OUT/fobs_state_a"
STATE14B="$OUT/fobs_state_b"
rm -f "$TRACE14A" "$TRACE14B" "$TRACE14C" "$SOCK14A" "$SOCK14B"
rm -rf "$STATE14A" "$STATE14B"
JAX_PLATFORMS=cpu python -m sheep_tpu.server.daemon \
    --socket "$SOCK14A" --trace "$TRACE14A" --heartbeat-secs 0.2 \
    --state-dir "$STATE14A" --checkpoint-every 1 --metrics-port 0 \
    2> "$OUT/sheepd_fobs_a.err" &
SHEEPD14A_PID=$!
JAX_PLATFORMS=cpu python -m sheep_tpu.server.daemon \
    --socket "$SOCK14B" --trace "$TRACE14B" --heartbeat-secs 0.2 \
    --state-dir "$STATE14B" --checkpoint-every 1 --metrics-port 0 \
    2> "$OUT/sheepd_fobs_b.err" &
SHEEPD14B_PID=$!
trap 'kill $SHEEPD7_PID $SHEEPD7B_PID $SHEEPD10_PID $SHEEPD11_PID $SHEEPD12A_PID $SHEEPD12B_PID $SHEEPD13_PID $SHEEPD14A_PID $SHEEPD14B_PID 2>/dev/null || true' EXIT
for _ in $(seq 1 100); do
    [ -S "$SOCK14A" ] && [ -S "$SOCK14B" ] && break; sleep 0.2
done
[ -S "$SOCK14A" ] || { echo "fobs sheepd A never bound" >&2; exit 1; }
[ -S "$SOCK14B" ] || { echo "fobs sheepd B never bound" >&2; exit 1; }
# the fleet console sees both replicas (sheeptop --endpoints mode)
JAX_PLATFORMS=cpu python -m sheep_tpu.server.sheeptop \
    --endpoints "$SOCK14A,$SOCK14B" --once > "$OUT/fobs_sheeptop.txt"
grep -q "2/2 replicas up" "$OUT/fobs_sheeptop.txt"
if ! JAX_PLATFORMS=cpu python - "$SOCK14A" "$SOCK14B" \
        "$SHEEPD14B_PID" "$OUT" "$TRACE14C" \
        > "$OUT/fobs.json" 2> "$OUT/fobs.err" <<'PYEOF'
import json
import os
import signal
import sys
import time

from sheep_tpu import obs
from sheep_tpu.server.client import FleetClient, SheepClient

sock_a, sock_b, pid_b, out, trace = sys.argv[1:6]
# one small job per replica first, so BOTH scrapes carry request
# latency observations for the federation checks below
for ep in (sock_a, sock_b):
    with SheepClient(ep, timeout_s=600) as c:
        jid = c.submit("rmat:8:8:1", k=[4], tenant="fleetobs",
                       chunk_edges=1024)["job_id"]
        assert c.wait(jid, timeout_s=300)["state"] == "done"
with obs.tracing(trace):
    with FleetClient([sock_b, sock_a]) as fleet:
        rep = fleet.submit("rmat:12:8:5", k=[4], tenant="fleetobs",
                           chunk_edges=512, dispatch_batch=1)
        assert rep["endpoint"] == sock_b, rep
        with SheepClient(sock_b) as cb:
            # snapshot B's exposition BEFORE the kill: the saved
            # file stands in for the dead replica downstream
            with open(os.path.join(out, "fobs_scrape_b.txt"),
                      "w") as f:
                f.write(cb.metrics())
            for _ in range(4000):
                st = cb.status(rep["job_id"])
                if st.get("phase") == "build" \
                        and st.get("steps", 0) >= 3:
                    break
                time.sleep(0.005)
            else:
                raise SystemExit("fleet job never reached build on B")
        os.kill(int(pid_b), signal.SIGKILL)
        fin = fleet.wait(rep, timeout_s=300)
        assert fin["state"] == "done", fin
with SheepClient(sock_a) as ca:
    with open(os.path.join(out, "fobs_scrape_a.txt"), "w") as f:
        f.write(ca.metrics())
    ca.shutdown()
print(json.dumps({"cut": fin["results"][0]["edge_cut"]}))
PYEOF
then
    echo "fleet obs client failed:" >&2
    cat "$OUT/fobs.err" >&2
    exit 1
fi
wait "$SHEEPD14A_PID"
wait "$SHEEPD14B_PID" 2>/dev/null || true
# the SAME 32-hex trace id in the client trace and BOTH replicas'
# traces: B stamped it at submit, A on the failover resubmit
TID14=$(JAX_PLATFORMS=cpu python - "$TRACE14C" <<'PYEOF'
import json
import sys

for line in open(sys.argv[1]):
    rec = json.loads(line)
    if rec.get("span") == "fleet_request" and rec.get("trace"):
        print(rec["trace"])
        break
else:
    raise SystemExit("no traced fleet_request span in client trace")
PYEOF
)
echo "$TID14" | grep -Eq '^[0-9a-f]{32}$'
grep -q "\"trace\": \"$TID14\"" "$TRACE14A"
grep -q "\"trace\": \"$TID14\"" "$TRACE14B"
grep -q "\"trace\": \"$TID14\"" "$TRACE14C"
# the routing-scrape wall counter landed in the client trace
# (satellite: FleetClient scrape cache, ISSUE 18)
grep -q "fleet_scrape_ms" "$TRACE14C"
# one stitched tree across the three files, --check green, the
# killed job span flagged and both job spans remote-grafted
python tools/trace_report.py --stitch "$TRACE14C" "$TRACE14A" \
    "$TRACE14B" --check > "$OUT/report_fobs_stitch.txt"
grep -q "UNCLOSED (died mid-span" "$OUT/report_fobs_stitch.txt"
N14=$(grep -c -- "<-remote" "$OUT/report_fobs_stitch.txt" || true)
[ "$N14" -ge 2 ] || {
    echo "expected both job spans grafted remotely, got $N14" >&2
    exit 1
}
python tools/trace_report.py "$TRACE14A" --check > "$OUT/report_fobs_a.txt"
# federation: the CLI's merged p99 equals a hand-summed bucket merge
grep -q "sheepd_requests_total{" "$OUT/fobs_scrape_a.txt"
JAX_PLATFORMS=cpu python -m sheep_tpu.obs.federate \
    "$OUT/fobs_scrape_a.txt" "$OUT/fobs_scrape_b.txt" \
    --quantile sheepd_request_latency_seconds:0.99 \
    --json > "$OUT/fobs_fed.json"
JAX_PLATFORMS=cpu python - "$OUT" <<'PYEOF'
import json
import os
import sys

from sheep_tpu.obs.metrics import parse_prometheus, \
    quantile_from_cumulative

out = sys.argv[1]
fed = json.load(open(os.path.join(out, "fobs_fed.json")))
agg = {}
for rep in ("a", "b"):
    with open(os.path.join(out, f"fobs_scrape_{rep}.txt")) as f:
        m = parse_prometheus(f.read())
    for labels, v in m.get("sheepd_request_latency_seconds_bucket", []):
        agg[labels["le"]] = agg.get(labels["le"], 0) + v
rows = sorted(agg.items(),
              key=lambda kv: float(kv[0].replace("+Inf", "inf")))
uppers = [float(le) for le, _ in rows if le != "+Inf"]
cum = [int(c) for _, c in rows]
hand = quantile_from_cumulative(uppers, cum, 0.99)
got = fed["quantiles"]["sheepd_request_latency_seconds:0.99"]
assert got is not None and abs(got - hand) < 1e-12, (got, hand)
smp = fed["samples"]
assert any(lb.get("outcome") == "ok"
           for lb, _ in smp["sheepd_requests_total"]), \
    sorted(smp)
ups = {lb["replica"]: v for lb, v in smp["sheep_federated_up"]}
assert len(ups) == 2 and all(v == 1 for v in ups.values()), ups
print(json.dumps({"fleet_p99": got, "hand_p99": hand}))
PYEOF
# the SLO gate: sane rules hold (exit 0), a deliberately-tight p99
# bound burns (exit 2) — over the same two saved scrapes
cat > "$OUT/fobs_slo.json" <<'JSON'
{"tenants": {
    "fleetobs": {"p99_latency_s": 600.0, "max_update_throttled": 0},
    "*": {"p99_latency_s": 600.0, "max_error_rate": 0.25}}}
JSON
JAX_PLATFORMS=cpu python tools/slo_check.py --rules "$OUT/fobs_slo.json" \
    "$OUT/fobs_scrape_a.txt" "$OUT/fobs_scrape_b.txt" \
    > "$OUT/fobs_slo_ok.txt"
grep -q "4/4 bounds hold" "$OUT/fobs_slo_ok.txt"
cat > "$OUT/fobs_slo_tight.json" <<'JSON'
{"tenants": {"*": {"p99_latency_s": 0.000001}}}
JSON
rc=0
JAX_PLATFORMS=cpu python tools/slo_check.py \
    --rules "$OUT/fobs_slo_tight.json" \
    "$OUT/fobs_scrape_a.txt" "$OUT/fobs_scrape_b.txt" \
    > "$OUT/fobs_slo_burn.txt" || rc=$?
[ "$rc" -eq 2 ] || { echo "tight SLO rule did not burn (rc=$rc)" >&2; exit 1; }
grep -q "BURN" "$OUT/fobs_slo_burn.txt"

# fifteenth leg: multi-device O(delta) epochs served end-to-end
# (ISSUE 19) — a tiny RESIDENT partition submitted with
# update_backend=tpu-sharded (the daemon runs an 8-way virtual device
# mesh), absorbs one >UPDATE_CHUNK_EDGES epoch through the chunked
# begin/chunk/commit wire form — folded through the sharded lockstep
# pipeline and rescored with the distributed score cache (the scored
# reply's diagnostics must carry update_folds and score_distributed),
# with SHEEP_SCORE_AUDIT shadow-checking every incremental score —
# then the daemon is SIGKILLed and the restart must reattach at the
# applied epoch and absorb one more scored epoch.
TRACE15="$OUT/trace_shupd.jsonl"
SOCK15="$OUT/sheepd_shupd.sock"
STATE15="$OUT/sheepd_shupd_state"
rm -f "$TRACE15" "$SOCK15"
rm -rf "$STATE15"
JAX_PLATFORMS=cpu SHEEP_SCORE_AUDIT=1 \
XLA_FLAGS=--xla_force_host_platform_device_count=8 \
python -m sheep_tpu.server.daemon \
    --socket "$SOCK15" --trace "$TRACE15" --heartbeat-secs 0.2 \
    --state-dir "$STATE15" --checkpoint-every 1 --metrics-port 0 \
    2> "$OUT/sheepd_shupd.err" &
SHEEPD15_PID=$!
trap 'kill $SHEEPD7_PID $SHEEPD7B_PID $SHEEPD10_PID $SHEEPD11_PID $SHEEPD12A_PID $SHEEPD12B_PID $SHEEPD13_PID $SHEEPD14A_PID $SHEEPD14B_PID $SHEEPD15_PID 2>/dev/null || true' EXIT
for _ in $(seq 1 100); do [ -S "$SOCK15" ] && break; sleep 0.2; done
[ -S "$SOCK15" ] || { echo "shupd sheepd never bound $SOCK15" >&2; exit 1; }
JAX_PLATFORMS=cpu python - "$SOCK15" "$OUT" \
    > "$OUT/shupd_plan.json" <<'PYEOF'
import json
import os
import sys

import numpy as np

from sheep_tpu.server.client import SheepClient

sock, out = sys.argv[1], sys.argv[2]
rng = np.random.default_rng(15)
n = 2048
# SPARSE on purpose: a dense random graph's elimination forest is so
# stable that delta epochs move no labels and the rescore (correctly)
# has nothing to do — sparse epochs actually exercise it
E = rng.integers(0, n, (30000, 2)).astype(np.int64)
base = os.path.join(out, "shupd_base.bin64")
with open(base, "wb") as f:
    f.write(E[:6000].astype("<u8").tobytes())
np.save(os.path.join(out, "shupd_edges.npy"), E)
with SheepClient(sock, timeout_s=600) as c:
    jid = c.submit(base, k=[4], tenant="shupd", resident=True,
                   chunk_edges=2048, num_vertices=n,
                   update_backend="tpu-sharded")["job_id"]
    assert c.wait(jid, timeout_s=600)["state"] == "done"
    # a 4k-edge epoch at chunk_edges=1024 rides the chunked
    # begin/chunk/commit framing (one txn, applied as ONE epoch);
    # its scored refresh SEEDS the score cache (one full pass)
    r = c.update(jid, adds=E[6000:10000], score=True,
                 chunk_edges=1024)
    assert r["applied"] and r.get("txn"), r
    diag = r["results"][0]["diagnostics"]
    assert diag.get("update_folds", 0) >= 1, diag
    # the next epoch takes the O(delta) path: folded through the
    # sharded lockstep pipeline, rescored with ONE all-reduce
    r = c.update(jid, adds=E[10000:13000], score=True)
    assert r["applied"], r
    diag = r["results"][0]["diagnostics"]
    assert diag.get("score_distributed", 0) >= 1, diag
    print(json.dumps({"job_id": jid, "epoch": int(r["epoch"]),
                      "cut": r["results"][0]["edge_cut"]}))
PYEOF
EPOCH15=$(python -c "import json,sys; \
print(json.load(open(sys.argv[1]))['epoch'])" "$OUT/shupd_plan.json")
JID15=$(python -c "import json,sys; \
print(json.load(open(sys.argv[1]))['job_id'])" "$OUT/shupd_plan.json")
kill -9 "$SHEEPD15_PID"
wait "$SHEEPD15_PID" 2>/dev/null || true
JAX_PLATFORMS=cpu SHEEP_SCORE_AUDIT=1 \
XLA_FLAGS=--xla_force_host_platform_device_count=8 \
python -m sheep_tpu.server.daemon \
    --socket "$SOCK15" --trace "$TRACE15" --heartbeat-secs 0.2 \
    --state-dir "$STATE15" --checkpoint-every 1 --metrics-port 0 \
    2>> "$OUT/sheepd_shupd.err" &
SHEEPD15_PID=$!
trap 'kill $SHEEPD7_PID $SHEEPD7B_PID $SHEEPD10_PID $SHEEPD11_PID $SHEEPD12A_PID $SHEEPD12B_PID $SHEEPD13_PID $SHEEPD14A_PID $SHEEPD14B_PID $SHEEPD15_PID 2>/dev/null || true' EXIT
JAX_PLATFORMS=cpu python - "$SOCK15" "$OUT" "$JID15" "$EPOCH15" \
    > "$OUT/shupd_resume.json" <<'PYEOF'
import json
import os
import sys

import numpy as np

from sheep_tpu.server.client import SheepClient

sock, out, jid, last = sys.argv[1], sys.argv[2], sys.argv[3], \
    int(sys.argv[4])
E = np.load(os.path.join(out, "shupd_edges.npy"))
with SheepClient(sock, reconnect=40, reconnect_base_s=0.3,
                 timeout_s=600) as c:
    ep = c.epoch(jid)
    assert ep["epoch"] == last, (ep, last)  # the SIGKILL lost nothing
    # first scored epoch after the restart seeds the score cache with
    # one full pass (the snapshot carries tables, not the cache); the
    # second takes the O(delta) path — distributed, and audited
    r = c.update(jid, adds=E[13000:15000], epoch=last + 1, score=True)
    assert r["applied"] and r["epoch"] == last + 1, r
    r = c.update(jid, adds=E[15000:17000], epoch=last + 2, score=True)
    assert r["applied"] and r["epoch"] == last + 2, r
    diag = r["results"][0]["diagnostics"]
    assert diag.get("update_folds", 0) >= 2, diag
    assert diag.get("score_distributed", 0) >= 1, diag
    c.shutdown()
print(json.dumps({"epoch": last + 2,
                  "cut": r["results"][0]["edge_cut"]}))
PYEOF
wait "$SHEEPD15_PID"
python tools/trace_report.py "$TRACE15" --check \
    > "$OUT/report_shupd.txt"
grep -q '"event": "delta_epoch_applied"' "$TRACE15"

# sixteenth leg: out-of-core (ISSUE 20) — a CLI build under a
# SHEEP_CACHE_BYTES budget clamped well under the modeled working set
# (RMAT-10 x 8 at chunk 256 = 32 chunks x 2 KiB = 64 KiB resident;
# budget 20000 bytes), so the residency manager MUST evict and reload
# through the disk tier. The run is killed 4 chunks into the build
# (mid-spill: evictions have already happened), resumed from its
# checkpoint under the SAME budget into the SAME trace, and the
# resumed partition must bit-equal an UNCONSTRAINED oracle — eviction
# moves bytes, never bits. Gates: trace --check green, the resume
# seam + spill counters on the record, cmp on the partition maps.
TRACE16="$OUT/trace_oocore.jsonl"
CKPT16="$OUT/ckpt_oocore"
rm -rf "$TRACE16" "$CKPT16" "$OUT/oocore_oracle.part" "$OUT/oocore.part"
JAX_PLATFORMS=cpu python -m sheep_tpu.cli \
    --input rmat:10:8:3 --k 4 --backend tpu \
    --chunk-edges 256 --no-comm-volume \
    --output "$OUT/oocore_oracle.part" --json \
    > "$OUT/result_oocore_oracle.json"
if JAX_PLATFORMS=cpu SHEEP_CACHE_BYTES=20000 SHEEP_FAULT_INJECT=build:4 \
    python -m sheep_tpu.cli \
    --input rmat:10:8:3 --k 4 --backend tpu \
    --chunk-edges 256 --no-comm-volume \
    --checkpoint-dir "$CKPT16" --checkpoint-every 1 \
    --trace "$TRACE16" --heartbeat-secs 0.2 --json \
    > /dev/null 2> "$OUT/oocore.err"; then
    echo "budget-clamped fault run unexpectedly succeeded" >&2
    exit 1
fi
JAX_PLATFORMS=cpu SHEEP_CACHE_BYTES=20000 python -m sheep_tpu.cli \
    --input rmat:10:8:3 --k 4 --backend tpu \
    --chunk-edges 256 --no-comm-volume \
    --checkpoint-dir "$CKPT16" --resume \
    --output "$OUT/oocore.part" \
    --trace "$TRACE16" --heartbeat-secs 0.2 --json \
    > "$OUT/result_oocore.json"
python tools/trace_report.py "$TRACE16" --check > "$OUT/report_oocore.txt"
grep -q '"event": "resume"' "$TRACE16"
cmp "$OUT/oocore_oracle.part" "$OUT/oocore.part"
JAX_PLATFORMS=cpu python - "$TRACE16" <<'PYEOF'
import json
import sys

ctr = {}
with open(sys.argv[1]) as f:
    for line in f:
        e = json.loads(line)
        if e.get("event") == "counters":
            ctr = e  # counter totals ride inline on the event
# the build ran out-of-core: it evicted, re-uploaded, and never held
# more than the budget resident
assert ctr.get("spill_evictions", 0) > 0, ctr
assert ctr.get("spill_reload_bytes", 0) > 0, ctr
assert 0 < ctr.get("spill_resident_bytes", 0) <= 20000, ctr
PYEOF

# and the static gate stays at zero with the new telemetry modules in
python tools/sheeplint.py --check sheep_tpu tools > "$OUT/sheeplint.txt"

echo "obs smoke OK: $TRACE $TRACE2 $TRACE3 $TRACE4 $TRACE5 $TRACE6 $TRACE7 $TRACE8 $TRACE9 $TRACE10 $TRACE11 $TRACE12A $TRACE13 $TRACE14A $TRACE15 $TRACE16"
