#!/usr/bin/env bash
# RETIRED (round 5): superseded by tools/tpu_watch3.sh, which adds the
# per-window linkstate leg (tools/tpu_probe_quick.py), the Mosaic
# lowering smoke (tools/pallas_smoke.py), and a single-watcher pidfile
# guard. Two watchers fighting over tools/out/CAPTURING and the single
# host core would contaminate the CPU-baseline denominator — so this
# script now refuses to run. Its round-3b leg history is preserved in
# git (and inherited verbatim by watch3's bench/microbench/tune legs).
echo "tpu_watch2.sh is retired; use tools/tpu_watch3.sh" >&2
exit 2
