#!/usr/bin/env bash
# Bench-FIRST tunnel watcher (round 3b). Differences from tpu_watch.sh,
# learned the hard way:
#   - the headline bench.py runs FIRST in the healthy window (the sweep
#     twice outlived the window and cost the round its headline);
#   - cheap 60s probes between attempts instead of letting bench.py's
#     30-min attempt timeout block blind (a wedged tunnel hangs clients
#     at jax init, burning the ladder with zero signal);
#   - tools/out/CAPTURING flag while working so concurrent dev work can
#     yield the (single) host core — the CPU baseline leg is
#     contention-sensitive (r2's numbers were polluted that way);
#   - JAX_COMPILATION_CACHE_DIR defaults into the repo (.jax_cache) so
#     machine resets don't re-pay the ~7 min cold warm-up.
set -u
cd "$(dirname "$0")/.."
export JAX_COMPILATION_CACHE_DIR="${JAX_COMPILATION_CACHE_DIR:-$PWD/.jax_cache}"
interval=${SHEEP_WATCH_INTERVAL:-180}
deadline=$(( $(date +%s) + ${SHEEP_WATCH_HOURS:-10} * 3600 ))
flag=tools/out/CAPTURING

probe() {
  timeout 75 python -c "
import jax, jax.numpy as jnp, numpy as np
assert int(np.asarray(jnp.sum(jnp.arange(8)))) == 28
print('ok')" 2>/dev/null | grep -q ok
}

cleanup() { rm -f "$flag"; }
trap cleanup EXIT

have_bench=""
have_micro=""
have_tune=""
while [ "$(date +%s)" -lt "$deadline" ]; do
  if probe; then
    ts=$(date -u +%Y%m%dT%H%M%S)
    out="tools/out/$ts"
    mkdir -p "$out"
    touch "$flag"
    echo "tunnel healthy at $ts; capturing (bench first)" | tee "$out/watch.log"
    if [ -z "$have_bench" ]; then
      timeout 2400 python bench.py >"$out/bench.json" 2>"$out/bench.stderr"
      cat "$out/bench.json" | tee -a "$out/watch.log"
      if grep -q '"vs_baseline"' "$out/bench.json" && \
         ! grep -q '"value": 0.0' "$out/bench.json" && \
         ! grep -q '"platform": "cpu"' "$out/bench.json"; then
        have_bench=yes
        echo "HEADLINE LANDED in $out" | tee -a "$out/watch.log"
      else
        echo "bench incomplete; resuming poll" | tee -a "$out/watch.log"
        rm -f "$flag"
        sleep "$interval"
        continue
      fi
    fi
    # headline on file: extras in priority order. Each leg counts as
    # done only on rc=0 (a timeout-killed sweep is a PARTIAL artifact:
    # keep the jsonl as data but retry the leg next healthy window);
    # completed legs never re-run.
    if [ -z "$have_micro" ]; then
      timeout 1500 python tools/microbench_fixpoint.py --scale 22 \
        --chunk-log 23 --profile-dir "$out/xprof" \
        >"$out/microbench.jsonl" 2>>"$out/watch.log"
      rc=$?
      echo "microbench rc=$rc" | tee -a "$out/watch.log"
      [ "$rc" = 0 ] && [ -s "$out/microbench.jsonl" ] && have_micro=yes
    fi
    if [ -z "$have_tune" ]; then
      timeout 3600 python tools/tune_fixpoint.py --scale 22 --ef 16 \
        --chunk-logs 23 --warm w1,w8 --segment-rounds 2 \
        --lift-levels 0 --tail-divisors 2 --stale 1,0 --stale-reuse 1,4 \
        --carry 0,1 --overlap 0,1 \
        >"$out/tune22_post.jsonl" 2>>"$out/watch.log"
      rc=$?
      echo "tune rc=$rc" | tee -a "$out/watch.log"
      [ "$rc" = 0 ] && [ -s "$out/tune22_post.jsonl" ] && have_tune=yes
    fi
    if [ -n "$have_micro" ] && [ -n "$have_tune" ]; then
      echo "full capture complete (bench+microbench+tune)" \
        | tee -a "$out/watch.log"
      rm -f "$flag"
      exit 0
    fi
    rm -f "$flag"
  fi
  sleep "$interval"
done
echo "deadline reached: bench=${have_bench:-no} micro=${have_micro:-no}" \
     "tune=${have_tune:-no}"
# exit 0 if the one critical artifact (the headline bench) landed
[ -n "$have_bench" ] && exit 0
exit 1
