#!/usr/bin/env python
"""Measure tpu-bigv's per-round collective cost on the virtual mesh
(VERDICT r2 item 5): rounds x (all_gather + all_to_all) counts and bytes
per run, on hub-heavy graphs (star = the routed worst case: every
request climbs to one owner; RMAT = the power-law production shape),
with the in-shard request dedup compaction A/B'd.

Usage:
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python tools/bigv_collectives.py [--scale 16] [--ef 8]

One JSON line per configuration; cross-config assert that the forest is
identical with and without dedup (the dedup is exact).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ["JAX_PLATFORMS"] = os.environ.get("JAX_PLATFORMS", "cpu")
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                           " --xla_force_host_platform_device_count=8").strip()

# the env var alone is NOT enough: the axon TPU plugin pre-imports jax at
# interpreter startup, so the platform must be pinned through the shared
# helper (same mechanism the CLI uses) before any jax import
from sheep_tpu.utils.platform import pin_platform  # noqa: E402

pin_platform(os.environ["JAX_PLATFORMS"])


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--scale", type=int, default=16)
    ap.add_argument("--ef", type=int, default=8)
    ap.add_argument("--k", type=int, default=8)
    ap.add_argument("--devices", type=int, default=8)
    args = ap.parse_args()

    import numpy as np

    from sheep_tpu.io import generators
    from sheep_tpu.io.edgestream import EdgeStream
    from sheep_tpu.parallel.bigv import BigVPipeline
    from sheep_tpu.parallel.mesh import shards_mesh

    n = 1 << args.scale
    graphs = {
        f"rmat{args.scale}": (generators.rmat(args.scale, args.ef, seed=9), n),
        f"star{args.scale}": (generators.star_graph(n), n),
    }
    mesh = shards_mesh(args.devices)
    out = {}
    for gname, (e, nv) in graphs.items():
        per_dedup = {}
        for dedup in (True, False):
            es = EdgeStream.from_array(e, n_vertices=nv)
            pipe = BigVPipeline(nv, max(1024, len(e) // args.devices), mesh,
                                dedup_compact=dedup)
            t0 = time.perf_counter()
            r = pipe.run(es, args.k, comm_volume=False)
            wall = time.perf_counter() - t0
            st = r["build_stats"]
            rec = {
                "graph": gname, "dedup_compact": dedup,
                "rounds": r["fixpoint_rounds"],
                "collective_ops": st.get("collective_ops", 0),
                "collective_MB": round(
                    st.get("collective_bytes", 0) / 1e6, 2),
                "q_rounds": st.get("q_rounds", 0),
                "compactions": st.get("compactions", 0),
                "edge_cut": r["edge_cut"], "wall_s": round(wall, 2),
            }
            per_dedup[dedup] = (r["parent"], rec)
            print(json.dumps(rec), flush=True)
        # the dedup must be exact: identical forest either way
        a, b = per_dedup[True][0], per_dedup[False][0]
        assert np.array_equal(a, b), f"{gname}: dedup changed the forest!"
        ra, rb = per_dedup[True][1], per_dedup[False][1]
        out[gname] = {
            "bytes_ratio": round(
                ra["collective_MB"] / max(rb["collective_MB"], 1e-9), 3),
            "rounds_ratio": round(
                ra["rounds"] / max(rb["rounds"], 1e-9), 3),
        }
    print(json.dumps({"summary": out}), flush=True)


if __name__ == "__main__":
    main()
