#!/usr/bin/env python
"""Chaos soak harness (ISSUE 9 acceptance gate).

Replays N randomized fault schedules through the real CLI and asserts
every run either converges BIT-IDENTICAL to a clean oracle or ends in a
documented degraded state — zero unhandled crashes. Each schedule arms

    SHEEP_FAULT_INJECT=chaos:<seed>[:<budget>[:<rate>]]

(utils/fault.py) over a small materialized .bin64 graph, so every fault
class has live injection points: OOM + device-loss at the dispatch/build
points (absorbed in-process by the retry/degrade layer), read errors at
the physical reads (absorbed by the edgestream retry), stalls (aging the
watchdog clocks), and kills (process death — the harness resumes the run
from its checkpoint with --resume, PR-8 style, under a fresh derived
seed so the same kill cannot recur forever).

Per-schedule verdicts:

    identical            output partition map byte-equal to the oracle
    degraded_documented  differs, but the trace carries the documented
                         degradation events (chunk_quarantined /
                         checkpoint_degraded)
    wrong_forest         differs with NO documented degradation  [FAIL]
    unhandled_crash      nonzero exit not caused by an injected KILL
                         (InjectedFault — fatal by design) or the
                         watchdog's stall exit; an escaped oom/device/
                         read injection lands here, because those are
                         supposed to be absorbed in-process    [FAIL]
    resume_exhausted     still dying after --max-resumes resumes  [FAIL]

Usage::

    python tools/chaos_soak.py                  # 20 schedules, tpu/cpu-jax
    python tools/chaos_soak.py --schedules 3 --json
    python tools/chaos_soak.py --backend tpu-sharded --schedules 5

Writes a summary JSON next to the per-schedule artifacts (kept with
--keep, else under a temp dir); exits nonzero on any FAIL verdict.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

FAIL_VERDICTS = ("wrong_forest", "unhandled_crash", "resume_exhausted")


def _run_cli(cmd, env, timeout):
    proc = subprocess.run(cmd, env=env, capture_output=True, text=True,
                          timeout=timeout)
    return proc.returncode, proc.stdout, proc.stderr


def _events(trace_path):
    """Counts of the interesting trace events across ALL runs appended
    to the schedule's trace file."""
    counts: dict = {}
    try:
        with open(trace_path) as f:
            for line in f:
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                ev = rec.get("event")
                if ev in ("chaos_inject", "fault_inject"):
                    k = f"inject_{rec.get('kind', '?')}"
                    counts[k] = counts.get(k, 0) + 1
                elif ev in ("retry", "dispatch_degraded",
                            "device_reinit", "chunk_quarantined",
                            "checkpoint_degraded", "straggler_timeout",
                            "resume"):
                    counts[ev] = counts.get(ev, 0) + 1
    except OSError:
        pass
    return counts


def run_schedule(i, seed, args, base_cmd, oracle_bytes, out_dir, env0,
                 stall_exit):
    sdir = os.path.join(out_dir, f"sched_{i:03d}")
    os.makedirs(sdir, exist_ok=True)
    trace = os.path.join(sdir, "trace.jsonl")
    parts = os.path.join(sdir, "parts.pbin")
    ckpt = os.path.join(sdir, "ckpt")
    cmd = base_cmd + ["--checkpoint-dir", ckpt,
                      "--checkpoint-every", str(args.checkpoint_every),
                      "--trace", trace, "--output", parts]
    if i % 2:
        # alternate schedules through the pipelined dispatch path so
        # dispatch-time OOM/degrade sees real in-flight chains
        cmd = cmd + ["--dispatch-batch", "2", "--inflight", "2"]
    rec = {"schedule": i, "seed": seed, "attempts": 0, "rcs": []}
    attempts = 0
    while True:
        env = dict(env0)
        # a fresh derived seed per resume: the re-run must not
        # deterministically re-kill at the same point forever
        env["SHEEP_FAULT_INJECT"] = (
            f"chaos:{seed * 1000 + attempts}:{args.budget}:{args.rate}")
        try:
            rc, _out, err = _run_cli(
                cmd + (["--resume"] if attempts else []), env,
                args.timeout)
        except subprocess.TimeoutExpired:
            # a wedged run is a verdict, not a harness crash — exactly
            # the hang class this gate exists to surface
            rec["verdict"] = "unhandled_crash"
            rec["stderr_tail"] = (f"run hung past --timeout "
                                  f"{args.timeout}s and was killed")
            return rec
        rec["rcs"].append(rc)
        if rc == 0:
            break
        attempts += 1
        rec["attempts"] = attempts
        # only a KILL-kind injection (fatal by design) or the
        # watchdog's stall exit is an EXPECTED death. An escaped
        # InjectedResourceExhausted/InjectedDeviceLoss/InjectedReadError
        # means the in-process handling regressed — exactly the bug
        # class this gate exists to catch, so it must flag, not resume.
        if "InjectedFault" not in err and rc != stall_exit:
            rec["verdict"] = "unhandled_crash"
            rec["stderr_tail"] = err[-800:]
            return rec
        if attempts > args.max_resumes:
            rec["verdict"] = "resume_exhausted"
            return rec
    rec["attempts"] = attempts
    rec["events"] = _events(trace)
    try:
        with open(parts, "rb") as f:
            got = f.read()
    except OSError:
        rec["verdict"] = "unhandled_crash"
        rec["stderr_tail"] = "run exited 0 but wrote no partition map"
        return rec
    if got == oracle_bytes:
        rec["verdict"] = "identical"
    elif rec["events"].get("chunk_quarantined") or \
            rec["events"].get("checkpoint_degraded"):
        rec["verdict"] = "degraded_documented"
    else:
        rec["verdict"] = "wrong_forest"
    return rec


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Replay randomized fault schedules through the CLI "
                    "and assert oracle-identical or documented-degraded "
                    "convergence.")
    ap.add_argument("--schedules", type=int, default=20)
    ap.add_argument("--seed0", type=int, default=1)
    ap.add_argument("--scale", type=int, default=9,
                    help="2^SCALE vertices for the soak graph")
    ap.add_argument("--ef", type=int, default=8, help="edges per vertex")
    ap.add_argument("--k", type=int, default=4)
    ap.add_argument("--chunk-edges", type=int, default=512)
    ap.add_argument("--checkpoint-every", type=int, default=2)
    ap.add_argument("--backend", default="tpu")
    ap.add_argument("--budget", type=int, default=2,
                    help="max injected faults per schedule attempt")
    ap.add_argument("--rate", type=float, default=0.15,
                    help="per-point injection probability")
    ap.add_argument("--max-resumes", type=int, default=8)
    ap.add_argument("--timeout", type=float, default=300.0,
                    help="seconds per CLI invocation")
    ap.add_argument("--out", default=None,
                    help="artifact dir (default: fresh temp dir)")
    ap.add_argument("--keep", action="store_true",
                    help="keep per-schedule artifacts on success")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)

    from sheep_tpu.io import formats, generators
    from sheep_tpu.utils import watchdog

    out_dir = args.out or tempfile.mkdtemp(prefix="sheep_chaos_")
    os.makedirs(out_dir, exist_ok=True)
    n = 1 << args.scale
    graph = os.path.join(out_dir, f"soak_s{args.scale}.bin64")
    e = generators.random_graph(n, args.ef << args.scale,
                                seed=args.seed0)
    formats.write_edges(graph, e)

    env0 = dict(os.environ)
    env0["JAX_PLATFORMS"] = env0.get("JAX_PLATFORMS", "cpu")
    env0.pop("SHEEP_FAULT_INJECT", None)
    # faster retry backoff: the soak injects dozens of faults and the
    # production default backoff would be pure dead time here
    env0.setdefault("SHEEP_RETRY_BASE_S", "0.01")

    base_cmd = [sys.executable, "-m", "sheep_tpu.cli",
                "--input", graph, "--num-vertices", str(n),
                "--k", str(args.k), "--backend", args.backend,
                "--chunk-edges", str(args.chunk_edges),
                "--no-comm-volume", "--json"]

    # clean oracle: same command, no faults, no checkpointing
    oracle_parts = os.path.join(out_dir, "oracle.pbin")
    rc, out, err = _run_cli(base_cmd + ["--output", oracle_parts],
                            env0, args.timeout)
    if rc != 0:
        print(f"oracle run failed (rc={rc}):\n{err[-800:]}",
              file=sys.stderr)
        return 1
    with open(oracle_parts, "rb") as f:
        oracle_bytes = f.read()

    results = []
    for i in range(args.schedules):
        rec = run_schedule(i, args.seed0 + i, args, base_cmd,
                           oracle_bytes, out_dir, env0,
                           watchdog.EXIT_CODE)
        results.append(rec)
        ev = rec.get("events", {})
        injected = sum(v for k, v in ev.items()
                       if k.startswith("inject_"))
        print(f"schedule {i:3d} seed {rec['seed']:4d}: "
              f"{rec['verdict']:<20} attempts={rec['attempts']} "
              f"injected={injected} events={ev}", flush=True)

    summary = {
        "schedules": args.schedules,
        "backend": args.backend,
        "verdicts": {},
        "total_injected": 0,
        "results": results,
    }
    for rec in results:
        v = rec["verdict"]
        summary["verdicts"][v] = summary["verdicts"].get(v, 0) + 1
        summary["total_injected"] += sum(
            c for k, c in rec.get("events", {}).items()
            if k.startswith("inject_"))
    failed = sum(summary["verdicts"].get(v, 0) for v in FAIL_VERDICTS)
    summary["failed"] = failed
    with open(os.path.join(out_dir, "chaos_soak.json"), "w") as f:
        json.dump(summary, f, indent=1)
    if args.json:
        print(json.dumps(summary))
    else:
        print(f"chaos soak: {summary['verdicts']} "
              f"({summary['total_injected']} faults injected) "
              f"-> {'FAIL' if failed else 'PASS'} "
              f"(artifacts: {out_dir})")
    if not args.keep and not failed and args.out is None:
        shutil.rmtree(out_dir, ignore_errors=True)
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
