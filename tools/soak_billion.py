"""Billion-edge-class soak driver (BASELINE.json eval config 3 size class).

The largest run previously executed end-to-end was RMAT-22x16 = 67M edges
(LiveJournal class). This driver proves the twitter-2010 class actually
streams: it generates RMAT-26 with edge factor 22 — 1.476B edges, matching
twitter-2010's 1.47B — to a .bin32 file (the generator-streamed ingest
pass), then partitions it at k=64 through the REAL CLI in a subprocess,
SIGKILLs that process mid-build, and resumes from the chunk checkpoint
with ``--resume``. Memory stays O(V + chunk) throughout; the file is
12 GB and is .gitignored (tools/out/soak/).

Usage:
    python tools/soak_billion.py              # full orchestrated soak
    python tools/soak_billion.py --scale 24   # smaller rehearsal

Results land in tools/out/soak/soak_s{scale}.json:
  gen_seconds, first_run (killed_at_phase/chunk), resume JSON summary,
  end-to-end edges/sec for the resumed run.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def generate(path: str, scale: int, ef: int, seed: int = 42,
             chunk: int = 1 << 22, gen: str = "hash") -> float:
    """Stream RMAT chunks to a .bin32 file; returns wall seconds.

    ``gen="hash"`` (default) uses the counter-based generator, whose
    native C loop runs ~3 M edges/s/core — the PCG path (``gen="pcg"``,
    the r3 soak_s26 artifact's generator) measured ~0.4 M edges/s and
    made generation, not partitioning, the soak bottleneck."""
    from sheep_tpu.io import generators

    def blocks():
        if gen == "hash":
            yield from generators.RmatHashStream(
                scale, ef, seed=seed).chunks(chunk)
        else:
            yield from generators.rmat_stream(scale, ef, seed=seed,
                                              chunk=chunk)

    t0 = time.perf_counter()
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        done = 0
        for block in blocks():
            np.ascontiguousarray(block, dtype="<u4").tofile(f)
            done += len(block)
            if done % (chunk << 5) == 0:
                print(f"  gen {done / 1e9:.2f}B edges "
                      f"({time.perf_counter() - t0:.0f}s)", flush=True)
    os.replace(tmp, path)
    return time.perf_counter() - t0


def cli_cmd(path: str, k: int, ckpt_dir: str, chunk_edges: int,
            n_vertices: int, resume: bool) -> list:
    cmd = [sys.executable, "-m", "sheep_tpu.cli", "--input", path,
           "--k", str(k), "--backend", "cpu", "--json", "--no-comm-volume",
           "--num-vertices", str(n_vertices),
           "--chunk-edges", str(chunk_edges),
           "--checkpoint-dir", ckpt_dir, "--checkpoint-every", "8"]
    if resume:
        cmd.append("--resume")
    return cmd


def read_manifest(ckpt_dir: str):
    try:
        with open(os.path.join(ckpt_dir, "sheep_ckpt_p0.json")) as f:
            return json.load(f)
    except (FileNotFoundError, json.JSONDecodeError):
        return None


def orchestrate(args) -> dict:
    out_dir = os.path.join(REPO, "tools", "out", "soak")
    os.makedirs(out_dir, exist_ok=True)
    # encode the generator in the artifact name: hash and pcg produce
    # different streams of the same size, so a cached file from one must
    # not satisfy a soak requested with the other
    data = os.path.join(
        out_dir, f"rmat{args.scale}_ef{args.ef}_{args.gen}.bin32")
    ckpt_dir = os.path.join(out_dir, f"ckpt_s{args.scale}")
    n = 1 << args.scale
    m = args.ef << args.scale
    result = {"scale": args.scale, "ef": args.ef, "k": args.k,
              "n_vertices": n, "n_edges": m, "gen": args.gen,
              "chunk_edges": args.chunk_edges}

    if os.path.exists(data) and os.path.getsize(data) == 8 * m:
        print(f"reusing {data}")
        result["gen_seconds"] = None
    else:
        print(f"generating {m / 1e9:.2f}B edges -> {data} ({args.gen})")
        result["gen_seconds"] = round(
            generate(data, args.scale, args.ef, gen=args.gen), 1)
        print(f"  done in {result['gen_seconds']}s")

    # fresh run; SIGKILL once the build phase has checkpointed past the
    # kill threshold (a real process death, not an in-process exception)
    for f in os.listdir(ckpt_dir) if os.path.isdir(ckpt_dir) else []:
        os.remove(os.path.join(ckpt_dir, f))
    cmd = cli_cmd(data, args.k, ckpt_dir, args.chunk_edges, n, resume=False)
    print("first run:", " ".join(cmd), flush=True)
    t0 = time.perf_counter()
    proc = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True, cwd=REPO)
    kill_after = args.kill_at_chunk
    killed = None
    while proc.poll() is None:
        time.sleep(0.25)
        man = read_manifest(ckpt_dir)
        if man and (man["phase"] != "degrees") and \
                (man["phase"] != "build" or man["chunk_idx"] >= kill_after):
            os.kill(proc.pid, signal.SIGKILL)
            proc.wait()
            killed = {"phase": man["phase"], "chunk_idx": man["chunk_idx"],
                      "at_seconds": round(time.perf_counter() - t0, 1)}
            break
        if time.perf_counter() - t0 > args.timeout:
            proc.kill()
            raise RuntimeError("first run exceeded timeout before kill point")
    if killed is None:
        raise RuntimeError(
            f"worker exited (rc={proc.returncode}) before the kill point:\n"
            + (proc.stdout.read() if proc.stdout else ""))
    result["first_run_killed"] = killed
    print(f"  SIGKILLed at {killed}", flush=True)

    cmd = cli_cmd(data, args.k, ckpt_dir, args.chunk_edges, n, resume=True)
    print("resume run:", " ".join(cmd), flush=True)
    t0 = time.perf_counter()
    # the resume leg runs the REST of the pipeline (most of the build +
    # split + the whole scoring pass) — sharing the first leg's
    # to-the-kill-point timeout killed a 3.76B-edge soak at build chunk
    # 440/448 (r3b); give it its own, much larger budget
    out = subprocess.run(cmd, capture_output=True, text=True,
                         timeout=args.resume_timeout, cwd=REPO)
    if out.returncode != 0:
        raise RuntimeError(f"resume failed rc={out.returncode}:\n"
                           f"{out.stdout}\n{out.stderr}")
    summary = json.loads(out.stdout.strip().splitlines()[-1])
    result["resume_wall_seconds"] = round(time.perf_counter() - t0, 1)
    result["resume_summary"] = summary
    result["resumed_edges_per_sec"] = summary["edges_per_sec"]
    return result


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--scale", type=int, default=26)
    ap.add_argument("--ef", type=int, default=22,
                    help="22 @ scale 26 = 1.476B edges = twitter-2010's count")
    ap.add_argument("--k", type=int, default=64)
    ap.add_argument("--chunk-edges", type=int, default=1 << 23)
    ap.add_argument("--kill-at-chunk", type=int, default=64,
                    help="SIGKILL once a build checkpoint >= this chunk exists")
    ap.add_argument("--timeout", type=float, default=7200,
                    help="first leg: generate + run to the kill point")
    ap.add_argument("--resume-timeout", type=float, default=86400,
                    help="resume leg: the rest of the whole pipeline")
    ap.add_argument("--gen", choices=["hash", "pcg"], default="hash",
                    help="edge generator: counter-hash (native C loop, "
                         "fast) or the PCG replay generator")
    args = ap.parse_args()

    res = orchestrate(args)
    out = os.path.join(REPO, "tools", "out", "soak",
                       f"soak_s{args.scale}.json")
    with open(out, "w") as f:
        json.dump(res, f, indent=1)
    print(json.dumps(res))
    print(f"written to {out}")


if __name__ == "__main__":
    main()
