"""Balance/cut frontier sweep (VERDICT r4 item 5): BETA in {1.1, 1.25,
1.5, 2.0} (as alpha = BETA - 1) plus the alpha=1.0 default, across the
eval graph families, cpu + tpu backends — plus the tpu-bigv row at the
config-5 part count k=1024 (ROADMAP item 5: the committed bigv
artifacts shipped balance ~1.97 from the alpha=1.0 default, the 2x
envelope at its worst). Cut/balance are deterministic per config; walls
are not recorded (sweeps run contended). Decides the default-alpha
question with data -> tools/out/soak/balance_frontier.json and the
BASELINE.md table."""
import json, os, sys
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
if "xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    # the bigv leg wants a multi-device (virtual) mesh; must precede jax init
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8").strip()
from sheep_tpu.utils.platform import pin_platform
pin_platform("cpu")
import sheep_tpu

GRAPHS = [
    ("karate", "GOLDEN", 2),            # eval config 1
    ("rmat-hash:14:8:5", None, 64),     # expander-like, config 3 shape class
    ("sbm-hash:12:8:0.05:16:1", None, 8),  # community-structured, config 2 class
]
ALPHAS = [("default_1.0", 1.0), ("beta_2.0", 1.0), ("beta_1.5", 0.5),
          ("beta_1.25", 0.25), ("beta_1.1", 0.1)]

def soak_bigv_rows():
    """Frontier rows from committed ``bigv_s*_b*.json`` capability
    artifacts (tools/bigv_scale30.py --balance BETA): each carries a
    measured cut/balance at a real vertex scale under a guaranteed
    balance budget. Malformed or budget-less artifacts are skipped —
    one bad file must never cost the sweep its own rows."""
    import glob
    rows = []
    soak = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "out", "soak")
    for path in sorted(glob.glob(os.path.join(soak, "bigv_s*_b*.json"))):
        try:
            with open(path) as f:
                art = json.load(f)
            beta = art["balance_budget"]
            bv = art["bigv"]
            if beta is None or not bv.get("total_edges"):
                continue
            rows.append({
                "graph": f"rmat-stream:{art['scale']}"
                         f":{art['n_edges']}-edge-prefix",
                "k": art["k"], "backend": "tpu-bigv",
                "config": f"beta_{beta:g}", "alpha": art["alpha"],
                "cut_ratio": round(bv["edge_cut"] / bv["total_edges"], 5),
                "balance": round(float(bv["balance"]), 4),
                "artifact": os.path.basename(path),
                "oracle_equal": art.get("oracle_equal"),
            })
            print(json.dumps(rows[-1]), flush=True)
        except (OSError, json.JSONDecodeError, KeyError, TypeError):
            continue
    return rows


def main():
    import tempfile
    from sheep_tpu.io import formats, generators
    rows = []
    with tempfile.TemporaryDirectory() as td:
        kpath = os.path.join(td, "karate.edges")
        formats.write_edges(kpath, generators.karate_club())
        for gname, marker, k in GRAPHS:
            path = kpath if marker == "GOLDEN" else gname
            for be in ("cpu", "tpu"):
                if be not in sheep_tpu.list_backends():
                    continue
                for aname, alpha in ALPHAS:
                    r = sheep_tpu.partition(path, k, backend=be,
                                            alpha=alpha, comm_volume=False)
                    rows.append({"graph": gname, "k": k, "backend": be,
                                 "config": aname, "alpha": alpha,
                                 "cut_ratio": round(r.cut_ratio, 5),
                                 "balance": round(float(r.balance), 4)})
                    print(json.dumps(rows[-1]), flush=True)
        if "tpu-bigv" in sheep_tpu.list_backends():
            # the vertex-sharded frontier row at the config-5 part count
            for aname, alpha in ALPHAS:
                r = sheep_tpu.partition("rmat-hash:14:8:5", 1024,
                                        backend="tpu-bigv", alpha=alpha,
                                        comm_volume=False)
                rows.append({"graph": "rmat-hash:14:8:5", "k": 1024,
                             "backend": "tpu-bigv", "config": aname,
                             "alpha": alpha,
                             "cut_ratio": round(r.cut_ratio, 5),
                             "balance": round(float(r.balance), 4)})
                print(json.dumps(rows[-1]), flush=True)
    # absorb committed capability-run rows (ISSUE 20): a bigv_s30*_b*.json
    # artifact from tools/bigv_scale30.py --balance BETA is a frontier
    # point at the REAL config-5 vertex scale — multi-hour evidence this
    # sweep could never re-measure inline, so the committed artifact is
    # the source of truth (same no-clobber rule as the artifact itself).
    rows.extend(soak_bigv_rows())
    out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "out", "soak", "balance_frontier.json")
    with open(out, "w") as f:
        json.dump(rows, f, indent=1)
    print("written", out)

if __name__ == "__main__":
    main()
