#!/usr/bin/env python
"""sheeplint: JAX-hazard static analyzer for this repo (ISSUE 6).

Thin launcher for :mod:`sheep_tpu.analysis.cli` that works from a
checkout without installation. The tier-1 gate invocation is

    python tools/sheeplint.py --check sheep_tpu tools

which exits 0 only at zero non-baselined findings (1 = errors,
2 = warnings only). See README "Static analysis & sanitizers" for the
rule catalog and the pragma/baseline workflow.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from sheep_tpu.analysis.cli import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
