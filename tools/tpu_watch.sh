#!/usr/bin/env bash
# Poll the axon tunnel; the moment it heals, capture the post-optimization
# evidence in one shot: focused RMAT-22 schedule sweep + headline bench.
# The tunnel wedges for long stretches (observed twice this round), so
# polling + immediate capture beats hoping it is up when a human looks.
set -u
cd "$(dirname "$0")/.."
interval=${SHEEP_WATCH_INTERVAL:-240}
deadline=$(( $(date +%s) + ${SHEEP_WATCH_HOURS:-10} * 3600 ))

probe() {
  timeout 90 python -c "
import jax, jax.numpy as jnp, numpy as np
assert int(np.asarray(jnp.sum(jnp.arange(8)))) == 28
print('ok')" 2>/dev/null | grep -q ok
}

while [ "$(date +%s)" -lt "$deadline" ]; do
  if probe; then
    ts=$(date -u +%Y%m%dT%H%M%S)
    out="tools/out/$ts"
    mkdir -p "$out"
    echo "tunnel healthy at $ts; capturing" | tee "$out/watch.log"
    # chunk-log 23 FIRST: its programs are warm in the persistent
    # compilation cache from any prior bench.py run, so the sweep's
    # first lines land within minutes — the tunnel has twice wedged
    # mid-capture while cold 2^24 programs compiled (r3: 30 min of
    # remote_compile then connection refused, zero lines landed)
    timeout 3600 python tools/tune_fixpoint.py --scale 22 --ef 16 \
      --chunk-logs 23 --warm w1,w8 --segment-rounds 2 \
      --lift-levels 0 --tail-divisors 2 --stale 1,0 --carry 0,1 \
      --overlap 0,1 \
      >"$out/tune22_post.jsonl" 2>>"$out/watch.log"
    tune_rc=$?
    timeout 3600 python bench.py >"$out/bench.json" 2>"$out/bench.stderr"
    cat "$out/bench.json" | tee -a "$out/watch.log"
    # success = the HEADLINE measurement landed (bench.py emits its JSON
    # contract even on failure, with value 0 + "error"); the tune sweep
    # is best-effort extra evidence — a partial jsonl is still data. A
    # mid-capture wedge keeps polling for another try.
    if grep -q '"vs_baseline"' "$out/bench.json" && \
       ! grep -q '"value": 0.0' "$out/bench.json"; then
      echo "bench landed (tune rc=$tune_rc)" | tee -a "$out/watch.log"
      # best-effort: micro-roofline numbers + an xprof trace of one
      # fixpoint round (the VERDICT r1 item 3 trace artifact)
      timeout 1200 python tools/microbench_fixpoint.py --scale 22 \
        --chunk-log 23 --profile-dir "$out/xprof" \
        >"$out/microbench.jsonl" 2>>"$out/watch.log"
      echo "microbench rc=$?" | tee -a "$out/watch.log"
      exit 0
    fi
    echo "capture incomplete (tune rc=$tune_rc); resuming poll" \
      | tee -a "$out/watch.log"
  fi
  sleep "$interval"
done
echo "deadline reached without a healthy tunnel"
exit 1
