#!/usr/bin/env python
"""Mosaic lowering smokes for the Pallas gather lever, all variants in
one tool (the lever is CLOSED per the round-5/6 captures; one probe
file beats three drifting copies).

    python tools/pallas_smoke.py                 # variant 1 (default)
    python tools/pallas_smoke.py --variant 2 [--perf] [--interpret]
    python tools/pallas_smoke.py --variant 3 [--interpret]

Variants (formerly pallas_smoke.py / pallas_smoke2.py /
pallas_smoke3.py — artifacts under tools/out/ keep those names):

1. **1D VMEM gather** (VERDICT r4 weak #6): does the arbitrary-index
   ``jnp.take`` kernel (ops/pallas_gather.vmem_gather) lower through
   Mosaic at all? Measured verdict: NO — "Only 2D gather is
   supported" (tools/out/20260801T083204/pallas_smoke.json). One JSON
   line; rc 0 on any DECIDED outcome (lowered or rejected), rc 1 when
   undecided (backend init failed — retry next window).

2. **2D gather forms A-E**: row-take / sublane-gather / lane-gather /
   composite scalar / lane-routed bulk, lowered one by one; ``--perf``
   adds the matched-shape throughput A/B vs XLA's 1D take for the
   forms that lower. Verdict: only the single-tile lane gather (C)
   lowers; every multi-row sublane form dies in a Mosaic assertion.

3. **Lane-gather width scaling**: how wide can take_along_axis(axis=1)
   go before Mosaic rejects it (the transposed-table escape hatch
   needs extent R >= 4096). Stops at the first rejection.

Run on-chip only inside a confirmed-healthy window
(tools/tpu_watch3.sh leg 0); ``--interpret`` exercises variants 2/3
off-chip for shape/semantics sanity, not lowering truth.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

INTERPRET = False


# ---------------------------------------------------------------------------
# variant 1: the original 1D VMEM-gather lowering probe
# ---------------------------------------------------------------------------

def variant1() -> int:
    out = {"probe": "pallas_lower_smoke", "table_len": 1 << 20,
           "n_idx": 1 << 16, "block": 8192}
    try:
        import jax

        plat = jax.default_backend()
        out["platform"] = plat
        if plat == "cpu":
            out["decided"] = False
            out["error"] = "cpu backend: Mosaic lowering not exercised"
            print(json.dumps(out), flush=True)
            return 1

        import jax.numpy as jnp
        import numpy as np

        from sheep_tpu.ops.pallas_gather import vmem_gather

        table = jnp.arange(out["table_len"], dtype=jnp.int32)
        # build in int64 on host: the Knuth constant overflows int32
        idx = jnp.asarray(
            (np.arange(out["n_idx"], dtype=np.int64) * 2654435761)
            % out["table_len"], dtype=jnp.int32)

        t0 = time.perf_counter()
        try:
            lowered = jax.jit(
                lambda t, i: vmem_gather(t, i, block=out["block"])
            ).lower(table, idx)
            txt = lowered.compile()  # Mosaic runs at compile, not lower
            out["lowered"] = True
            out["compile_s"] = round(time.perf_counter() - t0, 2)
            del txt
        except Exception as e:
            # Only a genuine Mosaic/lowering rejection is a DECIDED
            # outcome. A transport/runtime error (tunnel wedging between
            # the health probe and compile — the documented common mode)
            # must return rc 1 so the watcher retries the leg instead of
            # retiring it on a false "rejected" artifact.
            msg = f"{type(e).__name__}: {str(e)[:800]}"
            out["compile_s"] = round(time.perf_counter() - t0, 2)
            low = msg.lower()
            mosaic = any(s in low for s in
                         ("mosaic", "unimplemented", "unsupported",
                          "cannot lower", "lowering", "internal: mlir",
                          "notimplementederror"))
            transport = any(s in low for s in
                            ("deadline", "unavailable", "connection",
                             "socket", "rpc", "cancelled"))
            if mosaic and not transport:
                out["lowered"] = False
                out["mosaic_error"] = msg
                out["decided"] = True
                print(json.dumps(out), flush=True)
                return 0
            out["decided"] = False
            out["error"] = msg
            print(json.dumps(out), flush=True)
            return 1

        # it compiles: one quick timed A/B vs the XLA take at the same
        # shape (tiny — the full sweep is microbench_fixpoint's job)
        f_pallas = jax.jit(
            lambda t, i: vmem_gather(t, i, block=out["block"]))
        f_xla = jax.jit(lambda t, i: jnp.take(t, i, mode="clip"))
        for name, f in (("pallas_s", f_pallas), ("xla_s", f_xla)):
            _ = np.asarray(f(table, idx)[:1])  # warm + force through tunnel  # sheeplint: sync-ok
            t0 = time.perf_counter()
            for _ in range(5):
                r = f(table, idx)
            _ = np.asarray(r[:1])  # sheeplint: sync-ok
            out[name] = round((time.perf_counter() - t0) / 5, 5)
        out["decided"] = True
        print(json.dumps(out), flush=True)
        return 0
    except Exception as e:
        out["decided"] = False
        out["error"] = f"{type(e).__name__}: {str(e)[:500]}"
        print(json.dumps(out), flush=True)
        return 1


# ---------------------------------------------------------------------------
# variant 2: 2D gather forms A-E (+ --perf A/B)
# ---------------------------------------------------------------------------

def _specs(pl, pltpu, shapes, out_shape):
    kw = {"memory_space": pltpu.VMEM} if pltpu else {}
    in_specs = [pl.BlockSpec(s, lambda i, r=len(s): (0,) * r, **kw)
                for s in shapes]
    out_specs = pl.BlockSpec(out_shape,
                             lambda i, r=len(out_shape): (0,) * r, **kw)
    return in_specs, out_specs


def try_form(name, kernel, in_arrays, out_shape_dtype, check=None):
    import numpy as np

    import jax
    from jax.experimental import pallas as pl

    pltpu = None
    if not INTERPRET:
        try:
            from jax.experimental.pallas import tpu as pltpu
        except Exception:
            pltpu = None

    rec = {"form": name}
    try:
        in_specs, out_specs = _specs(
            pl, pltpu, [a.shape for a in in_arrays], out_shape_dtype.shape)
        call = pl.pallas_call(
            kernel, grid=(1,), in_specs=in_specs, out_specs=out_specs,
            out_shape=out_shape_dtype, interpret=INTERPRET)
        t0 = time.perf_counter()
        lowered = jax.jit(call).lower(*in_arrays)
        compiled = lowered.compile()
        rec["lowered"] = True
        rec["compile_s"] = round(time.perf_counter() - t0, 2)
        out = np.asarray(compiled(*in_arrays))
        if check is not None:
            rec["ok"] = bool(check(out))
    except Exception as e:
        msg = f"{type(e).__name__}: {e}".splitlines()[0][:300]
        if rec.get("lowered"):
            # lowering succeeded; the failure is at run time — that is a
            # different (and better) answer than "does not lower"
            rec["run_error"] = msg
        else:
            rec["lowered"] = False
            rec["error"] = msg
    print(json.dumps(rec), flush=True)
    return rec


def variant2(perf: bool) -> int:
    import numpy as np

    import jax
    import jax.numpy as jnp

    plat = jax.devices()[0].platform
    print(json.dumps({"platform": plat,
                      "device": str(jax.devices()[0])}), flush=True)

    R, B = 4096, 1024
    rng = np.random.default_rng(0)
    table2 = jnp.asarray(
        rng.integers(0, 1 << 30, (R, 128), dtype=np.int32))
    tnp = np.asarray(table2)  # sheeplint: sync-ok

    # A: row-take
    idxA = jnp.asarray(rng.integers(0, R, (B,), dtype=np.int32))
    try_form(
        "A_row_take",
        lambda t, i, o: o.__setitem__(
            ..., jnp.take(t[...], i[...], axis=0, mode="clip")),
        [table2, idxA],
        jax.ShapeDtypeStruct((B, 128), jnp.int32),
        check=lambda out: np.array_equal(out, tnp[np.asarray(idxA)]))  # sheeplint: sync-ok

    # B: sublane gather (axis=0), idx same shape as a (8,128) tile
    idxB = jnp.asarray(rng.integers(0, R, (8, 128), dtype=np.int32))
    try_form(
        "B_sublane_gather",
        lambda t, i, o: o.__setitem__(
            ..., jnp.take_along_axis(t[...], i[...], axis=0)),
        [table2, idxB],
        jax.ShapeDtypeStruct((8, 128), jnp.int32),
        check=lambda out: np.array_equal(
            out, np.take_along_axis(tnp, np.asarray(idxB), axis=0)))  # sheeplint: sync-ok

    # C: lane gather (axis=1) on one (8,128) tile
    x8 = jnp.asarray(rng.integers(0, 1 << 30, (8, 128), dtype=np.int32))
    idxC = jnp.asarray(rng.integers(0, 128, (8, 128), dtype=np.int32))
    try_form(
        "C_lane_gather",
        lambda x, i, o: o.__setitem__(
            ..., jnp.take_along_axis(x[...], i[...], axis=1)),
        [x8, idxC],
        jax.ShapeDtypeStruct((8, 128), jnp.int32),
        check=lambda out: np.array_equal(
            out, np.take_along_axis(np.asarray(x8), np.asarray(idxC),  # sheeplint: sync-ok
                                    axis=1)))

    # D: composite arbitrary-index scalar gather, 8 per two 2D gathers.
    # idx (S, 8) int32 in [0, R*128); out (S, 8).
    S = 64
    idxD = jnp.asarray(rng.integers(0, R * 128, (S, 8), dtype=np.int32))

    def kernel_D(t, i, o):
        def one(s, _):
            g = i[s, :]                        # (8,) arbitrary indices
            row = (g >> 7).reshape(8, 1)       # broadcast rows across lanes
            col = (g & 127).reshape(8, 1)
            rows8 = jnp.take_along_axis(
                t[...], jnp.broadcast_to(row, (8, 128)), axis=0)
            z = jnp.take_along_axis(
                rows8, jnp.broadcast_to(col, (8, 128)), axis=1)
            o[s, :] = z[:, 0]
            return _

        import jax.lax as lax

        lax.fori_loop(0, S, one, 0)

    try_form(
        "D_composite_scalar",
        kernel_D,
        [table2, idxD],
        jax.ShapeDtypeStruct((S, 8), jnp.int32),
        check=lambda out: np.array_equal(
            out, tnp.reshape(-1)[np.asarray(idxD)]))  # sheeplint: sync-ok

    # E: lane-routed bulk gather. Indices PRE-ROUTED so lane j only
    # holds indices with (idx & 127) == j (the router is an XLA sort by
    # idx&127 OUTSIDE the kernel); then ONE sublane dynamic gather does
    # a full (SB,128) tile of arbitrary lookups.
    SB = 64
    lanes = np.arange(128, dtype=np.int32)[None, :]
    rowsE = rng.integers(0, R, (SB, 128), dtype=np.int32)
    idxE = jnp.asarray(rowsE * 128 + lanes)    # pre-routed by construction

    def kernel_E(t, i, o):
        o[...] = jnp.take_along_axis(t[...], i[...] >> 7, axis=0)

    try_form(
        "E_lane_routed_bulk",
        kernel_E,
        [table2, idxE],
        jax.ShapeDtypeStruct((SB, 128), jnp.int32),
        check=lambda out: np.array_equal(
            out, tnp.reshape(-1)[np.asarray(idxE)]))  # sheeplint: sync-ok

    if perf and plat == "tpu":
        _perf2(jax, jnp, rng)
    return 0


def _time(f, *a):
    import jax

    jax.block_until_ready(f(*a))               # warm-up / compile
    t0 = time.perf_counter()
    for _ in range(5):
        r = f(*a)
    jax.block_until_ready(r)
    return (time.perf_counter() - t0) / 5


def _perf2(jax, jnp, rng):
    """Throughput of the variant-2 forms that lowered vs XLA's 1D
    gather, matched shapes: table 2^20 int32 (4 MB — VMEM-resident
    territory), 2^20 lookups per call. Reports M elem/s; the XLA row is
    the ~100-150 M elem/s incumbent the re-negotiation cites."""
    import numpy as np

    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    R, NI = 1 << 13, 1 << 20                   # table (8192,128) = 2^20
    table2 = jnp.asarray(
        rng.integers(0, 1 << 30, (R, 128), dtype=np.int32))
    flat = table2.reshape(-1)
    # balanced residues BY CONSTRUCTION (NI/128 indices per lane class,
    # randomly interleaved): the block-routing reshape below is exact
    # only for balanced counts; arbitrary input would need per-bucket
    # padding, which is an integration concern, not a lowering probe's
    rows1 = rng.integers(0, R, (NI,), dtype=np.int32)
    res1 = np.repeat(np.arange(128, dtype=np.int32), NI // 128)
    rng.shuffle(res1)
    idx1 = jnp.asarray(rows1 * 128 + res1)

    xla = jax.jit(lambda t, i: jnp.take(t, i, mode="clip"))
    s = _time(xla, flat, idx1)
    print(json.dumps({"perf": "xla_take_1d", "n": NI,
                      "melems": round(NI / s / 1e6, 1)}), flush=True)

    # E + its XLA router (sort by idx&127, then in-kernel sublane gather)
    SB = NI // 128
    vm = {"memory_space": pltpu.VMEM}
    callE = pl.pallas_call(
        lambda t, i, o: o.__setitem__(
            ..., jnp.take_along_axis(t[...], i[...] >> 7, axis=0)),
        grid=(1,),
        in_specs=[pl.BlockSpec((R, 128), lambda g: (0, 0), **vm),
                  pl.BlockSpec((SB, 128), lambda g: (0, 0), **vm)],
        out_specs=pl.BlockSpec((SB, 128), lambda g: (0, 0), **vm),
        out_shape=jax.ShapeDtypeStruct((SB, 128), jnp.int32))
    # gate the E legs on the kernel actually lowering (on the 2026-08
    # toolchain it does NOT — multi-row sublane gather asserts in
    # Mosaic; this keeps the perf artifact complete instead of dying
    # mid-run like the first capture did)
    try:
        probeE = jnp.zeros((SB, 128), jnp.int32)
        jax.jit(callE).lower(table2, probeE).compile()
    except Exception as e:
        print(json.dumps({
            "perf": "E_kernel_only", "lowered": False,
            "error": f"{type(e).__name__}: {e}".splitlines()[0][:300]}),
            flush=True)
        return

    # routing: element with residue j must land in LANE j. After the
    # sort the array is contiguous residue blocks; with BALANCED residue
    # counts (true for the synthetic idx below, NOT for arbitrary input
    # — a real integration pads each bucket to the max count) the
    # column-major reshape(128, SB).T puts block j into column j.
    def routed(t2, i):
        order = jnp.argsort(i & 127)           # the router (XLA sort)
        z = callE(t2, i[order].reshape(128, SB).T)
        return z.T.reshape(-1)                 # values in ROUTED order

    def routed_unrouted(t2, i):
        order = jnp.argsort(i & 127)
        z = callE(t2, i[order].reshape(128, SB).T).T.reshape(-1)
        return jnp.zeros_like(z).at[order].set(z)  # original order

    # correctness of kernel-only leg on routed input
    rowsE = rng.integers(0, R, (SB, 128), dtype=np.int32)
    lanes = np.arange(128, dtype=np.int32)[None, :]
    idxE = jnp.asarray(rowsE * 128 + lanes)
    outE = np.asarray(callE(table2, idxE))
    okE = np.array_equal(outE, np.asarray(flat)[np.asarray(idxE)])  # sheeplint: sync-ok
    s = _time(callE, table2, idxE)
    print(json.dumps({"perf": "E_kernel_only", "ok": bool(okE), "n": NI,
                      "melems": round(NI / s / 1e6, 1)}), flush=True)
    okR = np.array_equal(
        np.sort(np.asarray(routed(table2, idx1))),
        np.sort(np.asarray(flat)[np.asarray(idx1)]))  # sheeplint: sync-ok
    s = _time(jax.jit(routed), table2, idx1)
    print(json.dumps({"perf": "E_with_router", "ok": bool(okR), "n": NI,
                      "melems": round(NI / s / 1e6, 1)}), flush=True)
    okU = np.array_equal(np.asarray(routed_unrouted(table2, idx1)),
                         np.asarray(flat)[np.asarray(idx1)])  # sheeplint: sync-ok
    s = _time(jax.jit(routed_unrouted), table2, idx1)
    print(json.dumps({"perf": "E_router_unroute", "ok": bool(okU),
                      "n": NI,
                      "melems": round(NI / s / 1e6, 1)}), flush=True)


# ---------------------------------------------------------------------------
# variant 3: lane-gather width scaling
# ---------------------------------------------------------------------------

def _probe_width(R):
    import numpy as np

    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    rec = {"probe": "lane_gather_width", "lane_extent": R,
           "table_elems": 128 * R,
           "table_mb": round(128 * R * 4 / 2**20, 1)}
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.integers(0, 1 << 30, (8, R), dtype=np.int32))
    idx = jnp.asarray(rng.integers(0, R, (8, R), dtype=np.int32))

    kw = {}
    if not INTERPRET:
        from jax.experimental.pallas import tpu as pltpu

        kw = {"memory_space": pltpu.VMEM}
    try:
        call = pl.pallas_call(
            lambda xr, ir, o: o.__setitem__(
                ..., jnp.take_along_axis(xr[...], ir[...], axis=1)),
            grid=(1,),
            in_specs=[pl.BlockSpec((8, R), lambda g: (0, 0), **kw),
                      pl.BlockSpec((8, R), lambda g: (0, 0), **kw)],
            out_specs=pl.BlockSpec((8, R), lambda g: (0, 0), **kw),
            out_shape=jax.ShapeDtypeStruct((8, R), jnp.int32),
            interpret=INTERPRET)
        t0 = time.perf_counter()
        compiled = jax.jit(call).lower(x, idx).compile()
        rec["lowered"] = True
        rec["compile_s"] = round(time.perf_counter() - t0, 2)
        out = np.asarray(compiled(x, idx))
        rec["ok"] = bool(np.array_equal(
            out, np.take_along_axis(np.asarray(x), np.asarray(idx),  # sheeplint: sync-ok
                                    axis=1)))
        n = 8 * R
        jax.block_until_ready(compiled(x, idx))
        t0 = time.perf_counter()
        reps = 20
        for _ in range(reps):
            r = compiled(x, idx)
        jax.block_until_ready(r)
        s = (time.perf_counter() - t0) / reps
        rec["melems"] = round(n / s / 1e6, 1)
    except Exception as e:
        msg = f"{type(e).__name__}: {e}".splitlines()[0][:300]
        if rec.get("lowered"):
            rec["run_error"] = msg
        else:
            rec["lowered"] = False
            rec["error"] = msg
    print(json.dumps(rec), flush=True)
    return rec


def variant3() -> int:
    import jax

    print(json.dumps({"platform": jax.devices()[0].platform,
                      "device": str(jax.devices()[0])}), flush=True)
    widths = [128, 256, 512]
    if not INTERPRET:
        widths += [1024, 4096, 8192, 16384, 32768]
    for R in widths:
        rec = _probe_width(R)
        if not rec.get("lowered") and not INTERPRET:
            break  # wider only gets harder; stop at first rejection
    return 0


def main(argv=None) -> int:
    global INTERPRET
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--variant", type=int, default=1, choices=(1, 2, 3))
    ap.add_argument("--perf", action="store_true",
                    help="variant 2: add the throughput A/B legs")
    ap.add_argument("--interpret", action="store_true",
                    help="variants 2/3: interpreter mode (semantics "
                         "only; no Mosaic lowering truth)")
    args = ap.parse_args(argv)
    INTERPRET = args.interpret
    if args.variant == 1:
        return variant1()
    if args.variant == 2:
        return variant2(args.perf)
    return variant3()


if __name__ == "__main__":
    sys.exit(main())
