"""Seconds-cheap Pallas Mosaic-lowering smoke (VERDICT r4 weak #6).

``tests/test_pallas_gather.py`` pins the VMEM-gather kernel's semantics
in interpreter mode only — it cannot catch a Mosaic lowering rejection,
so a healthy tunnel window could burn minutes discovering the kernel
does not compile. This probe answers that in seconds and leaves an
artifact EITHER way:

- ``lowered: true``  -> the arbitrary-index ``jnp.take`` is expressible;
  the full ``pallas_vmem_gather_C`` microbench leg is worth the window.
- ``lowered: false`` + the Mosaic error -> the gather roofline stands
  with a recorded rejection instead of an argument (the probe module's
  own docstring names this as an expected outcome).

Run ONLY inside a confirmed-healthy window (tools/tpu_watch3.sh leg 0);
the lowering itself needs the real TPU backend to target Mosaic.

Output: one JSON line on stdout; rc 0 on any *decided* outcome
(lowered or rejected), rc 1 only when no decision was reached (e.g.
backend init failed — retry next window).
"""

from __future__ import annotations

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main():
    out = {"probe": "pallas_lower_smoke", "table_len": 1 << 20,
           "n_idx": 1 << 16, "block": 8192}
    try:
        import jax

        plat = jax.default_backend()
        out["platform"] = plat
        if plat == "cpu":
            out["decided"] = False
            out["error"] = "cpu backend: Mosaic lowering not exercised"
            print(json.dumps(out), flush=True)
            return 1

        import jax.numpy as jnp
        import numpy as np

        from sheep_tpu.ops.pallas_gather import vmem_gather

        table = jnp.arange(out["table_len"], dtype=jnp.int32)
        # build in int64 on host: the Knuth constant overflows int32
        idx = jnp.asarray(
            (np.arange(out["n_idx"], dtype=np.int64) * 2654435761)
            % out["table_len"], dtype=jnp.int32)

        t0 = time.perf_counter()
        try:
            lowered = jax.jit(
                lambda t, i: vmem_gather(t, i, block=out["block"])
            ).lower(table, idx)
            txt = lowered.compile()  # Mosaic runs at compile, not lower
            out["lowered"] = True
            out["compile_s"] = round(time.perf_counter() - t0, 2)
            del txt
        except Exception as e:
            # Only a genuine Mosaic/lowering rejection is a DECIDED
            # outcome. A transport/runtime error (tunnel wedging between
            # the health probe and compile — the documented common mode)
            # must return rc 1 so the watcher retries the leg instead of
            # retiring it on a false "rejected" artifact.
            msg = f"{type(e).__name__}: {str(e)[:800]}"
            out["compile_s"] = round(time.perf_counter() - t0, 2)
            low = msg.lower()
            mosaic = any(s in low for s in
                         ("mosaic", "unimplemented", "unsupported",
                          "cannot lower", "lowering", "internal: mlir",
                          "notimplementederror"))
            transport = any(s in low for s in
                            ("deadline", "unavailable", "connection",
                             "socket", "rpc", "cancelled"))
            if mosaic and not transport:
                out["lowered"] = False
                out["mosaic_error"] = msg
                out["decided"] = True
                print(json.dumps(out), flush=True)
                return 0
            out["decided"] = False
            out["error"] = msg
            print(json.dumps(out), flush=True)
            return 1

        # it compiles: one quick timed A/B vs the XLA take at the same
        # shape (tiny — the full sweep is microbench_fixpoint's job)
        import numpy as np

        f_pallas = jax.jit(lambda t, i: vmem_gather(t, i, block=out["block"]))
        f_xla = jax.jit(lambda t, i: jnp.take(t, i, mode="clip"))
        for name, f in (("pallas_s", f_pallas), ("xla_s", f_xla)):
            _ = np.asarray(f(table, idx)[:1])  # warm + force through tunnel  # sheeplint: sync-ok
            t0 = time.perf_counter()
            for _ in range(5):
                r = f(table, idx)
            _ = np.asarray(r[:1])  # sheeplint: sync-ok
            out[name] = round((time.perf_counter() - t0) / 5, 5)
        out["decided"] = True
        print(json.dumps(out), flush=True)
        return 0
    except Exception as e:
        out["decided"] = False
        out["error"] = f"{type(e).__name__}: {str(e)[:500]}"
        print(json.dumps(out), flush=True)
        return 1


if __name__ == "__main__":
    sys.exit(main())
