#!/usr/bin/env python
"""Hierarchical k=64 quality driver (VERDICT r4 item 3 continuation).

Runs partition_hierarchical on the planted-partition stream and writes
the artifact JSON keyed by every quality-relevant knob. Round-5 history
at s22 k=64 (planted optimum 0.050):

    flat refine-30            0.847   (sbm_s22_r30.json)
    hier [8,8] refine-10      0.431   (hier_s22.json)
    + final_refine=10         0.336   (hier_s22_fr.json — stopped at
                                       the round cap, NOT at rollback)

The refine loop stops on its own at the first non-improving round, so
generous --refine/--final-refine caps cost nothing once converged.
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

# quality runs are platform-invariant (cut/balance bit-identical cpu vs
# tpu — balance_frontier.json) and must never contend for the tunnel
# while the watcher is capturing: pin cpu UNCONDITIONALLY. (Not the env
# var: this environment sets JAX_PLATFORMS=axon globally, so an env
# fallback would pin the tunneled chip — the exact failure this guard
# exists to prevent. SHEEP_QUALITY_PLATFORM overrides deliberately.)
from sheep_tpu.utils.platform import pin_platform  # noqa: E402

pin_platform(os.environ.get("SHEEP_QUALITY_PLATFORM") or "cpu")


def _num(v):
    """Diagnostics values are floats in the common case but can be
    status strings (e.g. the refine pass's 'refine_skipped' fallback) —
    coerce defensively so a completed multi-hour partition always writes
    its artifact instead of dying on float('refine_skipped')."""
    try:
        return float(v)
    except (TypeError, ValueError):
        return str(v)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--scale", type=int, default=22)
    ap.add_argument("--blocks", type=int, default=64)
    ap.add_argument("--p-out", type=float, default=0.05)
    ap.add_argument("--edge-factor", type=int, default=16)
    ap.add_argument("--seed", type=int, default=1)
    ap.add_argument("--k-levels", default="8,8")
    ap.add_argument("--refine", type=int, default=30)
    ap.add_argument("--final-refine", type=int, default=60)
    ap.add_argument("--balance", type=float, default=None)
    ap.add_argument("--refine-budget-gb", type=float, default=6.0,
                    help="histogram budget for the final refine; the "
                         "4 GB library default misses s22/k=256 by 1 KB "
                         "and quintuples its passes")
    ap.add_argument("--tag", default="")
    args = ap.parse_args()

    from sheep_tpu.hierarchy import partition_hierarchical
    from sheep_tpu.io.edgestream import open_input

    spec = (f"sbm-hash:{args.scale}:{args.blocks}:{args.p_out}"
            f":{args.edge_factor}:{args.seed}")
    k_levels = [int(x) for x in args.k_levels.split(",")]

    t0 = time.perf_counter()
    res = partition_hierarchical(
        spec, k_levels, refine=args.refine,
        final_refine=args.final_refine, balance=args.balance,
        refine_budget_bytes=int(args.refine_budget_gb * (1 << 30)))
    wall = time.perf_counter() - t0

    with open_input(spec) as es:
        planted = es.planted_cut_ratio()
        # the cut ledger's residual attribution (ISSUE 13): per-level
        # achieved-vs-planted excess, naming which level owns the
        # residual — the diagnosis ROADMAP item 4's follow-up attacks
        from sheep_tpu.utils.metrics import ledger_residual

        residual = ledger_residual(res.diagnostics or {}, k_levels,
                                   es.planted_cut_ratio,
                                   res.total_edges)

    out = {
        "spec": spec,
        "k_levels": k_levels,
        "refine": args.refine,
        "final_refine": args.final_refine,
        "balance_budget": args.balance,
        "cut_ratio": round(res.cut_ratio, 6),
        "edge_cut": int(res.edge_cut),
        "total_edges": int(res.total_edges),
        "balance": round(res.balance, 4),
        "comm_volume": None if res.comm_volume is None
                       else int(res.comm_volume),
        "wall_s_contended": round(wall, 1),
        "phase_times": res.phase_times,
        "diagnostics": {k: _num(v) for k, v in
                        (res.diagnostics or {}).items()},
        "planted_optimum": round(planted, 4),
        "residual": residual,
        "history": {"flat_r30": 0.8467, "hier_r4": 0.4313,
                    "hier_fr10": 0.3364},
    }
    # every quality-relevant knob keys the filename (ADVICE r4's clobber
    # lesson, re-learned once: a balance run overwrote its unbalanced
    # twin before the budget joined the name)
    tag = f"_{args.tag}" if args.tag else ""
    bal = f"_b{args.balance}".replace(".", "") if args.balance else ""
    lv = "x".join(str(k) for k in k_levels)
    path = os.path.join(
        os.path.dirname(__file__), "out", "soak",
        f"hier_s{args.scale}_k{args.blocks}_L{lv}"
        f"_r{args.refine}_fr{args.final_refine}{bal}{tag}.json")
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
