#!/usr/bin/env python
"""Declarative fleet SLO gate over a federated scrape (ISSUE 18
tentpole, layer 3).

    python tools/slo_check.py --rules slo.json --endpoints A,B
    python tools/slo_check.py --rules slo.json saved_a.txt saved_b.txt

Rule file: JSON mapping tenant -> bounds. Tenant ``"*"`` means
fleet-wide (every tenant's series merged)::

    {"tenants": {
        "t0": {"p99_latency_s": 5.0, "max_update_throttled": 100},
        "*":  {"p99_latency_s": 30.0, "max_error_rate": 0.01}}}

Bounds (each optional):

- ``p<N>_latency_s`` — the N-th percentile of the federated
  ``sheepd_request_latency_seconds`` histogram (queued->done) must not
  exceed the bound. Any percentile works: ``p50_latency_s``,
  ``p99_latency_s``, ...
- ``max_error_rate`` — error/total over the federated
  ``sheepd_requests_total{verb,outcome}`` counter. The series has no
  tenant label, so this bound is ALWAYS evaluated fleet-wide (a
  per-tenant entry carrying it gets a note saying so).
- ``max_update_throttled`` — the federated
  ``sheepd_update_throttled_total`` count (update items deferred by
  the per-tenant byte budget, ISSUE 17) must not exceed the bound.

A bound whose series holds no data PASSES with a note — no traffic is
not an SLO burn (the obs_smoke leg exercises both directions with
live daemons). Replicas that fail to scrape degrade with a warning,
exactly as ``sheep-fleet-metrics`` does; ZERO answering replicas is an
error, not a pass.

Exit codes: 0 every bound holds; 1 usage/IO/no replica answered;
2 at least one bound burned.
"""

from __future__ import annotations

import argparse
import json
import math
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from sheep_tpu.obs import federate as federate_mod  # noqa: E402
from sheep_tpu.obs.metrics import quantile_from_cumulative  # noqa: E402

LATENCY_METRIC = "sheepd_request_latency_seconds"
REQUESTS_METRIC = "sheepd_requests_total"
THROTTLE_METRIC = "sheepd_update_throttled_total"

_PCT_RE = re.compile(r"^p(\d{1,2}(?:\.\d+)?)_latency_s$")


def tenant_quantile(fed: dict, q: float, tenant=None):
    """Quantile of the federated latency histogram for one tenant, or
    across ALL tenants when tenant is None — per-``le`` counts sum
    across tenant series first (histogram_series_quantile assumes one
    series, so the cross-tenant merge happens here)."""
    agg: dict = {}
    for labels, v in fed["samples"].get(LATENCY_METRIC + "_bucket", []):
        if tenant is not None and labels.get("tenant") != tenant:
            continue
        le = labels.get("le")
        if le is None:
            continue
        agg[le] = agg.get(le, 0) + v
    if not agg:
        return None
    rows = sorted(agg.items(),
                  key=lambda kv: float(kv[0].replace("+Inf", "inf")))
    uppers = [float(le) for le, _ in rows
              if not math.isinf(float(le.replace("+Inf", "inf")))]
    cum = [int(c) for _, c in rows]
    return quantile_from_cumulative(uppers, cum, q)


def fleet_error_rate(fed: dict):
    """(errors / total, total) over the federated requests counter, or
    None when no requests were tallied."""
    total = errors = 0.0
    for labels, v in fed["samples"].get(REQUESTS_METRIC, []):
        total += v
        if labels.get("outcome") == "error":
            errors += v
    if total <= 0:
        return None
    return errors / total, total


def tenant_throttled(fed: dict, tenant=None) -> float:
    return sum(v for labels, v
               in fed["samples"].get(THROTTLE_METRIC, [])
               if tenant is None or labels.get("tenant") == tenant)


def evaluate(rules: dict, fed: dict) -> list:
    """[{tenant, bound, limit, value, ok, note}] — one verdict per
    declared bound. Unknown bound keys are a rule-file error (raise),
    not a silent pass: a typo'd bound that never evaluates is an SLO
    gate that never gates."""
    tenants = rules.get("tenants")
    if not isinstance(tenants, dict) or not tenants:
        raise ValueError('rules must be {"tenants": {tenant: '
                         '{bound: limit}}} with >= 1 tenant')
    verdicts = []
    for tenant, bounds in sorted(tenants.items()):
        if not isinstance(bounds, dict):
            raise ValueError(f"tenant {tenant!r}: bounds must be a "
                             f"dict, got {type(bounds).__name__}")
        t = None if tenant == "*" else tenant
        for key, limit in sorted(bounds.items()):
            limit = float(limit)
            v = {"tenant": tenant, "bound": key, "limit": limit,
                 "value": None, "ok": True, "note": ""}
            m = _PCT_RE.match(key)
            if m:
                q = float(m.group(1)) / 100.0
                got = tenant_quantile(fed, q, t)
                if got is None:
                    v["note"] = "no latency observations — no traffic"
                else:
                    v["value"] = got
                    v["ok"] = got <= limit
            elif key == "max_error_rate":
                got = fleet_error_rate(fed)
                if t is not None:
                    v["note"] = (f"{REQUESTS_METRIC} has no tenant "
                                 f"label; evaluated fleet-wide")
                if got is None:
                    v["note"] = (v["note"] + "; " if v["note"] else
                                 "") + "no requests tallied"
                else:
                    rate, total = got
                    v["value"] = rate
                    v["ok"] = rate <= limit
                    v["note"] = (v["note"] + "; " if v["note"] else
                                 "") + f"{int(total)} requests"
            elif key == "max_update_throttled":
                got = tenant_throttled(fed, t)
                v["value"] = got
                v["ok"] = got <= limit
            else:
                raise ValueError(
                    f"tenant {tenant!r}: unknown bound {key!r} "
                    f"(want p<N>_latency_s, max_error_rate, or "
                    f"max_update_throttled)")
            verdicts.append(v)
    return verdicts


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Evaluate declarative per-tenant SLO rules over a "
                    "federated fleet scrape; exit 2 on any burn.")
    ap.add_argument("endpoint", nargs="*",
                    help="replica endpoints (unix socket / URL / "
                         "saved scrape file)")
    ap.add_argument("--rules", required=True,
                    help="JSON rule file (see module docstring)")
    ap.add_argument("--endpoints", default=None,
                    help="comma-separated endpoints")
    ap.add_argument("--timeout", type=float, default=10.0)
    ap.add_argument("--json", action="store_true",
                    help="machine-readable verdicts")
    args = ap.parse_args(argv)

    endpoints = list(args.endpoint)
    if args.endpoints:
        endpoints += [e.strip() for e in args.endpoints.split(",")
                      if e.strip()]
    if not endpoints:
        ap.error("no endpoints given")
    try:
        with open(args.rules) as f:
            rules = json.load(f)
    except (OSError, ValueError) as e:
        print(f"error: cannot read rules {args.rules}: {e}",
              file=sys.stderr)
        return 1

    scrapes = federate_mod.scrape_fleet(endpoints,
                                        timeout_s=args.timeout)
    try:
        fed = federate_mod.federate(scrapes)
        verdicts = evaluate(rules, fed)
    except (federate_mod.FederationError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    for w in fed["warnings"]:
        print(f"warning: {w}", file=sys.stderr)
    if not fed["answered"]:
        print("error: no replica answered a scrape", file=sys.stderr)
        return 1

    burned = [v for v in verdicts if not v["ok"]]
    if args.json:
        json.dump({"ok": not burned, "verdicts": verdicts,
                   "replicas": fed["replicas"],
                   "answered": fed["answered"],
                   "warnings": fed["warnings"]},
                  sys.stdout, indent=1, sort_keys=True)
        print()
    else:
        for v in verdicts:
            val = "n/a" if v["value"] is None \
                else f"{v['value']:.6g}"
            note = f"  ({v['note']})" if v["note"] else ""
            print(f"{'BURN' if not v['ok'] else 'ok  '} "
                  f"tenant={v['tenant']} {v['bound']} "
                  f"value={val} limit={v['limit']:g}{note}")
        print(f"slo: {len(verdicts) - len(burned)}/{len(verdicts)} "
              f"bounds hold across {len(fed['answered'])} replica(s)"
              + (f" — {len(burned)} BURNED" if burned else ""))
    return 2 if burned else 0


if __name__ == "__main__":
    sys.exit(main())
