#!/usr/bin/env python
"""Repo-checkout shim for sheeptop (the installed console script maps
to the same entry point): a live console view over a running sheepd —
per-job progress, per-tenant latency percentiles, daemon headroom.

    python tools/sheeptop.py --server /run/sheepd.sock [--once|--plain]

Implementation lives in sheep_tpu/server/sheeptop.py (importable =
unit-testable; this file exists so every tool is runnable straight
from a checkout like the rest of tools/).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from sheep_tpu.server.sheeptop import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
