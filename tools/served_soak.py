#!/usr/bin/env python
"""Served-job mini-soak (ISSUE 10 satellite; chaos_soak's pattern
applied to sheepd): inject one OOM-class fault, one read fault, one
SIGKILL, one SIGTERM drain and one replica kill under fleet routing
into served jobs and assert the DAEMON (or its restarted incarnation,
or the surviving replica) delivers the job with the verdict
``identical`` or ``degraded_documented``.

    python tools/served_soak.py [--out DIR]

Five legs, each against REAL ``sheepd`` subprocesses on unix sockets
over a real on-disk graph (so the edgestream read points are live):

    oom      SHEEP_FAULT_INJECT=oom@dispatch:1 — RESOURCE_EXHAUSTED at
             the first issued dispatch of the served build; the per-job
             retry layer must degrade/re-fold bit-identically and leave
             the ``dispatch_retries`` trail in the job diagnostics.
    read     SHEEP_FAULT_INJECT=read@read:2 — a torn physical read; the
             edgestream's bounded transient retry absorbs it below the
             scheduler entirely.
    restart  (ISSUE 14) SIGKILL the durable daemon mid-build, restart
             it on the same socket/journal/state dir: the journaled job
             must RESUME from its per-job checkpoint (the
             ``sheepd_jobs_resumed_total`` counter is required — a leg
             where the kill landed after completion proved nothing) and
             finish bit-identical to the clean oracle.
    drain    (ISSUE 14) SIGTERM the durable daemon mid-build: it must
             exit rc=0 after checkpointing the job at its next flush
             barrier (the graceful drain), and the restarted daemon
             must resume it to a bit-identical finish.
    fleet    (ISSUE 16) two replicas behind the FleetClient: headroom
             routing must SPLIT concurrent jobs across both (route
             counters nonzero on each), then one replica is SIGKILLed
             mid-build and EVERY job must still complete via the
             reattach-idempotent failover resubmit, each forest
             bit-equal to the clean oracle.

Per leg the verdict is exactly chaos_soak's taxonomy:

    identical            served assignment bit-equals the clean oracle
    degraded_documented  differs, but the job carries a documented
                         degradation marker (quarantined chunks)
    wrong_forest         differs with NO documentation — a real bug
    unhandled_crash      the job failed, the daemon died (or, durable
                         legs: never resumed / drain exited nonzero),
                         or it stopped answering pings after the fault

After each job the daemon must still answer ``ping`` (the fault
degraded the JOB, not the service) and must shut down rc=0. Exit 0
iff every leg is identical/degraded_documented; wired tier-1 by
tests/test_server.py.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

LEGS = (
    ("oom", "oom@dispatch:1"),
    ("read", "read@read:2"),
)

# the durable legs (ISSUE 14) kill/drain the daemon MID-BUILD; the
# graph is bigger and the chunks smaller so the build phase has
# dozens of observable steps to land the signal in
DURABLE_V = 4096
DURABLE_E = 32768
DURABLE_CHUNK = 256


def build_graph(path: str, n: int = 512, m: int = 4096) -> None:
    from sheep_tpu.io import formats, generators

    formats.write_edges(path, generators.random_graph(n, m, seed=7))


def clean_oracle(path: str, n: int = 512, chunk_edges: int = 512):
    """The fault-free reference assignment, computed in THIS process
    (the daemons never see a fault-free run — the oracle must not)."""
    from sheep_tpu import _partition_stream
    from sheep_tpu.io.edgestream import open_input

    with open_input(path, n_vertices=n) as es:
        res = _partition_stream(es, 4, backend="tpu",
                                chunk_edges=chunk_edges,
                                comm_volume=False)
    return res.assignment


def run_leg(name: str, inject: str, graph: str, out_dir: str,
            oracle) -> dict:
    import numpy as np

    from sheep_tpu.server.client import ServerError, SheepClient

    sock = os.path.join(out_dir, f"soak_{name}.sock")
    trace = os.path.join(out_dir, f"soak_{name}.jsonl")
    err_path = os.path.join(out_dir, f"soak_{name}.err")
    env = {**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONPATH": REPO,
           "SHEEP_FAULT_INJECT": inject, "SHEEP_RETRY_BASE_S": "0.01"}
    rec = {"leg": name, "inject": inject}
    with open(err_path, "w") as err_f:
        proc = subprocess.Popen(
            [sys.executable, "-m", "sheep_tpu.server.daemon",
             "--socket", sock, "--trace", trace,
             "--heartbeat-secs", "0.2"],
            cwd=REPO, env=env, stderr=err_f)
    try:
        for _ in range(150):
            if os.path.exists(sock) or proc.poll() is not None:
                break
            time.sleep(0.2)
        if not os.path.exists(sock):
            rec["verdict"] = "unhandled_crash"
            rec["error"] = f"daemon never bound (rc={proc.poll()})"
            return rec
        with SheepClient(sock) as c:
            try:
                r = c.submit(graph, k=4, tenant="soak",
                             chunk_edges=512, num_vertices=512,
                             return_assignment=True)
                job = c.wait(r["job_id"], timeout_s=120)
            except ServerError as e:
                rec["verdict"] = "unhandled_crash"
                rec["error"] = f"daemon refused the job: {e}"
                return rec
            rec["state"] = job.get("state")
            diags = (job.get("results") or [{}])[0].get(
                "diagnostics", {})
            rec["dispatch_retries"] = diags.get("dispatch_retries")
            # the daemon must still be serving AFTER the fault
            try:
                c.ping()
            except (ServerError, OSError) as e:
                rec["verdict"] = "unhandled_crash"
                rec["error"] = f"daemon dead after fault: {e}"
                return rec
            if job.get("state") != "done":
                rec["verdict"] = "unhandled_crash"
                rec["error"] = job.get("error", "job not done")
                return rec
            served = c.result_assignment(job)
            if np.array_equal(served, np.asarray(oracle)):
                rec["verdict"] = "identical"
            else:
                # documented degradation = quarantined input (the only
                # lossy absorb on these paths); anything else is wrong
                quarantined = False
                try:
                    with open(trace) as f:
                        quarantined = '"chunk_quarantined"' in f.read()
                except OSError:
                    pass
                rec["verdict"] = "degraded_documented" if quarantined \
                    else "wrong_forest"
            try:
                c.shutdown()
            except (ServerError, OSError):
                pass
        proc.wait(timeout=30)
        rec["daemon_rc"] = proc.returncode
        if proc.returncode != 0:
            rec["verdict"] = "unhandled_crash"
            rec["error"] = f"daemon exit rc={proc.returncode}"
        return rec
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=10)


def _spawn_durable_daemon(sock, trace, state_dir, err_f):
    env = {**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONPATH": REPO}
    return subprocess.Popen(
        [sys.executable, "-m", "sheep_tpu.server.daemon",
         "--socket", sock, "--trace", trace,
         "--state-dir", state_dir, "--checkpoint-every", "1",
         "--drain-grace-s", "30", "--heartbeat-secs", "0.2"],
        cwd=REPO, env=env, stderr=err_f)


def run_durable_leg(name: str, sig: int, graph: str, out_dir: str,
                    oracle) -> dict:
    """ISSUE 14: signal the durable daemon mid-build (SIGKILL for the
    restart leg, SIGTERM for the graceful drain), restart it on the
    same socket/journal, and require the job to RESUME — counter on
    the record — to a forest bit-equal to the clean oracle."""
    import numpy as np

    from sheep_tpu.obs.metrics import parse_prometheus
    from sheep_tpu.server.client import ServerError, SheepClient

    sock = os.path.join(out_dir, f"soak_{name}.sock")
    trace = os.path.join(out_dir, f"soak_{name}.jsonl")
    state_dir = os.path.join(out_dir, f"soak_{name}.state")
    err_path = os.path.join(out_dir, f"soak_{name}.err")
    rec = {"leg": name,
           "inject": "SIGKILL mid-build" if sig == signal.SIGKILL
           else "SIGTERM graceful drain mid-build"}
    err_f = open(err_path, "w")
    proc = _spawn_durable_daemon(sock, trace, state_dir, err_f)
    proc2 = None
    try:
        for _ in range(300):
            if os.path.exists(sock) or proc.poll() is not None:
                break
            time.sleep(0.2)
        if not os.path.exists(sock):
            rec["verdict"] = "unhandled_crash"
            rec["error"] = f"daemon never bound (rc={proc.poll()})"
            return rec
        with SheepClient(sock) as c:
            r = c.submit(graph, k=4, tenant="soak",
                         chunk_edges=DURABLE_CHUNK,
                         num_vertices=DURABLE_V, dispatch_batch=1,
                         return_assignment=True)
            job_id = r["job_id"]
            # land the signal INSIDE the build phase: a kill that
            # arrives after completion proves nothing
            landed = False
            for _ in range(4000):
                st = c.status(job_id)
                if st["state"] in ("done", "failed"):
                    break
                if st.get("phase") == "build" \
                        and st.get("steps", 0) >= 3:
                    landed = True
                    break
                time.sleep(0.005)
            if not landed:
                rec["verdict"] = "unhandled_crash"
                rec["error"] = (f"signal window missed: job reached "
                                f"{st.get('state')}/{st.get('phase')} "
                                f"before mid-build")
                return rec
            rec["killed_at_steps"] = st.get("steps")
        proc.send_signal(sig)
        proc.wait(timeout=120)
        rec["first_daemon_rc"] = proc.returncode
        if sig == signal.SIGTERM and proc.returncode != 0:
            rec["verdict"] = "unhandled_crash"
            rec["error"] = (f"graceful drain exited "
                            f"rc={proc.returncode}, want 0")
            return rec
        # restart on the SAME socket/journal/state dir; the stale
        # socket file (SIGKILL case) must be probed away and the
        # journaled job must come back resumable
        proc2 = _spawn_durable_daemon(sock, trace, state_dir, err_f)
        with SheepClient(sock, reconnect=40,
                         reconnect_base_s=0.3) as c:
            try:
                job = c.wait(job_id, timeout_s=300)
            except ServerError as e:
                rec["verdict"] = "unhandled_crash"
                rec["error"] = f"restarted daemon lost the job: {e}"
                return rec
            rec["state"] = job.get("state")
            metrics = parse_prometheus(c.metrics())
            rec["jobs_resumed"] = sum(
                v for _, v in
                metrics.get("sheepd_jobs_resumed_total", []))
            rec["restarts"] = sum(
                v for _, v in metrics.get("sheepd_restarts_total", []))
            if job.get("state") != "done":
                rec["verdict"] = "unhandled_crash"
                rec["error"] = job.get("error", "job not done")
                return rec
            served = c.result_assignment(job)
            rec["verdict"] = "identical" if np.array_equal(
                served, np.asarray(oracle)) else "wrong_forest"
            try:
                c.shutdown()
            except (ServerError, OSError):
                pass
        proc2.wait(timeout=60)
        rec["daemon_rc"] = proc2.returncode
        if proc2.returncode != 0:
            rec["verdict"] = "unhandled_crash"
            rec["error"] = f"restarted daemon exit rc={proc2.returncode}"
        return rec
    finally:
        for p in (proc, proc2):
            if p is not None and p.poll() is None:
                p.kill()
                p.wait(timeout=10)
        err_f.close()


def run_fleet_leg(graph: str, out_dir: str, oracle) -> dict:
    """ISSUE 16: two replicas behind the fleet client. Headroom
    routing must SPLIT concurrent jobs across both replicas, then
    replica a is SIGKILLed mid-build and every job must still finish
    via the reattach-idempotent failover resubmit — each served
    forest bit-equal to the clean oracle (including any answered from
    the survivor's result store)."""
    import numpy as np

    from sheep_tpu.server.client import (FleetClient, ServerError,
                                         SheepClient)

    rec = {"leg": "fleet", "inject": "SIGKILL replica a mid-build"}
    socks, procs, errs = [], [], []
    try:
        for tag in ("a", "b"):
            sock = os.path.join(out_dir, f"soak_fleet_{tag}.sock")
            trace = os.path.join(out_dir, f"soak_fleet_{tag}.jsonl")
            state = os.path.join(out_dir, f"soak_fleet_{tag}.state")
            err_f = open(os.path.join(out_dir,
                                      f"soak_fleet_{tag}.err"), "w")
            errs.append(err_f)
            socks.append(sock)
            procs.append(_spawn_durable_daemon(sock, trace, state,
                                               err_f))
        for _ in range(300):
            if all(os.path.exists(s) for s in socks):
                break
            if any(p.poll() is not None for p in procs):
                break
            time.sleep(0.2)
        if not all(os.path.exists(s) for s in socks):
            rec["verdict"] = "unhandled_crash"
            rec["error"] = "a fleet replica never bound"
            return rec
        with FleetClient(socks) as fleet:
            # three concurrent jobs; the short sleep lets each
            # replica's load gauges see the previous admit so the
            # headroom sort actually alternates
            jobs = []
            for _ in range(3):
                jobs.append(fleet.submit(
                    graph, k=4, tenant="fleet",
                    chunk_edges=DURABLE_CHUNK, num_vertices=DURABLE_V,
                    dispatch_batch=1, return_assignment=True))
                time.sleep(0.5)
            rec["route_counts"] = dict(fleet.route_counts)
            if len({r["endpoint"] for r in jobs}) < 2:
                rec["verdict"] = "unhandled_crash"
                rec["error"] = (f"headroom routing never split: "
                                f"{rec['route_counts']}")
                return rec
            # land the kill INSIDE a replica-a build (a kill after
            # completion would prove reattach, not failover)
            victim = next(r for r in jobs
                          if r["endpoint"] == socks[0])
            with SheepClient(socks[0]) as c:
                landed = False
                for _ in range(4000):
                    st = c.status(victim["job_id"])
                    if st["state"] in ("done", "failed"):
                        break
                    if st.get("phase") == "build" \
                            and st.get("steps", 0) >= 3:
                        landed = True
                        break
                    time.sleep(0.005)
            if not landed:
                rec["verdict"] = "unhandled_crash"
                rec["error"] = (f"kill window missed: victim reached "
                                f"{st.get('state')}/{st.get('phase')}")
                return rec
            rec["killed_at_steps"] = st.get("steps")
            pre_kill_counts = dict(fleet.route_counts)
            procs[0].kill()
            procs[0].wait(timeout=30)
            # every job must complete: replica-b's directly, replica
            # a's via failover resubmission to the survivor
            # wait on DESCRIPTORS: both replicas mint per-process job
            # ids, so the bare ids collide across the fleet
            for r in jobs:
                try:
                    job = fleet.wait(r, timeout_s=300)
                except ServerError as e:
                    rec["verdict"] = "unhandled_crash"
                    rec["error"] = f"fleet lost a job: {e}"
                    return rec
                if job.get("state") != "done":
                    rec["verdict"] = "unhandled_crash"
                    rec["error"] = job.get("error", "job not done")
                    return rec
                served = fleet.result_assignment(job)
                if not np.array_equal(served, np.asarray(oracle)):
                    rec["verdict"] = "wrong_forest"
                    return rec
            rec["route_counts"] = dict(fleet.route_counts)
            rec["failovers"] = sum(
                fleet.route_counts[ep] - pre_kill_counts.get(ep, 0)
                for ep in fleet.route_counts)
            # the survivor must still be serving, and shut down clean
            with SheepClient(socks[1]) as c:
                try:
                    c.ping()
                except (ServerError, OSError) as e:
                    rec["verdict"] = "unhandled_crash"
                    rec["error"] = f"survivor dead after failover: {e}"
                    return rec
                try:
                    c.shutdown()
                except (ServerError, OSError):
                    pass
        procs[1].wait(timeout=60)
        rec["daemon_rc"] = procs[1].returncode
        if procs[1].returncode != 0:
            rec["verdict"] = "unhandled_crash"
            rec["error"] = f"survivor exit rc={procs[1].returncode}"
            return rec
        rec["verdict"] = "identical"
        return rec
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait(timeout=10)
        for f in errs:
            f.close()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="sheepd fault mini-soak (oom + read + restart + "
                    "drain + fleet legs)")
    ap.add_argument("--out", default=None,
                    help="artifact dir (default: fresh temp dir)")
    args = ap.parse_args(argv)
    out_dir = args.out or tempfile.mkdtemp(prefix="sheep_served_soak.")
    os.makedirs(out_dir, exist_ok=True)

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    graph = os.path.join(out_dir, "soak.bin64")
    build_graph(graph)
    oracle = clean_oracle(graph)

    ok = True
    for name, inject in LEGS:
        rec = run_leg(name, inject, graph, out_dir, oracle)
        print(json.dumps(rec), flush=True)
        if rec["verdict"] not in ("identical", "degraded_documented"):
            ok = False
        if name == "oom" and not rec.get("dispatch_retries"):
            # the injected fault must have been absorbed ON RECORD —
            # a silently-clean run means the injection missed and the
            # soak proved nothing
            print(json.dumps({"leg": name,
                              "error": "no dispatch_retries trail — "
                                       "injection never fired"}),
                  flush=True)
            ok = False

    # the durable legs (ISSUE 14): kill -9 + restart, then graceful
    # drain + restart, both resuming to the clean oracle's bits
    big_graph = os.path.join(out_dir, "soak_big.bin64")
    build_graph(big_graph, n=DURABLE_V, m=DURABLE_E)
    big_oracle = clean_oracle(big_graph, n=DURABLE_V,
                              chunk_edges=DURABLE_CHUNK)
    for name, sig in (("restart", signal.SIGKILL),
                      ("drain", signal.SIGTERM)):
        rec = run_durable_leg(name, sig, big_graph, out_dir,
                              big_oracle)
        print(json.dumps(rec), flush=True)
        if rec["verdict"] not in ("identical", "degraded_documented"):
            ok = False
        if rec.get("verdict") == "identical" \
                and not rec.get("jobs_resumed"):
            print(json.dumps({"leg": name,
                              "error": "no sheepd_jobs_resumed_total "
                                       "trail — the restart never "
                                       "resumed anything"}),
                  flush=True)
            ok = False

    # the fleet leg (ISSUE 16): two replicas, headroom-split jobs,
    # SIGKILL one replica mid-build, failover finishes everything
    rec = run_fleet_leg(big_graph, out_dir, big_oracle)
    print(json.dumps(rec), flush=True)
    if rec["verdict"] not in ("identical", "degraded_documented"):
        ok = False
    if rec.get("verdict") == "identical" and not rec.get("failovers"):
        print(json.dumps({"leg": "fleet",
                          "error": "no failover resubmit happened — "
                                   "the kill proved nothing"}),
              flush=True)
        ok = False
    print(json.dumps({"soak": "served", "ok": ok, "out": out_dir}),
          flush=True)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
