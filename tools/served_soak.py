#!/usr/bin/env python
"""Served-job mini-soak (ISSUE 10 satellite; chaos_soak's pattern
applied to sheepd): inject one OOM-class fault and one read fault into
served jobs and assert the DAEMON survives with the job verdict
``identical`` or ``degraded_documented``.

    python tools/served_soak.py [--out DIR]

Two legs, each a REAL ``sheepd`` subprocess on a unix socket over a
real on-disk graph (so the edgestream read points are live):

    oom    SHEEP_FAULT_INJECT=oom@dispatch:1 — RESOURCE_EXHAUSTED at
           the first issued dispatch of the served build; the per-job
           retry layer must degrade/re-fold bit-identically and leave
           the ``dispatch_retries`` trail in the job diagnostics.
    read   SHEEP_FAULT_INJECT=read@read:2 — a torn physical read; the
           edgestream's bounded transient retry absorbs it below the
           scheduler entirely.

Per leg the verdict is exactly chaos_soak's taxonomy:

    identical            served assignment bit-equals the clean oracle
    degraded_documented  differs, but the job carries a documented
                         degradation marker (quarantined chunks)
    wrong_forest         differs with NO documentation — a real bug
    unhandled_crash      the job failed, the daemon died, or it
                         stopped answering pings after the fault

After each job the daemon must still answer ``ping`` (the fault
degraded the JOB, not the service) and must shut down rc=0. Exit 0
iff every leg is identical/degraded_documented; wired tier-1 by
tests/test_server.py.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

LEGS = (
    ("oom", "oom@dispatch:1"),
    ("read", "read@read:2"),
)


def build_graph(path: str) -> None:
    from sheep_tpu.io import formats, generators

    formats.write_edges(path, generators.random_graph(512, 4096, seed=7))


def clean_oracle(path: str):
    """The fault-free reference assignment, computed in THIS process
    (the daemons never see a fault-free run — the oracle must not)."""
    from sheep_tpu import _partition_stream
    from sheep_tpu.io.edgestream import open_input

    with open_input(path, n_vertices=512) as es:
        res = _partition_stream(es, 4, backend="tpu", chunk_edges=512,
                                comm_volume=False)
    return res.assignment


def run_leg(name: str, inject: str, graph: str, out_dir: str,
            oracle) -> dict:
    import numpy as np

    from sheep_tpu.server.client import ServerError, SheepClient

    sock = os.path.join(out_dir, f"soak_{name}.sock")
    trace = os.path.join(out_dir, f"soak_{name}.jsonl")
    err_path = os.path.join(out_dir, f"soak_{name}.err")
    env = {**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONPATH": REPO,
           "SHEEP_FAULT_INJECT": inject, "SHEEP_RETRY_BASE_S": "0.01"}
    rec = {"leg": name, "inject": inject}
    with open(err_path, "w") as err_f:
        proc = subprocess.Popen(
            [sys.executable, "-m", "sheep_tpu.server.daemon",
             "--socket", sock, "--trace", trace,
             "--heartbeat-secs", "0.2"],
            cwd=REPO, env=env, stderr=err_f)
    try:
        for _ in range(150):
            if os.path.exists(sock) or proc.poll() is not None:
                break
            time.sleep(0.2)
        if not os.path.exists(sock):
            rec["verdict"] = "unhandled_crash"
            rec["error"] = f"daemon never bound (rc={proc.poll()})"
            return rec
        with SheepClient(sock) as c:
            try:
                r = c.submit(graph, k=4, tenant="soak",
                             chunk_edges=512, num_vertices=512,
                             return_assignment=True)
                job = c.wait(r["job_id"], timeout_s=120)
            except ServerError as e:
                rec["verdict"] = "unhandled_crash"
                rec["error"] = f"daemon refused the job: {e}"
                return rec
            rec["state"] = job.get("state")
            diags = (job.get("results") or [{}])[0].get(
                "diagnostics", {})
            rec["dispatch_retries"] = diags.get("dispatch_retries")
            # the daemon must still be serving AFTER the fault
            try:
                c.ping()
            except (ServerError, OSError) as e:
                rec["verdict"] = "unhandled_crash"
                rec["error"] = f"daemon dead after fault: {e}"
                return rec
            if job.get("state") != "done":
                rec["verdict"] = "unhandled_crash"
                rec["error"] = job.get("error", "job not done")
                return rec
            served = c.result_assignment(job)
            if np.array_equal(served, np.asarray(oracle)):
                rec["verdict"] = "identical"
            else:
                # documented degradation = quarantined input (the only
                # lossy absorb on these paths); anything else is wrong
                quarantined = False
                try:
                    with open(trace) as f:
                        quarantined = '"chunk_quarantined"' in f.read()
                except OSError:
                    pass
                rec["verdict"] = "degraded_documented" if quarantined \
                    else "wrong_forest"
            try:
                c.shutdown()
            except (ServerError, OSError):
                pass
        proc.wait(timeout=30)
        rec["daemon_rc"] = proc.returncode
        if proc.returncode != 0:
            rec["verdict"] = "unhandled_crash"
            rec["error"] = f"daemon exit rc={proc.returncode}"
        return rec
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=10)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="sheepd fault mini-soak (one oom + one read leg)")
    ap.add_argument("--out", default=None,
                    help="artifact dir (default: fresh temp dir)")
    args = ap.parse_args(argv)
    out_dir = args.out or tempfile.mkdtemp(prefix="sheep_served_soak.")
    os.makedirs(out_dir, exist_ok=True)

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    graph = os.path.join(out_dir, "soak.bin64")
    build_graph(graph)
    oracle = clean_oracle(graph)

    ok = True
    for name, inject in LEGS:
        rec = run_leg(name, inject, graph, out_dir, oracle)
        print(json.dumps(rec), flush=True)
        if rec["verdict"] not in ("identical", "degraded_documented"):
            ok = False
        if name == "oom" and not rec.get("dispatch_retries"):
            # the injected fault must have been absorbed ON RECORD —
            # a silently-clean run means the injection missed and the
            # soak proved nothing
            print(json.dumps({"leg": name,
                              "error": "no dispatch_retries trail — "
                                       "injection never fired"}),
                  flush=True)
            ok = False
    print(json.dumps({"soak": "served", "ok": ok, "out": out_dir}),
          flush=True)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
