#!/usr/bin/env python
"""Sweep the adaptive-fixpoint schedule on the real chip (or cpu-jax).

The build phase dominates the headline bench (BASELINE.md roofline:
~8 s/full-depth round at RMAT-22 on the axon v5e), and its cost is
~lift_levels x active-buffer-width gathers per round — so the schedule
knobs (cheap low-lift warm rounds, compaction cadence, rounds per
segment, chunk size) are where single-chip throughput lives. This tool
folds the same RMAT stream under each candidate schedule and reports
build-phase seconds + round/segment counts as JSON lines; every
candidate produces the identical forest (asserted), so the fastest line
wins outright.

Usage:
    python tools/tune_fixpoint.py [--scale 20] [--ef 16]
        [--chunk-logs 24] [--platform cpu] [--quick]
"""

from __future__ import annotations

import argparse
import itertools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def log(*a):
    print(*a, file=sys.stderr, flush=True)


WARM_SCHEDULES = {
    "none": (),
    "w1": ((1, 1),),     # near-pure retire round (scatter + 1-step climb)
    "w11": ((2, 1),),
    "w4": ((1, 4),),
    "w44": ((2, 4),),
    "w48": ((1, 4), (1, 8)),
    "w248": ((1, 2), (1, 4), (1, 8)),
    "w8": ((1, 8),),
    "w88": ((2, 8),),
}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--scale", type=int, default=20)
    ap.add_argument("--ef", type=int, default=16)
    ap.add_argument("--chunk-logs", default="24")
    ap.add_argument("--platform", default=None)
    ap.add_argument("--warm", default=None,
                    help="comma list of warm-schedule names "
                         f"(default: all of {list(WARM_SCHEDULES)})")
    ap.add_argument("--segment-rounds", default="2")
    ap.add_argument("--lift-levels", default="0",
                    help="comma list; 0 = full depth ceil(log2 V)")
    ap.add_argument("--tail-divisors", default="8",
                    help="comma list d: host_tail_threshold = C/d "
                         "(0 = keep the auto default)")
    ap.add_argument("--stale", default="1",
                    help="comma list of 0/1: per-segment stale lifting "
                         "tables on full exact-descent segments "
                         "(BASELINE.md 'stale lifting tables' A/B)")
    ap.add_argument("--stale-reuse", default="1",
                    help="comma list of K >= 1: full segments per "
                         "lifting-stack rebuild (elim.py "
                         "fold_segment_pos_stale; only with --stale 1)")
    ap.add_argument("--carry", default="0",
                    help="comma list of 0/1: carry-over tails between "
                         "chunks instead of per-chunk host tails "
                         "(BASELINE.md 'carry-over tails' A/B)")
    ap.add_argument("--overlap", default="0",
                    help="comma list of 0/1: resolve host tails in a "
                         "worker thread overlapped with the next chunk's "
                         "device rounds, delta re-injection "
                         "(tail_overlap A/B; excludes --carry 1)")
    ap.add_argument("--reps", type=int, default=1)
    args = ap.parse_args()

    if args.platform:
        from sheep_tpu.utils.platform import pin_platform

        pin_platform(args.platform)

    import numpy as np
    import jax
    import jax.numpy as jnp

    from sheep_tpu.backends.tpu_backend import pad_chunk
    from sheep_tpu.io import generators
    from sheep_tpu.ops import degrees as degrees_ops
    from sheep_tpu.ops import elim as elim_ops
    from sheep_tpu.ops import order as order_ops

    plat = jax.default_backend()
    n = 1 << args.scale
    t0 = time.perf_counter()
    edges = generators.rmat(args.scale, args.ef, seed=42)
    log(f"platform={plat} RMAT-{args.scale} ef={args.ef} "
        f"E={len(edges):,} (gen {time.perf_counter() - t0:.0f}s)")

    # degrees + order once (identical for every candidate)
    deg = degrees_ops.init_degrees(n)
    for i in range(0, len(edges), 1 << 24):
        deg = degrees_ops.degree_chunk(
            deg, jnp.asarray(pad_chunk(edges[i:i + (1 << 24)],  # sheeplint: h2d-ok, spill-ok (one-shot sweep-tool pass)
                                       1 << 24, n)),
            n)
    pos, order = order_ops.elimination_order(deg[:n], n)
    pos_host = np.asarray(pos[:n])  # sheeplint: sync-ok

    def run(chunk_log, warm_name, seg_rounds, lift, tail_div, stale, carry,
            overlap, reuse=1):
        cs = 1 << chunk_log
        # pre-pad + pre-upload all chunks so only fold time is measured
        dev_chunks = [jnp.asarray(pad_chunk(edges[i:i + cs], cs, n))
                      for i in range(0, len(edges), cs)]
        np.asarray(dev_chunks[-1][:2])  # settle uploads
        from contextlib import nullcontext

        stats: dict = {}
        P = jnp.full(n + 1, n, dtype=jnp.int32)
        total = 0
        carried = None
        ov_ctx = elim_ops.TailOverlap(n, pos_host) if overlap \
            else nullcontext()
        t0 = time.perf_counter()
        with ov_ctx as ov:
            for d in dev_chunks:
                if overlap:
                    ov.drain(False)
                    carried = ov.take_inject()
                step = elim_ops.build_chunk_step_adaptive_pos(
                    P, d, pos, pos_host, n,
                    lift_levels=lift,
                    segment_rounds=seg_rounds,
                    warm_schedule=WARM_SCHEDULES[warm_name], stats=stats,
                    host_tail_threshold=(cs // tail_div if tail_div else 0),
                    stale_tables=bool(stale), stale_reuse=reuse,
                    carry=carried, carry_out=bool(carry) or bool(overlap))
                if carry:
                    P, rounds, carried = step
                elif overlap:
                    P, rounds, tail = step
                    carried = None
                    if int(tail[0].shape[0]):
                        ov.submit(P, tail[0], tail[1])
                else:
                    P, rounds = step
                total += int(rounds)
            if overlap:
                ov.drain(True)
                carried = ov.take_inject()
        if carried is not None and int(carried[0].shape[0]):
            P, rounds = elim_ops.fold_edges_adaptive_pos(
                P, carried[0], carried[1], n, lift_levels=lift,
                segment_rounds=seg_rounds,
                host_tail_threshold=(cs // tail_div if tail_div else 0),
                pos_host=pos_host, stats=stats, stale_tables=bool(stale),
                stale_reuse=reuse)
            total += int(rounds)
        np.asarray(P[:8])  # force completion (block_until_ready lies
        # through the tunnel; see tools/microbench_fixpoint.py)
        dt = time.perf_counter() - t0
        return P, dt, total, stats

    warm_names = (args.warm.split(",") if args.warm
                  else list(WARM_SCHEDULES))
    chunk_logs = [int(x) for x in args.chunk_logs.split(",")]
    seg_rounds_list = [int(x) for x in args.segment_rounds.split(",")]
    lifts = [int(x) for x in args.lift_levels.split(",")]
    tail_divs = [int(x) for x in args.tail_divisors.split(",")]
    stales = [int(x) for x in args.stale.split(",")]
    reuses = [int(x) for x in args.stale_reuse.split(",")]
    carries = [int(x) for x in args.carry.split(",")]
    overlaps = [int(x) for x in args.overlap.split(",")]

    reference = None
    best = None
    for cl, wn, sr, lv, td, st, ru, ca, ov in itertools.product(
            chunk_logs, warm_names, seg_rounds_list, lifts, tail_divs,
            stales, reuses, carries, overlaps):
        if ca and ov:
            continue  # mutually exclusive tail strategies
        if not st and ru > 1:
            continue  # reuse cadence only exists on the stale path
        dts = []
        for rep in range(args.reps):
            P, dt, total, stats = run(cl, wn, sr, lv, td, st, ca, ov,
                                      reuse=ru)
            dts.append(dt)
        dt = min(dts)
        P_np = np.asarray(P)
        if reference is None:
            reference = P_np
        else:
            assert np.array_equal(reference, P_np), \
                (f"config warm={wn} seg={sr} L={lv} td={td} stale={st} "
                 f"reuse={ru} carry={ca} overlap={ov} changed the forest!")
        line = {"chunk_log": cl, "warm": wn, "segment_rounds": sr,
                "lift_levels": lv, "tail_div": td, "stale": st,
                "stale_reuse": ru, "carry": ca, "overlap": ov,
                "build_s": round(dt, 2), "rounds": total,
                "platform": plat, **{k: int(v) for k, v in stats.items()}}
        print(json.dumps(line), flush=True)
        log(f"chunk=2^{cl} warm={wn:5s} seg={sr} L={lv} td={td} st={st} "
            f"ru={ru} ca={ca} ov={ov}: {dt:7.2f}s rounds={total} {stats}")
        if best is None or dt < best[0]:
            best = (dt, line)
    if best is None:
        log("no runnable configs (every combination was skipped)")
        sys.exit(2)
    log(f"best: {best[1]}")


if __name__ == "__main__":
    main()
