#!/usr/bin/env python
"""Compare two bench contract captures and flag perf regressions —
the start of a perf-CI gate.

    python tools/bench_regress.py                      # latest two BENCH_*.json
    python tools/bench_regress.py NEW.json OLD.json    # explicit pair
    python tools/bench_regress.py --threshold 0.10

Accepts either shape on both sides: a driver-written ``BENCH_*.json``
artifact (``{"n": ..., "parsed": {contract line}}``) or a raw bench.py
output line/file (``{"metric": ..., "value": ...}``). Gated fields,
each compared only when present in BOTH captures:

    value, vs_baseline, r_colo_est    higher is better (relative drop
                                      beyond --threshold regresses)
    host_syncs, device_rounds,        lower is better (relative rise
    host_blocked_ms, h2d_blocked_ms,  beyond --threshold regresses —
    update_request_s,                 the resident-partition delta-fold
                                      wall (ISSUE 15), split since
    update_fold_s, update_score_s,    ISSUE 17 into the device fold vs
                                      the O(Δ) scored refresh (its
                                      epoch_scale_x2 probe rides
                                      info-only);
    sharded_update_request_s          the same scored epoch through the
                                      multi-device lockstep fold +
                                      distributed rescore (ISSUE 19);
    warm_up_s, warm_request_s,        warm_up_s is the cold-request jit
                                      tax and warm_request_s the warm
                                      served-request wall — the pair
                                      the sheepd server mode amortizes
                                      (ISSUE 10); a rise in either is a
                                      warm-path latency regression;
    dispatch_retries                  dispatch counts are deterministic,
                                      so a rise is a real scheduling
                                      change, not noise; host_blocked_ms
                                      is the dispatch pipeline's
                                      host-stall wall, the quantity the
                                      in-flight overlap exists to
                                      shrink; dispatch_retries is the
                                      fault-tolerance layer's
                                      graceful-degradation count — a
                                      healthy capture has 0, and any
                                      movement off 0 is gated
                                      absolutely)

Degradation info fields (never gated, always reported):
``degraded_dispatch_batch`` / ``degraded_inflight`` (the reduced knobs
after an OOM backoff), ``device_loss_recoveries``, and
``checkpoint_degraded`` (lossy checkpoint recoveries) — environmental
consequences that must be VISIBLE in the perf trajectory without
false-alarming the gate.

Contract fields present in exactly ONE capture compare nothing; they
are listed on a ``skipped-incomparable: <names>`` line (and in the
``skipped`` JSON field) so a cpu-jax fallback capture — which emits
fewer fields than a real-chip one — reads as the PARTIAL pass it is,
not a full-coverage green.

Link-state fields (rtt_ms, h2d_mbs, d2h_mbs) and device_gap_ms (device
idle between executions — collapses with pipelining but swings with
link quality) are environmental and reported but never gated. Two captures whose ``metric`` strings differ
(different RMAT scale or platform — e.g. a cpu-jax fallback row vs a
real-chip row) are NOT comparable: the tool says so and exits 0 unless
``--force``, because a false regression alarm that fires on every
tunnel outage would get the gate deleted within a week.

Exit codes: 0 pass (or not comparable), 1 usage/IO error,
2 regression detected.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

HIGHER_BETTER = ("value", "vs_baseline", "r_colo_est")
# host_blocked_ms is wall-derived (like value) and so can swing with
# link quality within one platform — gated anyway per the contract: a
# sustained rise is the dispatch pipeline regressing, and same-metric
# comparison plus the threshold absorb ordinary swings.
# dispatch_retries (ISSUE 9) gates graceful degradation: a healthy
# capture retries 0 times, so ANY rise (0 -> N is gated absolutely by
# the old==0 rule below) means the bench survived faults it used to
# not have — visible, not silent.
# h2d_blocked_ms (ISSUE 12) is the staged-ring underrun wall — the
# synchronous-upload tax the ring removed; a healthy depth>=2 capture
# holds it near 0, and the old==0 absolute rule below gates any
# reappearance. On the timed leg's device-stream input it is exactly 0
# (zero host bytes per chunk).
# update_request_s (ISSUE 15) is the resident-partition delta-fold
# wall — the O(Δ) promise of the incremental subsystem, gated with
# the warm_request_s convention (a rise is the update path slowing);
# its companion `compactions` count is info-only below (compactions
# are workload consequences, not regressions).
# update_fold_s / update_score_s (ISSUE 17) split that wall: the
# device delta-fold vs the scored refresh. update_score_s is THE
# number incremental scoring exists for — O(Δ) accounting holds it
# flat where full rescoring pays O(edges) per epoch — so both halves
# gate lower-better; their epoch_scale_x2 companion (scored-epoch
# wall on a 2x base, ~1.0 when the O(Δ) path holds) rides info-only
# as a property probe, not a perf series.
# cached_request_s (ISSUE 16) is the content-addressed result-store
# answer wall — a repeat submit served with zero build steps; its
# contract bar is >= 10x under warm_request_s, so a rise means the
# store read/decode path itself is slowing, gated like the warm path.
# sharded_update_request_s (ISSUE 19) is the same scored delta epoch
# through the multi-device lockstep fold + distributed rescore — the
# per-epoch cost of a resident SHARDED partition; gated lower-better
# with the update_request_s convention.
# oocore_request_s (ISSUE 20) is the build wall under a residency
# budget clamped to ~half the modeled working set — the price of
# running out-of-core (evict + reload through the disk tier); a rise
# means the spill/reload path is slowing, gated lower-better. Its
# spill_* companions describe the constraint (how much was evicted /
# re-uploaded / held resident), not a perf series — info-only below.
LOWER_BETTER = ("host_syncs", "device_rounds", "host_blocked_ms",
                "h2d_blocked_ms", "dispatch_retries", "warm_up_s",
                "warm_request_s", "cached_request_s",
                "update_request_s", "update_fold_s",
                "update_score_s", "sharded_update_request_s",
                "oocore_request_s")
# degraded_* and checkpoint_degraded are consequences of faults the
# environment injected, not regressions of the code under test — they
# ride as info so the degradation is VISIBLE in the perf trajectory
# while only the retry count itself gates
INFO_ONLY = ("rtt_ms", "h2d_mbs", "d2h_mbs", "dispatch_batch",
             "inflight_depth", "inflight_discards", "device_gap_ms",
             "h2d_staged_ms", "h2d_staged_bytes", "h2d_ring_depth",
             "device_stream_chunks",
             "degraded_dispatch_batch", "degraded_inflight",
             "degraded_h2d_ring",
             "device_loss_recoveries", "checkpoint_degraded",
             "cold_request_s", "compactions", "epoch_scale_x2",
             "spill_evictions", "spill_reload_bytes",
             "spill_resident_bytes")


def load_capture(path: str):
    """Contract-line dict from either artifact shape, or None with a
    reason string."""
    try:
        with open(path) as f:
            text = f.read()
    except OSError as e:
        return None, f"cannot read {path}: {e}"
    line = None
    try:
        doc = json.loads(text)
    except json.JSONDecodeError:
        # bench.py stdout style: JSONL, contract line last
        for raw in reversed(text.splitlines()):
            raw = raw.strip()
            if not raw:
                continue
            try:
                cand = json.loads(raw)
            except json.JSONDecodeError:
                continue
            if isinstance(cand, dict) and "value" in cand:
                line = cand
                break
        if line is None:
            return None, f"{path}: no parseable JSON contract line"
        return line, None
    if isinstance(doc, dict) and "parsed" in doc:
        line = doc["parsed"]
        if not isinstance(line, dict):
            return None, f"{path}: driver artifact has parsed=null " \
                         f"(the bench run produced no contract line)"
        return line, None
    if isinstance(doc, dict) and "value" in doc:
        return doc, None
    return None, f"{path}: unrecognized capture shape"


def compare(new: dict, old: dict, threshold: float) -> dict:
    """{"comparable": bool, "rows": [...], "regressions": [...],
    "skipped": [...]} — ``skipped`` lists contract fields present in
    exactly ONE capture (a cpu-jax fallback run emits fewer fields
    than a real-chip one): those comparisons are vacuous, and a
    vacuous pass that LOOKS like a full pass hides exactly the partial
    coverage it came from, so the caller prints them."""
    out = {"comparable": True, "reason": None, "rows": [],
           "regressions": [], "skipped": []}
    nm, om = new.get("metric"), old.get("metric")
    if nm != om:
        out["comparable"] = False
        out["reason"] = (f"metric mismatch: new={nm!r} vs old={om!r} "
                         f"(different scale/platform — no fair compare)")
        return out
    if not new.get("value") or not old.get("value"):
        out["comparable"] = False
        out["reason"] = "one capture has value 0/null (a failed run)"
        return out

    def _num(v):
        return isinstance(v, (int, float)) and not isinstance(v, bool)

    for field in HIGHER_BETTER + LOWER_BETTER + INFO_ONLY:
        a, b = new.get(field), old.get(field)
        if not isinstance(a, (int, float)) or not isinstance(b, (int, float)):
            if _num(a) != _num(b):
                out["skipped"].append(field)
            continue
        # old == 0: no relative change exists, but ANY movement off zero
        # is gated absolutely — host_syncs 0 -> 500 must not pass just
        # because the ratio is undefined
        rel = (a - b) / abs(b) if b else None
        worse = (a < b) if field in HIGHER_BETTER else (a > b)
        row = {"field": field, "old": b, "new": a,
               "rel_change": round(rel, 4) if rel is not None else None,
               "gated": field not in INFO_ONLY}
        regressed = worse if rel is None else (
            rel < -threshold if field in HIGHER_BETTER
            else rel > threshold)
        if field in INFO_ONLY:
            row["verdict"] = "info"
        elif regressed:
            row["verdict"] = "REGRESSION"
            out["regressions"].append(row)
        else:
            row["verdict"] = "ok"
        out["rows"].append(row)
    return out


def find_latest_pair(pattern: str):
    files = sorted(glob.glob(pattern))
    if len(files) < 2:
        return None
    return files[-1], files[-2]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Flag bench-contract regressions between two "
                    "captures (perf-CI gate).")
    ap.add_argument("new", nargs="?", default=None,
                    help="newer capture (default: latest BENCH_*.json)")
    ap.add_argument("old", nargs="?", default=None,
                    help="older capture (default: second-latest)")
    ap.add_argument("--threshold", type=float, default=0.15,
                    help="relative change tolerated before a gated "
                         "field regresses (default 0.15)")
    ap.add_argument("--glob", default=None,
                    help="artifact pattern for auto-discovery "
                         "(default: BENCH_*.json next to this repo)")
    ap.add_argument("--force", action="store_true",
                    help="gate even when the metric strings differ")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)

    if (args.new is None) != (args.old is None):
        ap.error("pass both NEW and OLD, or neither (auto-discovery)")
    if args.new is None:
        pattern = args.glob or os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "BENCH_*.json")
        pair = find_latest_pair(pattern)
        if pair is None:
            print(f"error: need >= 2 artifacts matching {pattern}",
                  file=sys.stderr)
            return 1
        args.new, args.old = pair

    new, err = load_capture(args.new)
    if err:
        print(f"error: {err}", file=sys.stderr)
        return 1
    old, err = load_capture(args.old)
    if err:
        print(f"error: {err}", file=sys.stderr)
        return 1

    res = compare(new, old, args.threshold)
    if args.force and not res["comparable"]:
        forced_reason = res["reason"]
        new2 = dict(new)
        old2 = dict(old)
        new2["metric"] = old2["metric"] = "(forced)"
        new2["value"] = new2.get("value") or 1e-12
        old2["value"] = old2.get("value") or 1e-12
        res = compare(new2, old2, args.threshold)
        res["reason"] = f"forced compare despite: {forced_reason}"

    if args.json:
        json.dump({"new": args.new, "old": args.old,
                   "threshold": args.threshold, **res},
                  sys.stdout, indent=1)
        print()
    else:
        print(f"new: {args.new}")
        print(f"old: {args.old}")
        if not res["comparable"]:
            print(f"not comparable: {res['reason']}")
            print("verdict: PASS (vacuous — nothing gated)")
            return 0
        if res.get("reason"):
            print(f"note: {res['reason']}")
        print(f"{'field':<16}{'old':>14}{'new':>14}{'change':>10}  verdict")
        for row in res["rows"]:
            change = (f"{100 * row['rel_change']:>9.1f}%"
                      if row["rel_change"] is not None else f"{'n/a':>10}")
            print(f"{row['field']:<16}{row['old']:>14,.3f}"
                  f"{row['new']:>14,.3f}{change}"
                  f"  {row['verdict']}")
        if res.get("skipped"):
            # fields one capture lacks compared nothing — say so, or a
            # cpu-jax fallback run reads as a full-coverage pass
            print(f"skipped-incomparable: {', '.join(res['skipped'])}")
        if res["regressions"]:
            names = ", ".join(r["field"] for r in res["regressions"])
            print(f"verdict: REGRESSION beyond {args.threshold:.0%} "
                  f"in: {names}")
        else:
            print(f"verdict: PASS (no gated field moved beyond "
                  f"{args.threshold:.0%})")
    if not res["comparable"]:
        return 0
    return 2 if res["regressions"] else 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:  # |head et al. closing stdout is not an error
        sys.exit(0)
