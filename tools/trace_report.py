#!/usr/bin/env python
"""Render an obs trace (JSONL from --trace / obs.tracing) as a
per-span self/total-time tree with counter deltas.

    python tools/trace_report.py out.jsonl [--check] [--json]
    python tools/trace_report.py out.jsonl --last-errors [N]
    python tools/trace_report.py run_a.jsonl run_b.jsonl   # + attribution
    python tools/trace_report.py --stitch client.jsonl a.jsonl b.jsonl

One trace: manifest summary, the span tree (spans with the same name
under the same parent aggregate into one row with a count), per-row
total seconds / self seconds (total minus children), per-row counter
deltas net of children, heartbeat summary, final counters, scores.
Spans that STARTED but never ENDED are flagged ``UNCLOSED`` — the
signature of a run that died mid-flight (the round-5 s30 soak's
failure mode), with the elapsed time from span start to the last
record in the file as the lower-bound duration. ``--last-errors``
renders the flight-recorder dumps (ISSUE 11) beside them: the final N
buffered events before each failed job / fault injection / daemon
shutdown, captured even when full tracing was off.

Two traces: additionally solves the count x round-cost dispatch
attribution (sheep_tpu.utils.metrics.solve_dispatch_attribution) from
each trace's build wall + host_syncs/device_rounds counters — two runs
of the same build at different --dispatch-batch yield the per-dispatch
overhead vs per-round device cost split.

``--stitch FILE...`` (ISSUE 18) merges SEVERAL trace files — a fleet
client's plus each replica daemon's — by propagated trace id into one
cross-process tree per fleet request: spans carrying a ``trace`` attr
(and their local descendants) group by that id, and a span whose
``remote_parent`` attr names a client span's local id grafts under
that span even though the two live in different files. A failover
renders as two ``job:`` spans under one ``fleet_request`` — the
killed replica's UNCLOSED, the survivor's closed. Unlike the
single-trace report, stitch reads EVERY appended run in each file (a
restarted daemon's runs all hold grafts). With ``--check`` it exits 3
unless >= 1 trace id is present and every trace forms exactly one
tree (no unmatched remote_parent, no second root); UNCLOSED spans are
fine there — they ARE the failover seam.

``--check`` (without --stitch) exits non-zero unless the trace is
well-formed AND complete: parses, has a manifest, every span end
matches a start, no span is left unclosed, and >= 1 heartbeat exists
(the obs_smoke gate).

Exit codes: 0 ok; 1 usage/IO; 2 malformed trace (an end without a
start, unparseable beyond stray truncation); 3 --check unsatisfied.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _read_events(path: str) -> tuple:
    """(events, bad line numbers). A truncated LAST line (the process
    died mid-write) is tolerated silently; any other unparseable line
    is reported."""
    all_events = []
    bad_lines = []
    with open(path) as f:
        lines = f.read().splitlines()
    for i, line in enumerate(lines):
        if not line.strip():
            continue
        try:
            all_events.append(json.loads(line))
        except json.JSONDecodeError:
            if i == len(lines) - 1:
                continue  # mid-write kill; everything before it counts
            bad_lines.append(i + 1)
    return all_events, bad_lines


def _segment_runs(all_events: list) -> list:
    """Split an appended-to trace stream into per-run segments.

    Run boundaries: a span_start whose id already exists in the
    current segment (ids restart at 1 per Tracer) OR a manifest
    arriving when every current span is closed. The open-span
    condition matters: multi-host traces legitimately emit the
    manifest AFTER the root span opened (deferred until
    jax.distributed.initialize) — splitting there would orphan the
    root's span_end and mis-report a valid trace as malformed."""
    segments: list = [[]]
    seen_ids: set = set()
    open_ids: set = set()
    for e in all_events:
        ev = e.get("event")
        new_run = (ev == "span_start" and e.get("id") in seen_ids) or \
            (ev == "manifest" and seen_ids and not open_ids)
        if new_run and segments[-1]:
            if ev == "span_start":
                # the new run's manifest (and trailing records) came
                # before its first span — carry them over; span events
                # themselves anchor segments, so only the tail past the
                # previous segment's last span event can move
                seg = segments[-1]
                last_span = max((i for i, x in enumerate(seg)
                                 if x.get("event") in ("span_start",
                                                       "span_end")),
                                default=-1)
                mans = [i for i, x in enumerate(seg)
                        if x.get("event") == "manifest" and i > last_span]
                carried: list = []
                if mans:
                    carried = seg[mans[0]:]
                    del seg[mans[0]:]
                segments.append(carried)
            else:
                segments.append([])
            seen_ids = set()
            open_ids = set()
        if ev == "span_start":
            seen_ids.add(e.get("id"))
            open_ids.add(e.get("id"))
        elif ev == "span_end":
            open_ids.discard(e.get("id"))
        segments[-1].append(e)
    return segments


def _build_spans(events: list) -> tuple:
    """One run's events -> (spans by id, roots, unclosed, orphan
    ends). Unclosed spans get a lower-bound duration (span start to
    the run's last record) and an ``unclosed`` flag."""
    spans: dict = {}  # id -> node
    orphan_ends = []
    last_ts = max((e.get("ts", 0) for e in events), default=0)
    for e in events:
        ev = e.get("event")
        if ev == "span_start":
            spans[e["id"]] = {
                "id": e["id"], "name": e.get("span", "?"),
                "parent": e.get("parent"), "ts": e.get("ts", 0),
                "attrs": {k: v for k, v in e.items()
                          if k not in ("event", "ts", "span", "id",
                                       "parent")},
                "secs": None, "counters": {}, "children": []}
        elif ev == "span_end":
            node = spans.get(e.get("id"))
            if node is None:
                orphan_ends.append(e.get("id"))
                continue
            node["secs"] = e.get("secs", 0.0)
            node["counters"] = e.get("counters", {})
            # span_end is where annotate()d attrs land — fold any the
            # start record lacked (reattach trace adoption, ISSUE 18)
            for k, v in e.items():
                if k not in ("event", "ts", "span", "id", "parent",
                             "secs", "counters"):
                    node["attrs"].setdefault(k, v)
    roots = []
    for node in spans.values():
        parent = spans.get(node["parent"])
        if parent is not None:
            parent["children"].append(node)
        else:
            roots.append(node)
    unclosed = [n for n in spans.values() if n["secs"] is None]
    for n in unclosed:
        # lower bound: span start to the last record the run managed
        n["secs"] = max(0.0, round(last_ts - n["ts"], 3))
        n["unclosed"] = True
    return spans, roots, unclosed, orphan_ends


def parse_trace(path: str) -> dict:
    """Parse one trace file into {events, spans, roots, errors...}.

    --trace appends, so one file may hold SEVERAL runs; each run's span
    ids restart at 1. The stream is segmented into runs
    (:func:`_segment_runs`) and the LAST run is reported, with
    ``n_runs`` recording how many the file holds — merging them would
    attach run 2's children to run 1's ids and silently corrupt every
    number in the report. span_end without a matching span_start marks
    the trace malformed."""
    all_events, bad_lines = _read_events(path)
    segments = _segment_runs(all_events)
    events = segments[-1]
    spans, roots, unclosed, orphan_ends = _build_spans(events)
    return {
        "events": events, "spans": spans, "roots": roots,
        "n_runs": len(segments),
        "unclosed": unclosed, "orphan_ends": orphan_ends,
        "bad_lines": bad_lines,
        "manifest": next((e for e in events
                          if e.get("event") == "manifest"), None),
        "backend_resolved": next(
            (e for e in events if e.get("event") == "backend_resolved"),
            None),
        "heartbeats": [e for e in events if e.get("event") == "heartbeat"],
        # where a killed run restarted from its checkpoint, and any
        # lossy recovery (corrupt step skipped) along the way — the
        # other half of the dead-run forensics the UNCLOSED flags begin
        "resumes": [e for e in events if e.get("event") == "resume"],
        "degraded": [e for e in events
                     if e.get("event") == "checkpoint_degraded"],
        "scores": [e for e in events if e.get("event") == "scores"],
        "counters": next((e for e in reversed(events)
                          if e.get("event") == "counters"), None),
        # served-mode forensics (sheepd): per-job cost rows from the
        # job span ends — under the interleaving scheduler the span-
        # DELTA counters mix tenants (the registry is global), so the
        # authoritative per-job costs are the explicit attrs the
        # scheduler stamps on each job span's end record
        "job_spans": [e for e in events
                      if e.get("event") == "span_end"
                      and str(e.get("span", "")).startswith("job:")],
        # flight-recorder dumps (ISSUE 11): each carries the last N
        # buffered events around a job failure / fault injection /
        # daemon shutdown — the untraced-path forensics rendered by
        # --last-errors next to the UNCLOSED-span flags
        "flight_dumps": [e for e in events
                         if e.get("event") == "flight_dump"],
        # the quality observability plane (ISSUE 13): per-level cut
        # attribution from hierarchical builds, the refine rounds'
        # move/capacity ledger, split balance accounting, and served
        # jobs' final scores — rendered as the quality tree below
        "quality_ledgers": [e for e in events
                            if e.get("event") == "quality_ledger"],
        "refine_rounds": [e for e in events
                          if e.get("event") == "refine_round"],
        "split_balance": [e for e in events
                          if e.get("event") == "split_balance"],
        "job_quality": [e for e in events
                        if e.get("event") == "job_quality"],
    }


_JOB_COST_FIELDS = ("device_rounds", "host_syncs", "batch_execs",
                    "dispatch_retries", "jit_compiles")


def tenant_costs(parsed: dict) -> dict:
    """{tenant: {jobs, secs, <cost sums>}} from the job span ends —
    the sheepd tenant-level cost attribution table."""
    out: dict = {}
    for e in parsed["job_spans"]:
        t = e.get("tenant", "?")
        row = out.setdefault(t, {"jobs": 0, "secs": 0.0})
        row["jobs"] += 1
        row["secs"] = round(row["secs"] + (e.get("secs") or 0.0), 3)
        for f in _JOB_COST_FIELDS:
            if isinstance(e.get(f), (int, float)):
                row[f] = row.get(f, 0) + e[f]
    return out


def _num(v):
    return isinstance(v, (int, float)) and not isinstance(v, bool)


def aggregate(nodes: list) -> list:
    """Group sibling spans by name into display rows: count, total
    secs, self secs (total - children), counter deltas NET of children
    (the same self/total decomposition, applied to counters), then
    recurse. Rows keep first-seen order."""
    rows: dict = {}
    for n in nodes:
        row = rows.setdefault(n["name"], {
            "name": n["name"], "count": 0, "total_s": 0.0, "self_s": 0.0,
            "counters": {}, "unclosed": 0, "children_nodes": []})
        row["count"] += 1
        row["total_s"] += n["secs"] or 0.0
        child_s = sum(c["secs"] or 0.0 for c in n["children"])
        row["self_s"] += max(0.0, (n["secs"] or 0.0) - child_s)
        row["unclosed"] += 1 if n.get("unclosed") else 0
        row["children_nodes"].extend(n["children"])
        # counter self-delta: this span's delta minus its children's
        child_counts: dict = {}
        for c in n["children"]:
            for k, v in c["counters"].items():
                if _num(v):
                    child_counts[k] = child_counts.get(k, 0) + v
        for k, v in n["counters"].items():
            if _num(v):
                d = v - child_counts.get(k, 0)
                if abs(d) > 1e-6:  # float residue is not a real delta
                    row["counters"][k] = row["counters"].get(k, 0) + d
            elif v != child_counts.get(k):
                row["counters"][k] = v
    out = []
    for row in rows.values():
        row["children"] = aggregate(row.pop("children_nodes"))
        out.append(row)
    return out


def _fmt_counters(c: dict) -> str:
    if not c:
        return ""
    parts = []
    for k, v in sorted(c.items()):
        if _num(v):
            parts.append(f"{k}=+{round(v, 3):g}" if v >= 0
                         else f"{k}={round(v, 3):g}")
        else:
            parts.append(f"{k}={v}")
    return "  " + " ".join(parts)


def render_tree(rows: list, out, depth: int = 0) -> None:
    for row in rows:
        name = row["name"] + (f" x{row['count']}" if row["count"] > 1
                              else "")
        flag = "  UNCLOSED (run died here?)" if row["unclosed"] else ""
        out.write(f"  {'  ' * depth}{name:<{max(1, 28 - 2 * depth)}}"
                  f"{row['total_s']:>9.3f}s {row['self_s']:>9.3f}s self"
                  f"{_fmt_counters(row['counters'])}{flag}\n")
        render_tree(row["children"], out, depth + 1)


def _build_wall(rows: list) -> float:
    """Total seconds of the build-phase rows (build / build+merge),
    searched depth-first — the wall the dispatch attribution prices."""
    for row in rows:
        if row["name"] in ("build", "build+merge"):
            return row["total_s"]
        w = _build_wall(row["children"])
        if w:
            return w
    return 0.0


def attribution_inputs(parsed: dict, rows: list):
    cnt = parsed["counters"] or {}
    if not cnt:
        # fall back to the last heartbeat's registry snapshot (a killed
        # run never writes the final counters event)
        hbs = parsed["heartbeats"]
        cnt = hbs[-1].get("counters", {}) if hbs else {}
    syncs, rounds = cnt.get("host_syncs"), cnt.get("device_rounds")
    wall = _build_wall(rows)
    if syncs is None or rounds is None or not wall:
        return None
    return {"wall_s": wall, "syncs": syncs, "rounds": rounds}


def report_one(path: str, args) -> tuple:
    """Returns (report dict, list of --check failures)."""
    parsed = parse_trace(path)
    rows = aggregate(parsed["roots"])
    problems = []
    if parsed["orphan_ends"]:
        problems.append(f"span_end without span_start: "
                        f"ids {parsed['orphan_ends'][:8]}")
    if parsed["bad_lines"]:
        problems.append(f"unparseable lines: {parsed['bad_lines'][:8]}")
    check_fail = list(problems)
    if parsed["manifest"] is None:
        check_fail.append("no manifest event")
    if parsed["unclosed"]:
        check_fail.append(
            f"unclosed spans: "
            f"{[n['name'] for n in parsed['unclosed']][:8]}")
    if not parsed["heartbeats"]:
        check_fail.append("no heartbeat events")
    if not parsed["spans"]:
        check_fail.append("no spans at all")
    return {"path": path, "parsed": parsed, "rows": rows,
            "problems": problems}, check_fail


def print_report(rep: dict, out) -> None:
    parsed = rep["parsed"]
    out.write(f"trace: {rep['path']}\n")
    if parsed["n_runs"] > 1:
        out.write(f"note: file holds {parsed['n_runs']} appended runs; "
                  f"reporting the last\n")
    m = parsed["manifest"]
    if m is not None:
        bits = [f"{k}={m[k]}" for k in ("backend", "platform",
                                        "device_count", "process_count",
                                        "jax_version", "git_sha")
                if m.get(k) is not None]
        resolved = parsed["backend_resolved"]
        if m.get("backend") is None and resolved is not None:
            bits.insert(0, f"backend={resolved.get('backend')} (auto)")
        cfg = m.get("config") or {}
        for k in ("input", "k", "dispatch_batch", "chunk_edges"):
            if cfg.get(k) is not None:
                bits.append(f"{k}={cfg[k]}")
        out.write(f"manifest: {' '.join(bits)}\n")
    else:
        out.write("manifest: MISSING\n")
    out.write("span tree (total / self seconds, counter deltas net of "
              "children):\n")
    if rep["rows"]:
        render_tree(rep["rows"], out)
    else:
        out.write("  (no spans)\n")
    for n in parsed["unclosed"]:
        out.write(f"  !! UNCLOSED span {n['name']!r} (id {n['id']}) — "
                  f"started, never ended; >= {n['secs']}s elapsed at "
                  f"last record. A killed/hung run, not a finished "
                  f"one.\n")
    hbs = parsed["heartbeats"]
    if hbs:
        last = hbs[-1]
        bits = [f"{k}={last[k]}" for k in ("phase", "chunks_done",
                                           "chunks_total", "edges_per_sec",
                                           "eta_s") if last.get(k)
                is not None]
        out.write(f"heartbeats: {len(hbs)}  last: {' '.join(bits)}\n")
    for r in parsed["resumes"]:
        bits = [f"{k}={r[k]}" for k in ("phase", "chunk_idx", "process")
                if r.get(k) is not None]
        out.write(f"resume: {' '.join(bits)} — this run restarted from "
                  f"a checkpoint (the killed attempt is a previous run "
                  f"in this file)\n")
    for r in parsed["degraded"]:
        out.write(f"checkpoint degraded: {r.get('message')}\n")
    for d in parsed["flight_dumps"]:
        out.write(f"flight dump: job={d.get('job')} "
                  f"reason={d.get('reason')} "
                  + (f"trace={d.get('trace')} " if d.get("trace") else "")
                  + f"events={d.get('n_events', len(d.get('events') or []))}"
                  f"  (render with --last-errors)\n")
    if parsed["job_spans"]:
        for e in parsed["job_spans"]:
            bits = [f"{k}={e[k]}" for k in
                    ("tenant", "state", "secs") + _JOB_COST_FIELDS
                    if e.get(k) is not None]
            out.write(f"job {e.get('span', '?')[4:]}: "
                      f"{' '.join(bits)}\n")
        for tenant, row in sorted(tenant_costs(parsed).items()):
            bits = [f"{k}={v}" for k, v in row.items()]
            out.write(f"tenant {tenant}: {' '.join(bits)}\n")
    print_quality(parsed, out)
    cnt = parsed["counters"]
    if cnt:
        cs = {k: v for k, v in cnt.items() if k not in ("event", "ts")}
        out.write(f"counters (final): "
                  f"{_fmt_counters(cs).strip() or '(none)'}\n")
    for s in parsed["scores"]:
        bits = [f"{k}={s[k]}" for k in ("k", "edge_cut", "cut_ratio",
                                        "balance", "comm_volume")
                if s.get(k) is not None]
        out.write(f"scores: {' '.join(bits)}\n")
    for p in rep["problems"]:
        out.write(f"warning: {p}\n")


def print_quality(parsed: dict, out) -> None:
    """The quality tree (ISSUE 13): per-level cut attribution from
    each hierarchical build's ledger, a refine-round summary (gain vs
    capacity-blocked moves), split balance accounting, and served
    jobs' final scores — the cut stops being one opaque number."""
    for q in parsed["quality_ledgers"]:
        out.write(f"quality ledger: k={q.get('k')} "
                  f"k_levels={q.get('k_levels')} "
                  f"cut_ratio={q.get('cut_ratio')} "
                  f"balance={q.get('balance')}\n")
        total_cut = max(q.get("edge_cut") or 1, 1)
        for lv in q.get("levels") or []:
            share = 100.0 * (lv.get("cut") or 0) / total_cut
            name = ("level0 (fragmentation)" if lv.get("level") == 0
                    else f"level{lv.get('level')} (misassignment)")
            out.write(f"  {name:<26} k={lv.get('k'):<6} "
                      f"cut {lv.get('cut'):>10,} "
                      f"({lv.get('cut_ratio')} of edges, "
                      f"{share:.1f}% of the cut)\n")
        if q.get("final_refine_repaired") is not None:
            out.write(f"  final refine repaired     "
                      f"{q['final_refine_repaired']:>14,} cut edges\n")
        if q.get("parts_at_capacity") is not None:
            out.write(f"  capacity-frozen parts     "
                      f"{q['parts_at_capacity']:>14,} "
                      f"(frozen load fraction "
                      f"{q.get('frozen_load_fraction')})\n")
    rr = parsed["refine_rounds"]
    if rr:
        gain = sum(e.get("gain") or 0 for e in rr
                   if e.get("accepted"))
        wanted = sum(e.get("moves_wanted") or 0 for e in rr)
        applied = sum(e.get("moves_applied") or 0 for e in rr)
        blocked = sum(e.get("moves_capacity_blocked") or 0 for e in rr)
        out.write(f"refine rounds: {len(rr)}  cut gain {gain:,}  moves "
                  f"{applied:,}/{wanted:,} applied "
                  f"({blocked:,} capacity-blocked)\n")
    for s in parsed["split_balance"]:
        if s.get("parts_at_capacity"):
            out.write(f"split balance: k={s.get('k')} "
                      f"balance={s.get('balance')} "
                      f"{s['parts_at_capacity']} part(s) at the "
                      f"capacity ceiling (frozen load fraction "
                      f"{s.get('frozen_load_fraction')})\n")
    for jq in parsed["job_quality"]:
        out.write(f"job quality: job={jq.get('job')} k={jq.get('k')} "
                  f"cut_ratio={jq.get('cut_ratio')} "
                  f"balance={jq.get('balance')}\n")


def parse_runs(path: str) -> list:
    """EVERY run in ``path`` (not just the last — contrast
    parse_trace), one {run, spans, roots} dict each: the --stitch
    input, where a failover story spans a client file plus several
    daemon files each possibly holding restart-appended runs."""
    all_events, _bad = _read_events(path)
    out = []
    for i, seg in enumerate(_segment_runs(all_events)):
        spans, roots, unclosed, orphans = _build_spans(seg)
        out.append({"run": i, "spans": spans, "roots": roots,
                    "orphan_ends": orphans})
    return out


def stitch_traces(paths: list) -> dict:
    """Merge spans from several trace files into one cross-process
    tree per propagated trace id (ISSUE 18).

    Membership: a span carrying a ``trace`` attr seeds its trace's
    group, and its local descendants (children by in-file parent
    links — the engine phase spans under a job span) ride along.
    Grafting: a member whose ``remote_parent`` attr names a 16-hex
    span id attaches under the member in a DIFFERENT file/run whose
    local id matches (the originating client span); members without
    one attach to their local parent when it is also a member, else
    they root the tree. Returns {trace_id: {"roots", "ungrafted",
    "n_spans", "files"}} where roots' entries carry
    ``stitch_children`` ordered by wall-clock start."""
    by_tid: dict = {}
    for path in paths:
        label = os.path.basename(path)
        for run in parse_runs(path):
            spans = run["spans"]
            tids = {n["attrs"].get("trace") for n in spans.values()}
            tids.discard(None)
            for tid in tids:
                members: dict = {}

                def add(n):
                    if n["id"] in members:
                        return
                    members[n["id"]] = n
                    for c in n["children"]:
                        add(c)

                for n in spans.values():
                    if n["attrs"].get("trace") == tid:
                        add(n)
                group = by_tid.setdefault(tid, [])
                for n in members.values():
                    group.append({"node": n, "file": label,
                                  "run": run["run"]})
    trees: dict = {}
    for tid, entries in sorted(by_tid.items()):
        by_local_id: dict = {}
        by_key: dict = {}
        for e in entries:
            e["stitch_children"] = []
            by_local_id.setdefault(e["node"]["id"], []).append(e)
            by_key[(e["file"], e["run"], e["node"]["id"])] = e
        roots = []
        ungrafted = []
        for e in entries:
            n = e["node"]
            parent_entry = None
            rp = n["attrs"].get("remote_parent")
            if rp is not None:
                try:
                    pid = int(str(rp), 16)
                except ValueError:
                    pid = None
                # the remote parent is by definition in ANOTHER
                # process's file — same-file id collisions (span ids
                # restart at 1 per run) never qualify
                cands = [c for c in by_local_id.get(pid, [])
                         if (c["file"], c["run"]) != (e["file"],
                                                      e["run"])]
                if cands:
                    parent_entry = cands[0]
                else:
                    ungrafted.append(e)
            else:
                parent_entry = by_key.get(
                    (e["file"], e["run"], n["parent"]))
            if parent_entry is not None:
                parent_entry["stitch_children"].append(e)
            else:
                roots.append(e)
        trees[tid] = {"roots": roots, "ungrafted": ungrafted,
                      "n_spans": len(entries),
                      "files": sorted({e["file"] for e in entries})}
    return trees


_STITCH_ATTRS = ("tenant", "job", "job_id", "endpoint", "why",
                 "from_endpoint", "from_job", "state")


def _stitch_entry_dict(e: dict) -> dict:
    n = e["node"]
    return {"span": n["name"], "file": e["file"], "run": e["run"],
            "id": n["id"], "secs": n["secs"],
            "unclosed": bool(n.get("unclosed")),
            "remote": n["attrs"].get("remote_parent") is not None,
            "attrs": {k: n["attrs"][k] for k in _STITCH_ATTRS
                      if n["attrs"].get(k) is not None},
            "children": [_stitch_entry_dict(c)
                         for c in sorted(e["stitch_children"],
                                         key=lambda c: c["node"]["ts"])]}


def stitch_json(trees: dict) -> dict:
    return {"traces": [
        {"trace": tid, "n_spans": t["n_spans"], "files": t["files"],
         "ungrafted": len(t["ungrafted"]),
         "roots": [_stitch_entry_dict(r)
                   for r in sorted(t["roots"],
                                   key=lambda e: e["node"]["ts"])]}
        for tid, t in trees.items()]}


def print_stitched(trees: dict, out) -> None:
    first = True
    for tid, t in trees.items():
        if not first:
            out.write("\n")
        first = False
        out.write(f"trace {tid}  ({t['n_spans']} spans across "
                  f"{', '.join(t['files'])}):\n")

        def walk(e, depth):
            n = e["node"]
            bits = [f"{k}={n['attrs'][k]}" for k in _STITCH_ATTRS
                    if n["attrs"].get(k) is not None]
            mark = " <-remote" if n["attrs"].get("remote_parent") \
                is not None else ""
            flag = "  UNCLOSED (died mid-span — the failover seam?)" \
                if n.get("unclosed") else ""
            name = f"{n['name']} [{e['file']}]"
            out.write(f"  {'  ' * depth}{name:<{max(1, 40 - 2 * depth)}}"
                      f"{n['secs'] or 0.0:>9.3f}s{mark}"
                      f"{'  ' if bits else ''}{' '.join(bits)}{flag}\n")
            for c in sorted(e["stitch_children"],
                            key=lambda c: c["node"]["ts"]):
                walk(c, depth + 1)

        for r in sorted(t["roots"], key=lambda e: e["node"]["ts"]):
            walk(r, 0)
        for e in t["ungrafted"]:
            out.write(f"  warning: {e['node']['name']} [{e['file']}] "
                      f"names remote_parent="
                      f"{e['node']['attrs'].get('remote_parent')} but "
                      f"no given file holds that span — stitch is "
                      f"missing the originating trace file?\n")


def stitch_check(trees: dict) -> list:
    """--check failures for stitch mode: every propagated trace must
    form exactly ONE grafted tree. UNCLOSED spans are expected (a
    killed replica's job span IS the failover evidence) and do not
    fail the check."""
    fails = []
    if not trees:
        fails.append("no propagated trace ids in the given files")
    for tid, t in trees.items():
        if t["ungrafted"]:
            fails.append(
                f"trace {tid}: {len(t['ungrafted'])} span(s) with an "
                f"unmatched remote_parent (missing a trace file?)")
        if len(t["roots"]) != 1:
            fails.append(f"trace {tid}: {len(t['roots'])} roots — "
                         f"expected one stitched tree")
    return fails


def _fmt_flight_event(e: dict, t0: float) -> str:
    bits = [f"+{max(0.0, e.get('t', t0) - t0):7.3f}s",
            str(e.get("ev", "?"))]
    for k, v in e.items():
        if k in ("t", "ev", "events"):
            continue
        bits.append(f"{k}={str(v)[:80]}")
    return " ".join(bits)


def print_last_errors(reports: list, n: int, out) -> int:
    """--last-errors: for every flight dump, render the final N
    buffered events (fault/retry/span trail) before the failure —
    the 'what were its last moments' question answered without full
    tracing. Returns how many dumps were rendered."""
    shown = 0
    for rep in reports:
        dumps = rep["parsed"]["flight_dumps"]
        if not dumps:
            continue
        out.write(f"last-errors [{rep['path']}]:\n")
        for d in dumps:
            evs = d.get("events") or []
            tail = evs[-n:]
            # a propagated trace id (ISSUE 18) names the fleet request
            # this failure belongs to — the handle --stitch groups by
            out.write(f"  {d.get('job')}  reason={d.get('reason')}  "
                      + (f"trace={d.get('trace')}  "
                         if d.get("trace") else "")
                      + f"({len(evs)} buffered, last {len(tail)}):\n")
            t0 = tail[0].get("t", 0.0) if tail else 0.0
            for e in tail:
                out.write(f"    {_fmt_flight_event(e, t0)}\n")
            shown += 1
    if not shown:
        out.write("no flight-recorder dumps in the trace(s) — nothing "
                  "failed, nothing was injected, and no daemon shut "
                  "down while holding buffered events\n")
    return shown


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Render obs trace JSONL as a span tree; two traces "
                    "add the dispatch-cost attribution solve.")
    ap.add_argument("trace", nargs="?", default=None,
                    help="trace JSONL (from --trace)")
    ap.add_argument("trace_b", nargs="?", default=None,
                    help="second trace: solve per-dispatch vs per-round "
                         "cost from the two runs' dispatch counts")
    ap.add_argument("--stitch", nargs="+", default=None, metavar="FILE",
                    help="merge several trace files by propagated "
                         "trace id into one cross-process tree per "
                         "fleet request (client span + every "
                         "replica's job spans; reads ALL appended "
                         "runs per file)")
    ap.add_argument("--check", action="store_true",
                    help="exit 3 unless well-formed + manifest + "
                         "complete span tree + >= 1 heartbeat")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable output")
    ap.add_argument("--last-errors", type=int, nargs="?", const=8,
                    default=None, metavar="N",
                    help="render the final N (default 8) flight-"
                         "recorder events buffered before each failed "
                         "job / fault / shutdown dump")
    args = ap.parse_args(argv)

    if args.stitch:
        paths = list(args.stitch)
        paths += [p for p in (args.trace, args.trace_b) if p]
        for p in paths:
            if not os.path.exists(p):
                print(f"error: no such trace: {p}", file=sys.stderr)
                return 1
        trees = stitch_traces(paths)
        if args.json:
            json.dump(stitch_json(trees), sys.stdout, indent=1,
                      default=str)
            print()
        else:
            print_stitched(trees, sys.stdout)
        if args.check:
            fails = stitch_check(trees)
            if fails:
                for c in fails:
                    print(f"check failed [stitch]: {c}",
                          file=sys.stderr)
                return 3
        return 0
    if args.trace is None:
        ap.error("a trace file is required (or --stitch FILE...)")

    reports = []
    checks = []
    for path in [args.trace] + ([args.trace_b] if args.trace_b else []):
        if not os.path.exists(path):
            print(f"error: no such trace: {path}", file=sys.stderr)
            return 1
        rep, check_fail = report_one(path, args)
        reports.append(rep)
        checks.append(check_fail)

    attribution = None
    if len(reports) == 2:
        ins = [attribution_inputs(r["parsed"], r["rows"])
               for r in reports]
        if all(ins):
            from sheep_tpu.utils.metrics import solve_dispatch_attribution

            attribution = solve_dispatch_attribution(ins[0], ins[1])
            if attribution is not None:
                attribution = {"inputs": ins, **attribution}

    if args.json:
        out = []
        for rep, cf in zip(reports, checks):
            out.append({
                "path": rep["path"], "spans": rep["rows"],
                "n_runs": rep["parsed"]["n_runs"],
                "manifest": rep["parsed"]["manifest"],
                "heartbeats": len(rep["parsed"]["heartbeats"]),
                "resumes": rep["parsed"]["resumes"],
                "degraded": rep["parsed"]["degraded"],
                "unclosed": [n["name"] for n in rep["parsed"]["unclosed"]],
                "counters": rep["parsed"]["counters"],
                "jobs": rep["parsed"]["job_spans"],
                "tenants": tenant_costs(rep["parsed"]),
                "flight_dumps": rep["parsed"]["flight_dumps"],
                "quality_ledgers": rep["parsed"]["quality_ledgers"],
                "refine_rounds": rep["parsed"]["refine_rounds"],
                "job_quality": rep["parsed"]["job_quality"],
                "check_failures": cf,
            })
        doc = {"traces": out}
        if len(reports) == 2:
            doc["attribution"] = attribution
        json.dump(doc, sys.stdout, indent=1, default=str)
        print()
    elif args.last_errors is not None:
        print_last_errors(reports, args.last_errors, sys.stdout)
    else:
        for i, rep in enumerate(reports):
            if i:
                print()
            print_report(rep, sys.stdout)
        if len(reports) == 2:
            print()
            if attribution is not None:
                a = attribution
                print("dispatch attribution (wall = syncs*per_dispatch + "
                      "rounds*per_round):")
                print(f"  inputs A: {a['inputs'][0]}")
                print(f"  inputs B: {a['inputs'][1]}")
                print(f"  per_dispatch_s = {a['per_dispatch_s']:.6f}   "
                      f"per_round_s = {a['per_round_s']:.6f}")
            else:
                print("dispatch attribution: not solvable (need "
                      "host_syncs/device_rounds + a build span in both "
                      "traces, with different sync/round mixes)")

    if any(r["parsed"]["orphan_ends"] for r in reports):
        return 2
    if args.check and any(checks):
        for rep, cf in zip(reports, checks):
            for c in cf:
                print(f"check failed [{rep['path']}]: {c}",
                      file=sys.stderr)
        return 3
    return 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:  # |head et al. closing stdout is not an error
        sys.exit(0)
