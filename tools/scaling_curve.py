#!/usr/bin/env python
"""Multi-chip scaling evidence S(D) on the virtual CPU mesh (VERDICT r2
item 3): for D in 1,2,4,8 run tpu-sharded and tpu-bigv at a fixed graph
and record what transfers to real hardware — per-phase wall (reference
only on cpu-jax), fixpoint rounds, merge payload bytes (sharded),
collective ops/bytes (bigv) — plus the cross-D correctness assert
(identical cut at every D; sharded is bit-identical to D=1 by the
existing test suite).

The absolute wall numbers on a virtual mesh are NOT chip predictions;
the collective counts and payload bytes ARE the quantities the ICI cost
model consumes (BASELINE.md "revised 10x thesis").

Usage:
    python tools/scaling_curve.py [--scale 18] [--ef 16] [--k 64]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ["JAX_PLATFORMS"] = os.environ.get("JAX_PLATFORMS", "cpu")
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                           " --xla_force_host_platform_device_count=8").strip()

from sheep_tpu.utils.platform import pin_platform  # noqa: E402

pin_platform(os.environ["JAX_PLATFORMS"])


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--scale", type=int, default=18)
    ap.add_argument("--ef", type=int, default=16)
    ap.add_argument("--k", type=int, default=64)
    ap.add_argument("--backends", default="tpu-sharded,tpu-bigv")
    ap.add_argument("--graph", default="rmat", choices=["rmat", "hub"],
                    help="rmat: Graph500 R-MAT (mild boundary). hub: "
                         "worst-case dense boundary for the merge "
                         "(VERDICT r3 item 7) — every edge touches one "
                         "of 64 hubs, the other endpoint uniform, so "
                         "nearly every vertex is shared across devices "
                         "and the compact O(boundary) merge payload "
                         "crosses over to the dense table")
    args = ap.parse_args()

    import numpy as np

    from sheep_tpu.backends.base import get_backend
    from sheep_tpu.io import generators
    from sheep_tpu.io.edgestream import EdgeStream

    n = 1 << args.scale
    if args.graph == "hub":
        rng = np.random.default_rng(21)
        m = args.ef << args.scale
        e = np.stack([rng.integers(0, min(64, n), size=m),
                      rng.integers(0, n, size=m)], axis=1).astype(np.int64)
    else:
        e = generators.rmat(args.scale, args.ef, seed=21)
    cuts = {}
    for backend in args.backends.split(","):
        for d in (1, 2, 4, 8):
            es = EdgeStream.from_array(e, n_vertices=n)
            kw = {"n_devices": d, "chunk_edges": max(4096, len(e) // d)}
            t0 = time.perf_counter()
            res = get_backend(backend, **kw).partition(
                es, args.k, comm_volume=False)
            wall = time.perf_counter() - t0
            rec = {"backend": backend, "D": d,
                   "wall_s": round(wall, 2),
                   "phases": {p: round(s, 2)
                              for p, s in res.phase_times.items()},
                   "edge_cut": res.edge_cut,
                   **{k_: v for k_, v in (res.diagnostics or {}).items()}}
            cuts.setdefault(backend, set()).add(res.edge_cut)
            print(json.dumps(rec), flush=True)
    for backend, cs in cuts.items():
        assert len(cs) == 1, f"{backend}: cut varies across D: {cs}"
    print(json.dumps({"summary": "cut identical across all D per backend",
                      "cuts": {b: list(c)[0] for b, c in cuts.items()}}),
          flush=True)


if __name__ == "__main__":
    main()
