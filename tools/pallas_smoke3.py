#!/usr/bin/env python
"""Third Mosaic probe: how wide can the lane-gather go?

On-chip facts so far (tools/out/20260801T083204/pallas_smoke2.jsonl):
lane gather (take_along_axis axis=1 on one (8,128) tile) LOWERS and is
correct; every sublane-gather form (axis=0 with a multi-tile row
extent) dies in a Mosaic assertion. So the only lowered primitive
gathers WITHIN 128 lanes.

The escape hatch that needs exactly one more fact: store the position
table TRANSPOSED, t_T (128, R) with t_T[c, r] = flat[r*128 + c]; route
indices by c = idx & 127 into the matching sublane; then every lookup
is out[i, j] = t_T[c_i, idx >> 7] — a lane gather with lane extent R.
If Mosaic lowers take_along_axis(axis=1) at R = 4096..32768 (table
2^19..2^22 = VMEM ceiling), arbitrary-index gather decomposes into
routed lane gathers. This probe measures lowers-or-not AND M elem/s
per lane extent.
"""

import json
import sys
import time

import numpy as np

INTERPRET = "--interpret" in sys.argv


def probe_width(R):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    rec = {"probe": "lane_gather_width", "lane_extent": R,
           "table_elems": 128 * R, "table_mb": round(128 * R * 4 / 2**20, 1)}
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.integers(0, 1 << 30, (8, R), dtype=np.int32))
    idx = jnp.asarray(rng.integers(0, R, (8, R), dtype=np.int32))

    kw = {}
    if not INTERPRET:
        from jax.experimental.pallas import tpu as pltpu

        kw = {"memory_space": pltpu.VMEM}
    try:
        call = pl.pallas_call(
            lambda xr, ir, o: o.__setitem__(
                ..., jnp.take_along_axis(xr[...], ir[...], axis=1)),
            grid=(1,),
            in_specs=[pl.BlockSpec((8, R), lambda g: (0, 0), **kw),
                      pl.BlockSpec((8, R), lambda g: (0, 0), **kw)],
            out_specs=pl.BlockSpec((8, R), lambda g: (0, 0), **kw),
            out_shape=jax.ShapeDtypeStruct((8, R), jnp.int32),
            interpret=INTERPRET)
        t0 = time.perf_counter()
        compiled = jax.jit(call).lower(x, idx).compile()
        rec["lowered"] = True
        rec["compile_s"] = round(time.perf_counter() - t0, 2)
        out = np.asarray(compiled(x, idx))
        rec["ok"] = bool(np.array_equal(
            out, np.take_along_axis(np.asarray(x), np.asarray(idx),  # sheeplint: sync-ok
                                    axis=1)))
        n = 8 * R
        jax.block_until_ready(compiled(x, idx))
        t0 = time.perf_counter()
        reps = 20
        for _ in range(reps):
            r = compiled(x, idx)
        jax.block_until_ready(r)
        s = (time.perf_counter() - t0) / reps
        rec["melems"] = round(n / s / 1e6, 1)
    except Exception as e:
        msg = f"{type(e).__name__}: {e}".splitlines()[0][:300]
        if rec.get("lowered"):
            rec["run_error"] = msg
        else:
            rec["lowered"] = False
            rec["error"] = msg
    print(json.dumps(rec), flush=True)
    return rec


def main():
    import jax

    print(json.dumps({"platform": jax.devices()[0].platform,
                      "device": str(jax.devices()[0])}), flush=True)
    widths = [128, 256, 512]
    if not INTERPRET:
        widths += [1024, 4096, 8192, 16384, 32768]
    for R in widths:
        rec = probe_width(R)
        if not rec.get("lowered") and not INTERPRET:
            break  # wider only gets harder; stop at first rejection


if __name__ == "__main__":
    main()
