"""RMAT-30-class capability run: V = 2^30 through tpu-bigv
(BASELINE.json eval config 5's vertex scale).

The single-chip streaming build caps at V = 2^29 on a 16 GiB chip and
the tpu-sharded pipeline replicates tables per device, so neither can
hold the RMAT-30 class (BASELINE.md HBM table). tpu-bigv exists to
remove that ceiling: pos/P/deg block-sharded across the mesh (B =
(V+1)/D rows per device), ONE distributed forest via routed
collectives. This driver proves it at the real vertex scale on the
virtual CPU mesh (--devices sizes the mesh; see that flag's help for
why the virtual-mesh default is 2):

- graph: a PREFIX of the rmat_stream(30, ef=1) edge stream (Graph500
  R-MAT parameters, so the hub skew of the scale-30 class is real),
  edge count bounded so the run fits CI-hours on one host core;
- tpu-bigv partitions it at k=1024 (the config-5 part count);
- the native cpu backend partitions the same stream; the parent
  forests and scores must agree EXACTLY.

Results -> tools/out/soak/bigv_s30.json.

Usage:
    python tools/bigv_scale30.py [--edge-chunks 16] [--k 1024]
"""

from __future__ import annotations

import argparse
import json
import os
import resource
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--scale", type=int, default=30)
    ap.add_argument("--edge-chunks", type=int, default=16,
                    help="number of 2^22-edge rmat_stream chunks to take "
                         "(16 -> 67M edges over 1.07B vertices)")
    ap.add_argument("--k", type=int, default=1024)
    ap.add_argument("--chunk-edges", type=int, default=1 << 22)
    ap.add_argument("--lift-levels", type=int, default=4,
                    help="stream-descent lifting depth for bulk rounds. "
                         "At V=2^30 each level is a (D, B)-shaped routed "
                         "lookup (~4.3 GB of collective intermediates on "
                         "the single-host virtual mesh), and the auto "
                         "depth of 31 levels OOM-killed a 125 GB host — "
                         "small depth trades more rounds for a bounded "
                         "per-program footprint")
    ap.add_argument("--segment-rounds", type=int, default=1,
                    help="fixpoint rounds per device execution (same "
                         "memory trade as --lift-levels)")
    ap.add_argument("--jumps", type=int, default=16)
    ap.add_argument("--devices", type=int, default=2,
                    help="mesh size for the run. On the VIRTUAL mesh "
                         "every all_gather of a B-width buffer "
                         "replicates all D shards into ONE host RAM "
                         "(D * V words per live gathered buffer — 34 GB "
                         "at D=8/V=2^30, several live at once: the "
                         "observed 130 GB OOMs), where real chips hold "
                         "their own copy in their own HBM. D=2 proves "
                         "the identical block-sharded/routed design at "
                         "full vertex scale within 125 GB; per-device "
                         "collective counts for D=8 come from "
                         "build_stats at smaller V (BASELINE.md)")
    ap.add_argument("--hoist-bytes", type=int, default=None,
                    help="per-device budget for the per-segment stale "
                         "lifting stack. The s28+ class is the "
                         "V-dominant regime (B >> Q) BASELINE.md "
                         "reserves hoisting for: squarings are paid "
                         "once per segment instead of every round")
    ap.add_argument("--balance", type=float, default=None, metavar="BETA",
                    help="guaranteed balance bound, threaded like the "
                         "CLI's flat path: the host split runs at alpha "
                         "= BETA - 1, delivering max part load <= BETA "
                         "* total/k + max vertex weight. The committed "
                         "k=1024 artifacts shipped balance ~1.97 from "
                         "the alpha=1.0 default this flag replaces "
                         "(ROADMAP item 5); the oracle leg runs at the "
                         "same alpha so exact-equality checking holds")
    ap.add_argument("--checkpoint-dir", default=None,
                    help="per-batch checkpointing via utils/checkpoint "
                         "(VERDICT r4 item 2: the s28 run needs to span "
                         "sessions); pass with --resume to continue")
    ap.add_argument("--resume", action="store_true")
    ap.add_argument("--ckpt-every", type=int, default=1,
                    help="checkpoint cadence in CHUNKS (a D-device batch "
                         "consumes D chunks; 1 = every batch)")
    ap.add_argument("--skip-oracle", action="store_true")
    args = ap.parse_args()
    if args.resume and not args.checkpoint_dir:
        ap.error("--resume requires --checkpoint-dir (without it the "
                 "run would silently restart from scratch)")
    alpha = 1.0
    if args.balance is not None:
        if args.balance <= 1.0:
            ap.error("--balance must be > 1 (it bounds max part load "
                     "at BETA * total/k)")
        alpha = min(args.balance - 1.0, 1.0)

    # artifact path up front (also the auto-resume idempotency key)
    tag = "" if args.devices == 2 else f"_d{args.devices}"
    if args.balance is not None:
        # a balance-budgeted run is a different experiment; keep the
        # default-alpha artifact (same ADVICE-r4 no-clobber rule as D)
        tag += f"_b{args.balance:g}"
    out = os.path.join(REPO, "tools", "out", "soak",
                       f"bigv_s{args.scale}{tag}.json")
    if args.resume and os.path.exists(out):
        # unattended re-entry (tools/run_paused_aware.sh auto-resume,
        # ISSUE 9 satellite): a completed artifact means the previous
        # attempt finished AFTER the supervisor decided to retry (e.g.
        # killed between the final write and exit) — converge instead
        # of re-burning hours re-proving the same verdict
        try:
            with open(out) as f:
                prior = json.load(f)
        except (OSError, json.JSONDecodeError):
            prior = None
        if prior and "bigv" in prior and (
                prior.get("oracle_equal") is True
                or ("native_oracle" not in prior
                    and "oracle_equal" not in prior)):
            print(f"auto-resume: completed artifact already at {out} "
                  f"(oracle_equal={prior.get('oracle_equal')}); "
                  f"nothing to do")
            return

    nd = max(8, args.devices)
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={nd}").strip()
    from sheep_tpu.utils.platform import pin_platform

    pin_platform("cpu")
    import jax

    assert jax.device_count() >= args.devices, jax.devices()

    from sheep_tpu.backends.base import get_backend
    from sheep_tpu.io import generators
    from sheep_tpu.io.edgestream import EdgeStream

    n = 1 << args.scale
    gen_chunk = 1 << 22
    m = args.edge_chunks * gen_chunk

    def prefix():
        from itertools import islice

        yield from islice(
            generators.rmat_stream(args.scale, 1, seed=42, chunk=gen_chunk),
            args.edge_chunks)

    def stream():
        return EdgeStream.from_generator(prefix, n_vertices=n, num_edges=m)

    result = {"scale": args.scale, "n_vertices": n, "n_edges": m,
              "k": args.k, "devices": args.devices,
              "chunk_edges": args.chunk_edges}
    print(f"V=2^{args.scale} = {n:,}  E={m:,}  k={args.k}  "
          f"devices={args.devices} (virtual mesh of {jax.device_count()})", flush=True)

    result["lift_levels"] = args.lift_levels
    result["segment_rounds"] = args.segment_rounds
    result["jumps"] = args.jumps
    result["hoist_bytes"] = args.hoist_bytes
    result["balance_budget"] = args.balance
    result["alpha"] = alpha
    ckpt = None
    if args.checkpoint_dir:
        from sheep_tpu.utils.checkpoint import Checkpointer

        ckpt = Checkpointer(args.checkpoint_dir, every=args.ckpt_every)
    t0 = time.perf_counter()
    # through the REGISTERED backend (vertex-range check, chunk clamping,
    # PartitionResult packaging), not a hand-wired pipeline
    big = get_backend(
        "tpu-bigv", chunk_edges=args.chunk_edges, jumps=args.jumps,
        segment_rounds=args.segment_rounds, n_devices=args.devices,
        lift_levels=args.lift_levels, alpha=alpha,
        hoist_bytes=args.hoist_bytes).partition(
            stream(), args.k, comm_volume=False,
            checkpointer=ckpt, resume=args.resume)
    # the backend clamps chunk_edges for small streams; its diagnostics
    # carry the value actually run, so cross-round artifact comparisons
    # don't attribute a hidden chunk-size change to code changes
    result["chunk_edges_effective"] = int(
        big.diagnostics.get("chunk_edges_effective", args.chunk_edges))
    result["bigv"] = {
        "wall_s": round(time.perf_counter() - t0, 1),
        "edge_cut": int(big.edge_cut),
        "total_edges": int(big.total_edges),
        "balance": round(float(big.balance), 4),
        "phases": {p: round(s, 1) for p, s in big.phase_times.items()},
        "diagnostics": {k: int(v) for k, v in big.diagnostics.items()},
        "fixpoint_rounds": int(big.diagnostics["fixpoint_rounds"]),
        "peak_rss_gb": round(resource.getrusage(
            resource.RUSAGE_SELF).ru_maxrss / 1e6, 1),
    }
    print("bigv:", json.dumps(result["bigv"]), flush=True)

    if not args.skip_oracle:
        from sheep_tpu.core import native

        assert native.available(), "native core needed for the oracle"
        t0 = time.perf_counter()
        ref = get_backend("cpu", chunk_edges=args.chunk_edges,
                          alpha=alpha).partition(
            stream(), args.k, comm_volume=False)
        result["native_oracle"] = {
            "wall_s": round(time.perf_counter() - t0, 1),
            "edge_cut": int(ref.edge_cut),
            "balance": round(float(ref.balance), 4),
        }
        print("oracle:", json.dumps(result["native_oracle"]), flush=True)
        result["oracle_equal"] = bool(
            big.edge_cut == ref.edge_cut
            and np.array_equal(big.assignment, ref.assignment))

    # write the artifact BEFORE any equality verdicting exits: a
    # multi-hour disagreeing run must still leave its evidence on disk
    # (oracle_equal: false), not vanish into an AssertionError. The
    # path is keyed by mesh size / balance up top (ADVICE r4: a rerun
    # at another D or BETA is a semantically different run and must not
    # clobber committed evidence).
    os.makedirs(os.path.dirname(out), exist_ok=True)
    with open(out, "w") as f:
        json.dump(result, f, indent=1)
    print(json.dumps(result))
    print(f"written to {out}")
    if result.get("oracle_equal") is False:
        print("ORACLE MISMATCH: bigv != native at this scale",
              file=sys.stderr)
        sys.exit(1)


if __name__ == "__main__":
    main()
