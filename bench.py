#!/usr/bin/env python
"""Headline benchmark: edges/sec partitioned, TPU backend vs CPU baseline.

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

``vs_baseline`` is the TPU/CPU edges-per-second ratio — the north-star
target is >=10x (BASELINE.md). Graph: RMAT (Graph500 params), k=64,
matching the driver's streaming eval shape. Scale via SHEEP_BENCH_SCALE
(default 22 -> 4.2M vertices, 67M edges on a real TPU; smaller when
falling back to cpu-jax so the run stays bounded).

Robustness contract (VERDICT.md round 1 item 1, extended in round 2):
the JSON line is emitted on EVERY path. Accelerator availability is
probed in a SUBPROCESS (a failed backend init poisons the parent's JAX
process state), and the measurement itself ALSO runs in a subprocess
worker (``bench.py --measure SCALE PLATFORM``) — round 2 found that a
long compiled execution can crash the TPU *worker process* mid-run
("kernel fault"), which would otherwise take the whole bench down with
it. On a worker crash or timeout the parent retries down a scale ladder
(22 -> 20 -> 18) so a size-triggered fault still yields a real measured
ratio at the largest surviving scale, with the failures recorded in the
JSON diagnostics. The CPU baseline falls back native->pure if the C++
toolchain is absent.

Secondary metrics (cut ratio parity vs CPU, per-phase times) go to stderr
so the stdout contract stays one line.

Link-state contract (VERDICT.md round 5 item 7): every emitted JSON line
carries its own window's ``{rtt_ms, h2d_mbs, d2h_mbs}`` plus
``r_colo_est`` (the ratio with the measured per-sync link tax removed —
the co-located-host R estimate) and the dispatch-count attribution
inputs ``{host_syncs, device_rounds}``, so headline numbers are
comparable across the ~8x link-quality swing without artifact
archaeology.
"""

import json
import os
import subprocess
import sys
import time

METRIC = "edges/sec partitioned"


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def emit(value, vs_baseline, metric=METRIC, **extra):
    line = {"metric": metric, "value": value, "unit": "edges/sec",
            "vs_baseline": vs_baseline}
    line.update(extra)
    print(json.dumps(line), flush=True)


def measure_link_state() -> dict:
    """Per-window link state (VERDICT r5 item 7): the quantities that
    explained the 0.215 -> 0.064 headline swing (same code, ~8x link
    difference). Measured in the worker right before the timed legs so
    every bench JSON is normalizable without linkstate.jsonl
    archaeology: median tiny-put RTT plus one 16 MiB transfer each way,
    with host pulls as completion barriers (block_until_ready is not a
    barrier through the tunnel — BASELINE.md round-2 fact).

    Returns {} when jax cannot run a device op at all — the probe must
    never take down the jax-free cpu-vs-itself diagnostic path."""
    try:
        import numpy as np

        import jax

        np.asarray(jax.device_put(np.zeros(1, np.int32)))
    except Exception as e:
        log(f"link-state probe unavailable: {type(e).__name__}: "
            f"{str(e)[:120]}")
        return {}

    rtts = []
    for _ in range(5):
        t0 = time.perf_counter()
        np.asarray(jax.device_put(np.zeros(1, np.int32)))
        rtts.append(time.perf_counter() - t0)
    rtt = sorted(rtts)[len(rtts) // 2]
    host = np.zeros(1 << 22, np.int32)  # 16 MiB
    t0 = time.perf_counter()
    dev = jax.device_put(host)
    np.asarray(dev[:1])
    h2d = time.perf_counter() - t0
    t0 = time.perf_counter()
    np.asarray(dev)
    d2h = time.perf_counter() - t0
    return {"rtt_ms": round(1e3 * rtt, 2),
            "h2d_mbs": round(16 / max(h2d, 1e-9), 1),
            "d2h_mbs": round(16 / max(d2h, 1e-9), 1)}


_PROBE_SRC = """
import jax, jax.numpy as jnp
(jnp.arange(8) + 1).block_until_ready()   # first op forces backend init
print(jax.default_backend())
"""


_PROBE_CACHE: dict = {}


def probe_accelerator(tries=3, timeout=None):
    """Run the trivial-op probe in a fresh subprocess; return the working
    platform name or None. Retries cover transient UNAVAILABLE from the
    TPU runtime coming up; each attempt is a fresh process because jax
    caches a failed backend for the life of the process. Two consecutive
    hangs (vs fast errors) end the probe early — a dead tunnel doesn't
    heal within the bench window, and the timeouts are the bench's.

    The dead-device probe costs 2 x ``timeout`` on accelerator-less
    hosts (BENCH_r05 tail), so the verdict is cached PROCESS-WIDE:
    the first call's verdict answers every later one regardless of
    (tries, timeout) — a bench that probes from several call sites
    pays the dead-tunnel tail at most once (the old per-args cache
    re-burned it per distinct call shape). A platform that came up
    stays up for the bench window; one that hung twice will not heal
    inside it. ``SHEEP_PROBE_TIMEOUT_S`` overrides the per-attempt
    timeout (default 180) when no explicit ``timeout`` is passed, and
    ``SHEEP_SKIP_PROBE=1`` short-circuits straight to the cpu-jax
    fallback — the knobs for CI and cpu-only hosts that know the
    answer already."""
    if os.environ.get("SHEEP_SKIP_PROBE") == "1":
        log("SHEEP_SKIP_PROBE=1: skipping the device probe "
            "(cpu-jax fallback)")
        return None
    if timeout is None:
        try:
            timeout = float(os.environ.get("SHEEP_PROBE_TIMEOUT_S",
                                           "") or 180.0)
        except ValueError:
            timeout = 180.0
    if "verdict" in _PROBE_CACHE:
        log(f"device probe: cached verdict {_PROBE_CACHE['verdict']!r}")
        return _PROBE_CACHE["verdict"]
    _PROBE_CACHE["verdict"] = plat = \
        _probe_accelerator_uncached(tries, timeout)
    return plat


def _probe_accelerator_uncached(tries, timeout):
    hangs = 0
    for attempt in range(tries):
        try:
            r = subprocess.run([sys.executable, "-c", _PROBE_SRC],
                               capture_output=True, text=True, timeout=timeout)
        except subprocess.TimeoutExpired:
            log(f"device probe attempt {attempt + 1}: timed out after {timeout}s")
            hangs += 1
            if hangs >= 2:
                break
            continue
        hangs = 0
        plat = r.stdout.strip().splitlines()[-1] if r.stdout.strip() else ""
        if r.returncode == 0 and plat:
            log(f"device probe: platform={plat}")
            return plat
        tail = (r.stderr or "").strip().splitlines()
        log(f"device probe attempt {attempt + 1} failed (rc={r.returncode}): "
            + (tail[-1][:300] if tail else "no stderr"))
        if attempt < tries - 1:
            time.sleep(5 * (attempt + 1))
    return None


def measure(scale: int, platform: str) -> dict:
    """Worker body: measure CPU baseline + accelerated backend at one RMAT
    scale. Runs in a subprocess so a TPU worker crash only loses this
    attempt. Returns the result dict (also printed as the last stdout
    line when invoked via --measure)."""
    # persistent compilation cache: a retried/repeated bench skips the
    # multi-minute first-compile warm-up (the programs are identical)
    from sheep_tpu.utils.platform import enable_compilation_cache, \
        pin_platform

    if platform == "cpu":
        pin_platform("cpu")
    enable_compilation_cache()

    from sheep_tpu.backends.base import get_backend, list_backends

    if "cpu" in list_backends():
        base_name = "cpu"
    else:
        log("native cpu backend unavailable (C++ toolchain?); baseline=pure")
        base_name = "pure"

    edge_factor = int(os.environ.get("SHEEP_BENCH_EDGE_FACTOR", "16"))
    k = int(os.environ.get("SHEEP_BENCH_K", "64"))

    from sheep_tpu.io import generators
    from sheep_tpu.io.edgestream import EdgeStream

    # Counter-based R-MAT: the accelerated side materializes chunks ON
    # DEVICE (generators.rmat_hash_chunk_device) so the bench measures the
    # pipeline, not the host link — through the axon tunnel the chunk
    # upload alone was 92 s of a 254 s run (tools/out/20260731T010412/);
    # on a co-located host it hides a PCIe pass. The CPU baseline gets
    # the IDENTICAL edges (bit-equal host twin), materialized once so its
    # passes read memory rather than re-hashing.
    t0 = time.perf_counter()
    n = 1 << scale
    dev_stream = generators.RmatHashStream(scale, edge_factor, seed=42)
    edges = dev_stream.read_all()
    es = EdgeStream.from_array(edges, n_vertices=n)
    m = len(edges)
    log(f"graph: RMAT-{scale} ef={edge_factor} (counter-hash)  "
        f"V={n:,} E={m:,}  (gen {time.perf_counter() - t0:.1f}s)  k={k}")

    # --- CPU single-socket baseline (the denominator) ---------------------
    cpu = get_backend(base_name, chunk_edges=1 << 24)
    t0 = time.perf_counter()
    res_cpu = cpu.partition(es, k, comm_volume=False)
    cpu_s = time.perf_counter() - t0
    cpu_eps = m / cpu_s
    log(f"{base_name}: {cpu_s:.2f}s = {cpu_eps / 1e6:.2f} Me/s  "
        f"cut_ratio={res_cpu.cut_ratio:.4f} balance={res_cpu.balance:.3f} "
        f"phases={ {p: round(s, 2) for p, s in res_cpu.phase_times.items()} }")

    out = {"scale": scale, "k": k, "edges": m, "platform": platform,
           "baseline": base_name, "cpu_eps": round(cpu_eps, 1),
           "cpu_cut_ratio": round(res_cpu.cut_ratio, 6)}

    # per-window link state rides in the contract so any capture from
    # this window normalizes to the co-located bound (VERDICT r5 item 7)
    link = measure_link_state()
    if link:
        log(f"link state: rtt {link['rtt_ms']} ms  h2d {link['h2d_mbs']} "
            f"MB/s  d2h {link['d2h_mbs']} MB/s")
        out.update(link)

    if "tpu" not in list_backends():
        log("tpu backend unavailable; reporting cpu vs itself")
        # cpu vs itself: no link tax to remove, so the co-located
        # estimate IS the ratio — the field stays on every emitted line
        out.update(tpu_eps=round(cpu_eps, 1), ratio=1.0, r_colo_est=1.0,
                   error="tpu backend unregistered")
        return out

    # --- accelerated backend ---------------------------------------------
    # chunk sizes from the tools/tune_fixpoint.py sweeps: 2^23 on the
    # real chip (RMAT-20/22, fewest fixpoint sequences that still hand
    # the tail off early), 2^22 on the cpu-jax fallback (width-
    # proportional round cost thrashes host caches)
    accel_chunk = 1 << (23 if platform != "cpu" else 22)

    def timed_leg(backend_name):
        """Warm-up (compile) partition + one timed partition; shared by
        the single-chip and multi-chip legs so the timing methodology
        cannot drift between them. SHEEP_BENCH_TRACE=DIR captures a
        structured obs trace (manifest + span tree + counters; see
        tools/trace_report.py) of the TIMED leg only — the warm-up's
        compile wall would drown the steady-state tree. Tracing off is
        the default and adds nothing to the measured path."""
        be = get_backend(backend_name, chunk_edges=min(accel_chunk, m))
        t0 = time.perf_counter()
        be.partition(dev_stream, k, comm_volume=False)  # compile warm-up
        warm = time.perf_counter() - t0
        # the timed leg runs with the ALWAYS-ON flight recorder
        # installed, exactly as every request under sheepd does
        # (ISSUE 11): warm_request_s therefore carries the telemetry
        # tax inside the gated contract number — if the "negligible
        # overhead" claim ever rots, bench_regress catches it as a
        # warm-path regression, not as an untested assertion
        from sheep_tpu import obs as _obs
        from sheep_tpu.obs.flightrec import FlightRecorder as _FR

        _obs.install_flight(_FR())
        try:
            trace_dir = os.environ.get("SHEEP_BENCH_TRACE")
            if trace_dir:
                from sheep_tpu import obs

                os.makedirs(trace_dir, exist_ok=True)
                path = os.path.join(
                    trace_dir, f"trace_{backend_name}_s{scale}.jsonl")
                with obs.tracing(path) as tr:
                    obs.emit_manifest(tr, backend=backend_name,
                                      config={"scale": scale, "k": k,
                                              "edge_factor": edge_factor,
                                              "platform": platform})
                    t0 = time.perf_counter()
                    res = be.partition(dev_stream, k, comm_volume=False)
                    leg_s = time.perf_counter() - t0
                log(f"obs trace captured: {path}")
                return res, leg_s, warm
            t0 = time.perf_counter()
            res = be.partition(dev_stream, k, comm_volume=False)
            return res, time.perf_counter() - t0, warm
        finally:
            _obs.uninstall_flight()

    res_tpu, tpu_s, warm_s = timed_leg("tpu")
    tpu_eps = m / tpu_s
    log(f"{platform}: {tpu_s:.2f}s = {tpu_eps / 1e6:.2f} Me/s (warm-up {warm_s:.1f}s)  "
        f"cut_ratio={res_tpu.cut_ratio:.4f} balance={res_tpu.balance:.3f} "
        f"rounds={res_tpu.diagnostics.get('fixpoint_rounds')} "
        f"phases={ {p: round(s, 2) for p, s in res_tpu.phase_times.items()} }")
    # warm-vs-cold served-request contract (ISSUE 10 satellite): the
    # warm-up leg IS a cold request (first call, jit compiles included)
    # and the timed leg IS a warm one (what a resident sheepd serves
    # from its warm program caches) — emit both so bench_regress can
    # gate the warm path and the jit tax like the other perf fields.
    # bench.py printed warm-up for three rounds (BENCH_r03-r05) but
    # never emitted it; the 8-13 s gap is the number the server mode
    # exists to amortize.
    out["warm_up_s"] = round(warm_s, 2)
    out["cold_request_s"] = round(warm_s, 2)
    out["warm_request_s"] = round(tpu_s, 2)
    log(f"served-request comparison: cold {warm_s:.2f}s vs warm "
        f"{tpu_s:.2f}s ({warm_s / max(tpu_s, 1e-9):.1f}x)")
    # incremental contract field (ISSUE 15): one resident-partition
    # update — a delta batch folded into a converged carried table —
    # timed at a reduced scale so the leg stays seconds everywhere
    # (the metric tracks the UPDATE machinery, not the headline build;
    # scale rides in the metric string via the derived size). Gated
    # lower-better by bench_regress like warm_request_s; compactions
    # rides info-only.
    try:
        import numpy as np

        from sheep_tpu import incremental as inc_mod

        us = max(10, scale - 4)
        un = 1 << us
        delta = np.random.default_rng(1234).integers(
            0, un, (min(1 << 15, max(1024, (un * edge_factor) // 256)),
                    2), dtype=np.int64)

        def scored_epoch(sc, name="tpu"):
            """One SCORED update epoch at RMAT-``sc``: returns the
            (fold_s, score_s, state) split — the score side comes
            from the state's own update_score_s accounting, so it
            measures exactly the refresh's scoring pass. A seed
            refresh runs first so the timed epoch takes the
            O(delta) incremental-score path, not the one-time full
            pass that builds the survivor index."""
            stream = generators.RmatHashStream(sc, edge_factor,
                                               seed=42)
            be = get_backend(name, chunk_edges=min(
                accel_chunk, (1 << sc) * edge_factor))
            st, _ = inc_mod.begin_incremental(stream, k, backend=be,
                                              comm_volume=False)
            inc_mod.refresh(be, st)  # seed the score cache
            s0 = float(st.stats.get("update_score_s", 0.0))
            t0 = time.perf_counter()
            be.partition_update(st, adds=delta, score=True)
            wall = time.perf_counter() - t0
            score_s = float(st.stats.get("update_score_s", 0.0)) - s0
            return max(0.0, wall - score_s), score_s, st

        fold_s, score_s, ustate = scored_epoch(us)
        out["update_fold_s"] = round(fold_s, 4)
        out["update_score_s"] = round(score_s, 4)
        out["update_request_s"] = round(fold_s + score_s, 4)
        out["compactions"] = int(ustate.compactions)
        inc_hits = int(ustate.stats.get("score_incremental", 0))
        log(f"incremental: update_fold_s {out['update_fold_s']}s + "
            f"update_score_s {out['update_score_s']}s (RMAT-{us}, "
            f"{len(delta)} delta edges, epoch {ustate.epoch}, "
            f"score_incremental={inc_hits})")
        # epoch-cost scaling probe (ISSUE 17): the SAME delta folded
        # + scored over a 2x larger base; O(delta) epochs keep the
        # scored-epoch wall roughly flat (the contract bar is
        # ~<=1.2x), O(edges) rescoring would double it. Rides
        # info-only in bench_regress — it is a property, not a perf
        # series.
        fold2, score2, _ = scored_epoch(us + 1)
        w1, w2 = fold_s + score_s, fold2 + score2
        out["epoch_scale_x2"] = round(w2 / max(w1, 1e-9), 3)
        log(f"incremental scaling: 2x base -> "
            f"{out['epoch_scale_x2']}x scored-epoch wall "
            f"({w1:.4f}s -> {w2:.4f}s; score "
            f"{score_s:.4f}s -> {score2:.4f}s)")
        if out["epoch_scale_x2"] > 1.5:
            log(f"WARNING: scored-epoch wall scaled "
                f"{out['epoch_scale_x2']}x on a 2x base — the "
                f"O(delta) incremental-score path may have fallen "
                f"back to full rescoring")
        # multi-device update leg (ISSUE 19): the SAME scored epoch
        # through the sharded lockstep fold + distributed rescore —
        # what a resident sharded partition pays per delta epoch.
        # Gated lower-better by bench_regress like update_request_s.
        fold_sh, score_sh, sh_state = scored_epoch(us,
                                                   name="tpu-sharded")
        out["sharded_update_request_s"] = round(fold_sh + score_sh, 4)
        log(f"sharded incremental: {out['sharded_update_request_s']}s "
            f"(fold {fold_sh:.4f}s + score {score_sh:.4f}s, "
            f"update_folds="
            f"{int(sh_state.stats.get('update_folds', 0))}, "
            f"score_distributed="
            f"{int(sh_state.stats.get('score_distributed', 0))}, "
            f"device_rounds="
            f"{int(sh_state.stats.get('device_rounds', 0))})")
    except Exception as e:  # noqa: BLE001 — the leg must not kill bench
        log(f"incremental leg skipped: {type(e).__name__}: "
            f"{str(e)[:200]}")
    # fleet warm-path contract field (ISSUE 16): cached_request_s —
    # one repeat submit answered from the content-addressed result
    # store (zero dispatch steps, zero recompiles, bit-identical) at
    # the reduced update-leg scale against an in-process scheduler.
    # Gated lower-better by bench_regress; the contract bar is at
    # least 10x under warm_request_s (the store read is file IO +
    # decode, not a build).
    try:
        import tempfile
        import threading

        from sheep_tpu.server import journal as journal_mod
        from sheep_tpu.server import protocol as proto_mod
        from sheep_tpu.server.scheduler import Scheduler

        cs2 = max(10, scale - 4)
        body = {"input": f"rmat:{cs2}:{edge_factor}:7", "k": [k],
                "chunk_edges": min(accel_chunk,
                                   (1 << cs2) * edge_factor)}
        with tempfile.TemporaryDirectory() as td:
            sched = Scheduler(
                result_store=os.path.join(td, "results"))
            th = threading.Thread(target=sched.run, daemon=True,
                                  name="bench-sheepd-dispatch")
            th.start()
            try:
                sp = proto_mod.JobSpec.from_request(body,
                                                    tenant="bench")
                dg = journal_mod.job_digest(sp)
                cold = sched.submit(sp, digest=dg)
                cold = sched.wait(cold.id, timeout_s=600)
                if cold.state != "done":
                    raise RuntimeError(
                        f"cold fill {cold.state}: {cold.error}")
                # the store publish runs after the terminal on the
                # dispatch thread; wait for the digest to land
                deadline = time.time() + 30
                while not sched.lookup_digest(dg) \
                        and time.time() < deadline:
                    time.sleep(0.01)
                sp2 = proto_mod.JobSpec.from_request(body,
                                                     tenant="bench")
                t0 = time.perf_counter()
                rep = sched.submit(sp2, digest=dg)
                rep = sched.wait(rep.id, timeout_s=600)
                cached_s = time.perf_counter() - t0
                hit = int(rep.stats.get("result_cache_hit", 0))
                if rep.state == "done" and hit:
                    out["cached_request_s"] = round(cached_s, 4)
                    log(f"result cache: cached_request_s "
                        f"{out['cached_request_s']}s (RMAT-{cs2}, "
                        f"digest {dg[:12]}, jit_compiles="
                        f"{rep.jit_compiles})")
                    warm = out.get("warm_request_s")
                    if warm and cached_s > warm / 10.0:
                        # the contract bar: a store answer is file IO
                        # + decode, >= 10x under the warm build wall
                        log(f"WARNING: cached_request_s {cached_s:.4f}"
                            f"s is not >=10x under warm_request_s "
                            f"{warm}s — store path slowing?")
                else:
                    log(f"result-cache leg unusable: "
                        f"state={rep.state} hit={hit}")
            finally:
                sched.shutdown()
                th.join(timeout=30)
    except Exception as e:  # noqa: BLE001 — the leg must not kill bench
        log(f"result-cache leg skipped: {type(e).__name__}: "
            f"{str(e)[:200]}")
    # out-of-core contract field (ISSUE 20): oocore_request_s — one
    # full build with SHEEP_CACHE_BYTES clamped to ~half the modeled
    # working set, so the residency manager MUST evict and re-upload
    # mid-build (the disk tier is live, not idle). Gated lower-better
    # by bench_regress; the spill counters ride info-only — they
    # describe the constraint, not a perf series. Runs at the reduced
    # update-leg scale with a small chunk so the stream has enough
    # chunks to rotate, and stays seconds everywhere.
    try:
        os2 = max(10, scale - 4)
        m2 = (1 << os2) * edge_factor
        oc_chunk = max(1024, m2 // 8)       # ~8 chunks to rotate over
        nchunks = -(-m2 // oc_chunk)
        # modeled working set: every padded (cs, 2) int32 chunk resident
        working = nchunks * oc_chunk * 2 * 4
        budget = max(1, working // 2)
        oc_stream = generators.RmatHashStream(os2, edge_factor, seed=42)
        oc_be = get_backend("tpu", chunk_edges=oc_chunk)
        oc_be.partition(oc_stream, k, comm_volume=False)  # compile warm-up
        prev = os.environ.get("SHEEP_CACHE_BYTES")
        os.environ["SHEEP_CACHE_BYTES"] = str(budget)
        try:
            t0 = time.perf_counter()
            res_oc = oc_be.partition(oc_stream, k, comm_volume=False)
            oc_s = time.perf_counter() - t0
        finally:
            if prev is None:
                os.environ.pop("SHEEP_CACHE_BYTES", None)
            else:
                os.environ["SHEEP_CACHE_BYTES"] = prev
        out["oocore_request_s"] = round(oc_s, 4)
        for f in ("spill_evictions", "spill_reload_bytes",
                  "spill_resident_bytes"):
            out[f] = int(res_oc.diagnostics.get(f, 0))
        log(f"out-of-core: oocore_request_s {out['oocore_request_s']}s "
            f"(RMAT-{os2}, {nchunks} chunks, budget {budget:,} of "
            f"modeled {working:,} bytes; spill_evictions="
            f"{out['spill_evictions']}, spill_reload_bytes="
            f"{out['spill_reload_bytes']}, spill_resident_bytes="
            f"{out['spill_resident_bytes']})")
        if not out["spill_evictions"]:
            log("WARNING: out-of-core leg evicted nothing — the "
                "budget clamp is not constraining the build and "
                "oocore_request_s is measuring a fully-resident run")
    except Exception as e:  # noqa: BLE001 — the leg must not kill bench
        log(f"out-of-core leg skipped: {type(e).__name__}: "
            f"{str(e)[:200]}")
    # per-segment build-wall attribution (t_warm_s/t_full_s/t_small_s/
    # t_host_tail_s — elim.py accumulates them per sync), the numbers
    # that decompose build wall into device floor vs tunnel/host tax
    seg_t = {k: round(v, 3) for k, v in res_tpu.diagnostics.items()
             if k.startswith("t_")}
    if seg_t:
        log(f"build wall attribution: {seg_t}")
    # count x round-cost attribution inputs: with the dispatch counts in
    # the contract, two bench rows at different --dispatch-batch solve
    # per-dispatch overhead vs per-round device cost exactly
    # (sheep_tpu.utils.metrics.solve_dispatch_attribution) — the batched
    # dispatch win is provable from counts alone, even on the CPU mesh
    disp = {k: int(res_tpu.diagnostics[k])
            for k in ("host_syncs", "device_rounds", "batch_execs",
                      "dispatch_batch", "inflight_depth",
                      "inflight_discards", "dispatch_retries",
                      "degraded_dispatch_batch", "degraded_inflight",
                      "degraded_h2d_ring", "device_loss_recoveries",
                      "checkpoint_degraded", "h2d_staged_bytes",
                      "device_stream_chunks", "h2d_ring_depth")
            if k in res_tpu.diagnostics}
    # fault-tolerance contract fields (ISSUE 9): ALWAYS emit
    # dispatch_retries so the regression gate can see 0 -> N movement
    # (a field missing on one side is incomparable, not zero)
    disp.setdefault("dispatch_retries",
                    int(res_tpu.diagnostics.get("dispatch_retries", 0)))
    if disp:
        log(f"dispatch counts (count x round-cost attribution): {disp}")
        out.update(disp)
    # dispatch-overlap contract fields (ISSUE 4) + the ingest pair
    # (ISSUE 12): host wall blocked in stats pulls, device idle between
    # executions, and the H2D staging/underrun walls — the timed leg
    # runs the device-stream path for its rmat-hash input, so
    # h2d_blocked_ms/h2d_staged_bytes SHOULD be 0 there (zero host
    # bytes per chunk); a file-backed capture reports the ring's
    # numbers instead. h2d_blocked_ms is gated lower-is-better by
    # bench_regress like host_blocked_ms.
    overlap = {k: round(float(res_tpu.diagnostics[k]), 1)
               for k in ("host_blocked_ms", "device_gap_ms",
                         "h2d_staged_ms", "h2d_blocked_ms")
               if k in res_tpu.diagnostics}
    if overlap:
        log(f"dispatch overlap: {overlap}")
        out.update(overlap)
    # r_colo_est: the headline ratio with this window's measured
    # per-sync link tax subtracted — the co-located-host R estimate that
    # makes rounds comparable across the ~8x link swing. If the rtt
    # sample claims MORE tax than the whole measured wall (a probe-time
    # spike on a link that later recovered), the estimate is invalid —
    # fall back to the unnormalized ratio rather than emitting a
    # clamped-denominator absurdity into the contract.
    syncs = disp.get("host_syncs", 0)
    colo_s = tpu_s - syncs * link.get("rtt_ms", 0.0) / 1e3
    if colo_s <= 0:
        log(f"rtt sample ({link.get('rtt_ms')} ms x {syncs} syncs) "
            f"exceeds the measured wall; r_colo_est left unnormalized")
        colo_s = tpu_s
    out["r_colo_est"] = round((m / colo_s) / cpu_eps, 3)
    reg = (res_tpu.cut_ratio - res_cpu.cut_ratio) / max(res_cpu.cut_ratio, 1e-9)
    log(f"edge-cut regression vs cpu: {100 * reg:+.2f}% (target <= +2%)")
    out.update(tpu_eps=round(tpu_eps, 1), ratio=round(tpu_eps / cpu_eps, 3),
               tpu_cut_ratio=round(res_tpu.cut_ratio, 6),
               cut_regression_pct=round(100 * reg, 2))

    # --- multi-chip leg (VERDICT r3 item 6a) ------------------------------
    # The north star is R x S(D): the moment the tunnel exposes more than
    # one real chip, measure the D-device tpu-sharded product instead of
    # projecting it from collective counts. Opt-in on cpu-jax
    # (SHEEP_BENCH_MULTICHIP=1) so the virtual 8-device mesh can dryrun
    # this exact code path in tests without polluting fallback numbers.
    import jax

    n_dev = jax.device_count()
    force_multi = os.environ.get("SHEEP_BENCH_MULTICHIP") == "1"
    if n_dev > 1 and (platform != "cpu" or force_multi):
        res_sh, sh_s, sh_warm = timed_leg("tpu-sharded")
        sh_eps = m / sh_s
        log(f"tpu-sharded D={n_dev}: {sh_s:.2f}s = {sh_eps / 1e6:.2f} Me/s "
            f"(warm-up {sh_warm:.1f}s) cut_ratio={res_sh.cut_ratio:.4f} "
            f"balance={res_sh.balance:.3f}")
        out.update(n_devices=n_dev, sharded_eps=round(sh_eps, 1),
                   ratio_multichip=round(sh_eps / cpu_eps, 3),
                   sharded_cut_ratio=round(res_sh.cut_ratio, 6))
    return out


def find_last_real_capture():
    """Most recent tools/out/*/bench.json with a real accelerator
    measurement (value > 0, platform != cpu), as a small dict, or None.
    Attached to the diagnostics when the current run had to fall back —
    the judge/operator can see the last healthy-window number and where
    its artifacts live without trusting it as the current measurement."""
    import glob

    best = None
    root = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "tools", "out")
    for path in sorted(glob.glob(os.path.join(root, "*", "bench.json"))):
        try:
            with open(path) as f:
                line = json.loads(f.readline())
            if (isinstance(line, dict)
                    and isinstance(line.get("value"), (int, float))
                    and line["value"] > 0
                    and line.get("platform") not in (None, "cpu")):
                best = {"dir": os.path.dirname(path),
                        "value": line["value"],
                        "vs_baseline": line.get("vs_baseline"),
                        "metric": line.get("metric")}
        except Exception:
            # best-effort diagnostics: one bad artifact file must never
            # cost the run its headline measurement
            continue
    return best


_RESULT_TAG = "SHEEP_BENCH_RESULT "


def run_attempt(scale: int, platform: str, timeout: float):
    """One subprocess measurement attempt; returns (result dict | None,
    failure string | None)."""
    try:
        r = subprocess.run(
            [sys.executable, os.path.abspath(__file__),
             "--measure", str(scale), platform],
            capture_output=True, text=True, timeout=timeout)
    except subprocess.TimeoutExpired:
        return None, f"scale {scale}: timed out after {int(timeout)}s"
    sys.stderr.write(r.stderr or "")
    for line in (r.stdout or "").splitlines():
        if line.startswith(_RESULT_TAG):
            try:
                return json.loads(line[len(_RESULT_TAG):]), None
            except json.JSONDecodeError as e:
                return None, f"scale {scale}: bad worker result ({e})"
    tail = (r.stderr or "").strip().splitlines()
    return None, (f"scale {scale}: worker died rc={r.returncode}: "
                  + (tail[-1][:300] if tail else "no stderr"))


def main():
    forced = os.environ.get("SHEEP_BENCH_PLATFORM")
    platform = forced if forced else probe_accelerator()
    fell_back = platform is None
    if fell_back:
        log("no accelerator available; falling back to cpu-jax "
            "(vs_baseline will reflect cpu-jax, not TPU)")
        platform = "cpu"

    default_scale = {"cpu": "18"}.get(platform, "22")
    top = int(os.environ.get("SHEEP_BENCH_SCALE", default_scale))
    try:
        from sheep_tpu.core import native

        have_native = native.available()
    except Exception:
        have_native = False
    if not have_native and "SHEEP_BENCH_SCALE" not in os.environ:
        # pure-numpy baseline is O(V) python per vertex: scale-18 attempts
        # would just burn the attempt timeout before 14 could succeed
        top = min(top, 14)
    ladder = list(range(top, max(top - 5, 13), -2)) or [top]
    # budget per attempt: graph gen (~2 min at scale 22 on a 1-core
    # host) + native baseline + first-compile warm-up (~6 min through
    # the tunnel, mostly amortized away by the persistent compilation
    # cache below on reruns) + two timed runs
    attempt_timeout = float(os.environ.get("SHEEP_BENCH_ATTEMPT_TIMEOUT",
                                           "1800"))

    failures = []
    result = None
    for scale in ladder:
        result, fail = run_attempt(scale, platform, attempt_timeout)
        if result is not None:
            break
        failures.append(fail)
        log(f"attempt failed: {fail}; "
            + ("retrying down the ladder" if scale != ladder[-1] else
               "ladder exhausted"))

    if result is None and platform != "cpu":
        # accelerator kept dying: last resort is a cpu-jax ratio so the
        # round still records a parsed number (clearly diagnosed as such)
        log("all accelerator attempts failed; falling back to cpu-jax")
        fell_back = True
        platform = "cpu"
        result, fail = run_attempt(16, platform, attempt_timeout)
        if fail:
            failures.append(fail)

    last_real = find_last_real_capture() \
        if (fell_back or platform == "cpu") else None
    if last_real:
        # the measured-now value stays the headline; this is a POINTER to
        # the most recent real-accelerator capture on disk for context
        # when the tunnel is down at bench time (a recurring failure
        # mode: it wedges for hours)
        log(f"last real-accelerator capture: {last_real}")

    if result is None:
        emit(0.0, 0.0, error="; ".join(failures)[:600])
        return

    metric = (f"{METRIC} (RMAT-{result['scale']}, k={result['k']}, "
              f"{result['platform']} vs 1-socket CPU)")
    extra = {"platform": result["platform"]}
    # link-state + dispatch-attribution contract fields (VERDICT r5
    # items 2/7): every bench row carries its own window's link state
    # and the co-located R estimate, so numbers stay comparable across
    # link-quality swings without artifact archaeology
    for f in ("rtt_ms", "h2d_mbs", "d2h_mbs", "r_colo_est", "host_syncs",
              "device_rounds", "dispatch_batch", "inflight_depth",
              "inflight_discards", "host_blocked_ms", "device_gap_ms",
              "h2d_staged_ms", "h2d_blocked_ms", "h2d_staged_bytes",
              "h2d_ring_depth", "device_stream_chunks",
              "dispatch_retries", "degraded_dispatch_batch",
              "degraded_inflight", "degraded_h2d_ring",
              "device_loss_recoveries",
              "checkpoint_degraded", "warm_up_s", "cold_request_s",
              "warm_request_s", "cached_request_s", "update_request_s",
              "update_fold_s", "update_score_s", "epoch_scale_x2",
              "sharded_update_request_s", "compactions",
              "oocore_request_s", "spill_evictions",
              "spill_reload_bytes", "spill_resident_bytes"):
        if f in result:
            extra[f] = result[f]
    if failures:
        extra["retries"] = failures
    vs = result["ratio"]
    errors = []
    on_fallback = fell_back or result["platform"] == "cpu"
    if on_fallback:
        # VERDICT r3 item 6b: a cpu-jax fallback measures framework
        # overhead (cpu-jax vs native CPU), not the north-star TPU ratio.
        # Report vs_baseline as null so the number can't be mistaken for
        # progress against the 10x target; the ratio survives under a
        # diagnostic name.
        errors.append("accelerator unavailable; vs_baseline withheld "
                      "(cpu-jax fallback)")
        extra["cpu_jax_vs_native_cpu"] = vs
        vs = None
    if last_real:
        extra["last_real_capture"] = last_real
    if (not on_fallback and result.get("n_devices", 1) > 1
            and "ratio_multichip" in result):
        # the R x S(D) product, measured the moment real multi-chip
        # hardware appears; never emitted on fallback, where it would be
        # a fake multichip "progress" number
        extra[f"vs_baseline_{result['n_devices']}chip"] = \
            result["ratio_multichip"]
    if "error" in result:
        errors.append(result["error"])
    if errors:
        extra["error"] = "; ".join(errors)
    emit(result["tpu_eps"], vs, metric=metric, **extra)


if __name__ == "__main__":
    if len(sys.argv) >= 4 and sys.argv[1] == "--measure":
        out = measure(int(sys.argv[2]), sys.argv[3])
        print(_RESULT_TAG + json.dumps(out), flush=True)
        sys.exit(0)
    try:
        main()
    except Exception as e:
        # Deliberate: emit the JSON contract line and exit 0 so the
        # driver records a PARSED result instead of rc!=0/parsed=null
        # (round 1 lost its number exactly that way). A genuine failure
        # is unambiguous in the parsed output — value 0.0 plus the
        # "error" diagnostic — which is where harnesses should look.
        import traceback

        traceback.print_exc(file=sys.stderr)
        emit(0.0, 0.0, error=f"{type(e).__name__}: {str(e)[:300]}")
        sys.exit(0)
