#!/usr/bin/env python
"""Headline benchmark: edges/sec partitioned, TPU backend vs CPU baseline.

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

``vs_baseline`` is the TPU/CPU edges-per-second ratio — the north-star
target is >=10x (BASELINE.md). Graph: RMAT (Graph500 params), k=64,
matching the driver's streaming eval shape. Scale via SHEEP_BENCH_SCALE
(default 22 -> 4.2M vertices, 67M edges).

Secondary metrics (cut ratio parity vs CPU, per-phase times) go to stderr
so the stdout contract stays one line.
"""

import json
import os
import sys
import time


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def main():
    scale = int(os.environ.get("SHEEP_BENCH_SCALE", "22"))
    edge_factor = int(os.environ.get("SHEEP_BENCH_EDGE_FACTOR", "16"))
    k = int(os.environ.get("SHEEP_BENCH_K", "64"))

    from sheep_tpu.io import generators
    from sheep_tpu.io.edgestream import EdgeStream
    from sheep_tpu.backends.base import get_backend, list_backends

    t0 = time.perf_counter()
    edges = generators.rmat(scale, edge_factor, seed=42)
    n = 1 << scale
    es = EdgeStream.from_array(edges, n_vertices=n)
    m = len(edges)
    log(f"graph: RMAT-{scale} ef={edge_factor}  V={n:,} E={m:,}  "
        f"(gen {time.perf_counter() - t0:.1f}s)  k={k}")

    # --- CPU single-socket baseline (the denominator) ---------------------
    cpu = get_backend("cpu", chunk_edges=1 << 24)
    t0 = time.perf_counter()
    res_cpu = cpu.partition(es, k, comm_volume=False)
    cpu_s = time.perf_counter() - t0
    cpu_eps = m / cpu_s
    log(f"cpu: {cpu_s:.2f}s = {cpu_eps / 1e6:.2f} Me/s  "
        f"cut_ratio={res_cpu.cut_ratio:.4f} balance={res_cpu.balance:.3f} "
        f"phases={ {p: round(s, 2) for p, s in res_cpu.phase_times.items()} }")

    # --- TPU backend ------------------------------------------------------
    if "tpu" not in list_backends():
        log("tpu backend unavailable; reporting cpu vs itself")
        print(json.dumps({
            "metric": f"edges/sec partitioned (RMAT-{scale}, k={k})",
            "value": round(cpu_eps, 1), "unit": "edges/sec", "vs_baseline": 1.0,
        }))
        return

    tpu = get_backend("tpu", chunk_edges=min(1 << 24, m))
    t0 = time.perf_counter()
    res_warm = tpu.partition(es, k, comm_volume=False)  # compile warm-up
    warm_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    res_tpu = tpu.partition(es, k, comm_volume=False)
    tpu_s = time.perf_counter() - t0
    tpu_eps = m / tpu_s
    log(f"tpu: {tpu_s:.2f}s = {tpu_eps / 1e6:.2f} Me/s (warm-up {warm_s:.1f}s)  "
        f"cut_ratio={res_tpu.cut_ratio:.4f} balance={res_tpu.balance:.3f} "
        f"phases={ {p: round(s, 2) for p, s in res_tpu.phase_times.items()} }")
    reg = (res_tpu.cut_ratio - res_cpu.cut_ratio) / max(res_cpu.cut_ratio, 1e-9)
    log(f"edge-cut regression vs cpu: {100 * reg:+.2f}% (target <= +2%)")

    print(json.dumps({
        "metric": f"edges/sec partitioned (RMAT-{scale}, k={k}, TPU vs 1-socket CPU)",
        "value": round(tpu_eps, 1),
        "unit": "edges/sec",
        "vs_baseline": round(tpu_eps / cpu_eps, 3),
    }))


if __name__ == "__main__":
    main()
