"""Shared result/value types for sheep_tpu.

SURVEY.md §2 #7-8: the partition pipeline produces an elimination tree, a
vertex->part assignment, and cut/balance scores. These containers are the
common currency between backends (cpu C++ core, tpu JAX path) so the
cross-backend equivalence tests (SURVEY.md §4.3) can compare like with like.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional

import numpy as np

# All device-resident vertex tables (pos/order/minp/assignment) are int32,
# on every TPU backend including the block-sharded tpu-bigv — so vertex
# ids must stay below 2^31. Every in-contract eval config does (RMAT-30 =
# 2^30 vertices, BASELINE.md); beyond that the int64 cpu backend applies.
MAX_TPU_VERTICES = 2**31 - 1


class UnsupportedGraphError(ValueError):
    """Graph outside a backend's documented envelope — raised up front
    (before any streaming pass) so the CLI can reject it cleanly instead
    of surfacing a mid-build stack trace (SURVEY.md §2 #1: trillion-edge
    capable means failing loudly at the documented boundary)."""


def check_tpu_vertex_range(n: int, backend: str) -> None:
    if n > MAX_TPU_VERTICES:
        raise UnsupportedGraphError(
            f"graph has {n:,} vertices but backend {backend!r} keeps "
            f"int32 device tables (max {MAX_TPU_VERTICES:,}); use "
            f"--backend cpu (int64) for larger vertex ids")


@dataclasses.dataclass
class ElimTree:
    """An elimination forest over a fixed global vertex order.

    ``parent[v]`` is the tree parent of vertex ``v`` (-1 for roots).
    ``pos[v]`` is the global elimination position of ``v`` (ascending degree,
    ties by id). Invariant: ``pos[parent[v]] > pos[v]`` for every non-root —
    parents are always eliminated later.
    """

    parent: np.ndarray  # int64[V], -1 for roots
    pos: np.ndarray  # int64[V]
    n: int

    def validate(self) -> None:
        p = self.parent
        nonroot = p >= 0
        assert p.shape == (self.n,)
        assert np.all(p[nonroot] < self.n)
        # parents strictly later in the elimination order => acyclic
        assert np.all(self.pos[p[nonroot]] > self.pos[np.nonzero(nonroot)[0]]), (
            "elimination tree has a parent earlier in the order (cycle risk)"
        )

    def edges(self) -> np.ndarray:
        """Tree edges as an (m, 2) array — the mergeable O(V) summary of the
        graph's connectivity process (SURVEY.md §2 #6)."""
        v = np.nonzero(self.parent >= 0)[0]
        return np.stack([v, self.parent[v]], axis=1)


@dataclasses.dataclass
class PartitionResult:
    assignment: np.ndarray  # int32[V] vertex -> part
    k: int
    edge_cut: int  # edges with endpoints in different parts
    total_edges: int
    cut_ratio: float  # edge_cut / total_edges
    balance: float  # max part load / ideal load
    comm_volume: Optional[int] = None  # distinct (vertex, foreign part) pairs
    phase_times: Dict[str, float] = dataclasses.field(default_factory=dict)
    backend: str = ""
    # non-time diagnostics (e.g. fixpoint round counts) — kept out of
    # phase_times so per-phase throughput math stays meaningful
    diagnostics: Dict[str, float] = dataclasses.field(default_factory=dict)
    # the k-INDEPENDENT build state {parent, pos, deg}, attached when the
    # caller passed keep_tree=True — what partition_multi re-splits for
    # further k values without re-streaming degrees/build [PAPER: the
    # elimination tree is reusable across part counts]
    tree: Optional[Dict[str, np.ndarray]] = None

    def validate(self, n: int) -> None:
        a = self.assignment
        assert a.shape == (n,)
        assert a.min() >= 0 and a.max() < self.k, "vertex assigned out of range"

    def summary(self) -> Dict:
        return {
            "k": self.k,
            "edge_cut": int(self.edge_cut),
            "total_edges": int(self.total_edges),
            "cut_ratio": float(self.cut_ratio),
            "balance": float(self.balance),
            "comm_volume": None if self.comm_volume is None else int(self.comm_volume),
            "backend": self.backend,
            "phase_times": {k: round(v, 6) for k, v in self.phase_times.items()},
            **({"diagnostics": self.diagnostics} if self.diagnostics else {}),
        }
