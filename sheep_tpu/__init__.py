"""sheep_tpu — a TPU-native distributed graph partitioner.

A from-scratch rebuild of the capabilities of the reference partitioner
``chan150/sheep`` (SHEEP: Margo & Seltzer, "A Scalable Distributed Graph
Partitioner", PVLDB 8(12), 2015), designed TPU-first:

- the streaming elimination-tree build is expressed as an associative
  reduction over edge chunks (``lax.scan`` + scatter-min fixpoint), not a
  sequential union-find loop;
- multi-device scaling uses ``jax.sharding.Mesh`` + ``shard_map`` with XLA
  collectives (psum / all_gather / ppermute) over ICI/DCN, not MPI;
- the CPU reference path is native C++ (``sheep_tpu/core/csrc``) exposed via
  ctypes, mirroring the reference's all-native core.

Reference provenance: the reference mount was empty this round (see
SURVEY.md §0); component parity targets come from SURVEY.md §2.
"""

__version__ = "0.1.0"

from sheep_tpu.types import ElimTree, PartitionResult  # noqa: F401
from sheep_tpu.backends.base import get_backend, list_backends  # noqa: F401


def partition_hierarchical(path, k_levels, **kw):
    """Lazy re-export of :func:`sheep_tpu.hierarchy.partition_hierarchical`
    (k = prod(k_levels) via per-level partition + refine — keeps every
    level above the LP signal threshold; see that module)."""
    from sheep_tpu.hierarchy import partition_hierarchical as ph

    return ph(path, k_levels, **kw)


def partition(path, k, backend=None, refine=0, refine_alpha=1.10, **opts):
    """One-call API: partition the graph stored at *path* into *k* parts.

    *path* also accepts the synthetic stream specs of
    :func:`sheep_tpu.io.edgestream.open_input`
    (``rmat-hash:SCALE[:EF[:SEED]]`` / ``rmat:SCALE[:EF[:SEED]]``).

    ``backend=None`` auto-selects the best registered backend
    (tpu > cpu > pure). Constructor options of the chosen backend (e.g.
    ``chunk_edges``, ``alpha``, ``lift_levels``) and partition options
    (e.g. ``weights``, ``comm_volume``) are both accepted; unknown options
    raise TypeError rather than being silently dropped.

    ``refine=N`` runs up to N rounds of capacity-constrained label
    propagation after the backend finishes (``ops/refine.py``) — an
    extension beyond the reference's surface; the refined cut is
    guaranteed <= the unrefined cut (non-improving rounds roll back).
    """
    from sheep_tpu.io.edgestream import open_input

    with open_input(path) as es:
        return _partition_stream(es, k, backend=backend, refine=refine,
                                 refine_alpha=refine_alpha, **opts)


def _partition_stream(stream, k, backend=None, refine=0,
                      refine_alpha=1.10, **opts):
    """:func:`partition` over an already-open stream (shared by the
    path API and :func:`sheep_tpu.hierarchy.partition_hierarchical`,
    whose induced subgraphs exist only in memory)."""
    cls, ctor_opts, part_opts = _resolve_backend(backend, opts)
    be = cls(**ctor_opts)
    res = be.partition(stream, k, **part_opts)
    if refine:
        res = refine_result(res, stream, rounds=refine,
                            alpha=refine_alpha,
                            weights=opts.get("weights", "unit"))
    return res


def _resolve_backend(backend, opts):
    """Shared backend resolution for :func:`partition` /
    :func:`partition_multi`: auto-select (tpu > cpu > pure) with a clear
    error when none is registered, reject unknown backend names, and
    split ``opts`` into constructor vs partition kwargs — raising
    TypeError on options neither accepts instead of silently dropping
    them (ADVICE r3)."""
    import inspect

    from sheep_tpu.backends.base import _REGISTRY

    avail = list_backends()
    if backend is None:
        backend = next((b for b in ("tpu", "cpu", "pure") if b in avail),
                       None)
        if backend is None:
            raise RuntimeError(
                "no default backend registered (need one of tpu/cpu/pure); "
                f"registered: {', '.join(avail) or 'none'}")
    cls = _REGISTRY.get(backend)
    if cls is None:
        raise ValueError(f"unknown backend {backend!r}; available: "
                         f"{', '.join(avail)}")

    def named_params(fn, skip):
        sig = inspect.signature(fn)
        return {name for name, p in sig.parameters.items()
                if p.kind in (p.POSITIONAL_OR_KEYWORD, p.KEYWORD_ONLY)} - skip

    ctor_params = named_params(cls.__init__, {"self"})
    part_params = named_params(cls.partition, {"self", "stream", "k"})
    unknown = set(opts) - ctor_params - part_params
    if unknown:
        raise TypeError(f"unknown option(s) for backend {backend!r}: "
                        f"{sorted(unknown)}")
    ctor_opts = {o: v for o, v in opts.items() if o in ctor_params}
    part_opts = {o: v for o, v in opts.items()
                 if o in part_params and o not in ctor_params}
    return cls, ctor_opts, part_opts


def partition_multi(path, ks, backend=None, **opts):
    """Like :func:`partition`, but one result per part count in ``ks``
    from ONE elimination-tree build where the backend supports it (the
    tree is k-independent — SHEEP's reuse property): extra k values cost
    an O(V) re-split plus one shared scoring pass. Returns a list of
    PartitionResult in ``ks`` order. Unknown options raise TypeError,
    matching :func:`partition`."""
    from sheep_tpu.io.edgestream import open_input

    cls, ctor_opts, part_opts = _resolve_backend(backend, opts)
    be = cls(**ctor_opts)
    with open_input(path) as es:
        return be.partition_multi(es, ks, **part_opts)


def comm_volume_of(assignment, stream, n, k, chunk_edges=1 << 22):
    """Deduped (vertex, foreign-part) comm volume of an assignment over
    one stream pass — the counter every backend reports, exposed for
    post-passes (refine/hierarchy) that change the assignment after the
    scored pass already happened."""
    import numpy as np

    import jax.numpy as jnp

    from sheep_tpu.ops import score as score_ops
    from sheep_tpu.utils.checkpoint import compact_cv_keys

    a_dev = jnp.asarray(np.concatenate(
        [np.asarray(assignment, np.int32), np.zeros(1, np.int32)]))
    acc: list = []
    for c in stream.chunks(chunk_edges):
        score_ops.accumulate_cv_keys(
            acc, score_ops.cut_pair_keys_host(c, a_dev, n, k))
    return int(len(compact_cv_keys(acc)))


def refine_result(res, stream, rounds=3, alpha=1.10, weights="unit",
                  degrees=None, budget_bytes: int = 4 << 30):
    """Apply the post-pass refinement to a PartitionResult (shared by the
    library API and the CLI's --refine flag); rescores cut/balance (and
    comm volume when the input carried one). ``weights="degree"`` caps
    parts by degree weight, matching the backend's balance semantics
    (one extra stream pass recomputes the degrees — pass ``degrees`` to
    reuse an already-computed table instead). ``budget_bytes`` bounds
    the (V+1) x k histogram before refinement switches to the blocked
    (multi-pass) mode — s22/k=256 misses the 4 GB default by exactly
    1 KB and quintuples its stream passes, so callers with RAM should
    raise it."""
    import dataclasses

    import numpy as np

    from sheep_tpu.core import pure
    from sheep_tpu.ops.refine import refine_assignment

    n = stream.num_vertices
    w = degrees
    if weights == "degree" and w is None:
        w = np.zeros(n, dtype=np.int64)
        for c in stream.chunks(1 << 22):
            w += np.bincount(np.asarray(c, np.int64).ravel(),
                             minlength=n)[:n]
    try:
        new_assign, rstats = refine_assignment(
            res.assignment, stream, n, res.k, rounds=rounds, alpha=alpha,
            weights=w, budget_bytes=budget_bytes)
    except ValueError as e:
        # never lose a finished partition to an over-budget refinement —
        # return it unrefined with the reason in the diagnostics
        import sys

        print(f"refine skipped: {e}", file=sys.stderr)
        return dataclasses.replace(
            res, diagnostics={**(res.diagnostics or {}),
                              "refine_skipped": str(e)})
    cv = res.comm_volume
    if cv is not None:
        cv = comm_volume_of(new_assign, stream, n, res.k)
    return dataclasses.replace(
        res, assignment=new_assign,
        edge_cut=rstats["refine_cut_after"],
        cut_ratio=rstats["refine_cut_after"] / max(res.total_edges, 1),
        balance=pure.part_balance(new_assign, res.k, w),
        comm_volume=cv,
        diagnostics={**(res.diagnostics or {}),
                     **{kk: float(vv) for kk, vv in rstats.items()}})
