from sheep_tpu.parallel.mesh import shards_mesh, device_count  # noqa: F401
from sheep_tpu.parallel import pipeline  # noqa: F401
