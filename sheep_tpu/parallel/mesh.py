"""Device mesh + multi-host initialization (SURVEY.md §2 #9, §5).

The workload is pure data parallelism over edge shards (SURVEY.md §2
parallelism table), so the mesh is one axis, ``shards``. Within a slice the
collectives ride ICI; across hosts (jax.distributed) the same program runs
with the global device set and the collectives ride DCN — the comm surface
(merge reduction + counter psum) is identical, mirroring how the
reference's MPI ranks scatter shards and reduce partial trees (§3.1).
"""

from __future__ import annotations

import os
from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh

SHARD_AXIS = "shards"

try:  # jax >= 0.5 promoted shard_map to the top-level namespace
    from jax import shard_map as _jax_shard_map

    _SHARD_MAP_COMPAT_KW: dict = {}
except ImportError:  # pragma: no cover - older jaxlib
    from jax.experimental.shard_map import shard_map as _jax_shard_map

    # the experimental form has no replication rule for while_loop (the
    # fixpoint segments' shape); check_rep=False skips the static check
    # — every replicated output here really is replicated (pmax/pmin/
    # psum results), so semantics are unchanged
    _SHARD_MAP_COMPAT_KW = {"check_rep": False}


def shard_map(f, **kw):
    """``jax.shard_map`` across the jax versions this repo meets — the
    single import point for the sharded pipeline and the bigv backend."""
    return _jax_shard_map(f, **{**_SHARD_MAP_COMPAT_KW, **kw})


def device_count() -> int:
    return jax.device_count()


def shards_mesh(n_devices: Optional[int] = None) -> Mesh:
    """A 1-D mesh over the first n_devices (default: all)."""
    devs = jax.devices()
    if n_devices is not None:
        if n_devices > len(devs):
            raise ValueError(f"requested {n_devices} devices, have {len(devs)}")
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (SHARD_AXIS,))


def init_distributed(coordinator: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None) -> None:
    """Multi-host bring-up (the reference's mpirun equivalent).

    With no arguments, reads the standard JAX env vars / cluster
    autodetection. Safe to call once per process before any jax op.
    """
    kwargs = {}
    if coordinator is not None:
        kwargs["coordinator_address"] = coordinator
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    jax.distributed.initialize(**kwargs)


def host_shard_info():
    """(shard, num_shards) for EdgeStream sharding at the host level."""
    return jax.process_index(), jax.process_count()


def force_cpu_devices(n: int) -> None:
    """Best-effort: fake an n-device CPU platform (test/dryrun helper).

    Must run before the backend initializes; jax is pre-imported in this
    environment, so we use config.update rather than env vars alone.
    """
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}").strip()
    os.environ["JAX_PLATFORMS"] = "cpu"
    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass
